# Deneb -- Light Client (blob-gas fields in the execution header).
#
# Parity contract: specs/deneb/light-client/sync-protocol.md (modified
# get_lc_execution_root / is_valid_light_client_header), full-node.md,
# fork.md (upgrade functions).  The LightClientHeader layout is unchanged
# from capella; only the embedded ExecutionPayloadHeader grows
# blob_gas_used / excess_blob_gas, so capella-epoch headers must be
# re-rooted against the capella field set.


class _CapellaExecutionPayloadHeader(Container):
    # The capella-era header shape, kept for re-rooting pre-deneb headers
    # (the reference reaches into `capella.ExecutionPayloadHeader`;
    # this build re-declares the shape in place).
    parent_hash: Hash32
    fee_recipient: ExecutionAddress
    state_root: Bytes32
    receipts_root: Bytes32
    logs_bloom: ByteVector[BYTES_PER_LOGS_BLOOM]
    prev_randao: Bytes32
    block_number: uint64
    gas_limit: uint64
    gas_used: uint64
    timestamp: uint64
    extra_data: ByteList[MAX_EXTRA_DATA_BYTES]
    base_fee_per_gas: uint256
    block_hash: Hash32
    transactions_root: Root
    withdrawals_root: Root


# Deneb's beacon chain redefines ExecutionPayloadHeader (blob-gas fields);
# the LC containers bind field types at class creation, so re-declare them
# against the new header shape (the reference's generated module rebuilds
# every class per fork).


class LightClientHeader(Container):
    beacon: BeaconBlockHeader
    execution: ExecutionPayloadHeader
    execution_branch: ExecutionBranch


class LightClientBootstrap(Container):
    header: LightClientHeader
    current_sync_committee: SyncCommittee
    current_sync_committee_branch: CurrentSyncCommitteeBranch


class LightClientUpdate(Container):
    attested_header: LightClientHeader
    next_sync_committee: SyncCommittee
    next_sync_committee_branch: NextSyncCommitteeBranch
    finalized_header: LightClientHeader
    finality_branch: FinalityBranch
    sync_aggregate: SyncAggregate
    signature_slot: Slot


class LightClientFinalityUpdate(Container):
    attested_header: LightClientHeader
    finalized_header: LightClientHeader
    finality_branch: FinalityBranch
    sync_aggregate: SyncAggregate
    signature_slot: Slot


class LightClientOptimisticUpdate(Container):
    attested_header: LightClientHeader
    sync_aggregate: SyncAggregate
    signature_slot: Slot


@dataclass
class LightClientStore(object):
    finalized_header: LightClientHeader
    current_sync_committee: SyncCommittee
    next_sync_committee: SyncCommittee
    best_valid_update: Optional[LightClientUpdate]
    optimistic_header: LightClientHeader
    previous_max_active_participants: uint64
    current_max_active_participants: uint64


def get_lc_execution_root(header: LightClientHeader) -> Root:
    epoch = compute_epoch_at_slot(header.beacon.slot)

    # [New in Deneb]
    if epoch >= config.DENEB_FORK_EPOCH:
        return hash_tree_root(header.execution)

    # [Modified in Deneb] capella-era headers root over the capella shape
    if epoch >= config.CAPELLA_FORK_EPOCH:
        execution_header = _CapellaExecutionPayloadHeader(
            parent_hash=header.execution.parent_hash,
            fee_recipient=header.execution.fee_recipient,
            state_root=header.execution.state_root,
            receipts_root=header.execution.receipts_root,
            logs_bloom=header.execution.logs_bloom,
            prev_randao=header.execution.prev_randao,
            block_number=header.execution.block_number,
            gas_limit=header.execution.gas_limit,
            gas_used=header.execution.gas_used,
            timestamp=header.execution.timestamp,
            extra_data=header.execution.extra_data,
            base_fee_per_gas=header.execution.base_fee_per_gas,
            block_hash=header.execution.block_hash,
            transactions_root=header.execution.transactions_root,
            withdrawals_root=header.execution.withdrawals_root,
        )
        return hash_tree_root(execution_header)

    return Root()


def is_valid_light_client_header(header: LightClientHeader) -> bool:
    epoch = compute_epoch_at_slot(header.beacon.slot)

    # [New in Deneb:EIP4844] blob-gas fields must be zero before deneb
    if epoch < config.DENEB_FORK_EPOCH:
        if header.execution.blob_gas_used != uint64(0):
            return False
        if header.execution.excess_blob_gas != uint64(0):
            return False

    if epoch < config.CAPELLA_FORK_EPOCH:
        return (header.execution == ExecutionPayloadHeader()
                and header.execution_branch == ExecutionBranch())

    return is_valid_merkle_branch(
        leaf=get_lc_execution_root(header),
        branch=header.execution_branch,
        depth=floorlog2(EXECUTION_PAYLOAD_GINDEX),
        index=get_subtree_index(EXECUTION_PAYLOAD_GINDEX),
        root=header.beacon.body_root,
    )


def get_lc_execution_payload_header(payload,
                                    epoch: Epoch) -> ExecutionPayloadHeader:
    header = ExecutionPayloadHeader(
        parent_hash=payload.parent_hash,
        fee_recipient=payload.fee_recipient,
        state_root=payload.state_root,
        receipts_root=payload.receipts_root,
        logs_bloom=payload.logs_bloom,
        prev_randao=payload.prev_randao,
        block_number=payload.block_number,
        gas_limit=payload.gas_limit,
        gas_used=payload.gas_used,
        timestamp=payload.timestamp,
        extra_data=payload.extra_data,
        base_fee_per_gas=payload.base_fee_per_gas,
        block_hash=payload.block_hash,
        transactions_root=hash_tree_root(payload.transactions),
        withdrawals_root=hash_tree_root(payload.withdrawals),
    )
    # [New in Deneb] capella-era payloads carry no blob-gas fields
    if epoch >= config.DENEB_FORK_EPOCH:
        header.blob_gas_used = payload.blob_gas_used
        header.excess_blob_gas = payload.excess_blob_gas
    return header


def block_to_light_client_header(block: SignedBeaconBlock) -> LightClientHeader:
    epoch = compute_epoch_at_slot(block.message.slot)

    if epoch >= config.CAPELLA_FORK_EPOCH:
        execution_header = get_lc_execution_payload_header(
            block.message.body.execution_payload, epoch)
        execution_branch = ExecutionBranch(
            compute_merkle_proof(block.message.body,
                                 EXECUTION_PAYLOAD_GINDEX))
    else:
        execution_header = ExecutionPayloadHeader()
        execution_branch = ExecutionBranch()

    return LightClientHeader(
        beacon=BeaconBlockHeader(
            slot=block.message.slot,
            proposer_index=block.message.proposer_index,
            parent_root=block.message.parent_root,
            state_root=block.message.state_root,
            body_root=hash_tree_root(block.message.body),
        ),
        execution=execution_header,
        execution_branch=execution_branch,
    )


# -- fork.md upgrade functions ----------------------------------------------


def upgrade_lc_header_to_deneb(pre) -> LightClientHeader:
    return LightClientHeader(
        beacon=pre.beacon,
        execution=ExecutionPayloadHeader(
            parent_hash=pre.execution.parent_hash,
            fee_recipient=pre.execution.fee_recipient,
            state_root=pre.execution.state_root,
            receipts_root=pre.execution.receipts_root,
            logs_bloom=pre.execution.logs_bloom,
            prev_randao=pre.execution.prev_randao,
            block_number=pre.execution.block_number,
            gas_limit=pre.execution.gas_limit,
            gas_used=pre.execution.gas_used,
            timestamp=pre.execution.timestamp,
            extra_data=pre.execution.extra_data,
            base_fee_per_gas=pre.execution.base_fee_per_gas,
            block_hash=pre.execution.block_hash,
            transactions_root=pre.execution.transactions_root,
            withdrawals_root=pre.execution.withdrawals_root,
            # blob_gas_used / excess_blob_gas default to zero
        ),
        execution_branch=pre.execution_branch,
    )


def upgrade_lc_bootstrap_to_deneb(pre) -> LightClientBootstrap:
    return LightClientBootstrap(
        header=upgrade_lc_header_to_deneb(pre.header),
        current_sync_committee=pre.current_sync_committee,
        current_sync_committee_branch=pre.current_sync_committee_branch,
    )


def upgrade_lc_update_to_deneb(pre) -> LightClientUpdate:
    return LightClientUpdate(
        attested_header=upgrade_lc_header_to_deneb(pre.attested_header),
        next_sync_committee=pre.next_sync_committee,
        next_sync_committee_branch=pre.next_sync_committee_branch,
        finalized_header=upgrade_lc_header_to_deneb(pre.finalized_header),
        finality_branch=pre.finality_branch,
        sync_aggregate=pre.sync_aggregate,
        signature_slot=pre.signature_slot,
    )


def upgrade_lc_finality_update_to_deneb(pre) -> LightClientFinalityUpdate:
    return LightClientFinalityUpdate(
        attested_header=upgrade_lc_header_to_deneb(pre.attested_header),
        finalized_header=upgrade_lc_header_to_deneb(pre.finalized_header),
        finality_branch=pre.finality_branch,
        sync_aggregate=pre.sync_aggregate,
        signature_slot=pre.signature_slot,
    )


def upgrade_lc_optimistic_update_to_deneb(pre) -> LightClientOptimisticUpdate:
    return LightClientOptimisticUpdate(
        attested_header=upgrade_lc_header_to_deneb(pre.attested_header),
        sync_aggregate=pre.sync_aggregate,
        signature_slot=pre.signature_slot,
    )


def upgrade_lc_store_to_deneb(pre) -> LightClientStore:
    if pre.best_valid_update is None:
        best_valid_update = None
    else:
        best_valid_update = upgrade_lc_update_to_deneb(pre.best_valid_update)
    return LightClientStore(
        finalized_header=upgrade_lc_header_to_deneb(pre.finalized_header),
        current_sync_committee=pre.current_sync_committee,
        next_sync_committee=pre.next_sync_committee,
        best_valid_update=best_valid_update,
        optimistic_header=upgrade_lc_header_to_deneb(pre.optimistic_header),
        previous_max_active_participants=(
            pre.previous_max_active_participants),
        current_max_active_participants=pre.current_max_active_participants,
    )
