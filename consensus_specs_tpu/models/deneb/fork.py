# Deneb -- Fork Logic (executable spec source).
# Parity contract: specs/deneb/fork.md.


def compute_fork_version(epoch: Epoch) -> Version:
    """Fork version at `epoch`."""
    if epoch >= config.DENEB_FORK_EPOCH:
        return config.DENEB_FORK_VERSION
    if epoch >= config.CAPELLA_FORK_EPOCH:
        return config.CAPELLA_FORK_VERSION
    if epoch >= config.BELLATRIX_FORK_EPOCH:
        return config.BELLATRIX_FORK_VERSION
    if epoch >= config.ALTAIR_FORK_EPOCH:
        return config.ALTAIR_FORK_VERSION
    return config.GENESIS_FORK_VERSION


def upgrade_to_deneb(pre) -> BeaconState:
    """capella -> deneb state upgrade (fork.md `upgrade_to_deneb`)."""
    epoch = compute_epoch_at_slot(pre.slot)
    h = pre.latest_execution_payload_header
    latest_execution_payload_header = ExecutionPayloadHeader(
        parent_hash=h.parent_hash,
        fee_recipient=h.fee_recipient,
        state_root=h.state_root,
        receipts_root=h.receipts_root,
        logs_bloom=h.logs_bloom,
        prev_randao=h.prev_randao,
        block_number=h.block_number,
        gas_limit=h.gas_limit,
        gas_used=h.gas_used,
        timestamp=h.timestamp,
        extra_data=h.extra_data,
        base_fee_per_gas=h.base_fee_per_gas,
        block_hash=h.block_hash,
        transactions_root=h.transactions_root,
        withdrawals_root=h.withdrawals_root,
        # [New in Deneb:EIP4844]
        blob_gas_used=uint64(0),
        excess_blob_gas=uint64(0),
    )
    post = BeaconState(
        genesis_time=pre.genesis_time,
        genesis_validators_root=pre.genesis_validators_root,
        slot=pre.slot,
        fork=Fork(
            previous_version=pre.fork.current_version,
            # [Modified in Deneb]
            current_version=config.DENEB_FORK_VERSION,
            epoch=epoch,
        ),
        latest_block_header=pre.latest_block_header,
        block_roots=pre.block_roots,
        state_roots=pre.state_roots,
        historical_roots=pre.historical_roots,
        eth1_data=pre.eth1_data,
        eth1_data_votes=pre.eth1_data_votes,
        eth1_deposit_index=pre.eth1_deposit_index,
        validators=pre.validators,
        balances=pre.balances,
        randao_mixes=pre.randao_mixes,
        slashings=pre.slashings,
        previous_epoch_participation=pre.previous_epoch_participation,
        current_epoch_participation=pre.current_epoch_participation,
        justification_bits=pre.justification_bits,
        previous_justified_checkpoint=pre.previous_justified_checkpoint,
        current_justified_checkpoint=pre.current_justified_checkpoint,
        finalized_checkpoint=pre.finalized_checkpoint,
        inactivity_scores=pre.inactivity_scores,
        current_sync_committee=pre.current_sync_committee,
        next_sync_committee=pre.next_sync_committee,
        # [Modified in Deneb:EIP4844]
        latest_execution_payload_header=latest_execution_payload_header,
        next_withdrawal_index=pre.next_withdrawal_index,
        next_withdrawal_validator_index=pre.next_withdrawal_validator_index,
        historical_summaries=pre.historical_summaries,
    )

    return post
