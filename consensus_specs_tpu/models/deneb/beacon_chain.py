# Deneb -- The Beacon Chain (executable spec source, delta over capella).
#
# EIP-4844 blobs (KZG commitments in the block body, versioned hashes to
# the EL), EIP-4788 (parent beacon root to the EL), EIP-7044 (fixed exit
# domain), EIP-7045 (extended attestation inclusion), EIP-7514
# (activation churn cap).  Parity contract: specs/deneb/beacon-chain.md
# (types :59-72, containers :101-210, helpers :212-274, engine :276-366,
#  block processing :368-507, epoch processing :509-545).

# ---------------------------------------------------------------------------
# Custom types + constants (beacon-chain.md :59-72)
# ---------------------------------------------------------------------------


class VersionedHash(Bytes32):
    pass


class BlobIndex(uint64):
    pass


VERSIONED_HASH_VERSION_KZG = Bytes1("0x01")


# ---------------------------------------------------------------------------
# Containers (beacon-chain.md :101-210)
# ---------------------------------------------------------------------------


class ExecutionPayload(Container):
    parent_hash: Hash32
    fee_recipient: ExecutionAddress
    state_root: Bytes32
    receipts_root: Bytes32
    logs_bloom: ByteVector[BYTES_PER_LOGS_BLOOM]
    prev_randao: Bytes32
    block_number: uint64
    gas_limit: uint64
    gas_used: uint64
    timestamp: uint64
    extra_data: ByteList[MAX_EXTRA_DATA_BYTES]
    base_fee_per_gas: uint256
    block_hash: Hash32
    transactions: List[Transaction, MAX_TRANSACTIONS_PER_PAYLOAD]
    withdrawals: List[Withdrawal, MAX_WITHDRAWALS_PER_PAYLOAD]
    # [New in Deneb:EIP4844]
    blob_gas_used: uint64
    # [New in Deneb:EIP4844]
    excess_blob_gas: uint64


class ExecutionPayloadHeader(Container):
    parent_hash: Hash32
    fee_recipient: ExecutionAddress
    state_root: Bytes32
    receipts_root: Bytes32
    logs_bloom: ByteVector[BYTES_PER_LOGS_BLOOM]
    prev_randao: Bytes32
    block_number: uint64
    gas_limit: uint64
    gas_used: uint64
    timestamp: uint64
    extra_data: ByteList[MAX_EXTRA_DATA_BYTES]
    base_fee_per_gas: uint256
    block_hash: Hash32
    transactions_root: Root
    withdrawals_root: Root
    # [New in Deneb:EIP4844]
    blob_gas_used: uint64
    # [New in Deneb:EIP4844]
    excess_blob_gas: uint64


class BeaconBlockBody(Container):
    randao_reveal: BLSSignature
    eth1_data: Eth1Data
    graffiti: Bytes32
    proposer_slashings: List[ProposerSlashing, MAX_PROPOSER_SLASHINGS]
    attester_slashings: List[AttesterSlashing, MAX_ATTESTER_SLASHINGS]
    attestations: List[Attestation, MAX_ATTESTATIONS]
    deposits: List[Deposit, MAX_DEPOSITS]
    voluntary_exits: List[SignedVoluntaryExit, MAX_VOLUNTARY_EXITS]
    sync_aggregate: SyncAggregate
    # [Modified in Deneb:EIP4844]
    execution_payload: ExecutionPayload
    bls_to_execution_changes: List[SignedBLSToExecutionChange, MAX_BLS_TO_EXECUTION_CHANGES]
    # [New in Deneb:EIP4844]
    blob_kzg_commitments: List[KZGCommitment, MAX_BLOB_COMMITMENTS_PER_BLOCK]


class BeaconBlock(Container):
    slot: Slot
    proposer_index: ValidatorIndex
    parent_root: Root
    state_root: Root
    body: BeaconBlockBody


class SignedBeaconBlock(Container):
    message: BeaconBlock
    signature: BLSSignature


class BeaconState(Container):
    genesis_time: uint64
    genesis_validators_root: Root
    slot: Slot
    fork: Fork
    latest_block_header: BeaconBlockHeader
    block_roots: Vector[Root, SLOTS_PER_HISTORICAL_ROOT]
    state_roots: Vector[Root, SLOTS_PER_HISTORICAL_ROOT]
    historical_roots: List[Root, HISTORICAL_ROOTS_LIMIT]
    eth1_data: Eth1Data
    eth1_data_votes: List[Eth1Data, EPOCHS_PER_ETH1_VOTING_PERIOD * SLOTS_PER_EPOCH]
    eth1_deposit_index: uint64
    validators: List[Validator, VALIDATOR_REGISTRY_LIMIT]
    balances: List[Gwei, VALIDATOR_REGISTRY_LIMIT]
    randao_mixes: Vector[Bytes32, EPOCHS_PER_HISTORICAL_VECTOR]
    slashings: Vector[Gwei, EPOCHS_PER_SLASHINGS_VECTOR]
    previous_epoch_participation: List[ParticipationFlags, VALIDATOR_REGISTRY_LIMIT]
    current_epoch_participation: List[ParticipationFlags, VALIDATOR_REGISTRY_LIMIT]
    justification_bits: Bitvector[JUSTIFICATION_BITS_LENGTH]
    previous_justified_checkpoint: Checkpoint
    current_justified_checkpoint: Checkpoint
    finalized_checkpoint: Checkpoint
    inactivity_scores: List[uint64, VALIDATOR_REGISTRY_LIMIT]
    current_sync_committee: SyncCommittee
    next_sync_committee: SyncCommittee
    # [Modified in Deneb:EIP4844]
    latest_execution_payload_header: ExecutionPayloadHeader
    next_withdrawal_index: WithdrawalIndex
    next_withdrawal_validator_index: ValidatorIndex
    historical_summaries: List[HistoricalSummary, HISTORICAL_ROOTS_LIMIT]


# ---------------------------------------------------------------------------
# Helpers (beacon-chain.md :212-274)
# ---------------------------------------------------------------------------


def kzg_commitment_to_versioned_hash(
        kzg_commitment: KZGCommitment) -> VersionedHash:
    return VERSIONED_HASH_VERSION_KZG + hash(kzg_commitment)[1:]


def get_attestation_participation_flag_indices(
        state: BeaconState, data: AttestationData,
        inclusion_delay: uint64) -> Sequence[int]:
    """Flag indices an attestation satisfies; the target flag no longer
    depends on inclusion delay (EIP-7045)."""
    if data.target.epoch == get_current_epoch(state):
        justified_checkpoint = state.current_justified_checkpoint
    else:
        justified_checkpoint = state.previous_justified_checkpoint

    # Matching roots
    is_matching_source = data.source == justified_checkpoint
    is_matching_target = (is_matching_source
                          and data.target.root
                          == get_block_root(state, data.target.epoch))
    is_matching_head = (is_matching_target
                        and data.beacon_block_root
                        == get_block_root_at_slot(state, data.slot))
    assert is_matching_source

    participation_flag_indices = []
    if (is_matching_source
            and inclusion_delay <= integer_squareroot(SLOTS_PER_EPOCH)):
        participation_flag_indices.append(TIMELY_SOURCE_FLAG_INDEX)
    if is_matching_target:  # [Modified in Deneb:EIP7045]
        participation_flag_indices.append(TIMELY_TARGET_FLAG_INDEX)
    if is_matching_head and inclusion_delay == MIN_ATTESTATION_INCLUSION_DELAY:
        participation_flag_indices.append(TIMELY_HEAD_FLAG_INDEX)

    return participation_flag_indices


def get_validator_activation_churn_limit(state: BeaconState) -> uint64:
    """Activation churn limit, capped by EIP-7514."""
    return min(config.MAX_PER_EPOCH_ACTIVATION_CHURN_LIMIT,
               get_validator_churn_limit(state))


# ---------------------------------------------------------------------------
# Execution engine (beacon-chain.md :276-366)
# ---------------------------------------------------------------------------


@dataclass
class NewPayloadRequest(object):
    execution_payload: ExecutionPayload
    versioned_hashes: Sequence[VersionedHash]
    parent_beacon_block_root: Root


class ExecutionEngine:
    """EL protocol, extended with versioned-hash and parent-root checks
    (EIP-4844/4788)."""

    def notify_new_payload(self, execution_payload: ExecutionPayload,
                           parent_beacon_block_root: Root) -> bool:
        raise NotImplementedError

    def is_valid_block_hash(self, execution_payload: ExecutionPayload,
                            parent_beacon_block_root: Root) -> bool:
        raise NotImplementedError

    def is_valid_versioned_hashes(self, new_payload_request) -> bool:
        raise NotImplementedError

    def verify_and_notify_new_payload(self, new_payload_request) -> bool:
        execution_payload = new_payload_request.execution_payload
        # [New in Deneb:EIP4788]
        parent_beacon_block_root = new_payload_request.parent_beacon_block_root

        if b"" in execution_payload.transactions:
            return False

        # [Modified in Deneb:EIP4788]
        if not self.is_valid_block_hash(execution_payload,
                                        parent_beacon_block_root):
            return False

        # [New in Deneb:EIP4844]
        if not self.is_valid_versioned_hashes(new_payload_request):
            return False

        # [Modified in Deneb:EIP4788]
        if not self.notify_new_payload(execution_payload,
                                       parent_beacon_block_root):
            return False

        return True

    def notify_forkchoice_updated(self, head_block_hash, safe_block_hash,
                                  finalized_block_hash, payload_attributes):
        raise NotImplementedError

    def get_payload(self, payload_id):
        raise NotImplementedError


class NoopExecutionEngine(ExecutionEngine):
    """Accept-everything EL stub (`pysetup/spec_builders/deneb.py:46-79`)."""

    def notify_new_payload(self, execution_payload,
                           parent_beacon_block_root) -> bool:
        return True

    def notify_forkchoice_updated(self, head_block_hash, safe_block_hash,
                                  finalized_block_hash, payload_attributes):
        pass

    def get_payload(self, payload_id):
        raise NotImplementedError("no default block production")

    def is_valid_block_hash(self, execution_payload,
                            parent_beacon_block_root) -> bool:
        return True

    def is_valid_versioned_hashes(self, new_payload_request) -> bool:
        return True

    def verify_and_notify_new_payload(self, new_payload_request) -> bool:
        return True


EXECUTION_ENGINE = NoopExecutionEngine()


# ---------------------------------------------------------------------------
# Block processing (beacon-chain.md :368-507)
# ---------------------------------------------------------------------------


def process_attestation(state: BeaconState, attestation: Attestation) -> None:
    """Valid inclusion now extends through target.epoch + 1 (EIP-7045)."""
    data = attestation.data
    assert data.target.epoch in (get_previous_epoch(state),
                                 get_current_epoch(state))
    assert data.target.epoch == compute_epoch_at_slot(data.slot)
    # [Modified in Deneb:EIP7045] no upper bound on inclusion slot
    assert data.slot + MIN_ATTESTATION_INCLUSION_DELAY <= state.slot
    assert data.index < get_committee_count_per_slot(state, data.target.epoch)

    committee = get_beacon_committee(state, data.slot, data.index)
    assert len(attestation.aggregation_bits) == len(committee)

    # Participation flag indices
    participation_flag_indices = get_attestation_participation_flag_indices(
        state, data, state.slot - data.slot)

    # Verify signature
    assert is_valid_indexed_attestation(
        state, get_indexed_attestation(state, attestation))

    # Update epoch participation flags
    if data.target.epoch == get_current_epoch(state):
        epoch_participation = state.current_epoch_participation
    else:
        epoch_participation = state.previous_epoch_participation

    proposer_reward_numerator = 0
    for index in get_attesting_indices(state, attestation):
        for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
            if (flag_index in participation_flag_indices
                    and not has_flag(epoch_participation[index], flag_index)):
                epoch_participation[index] = add_flag(
                    epoch_participation[index], flag_index)
                proposer_reward_numerator += get_base_reward(state, index) * weight

    # Reward proposer
    proposer_reward_denominator = ((WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
                                   * WEIGHT_DENOMINATOR // PROPOSER_WEIGHT)
    proposer_reward = Gwei(proposer_reward_numerator
                           // proposer_reward_denominator)
    increase_balance(state, get_beacon_proposer_index(state), proposer_reward)


def process_execution_payload(state: BeaconState, body: BeaconBlockBody,
                              execution_engine: ExecutionEngine) -> None:
    payload = body.execution_payload

    # Verify consistency with the previous execution payload header
    assert payload.parent_hash == state.latest_execution_payload_header.block_hash
    # Verify prev_randao
    assert payload.prev_randao == get_randao_mix(state, get_current_epoch(state))
    # Verify timestamp
    assert payload.timestamp == compute_time_at_slot(state, state.slot)

    # [New in Deneb:EIP4844] Verify commitments are under limit
    assert len(body.blob_kzg_commitments) <= config.MAX_BLOBS_PER_BLOCK

    # Verify the execution payload is valid
    # [Modified in Deneb:EIP4844+EIP4788]
    versioned_hashes = [kzg_commitment_to_versioned_hash(commitment)
                        for commitment in body.blob_kzg_commitments]
    assert execution_engine.verify_and_notify_new_payload(
        NewPayloadRequest(
            execution_payload=payload,
            versioned_hashes=versioned_hashes,
            parent_beacon_block_root=state.latest_block_header.parent_root,
        ))

    # Cache execution payload header
    state.latest_execution_payload_header = ExecutionPayloadHeader(
        parent_hash=payload.parent_hash,
        fee_recipient=payload.fee_recipient,
        state_root=payload.state_root,
        receipts_root=payload.receipts_root,
        logs_bloom=payload.logs_bloom,
        prev_randao=payload.prev_randao,
        block_number=payload.block_number,
        gas_limit=payload.gas_limit,
        gas_used=payload.gas_used,
        timestamp=payload.timestamp,
        extra_data=payload.extra_data,
        base_fee_per_gas=payload.base_fee_per_gas,
        block_hash=payload.block_hash,
        transactions_root=hash_tree_root(payload.transactions),
        withdrawals_root=hash_tree_root(payload.withdrawals),
        blob_gas_used=payload.blob_gas_used,  # [New in Deneb:EIP4844]
        excess_blob_gas=payload.excess_blob_gas,  # [New in Deneb:EIP4844]
    )


def process_voluntary_exit(state: BeaconState,
                           signed_voluntary_exit: SignedVoluntaryExit) -> None:
    """Exit signatures are locked to CAPELLA_FORK_VERSION (EIP-7044)."""
    voluntary_exit = signed_voluntary_exit.message
    validator = state.validators[voluntary_exit.validator_index]
    # Verify the validator is active
    assert is_active_validator(validator, get_current_epoch(state))
    # Verify exit has not been initiated
    assert validator.exit_epoch == FAR_FUTURE_EPOCH
    # Exits are not valid before their epoch
    assert get_current_epoch(state) >= voluntary_exit.epoch
    # Verify the validator has been active long enough
    assert (get_current_epoch(state)
            >= validator.activation_epoch + config.SHARD_COMMITTEE_PERIOD)
    # Verify signature
    # [Modified in Deneb:EIP7044]
    domain = compute_domain(DOMAIN_VOLUNTARY_EXIT,
                            config.CAPELLA_FORK_VERSION,
                            state.genesis_validators_root)
    signing_root = compute_signing_root(voluntary_exit, domain)
    assert bls.Verify(validator.pubkey, signing_root,
                      signed_voluntary_exit.signature)
    # Initiate exit
    initiate_validator_exit(state, voluntary_exit.validator_index)


# ---------------------------------------------------------------------------
# Epoch processing (beacon-chain.md :509-545)
# ---------------------------------------------------------------------------


def process_registry_updates(state: BeaconState) -> None:
    """Activations rate-limited by the EIP-7514 churn cap."""
    # Process activation eligibility and ejections
    for index, validator in enumerate(state.validators):
        if is_eligible_for_activation_queue(validator):
            validator.activation_eligibility_epoch = get_current_epoch(state) + 1

        if (is_active_validator(validator, get_current_epoch(state))
                and validator.effective_balance <= config.EJECTION_BALANCE):
            initiate_validator_exit(state, ValidatorIndex(index))

    # Queue validators eligible for activation, ordered by eligibility
    activation_queue = sorted(
        [index for index, validator in enumerate(state.validators)
         if is_eligible_for_activation(state, validator)],
        key=lambda index: (
            state.validators[index].activation_eligibility_epoch, index),
    )
    # Dequeue up to the activation churn limit
    # [Modified in Deneb:EIP7514]
    for index in activation_queue[:get_validator_activation_churn_limit(state)]:
        validator = state.validators[index]
        validator.activation_epoch = compute_activation_exit_epoch(
            get_current_epoch(state))
