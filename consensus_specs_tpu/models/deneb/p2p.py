# Deneb -- p2p pure functions: blob sidecars.
# Parity contract: specs/deneb/p2p-interface.md (:70-135).


class BlobSidecar(Container):
    index: BlobIndex
    blob: Blob
    kzg_commitment: KZGCommitment
    kzg_proof: KZGProof
    signed_block_header: SignedBeaconBlockHeader
    kzg_commitment_inclusion_proof: Vector[Bytes32, KZG_COMMITMENT_INCLUSION_PROOF_DEPTH]


class BlobIdentifier(Container):
    block_root: Root
    index: BlobIndex


def verify_blob_sidecar_inclusion_proof(blob_sidecar: BlobSidecar) -> bool:
    """Merkle proof of the commitment's membership in the block body."""
    gindex = get_subtree_index(get_generalized_index(
        BeaconBlockBody, "blob_kzg_commitments", int(blob_sidecar.index)))
    return is_valid_merkle_branch(
        leaf=hash_tree_root(blob_sidecar.kzg_commitment),
        branch=blob_sidecar.kzg_commitment_inclusion_proof,
        depth=KZG_COMMITMENT_INCLUSION_PROOF_DEPTH,
        index=gindex,
        root=blob_sidecar.signed_block_header.message.body_root,
    )
