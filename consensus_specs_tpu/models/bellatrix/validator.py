# Bellatrix -- Honest Validator (executable spec source, delta).
# Parity contract: specs/bellatrix/validator.md (:44-215).


def get_pow_block_at_terminal_total_difficulty(pow_chain):
    """First PoW block crossing TTD whose parent has not
    (validator.md :51-67)."""
    # pow_chain abstractly represents all blocks in the PoW chain
    for block in pow_chain.values():
        block_reached_ttd = (block.total_difficulty
                             >= config.TERMINAL_TOTAL_DIFFICULTY)
        if block_reached_ttd:
            # Genesis block: reaching TTD alone qualifies
            if block.parent_hash == Hash32():
                return block
            parent = pow_chain[block.parent_hash]
            parent_reached_ttd = (parent.total_difficulty
                                  >= config.TERMINAL_TOTAL_DIFFICULTY)
            if not parent_reached_ttd:
                return block

    return None


def get_terminal_pow_block(pow_chain):
    if config.TERMINAL_BLOCK_HASH != Hash32():
        # Terminal block hash override takes precedence over TTD
        if config.TERMINAL_BLOCK_HASH in pow_chain:
            return pow_chain[config.TERMINAL_BLOCK_HASH]
        return None

    return get_pow_block_at_terminal_total_difficulty(pow_chain)


def prepare_execution_payload(state: BeaconState, safe_block_hash: Hash32,
                              finalized_block_hash: Hash32,
                              suggested_fee_recipient: ExecutionAddress,
                              execution_engine: ExecutionEngine,
                              pow_chain=None):
    """Kick off payload building via fcU; returns the PayloadId or None
    pre-merge (validator.md :145-186)."""
    if not is_merge_transition_complete(state):
        assert pow_chain is not None
        is_terminal_block_hash_set = config.TERMINAL_BLOCK_HASH != Hash32()
        is_activation_epoch_reached = (
            get_current_epoch(state)
            >= config.TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH)
        if is_terminal_block_hash_set and not is_activation_epoch_reached:
            # Terminal hash override set but not yet active
            return None

        terminal_pow_block = get_terminal_pow_block(pow_chain)
        if terminal_pow_block is None:
            # Pre-merge, no prepare payload call is needed
            return None
        # Signify merge via producing on top of the terminal PoW block
        parent_hash = terminal_pow_block.block_hash
    else:
        # Post-merge, normal payload
        parent_hash = state.latest_execution_payload_header.block_hash

    # Set the forkchoice head and initiate the payload build process
    payload_attributes = PayloadAttributes(
        timestamp=compute_time_at_slot(state, state.slot),
        prev_randao=get_randao_mix(state, get_current_epoch(state)),
        suggested_fee_recipient=suggested_fee_recipient,
    )
    return execution_engine.notify_forkchoice_updated(
        head_block_hash=parent_hash,
        safe_block_hash=safe_block_hash,
        finalized_block_hash=finalized_block_hash,
        payload_attributes=payload_attributes,
    )


def get_execution_payload(payload_id,
                          execution_engine: ExecutionEngine) -> ExecutionPayload:
    if payload_id is None:
        # Pre-merge, empty payload
        return ExecutionPayload()
    return execution_engine.get_payload(payload_id).execution_payload
