# Bellatrix -- Optimistic sync (executable spec source).
# Parity contract: sync/optimistic.md (:50-123 store + helpers, :138-260
# import conditions and NOT_VALIDATED transition machinery).

SAFE_SLOTS_TO_IMPORT_OPTIMISTICALLY = 128


@dataclass
class OptimisticStore(object):
    optimistic_roots: Set[Root]
    head_block_root: Root
    blocks: Dict[Root, BeaconBlock] = field(default_factory=dict)
    block_states: Dict[Root, BeaconState] = field(default_factory=dict)


def is_optimistic(opt_store: OptimisticStore, block: BeaconBlock) -> bool:
    return hash_tree_root(block) in opt_store.optimistic_roots


def latest_verified_ancestor(opt_store: OptimisticStore,
                             block: BeaconBlock) -> BeaconBlock:
    # It is assumed that the `block` parameter is never an INVALIDATED block.
    while True:
        if not is_optimistic(opt_store, block) or block.parent_root == Root():
            return block
        block = opt_store.blocks[block.parent_root]


def is_execution_block(block: BeaconBlock) -> bool:
    return block.body.execution_payload != ExecutionPayload()


def is_optimistic_candidate_block(opt_store: OptimisticStore,
                                  current_slot: Slot,
                                  block: BeaconBlock) -> bool:
    if is_execution_block(opt_store.blocks[block.parent_root]):
        return True

    if block.slot + SAFE_SLOTS_TO_IMPORT_OPTIMISTICALLY <= current_slot:
        return True

    return False


def mark_block_valid(opt_store: OptimisticStore, block_root: Root) -> None:
    """NOT_VALIDATED -> VALID: the block and all its optimistic ancestors
    leave the optimistic set (sync/optimistic.md :225-232)."""
    block = opt_store.blocks[block_root]
    while True:
        opt_store.optimistic_roots.discard(hash_tree_root(block))
        if block.parent_root == Root() \
                or block.parent_root not in opt_store.blocks:
            return
        parent = opt_store.blocks[block.parent_root]
        if hash_tree_root(parent) not in opt_store.optimistic_roots:
            return
        block = parent


def mark_block_invalidated(opt_store: OptimisticStore,
                           block_root: Root) -> None:
    """NOT_VALIDATED -> INVALIDATED: the block and all its descendants are
    removed from the optimistic store (sync/optimistic.md :234-241)."""
    invalidated = {block_root}
    # repeatedly sweep for descendants of the invalidated set
    changed = True
    while changed:
        changed = False
        for root, blk in list(opt_store.blocks.items()):
            if root in invalidated:
                continue
            if blk.parent_root in invalidated:
                invalidated.add(root)
                changed = True
    for root in invalidated:
        opt_store.optimistic_roots.discard(root)
        opt_store.blocks.pop(root, None)
        opt_store.block_states.pop(root, None)


def get_invalidated_block_roots(opt_store: OptimisticStore,
                                block_root: Root,
                                latest_valid_hash: Hash32) -> Set[Root]:
    """The blocks to invalidate for an INVALID payload status with the
    given latestValidHash (sync/optimistic.md latestValidHash table):
    everything in the chain of `block_root` *after* the block whose payload
    hash equals latest_valid_hash; the whole execution chain when the hash
    is all zeroes or unknown."""
    chain = []
    root = block_root
    while root in opt_store.blocks:
        block = opt_store.blocks[root]
        chain.append((root, block))
        if block.body.execution_payload.block_hash == latest_valid_hash \
                and latest_valid_hash != Hash32():
            # blocks after this one (walked newest->oldest: all collected
            # before, minus this entry) are invalid
            return set(r for r, _ in chain[:-1])
        if block.parent_root == Root():
            break
        root = block.parent_root
    if latest_valid_hash == Hash32():
        # invalidate back to (and excluding) the last pre-execution block
        return set(r for r, b in chain if is_execution_block(b))
    # unknown hash: treat as null -- only the block in question
    return {block_root}
