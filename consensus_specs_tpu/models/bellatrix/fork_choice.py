# Bellatrix -- Fork Choice (executable spec source, delta over phase0).
#
# Adds merge-transition validation to `on_block` and the PoW terminal
# block machinery.  Parity contract: specs/bellatrix/fork-choice.md
# (PowBlock :207, is_valid_terminal_pow_block :227,
#  validate_merge_block :236, on_block :268-330,
#  should_override_forkchoice_update :114) and
# fork_choice/safe-block.md `get_safe_execution_block_hash`.


class PowBlock(Container):
    block_hash: Hash32
    parent_hash: Hash32
    total_difficulty: uint256


def get_pow_block(hash: Bytes32):
    """Stub: real clients query the EL via eth_getBlockByHash
    (`pysetup/spec_builders/bellatrix.py:22-23`); tests monkeypatch."""
    return PowBlock(block_hash=hash, parent_hash=Bytes32(),
                    total_difficulty=uint256(0))


def is_valid_terminal_pow_block(block: PowBlock, parent: PowBlock) -> bool:
    is_total_difficulty_reached = (block.total_difficulty
                                   >= config.TERMINAL_TOTAL_DIFFICULTY)
    is_parent_total_difficulty_valid = (parent.total_difficulty
                                        < config.TERMINAL_TOTAL_DIFFICULTY)
    return is_total_difficulty_reached and is_parent_total_difficulty_valid


def validate_merge_block(block: BeaconBlock) -> None:
    """Check the payload's parent is a valid terminal PoW block.
    Unavailable PoW blocks may become available later; callers MAY delay
    (fork-choice.md :236-261)."""
    if config.TERMINAL_BLOCK_HASH != Hash32():
        # Terminal-hash override: the activation epoch must be reached
        assert (compute_epoch_at_slot(block.slot)
                >= config.TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH)
        assert (block.body.execution_payload.parent_hash
                == config.TERMINAL_BLOCK_HASH)
        return

    pow_block = get_pow_block(block.body.execution_payload.parent_hash)
    assert pow_block is not None
    pow_parent = get_pow_block(pow_block.parent_hash)
    assert pow_parent is not None
    assert is_valid_terminal_pow_block(pow_block, pow_parent)


def on_block(store: Store, signed_block: SignedBeaconBlock) -> None:
    """phase0 on_block + merge-transition validation
    (fork-choice.md :268-330)."""
    block = signed_block.message
    # Parent must be known
    assert block.parent_root in store.block_states
    pre_state = copy(store.block_states[block.parent_root])
    # Future blocks wait until their slot arrives
    assert get_current_slot(store) >= block.slot

    # Must descend from (and be after) the finalized checkpoint
    finalized_slot = compute_start_slot_at_epoch(
        store.finalized_checkpoint.epoch)
    assert block.slot > finalized_slot
    finalized_checkpoint_block = get_checkpoint_block(
        store, block.parent_root, store.finalized_checkpoint.epoch)
    assert store.finalized_checkpoint.root == finalized_checkpoint_block

    # Full state transition (asserts internally on invalid blocks)
    state = pre_state.copy()
    block_root = hash_tree_root(block)
    state_transition(state, signed_block, True)

    # [New in Bellatrix]
    if is_merge_transition_block(pre_state, block.body):
        validate_merge_block(block)

    store.blocks[block_root] = block
    store.block_states[block_root] = state

    # Timeliness: arrived in its own slot, before the attesting interval
    time_into_slot = ((store.time - store.genesis_time)
                      % config.SECONDS_PER_SLOT)
    is_before_attesting_interval = (
        time_into_slot < config.SECONDS_PER_SLOT // INTERVALS_PER_SLOT)
    is_timely = (get_current_slot(store) == block.slot
                 and is_before_attesting_interval)
    store.block_timeliness[block_root] = is_timely

    # Boost the first timely block of the slot
    if is_timely and store.proposer_boost_root == Root():
        store.proposer_boost_root = block_root

    update_checkpoints(store, state.current_justified_checkpoint,
                       state.finalized_checkpoint)
    compute_pulled_up_tip(store, block_root)


def should_override_forkchoice_update(store: Store, head_root: Root) -> bool:
    """Whether a proposing node should withhold the fcU for a weak head
    it intends to re-org (fork-choice.md :114-186)."""
    head_block = store.blocks[head_root]
    parent_root = head_block.parent_root
    parent_block = store.blocks[parent_root]
    current_slot = get_current_slot(store)
    proposal_slot = head_block.slot + Slot(1)

    head_late = is_head_late(store, head_root)
    shuffling_stable = is_shuffling_stable(proposal_slot)
    ffg_competitive = is_ffg_competitive(store, head_root, parent_root)
    finalization_ok = is_finalization_ok(store, proposal_slot)

    # Only suppress the fork choice update if we are confident that we
    # will propose the next block
    parent_state_advanced = store.block_states[parent_root].copy()
    process_slots(parent_state_advanced, proposal_slot)
    proposer_index = get_beacon_proposer_index(parent_state_advanced)
    proposing_reorg_slot = validator_is_connected(proposer_index)

    # Single-slot re-org
    parent_slot_ok = parent_block.slot + 1 == head_block.slot
    proposing_on_time = is_proposing_on_time(store)

    # Note that this condition is different from `get_proposer_head`
    current_time_ok = head_block.slot == current_slot or (
        proposal_slot == current_slot and is_proposing_on_time(store))
    single_slot_reorg = parent_slot_ok and current_time_ok

    # Check the head weight only if the attestations from the head slot
    # have already been applied
    if current_slot > head_block.slot:
        head_weak = is_head_weak(store, head_root)
        parent_strong = is_parent_strong(store, parent_root)
    else:
        head_weak = True
        parent_strong = True

    return all([head_late, shuffling_stable, ffg_competitive,
                finalization_ok, proposing_reorg_slot, single_slot_reorg,
                head_weak, parent_strong])


def get_safe_execution_block_hash(store: Store) -> Hash32:
    """Execution block hash of the safe beacon block
    (fork_choice/safe-block.md)."""
    safe_block_root = get_safe_beacon_block_root(store)
    safe_block = store.blocks[safe_block_root]
    # Return Hash32() if no payload is yet available (pre-merge)
    if compute_epoch_at_slot(safe_block.slot) >= config.BELLATRIX_FORK_EPOCH:
        return safe_block.body.execution_payload.block_hash
    return Hash32()
