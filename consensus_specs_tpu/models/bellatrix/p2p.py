# Bellatrix -- p2p deltas: the Merge keeps the altair wire surface; the
# only executable change is the gossip block-validity condition around
# execution payloads (specs/bellatrix/p2p-interface.md, beacon_block topic
# conditions) -- everything else is payload-type swaps handled by the
# container overrides in beacon_chain.py.


def is_valid_gossip_execution_payload_timestamp(
        state: BeaconState, block: BeaconBlock) -> bool:
    """beacon_block gossip condition: the payload timestamp must match the
    slot (bellatrix/p2p-interface.md beacon_block validation)."""
    if not is_execution_enabled(state, block.body):
        return True
    return (block.body.execution_payload.timestamp
            == compute_time_at_slot(state, block.slot))
