# Bellatrix -- The Beacon Chain (executable spec source, delta over altair).
#
# The Merge: execution payloads enter the beacon block, the ExecutionEngine
# protocol abstracts the EL, and penalty parameters reach their final
# values.  Parity contract: specs/bellatrix/beacon-chain.md
# (types :53-60, containers :97-197, predicates :203-222, engine :291-360,
# block processing :362-417, epoch processing :419-440); the
# NoopExecutionEngine mirrors the reference's build-time stub
# (`pysetup/spec_builders/bellatrix.py` execution_engine_cls).

# ---------------------------------------------------------------------------
# Custom types (beacon-chain.md :53-60, fork-choice.md :30-34)
# ---------------------------------------------------------------------------

Transaction = ByteList[MAX_BYTES_PER_TRANSACTION]


class ExecutionAddress(Bytes20):
    pass


class PayloadId(Bytes8):
    pass


# ---------------------------------------------------------------------------
# Containers (beacon-chain.md :97-197)
# ---------------------------------------------------------------------------


class ExecutionPayload(Container):
    parent_hash: Hash32
    fee_recipient: ExecutionAddress
    state_root: Bytes32
    receipts_root: Bytes32
    logs_bloom: ByteVector[BYTES_PER_LOGS_BLOOM]
    prev_randao: Bytes32
    block_number: uint64
    gas_limit: uint64
    gas_used: uint64
    timestamp: uint64
    extra_data: ByteList[MAX_EXTRA_DATA_BYTES]
    base_fee_per_gas: uint256
    block_hash: Hash32
    transactions: List[Transaction, MAX_TRANSACTIONS_PER_PAYLOAD]


class ExecutionPayloadHeader(Container):
    parent_hash: Hash32
    fee_recipient: ExecutionAddress
    state_root: Bytes32
    receipts_root: Bytes32
    logs_bloom: ByteVector[BYTES_PER_LOGS_BLOOM]
    prev_randao: Bytes32
    block_number: uint64
    gas_limit: uint64
    gas_used: uint64
    timestamp: uint64
    extra_data: ByteList[MAX_EXTRA_DATA_BYTES]
    base_fee_per_gas: uint256
    block_hash: Hash32
    transactions_root: Root


class BeaconBlockBody(Container):
    randao_reveal: BLSSignature
    eth1_data: Eth1Data
    graffiti: Bytes32
    proposer_slashings: List[ProposerSlashing, MAX_PROPOSER_SLASHINGS]
    attester_slashings: List[AttesterSlashing, MAX_ATTESTER_SLASHINGS]
    attestations: List[Attestation, MAX_ATTESTATIONS]
    deposits: List[Deposit, MAX_DEPOSITS]
    voluntary_exits: List[SignedVoluntaryExit, MAX_VOLUNTARY_EXITS]
    sync_aggregate: SyncAggregate
    # [New in Bellatrix]
    execution_payload: ExecutionPayload


class BeaconBlock(Container):
    slot: Slot
    proposer_index: ValidatorIndex
    parent_root: Root
    state_root: Root
    body: BeaconBlockBody


class SignedBeaconBlock(Container):
    message: BeaconBlock
    signature: BLSSignature


class BeaconState(Container):
    genesis_time: uint64
    genesis_validators_root: Root
    slot: Slot
    fork: Fork
    latest_block_header: BeaconBlockHeader
    block_roots: Vector[Root, SLOTS_PER_HISTORICAL_ROOT]
    state_roots: Vector[Root, SLOTS_PER_HISTORICAL_ROOT]
    historical_roots: List[Root, HISTORICAL_ROOTS_LIMIT]
    eth1_data: Eth1Data
    eth1_data_votes: List[Eth1Data, EPOCHS_PER_ETH1_VOTING_PERIOD * SLOTS_PER_EPOCH]
    eth1_deposit_index: uint64
    validators: List[Validator, VALIDATOR_REGISTRY_LIMIT]
    balances: List[Gwei, VALIDATOR_REGISTRY_LIMIT]
    randao_mixes: Vector[Bytes32, EPOCHS_PER_HISTORICAL_VECTOR]
    slashings: Vector[Gwei, EPOCHS_PER_SLASHINGS_VECTOR]
    previous_epoch_participation: List[ParticipationFlags, VALIDATOR_REGISTRY_LIMIT]
    current_epoch_participation: List[ParticipationFlags, VALIDATOR_REGISTRY_LIMIT]
    justification_bits: Bitvector[JUSTIFICATION_BITS_LENGTH]
    previous_justified_checkpoint: Checkpoint
    current_justified_checkpoint: Checkpoint
    finalized_checkpoint: Checkpoint
    inactivity_scores: List[uint64, VALIDATOR_REGISTRY_LIMIT]
    current_sync_committee: SyncCommittee
    next_sync_committee: SyncCommittee
    # [New in Bellatrix]
    latest_execution_payload_header: ExecutionPayloadHeader


# ---------------------------------------------------------------------------
# Predicates (beacon-chain.md :203-222)
# ---------------------------------------------------------------------------


def is_merge_transition_complete(state: BeaconState) -> bool:
    return state.latest_execution_payload_header != ExecutionPayloadHeader()


def is_merge_transition_block(state: BeaconState,
                              body: BeaconBlockBody) -> bool:
    return (not is_merge_transition_complete(state)
            and body.execution_payload != ExecutionPayload())


def is_execution_enabled(state: BeaconState, body: BeaconBlockBody) -> bool:
    return (is_merge_transition_block(state, body)
            or is_merge_transition_complete(state))


# ---------------------------------------------------------------------------
# Modified accessors / mutators (beacon-chain.md :226-287)
# ---------------------------------------------------------------------------


def get_inactivity_penalty_deltas(state: BeaconState):
    """Inactivity penalties with the final (bellatrix) quotient."""
    rewards = [Gwei(0) for _ in range(len(state.validators))]
    penalties = [Gwei(0) for _ in range(len(state.validators))]
    previous_epoch = get_previous_epoch(state)
    matching_target_indices = get_unslashed_participating_indices(
        state, TIMELY_TARGET_FLAG_INDEX, previous_epoch)
    for index in get_eligible_validator_indices(state):
        if index not in matching_target_indices:
            penalty_numerator = (state.validators[index].effective_balance
                                 * state.inactivity_scores[index])
            # [Modified in Bellatrix]
            penalty_denominator = (config.INACTIVITY_SCORE_BIAS
                                   * INACTIVITY_PENALTY_QUOTIENT_BELLATRIX)
            penalties[index] += Gwei(penalty_numerator // penalty_denominator)
    return rewards, penalties


def slash_validator(state: BeaconState, slashed_index: ValidatorIndex,
                    whistleblower_index: ValidatorIndex = None) -> None:
    """Slash with the final (bellatrix) penalty quotient."""
    epoch = get_current_epoch(state)
    initiate_validator_exit(state, slashed_index)
    validator = state.validators[slashed_index]
    validator.slashed = True
    validator.withdrawable_epoch = max(
        validator.withdrawable_epoch,
        Epoch(epoch + EPOCHS_PER_SLASHINGS_VECTOR))
    state.slashings[epoch % EPOCHS_PER_SLASHINGS_VECTOR] += validator.effective_balance
    # [Modified in Bellatrix]
    slashing_penalty = (validator.effective_balance
                        // MIN_SLASHING_PENALTY_QUOTIENT_BELLATRIX)
    decrease_balance(state, slashed_index, slashing_penalty)

    # Apply proposer and whistleblower rewards
    proposer_index = get_beacon_proposer_index(state)
    if whistleblower_index is None:
        whistleblower_index = proposer_index
    whistleblower_reward = Gwei(validator.effective_balance
                                // WHISTLEBLOWER_REWARD_QUOTIENT)
    proposer_reward = Gwei(whistleblower_reward * PROPOSER_WEIGHT
                           // WEIGHT_DENOMINATOR)
    increase_balance(state, proposer_index, proposer_reward)
    increase_balance(state, whistleblower_index,
                     Gwei(whistleblower_reward - proposer_reward))


# ---------------------------------------------------------------------------
# Execution engine (beacon-chain.md :291-360)
# ---------------------------------------------------------------------------

ExecutionState = Any


@dataclass
class NewPayloadRequest(object):
    execution_payload: ExecutionPayload


@dataclass
class PayloadAttributes(object):
    timestamp: uint64
    prev_randao: Bytes32
    suggested_fee_recipient: ExecutionAddress


@dataclass
class GetPayloadResponse(object):
    execution_payload: ExecutionPayload


class ExecutionEngine:
    """Implementation-dependent EL protocol; the spec only pins the method
    contracts (beacon-chain.md :303-360, fork-choice.md :38-92,
    validator.md :96-110)."""

    def notify_new_payload(self, execution_payload: ExecutionPayload) -> bool:
        """True iff `execution_payload` is valid wrt the execution state."""
        raise NotImplementedError

    def is_valid_block_hash(self, execution_payload: ExecutionPayload) -> bool:
        """True iff `execution_payload.block_hash` is computed correctly."""
        raise NotImplementedError

    def verify_and_notify_new_payload(
            self, new_payload_request: NewPayloadRequest) -> bool:
        execution_payload = new_payload_request.execution_payload

        if b"" in execution_payload.transactions:
            return False

        if not self.is_valid_block_hash(execution_payload):
            return False

        if not self.notify_new_payload(execution_payload):
            return False

        return True

    def notify_forkchoice_updated(self, head_block_hash: Hash32,
                                  safe_block_hash: Hash32,
                                  finalized_block_hash: Hash32,
                                  payload_attributes):
        raise NotImplementedError

    def get_payload(self, payload_id: PayloadId) -> GetPayloadResponse:
        raise NotImplementedError


class NoopExecutionEngine(ExecutionEngine):
    """Build-time stub standing in for a real EL
    (`pysetup/spec_builders/bellatrix.py:39-65`); accepts everything."""

    def notify_new_payload(self, execution_payload: ExecutionPayload) -> bool:
        return True

    def notify_forkchoice_updated(self, head_block_hash: Hash32,
                                  safe_block_hash: Hash32,
                                  finalized_block_hash: Hash32,
                                  payload_attributes):
        pass

    def get_payload(self, payload_id: PayloadId) -> GetPayloadResponse:
        raise NotImplementedError("no default block production")

    def is_valid_block_hash(self, execution_payload: ExecutionPayload) -> bool:
        return True

    def verify_and_notify_new_payload(
            self, new_payload_request: NewPayloadRequest) -> bool:
        return True


EXECUTION_ENGINE = NoopExecutionEngine()


# ---------------------------------------------------------------------------
# Block processing (beacon-chain.md :362-417)
# ---------------------------------------------------------------------------


def process_block(state: BeaconState, block: BeaconBlock) -> None:
    process_block_header(state, block)
    # payload before randao: it consumes the PREVIOUS block's randao mix
    if is_execution_enabled(state, block.body):
        process_execution_payload(state, block.body, EXECUTION_ENGINE)  # [New in Bellatrix]
    process_randao(state, block.body)
    process_eth1_data(state, block.body)
    process_operations(state, block.body)
    process_sync_aggregate(state, block.body.sync_aggregate)


def process_execution_payload(state: BeaconState, body: BeaconBlockBody,
                              execution_engine: ExecutionEngine) -> None:
    payload = body.execution_payload

    # Verify consistency with the previous execution payload header
    if is_merge_transition_complete(state):
        assert payload.parent_hash == state.latest_execution_payload_header.block_hash
    # Verify prev_randao
    assert payload.prev_randao == get_randao_mix(state, get_current_epoch(state))
    # Verify timestamp
    assert payload.timestamp == compute_time_at_slot(state, state.slot)
    # Verify the execution payload is valid
    assert execution_engine.verify_and_notify_new_payload(
        NewPayloadRequest(execution_payload=payload))
    # Cache execution payload header
    state.latest_execution_payload_header = ExecutionPayloadHeader(
        parent_hash=payload.parent_hash,
        fee_recipient=payload.fee_recipient,
        state_root=payload.state_root,
        receipts_root=payload.receipts_root,
        logs_bloom=payload.logs_bloom,
        prev_randao=payload.prev_randao,
        block_number=payload.block_number,
        gas_limit=payload.gas_limit,
        gas_used=payload.gas_used,
        timestamp=payload.timestamp,
        extra_data=payload.extra_data,
        base_fee_per_gas=payload.base_fee_per_gas,
        block_hash=payload.block_hash,
        transactions_root=hash_tree_root(payload.transactions),
    )


# ---------------------------------------------------------------------------
# Epoch processing (beacon-chain.md :419-440)
# ---------------------------------------------------------------------------


def process_slashings(state: BeaconState) -> None:
    epoch = get_current_epoch(state)
    total_balance = get_total_active_balance(state)
    adjusted_total_slashing_balance = min(
        sum(state.slashings) * PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX,
        total_balance)
    for index, validator in enumerate(state.validators):
        if (validator.slashed
                and epoch + EPOCHS_PER_SLASHINGS_VECTOR // 2
                == validator.withdrawable_epoch):
            # Factor out the increment to avoid uint64 overflow
            increment = EFFECTIVE_BALANCE_INCREMENT
            penalty_numerator = (validator.effective_balance // increment
                                 * adjusted_total_slashing_balance)
            penalty = penalty_numerator // total_balance * increment
            decrease_balance(state, ValidatorIndex(index), penalty)


# ---------------------------------------------------------------------------
# Sundry EL-facing stubs (`pysetup/spec_builders/bellatrix.py:17-36`)
# ---------------------------------------------------------------------------


def get_execution_state(_execution_state_root: Bytes32) -> ExecutionState:
    pass


def get_pow_chain_head():
    pass


def validator_is_connected(validator_index: ValidatorIndex) -> bool:
    return True
