# Bellatrix -- Fork Logic (executable spec source).
# Parity contract: specs/bellatrix/fork.md (:34-130).


def compute_fork_version(epoch: Epoch) -> Version:
    """Fork version at `epoch`."""
    if epoch >= config.BELLATRIX_FORK_EPOCH:
        return config.BELLATRIX_FORK_VERSION
    if epoch >= config.ALTAIR_FORK_EPOCH:
        return config.ALTAIR_FORK_VERSION
    return config.GENESIS_FORK_VERSION


def upgrade_to_bellatrix(pre) -> BeaconState:
    """altair -> bellatrix state upgrade (fork.md `upgrade_to_bellatrix`)."""
    epoch = compute_epoch_at_slot(pre.slot)
    post = BeaconState(
        genesis_time=pre.genesis_time,
        genesis_validators_root=pre.genesis_validators_root,
        slot=pre.slot,
        fork=Fork(
            previous_version=pre.fork.current_version,
            # [New in Bellatrix]
            current_version=config.BELLATRIX_FORK_VERSION,
            epoch=epoch,
        ),
        latest_block_header=pre.latest_block_header,
        block_roots=pre.block_roots,
        state_roots=pre.state_roots,
        historical_roots=pre.historical_roots,
        eth1_data=pre.eth1_data,
        eth1_data_votes=pre.eth1_data_votes,
        eth1_deposit_index=pre.eth1_deposit_index,
        validators=pre.validators,
        balances=pre.balances,
        randao_mixes=pre.randao_mixes,
        slashings=pre.slashings,
        previous_epoch_participation=pre.previous_epoch_participation,
        current_epoch_participation=pre.current_epoch_participation,
        justification_bits=pre.justification_bits,
        previous_justified_checkpoint=pre.previous_justified_checkpoint,
        current_justified_checkpoint=pre.current_justified_checkpoint,
        finalized_checkpoint=pre.finalized_checkpoint,
        inactivity_scores=pre.inactivity_scores,
        current_sync_committee=pre.current_sync_committee,
        next_sync_committee=pre.next_sync_committee,
        # [New in Bellatrix]
        latest_execution_payload_header=ExecutionPayloadHeader(),
    )

    return post
