# EIP-6800 (Verkle) -- Fork Logic (executable spec source).
# Parity contract: specs/_features/eip6800/fork.md.


def compute_fork_version(epoch: Epoch) -> Version:
    """Fork version at `epoch`."""
    if epoch >= config.EIP6800_FORK_EPOCH:
        return config.EIP6800_FORK_VERSION
    if epoch >= config.DENEB_FORK_EPOCH:
        return config.DENEB_FORK_VERSION
    if epoch >= config.CAPELLA_FORK_EPOCH:
        return config.CAPELLA_FORK_VERSION
    if epoch >= config.BELLATRIX_FORK_EPOCH:
        return config.BELLATRIX_FORK_VERSION
    if epoch >= config.ALTAIR_FORK_EPOCH:
        return config.ALTAIR_FORK_VERSION
    return config.GENESIS_FORK_VERSION


def upgrade_to_eip6800(pre) -> BeaconState:
    """deneb -> eip6800 state upgrade: the committed header gains an
    (empty) execution-witness root (fork.md `upgrade_to_eip6800`)."""
    epoch = compute_epoch_at_slot(pre.slot)
    latest_execution_payload_header = ExecutionPayloadHeader(
        parent_hash=pre.latest_execution_payload_header.parent_hash,
        fee_recipient=pre.latest_execution_payload_header.fee_recipient,
        state_root=pre.latest_execution_payload_header.state_root,
        receipts_root=pre.latest_execution_payload_header.receipts_root,
        logs_bloom=pre.latest_execution_payload_header.logs_bloom,
        prev_randao=pre.latest_execution_payload_header.prev_randao,
        block_number=pre.latest_execution_payload_header.block_number,
        gas_limit=pre.latest_execution_payload_header.gas_limit,
        gas_used=pre.latest_execution_payload_header.gas_used,
        timestamp=pre.latest_execution_payload_header.timestamp,
        extra_data=pre.latest_execution_payload_header.extra_data,
        base_fee_per_gas=pre.latest_execution_payload_header.base_fee_per_gas,
        blob_gas_used=pre.latest_execution_payload_header.blob_gas_used,
        # zeroed at the fork, as the feature spec writes it (the pre
        # state's excess_blob_gas is NOT carried into the renamed field)
        excess_data_gas=0,
        block_hash=pre.latest_execution_payload_header.block_hash,
        transactions_root=pre.latest_execution_payload_header.transactions_root,
        withdrawals_root=pre.latest_execution_payload_header.withdrawals_root,
        # [New in EIP6800]
        execution_witness_root=hash_tree_root(ExecutionWitness()),
    )
    post = BeaconState(
        genesis_time=pre.genesis_time,
        genesis_validators_root=pre.genesis_validators_root,
        slot=pre.slot,
        fork=Fork(
            previous_version=pre.fork.current_version,
            # [Modified in EIP6800]
            current_version=config.EIP6800_FORK_VERSION,
            epoch=epoch,
        ),
        latest_block_header=pre.latest_block_header,
        block_roots=pre.block_roots,
        state_roots=pre.state_roots,
        historical_roots=pre.historical_roots,
        eth1_data=pre.eth1_data,
        eth1_data_votes=pre.eth1_data_votes,
        eth1_deposit_index=pre.eth1_deposit_index,
        validators=pre.validators,
        balances=pre.balances,
        randao_mixes=pre.randao_mixes,
        slashings=pre.slashings,
        previous_epoch_participation=pre.previous_epoch_participation,
        current_epoch_participation=pre.current_epoch_participation,
        justification_bits=pre.justification_bits,
        previous_justified_checkpoint=pre.previous_justified_checkpoint,
        current_justified_checkpoint=pre.current_justified_checkpoint,
        finalized_checkpoint=pre.finalized_checkpoint,
        inactivity_scores=pre.inactivity_scores,
        current_sync_committee=pre.current_sync_committee,
        next_sync_committee=pre.next_sync_committee,
        latest_execution_payload_header=latest_execution_payload_header,
        next_withdrawal_index=pre.next_withdrawal_index,
        next_withdrawal_validator_index=pre.next_withdrawal_validator_index,
        historical_summaries=pre.historical_summaries,
    )

    return post
