# EIP-6800 (Verkle) -- The Beacon Chain (executable spec source, delta
# over deneb).
#
# Stateless-Ethereum witness types: execution payloads carry an
# `ExecutionWitness` (verkle state diff + IPA multiproof) whose root the
# header commits to.  Verification of the witness happens in the
# execution layer; the CL carries and commits to it.  Parity contract:
# specs/_features/eip6800/beacon-chain.md (custom types :30-41,
# preset :43-52, containers :54-166, block :167-220).

# Custom types (beacon-chain.md :30-41)
BanderwagonGroupElement = Bytes32
BanderwagonFieldElement = Bytes32
Stem = Bytes31


class SuffixStateDiff(Container):
    suffix: Bytes1
    # the md's `Optional[T]` is SSZ Union[None, T]
    current_value: Union[None, Bytes32]
    new_value: Union[None, Bytes32]


class StemStateDiff(Container):
    """`suffix_diffs` is only valid if sorted by suffixes."""
    stem: Stem
    suffix_diffs: List[SuffixStateDiff, VERKLE_WIDTH]


class IPAProof(Container):
    cl: Vector[BanderwagonGroupElement, IPA_PROOF_DEPTH]
    cr: Vector[BanderwagonGroupElement, IPA_PROOF_DEPTH]
    final_evaluation: BanderwagonFieldElement


class VerkleProof(Container):
    other_stems: List[Bytes31, MAX_STEMS]
    depth_extension_present: ByteList[MAX_STEMS]
    commitments_by_path: List[BanderwagonGroupElement,
                              MAX_STEMS * MAX_COMMITMENTS_PER_STEM]
    d: BanderwagonGroupElement
    ipa_proof: IPAProof


class ExecutionWitness(Container):
    state_diff: List[StemStateDiff, MAX_STEMS]
    verkle_proof: VerkleProof


class ExecutionPayload(Container):
    parent_hash: Hash32
    fee_recipient: ExecutionAddress
    state_root: Bytes32
    receipts_root: Bytes32
    logs_bloom: ByteVector[BYTES_PER_LOGS_BLOOM]
    prev_randao: Bytes32
    block_number: uint64
    gas_limit: uint64
    gas_used: uint64
    timestamp: uint64
    extra_data: ByteList[MAX_EXTRA_DATA_BYTES]
    base_fee_per_gas: uint256
    block_hash: Hash32
    transactions: List[Transaction, MAX_TRANSACTIONS_PER_PAYLOAD]
    withdrawals: List[Withdrawal, MAX_WITHDRAWALS_PER_PAYLOAD]
    blob_gas_used: uint64
    excess_blob_gas: uint64
    # [New in EIP6800]
    execution_witness: ExecutionWitness


class ExecutionPayloadHeader(Container):
    # field set as the feature spec writes it (the stale
    # `excess_data_gas` name included, beacon-chain.md :85-106)
    parent_hash: Hash32
    fee_recipient: ExecutionAddress
    state_root: Bytes32
    receipts_root: Bytes32
    logs_bloom: ByteVector[BYTES_PER_LOGS_BLOOM]
    prev_randao: Bytes32
    block_number: uint64
    gas_limit: uint64
    gas_used: uint64
    timestamp: uint64
    extra_data: ByteList[MAX_EXTRA_DATA_BYTES]
    base_fee_per_gas: uint256
    block_hash: Hash32
    transactions_root: Root
    withdrawals_root: Root
    blob_gas_used: uint64
    excess_data_gas: uint64
    # [New in EIP6800]
    execution_witness_root: Root


# Re-bound containers: the exec'd namespace binds field types at class
# creation, so the deneb-defined body/state would still carry deneb's
# payload classes — re-declare them against the witness-bearing types
# (the reference's generated module has the same ordering property).


class BeaconBlockBody(Container):
    randao_reveal: BLSSignature
    eth1_data: Eth1Data
    graffiti: Bytes32
    proposer_slashings: List[ProposerSlashing, MAX_PROPOSER_SLASHINGS]
    attester_slashings: List[AttesterSlashing, MAX_ATTESTER_SLASHINGS]
    attestations: List[Attestation, MAX_ATTESTATIONS]
    deposits: List[Deposit, MAX_DEPOSITS]
    voluntary_exits: List[SignedVoluntaryExit, MAX_VOLUNTARY_EXITS]
    sync_aggregate: SyncAggregate
    # [Modified in EIP6800]
    execution_payload: ExecutionPayload
    bls_to_execution_changes: List[SignedBLSToExecutionChange, MAX_BLS_TO_EXECUTION_CHANGES]
    blob_kzg_commitments: List[KZGCommitment, MAX_BLOB_COMMITMENTS_PER_BLOCK]


class BeaconBlock(Container):
    slot: Slot
    proposer_index: ValidatorIndex
    parent_root: Root
    state_root: Root
    body: BeaconBlockBody


class SignedBeaconBlock(Container):
    message: BeaconBlock
    signature: BLSSignature


class BeaconState(Container):
    genesis_time: uint64
    genesis_validators_root: Root
    slot: Slot
    fork: Fork
    latest_block_header: BeaconBlockHeader
    block_roots: Vector[Root, SLOTS_PER_HISTORICAL_ROOT]
    state_roots: Vector[Root, SLOTS_PER_HISTORICAL_ROOT]
    historical_roots: List[Root, HISTORICAL_ROOTS_LIMIT]
    eth1_data: Eth1Data
    eth1_data_votes: List[Eth1Data, EPOCHS_PER_ETH1_VOTING_PERIOD * SLOTS_PER_EPOCH]
    eth1_deposit_index: uint64
    validators: List[Validator, VALIDATOR_REGISTRY_LIMIT]
    balances: List[Gwei, VALIDATOR_REGISTRY_LIMIT]
    randao_mixes: Vector[Bytes32, EPOCHS_PER_HISTORICAL_VECTOR]
    slashings: Vector[Gwei, EPOCHS_PER_SLASHINGS_VECTOR]
    previous_epoch_participation: List[ParticipationFlags, VALIDATOR_REGISTRY_LIMIT]
    current_epoch_participation: List[ParticipationFlags, VALIDATOR_REGISTRY_LIMIT]
    justification_bits: Bitvector[JUSTIFICATION_BITS_LENGTH]
    previous_justified_checkpoint: Checkpoint
    current_justified_checkpoint: Checkpoint
    finalized_checkpoint: Checkpoint
    inactivity_scores: List[uint64, VALIDATOR_REGISTRY_LIMIT]
    current_sync_committee: SyncCommittee
    next_sync_committee: SyncCommittee
    # [Modified in EIP6800]
    latest_execution_payload_header: ExecutionPayloadHeader
    next_withdrawal_index: WithdrawalIndex
    next_withdrawal_validator_index: ValidatorIndex
    historical_summaries: List[HistoricalSummary, HISTORICAL_ROOTS_LIMIT]


def process_execution_payload(state: BeaconState, body: BeaconBlockBody,
                              execution_engine: ExecutionEngine) -> None:
    """[Modified in EIP6800] the cached header commits to the payload's
    execution witness root."""
    payload = body.execution_payload

    assert (payload.parent_hash
            == state.latest_execution_payload_header.block_hash)
    assert payload.prev_randao == get_randao_mix(
        state, get_current_epoch(state))
    assert payload.timestamp == compute_time_at_slot(state, state.slot)
    assert len(body.blob_kzg_commitments) <= config.MAX_BLOBS_PER_BLOCK
    versioned_hashes = [kzg_commitment_to_versioned_hash(commitment)
                        for commitment in body.blob_kzg_commitments]
    assert execution_engine.verify_and_notify_new_payload(
        NewPayloadRequest(
            execution_payload=payload,
            versioned_hashes=versioned_hashes,
            parent_beacon_block_root=state.latest_block_header.parent_root,
        ))
    state.latest_execution_payload_header = ExecutionPayloadHeader(
        parent_hash=payload.parent_hash,
        fee_recipient=payload.fee_recipient,
        state_root=payload.state_root,
        receipts_root=payload.receipts_root,
        logs_bloom=payload.logs_bloom,
        prev_randao=payload.prev_randao,
        block_number=payload.block_number,
        gas_limit=payload.gas_limit,
        gas_used=payload.gas_used,
        timestamp=payload.timestamp,
        extra_data=payload.extra_data,
        base_fee_per_gas=payload.base_fee_per_gas,
        block_hash=payload.block_hash,
        transactions_root=hash_tree_root(payload.transactions),
        withdrawals_root=hash_tree_root(payload.withdrawals),
        blob_gas_used=payload.blob_gas_used,
        excess_data_gas=payload.excess_blob_gas,
        # [New in EIP6800]
        execution_witness_root=hash_tree_root(
            payload.execution_witness),
    )
