"""The spec build pipeline (layer L2).

The reference extracts executable Python out of Markdown and assembles one
flat module per (fork, preset) (`setup.py:86-112`, `pysetup/md_to_spec.py`).
This build keeps the same *contract* — a flat namespace per (fork, preset)
holding every container, constant, config object and spec function, with
later forks overriding earlier definitions — but the canonical spec sources
are Python files (`models/<fork>/*.py`) executed in fork order into a shared
namespace.  That reproduces the reference's override semantics (generated
modules re-bind names; all functions late-bind through module globals) with
a ~200-line builder instead of a Markdown parser, and makes the spec sources
directly lintable/diffable.

Public API:
    build_spec(fork, preset)      -> module-like Spec object (cached)
    spec_with_config(spec, overrides) -> fresh spec copy with config edits
"""

from __future__ import annotations

import re
import types
from pathlib import Path
from typing import Any

import yaml

from .. import telemetry


class _SpecYamlLoader(yaml.SafeLoader):
    """SafeLoader that keeps 0x… scalars as strings (PyYAML would parse
    them as hex ints, destroying Version/address byte values)."""


# prepend (add_implicit_resolver appends, and the stock int resolver for
# '0' would win): 0x… must resolve to !hexstr before tag:yaml.org,2002:int
_SpecYamlLoader.yaml_implicit_resolvers = {
    k: list(v) for k, v in yaml.SafeLoader.yaml_implicit_resolvers.items()
}
_SpecYamlLoader.yaml_implicit_resolvers["0"] = (
    [("!hexstr", re.compile(r"^0x[0-9a-fA-F]+$"))]
    + _SpecYamlLoader.yaml_implicit_resolvers.get("0", [])
)
_SpecYamlLoader.add_constructor(
    "!hexstr", lambda loader, node: str(node.value))

PKG_ROOT = Path(__file__).resolve().parent.parent

# fork DAG (mirrors `pysetup/md_doc_paths.py:17-41`)
PREVIOUS_FORK_OF: dict[str, str | None] = {
    "phase0": None,
    "altair": "phase0",
    "bellatrix": "altair",
    "capella": "bellatrix",
    "deneb": "capella",
    "electra": "deneb",
    "fulu": "electra",
    # feature forks (specs/_features/)
    "eip7732": "electra",
    "eip7805": "electra",
    "eip6800": "deneb",
    "eip7441": "capella",
}

# Mainline forks only — the default phase list for tests and generators;
# feature forks build via `build_spec` but don't join @with_all_phases
# (the reference's ALL_PHASES vs ALL_PHASES+features split,
# `test/helpers/constants.py`).
ALL_FORKS = ["phase0", "altair", "bellatrix", "capella", "deneb",
             "electra", "fulu"]
FEATURE_FORKS = ["eip7732", "eip7805", "eip6800", "eip7441"]
BUILDABLE_FORKS = ALL_FORKS + FEATURE_FORKS

# source files per fork, executed in order; later forks only list their own
# delta files (ancestors' files run first)
SPEC_SOURCES: dict[str, list[str]] = {
    "phase0": ["beacon_chain.py", "fork_choice.py", "validator.py",
               "genesis.py", "p2p.py"],
    "altair": ["beacon_chain.py", "fork.py", "light_client.py",
               "validator.py", "p2p.py"],
    "bellatrix": ["beacon_chain.py", "fork.py", "fork_choice.py",
                  "validator.py", "p2p.py", "optimistic.py"],
    "capella": ["beacon_chain.py", "fork.py", "fork_choice.py",
                "validator.py", "light_client.py", "p2p.py"],
    "deneb": ["polynomial_commitments.py", "beacon_chain.py", "fork.py",
              "fork_choice.py", "light_client.py", "p2p.py",
              "validator.py"],
    "electra": ["beacon_chain.py", "fork.py", "light_client.py",
                "validator.py", "p2p.py"],
    "fulu": ["polynomial_commitments_sampling.py", "das_core.py",
             "beacon_chain.py", "fork.py", "fork_choice.py", "p2p.py",
             "validator.py"],
    "eip7732": ["beacon_chain.py", "fork.py", "validator.py", "p2p.py"],
    "eip7805": ["beacon_chain.py", "fork.py", "fork_choice.py",
                "validator.py", "p2p.py"],
    "eip6800": ["beacon_chain.py", "fork.py"],
    "eip7441": ["beacon_chain.py", "fork.py"],
}


def fork_chain(fork: str) -> list[str]:
    chain = []
    f: str | None = fork
    while f is not None:
        chain.append(f)
        f = PREVIOUS_FORK_OF[f]
    return list(reversed(chain))


def _parse_value(v: Any) -> Any:
    if isinstance(v, str):
        if v.startswith("0x"):
            return bytes.fromhex(v[2:])
        if v.isdigit():
            return int(v)
    return v


def load_preset(preset_name: str, fork: str) -> dict[str, Any]:
    """Merge preset files of the fork and all ancestors."""
    out: dict[str, Any] = {}
    for f in fork_chain(fork):
        path = PKG_ROOT / "presets" / preset_name / f"{f}.yaml"
        if path.exists():
            with open(path) as fh:
                data = yaml.load(fh, Loader=_SpecYamlLoader) or {}
            out.update({k: _parse_value(v) for k, v in data.items()})
    return out


def load_config(config_name: str) -> dict[str, Any]:
    path = PKG_ROOT / "configs" / f"{config_name}.yaml"
    with open(path) as fh:
        data = yaml.load(fh, Loader=_SpecYamlLoader) or {}
    return {k: _parse_value(v) for k, v in data.items()}


class Configuration(types.SimpleNamespace):
    """Runtime config object; spec code reads `config.NAME`."""

    def to_dict(self) -> dict[str, Any]:
        return dict(self.__dict__)


def _preamble_namespace() -> dict[str, Any]:
    """Names available to every spec source file before execution."""
    import dataclasses
    from typing import Any, Dict, List as PyList, Optional, Sequence, Set, Tuple

    from ..ops import bls
    from ..utils.hash import hash_eth2
    from ..utils.ssz import ssz_typing as tz
    from ..utils.ssz.gindex import (
        compute_merkle_proof,
        concat_generalized_indices,
        get_generalized_index,
    )
    from ..utils.ssz.ssz_impl import (
        copy, deserialize, hash_tree_root, serialize, uint_to_bytes)

    ns: dict[str, Any] = {
        # ssz types
        **{n: getattr(tz, n) for n in (
            "Bitlist", "Bitvector", "ByteList", "ByteVector", "Bytes1",
            "Bytes4", "Bytes8", "Bytes20", "Bytes31", "Bytes32", "Bytes48",
            "Bytes96", "Container", "List", "Union", "Vector", "View",
            "boolean", "byte", "uint8", "uint16", "uint32", "uint64",
            "uint128", "uint256", "bit",
        )},
        # ssz functions
        "hash_tree_root": hash_tree_root,
        "serialize": serialize,
        "ssz_serialize": serialize,
        "ssz_deserialize": deserialize,
        "uint_to_bytes": uint_to_bytes,
        "copy": copy,
        "get_generalized_index": get_generalized_index,
        "concat_generalized_indices": concat_generalized_indices,
        "compute_merkle_proof_backing": compute_merkle_proof,
        # crypto
        "bls": bls,
        "hash": hash_eth2,
        # python utilities the spec sources use
        "dataclass": dataclasses.dataclass,
        "field": dataclasses.field,
        "Dict": Dict,
        "PyList": PyList,
        "Optional": Optional,
        "Sequence": Sequence,
        "Set": Set,
        "Tuple": Tuple,
        "Any": Any,
        "ceillog2": lambda x: (int(x) - 1).bit_length(),
        "floorlog2": lambda x: int(x).bit_length() - 1,
    }
    return ns


class Spec:
    """A built (fork, preset) spec namespace; attribute access like the
    reference's generated `eth2spec.<fork>.<preset>` module.

    Attribute get/set are live views over the exec namespace, so
    monkeypatching `spec.get_eth1_data = ...` (the reference's per-test
    stub pattern, `helpers/fork_choice.py:55-115`) is seen by every spec
    function (they late-bind through the same dict)."""

    def __init__(self, fork: str, preset_name: str, ns: dict[str, Any]):
        object.__setattr__(self, "_namespace", ns)
        ns["fork"] = fork
        ns["preset_name"] = preset_name

    def __getattr__(self, name):
        try:
            return self._namespace[name]
        except KeyError:
            raise AttributeError(f"spec has no attribute {name!r}") from None

    def __setattr__(self, name, value):
        self._namespace[name] = value

    def __repr__(self):
        return f"<Spec {self._namespace['fork']}/{self._namespace['preset_name']}>"


class _LRU:
    """Small dict-backed LRU (the reference uses the `lru-dict` C ext,
    `pysetup/spec_builders/phase0.py:47-56`; this build avoids the dep)."""

    __slots__ = ("size", "data")

    def __init__(self, size: int):
        self.size = size
        self.data: dict = {}

    def __contains__(self, key):
        return key in self.data

    def __getitem__(self, key):
        v = self.data.pop(key)
        self.data[key] = v  # move to back (most recent)
        return v

    def __setitem__(self, key, value):
        if key in self.data:
            self.data.pop(key)
        elif len(self.data) >= self.size:
            self.data.pop(next(iter(self.data)))
        self.data[key] = value


def _cache_this(key_fn, value_fn, lru_size: int):
    cache = _LRU(lru_size)

    def wrapper(*args, **kw):
        key = key_fn(*args, **kw)
        if key not in cache:
            cache[key] = value_fn(*args, **kw)
        return cache[key]

    wrapper.__wrapped__ = value_fn  # monkeypatch/debug escape hatch
    return wrapper


def _install_caches(ns: dict[str, Any]) -> None:
    """Wrap the committee/shuffle/balance lookups in per-namespace LRU
    caches, mirroring the reference's generated-spec cache layer
    (`pysetup/spec_builders/phase0.py:58-104`).  Installed after all fork
    sources executed, so the wrappers capture each fork's final overrides;
    keys lean on the SSZ engine's dirty-propagation root cache making
    `.hash_tree_root()` cheap on unchanged subtrees."""
    slots_per_epoch = int(ns["SLOTS_PER_EPOCH"])
    max_committees = int(ns.get("MAX_COMMITTEES_PER_SLOT", 64))
    epoch_at = ns["compute_epoch_at_slot"]

    def wrap(name, key_fn, size):
        if name in ns:
            ns[name] = _cache_this(key_fn, ns[name], size)

    wrap("compute_shuffled_index",
         lambda index, index_count, seed: (int(index), int(index_count),
                                           bytes(seed)),
         slots_per_epoch * 3)
    wrap("get_total_active_balance",
         lambda state: (state.validators.hash_tree_root(),
                        epoch_at(state.slot)),
         10)
    wrap("get_base_reward",
         lambda state, index: (state.validators.hash_tree_root(), state.slot,
                               int(index)),
         2048)
    wrap("get_committee_count_per_slot",
         lambda state, epoch: (state.validators.hash_tree_root(), int(epoch)),
         slots_per_epoch * 3)
    wrap("get_active_validator_indices",
         lambda state, epoch: (state.validators.hash_tree_root(), int(epoch)),
         3)
    wrap("get_beacon_committee",
         lambda state, slot, index: (state.validators.hash_tree_root(),
                                     state.randao_mixes.hash_tree_root(),
                                     int(slot), int(index)),
         slots_per_epoch * max_committees * 3)
    wrap("get_matching_target_attestations",
         lambda state, epoch: (state.hash_tree_root(), int(epoch)),
         10)
    wrap("get_matching_head_attestations",
         lambda state, epoch: (state.hash_tree_root(), int(epoch)),
         10)
    wrap("get_attesting_indices",
         lambda state, attestation: (state.randao_mixes.hash_tree_root(),
                                     state.validators.hash_tree_root(),
                                     attestation.hash_tree_root()),
         slots_per_epoch * max_committees * 3)


def _exec_sources(fork: str, ns: dict[str, Any]) -> None:
    for f in fork_chain(fork):
        ns["CURRENT_FORK"] = f
        for fname in SPEC_SOURCES.get(f, []):
            path = PKG_ROOT / "models" / f / fname
            if not path.exists():
                continue
            # dont_inherit: without it compile() inherits this module's
            # `from __future__ import annotations`, turning the spec
            # sources' container field annotations into strings (PEP 236)
            code = compile(path.read_text(), str(path), "exec",
                           dont_inherit=True)
            exec(code, ns)  # noqa: S102 - the spec sources are first-party


_SPEC_CACHE: dict[tuple[str, str], Spec] = {}


def build_spec(fork: str, preset_name: str) -> Spec:
    """Assemble (and cache) the flat executable spec for fork × preset."""
    key = (fork, preset_name)
    if key in _SPEC_CACHE:
        return _SPEC_CACHE[key]
    # cache misses only: the cumulative `spec.build` span is what the
    # per-test phase attribution (tests/conftest.py -> benchwatch
    # tier-1 table) charges to the spec-build phase
    with telemetry.span("spec.build", fork=fork, preset=preset_name):
        ns = _preamble_namespace()
        ns.update(load_preset(preset_name, fork))
        ns["config"] = Configuration(**load_config(preset_name))
        ns["TRUSTED_SETUPS_DIR"] = str(
            PKG_ROOT / "presets" / preset_name / "trusted_setups")
        _exec_sources(fork, ns)
        _install_caches(ns)
        # bind functions' globals: they already close over `ns` via exec
        # globals
        spec = Spec(fork, preset_name, ns)
        ns["spec"] = spec
    _SPEC_CACHE[key] = spec
    return spec


def get_copy_of_spec(spec: Spec) -> Spec:
    """Fresh, uncached spec namespace for tests that monkeypatch spec
    functions (`spec.retrieve_blobs_and_proofs = stub` …): writes to the
    copy never leak into the shared `build_spec` cache.  Mirrors the
    reference's re-import isolation (`test/context.py:663-734`)."""
    with telemetry.span("spec.build", fork=spec.fork,
                        preset=spec.preset_name, copy=True):
        ns = _preamble_namespace()
        ns.update(load_preset(spec.preset_name, spec.fork))
        # carry the source spec's live config (it may hold overrides from
        # spec_with_config), not a fresh load of the preset defaults
        ns["config"] = Configuration(**spec.config.to_dict())
        ns["TRUSTED_SETUPS_DIR"] = str(
            PKG_ROOT / "presets" / spec.preset_name / "trusted_setups")
        _exec_sources(spec.fork, ns)
        _install_caches(ns)
        fresh = Spec(spec.fork, spec.preset_name, ns)
        ns["spec"] = fresh
    return fresh


_OVERRIDE_SPEC_CACHE: dict[tuple, Spec] = {}


def spec_with_config(spec: Spec, overrides: dict[str, Any]) -> Spec:
    """Fresh spec instance with config overrides (the reference's
    `with_config_overrides` re-import, `test/context.py:663-734`).
    Cached per (fork, preset, overrides) — rebuilding the namespace means
    re-executing every spec source file."""
    def _hashable(v):
        if isinstance(v, bytes):
            return bytes(v)
        if isinstance(v, (list, tuple)):
            return tuple(_hashable(x) for x in v)
        if isinstance(v, dict):
            return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
        return v

    fp = tuple(sorted((k, _hashable(v)) for k, v in overrides.items()))
    key = (spec.fork, spec.preset_name, fp)
    if key in _OVERRIDE_SPEC_CACHE:
        return _OVERRIDE_SPEC_CACHE[key]
    with telemetry.span("spec.build", fork=spec.fork,
                        preset=spec.preset_name, overrides=True):
        ns = _preamble_namespace()
        ns.update(load_preset(spec.preset_name, spec.fork))
        cfg = load_config(spec.preset_name)
        cfg.update(overrides)
        ns["config"] = Configuration(
            **{k: _parse_value(v) for k, v in cfg.items()})
        ns["TRUSTED_SETUPS_DIR"] = str(
            PKG_ROOT / "presets" / spec.preset_name / "trusted_setups")
        _exec_sources(spec.fork, ns)
        _install_caches(ns)
        fresh = Spec(spec.fork, spec.preset_name, ns)
        ns["spec"] = fresh
    _OVERRIDE_SPEC_CACHE[key] = fresh
    return fresh
