# Altair -- p2p deltas: MetaData gains syncnets, message-id becomes
# topic-aware, sync-committee gossip topics.
# Parity contract: specs/altair/p2p-interface.md (:44-61 MetaData,
# :84-102 message-id, :318-340 req/resp context table).


class MetaData(Container):
    seq_number: uint64
    attnets: Bitvector[64]  # ATTESTATION_SUBNET_COUNT
    syncnets: Bitvector[4]  # SYNC_COMMITTEE_SUBNET_COUNT


def compute_message_id(topic: str, message_data: bytes) -> bytes:
    """Altair message-id mixes in the topic (altair/p2p-interface.md
    :84-95); messages on phase0-digest topics keep the phase0 rule."""
    topic_bytes = topic.encode()
    prefix_len = uint_to_bytes(uint64(len(topic_bytes)))
    try:
        from consensus_specs_tpu.utils.snappy import decompress

        decompressed = decompress(message_data)
        return hash(config.MESSAGE_DOMAIN_VALID_SNAPPY + prefix_len
                    + topic_bytes + decompressed)[:20]
    except Exception:
        return hash(config.MESSAGE_DOMAIN_INVALID_SNAPPY + prefix_len
                    + topic_bytes + message_data)[:20]


def compute_sync_committee_subnet_topic(fork_digest: ForkDigest,
                                        subnet_id: uint64) -> str:
    return compute_gossip_topic(fork_digest,
                                f"sync_committee_{int(subnet_id)}")


def compute_response_context(epoch: Epoch,
                             genesis_validators_root: Root) -> ForkDigest:
    """Context bytes for v2 req/resp chunks: the fork digest of the epoch
    the payload belongs to (altair/p2p-interface.md :307-340)."""
    return compute_fork_digest(compute_fork_version(epoch),
                               genesis_validators_root)
