# Altair -- Fork Logic (executable spec source).
#
# Parity contract: specs/altair/fork.md (:34-130).  `upgrade_to_altair`
# consumes a *phase0* BeaconState instance (built by the phase0 spec) and
# returns this fork's BeaconState; fields are copied attribute-wise, so no
# cross-module type reference is needed.


def compute_fork_version(epoch: Epoch) -> Version:
    """Fork version at `epoch`."""
    if epoch >= config.ALTAIR_FORK_EPOCH:
        return config.ALTAIR_FORK_VERSION
    return config.GENESIS_FORK_VERSION


def translate_participation(state: BeaconState,
                            pending_attestations) -> None:
    """Convert phase0 PendingAttestations into previous-epoch
    participation flags (fork.md `translate_participation`)."""
    for attestation in pending_attestations:
        data = attestation.data
        inclusion_delay = attestation.inclusion_delay
        # Translate attestation inclusion info to flag indices
        participation_flag_indices = get_attestation_participation_flag_indices(
            state, data, inclusion_delay)

        # Apply flags to all attesting validators
        epoch_participation = state.previous_epoch_participation
        for index in get_attesting_indices(state, attestation):
            for flag_index in participation_flag_indices:
                epoch_participation[index] = add_flag(
                    epoch_participation[index], flag_index)


def upgrade_to_altair(pre) -> BeaconState:
    """phase0 -> altair state upgrade (fork.md `upgrade_to_altair`)."""
    epoch = compute_epoch_at_slot(pre.slot)
    post = BeaconState(
        genesis_time=pre.genesis_time,
        genesis_validators_root=pre.genesis_validators_root,
        slot=pre.slot,
        fork=Fork(
            previous_version=pre.fork.current_version,
            current_version=config.ALTAIR_FORK_VERSION,
            epoch=epoch,
        ),
        latest_block_header=pre.latest_block_header,
        block_roots=pre.block_roots,
        state_roots=pre.state_roots,
        historical_roots=pre.historical_roots,
        eth1_data=pre.eth1_data,
        eth1_data_votes=pre.eth1_data_votes,
        eth1_deposit_index=pre.eth1_deposit_index,
        validators=pre.validators,
        balances=pre.balances,
        randao_mixes=pre.randao_mixes,
        slashings=pre.slashings,
        previous_epoch_participation=[
            ParticipationFlags(0b0000_0000) for _ in range(len(pre.validators))
        ],
        current_epoch_participation=[
            ParticipationFlags(0b0000_0000) for _ in range(len(pre.validators))
        ],
        justification_bits=pre.justification_bits,
        previous_justified_checkpoint=pre.previous_justified_checkpoint,
        current_justified_checkpoint=pre.current_justified_checkpoint,
        finalized_checkpoint=pre.finalized_checkpoint,
        inactivity_scores=[uint64(0) for _ in range(len(pre.validators))],
    )
    # Fill in previous epoch participation from pending attestations
    translate_participation(post, pre.previous_epoch_attestations)

    # Fill in sync committees
    # Note: A duplicate committee is assigned at the fork boundary
    post.current_sync_committee = get_next_sync_committee(post)
    post.next_sync_committee = get_next_sync_committee(post)
    return post
