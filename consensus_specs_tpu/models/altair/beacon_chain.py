# Altair -- The Beacon Chain (executable spec source, delta over phase0).
#
# Executed into the namespace AFTER the phase0 sources: class/function
# definitions here override the phase0 bindings, and phase0 functions that
# call overridden names pick up the new versions through the shared
# namespace (the reference's generated-module override semantics).
# Parity contract: specs/altair/beacon-chain.md (constants :70-137,
# containers :139-210, helpers :263-447, block processing :486-606,
# epoch processing :608-745) and specs/altair/bls.md (:29-67).

# ---------------------------------------------------------------------------
# Custom types + constants (beacon-chain.md :64-105)
# ---------------------------------------------------------------------------


class ParticipationFlags(uint8):
    pass


TIMELY_SOURCE_FLAG_INDEX = 0
TIMELY_TARGET_FLAG_INDEX = 1
TIMELY_HEAD_FLAG_INDEX = 2

TIMELY_SOURCE_WEIGHT = uint64(14)
TIMELY_TARGET_WEIGHT = uint64(26)
TIMELY_HEAD_WEIGHT = uint64(14)
SYNC_REWARD_WEIGHT = uint64(2)
PROPOSER_WEIGHT = uint64(8)
WEIGHT_DENOMINATOR = uint64(64)

DOMAIN_SYNC_COMMITTEE = DomainType("0x07000000")
DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF = DomainType("0x08000000")
DOMAIN_CONTRIBUTION_AND_PROOF = DomainType("0x09000000")

PARTICIPATION_FLAG_WEIGHTS = [TIMELY_SOURCE_WEIGHT, TIMELY_TARGET_WEIGHT,
                              TIMELY_HEAD_WEIGHT]

G2_POINT_AT_INFINITY = BLSSignature(b"\xc0" + b"\x00" * 95)


# ---------------------------------------------------------------------------
# Containers (beacon-chain.md :139-210)
# ---------------------------------------------------------------------------


class SyncAggregate(Container):
    sync_committee_bits: Bitvector[SYNC_COMMITTEE_SIZE]
    sync_committee_signature: BLSSignature


class SyncCommittee(Container):
    pubkeys: Vector[BLSPubkey, SYNC_COMMITTEE_SIZE]
    aggregate_pubkey: BLSPubkey


class BeaconBlockBody(Container):
    randao_reveal: BLSSignature
    eth1_data: Eth1Data
    graffiti: Bytes32
    proposer_slashings: List[ProposerSlashing, MAX_PROPOSER_SLASHINGS]
    attester_slashings: List[AttesterSlashing, MAX_ATTESTER_SLASHINGS]
    attestations: List[Attestation, MAX_ATTESTATIONS]
    deposits: List[Deposit, MAX_DEPOSITS]
    voluntary_exits: List[SignedVoluntaryExit, MAX_VOLUNTARY_EXITS]
    # [New in Altair]
    sync_aggregate: SyncAggregate


class BeaconBlock(Container):
    slot: Slot
    proposer_index: ValidatorIndex
    parent_root: Root
    state_root: Root
    body: BeaconBlockBody


class SignedBeaconBlock(Container):
    message: BeaconBlock
    signature: BLSSignature


class BeaconState(Container):
    genesis_time: uint64
    genesis_validators_root: Root
    slot: Slot
    fork: Fork
    latest_block_header: BeaconBlockHeader
    block_roots: Vector[Root, SLOTS_PER_HISTORICAL_ROOT]
    state_roots: Vector[Root, SLOTS_PER_HISTORICAL_ROOT]
    historical_roots: List[Root, HISTORICAL_ROOTS_LIMIT]
    eth1_data: Eth1Data
    eth1_data_votes: List[Eth1Data, EPOCHS_PER_ETH1_VOTING_PERIOD * SLOTS_PER_EPOCH]
    eth1_deposit_index: uint64
    validators: List[Validator, VALIDATOR_REGISTRY_LIMIT]
    balances: List[Gwei, VALIDATOR_REGISTRY_LIMIT]
    randao_mixes: Vector[Bytes32, EPOCHS_PER_HISTORICAL_VECTOR]
    slashings: Vector[Gwei, EPOCHS_PER_SLASHINGS_VECTOR]
    # [Modified in Altair]
    previous_epoch_participation: List[ParticipationFlags, VALIDATOR_REGISTRY_LIMIT]
    # [Modified in Altair]
    current_epoch_participation: List[ParticipationFlags, VALIDATOR_REGISTRY_LIMIT]
    justification_bits: Bitvector[JUSTIFICATION_BITS_LENGTH]
    previous_justified_checkpoint: Checkpoint
    current_justified_checkpoint: Checkpoint
    finalized_checkpoint: Checkpoint
    # [New in Altair]
    inactivity_scores: List[uint64, VALIDATOR_REGISTRY_LIMIT]
    # [New in Altair]
    current_sync_committee: SyncCommittee
    # [New in Altair]
    next_sync_committee: SyncCommittee


# ---------------------------------------------------------------------------
# Crypto extensions (altair/bls.md :29-67)
# ---------------------------------------------------------------------------


def eth_aggregate_pubkeys(pubkeys: Sequence[BLSPubkey]) -> BLSPubkey:
    """EC point sum of the input pubkeys (altair/bls.md :36-53)."""
    assert len(pubkeys) > 0
    assert all(bls.KeyValidate(pubkey) for pubkey in pubkeys)
    return BLSPubkey(bls.AggregatePKs(pubkeys))


def eth_fast_aggregate_verify(pubkeys: Sequence[BLSPubkey], message: Bytes32,
                              signature: BLSSignature) -> bool:
    """FastAggregateVerify that also accepts an empty committee signing
    the infinity point (altair/bls.md :55-67)."""
    if len(pubkeys) == 0 and signature == G2_POINT_AT_INFINITY:
        return True
    return bls.FastAggregateVerify(pubkeys, message, signature)


# ---------------------------------------------------------------------------
# Misc helpers (beacon-chain.md :224-261)
# ---------------------------------------------------------------------------


def add_flag(flags: ParticipationFlags, flag_index: int) -> ParticipationFlags:
    """Return a new ``ParticipationFlags`` adding ``flag_index``."""
    flag = ParticipationFlags(2**flag_index)
    return flags | flag


def has_flag(flags: ParticipationFlags, flag_index: int) -> bool:
    """Return whether ``flags`` has ``flag_index`` set."""
    flag = ParticipationFlags(2**flag_index)
    return flags & flag == flag


def get_index_for_new_validator(state: BeaconState) -> ValidatorIndex:
    return ValidatorIndex(len(state.validators))


def set_or_append_list(list, index: ValidatorIndex, value) -> None:
    if index == len(list):
        list.append(value)
    else:
        list[index] = value


# ---------------------------------------------------------------------------
# Beacon state accessors (beacon-chain.md :263-447)
# ---------------------------------------------------------------------------


def get_next_sync_committee_indices(state: BeaconState) -> Sequence[ValidatorIndex]:
    """Sync committee indices (with possible duplicates) for the NEXT
    period: effective-balance-weighted sampling over the shuffled active
    set (beacon-chain.md :268-291)."""
    epoch = Epoch(get_current_epoch(state) + 1)

    MAX_RANDOM_BYTE = 2**8 - 1
    active_validator_indices = get_active_validator_indices(state, epoch)
    active_validator_count = uint64(len(active_validator_indices))
    seed = get_seed(state, epoch, DOMAIN_SYNC_COMMITTEE)
    i = 0
    sync_committee_indices = []
    while len(sync_committee_indices) < SYNC_COMMITTEE_SIZE:
        shuffled_index = compute_shuffled_index(
            uint64(i % active_validator_count), active_validator_count, seed)
        candidate_index = active_validator_indices[shuffled_index]
        random_byte = hash(seed + uint_to_bytes(uint64(i // 32)))[i % 32]
        effective_balance = state.validators[candidate_index].effective_balance
        if (effective_balance * MAX_RANDOM_BYTE
                >= MAX_EFFECTIVE_BALANCE * random_byte):
            sync_committee_indices.append(candidate_index)
        i += 1
    return sync_committee_indices


def get_next_sync_committee(state: BeaconState) -> SyncCommittee:
    """Next sync committee, with possible pubkey duplicates; only call at
    period boundaries / the altair upgrade (beacon-chain.md :300-307)."""
    indices = get_next_sync_committee_indices(state)
    pubkeys = [state.validators[index].pubkey for index in indices]
    aggregate_pubkey = eth_aggregate_pubkeys(pubkeys)
    return SyncCommittee(pubkeys=pubkeys, aggregate_pubkey=aggregate_pubkey)


def get_base_reward_per_increment(state: BeaconState) -> Gwei:
    return Gwei(EFFECTIVE_BALANCE_INCREMENT * BASE_REWARD_FACTOR
                // integer_squareroot(get_total_active_balance(state)))


def get_base_reward(state: BeaconState, index: ValidatorIndex) -> Gwei:
    """Increment-based base reward (replaces phase0's
    BASE_REWARDS_PER_EPOCH accounting)."""
    increments = (state.validators[index].effective_balance
                  // EFFECTIVE_BALANCE_INCREMENT)
    return Gwei(increments * get_base_reward_per_increment(state))


def get_unslashed_participating_indices(state: BeaconState, flag_index: int,
                                        epoch: Epoch) -> Set[ValidatorIndex]:
    """Active, unslashed validators with `flag_index` set for `epoch`."""
    assert epoch in (get_previous_epoch(state), get_current_epoch(state))
    if epoch == get_current_epoch(state):
        epoch_participation = state.current_epoch_participation
    else:
        epoch_participation = state.previous_epoch_participation
    active_validator_indices = get_active_validator_indices(state, epoch)
    participating_indices = [
        i for i in active_validator_indices
        if has_flag(epoch_participation[i], flag_index)
    ]
    return set(filter(lambda index: not state.validators[index].slashed,
                      participating_indices))


def get_attestation_participation_flag_indices(
        state: BeaconState, data: AttestationData,
        inclusion_delay: uint64) -> Sequence[int]:
    """Flag indices an attestation satisfies: source/target/head matches
    gated by inclusion-delay timeliness (beacon-chain.md :362-391)."""
    if data.target.epoch == get_current_epoch(state):
        justified_checkpoint = state.current_justified_checkpoint
    else:
        justified_checkpoint = state.previous_justified_checkpoint

    # Matching roots
    is_matching_source = data.source == justified_checkpoint
    is_matching_target = (is_matching_source
                          and data.target.root
                          == get_block_root(state, data.target.epoch))
    is_matching_head = (is_matching_target
                        and data.beacon_block_root
                        == get_block_root_at_slot(state, data.slot))
    assert is_matching_source

    participation_flag_indices = []
    if (is_matching_source
            and inclusion_delay <= integer_squareroot(SLOTS_PER_EPOCH)):
        participation_flag_indices.append(TIMELY_SOURCE_FLAG_INDEX)
    if is_matching_target and inclusion_delay <= SLOTS_PER_EPOCH:
        participation_flag_indices.append(TIMELY_TARGET_FLAG_INDEX)
    if is_matching_head and inclusion_delay == MIN_ATTESTATION_INCLUSION_DELAY:
        participation_flag_indices.append(TIMELY_HEAD_FLAG_INDEX)

    return participation_flag_indices


def get_flag_index_deltas(state: BeaconState, flag_index: int):
    """Per-validator (rewards, penalties) for one participation flag
    (beacon-chain.md :397-423)."""
    rewards = [Gwei(0)] * len(state.validators)
    penalties = [Gwei(0)] * len(state.validators)
    previous_epoch = get_previous_epoch(state)
    unslashed_participating_indices = get_unslashed_participating_indices(
        state, flag_index, previous_epoch)
    weight = PARTICIPATION_FLAG_WEIGHTS[flag_index]
    unslashed_participating_balance = get_total_balance(
        state, unslashed_participating_indices)
    unslashed_participating_increments = (
        unslashed_participating_balance // EFFECTIVE_BALANCE_INCREMENT)
    active_increments = (get_total_active_balance(state)
                         // EFFECTIVE_BALANCE_INCREMENT)
    for index in get_eligible_validator_indices(state):
        base_reward = get_base_reward(state, index)
        if index in unslashed_participating_indices:
            if not is_in_inactivity_leak(state):
                reward_numerator = (base_reward * weight
                                    * unslashed_participating_increments)
                rewards[index] += Gwei(
                    reward_numerator
                    // (active_increments * WEIGHT_DENOMINATOR))
        elif flag_index != TIMELY_HEAD_FLAG_INDEX:
            penalties[index] += Gwei(base_reward * weight
                                     // WEIGHT_DENOMINATOR)
    return rewards, penalties


def get_inactivity_penalty_deltas(state: BeaconState):
    """Inactivity penalties from inactivity scores (quadratic leak);
    no rewards (beacon-chain.md :429-446)."""
    rewards = [Gwei(0) for _ in range(len(state.validators))]
    penalties = [Gwei(0) for _ in range(len(state.validators))]
    previous_epoch = get_previous_epoch(state)
    matching_target_indices = get_unslashed_participating_indices(
        state, TIMELY_TARGET_FLAG_INDEX, previous_epoch)
    for index in get_eligible_validator_indices(state):
        if index not in matching_target_indices:
            penalty_numerator = (state.validators[index].effective_balance
                                 * state.inactivity_scores[index])
            penalty_denominator = (config.INACTIVITY_SCORE_BIAS
                                   * INACTIVITY_PENALTY_QUOTIENT_ALTAIR)
            penalties[index] += Gwei(penalty_numerator // penalty_denominator)
    return rewards, penalties


# ---------------------------------------------------------------------------
# Beacon state mutators (beacon-chain.md :451-483)
# ---------------------------------------------------------------------------


def slash_validator(state: BeaconState, slashed_index: ValidatorIndex,
                    whistleblower_index: ValidatorIndex = None) -> None:
    """Slash with the altair penalty quotient and proposer-weighted
    whistleblower split."""
    epoch = get_current_epoch(state)
    initiate_validator_exit(state, slashed_index)
    validator = state.validators[slashed_index]
    validator.slashed = True
    validator.withdrawable_epoch = max(
        validator.withdrawable_epoch, Epoch(epoch + EPOCHS_PER_SLASHINGS_VECTOR))
    state.slashings[epoch % EPOCHS_PER_SLASHINGS_VECTOR] += validator.effective_balance
    decrease_balance(state, slashed_index,
                     validator.effective_balance
                     // MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR)

    # Apply proposer and whistleblower rewards
    proposer_index = get_beacon_proposer_index(state)
    if whistleblower_index is None:
        whistleblower_index = proposer_index
    whistleblower_reward = Gwei(validator.effective_balance
                                // WHISTLEBLOWER_REWARD_QUOTIENT)
    proposer_reward = Gwei(whistleblower_reward * PROPOSER_WEIGHT
                           // WEIGHT_DENOMINATOR)
    increase_balance(state, proposer_index, proposer_reward)
    increase_balance(state, whistleblower_index,
                     Gwei(whistleblower_reward - proposer_reward))


# ---------------------------------------------------------------------------
# Block processing (beacon-chain.md :486-606)
# ---------------------------------------------------------------------------


def process_block(state: BeaconState, block: BeaconBlock) -> None:
    process_block_header(state, block)
    process_randao(state, block.body)
    process_eth1_data(state, block.body)
    process_operations(state, block.body)
    # [New in Altair]
    process_sync_aggregate(state, block.body.sync_aggregate)


def process_attestation(state: BeaconState, attestation: Attestation) -> None:
    """Participation-flag incentive accounting (beacon-chain.md :503-541)."""
    data = attestation.data
    assert data.target.epoch in (get_previous_epoch(state),
                                 get_current_epoch(state))
    assert data.target.epoch == compute_epoch_at_slot(data.slot)
    assert (data.slot + MIN_ATTESTATION_INCLUSION_DELAY
            <= state.slot
            <= data.slot + SLOTS_PER_EPOCH)
    assert data.index < get_committee_count_per_slot(state, data.target.epoch)

    committee = get_beacon_committee(state, data.slot, data.index)
    assert len(attestation.aggregation_bits) == len(committee)

    # Participation flag indices
    participation_flag_indices = get_attestation_participation_flag_indices(
        state, data, state.slot - data.slot)

    # Verify signature
    assert is_valid_indexed_attestation(
        state, get_indexed_attestation(state, attestation))

    # Update epoch participation flags
    if data.target.epoch == get_current_epoch(state):
        epoch_participation = state.current_epoch_participation
    else:
        epoch_participation = state.previous_epoch_participation

    proposer_reward_numerator = 0
    for index in get_attesting_indices(state, attestation):
        for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
            if (flag_index in participation_flag_indices
                    and not has_flag(epoch_participation[index], flag_index)):
                epoch_participation[index] = add_flag(
                    epoch_participation[index], flag_index)
                proposer_reward_numerator += get_base_reward(state, index) * weight

    # Reward proposer
    proposer_reward_denominator = ((WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
                                   * WEIGHT_DENOMINATOR // PROPOSER_WEIGHT)
    proposer_reward = Gwei(proposer_reward_numerator
                           // proposer_reward_denominator)
    increase_balance(state, get_beacon_proposer_index(state), proposer_reward)


def add_validator_to_registry(state: BeaconState, pubkey: BLSPubkey,
                              withdrawal_credentials: Bytes32,
                              amount: uint64) -> None:
    """Also initialize participation flags + inactivity score."""
    index = get_index_for_new_validator(state)
    validator = get_validator_from_deposit(pubkey, withdrawal_credentials,
                                           amount)
    set_or_append_list(state.validators, index, validator)
    set_or_append_list(state.balances, index, amount)
    # [New in Altair]
    set_or_append_list(state.previous_epoch_participation, index,
                       ParticipationFlags(0b0000_0000))
    set_or_append_list(state.current_epoch_participation, index,
                       ParticipationFlags(0b0000_0000))
    set_or_append_list(state.inactivity_scores, index, uint64(0))


def process_sync_aggregate(state: BeaconState,
                           sync_aggregate: SyncAggregate) -> None:
    """Verify the committee signature over the previous slot's block root
    and settle participant/proposer rewards (beacon-chain.md :569-606)."""
    # Verify sync committee aggregate signature signing over the previous slot block root
    committee_pubkeys = state.current_sync_committee.pubkeys
    participant_pubkeys = [
        pubkey for pubkey, bit
        in zip(committee_pubkeys, sync_aggregate.sync_committee_bits) if bit
    ]
    previous_slot = max(state.slot, Slot(1)) - Slot(1)
    domain = get_domain(state, DOMAIN_SYNC_COMMITTEE,
                        compute_epoch_at_slot(previous_slot))
    signing_root = compute_signing_root(
        get_block_root_at_slot(state, previous_slot), domain)
    assert eth_fast_aggregate_verify(
        participant_pubkeys, signing_root,
        sync_aggregate.sync_committee_signature)

    # Compute participant and proposer rewards
    total_active_increments = (get_total_active_balance(state)
                               // EFFECTIVE_BALANCE_INCREMENT)
    total_base_rewards = Gwei(get_base_reward_per_increment(state)
                              * total_active_increments)
    max_participant_rewards = Gwei(total_base_rewards * SYNC_REWARD_WEIGHT
                                   // WEIGHT_DENOMINATOR // SLOTS_PER_EPOCH)
    participant_reward = Gwei(max_participant_rewards // SYNC_COMMITTEE_SIZE)
    proposer_reward = Gwei(participant_reward * PROPOSER_WEIGHT
                           // (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT))

    # Apply participant and proposer rewards
    all_pubkeys = [v.pubkey for v in state.validators]
    committee_indices = [
        ValidatorIndex(all_pubkeys.index(pubkey))
        for pubkey in state.current_sync_committee.pubkeys
    ]
    for participant_index, participation_bit in zip(
            committee_indices, sync_aggregate.sync_committee_bits):
        if participation_bit:
            increase_balance(state, participant_index, participant_reward)
            increase_balance(state, get_beacon_proposer_index(state),
                             proposer_reward)
        else:
            decrease_balance(state, participant_index, participant_reward)


# ---------------------------------------------------------------------------
# Epoch processing (beacon-chain.md :608-745)
# ---------------------------------------------------------------------------


def process_epoch(state: BeaconState) -> None:
    process_justification_and_finalization(state)  # [Modified in Altair]
    process_inactivity_updates(state)  # [New in Altair]
    process_rewards_and_penalties(state)  # [Modified in Altair]
    process_registry_updates(state)
    process_slashings(state)  # [Modified in Altair]
    process_eth1_data_reset(state)
    process_effective_balance_updates(state)
    process_slashings_reset(state)
    process_randao_mixes_reset(state)
    process_historical_roots_update(state)
    process_participation_flag_updates(state)  # [New in Altair]
    process_sync_committee_updates(state)  # [New in Altair]


def process_justification_and_finalization(state: BeaconState) -> None:
    # Skip FFG updates in the first two epochs (stub-root corner cases)
    if get_current_epoch(state) <= GENESIS_EPOCH + 1:
        return
    previous_indices = get_unslashed_participating_indices(
        state, TIMELY_TARGET_FLAG_INDEX, get_previous_epoch(state))
    current_indices = get_unslashed_participating_indices(
        state, TIMELY_TARGET_FLAG_INDEX, get_current_epoch(state))
    total_active_balance = get_total_active_balance(state)
    previous_target_balance = get_total_balance(state, previous_indices)
    current_target_balance = get_total_balance(state, current_indices)
    weigh_justification_and_finalization(
        state, total_active_balance, previous_target_balance,
        current_target_balance)


def process_inactivity_updates(state: BeaconState) -> None:
    """Score up inactive validators, score everyone down in leak-free
    epochs (beacon-chain.md :656-673)."""
    # Score updates are based on previous-epoch participation
    if get_current_epoch(state) == GENESIS_EPOCH:
        return

    for index in get_eligible_validator_indices(state):
        if index in get_unslashed_participating_indices(
                state, TIMELY_TARGET_FLAG_INDEX, get_previous_epoch(state)):
            state.inactivity_scores[index] -= min(
                1, state.inactivity_scores[index])
        else:
            state.inactivity_scores[index] += config.INACTIVITY_SCORE_BIAS
        if not is_in_inactivity_leak(state):
            state.inactivity_scores[index] -= min(
                config.INACTIVITY_SCORE_RECOVERY_RATE,
                state.inactivity_scores[index])


def process_rewards_and_penalties(state: BeaconState) -> None:
    # No work was done in the epoch before genesis
    if get_current_epoch(state) == GENESIS_EPOCH:
        return

    flag_deltas = [
        get_flag_index_deltas(state, flag_index)
        for flag_index in range(len(PARTICIPATION_FLAG_WEIGHTS))
    ]
    deltas = flag_deltas + [get_inactivity_penalty_deltas(state)]
    for rewards, penalties in deltas:
        for index in range(len(state.validators)):
            increase_balance(state, ValidatorIndex(index), rewards[index])
            decrease_balance(state, ValidatorIndex(index), penalties[index])


def process_slashings(state: BeaconState) -> None:
    epoch = get_current_epoch(state)
    total_balance = get_total_active_balance(state)
    adjusted_total_slashing_balance = min(
        sum(state.slashings) * PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR,
        total_balance)
    for index, validator in enumerate(state.validators):
        if (validator.slashed
                and epoch + EPOCHS_PER_SLASHINGS_VECTOR // 2
                == validator.withdrawable_epoch):
            # Factor out the increment to avoid uint64 overflow
            increment = EFFECTIVE_BALANCE_INCREMENT
            penalty_numerator = (validator.effective_balance // increment
                                 * adjusted_total_slashing_balance)
            penalty = penalty_numerator // total_balance * increment
            decrease_balance(state, ValidatorIndex(index), penalty)


def process_participation_flag_updates(state: BeaconState) -> None:
    state.previous_epoch_participation = state.current_epoch_participation
    state.current_epoch_participation = [
        ParticipationFlags(0b0000_0000) for _ in range(len(state.validators))
    ]


def process_sync_committee_updates(state: BeaconState) -> None:
    next_epoch = get_current_epoch(state) + Epoch(1)
    if next_epoch % EPOCHS_PER_SYNC_COMMITTEE_PERIOD == 0:
        state.current_sync_committee = state.next_sync_committee
        state.next_sync_committee = get_next_sync_committee(state)
