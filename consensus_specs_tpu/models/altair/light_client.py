# Altair -- Light Client (sync protocol + full-node data derivation).
#
# Parity contract: specs/altair/light-client/sync-protocol.md
# (containers :85-171, helpers :173-320, init :322-354, updates :356-590)
# and specs/altair/light-client/full-node.md (:37-221).

# ---------------------------------------------------------------------------
# Constants (sync-protocol.md :68-74) — computed, then pinned by assert
# ---------------------------------------------------------------------------

FINALIZED_ROOT_GINDEX = get_generalized_index(
    BeaconState, "finalized_checkpoint", "root")
CURRENT_SYNC_COMMITTEE_GINDEX = get_generalized_index(
    BeaconState, "current_sync_committee")
NEXT_SYNC_COMMITTEE_GINDEX = get_generalized_index(
    BeaconState, "next_sync_committee")

assert FINALIZED_ROOT_GINDEX == 105, FINALIZED_ROOT_GINDEX
assert CURRENT_SYNC_COMMITTEE_GINDEX == 54, CURRENT_SYNC_COMMITTEE_GINDEX
assert NEXT_SYNC_COMMITTEE_GINDEX == 55, NEXT_SYNC_COMMITTEE_GINDEX

FinalityBranch = Vector[Bytes32, floorlog2(FINALIZED_ROOT_GINDEX)]
CurrentSyncCommitteeBranch = Vector[
    Bytes32, floorlog2(CURRENT_SYNC_COMMITTEE_GINDEX)]
NextSyncCommitteeBranch = Vector[
    Bytes32, floorlog2(NEXT_SYNC_COMMITTEE_GINDEX)]


# ---------------------------------------------------------------------------
# Containers (sync-protocol.md :85-171)
# ---------------------------------------------------------------------------


class LightClientHeader(Container):
    beacon: BeaconBlockHeader


class LightClientBootstrap(Container):
    # Header matching the requested beacon block root
    header: LightClientHeader
    # Current sync committee corresponding to `header.beacon.state_root`
    current_sync_committee: SyncCommittee
    current_sync_committee_branch: CurrentSyncCommitteeBranch


class LightClientUpdate(Container):
    # Header attested to by the sync committee
    attested_header: LightClientHeader
    # Next sync committee corresponding to `attested_header.beacon.state_root`
    next_sync_committee: SyncCommittee
    next_sync_committee_branch: NextSyncCommitteeBranch
    # Finalized header corresponding to `attested_header.beacon.state_root`
    finalized_header: LightClientHeader
    finality_branch: FinalityBranch
    # Sync committee aggregate signature
    sync_aggregate: SyncAggregate
    # Slot at which the aggregate signature was created (untrusted)
    signature_slot: Slot


class LightClientFinalityUpdate(Container):
    attested_header: LightClientHeader
    finalized_header: LightClientHeader
    finality_branch: FinalityBranch
    sync_aggregate: SyncAggregate
    signature_slot: Slot


class LightClientOptimisticUpdate(Container):
    attested_header: LightClientHeader
    sync_aggregate: SyncAggregate
    signature_slot: Slot


@dataclass
class LightClientStore(object):
    # Header that is finalized
    finalized_header: LightClientHeader
    # Sync committees corresponding to the finalized header
    current_sync_committee: SyncCommittee
    next_sync_committee: SyncCommittee
    # Best available header to switch finalized head to
    best_valid_update: Optional[LightClientUpdate]
    # Most recent available reasonably-safe header
    optimistic_header: LightClientHeader
    # Max committee participation seen (for the safety threshold)
    previous_max_active_participants: uint64
    current_max_active_participants: uint64


# ---------------------------------------------------------------------------
# Helpers (sync-protocol.md :173-320)
# ---------------------------------------------------------------------------


def finalized_root_gindex_at_slot(_slot: Slot):
    return FINALIZED_ROOT_GINDEX


def current_sync_committee_gindex_at_slot(_slot: Slot):
    return CURRENT_SYNC_COMMITTEE_GINDEX


def next_sync_committee_gindex_at_slot(_slot: Slot):
    return NEXT_SYNC_COMMITTEE_GINDEX


def is_valid_light_client_header(_header: LightClientHeader) -> bool:
    return True


def is_sync_committee_update(update: LightClientUpdate) -> bool:
    return update.next_sync_committee_branch != NextSyncCommitteeBranch()


def is_finality_update(update: LightClientUpdate) -> bool:
    return update.finality_branch != FinalityBranch()


def is_better_update(new_update: LightClientUpdate,
                     old_update: LightClientUpdate) -> bool:
    """Update ranking (sync-protocol.md :220-270): supermajority first,
    then relevant-committee presence, finality, committee finality,
    participation, and age tiebreakers."""
    # Compare supermajority (> 2/3) sync committee participation
    max_active_participants = len(new_update.sync_aggregate.sync_committee_bits)
    new_num_active_participants = sum(
        new_update.sync_aggregate.sync_committee_bits)
    old_num_active_participants = sum(
        old_update.sync_aggregate.sync_committee_bits)
    new_has_supermajority = (new_num_active_participants * 3
                             >= max_active_participants * 2)
    old_has_supermajority = (old_num_active_participants * 3
                             >= max_active_participants * 2)
    if new_has_supermajority != old_has_supermajority:
        return new_has_supermajority
    if (not new_has_supermajority
            and new_num_active_participants != old_num_active_participants):
        return new_num_active_participants > old_num_active_participants

    # Compare presence of relevant sync committee
    new_has_relevant_sync_committee = is_sync_committee_update(new_update) and (
        compute_sync_committee_period_at_slot(
            new_update.attested_header.beacon.slot)
        == compute_sync_committee_period_at_slot(new_update.signature_slot))
    old_has_relevant_sync_committee = is_sync_committee_update(old_update) and (
        compute_sync_committee_period_at_slot(
            old_update.attested_header.beacon.slot)
        == compute_sync_committee_period_at_slot(old_update.signature_slot))
    if new_has_relevant_sync_committee != old_has_relevant_sync_committee:
        return new_has_relevant_sync_committee

    # Compare indication of any finality
    new_has_finality = is_finality_update(new_update)
    old_has_finality = is_finality_update(old_update)
    if new_has_finality != old_has_finality:
        return new_has_finality

    # Compare sync committee finality
    if new_has_finality:
        new_has_sync_committee_finality = (
            compute_sync_committee_period_at_slot(
                new_update.finalized_header.beacon.slot)
            == compute_sync_committee_period_at_slot(
                new_update.attested_header.beacon.slot))
        old_has_sync_committee_finality = (
            compute_sync_committee_period_at_slot(
                old_update.finalized_header.beacon.slot)
            == compute_sync_committee_period_at_slot(
                old_update.attested_header.beacon.slot))
        if (new_has_sync_committee_finality
                != old_has_sync_committee_finality):
            return new_has_sync_committee_finality

    # Tiebreaker 1: Sync committee participation beyond supermajority
    if new_num_active_participants != old_num_active_participants:
        return new_num_active_participants > old_num_active_participants

    # Tiebreaker 2: Prefer older data (fewer changes to best)
    if (new_update.attested_header.beacon.slot
            != old_update.attested_header.beacon.slot):
        return (new_update.attested_header.beacon.slot
                < old_update.attested_header.beacon.slot)

    # Tiebreaker 3: Prefer updates with earlier signature slots
    return new_update.signature_slot < old_update.signature_slot


def is_next_sync_committee_known(store: LightClientStore) -> bool:
    return store.next_sync_committee != SyncCommittee()


def get_safety_threshold(store: LightClientStore) -> uint64:
    return max(store.previous_max_active_participants,
               store.current_max_active_participants) // 2


def get_subtree_index(generalized_index) -> uint64:
    return uint64(generalized_index % 2**(floorlog2(generalized_index)))


def is_valid_normalized_merkle_branch(leaf: Bytes32, branch,
                                      gindex, root: Root) -> bool:
    """Branch check tolerating zero-padded extra nodes in front (future
    forks deepen the state tree; branches are normalized to max depth)."""
    depth = floorlog2(gindex)
    index = get_subtree_index(gindex)
    num_extra = len(branch) - depth
    for i in range(num_extra):
        if branch[i] != Bytes32():
            return False
    return is_valid_merkle_branch(leaf, branch[num_extra:], depth, index, root)


def normalize_merkle_branch(branch, gindex):
    """Zero-pad a branch at the front to the depth of `gindex` (electra
    light-client spec `specs/electra/light-client/sync-protocol.md`; a
    no-op pre-electra where branch depths already match)."""
    depth = floorlog2(gindex)
    num_extra = depth - len(branch)
    return [Bytes32()] * num_extra + [Bytes32(bytes(b)) for b in branch]


def compute_sync_committee_period_at_slot(slot: Slot) -> uint64:
    return compute_sync_committee_period(compute_epoch_at_slot(slot))


# ---------------------------------------------------------------------------
# Initialization (sync-protocol.md :322-354)
# ---------------------------------------------------------------------------


def initialize_light_client_store(
        trusted_block_root: Root,
        bootstrap: LightClientBootstrap) -> LightClientStore:
    assert is_valid_light_client_header(bootstrap.header)
    assert hash_tree_root(bootstrap.header.beacon) == trusted_block_root

    assert is_valid_normalized_merkle_branch(
        leaf=hash_tree_root(bootstrap.current_sync_committee),
        branch=bootstrap.current_sync_committee_branch,
        gindex=current_sync_committee_gindex_at_slot(
            bootstrap.header.beacon.slot),
        root=bootstrap.header.beacon.state_root,
    )

    return LightClientStore(
        finalized_header=bootstrap.header,
        current_sync_committee=bootstrap.current_sync_committee,
        next_sync_committee=SyncCommittee(),
        best_valid_update=None,
        optimistic_header=bootstrap.header,
        previous_max_active_participants=0,
        current_max_active_participants=0,
    )


# ---------------------------------------------------------------------------
# Update processing (sync-protocol.md :356-590)
# ---------------------------------------------------------------------------


def validate_light_client_update(store: LightClientStore,
                                 update: LightClientUpdate,
                                 current_slot: Slot,
                                 genesis_validators_root: Root) -> None:
    # Verify sync committee has sufficient participants
    sync_aggregate = update.sync_aggregate
    assert (sum(sync_aggregate.sync_committee_bits)
            >= MIN_SYNC_COMMITTEE_PARTICIPANTS)

    # Verify update does not skip a sync committee period
    assert is_valid_light_client_header(update.attested_header)
    update_attested_slot = update.attested_header.beacon.slot
    update_finalized_slot = update.finalized_header.beacon.slot
    assert (current_slot >= update.signature_slot
            > update_attested_slot >= update_finalized_slot)
    store_period = compute_sync_committee_period_at_slot(
        store.finalized_header.beacon.slot)
    update_signature_period = compute_sync_committee_period_at_slot(
        update.signature_slot)
    if is_next_sync_committee_known(store):
        assert update_signature_period in (store_period, store_period + 1)
    else:
        assert update_signature_period == store_period

    # Verify update is relevant
    update_attested_period = compute_sync_committee_period_at_slot(
        update_attested_slot)
    update_has_next_sync_committee = (
        not is_next_sync_committee_known(store)
        and is_sync_committee_update(update)
        and update_attested_period == store_period)
    assert (update_attested_slot > store.finalized_header.beacon.slot
            or update_has_next_sync_committee)

    # Verify the finality branch confirms finalized_header to match the
    # finalized checkpoint root of the attested state (genesis finalized
    # root is the zero hash)
    if not is_finality_update(update):
        assert update.finalized_header == LightClientHeader()
    else:
        if update_finalized_slot == GENESIS_SLOT:
            assert update.finalized_header == LightClientHeader()
            finalized_root = Bytes32()
        else:
            assert is_valid_light_client_header(update.finalized_header)
            finalized_root = hash_tree_root(update.finalized_header.beacon)
        assert is_valid_normalized_merkle_branch(
            leaf=finalized_root,
            branch=update.finality_branch,
            gindex=finalized_root_gindex_at_slot(
                update.attested_header.beacon.slot),
            root=update.attested_header.beacon.state_root,
        )

    # Verify the next_sync_committee is the one saved in the attested state
    if not is_sync_committee_update(update):
        assert update.next_sync_committee == SyncCommittee()
    else:
        if (update_attested_period == store_period
                and is_next_sync_committee_known(store)):
            assert update.next_sync_committee == store.next_sync_committee
        assert is_valid_normalized_merkle_branch(
            leaf=hash_tree_root(update.next_sync_committee),
            branch=update.next_sync_committee_branch,
            gindex=next_sync_committee_gindex_at_slot(
                update.attested_header.beacon.slot),
            root=update.attested_header.beacon.state_root,
        )

    # Verify sync committee aggregate signature
    if update_signature_period == store_period:
        sync_committee = store.current_sync_committee
    else:
        sync_committee = store.next_sync_committee
    participant_pubkeys = [
        pubkey for (bit, pubkey)
        in zip(sync_aggregate.sync_committee_bits, sync_committee.pubkeys)
        if bit
    ]
    fork_version_slot = max(update.signature_slot, Slot(1)) - Slot(1)
    fork_version = compute_fork_version(
        compute_epoch_at_slot(fork_version_slot))
    domain = compute_domain(DOMAIN_SYNC_COMMITTEE, fork_version,
                            genesis_validators_root)
    signing_root = compute_signing_root(update.attested_header.beacon, domain)
    assert bls.FastAggregateVerify(
        participant_pubkeys, signing_root,
        sync_aggregate.sync_committee_signature)


def apply_light_client_update(store: LightClientStore,
                              update: LightClientUpdate) -> None:
    store_period = compute_sync_committee_period_at_slot(
        store.finalized_header.beacon.slot)
    update_finalized_period = compute_sync_committee_period_at_slot(
        update.finalized_header.beacon.slot)
    if not is_next_sync_committee_known(store):
        assert update_finalized_period == store_period
        store.next_sync_committee = update.next_sync_committee
    elif update_finalized_period == store_period + 1:
        store.current_sync_committee = store.next_sync_committee
        store.next_sync_committee = update.next_sync_committee
        store.previous_max_active_participants = (
            store.current_max_active_participants)
        store.current_max_active_participants = 0
    if (update.finalized_header.beacon.slot
            > store.finalized_header.beacon.slot):
        store.finalized_header = update.finalized_header
        if (store.finalized_header.beacon.slot
                > store.optimistic_header.beacon.slot):
            store.optimistic_header = store.finalized_header


def process_light_client_store_force_update(store: LightClientStore,
                                            current_slot: Slot) -> None:
    """Forced best update after UPDATE_TIMEOUT: treats the attested
    header as finalized to guarantee period progression during extended
    non-finality (sync-protocol.md :483-499)."""
    if (current_slot > store.finalized_header.beacon.slot + UPDATE_TIMEOUT
            and store.best_valid_update is not None):
        if (store.best_valid_update.finalized_header.beacon.slot
                <= store.finalized_header.beacon.slot):
            store.best_valid_update.finalized_header = (
                store.best_valid_update.attested_header)
        apply_light_client_update(store, store.best_valid_update)
        store.best_valid_update = None


def process_light_client_update(store: LightClientStore,
                                update: LightClientUpdate,
                                current_slot: Slot,
                                genesis_validators_root: Root) -> None:
    validate_light_client_update(store, update, current_slot,
                                 genesis_validators_root)

    sync_committee_bits = update.sync_aggregate.sync_committee_bits

    # Track the best update for a potential forced update
    if (store.best_valid_update is None
            or is_better_update(update, store.best_valid_update)):
        store.best_valid_update = update

    # Track the maximum number of active participants
    store.current_max_active_participants = max(
        store.current_max_active_participants, sum(sync_committee_bits))

    # Update the optimistic header
    if (sum(sync_committee_bits) > get_safety_threshold(store)
            and update.attested_header.beacon.slot
            > store.optimistic_header.beacon.slot):
        store.optimistic_header = update.attested_header

    # Update finalized header
    update_has_finalized_next_sync_committee = (
        not is_next_sync_committee_known(store)
        and is_sync_committee_update(update)
        and is_finality_update(update)
        and (compute_sync_committee_period_at_slot(
                update.finalized_header.beacon.slot)
             == compute_sync_committee_period_at_slot(
                update.attested_header.beacon.slot)))
    if (sum(sync_committee_bits) * 3 >= len(sync_committee_bits) * 2
            and (update.finalized_header.beacon.slot
                 > store.finalized_header.beacon.slot
                 or update_has_finalized_next_sync_committee)):
        # Normal update through 2/3 threshold
        apply_light_client_update(store, update)
        store.best_valid_update = None


def process_light_client_finality_update(
        store: LightClientStore,
        finality_update: LightClientFinalityUpdate,
        current_slot: Slot, genesis_validators_root: Root) -> None:
    update = LightClientUpdate(
        attested_header=finality_update.attested_header,
        next_sync_committee=SyncCommittee(),
        next_sync_committee_branch=NextSyncCommitteeBranch(),
        finalized_header=finality_update.finalized_header,
        finality_branch=finality_update.finality_branch,
        sync_aggregate=finality_update.sync_aggregate,
        signature_slot=finality_update.signature_slot,
    )
    process_light_client_update(store, update, current_slot,
                                genesis_validators_root)


def process_light_client_optimistic_update(
        store: LightClientStore,
        optimistic_update: LightClientOptimisticUpdate,
        current_slot: Slot, genesis_validators_root: Root) -> None:
    update = LightClientUpdate(
        attested_header=optimistic_update.attested_header,
        next_sync_committee=SyncCommittee(),
        next_sync_committee_branch=NextSyncCommitteeBranch(),
        finalized_header=LightClientHeader(),
        finality_branch=FinalityBranch(),
        sync_aggregate=optimistic_update.sync_aggregate,
        signature_slot=optimistic_update.signature_slot,
    )
    process_light_client_update(store, update, current_slot,
                                genesis_validators_root)


# ---------------------------------------------------------------------------
# Full node: deriving light client data (full-node.md :37-221)
# ---------------------------------------------------------------------------


def compute_merkle_proof(object, index):
    """Branch for gindex `index` of an SSZ object (full-node.md :31)."""
    return compute_merkle_proof_backing(object, index)


def block_to_light_client_header(block: SignedBeaconBlock) -> LightClientHeader:
    return LightClientHeader(
        beacon=BeaconBlockHeader(
            slot=block.message.slot,
            proposer_index=block.message.proposer_index,
            parent_root=block.message.parent_root,
            state_root=block.message.state_root,
            body_root=hash_tree_root(block.message.body),
        ),
    )


def create_light_client_bootstrap(
        state: BeaconState,
        block: SignedBeaconBlock) -> LightClientBootstrap:
    assert compute_epoch_at_slot(state.slot) >= config.ALTAIR_FORK_EPOCH

    assert state.slot == state.latest_block_header.slot
    header = state.latest_block_header.copy()
    header.state_root = hash_tree_root(state)
    assert hash_tree_root(header) == hash_tree_root(block.message)

    return LightClientBootstrap(
        header=block_to_light_client_header(block),
        current_sync_committee=state.current_sync_committee,
        current_sync_committee_branch=CurrentSyncCommitteeBranch(
            normalize_merkle_branch(
                compute_merkle_proof(
                    state,
                    current_sync_committee_gindex_at_slot(state.slot)),
                CURRENT_SYNC_COMMITTEE_GINDEX)),
    )


def create_light_client_update(state: BeaconState, block: SignedBeaconBlock,
                               attested_state: BeaconState,
                               attested_block: SignedBeaconBlock,
                               finalized_block) -> LightClientUpdate:
    """Derive the period's LightClientUpdate from a block whose
    sync_aggregate attests its parent (full-node.md :109-168)."""
    assert (compute_epoch_at_slot(attested_state.slot)
            >= config.ALTAIR_FORK_EPOCH)
    assert (sum(block.message.body.sync_aggregate.sync_committee_bits)
            >= MIN_SYNC_COMMITTEE_PARTICIPANTS)

    assert state.slot == state.latest_block_header.slot
    header = state.latest_block_header.copy()
    header.state_root = hash_tree_root(state)
    assert hash_tree_root(header) == hash_tree_root(block.message)
    update_signature_period = compute_sync_committee_period_at_slot(
        block.message.slot)

    assert attested_state.slot == attested_state.latest_block_header.slot
    attested_header = attested_state.latest_block_header.copy()
    attested_header.state_root = hash_tree_root(attested_state)
    assert (hash_tree_root(attested_header)
            == hash_tree_root(attested_block.message)
            == block.message.parent_root)
    update_attested_period = compute_sync_committee_period_at_slot(
        attested_block.message.slot)

    update = LightClientUpdate()

    update.attested_header = block_to_light_client_header(attested_block)

    # next_sync_committee is only useful if signed by the current committee
    if update_attested_period == update_signature_period:
        update.next_sync_committee = attested_state.next_sync_committee
        update.next_sync_committee_branch = NextSyncCommitteeBranch(
            normalize_merkle_branch(
                compute_merkle_proof(
                    attested_state,
                    next_sync_committee_gindex_at_slot(attested_state.slot)),
                NEXT_SYNC_COMMITTEE_GINDEX))

    # Indicate finality whenever possible
    if finalized_block is not None:
        if finalized_block.message.slot != GENESIS_SLOT:
            update.finalized_header = block_to_light_client_header(
                finalized_block)
            assert (hash_tree_root(update.finalized_header.beacon)
                    == attested_state.finalized_checkpoint.root)
        else:
            assert attested_state.finalized_checkpoint.root == Bytes32()
        update.finality_branch = FinalityBranch(
            normalize_merkle_branch(
                compute_merkle_proof(
                    attested_state,
                    finalized_root_gindex_at_slot(attested_state.slot)),
                FINALIZED_ROOT_GINDEX))

    update.sync_aggregate = block.message.body.sync_aggregate
    update.signature_slot = block.message.slot

    return update


def create_light_client_finality_update(
        update: LightClientUpdate) -> LightClientFinalityUpdate:
    return LightClientFinalityUpdate(
        attested_header=update.attested_header,
        finalized_header=update.finalized_header,
        finality_branch=update.finality_branch,
        sync_aggregate=update.sync_aggregate,
        signature_slot=update.signature_slot,
    )


def create_light_client_optimistic_update(
        update: LightClientUpdate) -> LightClientOptimisticUpdate:
    return LightClientOptimisticUpdate(
        attested_header=update.attested_header,
        sync_aggregate=update.sync_aggregate,
        signature_slot=update.signature_slot,
    )
