# Electra -- The Beacon Chain (executable spec source, delta over deneb).
#
# EIP-7251 (maxEB: compounding credentials, balance-denominated churn,
# pending deposits/withdrawals/consolidations), EIP-6110 (EL-triggered
# deposits), EIP-7002 (EL-triggered withdrawals), EIP-7549 (committee-bits
# attestations), EIP-7691 (blob throughput).  Parity contract:
# specs/electra/beacon-chain.md (constants :126-216, containers :218-421,
# helpers :423-830, epoch :833-1069, engine :1071-1163,
# block :1165-1860).

# ---------------------------------------------------------------------------
# Constants (beacon-chain.md :126-150)
# ---------------------------------------------------------------------------

UNSET_DEPOSIT_REQUESTS_START_INDEX = uint64(2**64 - 1)
FULL_EXIT_REQUEST_AMOUNT = uint64(0)
COMPOUNDING_WITHDRAWAL_PREFIX = Bytes1("0x02")
DEPOSIT_REQUEST_TYPE = Bytes1("0x00")
WITHDRAWAL_REQUEST_TYPE = Bytes1("0x01")
CONSOLIDATION_REQUEST_TYPE = Bytes1("0x02")


# ---------------------------------------------------------------------------
# New containers (beacon-chain.md :220-311)
# ---------------------------------------------------------------------------


class PendingDeposit(Container):
    pubkey: BLSPubkey
    withdrawal_credentials: Bytes32
    amount: Gwei
    signature: BLSSignature
    slot: Slot


class PendingPartialWithdrawal(Container):
    validator_index: ValidatorIndex
    amount: Gwei
    withdrawable_epoch: Epoch


class PendingConsolidation(Container):
    source_index: ValidatorIndex
    target_index: ValidatorIndex


class DepositRequest(Container):
    pubkey: BLSPubkey
    withdrawal_credentials: Bytes32
    amount: Gwei
    signature: BLSSignature
    index: uint64


class WithdrawalRequest(Container):
    source_address: ExecutionAddress
    validator_pubkey: BLSPubkey
    amount: Gwei


class ConsolidationRequest(Container):
    source_address: ExecutionAddress
    source_pubkey: BLSPubkey
    target_pubkey: BLSPubkey


class ExecutionRequests(Container):
    # [New in Electra:EIP6110]
    deposits: List[DepositRequest, MAX_DEPOSIT_REQUESTS_PER_PAYLOAD]
    # [New in Electra:EIP7002:EIP7251]
    withdrawals: List[WithdrawalRequest, MAX_WITHDRAWAL_REQUESTS_PER_PAYLOAD]
    # [New in Electra:EIP7251]
    consolidations: List[ConsolidationRequest, MAX_CONSOLIDATION_REQUESTS_PER_PAYLOAD]


class SingleAttestation(Container):
    committee_index: CommitteeIndex
    attester_index: ValidatorIndex
    data: AttestationData
    signature: BLSSignature


# ---------------------------------------------------------------------------
# Modified containers (beacon-chain.md :313-421)
# ---------------------------------------------------------------------------


class Attestation(Container):
    # [Modified in Electra:EIP7549]
    aggregation_bits: Bitlist[MAX_VALIDATORS_PER_COMMITTEE * MAX_COMMITTEES_PER_SLOT]
    data: AttestationData
    signature: BLSSignature
    # [New in Electra:EIP7549]
    committee_bits: Bitvector[MAX_COMMITTEES_PER_SLOT]


class IndexedAttestation(Container):
    # [Modified in Electra:EIP7549]
    attesting_indices: List[ValidatorIndex, MAX_VALIDATORS_PER_COMMITTEE * MAX_COMMITTEES_PER_SLOT]
    data: AttestationData
    signature: BLSSignature


class AttesterSlashing(Container):
    # [Modified in Electra:EIP7549]
    attestation_1: IndexedAttestation
    attestation_2: IndexedAttestation


class BeaconBlockBody(Container):
    randao_reveal: BLSSignature
    eth1_data: Eth1Data
    graffiti: Bytes32
    proposer_slashings: List[ProposerSlashing, MAX_PROPOSER_SLASHINGS]
    # [Modified in Electra:EIP7549]
    attester_slashings: List[AttesterSlashing, MAX_ATTESTER_SLASHINGS_ELECTRA]
    # [Modified in Electra:EIP7549]
    attestations: List[Attestation, MAX_ATTESTATIONS_ELECTRA]
    deposits: List[Deposit, MAX_DEPOSITS]
    voluntary_exits: List[SignedVoluntaryExit, MAX_VOLUNTARY_EXITS]
    sync_aggregate: SyncAggregate
    execution_payload: ExecutionPayload
    bls_to_execution_changes: List[SignedBLSToExecutionChange, MAX_BLS_TO_EXECUTION_CHANGES]
    blob_kzg_commitments: List[KZGCommitment, MAX_BLOB_COMMITMENTS_PER_BLOCK]
    # [New in Electra]
    execution_requests: ExecutionRequests


class BeaconBlock(Container):
    slot: Slot
    proposer_index: ValidatorIndex
    parent_root: Root
    state_root: Root
    body: BeaconBlockBody


class SignedBeaconBlock(Container):
    message: BeaconBlock
    signature: BLSSignature


class BeaconState(Container):
    genesis_time: uint64
    genesis_validators_root: Root
    slot: Slot
    fork: Fork
    latest_block_header: BeaconBlockHeader
    block_roots: Vector[Root, SLOTS_PER_HISTORICAL_ROOT]
    state_roots: Vector[Root, SLOTS_PER_HISTORICAL_ROOT]
    historical_roots: List[Root, HISTORICAL_ROOTS_LIMIT]
    eth1_data: Eth1Data
    eth1_data_votes: List[Eth1Data, EPOCHS_PER_ETH1_VOTING_PERIOD * SLOTS_PER_EPOCH]
    eth1_deposit_index: uint64
    validators: List[Validator, VALIDATOR_REGISTRY_LIMIT]
    balances: List[Gwei, VALIDATOR_REGISTRY_LIMIT]
    randao_mixes: Vector[Bytes32, EPOCHS_PER_HISTORICAL_VECTOR]
    slashings: Vector[Gwei, EPOCHS_PER_SLASHINGS_VECTOR]
    previous_epoch_participation: List[ParticipationFlags, VALIDATOR_REGISTRY_LIMIT]
    current_epoch_participation: List[ParticipationFlags, VALIDATOR_REGISTRY_LIMIT]
    justification_bits: Bitvector[JUSTIFICATION_BITS_LENGTH]
    previous_justified_checkpoint: Checkpoint
    current_justified_checkpoint: Checkpoint
    finalized_checkpoint: Checkpoint
    inactivity_scores: List[uint64, VALIDATOR_REGISTRY_LIMIT]
    current_sync_committee: SyncCommittee
    next_sync_committee: SyncCommittee
    latest_execution_payload_header: ExecutionPayloadHeader
    next_withdrawal_index: WithdrawalIndex
    next_withdrawal_validator_index: ValidatorIndex
    historical_summaries: List[HistoricalSummary, HISTORICAL_ROOTS_LIMIT]
    # [New in Electra:EIP6110]
    deposit_requests_start_index: uint64
    # [New in Electra:EIP7251]
    deposit_balance_to_consume: Gwei
    exit_balance_to_consume: Gwei
    earliest_exit_epoch: Epoch
    consolidation_balance_to_consume: Gwei
    earliest_consolidation_epoch: Epoch
    pending_deposits: List[PendingDeposit, PENDING_DEPOSITS_LIMIT]
    pending_partial_withdrawals: List[PendingPartialWithdrawal, PENDING_PARTIAL_WITHDRAWALS_LIMIT]
    pending_consolidations: List[PendingConsolidation, PENDING_CONSOLIDATIONS_LIMIT]


# ---------------------------------------------------------------------------
# Predicates (beacon-chain.md :425-546)
# ---------------------------------------------------------------------------


def compute_proposer_index(state: BeaconState, indices, seed: Bytes32) -> ValidatorIndex:
    """Effective-balance-weighted sampling with a 16-bit random value and
    the electra max effective balance."""
    assert len(indices) > 0
    MAX_RANDOM_VALUE = 2**16 - 1  # [Modified in Electra]
    i = uint64(0)
    total = uint64(len(indices))
    while True:
        candidate_index = indices[compute_shuffled_index(i % total, total, seed)]
        # [Modified in Electra]
        random_bytes = hash(seed + uint_to_bytes(uint64(i // 16)))
        offset = i % 16 * 2
        random_value = bytes_to_uint64(random_bytes[offset:offset + 2])
        effective_balance = state.validators[candidate_index].effective_balance
        # [Modified in Electra:EIP7251]
        if (effective_balance * MAX_RANDOM_VALUE
                >= MAX_EFFECTIVE_BALANCE_ELECTRA * random_value):
            return candidate_index
        i += 1


def is_eligible_for_activation_queue(validator: Validator) -> bool:
    """Eligible for the activation queue (EIP-7251 threshold)."""
    return (
        validator.activation_eligibility_epoch == FAR_FUTURE_EPOCH
        # [Modified in Electra:EIP7251]
        and validator.effective_balance >= MIN_ACTIVATION_BALANCE
    )


def is_compounding_withdrawal_credential(withdrawal_credentials: Bytes32) -> bool:
    return withdrawal_credentials[:1] == COMPOUNDING_WITHDRAWAL_PREFIX


def has_compounding_withdrawal_credential(validator: Validator) -> bool:
    """0x02-prefixed ("compounding") withdrawal credential?"""
    return is_compounding_withdrawal_credential(validator.withdrawal_credentials)


def has_execution_withdrawal_credential(validator: Validator) -> bool:
    """0x01 or 0x02 prefixed withdrawal credential?"""
    return (has_eth1_withdrawal_credential(validator)
            or has_compounding_withdrawal_credential(validator))


def is_fully_withdrawable_validator(validator: Validator, balance: Gwei,
                                    epoch: Epoch) -> bool:
    return (
        # [Modified in Electra:EIP7251]
        has_execution_withdrawal_credential(validator)
        and validator.withdrawable_epoch <= epoch
        and balance > 0
    )


def is_partially_withdrawable_validator(validator: Validator,
                                        balance: Gwei) -> bool:
    max_effective_balance = get_max_effective_balance(validator)
    # [Modified in Electra:EIP7251]
    has_max_effective_balance = (validator.effective_balance
                                 == max_effective_balance)
    has_excess_balance = balance > max_effective_balance
    return (
        has_execution_withdrawal_credential(validator)
        and has_max_effective_balance
        and has_excess_balance
    )


# ---------------------------------------------------------------------------
# Misc + accessors (beacon-chain.md :548-673)
# ---------------------------------------------------------------------------


def get_committee_indices(committee_bits) -> Sequence[CommitteeIndex]:
    return [CommitteeIndex(index) for index, bit in enumerate(committee_bits)
            if bit]


def get_max_effective_balance(validator: Validator) -> Gwei:
    """Max effective balance by credential type."""
    if has_compounding_withdrawal_credential(validator):
        return MAX_EFFECTIVE_BALANCE_ELECTRA
    else:
        return MIN_ACTIVATION_BALANCE


def get_balance_churn_limit(state: BeaconState) -> Gwei:
    """Balance-denominated churn limit for the current epoch."""
    churn = max(config.MIN_PER_EPOCH_CHURN_LIMIT_ELECTRA,
                get_total_active_balance(state) // config.CHURN_LIMIT_QUOTIENT)
    return churn - churn % EFFECTIVE_BALANCE_INCREMENT


def get_activation_exit_churn_limit(state: BeaconState) -> Gwei:
    """Churn limit dedicated to activations and exits."""
    return min(config.MAX_PER_EPOCH_ACTIVATION_EXIT_CHURN_LIMIT,
               get_balance_churn_limit(state))


def get_consolidation_churn_limit(state: BeaconState) -> Gwei:
    return get_balance_churn_limit(state) - get_activation_exit_churn_limit(state)


def get_pending_balance_to_withdraw(state: BeaconState,
                                    validator_index: ValidatorIndex) -> Gwei:
    return sum(
        withdrawal.amount for withdrawal in state.pending_partial_withdrawals
        if withdrawal.validator_index == validator_index
    )


def get_attesting_indices(state: BeaconState,
                          attestation: Attestation) -> Set[ValidatorIndex]:
    """Attesting indices from aggregation_bits + committee_bits
    (EIP-7549)."""
    output: Set[ValidatorIndex] = set()
    committee_indices = get_committee_indices(attestation.committee_bits)
    committee_offset = 0
    for committee_index in committee_indices:
        committee = get_beacon_committee(state, attestation.data.slot,
                                         committee_index)
        committee_attesters = set(
            attester_index for i, attester_index in enumerate(committee)
            if attestation.aggregation_bits[committee_offset + i])
        output = output.union(committee_attesters)

        committee_offset += len(committee)

    return output


def get_next_sync_committee_indices(state: BeaconState) -> Sequence[ValidatorIndex]:
    """Sampling with a 16-bit random value and the electra max effective
    balance."""
    epoch = Epoch(get_current_epoch(state) + 1)

    MAX_RANDOM_VALUE = 2**16 - 1  # [Modified in Electra]
    active_validator_indices = get_active_validator_indices(state, epoch)
    active_validator_count = uint64(len(active_validator_indices))
    seed = get_seed(state, epoch, DOMAIN_SYNC_COMMITTEE)
    i = uint64(0)
    sync_committee_indices = []
    while len(sync_committee_indices) < SYNC_COMMITTEE_SIZE:
        shuffled_index = compute_shuffled_index(
            uint64(i % active_validator_count), active_validator_count, seed)
        candidate_index = active_validator_indices[shuffled_index]
        # [Modified in Electra]
        random_bytes = hash(seed + uint_to_bytes(uint64(i // 16)))
        offset = i % 16 * 2
        random_value = bytes_to_uint64(random_bytes[offset:offset + 2])
        effective_balance = state.validators[candidate_index].effective_balance
        # [Modified in Electra:EIP7251]
        if (effective_balance * MAX_RANDOM_VALUE
                >= MAX_EFFECTIVE_BALANCE_ELECTRA * random_value):
            sync_committee_indices.append(candidate_index)
        i += 1
    return sync_committee_indices


# ---------------------------------------------------------------------------
# Mutators (beacon-chain.md :675-830)
# ---------------------------------------------------------------------------


def initiate_validator_exit(state: BeaconState, index: ValidatorIndex) -> None:
    """Exit via the balance-churn queue (EIP-7251)."""
    # Return if validator already initiated exit
    validator = state.validators[index]
    if validator.exit_epoch != FAR_FUTURE_EPOCH:
        return

    # Compute exit queue epoch [Modified in Electra:EIP7251]
    exit_queue_epoch = compute_exit_epoch_and_update_churn(
        state, validator.effective_balance)

    # Set validator exit epoch and withdrawable epoch
    validator.exit_epoch = exit_queue_epoch
    validator.withdrawable_epoch = Epoch(
        validator.exit_epoch + config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY)


def switch_to_compounding_validator(state: BeaconState,
                                    index: ValidatorIndex) -> None:
    validator = state.validators[index]
    validator.withdrawal_credentials = (
        COMPOUNDING_WITHDRAWAL_PREFIX + validator.withdrawal_credentials[1:])
    queue_excess_active_balance(state, index)


def queue_excess_active_balance(state: BeaconState,
                                index: ValidatorIndex) -> None:
    balance = state.balances[index]
    if balance > MIN_ACTIVATION_BALANCE:
        excess_balance = balance - MIN_ACTIVATION_BALANCE
        state.balances[index] = MIN_ACTIVATION_BALANCE
        validator = state.validators[index]
        # G2 infinity signature + GENESIS_SLOT distinguish this from a
        # pending deposit request
        state.pending_deposits.append(PendingDeposit(
            pubkey=validator.pubkey,
            withdrawal_credentials=validator.withdrawal_credentials,
            amount=excess_balance,
            signature=G2_POINT_AT_INFINITY,
            slot=GENESIS_SLOT,
        ))


def compute_exit_epoch_and_update_churn(state: BeaconState,
                                        exit_balance: Gwei) -> Epoch:
    """Allocate `exit_balance` into the earliest epoch(s) with spare exit
    churn (beacon-chain.md :733-759)."""
    earliest_exit_epoch = max(
        state.earliest_exit_epoch,
        compute_activation_exit_epoch(get_current_epoch(state)))
    per_epoch_churn = get_activation_exit_churn_limit(state)
    # New epoch for exits
    if state.earliest_exit_epoch < earliest_exit_epoch:
        exit_balance_to_consume = per_epoch_churn
    else:
        exit_balance_to_consume = state.exit_balance_to_consume

    # Exit doesn't fit in the current earliest epoch
    if exit_balance > exit_balance_to_consume:
        balance_to_process = exit_balance - exit_balance_to_consume
        additional_epochs = (balance_to_process - 1) // per_epoch_churn + 1
        earliest_exit_epoch += additional_epochs
        exit_balance_to_consume += additional_epochs * per_epoch_churn

    # Consume the balance and update state variables
    state.exit_balance_to_consume = exit_balance_to_consume - exit_balance
    state.earliest_exit_epoch = earliest_exit_epoch

    return state.earliest_exit_epoch


def compute_consolidation_epoch_and_update_churn(
        state: BeaconState, consolidation_balance: Gwei) -> Epoch:
    """Same allocation scheme over the consolidation churn."""
    earliest_consolidation_epoch = max(
        state.earliest_consolidation_epoch,
        compute_activation_exit_epoch(get_current_epoch(state)))
    per_epoch_consolidation_churn = get_consolidation_churn_limit(state)
    # New epoch for consolidations
    if state.earliest_consolidation_epoch < earliest_consolidation_epoch:
        consolidation_balance_to_consume = per_epoch_consolidation_churn
    else:
        consolidation_balance_to_consume = state.consolidation_balance_to_consume

    # Consolidation doesn't fit in the current earliest epoch
    if consolidation_balance > consolidation_balance_to_consume:
        balance_to_process = (consolidation_balance
                              - consolidation_balance_to_consume)
        additional_epochs = ((balance_to_process - 1)
                             // per_epoch_consolidation_churn + 1)
        earliest_consolidation_epoch += additional_epochs
        consolidation_balance_to_consume += (additional_epochs
                                             * per_epoch_consolidation_churn)

    # Consume the balance and update state variables
    state.consolidation_balance_to_consume = (
        consolidation_balance_to_consume - consolidation_balance)
    state.earliest_consolidation_epoch = earliest_consolidation_epoch

    return state.earliest_consolidation_epoch


def slash_validator(state: BeaconState, slashed_index: ValidatorIndex,
                    whistleblower_index: ValidatorIndex = None) -> None:
    """EIP-7251 slashing penalty and whistleblower quotients."""
    epoch = get_current_epoch(state)
    initiate_validator_exit(state, slashed_index)
    validator = state.validators[slashed_index]
    validator.slashed = True
    validator.withdrawable_epoch = max(
        validator.withdrawable_epoch,
        Epoch(epoch + EPOCHS_PER_SLASHINGS_VECTOR))
    state.slashings[epoch % EPOCHS_PER_SLASHINGS_VECTOR] += validator.effective_balance
    # [Modified in Electra:EIP7251]
    slashing_penalty = (validator.effective_balance
                        // MIN_SLASHING_PENALTY_QUOTIENT_ELECTRA)
    decrease_balance(state, slashed_index, slashing_penalty)

    # Apply proposer and whistleblower rewards
    proposer_index = get_beacon_proposer_index(state)
    if whistleblower_index is None:
        whistleblower_index = proposer_index
    # [Modified in Electra:EIP7251]
    whistleblower_reward = Gwei(validator.effective_balance
                                // WHISTLEBLOWER_REWARD_QUOTIENT_ELECTRA)
    proposer_reward = Gwei(whistleblower_reward * PROPOSER_WEIGHT
                           // WEIGHT_DENOMINATOR)
    increase_balance(state, proposer_index, proposer_reward)
    increase_balance(state, whistleblower_index,
                     Gwei(whistleblower_reward - proposer_reward))


# ---------------------------------------------------------------------------
# Epoch processing (beacon-chain.md :833-1069)
# ---------------------------------------------------------------------------


def process_epoch(state: BeaconState) -> None:
    process_justification_and_finalization(state)
    process_inactivity_updates(state)
    process_rewards_and_penalties(state)
    process_registry_updates(state)  # [Modified in Electra:EIP7251]
    process_slashings(state)  # [Modified in Electra:EIP7251]
    process_eth1_data_reset(state)
    process_pending_deposits(state)  # [New in Electra:EIP7251]
    process_pending_consolidations(state)  # [New in Electra:EIP7251]
    process_effective_balance_updates(state)  # [Modified in Electra:EIP7251]
    process_slashings_reset(state)
    process_randao_mixes_reset(state)
    process_historical_summaries_update(state)
    process_participation_flag_updates(state)
    process_sync_committee_updates(state)


def process_registry_updates(state: BeaconState) -> None:
    """Eligibility, ejections, and activations in a single sweep."""
    current_epoch = get_current_epoch(state)
    activation_epoch = compute_activation_exit_epoch(current_epoch)

    for index, validator in enumerate(state.validators):
        if is_eligible_for_activation_queue(validator):  # [Modified in Electra:EIP7251]
            validator.activation_eligibility_epoch = current_epoch + 1
        elif (is_active_validator(validator, current_epoch)
                and validator.effective_balance <= config.EJECTION_BALANCE):
            initiate_validator_exit(state, ValidatorIndex(index))  # [Modified in Electra:EIP7251]
        elif is_eligible_for_activation(state, validator):
            validator.activation_epoch = activation_epoch


def process_slashings(state: BeaconState) -> None:
    """Per-increment correlation penalty (EIP-7251)."""
    epoch = get_current_epoch(state)
    total_balance = get_total_active_balance(state)
    adjusted_total_slashing_balance = min(
        sum(state.slashings) * PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX,
        total_balance)
    # Factored out from total balance to avoid uint64 overflow
    increment = EFFECTIVE_BALANCE_INCREMENT
    penalty_per_effective_balance_increment = (
        adjusted_total_slashing_balance // (total_balance // increment))
    for index, validator in enumerate(state.validators):
        if (validator.slashed
                and epoch + EPOCHS_PER_SLASHINGS_VECTOR // 2
                == validator.withdrawable_epoch):
            effective_balance_increments = (validator.effective_balance
                                            // increment)
            # [Modified in Electra:EIP7251]
            penalty = (penalty_per_effective_balance_increment
                       * effective_balance_increments)
            decrease_balance(state, ValidatorIndex(index), penalty)


def apply_pending_deposit(state: BeaconState, deposit: PendingDeposit) -> None:
    """Apply `deposit` to the state (new validator or top-up)."""
    validator_pubkeys = [v.pubkey for v in state.validators]
    if deposit.pubkey not in validator_pubkeys:
        # Verify the proof of possession (not checked by the contract)
        if is_valid_deposit_signature(deposit.pubkey,
                                      deposit.withdrawal_credentials,
                                      deposit.amount, deposit.signature):
            add_validator_to_registry(state, deposit.pubkey,
                                      deposit.withdrawal_credentials,
                                      deposit.amount)
    else:
        validator_index = ValidatorIndex(
            validator_pubkeys.index(deposit.pubkey))
        increase_balance(state, validator_index, deposit.amount)


def process_pending_deposits(state: BeaconState) -> None:
    """Drain the pending-deposit queue subject to: Eth1-bridge ordering,
    finality of the deposit's slot, the per-epoch count limit, and the
    activation churn (beacon-chain.md :940-1017)."""
    next_epoch = Epoch(get_current_epoch(state) + 1)
    available_for_processing = (state.deposit_balance_to_consume
                                + get_activation_exit_churn_limit(state))
    processed_amount = 0
    next_deposit_index = 0
    deposits_to_postpone = []
    is_churn_limit_reached = False
    finalized_slot = compute_start_slot_at_epoch(
        state.finalized_checkpoint.epoch)

    for deposit in state.pending_deposits:
        # Deposit requests wait until all Eth1 bridge deposits apply
        if (deposit.slot > GENESIS_SLOT
                and state.eth1_deposit_index
                < state.deposit_requests_start_index):
            break

        # Stop once deposits are no longer finalized
        if deposit.slot > finalized_slot:
            break

        # Stop at the per-epoch processing limit
        if next_deposit_index >= MAX_PENDING_DEPOSITS_PER_EPOCH:
            break

        # Read validator state
        is_validator_exited = False
        is_validator_withdrawn = False
        validator_pubkeys = [v.pubkey for v in state.validators]
        if deposit.pubkey in validator_pubkeys:
            validator = state.validators[
                ValidatorIndex(validator_pubkeys.index(deposit.pubkey))]
            is_validator_exited = validator.exit_epoch < FAR_FUTURE_EPOCH
            is_validator_withdrawn = validator.withdrawable_epoch < next_epoch

        if is_validator_withdrawn:
            # Balance can never activate: credit without consuming churn
            apply_pending_deposit(state, deposit)
        elif is_validator_exited:
            # Exiting: postpone until after the withdrawable epoch
            deposits_to_postpone.append(deposit)
        else:
            # Stop at the churn limit
            is_churn_limit_reached = (processed_amount + deposit.amount
                                      > available_for_processing)
            if is_churn_limit_reached:
                break

            # Consume churn and apply deposit
            processed_amount += deposit.amount
            apply_pending_deposit(state, deposit)

        # However handled, move on in the queue
        next_deposit_index += 1

    state.pending_deposits = (list(state.pending_deposits)[next_deposit_index:]
                              + deposits_to_postpone)

    # Accumulate churn only if the limit was hit
    if is_churn_limit_reached:
        state.deposit_balance_to_consume = (available_for_processing
                                            - processed_amount)
    else:
        state.deposit_balance_to_consume = Gwei(0)


def process_pending_consolidations(state: BeaconState) -> None:
    next_epoch = Epoch(get_current_epoch(state) + 1)
    next_pending_consolidation = 0
    for pending_consolidation in state.pending_consolidations:
        source_validator = state.validators[pending_consolidation.source_index]
        if source_validator.slashed:
            next_pending_consolidation += 1
            continue
        if source_validator.withdrawable_epoch > next_epoch:
            break

        # Consolidated balance = min(balance, effective balance)
        source_effective_balance = min(
            state.balances[pending_consolidation.source_index],
            source_validator.effective_balance)

        # Move active balance to target; excess stays withdrawable
        decrease_balance(state, pending_consolidation.source_index,
                         source_effective_balance)
        increase_balance(state, pending_consolidation.target_index,
                         source_effective_balance)
        next_pending_consolidation += 1

    state.pending_consolidations = list(
        state.pending_consolidations)[next_pending_consolidation:]


def process_effective_balance_updates(state: BeaconState) -> None:
    """Hysteresis update against the per-validator max effective
    balance (EIP-7251)."""
    for index, validator in enumerate(state.validators):
        balance = state.balances[index]
        HYSTERESIS_INCREMENT = uint64(EFFECTIVE_BALANCE_INCREMENT
                                      // HYSTERESIS_QUOTIENT)
        DOWNWARD_THRESHOLD = (HYSTERESIS_INCREMENT
                              * HYSTERESIS_DOWNWARD_MULTIPLIER)
        UPWARD_THRESHOLD = HYSTERESIS_INCREMENT * HYSTERESIS_UPWARD_MULTIPLIER
        # [Modified in Electra:EIP7251]
        max_effective_balance = get_max_effective_balance(validator)

        if (balance + DOWNWARD_THRESHOLD < validator.effective_balance
                or validator.effective_balance + UPWARD_THRESHOLD < balance):
            validator.effective_balance = min(
                balance - balance % EFFECTIVE_BALANCE_INCREMENT,
                max_effective_balance)


# ---------------------------------------------------------------------------
# Execution engine (beacon-chain.md :1071-1163)
# ---------------------------------------------------------------------------


@dataclass
class NewPayloadRequest(object):
    execution_payload: ExecutionPayload
    versioned_hashes: Sequence[VersionedHash]
    parent_beacon_block_root: Root
    # [New in Electra]
    execution_requests: ExecutionRequests


def get_execution_requests_list(
        execution_requests: ExecutionRequests) -> Sequence[bytes]:
    """EIP-7685 encoding: type byte + SSZ of each non-empty list."""
    requests = [
        (DEPOSIT_REQUEST_TYPE, execution_requests.deposits),
        (WITHDRAWAL_REQUEST_TYPE, execution_requests.withdrawals),
        (CONSOLIDATION_REQUEST_TYPE, execution_requests.consolidations),
    ]

    return [
        request_type + serialize(request_data)
        for request_type, request_data in requests
        if len(request_data) != 0
    ]


class ExecutionEngine:
    """EL protocol; notify/is_valid_block_hash carry the EIP-7685
    requests list in Electra."""

    def notify_new_payload(self, execution_payload, parent_beacon_block_root,
                           execution_requests_list) -> bool:
        raise NotImplementedError

    def is_valid_block_hash(self, execution_payload,
                            parent_beacon_block_root,
                            execution_requests_list) -> bool:
        raise NotImplementedError

    def is_valid_versioned_hashes(self, new_payload_request) -> bool:
        raise NotImplementedError

    def verify_and_notify_new_payload(self, new_payload_request) -> bool:
        execution_payload = new_payload_request.execution_payload
        parent_beacon_block_root = new_payload_request.parent_beacon_block_root
        # [New in Electra]
        execution_requests_list = get_execution_requests_list(
            new_payload_request.execution_requests)

        if b"" in execution_payload.transactions:
            return False

        # [Modified in Electra]
        if not self.is_valid_block_hash(execution_payload,
                                        parent_beacon_block_root,
                                        execution_requests_list):
            return False

        if not self.is_valid_versioned_hashes(new_payload_request):
            return False

        # [Modified in Electra]
        if not self.notify_new_payload(execution_payload,
                                       parent_beacon_block_root,
                                       execution_requests_list):
            return False

        return True

    def notify_forkchoice_updated(self, head_block_hash, safe_block_hash,
                                  finalized_block_hash, payload_attributes):
        raise NotImplementedError

    def get_payload(self, payload_id):
        raise NotImplementedError


class NoopExecutionEngine(ExecutionEngine):
    """Accept-everything EL stub
    (`pysetup/spec_builders/electra.py` execution_engine_cls)."""

    def notify_new_payload(self, execution_payload, parent_beacon_block_root,
                           execution_requests_list) -> bool:
        return True

    def notify_forkchoice_updated(self, head_block_hash, safe_block_hash,
                                  finalized_block_hash, payload_attributes):
        pass

    def get_payload(self, payload_id):
        raise NotImplementedError("no default block production")

    def is_valid_block_hash(self, execution_payload,
                            parent_beacon_block_root,
                            execution_requests_list) -> bool:
        return True

    def is_valid_versioned_hashes(self, new_payload_request) -> bool:
        return True

    def verify_and_notify_new_payload(self, new_payload_request) -> bool:
        return True


EXECUTION_ENGINE = NoopExecutionEngine()


# ---------------------------------------------------------------------------
# Block processing (beacon-chain.md :1165-1860)
# ---------------------------------------------------------------------------


def process_block(state: BeaconState, block: BeaconBlock) -> None:
    process_block_header(state, block)
    process_withdrawals(state, block.body.execution_payload)  # [Modified in Electra:EIP7251]
    process_execution_payload(state, block.body, EXECUTION_ENGINE)  # [Modified in Electra:EIP6110]
    process_randao(state, block.body)
    process_eth1_data(state, block.body)
    process_operations(state, block.body)  # [Modified in Electra]
    process_sync_aggregate(state, block.body.sync_aggregate)


def get_expected_withdrawals(state: BeaconState):
    """Pending partial withdrawals first (EIP-7251), then the sweep;
    returns (withdrawals, processed_partial_withdrawals_count)."""
    epoch = get_current_epoch(state)
    withdrawal_index = state.next_withdrawal_index
    validator_index = state.next_withdrawal_validator_index
    withdrawals = []
    processed_partial_withdrawals_count = 0

    # [New in Electra:EIP7251] Consume pending partial withdrawals
    for withdrawal in state.pending_partial_withdrawals:
        if (withdrawal.withdrawable_epoch > epoch
                or len(withdrawals)
                == MAX_PENDING_PARTIALS_PER_WITHDRAWALS_SWEEP):
            break

        validator = state.validators[withdrawal.validator_index]
        has_sufficient_effective_balance = (
            validator.effective_balance >= MIN_ACTIVATION_BALANCE)
        total_withdrawn = sum(
            w.amount for w in withdrawals
            if w.validator_index == withdrawal.validator_index)
        balance = state.balances[withdrawal.validator_index] - total_withdrawn
        has_excess_balance = balance > MIN_ACTIVATION_BALANCE
        if (validator.exit_epoch == FAR_FUTURE_EPOCH
                and has_sufficient_effective_balance
                and has_excess_balance):
            withdrawable_balance = min(balance - MIN_ACTIVATION_BALANCE,
                                       withdrawal.amount)
            withdrawals.append(Withdrawal(
                index=withdrawal_index,
                validator_index=withdrawal.validator_index,
                address=ExecutionAddress(validator.withdrawal_credentials[12:]),
                amount=withdrawable_balance,
            ))
            withdrawal_index += WithdrawalIndex(1)

        processed_partial_withdrawals_count += 1

    # Sweep for remaining
    bound = min(len(state.validators), MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)
    for _ in range(bound):
        validator = state.validators[validator_index]
        # [Modified in Electra:EIP7251]
        total_withdrawn = sum(w.amount for w in withdrawals
                              if w.validator_index == validator_index)
        balance = state.balances[validator_index] - total_withdrawn
        if is_fully_withdrawable_validator(validator, balance, epoch):
            withdrawals.append(Withdrawal(
                index=withdrawal_index,
                validator_index=validator_index,
                address=ExecutionAddress(validator.withdrawal_credentials[12:]),
                amount=balance,
            ))
            withdrawal_index += WithdrawalIndex(1)
        elif is_partially_withdrawable_validator(validator, balance):
            withdrawals.append(Withdrawal(
                index=withdrawal_index,
                validator_index=validator_index,
                address=ExecutionAddress(validator.withdrawal_credentials[12:]),
                # [Modified in Electra:EIP7251]
                amount=balance - get_max_effective_balance(validator),
            ))
            withdrawal_index += WithdrawalIndex(1)
        if len(withdrawals) == MAX_WITHDRAWALS_PER_PAYLOAD:
            break
        validator_index = ValidatorIndex(
            (validator_index + 1) % len(state.validators))
    return withdrawals, processed_partial_withdrawals_count


def process_withdrawals(state: BeaconState,
                        payload: ExecutionPayload) -> None:
    # [Modified in Electra:EIP7251]
    expected_withdrawals, processed_partial_withdrawals_count = (
        get_expected_withdrawals(state))

    assert payload.withdrawals == expected_withdrawals

    for withdrawal in expected_withdrawals:
        decrease_balance(state, withdrawal.validator_index, withdrawal.amount)

    # [New in Electra:EIP7251] Update pending partial withdrawals
    state.pending_partial_withdrawals = list(
        state.pending_partial_withdrawals)[processed_partial_withdrawals_count:]

    # Update the next withdrawal index if this block contained withdrawals
    if len(expected_withdrawals) != 0:
        latest_withdrawal = expected_withdrawals[-1]
        state.next_withdrawal_index = WithdrawalIndex(
            latest_withdrawal.index + 1)

    # Update the next validator index for the next sweep
    if len(expected_withdrawals) == MAX_WITHDRAWALS_PER_PAYLOAD:
        next_validator_index = ValidatorIndex(
            (expected_withdrawals[-1].validator_index + 1)
            % len(state.validators))
        state.next_withdrawal_validator_index = next_validator_index
    else:
        next_index = (state.next_withdrawal_validator_index
                      + MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)
        next_validator_index = ValidatorIndex(
            next_index % len(state.validators))
        state.next_withdrawal_validator_index = next_validator_index


def process_execution_payload(state: BeaconState, body: BeaconBlockBody,
                              execution_engine: ExecutionEngine) -> None:
    payload = body.execution_payload

    # Verify consistency with the previous execution payload header
    assert payload.parent_hash == state.latest_execution_payload_header.block_hash
    # Verify prev_randao
    assert payload.prev_randao == get_randao_mix(state, get_current_epoch(state))
    # Verify timestamp
    assert payload.timestamp == compute_time_at_slot(state, state.slot)
    # [Modified in Electra:EIP7691] Verify commitments are under limit
    assert (len(body.blob_kzg_commitments)
            <= config.MAX_BLOBS_PER_BLOCK_ELECTRA)
    # Verify the execution payload is valid
    versioned_hashes = [kzg_commitment_to_versioned_hash(commitment)
                        for commitment in body.blob_kzg_commitments]
    assert execution_engine.verify_and_notify_new_payload(
        NewPayloadRequest(
            execution_payload=payload,
            versioned_hashes=versioned_hashes,
            parent_beacon_block_root=state.latest_block_header.parent_root,
            # [New in Electra]
            execution_requests=body.execution_requests,
        ))
    # Cache execution payload header
    state.latest_execution_payload_header = ExecutionPayloadHeader(
        parent_hash=payload.parent_hash,
        fee_recipient=payload.fee_recipient,
        state_root=payload.state_root,
        receipts_root=payload.receipts_root,
        logs_bloom=payload.logs_bloom,
        prev_randao=payload.prev_randao,
        block_number=payload.block_number,
        gas_limit=payload.gas_limit,
        gas_used=payload.gas_used,
        timestamp=payload.timestamp,
        extra_data=payload.extra_data,
        base_fee_per_gas=payload.base_fee_per_gas,
        block_hash=payload.block_hash,
        transactions_root=hash_tree_root(payload.transactions),
        withdrawals_root=hash_tree_root(payload.withdrawals),
        blob_gas_used=payload.blob_gas_used,
        excess_blob_gas=payload.excess_blob_gas,
    )


def process_operations(state: BeaconState, body: BeaconBlockBody) -> None:
    # [Modified in Electra:EIP6110]
    # Disable the former deposit mechanism once all prior deposits apply
    eth1_deposit_index_limit = min(state.eth1_data.deposit_count,
                                   state.deposit_requests_start_index)
    if state.eth1_deposit_index < eth1_deposit_index_limit:
        assert len(body.deposits) == min(
            MAX_DEPOSITS,
            eth1_deposit_index_limit - state.eth1_deposit_index)
    else:
        assert len(body.deposits) == 0

    def for_ops(operations, fn):
        for operation in operations:
            fn(state, operation)

    for_ops(body.proposer_slashings, process_proposer_slashing)
    for_ops(body.attester_slashings, process_attester_slashing)
    for_ops(body.attestations, process_attestation)  # [Modified in Electra:EIP7549]
    for_ops(body.deposits, process_deposit)
    for_ops(body.voluntary_exits, process_voluntary_exit)  # [Modified in Electra:EIP7251]
    for_ops(body.bls_to_execution_changes, process_bls_to_execution_change)
    for_ops(body.execution_requests.deposits, process_deposit_request)  # [New in Electra:EIP6110]
    for_ops(body.execution_requests.withdrawals, process_withdrawal_request)  # [New in Electra:EIP7002:EIP7251]
    for_ops(body.execution_requests.consolidations, process_consolidation_request)  # [New in Electra:EIP7251]


def process_attestation(state: BeaconState, attestation: Attestation) -> None:
    """Committee-bits attestation processing (EIP-7549)."""
    data = attestation.data
    assert data.target.epoch in (get_previous_epoch(state),
                                 get_current_epoch(state))
    assert data.target.epoch == compute_epoch_at_slot(data.slot)
    assert data.slot + MIN_ATTESTATION_INCLUSION_DELAY <= state.slot

    # [Modified in Electra:EIP7549]
    assert data.index == 0
    committee_indices = get_committee_indices(attestation.committee_bits)
    committee_offset = 0
    for committee_index in committee_indices:
        assert committee_index < get_committee_count_per_slot(
            state, data.target.epoch)
        committee = get_beacon_committee(state, data.slot, committee_index)
        committee_attesters = set(
            attester_index for i, attester_index in enumerate(committee)
            if attestation.aggregation_bits[committee_offset + i])
        assert len(committee_attesters) > 0
        committee_offset += len(committee)

    # Bitfield length matches total number of participants
    assert len(attestation.aggregation_bits) == committee_offset

    # Participation flag indices
    participation_flag_indices = get_attestation_participation_flag_indices(
        state, data, state.slot - data.slot)

    # Verify signature
    assert is_valid_indexed_attestation(
        state, get_indexed_attestation(state, attestation))

    # Update epoch participation flags
    if data.target.epoch == get_current_epoch(state):
        epoch_participation = state.current_epoch_participation
    else:
        epoch_participation = state.previous_epoch_participation

    proposer_reward_numerator = 0
    for index in get_attesting_indices(state, attestation):
        for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
            if (flag_index in participation_flag_indices
                    and not has_flag(epoch_participation[index], flag_index)):
                epoch_participation[index] = add_flag(
                    epoch_participation[index], flag_index)
                proposer_reward_numerator += get_base_reward(state, index) * weight

    # Reward proposer
    proposer_reward_denominator = ((WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
                                   * WEIGHT_DENOMINATOR // PROPOSER_WEIGHT)
    proposer_reward = Gwei(proposer_reward_numerator
                           // proposer_reward_denominator)
    increase_balance(state, get_beacon_proposer_index(state), proposer_reward)


def get_validator_from_deposit(pubkey: BLSPubkey,
                               withdrawal_credentials: Bytes32,
                               amount: uint64) -> Validator:
    """Effective balance capped per credential type (EIP-7251)."""
    validator = Validator(
        pubkey=pubkey,
        withdrawal_credentials=withdrawal_credentials,
        effective_balance=Gwei(0),
        slashed=False,
        activation_eligibility_epoch=FAR_FUTURE_EPOCH,
        activation_epoch=FAR_FUTURE_EPOCH,
        exit_epoch=FAR_FUTURE_EPOCH,
        withdrawable_epoch=FAR_FUTURE_EPOCH,
    )

    # [Modified in Electra:EIP7251]
    max_effective_balance = get_max_effective_balance(validator)
    validator.effective_balance = min(
        amount - amount % EFFECTIVE_BALANCE_INCREMENT, max_effective_balance)

    return validator


def apply_deposit(state: BeaconState, pubkey: BLSPubkey,
                  withdrawal_credentials: Bytes32, amount: uint64,
                  signature: BLSSignature) -> None:
    """Register the validator with zero balance and queue the amount as
    a pending deposit (EIP-7251)."""
    validator_pubkeys = [v.pubkey for v in state.validators]
    if pubkey not in validator_pubkeys:
        # Verify the proof of possession (not checked by the contract)
        if is_valid_deposit_signature(pubkey, withdrawal_credentials,
                                      amount, signature):
            # [Modified in Electra:EIP7251]
            add_validator_to_registry(state, pubkey, withdrawal_credentials,
                                      Gwei(0))
        else:
            return

    # [Modified in Electra:EIP7251] queue the balance
    state.pending_deposits.append(PendingDeposit(
        pubkey=pubkey,
        withdrawal_credentials=withdrawal_credentials,
        amount=amount,
        signature=signature,
        # GENESIS_SLOT distinguishes from a pending deposit request
        slot=GENESIS_SLOT,
    ))


def process_voluntary_exit(state: BeaconState,
                           signed_voluntary_exit: SignedVoluntaryExit) -> None:
    """Additionally requires an empty pending-withdrawal queue for the
    validator (EIP-7251)."""
    voluntary_exit = signed_voluntary_exit.message
    validator = state.validators[voluntary_exit.validator_index]
    # Verify the validator is active
    assert is_active_validator(validator, get_current_epoch(state))
    # Verify exit has not been initiated
    assert validator.exit_epoch == FAR_FUTURE_EPOCH
    # Exits are not valid before their epoch
    assert get_current_epoch(state) >= voluntary_exit.epoch
    # Verify the validator has been active long enough
    assert (get_current_epoch(state)
            >= validator.activation_epoch + config.SHARD_COMMITTEE_PERIOD)
    # [New in Electra:EIP7251] no pending withdrawals in the queue
    assert get_pending_balance_to_withdraw(
        state, voluntary_exit.validator_index) == 0
    # Verify signature
    domain = compute_domain(DOMAIN_VOLUNTARY_EXIT,
                            config.CAPELLA_FORK_VERSION,
                            state.genesis_validators_root)
    signing_root = compute_signing_root(voluntary_exit, domain)
    assert bls.Verify(validator.pubkey, signing_root,
                      signed_voluntary_exit.signature)
    # Initiate exit
    initiate_validator_exit(state, voluntary_exit.validator_index)


def process_withdrawal_request(
        state: BeaconState, withdrawal_request: WithdrawalRequest) -> None:
    """EL-triggered exit / partial withdrawal (EIP-7002/EIP-7251);
    invalid requests are ignored, not asserted."""
    amount = withdrawal_request.amount
    is_full_exit_request = amount == FULL_EXIT_REQUEST_AMOUNT

    # If the partial queue is full, only full exits are processed
    if (len(state.pending_partial_withdrawals)
            == PENDING_PARTIAL_WITHDRAWALS_LIMIT
            and not is_full_exit_request):
        return

    validator_pubkeys = [v.pubkey for v in state.validators]
    # Verify pubkey exists
    request_pubkey = withdrawal_request.validator_pubkey
    if request_pubkey not in validator_pubkeys:
        return
    index = ValidatorIndex(validator_pubkeys.index(request_pubkey))
    validator = state.validators[index]

    # Verify withdrawal credentials
    has_correct_credential = has_execution_withdrawal_credential(validator)
    is_correct_source_address = (
        validator.withdrawal_credentials[12:]
        == withdrawal_request.source_address)
    if not (has_correct_credential and is_correct_source_address):
        return
    # Verify the validator is active
    if not is_active_validator(validator, get_current_epoch(state)):
        return
    # Verify exit has not been initiated
    if validator.exit_epoch != FAR_FUTURE_EPOCH:
        return
    # Verify the validator has been active long enough
    if (get_current_epoch(state)
            < validator.activation_epoch + config.SHARD_COMMITTEE_PERIOD):
        return

    pending_balance_to_withdraw = get_pending_balance_to_withdraw(state, index)

    if is_full_exit_request:
        # Only exit if the queue holds nothing for this validator
        if pending_balance_to_withdraw == 0:
            initiate_validator_exit(state, index)
        return

    has_sufficient_effective_balance = (
        validator.effective_balance >= MIN_ACTIVATION_BALANCE)
    has_excess_balance = (
        state.balances[index]
        > MIN_ACTIVATION_BALANCE + pending_balance_to_withdraw)

    # Partial withdrawals need compounding credentials
    if (has_compounding_withdrawal_credential(validator)
            and has_sufficient_effective_balance
            and has_excess_balance):
        to_withdraw = min(
            state.balances[index] - MIN_ACTIVATION_BALANCE
            - pending_balance_to_withdraw,
            amount)
        exit_queue_epoch = compute_exit_epoch_and_update_churn(state,
                                                               to_withdraw)
        withdrawable_epoch = Epoch(
            exit_queue_epoch + config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY)
        state.pending_partial_withdrawals.append(PendingPartialWithdrawal(
            validator_index=index,
            amount=to_withdraw,
            withdrawable_epoch=withdrawable_epoch,
        ))


def process_deposit_request(state: BeaconState,
                            deposit_request: DepositRequest) -> None:
    """EL-triggered deposit (EIP-6110)."""
    # Set deposit request start index
    if state.deposit_requests_start_index == UNSET_DEPOSIT_REQUESTS_START_INDEX:
        state.deposit_requests_start_index = deposit_request.index

    # Create pending deposit
    state.pending_deposits.append(PendingDeposit(
        pubkey=deposit_request.pubkey,
        withdrawal_credentials=deposit_request.withdrawal_credentials,
        amount=deposit_request.amount,
        signature=deposit_request.signature,
        slot=state.slot,
    ))


def is_valid_switch_to_compounding_request(
        state: BeaconState,
        consolidation_request: ConsolidationRequest) -> bool:
    # Switch to compounding requires source == target
    if (consolidation_request.source_pubkey
            != consolidation_request.target_pubkey):
        return False

    # Verify pubkey exists
    source_pubkey = consolidation_request.source_pubkey
    validator_pubkeys = [v.pubkey for v in state.validators]
    if source_pubkey not in validator_pubkeys:
        return False

    source_validator = state.validators[
        ValidatorIndex(validator_pubkeys.index(source_pubkey))]

    # Verify request has been authorized
    if (source_validator.withdrawal_credentials[12:]
            != consolidation_request.source_address):
        return False

    # Verify source withdrawal credentials
    if not has_eth1_withdrawal_credential(source_validator):
        return False

    # Verify the source is active
    current_epoch = get_current_epoch(state)
    if not is_active_validator(source_validator, current_epoch):
        return False

    # Verify exit for source has not been initiated
    if source_validator.exit_epoch != FAR_FUTURE_EPOCH:
        return False

    return True


def process_consolidation_request(
        state: BeaconState,
        consolidation_request: ConsolidationRequest) -> None:
    """EL-triggered consolidation / switch-to-compounding (EIP-7251)."""
    if is_valid_switch_to_compounding_request(state, consolidation_request):
        validator_pubkeys = [v.pubkey for v in state.validators]
        request_source_pubkey = consolidation_request.source_pubkey
        source_index = ValidatorIndex(
            validator_pubkeys.index(request_source_pubkey))
        switch_to_compounding_validator(state, source_index)
        return

    # source != target, so a consolidation cannot be used as an exit
    if (consolidation_request.source_pubkey
            == consolidation_request.target_pubkey):
        return
    # A full pending queue ignores consolidation requests
    if len(state.pending_consolidations) == PENDING_CONSOLIDATIONS_LIMIT:
        return
    # Too little consolidation churn also ignores them
    if get_consolidation_churn_limit(state) <= MIN_ACTIVATION_BALANCE:
        return

    validator_pubkeys = [v.pubkey for v in state.validators]
    # Verify pubkeys exist
    request_source_pubkey = consolidation_request.source_pubkey
    request_target_pubkey = consolidation_request.target_pubkey
    if request_source_pubkey not in validator_pubkeys:
        return
    if request_target_pubkey not in validator_pubkeys:
        return
    source_index = ValidatorIndex(
        validator_pubkeys.index(request_source_pubkey))
    target_index = ValidatorIndex(
        validator_pubkeys.index(request_target_pubkey))
    source_validator = state.validators[source_index]
    target_validator = state.validators[target_index]

    # Verify source withdrawal credentials
    has_correct_credential = has_execution_withdrawal_credential(
        source_validator)
    is_correct_source_address = (
        source_validator.withdrawal_credentials[12:]
        == consolidation_request.source_address)
    if not (has_correct_credential and is_correct_source_address):
        return

    # Target must have compounding credentials
    if not has_compounding_withdrawal_credential(target_validator):
        return

    # Both must be active with no exit initiated
    current_epoch = get_current_epoch(state)
    if not is_active_validator(source_validator, current_epoch):
        return
    if not is_active_validator(target_validator, current_epoch):
        return
    if source_validator.exit_epoch != FAR_FUTURE_EPOCH:
        return
    if target_validator.exit_epoch != FAR_FUTURE_EPOCH:
        return
    # Source must have been active long enough
    if (current_epoch
            < source_validator.activation_epoch
            + config.SHARD_COMMITTEE_PERIOD):
        return
    # Source must have no pending withdrawals in the queue
    if get_pending_balance_to_withdraw(state, source_index) > 0:
        return

    # Initiate source exit and append the pending consolidation
    source_validator.exit_epoch = compute_consolidation_epoch_and_update_churn(
        state, source_validator.effective_balance)
    source_validator.withdrawable_epoch = Epoch(
        source_validator.exit_epoch
        + config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY)
    state.pending_consolidations.append(PendingConsolidation(
        source_index=source_index, target_index=target_index))
