# Electra -- Light Client (gindex deepening).
#
# Parity contract: specs/electra/light-client/sync-protocol.md.
# Electra grows BeaconState past 32 fields, deepening its merkle tree from
# 5 to 6 levels; every light-client gindex and branch length changes.  The
# altair constants stay available (suffixed) for verifying pre-electra
# branches, and the `*_gindex_at_slot` selectors become fork-aware.

FINALIZED_ROOT_GINDEX_ALTAIR = FINALIZED_ROOT_GINDEX
CURRENT_SYNC_COMMITTEE_GINDEX_ALTAIR = CURRENT_SYNC_COMMITTEE_GINDEX
NEXT_SYNC_COMMITTEE_GINDEX_ALTAIR = NEXT_SYNC_COMMITTEE_GINDEX

FINALIZED_ROOT_GINDEX_ELECTRA = get_generalized_index(
    BeaconState, "finalized_checkpoint", "root")
CURRENT_SYNC_COMMITTEE_GINDEX_ELECTRA = get_generalized_index(
    BeaconState, "current_sync_committee")
NEXT_SYNC_COMMITTEE_GINDEX_ELECTRA = get_generalized_index(
    BeaconState, "next_sync_committee")

assert FINALIZED_ROOT_GINDEX_ELECTRA == 169, FINALIZED_ROOT_GINDEX_ELECTRA
assert CURRENT_SYNC_COMMITTEE_GINDEX_ELECTRA == 86, \
    CURRENT_SYNC_COMMITTEE_GINDEX_ELECTRA
assert NEXT_SYNC_COMMITTEE_GINDEX_ELECTRA == 87, \
    NEXT_SYNC_COMMITTEE_GINDEX_ELECTRA

# Unsuffixed names now refer to the deepest (current-fork) tree; the shared
# create_* functions normalize their branches against these.
FINALIZED_ROOT_GINDEX = FINALIZED_ROOT_GINDEX_ELECTRA
CURRENT_SYNC_COMMITTEE_GINDEX = CURRENT_SYNC_COMMITTEE_GINDEX_ELECTRA
NEXT_SYNC_COMMITTEE_GINDEX = NEXT_SYNC_COMMITTEE_GINDEX_ELECTRA

FinalityBranch = Vector[Bytes32, floorlog2(FINALIZED_ROOT_GINDEX_ELECTRA)]
CurrentSyncCommitteeBranch = Vector[
    Bytes32, floorlog2(CURRENT_SYNC_COMMITTEE_GINDEX_ELECTRA)]
NextSyncCommitteeBranch = Vector[
    Bytes32, floorlog2(NEXT_SYNC_COMMITTEE_GINDEX_ELECTRA)]


class LightClientBootstrap(Container):
    header: LightClientHeader
    current_sync_committee: SyncCommittee
    current_sync_committee_branch: CurrentSyncCommitteeBranch


class LightClientUpdate(Container):
    attested_header: LightClientHeader
    next_sync_committee: SyncCommittee
    next_sync_committee_branch: NextSyncCommitteeBranch
    finalized_header: LightClientHeader
    finality_branch: FinalityBranch
    sync_aggregate: SyncAggregate
    signature_slot: Slot


class LightClientFinalityUpdate(Container):
    attested_header: LightClientHeader
    finalized_header: LightClientHeader
    finality_branch: FinalityBranch
    sync_aggregate: SyncAggregate
    signature_slot: Slot


def finalized_root_gindex_at_slot(slot: Slot):
    epoch = compute_epoch_at_slot(slot)
    if epoch >= config.ELECTRA_FORK_EPOCH:
        return FINALIZED_ROOT_GINDEX_ELECTRA
    return FINALIZED_ROOT_GINDEX_ALTAIR


def current_sync_committee_gindex_at_slot(slot: Slot):
    epoch = compute_epoch_at_slot(slot)
    if epoch >= config.ELECTRA_FORK_EPOCH:
        return CURRENT_SYNC_COMMITTEE_GINDEX_ELECTRA
    return CURRENT_SYNC_COMMITTEE_GINDEX_ALTAIR


def next_sync_committee_gindex_at_slot(slot: Slot):
    epoch = compute_epoch_at_slot(slot)
    if epoch >= config.ELECTRA_FORK_EPOCH:
        return NEXT_SYNC_COMMITTEE_GINDEX_ELECTRA
    return NEXT_SYNC_COMMITTEE_GINDEX_ALTAIR
