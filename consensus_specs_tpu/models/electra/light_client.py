# Electra -- Light Client (gindex deepening).
#
# Parity contract: specs/electra/light-client/sync-protocol.md.
# Electra grows BeaconState past 32 fields, deepening its merkle tree from
# 5 to 6 levels; every light-client gindex and branch length changes.  The
# altair constants stay available (suffixed) for verifying pre-electra
# branches, and the `*_gindex_at_slot` selectors become fork-aware.

FINALIZED_ROOT_GINDEX_ALTAIR = FINALIZED_ROOT_GINDEX
CURRENT_SYNC_COMMITTEE_GINDEX_ALTAIR = CURRENT_SYNC_COMMITTEE_GINDEX
NEXT_SYNC_COMMITTEE_GINDEX_ALTAIR = NEXT_SYNC_COMMITTEE_GINDEX

FINALIZED_ROOT_GINDEX_ELECTRA = get_generalized_index(
    BeaconState, "finalized_checkpoint", "root")
CURRENT_SYNC_COMMITTEE_GINDEX_ELECTRA = get_generalized_index(
    BeaconState, "current_sync_committee")
NEXT_SYNC_COMMITTEE_GINDEX_ELECTRA = get_generalized_index(
    BeaconState, "next_sync_committee")

assert FINALIZED_ROOT_GINDEX_ELECTRA == 169, FINALIZED_ROOT_GINDEX_ELECTRA
assert CURRENT_SYNC_COMMITTEE_GINDEX_ELECTRA == 86, \
    CURRENT_SYNC_COMMITTEE_GINDEX_ELECTRA
assert NEXT_SYNC_COMMITTEE_GINDEX_ELECTRA == 87, \
    NEXT_SYNC_COMMITTEE_GINDEX_ELECTRA

# Unsuffixed names now refer to the deepest (current-fork) tree; the shared
# create_* functions normalize their branches against these.
FINALIZED_ROOT_GINDEX = FINALIZED_ROOT_GINDEX_ELECTRA
CURRENT_SYNC_COMMITTEE_GINDEX = CURRENT_SYNC_COMMITTEE_GINDEX_ELECTRA
NEXT_SYNC_COMMITTEE_GINDEX = NEXT_SYNC_COMMITTEE_GINDEX_ELECTRA

FinalityBranch = Vector[Bytes32, floorlog2(FINALIZED_ROOT_GINDEX_ELECTRA)]
CurrentSyncCommitteeBranch = Vector[
    Bytes32, floorlog2(CURRENT_SYNC_COMMITTEE_GINDEX_ELECTRA)]
NextSyncCommitteeBranch = Vector[
    Bytes32, floorlog2(NEXT_SYNC_COMMITTEE_GINDEX_ELECTRA)]


class LightClientBootstrap(Container):
    header: LightClientHeader
    current_sync_committee: SyncCommittee
    current_sync_committee_branch: CurrentSyncCommitteeBranch


class LightClientUpdate(Container):
    attested_header: LightClientHeader
    next_sync_committee: SyncCommittee
    next_sync_committee_branch: NextSyncCommitteeBranch
    finalized_header: LightClientHeader
    finality_branch: FinalityBranch
    sync_aggregate: SyncAggregate
    signature_slot: Slot


class LightClientFinalityUpdate(Container):
    attested_header: LightClientHeader
    finalized_header: LightClientHeader
    finality_branch: FinalityBranch
    sync_aggregate: SyncAggregate
    signature_slot: Slot


def finalized_root_gindex_at_slot(slot: Slot):
    epoch = compute_epoch_at_slot(slot)
    if epoch >= config.ELECTRA_FORK_EPOCH:
        return FINALIZED_ROOT_GINDEX_ELECTRA
    return FINALIZED_ROOT_GINDEX_ALTAIR


def current_sync_committee_gindex_at_slot(slot: Slot):
    epoch = compute_epoch_at_slot(slot)
    if epoch >= config.ELECTRA_FORK_EPOCH:
        return CURRENT_SYNC_COMMITTEE_GINDEX_ELECTRA
    return CURRENT_SYNC_COMMITTEE_GINDEX_ALTAIR


def next_sync_committee_gindex_at_slot(slot: Slot):
    epoch = compute_epoch_at_slot(slot)
    if epoch >= config.ELECTRA_FORK_EPOCH:
        return NEXT_SYNC_COMMITTEE_GINDEX_ELECTRA
    return NEXT_SYNC_COMMITTEE_GINDEX_ALTAIR


# -- electra light-client fork.md upgrade functions --------------------------
# Branches deepen with the 6-level electra state tree; pre-electra branches
# are zero-padded at the front via normalize_merkle_branch.


def upgrade_lc_header_to_electra(pre) -> LightClientHeader:
    return LightClientHeader(
        beacon=pre.beacon,
        execution=pre.execution,
        execution_branch=pre.execution_branch,
    )


def upgrade_lc_bootstrap_to_electra(pre) -> LightClientBootstrap:
    return LightClientBootstrap(
        header=upgrade_lc_header_to_electra(pre.header),
        current_sync_committee=pre.current_sync_committee,
        current_sync_committee_branch=normalize_merkle_branch(
            pre.current_sync_committee_branch,
            CURRENT_SYNC_COMMITTEE_GINDEX_ELECTRA),
    )


def upgrade_lc_update_to_electra(pre) -> LightClientUpdate:
    return LightClientUpdate(
        attested_header=upgrade_lc_header_to_electra(pre.attested_header),
        next_sync_committee=pre.next_sync_committee,
        next_sync_committee_branch=normalize_merkle_branch(
            pre.next_sync_committee_branch,
            NEXT_SYNC_COMMITTEE_GINDEX_ELECTRA),
        finalized_header=upgrade_lc_header_to_electra(pre.finalized_header),
        finality_branch=normalize_merkle_branch(
            pre.finality_branch, FINALIZED_ROOT_GINDEX_ELECTRA),
        sync_aggregate=pre.sync_aggregate,
        signature_slot=pre.signature_slot,
    )


def upgrade_lc_finality_update_to_electra(pre) -> LightClientFinalityUpdate:
    return LightClientFinalityUpdate(
        attested_header=upgrade_lc_header_to_electra(pre.attested_header),
        finalized_header=upgrade_lc_header_to_electra(pre.finalized_header),
        finality_branch=normalize_merkle_branch(
            pre.finality_branch, FINALIZED_ROOT_GINDEX_ELECTRA),
        sync_aggregate=pre.sync_aggregate,
        signature_slot=pre.signature_slot,
    )


def upgrade_lc_optimistic_update_to_electra(pre) -> LightClientOptimisticUpdate:
    return LightClientOptimisticUpdate(
        attested_header=upgrade_lc_header_to_electra(pre.attested_header),
        sync_aggregate=pre.sync_aggregate,
        signature_slot=pre.signature_slot,
    )


def upgrade_lc_store_to_electra(pre) -> LightClientStore:
    if pre.best_valid_update is None:
        best_valid_update = None
    else:
        best_valid_update = upgrade_lc_update_to_electra(
            pre.best_valid_update)
    return LightClientStore(
        finalized_header=upgrade_lc_header_to_electra(pre.finalized_header),
        current_sync_committee=pre.current_sync_committee,
        next_sync_committee=pre.next_sync_committee,
        best_valid_update=best_valid_update,
        optimistic_header=upgrade_lc_header_to_electra(
            pre.optimistic_header),
        previous_max_active_participants=(
            pre.previous_max_active_participants),
        current_max_active_participants=pre.current_max_active_participants,
    )
