# Electra -- p2p deltas: blob-sidecar limits move to the _ELECTRA config
# values and the attestation gossip conditions adapt to EIP-7549
# committee-bits attestations (specs/electra/p2p-interface.md :34-120).


class BlobSidecarsByRangeRequest(Container):
    start_slot: Slot
    count: uint64


def get_max_blobs_per_block(epoch: Epoch) -> uint64:
    """Electra raises the blob count (electra/p2p-interface.md config)."""
    return uint64(config.MAX_BLOBS_PER_BLOCK_ELECTRA)


def get_blob_sidecar_subnet_count(epoch: Epoch) -> uint64:
    return uint64(config.BLOB_SIDECAR_SUBNET_COUNT_ELECTRA)


def compute_subnet_for_blob_sidecar_electra(blob_index: BlobIndex) -> SubnetID:
    return SubnetID(blob_index % config.BLOB_SIDECAR_SUBNET_COUNT_ELECTRA)


def is_valid_attestation_gossip_aggregation_bits(
        state: BeaconState, attestation: Attestation) -> bool:
    """beacon_attestation_{subnet_id} condition: exactly one committee bit
    set and aggregation bits matching that committee's length
    (electra/p2p-interface.md beacon_attestation conditions)."""
    committee_indices = get_committee_indices(attestation.committee_bits)
    if len(committee_indices) != 1:
        return False
    committee = get_beacon_committee(
        state, attestation.data.slot, committee_indices[0])
    return len(attestation.aggregation_bits) == len(committee)
