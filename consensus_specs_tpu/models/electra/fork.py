# Electra -- Fork Logic (executable spec source).
# Parity contract: specs/electra/fork.md.


def compute_fork_version(epoch: Epoch) -> Version:
    """Fork version at `epoch`."""
    if epoch >= config.ELECTRA_FORK_EPOCH:
        return config.ELECTRA_FORK_VERSION
    if epoch >= config.DENEB_FORK_EPOCH:
        return config.DENEB_FORK_VERSION
    if epoch >= config.CAPELLA_FORK_EPOCH:
        return config.CAPELLA_FORK_VERSION
    if epoch >= config.BELLATRIX_FORK_EPOCH:
        return config.BELLATRIX_FORK_VERSION
    if epoch >= config.ALTAIR_FORK_EPOCH:
        return config.ALTAIR_FORK_VERSION
    return config.GENESIS_FORK_VERSION


def upgrade_to_electra(pre) -> BeaconState:
    """deneb -> electra state upgrade: initialize churn accounting and
    re-queue not-yet-active balances as pending deposits
    (fork.md `upgrade_to_electra`)."""
    epoch = compute_epoch_at_slot(pre.slot)

    earliest_exit_epoch = compute_activation_exit_epoch(epoch)
    for validator in pre.validators:
        if validator.exit_epoch != FAR_FUTURE_EPOCH:
            if validator.exit_epoch > earliest_exit_epoch:
                earliest_exit_epoch = validator.exit_epoch
    earliest_exit_epoch += Epoch(1)

    post = BeaconState(
        genesis_time=pre.genesis_time,
        genesis_validators_root=pre.genesis_validators_root,
        slot=pre.slot,
        fork=Fork(
            previous_version=pre.fork.current_version,
            # [Modified in Electra]
            current_version=config.ELECTRA_FORK_VERSION,
            epoch=epoch,
        ),
        latest_block_header=pre.latest_block_header,
        block_roots=pre.block_roots,
        state_roots=pre.state_roots,
        historical_roots=pre.historical_roots,
        eth1_data=pre.eth1_data,
        eth1_data_votes=pre.eth1_data_votes,
        eth1_deposit_index=pre.eth1_deposit_index,
        validators=pre.validators,
        balances=pre.balances,
        randao_mixes=pre.randao_mixes,
        slashings=pre.slashings,
        previous_epoch_participation=pre.previous_epoch_participation,
        current_epoch_participation=pre.current_epoch_participation,
        justification_bits=pre.justification_bits,
        previous_justified_checkpoint=pre.previous_justified_checkpoint,
        current_justified_checkpoint=pre.current_justified_checkpoint,
        finalized_checkpoint=pre.finalized_checkpoint,
        inactivity_scores=pre.inactivity_scores,
        current_sync_committee=pre.current_sync_committee,
        next_sync_committee=pre.next_sync_committee,
        latest_execution_payload_header=pre.latest_execution_payload_header,
        next_withdrawal_index=pre.next_withdrawal_index,
        next_withdrawal_validator_index=pre.next_withdrawal_validator_index,
        historical_summaries=pre.historical_summaries,
        # [New in Electra:EIP6110]
        deposit_requests_start_index=UNSET_DEPOSIT_REQUESTS_START_INDEX,
        # [New in Electra:EIP7251]
        deposit_balance_to_consume=0,
        exit_balance_to_consume=0,
        earliest_exit_epoch=earliest_exit_epoch,
        consolidation_balance_to_consume=0,
        earliest_consolidation_epoch=compute_activation_exit_epoch(epoch),
        pending_deposits=[],
        pending_partial_withdrawals=[],
        pending_consolidations=[],
    )

    post.exit_balance_to_consume = get_activation_exit_churn_limit(post)
    post.consolidation_balance_to_consume = get_consolidation_churn_limit(post)

    # [New in Electra:EIP7251] re-queue not-yet-active balances
    pre_activation = sorted(
        [index for index, validator in enumerate(post.validators)
         if validator.activation_epoch == FAR_FUTURE_EPOCH],
        key=lambda index: (
            post.validators[index].activation_eligibility_epoch, index),
    )

    for index in pre_activation:
        balance = post.balances[index]
        post.balances[index] = 0
        validator = post.validators[index]
        validator.effective_balance = 0
        validator.activation_eligibility_epoch = FAR_FUTURE_EPOCH
        # G2 infinity signature + GENESIS_SLOT mark a non-request deposit
        post.pending_deposits.append(PendingDeposit(
            pubkey=validator.pubkey,
            withdrawal_credentials=validator.withdrawal_credentials,
            amount=balance,
            signature=G2_POINT_AT_INFINITY,
            slot=GENESIS_SLOT,
        ))

    return post
