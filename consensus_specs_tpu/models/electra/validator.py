# Electra -- Honest Validator (executable spec source, delta).
# Parity contract: specs/electra/validator.md (:50-330).


@dataclass
class GetPayloadResponse(object):
    execution_payload: ExecutionPayload
    block_value: uint256
    blobs_bundle: Any
    execution_requests: Sequence[bytes]  # [New in Electra]


class AggregateAndProof(Container):
    aggregator_index: ValidatorIndex
    # [Modified in Electra:EIP7549]
    aggregate: Attestation
    selection_proof: BLSSignature


class SignedAggregateAndProof(Container):
    message: AggregateAndProof
    signature: BLSSignature


def compute_on_chain_aggregate(network_aggregates) -> Attestation:
    """Consolidate per-committee aggregates with equal AttestationData
    into one on-chain Attestation (EIP-7549)."""
    aggregates = sorted(
        network_aggregates,
        key=lambda a: get_committee_indices(a.committee_bits)[0])

    data = aggregates[0].data
    aggregation_bits = Bitlist[MAX_VALIDATORS_PER_COMMITTEE
                               * MAX_COMMITTEES_PER_SLOT]()
    for a in aggregates:
        for b in a.aggregation_bits:
            aggregation_bits.append(b)

    signature = bls.Aggregate([a.signature for a in aggregates])

    committee_indices = [get_committee_indices(a.committee_bits)[0]
                         for a in aggregates]
    committee_flags = [(index in committee_indices)
                       for index in range(0, MAX_COMMITTEES_PER_SLOT)]
    committee_bits = Bitvector[MAX_COMMITTEES_PER_SLOT](committee_flags)

    return Attestation(
        aggregation_bits=aggregation_bits,
        data=data,
        committee_bits=committee_bits,
        signature=signature,
    )


def get_eth1_pending_deposit_count(state: BeaconState) -> uint64:
    eth1_deposit_index_limit = min(state.eth1_data.deposit_count,
                                   state.deposit_requests_start_index)
    if state.eth1_deposit_index < eth1_deposit_index_limit:
        return min(MAX_DEPOSITS,
                   eth1_deposit_index_limit - state.eth1_deposit_index)
    else:
        return uint64(0)


def get_eth1_vote(state: BeaconState, eth1_chain):
    # [New in Electra:EIP6110] no more polling once requests take over
    if state.eth1_deposit_index == state.deposit_requests_start_index:
        return state.eth1_data

    period_start = voting_period_start_time(state)
    votes_to_consider = [
        get_eth1_data(block) for block in eth1_chain
        if (is_candidate_block(block, period_start)
            and get_eth1_data(block).deposit_count
            >= state.eth1_data.deposit_count)
    ]

    valid_votes = [vote for vote in state.eth1_data_votes
                   if vote in votes_to_consider]

    if any(votes_to_consider):
        default_vote = votes_to_consider[len(votes_to_consider) - 1]
    else:
        default_vote = state.eth1_data

    return max(
        valid_votes,
        key=lambda v: (valid_votes.count(v), -valid_votes.index(v)),
        default=default_vote,
    )


def prepare_execution_payload(state: BeaconState, safe_block_hash: Hash32,
                              finalized_block_hash: Hash32,
                              suggested_fee_recipient: ExecutionAddress,
                              execution_engine: ExecutionEngine):
    """Only change: the tuple-returning get_expected_withdrawals."""
    parent_hash = state.latest_execution_payload_header.block_hash

    withdrawals, _ = get_expected_withdrawals(state)  # [Modified in EIP-7251]

    payload_attributes = PayloadAttributes(
        timestamp=compute_time_at_slot(state, state.slot),
        prev_randao=get_randao_mix(state, get_current_epoch(state)),
        suggested_fee_recipient=suggested_fee_recipient,
        withdrawals=withdrawals,
        parent_beacon_block_root=hash_tree_root(state.latest_block_header),
    )
    return execution_engine.notify_forkchoice_updated(
        head_block_hash=parent_hash,
        safe_block_hash=safe_block_hash,
        finalized_block_hash=finalized_block_hash,
        payload_attributes=payload_attributes,
    )


def get_execution_requests(execution_requests_list) -> ExecutionRequests:
    """Decode the EIP-7685 requests list (strictly ascending types, no
    empties, at most one of each)."""
    deposits = []
    withdrawals = []
    consolidations = []

    request_types = [
        DEPOSIT_REQUEST_TYPE,
        WITHDRAWAL_REQUEST_TYPE,
        CONSOLIDATION_REQUEST_TYPE,
    ]

    prev_request_type = None
    for request in execution_requests_list:
        request_type, request_data = request[0:1], request[1:]

        # The request type must be known
        assert request_type in request_types
        # The request data must not be empty
        assert len(request_data) != 0
        # Strictly ascending order, no duplicates
        assert prev_request_type is None or prev_request_type < request_type
        prev_request_type = request_type

        if request_type == DEPOSIT_REQUEST_TYPE:
            deposits = ssz_deserialize(
                List[DepositRequest, MAX_DEPOSIT_REQUESTS_PER_PAYLOAD],
                request_data)
        elif request_type == WITHDRAWAL_REQUEST_TYPE:
            withdrawals = ssz_deserialize(
                List[WithdrawalRequest, MAX_WITHDRAWAL_REQUESTS_PER_PAYLOAD],
                request_data)
        elif request_type == CONSOLIDATION_REQUEST_TYPE:
            consolidations = ssz_deserialize(
                List[ConsolidationRequest,
                     MAX_CONSOLIDATION_REQUESTS_PER_PAYLOAD],
                request_data)

    return ExecutionRequests(
        deposits=deposits,
        withdrawals=withdrawals,
        consolidations=consolidations,
    )


def compute_subnet_for_blob_sidecar(blob_index: BlobIndex) -> SubnetID:
    # [Modified in Electra:EIP7691]
    return SubnetID(blob_index % config.BLOB_SIDECAR_SUBNET_COUNT_ELECTRA)


def compute_weak_subjectivity_period(state: BeaconState) -> uint64:
    """[Modified in Electra:EIP7251] churn is balance-denominated
    (specs/electra/weak-subjectivity.md :32-45): the period accounts for
    validator-set churn bounded by get_balance_churn_limit per epoch."""
    t = get_total_active_balance(state)
    delta = get_balance_churn_limit(state)
    epochs_for_validator_set_churn = SAFETY_DECAY * t // (2 * delta * 100)
    return (config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
            + epochs_for_validator_set_churn)
