# Capella -- Fork Logic (executable spec source).
# Parity contract: specs/capella/fork.md.


def compute_fork_version(epoch: Epoch) -> Version:
    """Fork version at `epoch`."""
    if epoch >= config.CAPELLA_FORK_EPOCH:
        return config.CAPELLA_FORK_VERSION
    if epoch >= config.BELLATRIX_FORK_EPOCH:
        return config.BELLATRIX_FORK_VERSION
    if epoch >= config.ALTAIR_FORK_EPOCH:
        return config.ALTAIR_FORK_VERSION
    return config.GENESIS_FORK_VERSION


def upgrade_to_capella(pre) -> BeaconState:
    """bellatrix -> capella state upgrade (fork.md `upgrade_to_capella`)."""
    epoch = compute_epoch_at_slot(pre.slot)
    pre_header = pre.latest_execution_payload_header
    latest_execution_payload_header = ExecutionPayloadHeader(
        parent_hash=pre_header.parent_hash,
        fee_recipient=pre_header.fee_recipient,
        state_root=pre_header.state_root,
        receipts_root=pre_header.receipts_root,
        logs_bloom=pre_header.logs_bloom,
        prev_randao=pre_header.prev_randao,
        block_number=pre_header.block_number,
        gas_limit=pre_header.gas_limit,
        gas_used=pre_header.gas_used,
        timestamp=pre_header.timestamp,
        extra_data=pre_header.extra_data,
        base_fee_per_gas=pre_header.base_fee_per_gas,
        block_hash=pre_header.block_hash,
        transactions_root=pre_header.transactions_root,
        # [New in Capella]
        withdrawals_root=Root(),
    )
    post = BeaconState(
        genesis_time=pre.genesis_time,
        genesis_validators_root=pre.genesis_validators_root,
        slot=pre.slot,
        fork=Fork(
            previous_version=pre.fork.current_version,
            current_version=config.CAPELLA_FORK_VERSION,
            epoch=epoch,
        ),
        latest_block_header=pre.latest_block_header,
        block_roots=pre.block_roots,
        state_roots=pre.state_roots,
        historical_roots=pre.historical_roots,
        eth1_data=pre.eth1_data,
        eth1_data_votes=pre.eth1_data_votes,
        eth1_deposit_index=pre.eth1_deposit_index,
        validators=pre.validators,
        balances=pre.balances,
        randao_mixes=pre.randao_mixes,
        slashings=pre.slashings,
        previous_epoch_participation=pre.previous_epoch_participation,
        current_epoch_participation=pre.current_epoch_participation,
        justification_bits=pre.justification_bits,
        previous_justified_checkpoint=pre.previous_justified_checkpoint,
        current_justified_checkpoint=pre.current_justified_checkpoint,
        finalized_checkpoint=pre.finalized_checkpoint,
        inactivity_scores=pre.inactivity_scores,
        current_sync_committee=pre.current_sync_committee,
        next_sync_committee=pre.next_sync_committee,
        latest_execution_payload_header=latest_execution_payload_header,
        # [New in Capella]
        next_withdrawal_index=WithdrawalIndex(0),
        next_withdrawal_validator_index=ValidatorIndex(0),
        historical_summaries=List[HistoricalSummary, HISTORICAL_ROOTS_LIMIT]([]),
    )

    return post
