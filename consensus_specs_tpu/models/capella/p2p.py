# Capella -- p2p deltas: the bls_to_execution_change gossip topic and its
# uniqueness condition (specs/capella/p2p-interface.md).


def compute_bls_to_execution_change_topic(fork_digest: ForkDigest) -> str:
    return compute_gossip_topic(fork_digest, "bls_to_execution_change")


def is_valid_bls_to_execution_change_gossip(
        state: BeaconState,
        signed_change: SignedBLSToExecutionChange) -> bool:
    """Gossip condition: the change must target a validator whose
    credentials are still BLS-prefixed, with a valid signature
    (capella/p2p-interface.md bls_to_execution_change conditions)."""
    change = signed_change.message
    if change.validator_index >= len(state.validators):
        return False
    validator = state.validators[change.validator_index]
    if validator.withdrawal_credentials[:1] != BLS_WITHDRAWAL_PREFIX:
        return False
    try:
        process_bls_to_execution_change(state.copy(), signed_change)
        return True
    except (AssertionError, IndexError, ValueError):
        return False
