# Capella -- The Beacon Chain (executable spec source, delta over
# bellatrix): withdrawals, BLS-to-execution credential changes, and
# historical summaries.  Parity contract: specs/capella/beacon-chain.md
# (types :58-70, containers :92-237, predicates :243-281,
#  epoch processing :285-318, block processing :320-500).

# ---------------------------------------------------------------------------
# Custom types + constants (beacon-chain.md :58-90)
# ---------------------------------------------------------------------------


class WithdrawalIndex(uint64):
    pass


DOMAIN_BLS_TO_EXECUTION_CHANGE = DomainType("0x0A000000")


# ---------------------------------------------------------------------------
# Containers (beacon-chain.md :92-237)
# ---------------------------------------------------------------------------


class Withdrawal(Container):
    index: WithdrawalIndex
    validator_index: ValidatorIndex
    address: ExecutionAddress
    amount: Gwei


class BLSToExecutionChange(Container):
    validator_index: ValidatorIndex
    from_bls_pubkey: BLSPubkey
    to_execution_address: ExecutionAddress


class SignedBLSToExecutionChange(Container):
    message: BLSToExecutionChange
    signature: BLSSignature


class HistoricalSummary(Container):
    # hash_tree_root-compatible with phase0 HistoricalBatch
    block_summary_root: Root
    state_summary_root: Root


class ExecutionPayload(Container):
    parent_hash: Hash32
    fee_recipient: ExecutionAddress
    state_root: Bytes32
    receipts_root: Bytes32
    logs_bloom: ByteVector[BYTES_PER_LOGS_BLOOM]
    prev_randao: Bytes32
    block_number: uint64
    gas_limit: uint64
    gas_used: uint64
    timestamp: uint64
    extra_data: ByteList[MAX_EXTRA_DATA_BYTES]
    base_fee_per_gas: uint256
    block_hash: Hash32
    transactions: List[Transaction, MAX_TRANSACTIONS_PER_PAYLOAD]
    # [New in Capella]
    withdrawals: List[Withdrawal, MAX_WITHDRAWALS_PER_PAYLOAD]


class ExecutionPayloadHeader(Container):
    parent_hash: Hash32
    fee_recipient: ExecutionAddress
    state_root: Bytes32
    receipts_root: Bytes32
    logs_bloom: ByteVector[BYTES_PER_LOGS_BLOOM]
    prev_randao: Bytes32
    block_number: uint64
    gas_limit: uint64
    gas_used: uint64
    timestamp: uint64
    extra_data: ByteList[MAX_EXTRA_DATA_BYTES]
    base_fee_per_gas: uint256
    block_hash: Hash32
    transactions_root: Root
    # [New in Capella]
    withdrawals_root: Root


class BeaconBlockBody(Container):
    randao_reveal: BLSSignature
    eth1_data: Eth1Data
    graffiti: Bytes32
    proposer_slashings: List[ProposerSlashing, MAX_PROPOSER_SLASHINGS]
    attester_slashings: List[AttesterSlashing, MAX_ATTESTER_SLASHINGS]
    attestations: List[Attestation, MAX_ATTESTATIONS]
    deposits: List[Deposit, MAX_DEPOSITS]
    voluntary_exits: List[SignedVoluntaryExit, MAX_VOLUNTARY_EXITS]
    sync_aggregate: SyncAggregate
    execution_payload: ExecutionPayload
    # [New in Capella]
    bls_to_execution_changes: List[SignedBLSToExecutionChange, MAX_BLS_TO_EXECUTION_CHANGES]


class BeaconBlock(Container):
    slot: Slot
    proposer_index: ValidatorIndex
    parent_root: Root
    state_root: Root
    body: BeaconBlockBody


class SignedBeaconBlock(Container):
    message: BeaconBlock
    signature: BLSSignature


class BeaconState(Container):
    genesis_time: uint64
    genesis_validators_root: Root
    slot: Slot
    fork: Fork
    latest_block_header: BeaconBlockHeader
    block_roots: Vector[Root, SLOTS_PER_HISTORICAL_ROOT]
    state_roots: Vector[Root, SLOTS_PER_HISTORICAL_ROOT]
    historical_roots: List[Root, HISTORICAL_ROOTS_LIMIT]
    eth1_data: Eth1Data
    eth1_data_votes: List[Eth1Data, EPOCHS_PER_ETH1_VOTING_PERIOD * SLOTS_PER_EPOCH]
    eth1_deposit_index: uint64
    validators: List[Validator, VALIDATOR_REGISTRY_LIMIT]
    balances: List[Gwei, VALIDATOR_REGISTRY_LIMIT]
    randao_mixes: Vector[Bytes32, EPOCHS_PER_HISTORICAL_VECTOR]
    slashings: Vector[Gwei, EPOCHS_PER_SLASHINGS_VECTOR]
    previous_epoch_participation: List[ParticipationFlags, VALIDATOR_REGISTRY_LIMIT]
    current_epoch_participation: List[ParticipationFlags, VALIDATOR_REGISTRY_LIMIT]
    justification_bits: Bitvector[JUSTIFICATION_BITS_LENGTH]
    previous_justified_checkpoint: Checkpoint
    current_justified_checkpoint: Checkpoint
    finalized_checkpoint: Checkpoint
    inactivity_scores: List[uint64, VALIDATOR_REGISTRY_LIMIT]
    current_sync_committee: SyncCommittee
    next_sync_committee: SyncCommittee
    # [Modified in Capella]
    latest_execution_payload_header: ExecutionPayloadHeader
    # [New in Capella]
    next_withdrawal_index: WithdrawalIndex
    # [New in Capella]
    next_withdrawal_validator_index: ValidatorIndex
    # [New in Capella]
    historical_summaries: List[HistoricalSummary, HISTORICAL_ROOTS_LIMIT]


# ---------------------------------------------------------------------------
# Predicates (beacon-chain.md :243-281)
# ---------------------------------------------------------------------------


def has_eth1_withdrawal_credential(validator: Validator) -> bool:
    """0x01-prefixed ("eth1") withdrawal credential?"""
    return validator.withdrawal_credentials[:1] == ETH1_ADDRESS_WITHDRAWAL_PREFIX


def is_fully_withdrawable_validator(validator: Validator, balance: Gwei,
                                    epoch: Epoch) -> bool:
    return (
        has_eth1_withdrawal_credential(validator)
        and validator.withdrawable_epoch <= epoch
        and balance > 0
    )


def is_partially_withdrawable_validator(validator: Validator,
                                        balance: Gwei) -> bool:
    has_max_effective_balance = (validator.effective_balance
                                 == MAX_EFFECTIVE_BALANCE)
    has_excess_balance = balance > MAX_EFFECTIVE_BALANCE
    return (
        has_eth1_withdrawal_credential(validator)
        and has_max_effective_balance
        and has_excess_balance
    )


# ---------------------------------------------------------------------------
# Epoch processing (beacon-chain.md :285-318)
# ---------------------------------------------------------------------------


def process_epoch(state: BeaconState) -> None:
    process_justification_and_finalization(state)
    process_inactivity_updates(state)
    process_rewards_and_penalties(state)
    process_registry_updates(state)
    process_slashings(state)
    process_eth1_data_reset(state)
    process_effective_balance_updates(state)
    process_slashings_reset(state)
    process_randao_mixes_reset(state)
    process_historical_summaries_update(state)  # [Modified in Capella]
    process_participation_flag_updates(state)
    process_sync_committee_updates(state)


def process_historical_summaries_update(state: BeaconState) -> None:
    # Set historical block root accumulator
    next_epoch = Epoch(get_current_epoch(state) + 1)
    if next_epoch % (SLOTS_PER_HISTORICAL_ROOT // SLOTS_PER_EPOCH) == 0:
        historical_summary = HistoricalSummary(
            block_summary_root=hash_tree_root(state.block_roots),
            state_summary_root=hash_tree_root(state.state_roots),
        )
        state.historical_summaries.append(historical_summary)


# ---------------------------------------------------------------------------
# Block processing (beacon-chain.md :320-500)
# ---------------------------------------------------------------------------


def process_block(state: BeaconState, block: BeaconBlock) -> None:
    process_block_header(state, block)
    # [Modified in Capella] `is_execution_enabled` check removed
    process_withdrawals(state, block.body.execution_payload)  # [New in Capella]
    process_execution_payload(state, block.body, EXECUTION_ENGINE)  # [Modified in Capella]
    process_randao(state, block.body)
    process_eth1_data(state, block.body)
    process_operations(state, block.body)  # [Modified in Capella]
    process_sync_aggregate(state, block.body.sync_aggregate)


def get_expected_withdrawals(state: BeaconState) -> Sequence[Withdrawal]:
    """Deterministic withdrawal sweep from
    `next_withdrawal_validator_index` (beacon-chain.md :337-369)."""
    epoch = get_current_epoch(state)
    withdrawal_index = state.next_withdrawal_index
    validator_index = state.next_withdrawal_validator_index
    withdrawals = []
    bound = min(len(state.validators), MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)
    for _ in range(bound):
        validator = state.validators[validator_index]
        balance = state.balances[validator_index]
        if is_fully_withdrawable_validator(validator, balance, epoch):
            withdrawals.append(Withdrawal(
                index=withdrawal_index,
                validator_index=validator_index,
                address=ExecutionAddress(validator.withdrawal_credentials[12:]),
                amount=balance,
            ))
            withdrawal_index += WithdrawalIndex(1)
        elif is_partially_withdrawable_validator(validator, balance):
            withdrawals.append(Withdrawal(
                index=withdrawal_index,
                validator_index=validator_index,
                address=ExecutionAddress(validator.withdrawal_credentials[12:]),
                amount=balance - MAX_EFFECTIVE_BALANCE,
            ))
            withdrawal_index += WithdrawalIndex(1)
        if len(withdrawals) == MAX_WITHDRAWALS_PER_PAYLOAD:
            break
        validator_index = ValidatorIndex(
            (validator_index + 1) % len(state.validators))
    return withdrawals


def process_withdrawals(state: BeaconState,
                        payload: ExecutionPayload) -> None:
    expected_withdrawals = get_expected_withdrawals(state)
    assert payload.withdrawals == expected_withdrawals

    for withdrawal in expected_withdrawals:
        decrease_balance(state, withdrawal.validator_index, withdrawal.amount)

    # Update the next withdrawal index if this block contained withdrawals
    if len(expected_withdrawals) != 0:
        latest_withdrawal = expected_withdrawals[-1]
        state.next_withdrawal_index = WithdrawalIndex(
            latest_withdrawal.index + 1)

    # Update the next validator index for the next sweep
    if len(expected_withdrawals) == MAX_WITHDRAWALS_PER_PAYLOAD:
        # Next sweep starts after the latest withdrawal's validator index
        next_validator_index = ValidatorIndex(
            (expected_withdrawals[-1].validator_index + 1)
            % len(state.validators))
        state.next_withdrawal_validator_index = next_validator_index
    else:
        # Advance by the sweep bound when the payload was not full
        next_index = (state.next_withdrawal_validator_index
                      + MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)
        next_validator_index = ValidatorIndex(
            next_index % len(state.validators))
        state.next_withdrawal_validator_index = next_validator_index


def process_execution_payload(state: BeaconState, body: BeaconBlockBody,
                              execution_engine: ExecutionEngine) -> None:
    payload = body.execution_payload
    # [Modified in Capella] `is_merge_transition_complete` check removed
    assert payload.parent_hash == state.latest_execution_payload_header.block_hash
    # Verify prev_randao
    assert payload.prev_randao == get_randao_mix(state, get_current_epoch(state))
    # Verify timestamp
    assert payload.timestamp == compute_time_at_slot(state, state.slot)
    # Verify the execution payload is valid
    assert execution_engine.verify_and_notify_new_payload(
        NewPayloadRequest(execution_payload=payload))
    # Cache execution payload header
    state.latest_execution_payload_header = ExecutionPayloadHeader(
        parent_hash=payload.parent_hash,
        fee_recipient=payload.fee_recipient,
        state_root=payload.state_root,
        receipts_root=payload.receipts_root,
        logs_bloom=payload.logs_bloom,
        prev_randao=payload.prev_randao,
        block_number=payload.block_number,
        gas_limit=payload.gas_limit,
        gas_used=payload.gas_used,
        timestamp=payload.timestamp,
        extra_data=payload.extra_data,
        base_fee_per_gas=payload.base_fee_per_gas,
        block_hash=payload.block_hash,
        transactions_root=hash_tree_root(payload.transactions),
        # [New in Capella]
        withdrawals_root=hash_tree_root(payload.withdrawals),
    )


def process_operations(state: BeaconState, body: BeaconBlockBody) -> None:
    # Outstanding deposits up to the max per block
    assert len(body.deposits) == min(
        MAX_DEPOSITS, state.eth1_data.deposit_count - state.eth1_deposit_index)

    def for_ops(operations, fn):
        for operation in operations:
            fn(state, operation)

    for_ops(body.proposer_slashings, process_proposer_slashing)
    for_ops(body.attester_slashings, process_attester_slashing)
    for_ops(body.attestations, process_attestation)
    for_ops(body.deposits, process_deposit)
    for_ops(body.voluntary_exits, process_voluntary_exit)
    # [New in Capella]
    for_ops(body.bls_to_execution_changes, process_bls_to_execution_change)


def process_bls_to_execution_change(
        state: BeaconState,
        signed_address_change: SignedBLSToExecutionChange) -> None:
    address_change = signed_address_change.message

    assert address_change.validator_index < len(state.validators)

    validator = state.validators[address_change.validator_index]

    assert validator.withdrawal_credentials[:1] == BLS_WITHDRAWAL_PREFIX
    assert (validator.withdrawal_credentials[1:]
            == hash(address_change.from_bls_pubkey)[1:])

    # Fork-agnostic domain: address changes stay valid across forks
    domain = compute_domain(
        DOMAIN_BLS_TO_EXECUTION_CHANGE,
        genesis_validators_root=state.genesis_validators_root)
    signing_root = compute_signing_root(address_change, domain)
    assert bls.Verify(address_change.from_bls_pubkey, signing_root,
                      signed_address_change.signature)

    validator.withdrawal_credentials = (
        ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11
        + address_change.to_execution_address
    )
