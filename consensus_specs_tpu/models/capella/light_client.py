# Capella -- Light Client (execution payload proofs).
#
# Parity contract: specs/capella/light-client/sync-protocol.md (modified
# LightClientHeader + execution-root helpers), full-node.md (header
# construction with the execution branch), fork.md (upgrade functions).
# From capella onward the light-client header commits to the execution
# payload header via a merkle branch into the block body.

EXECUTION_PAYLOAD_GINDEX = get_generalized_index(
    BeaconBlockBody, "execution_payload")
assert EXECUTION_PAYLOAD_GINDEX == 25, EXECUTION_PAYLOAD_GINDEX

ExecutionBranch = Vector[Bytes32, floorlog2(EXECUTION_PAYLOAD_GINDEX)]


class LightClientHeader(Container):
    # Beacon block header
    beacon: BeaconBlockHeader
    # Execution payload header for `beacon.body_root` (from Capella onward)
    execution: ExecutionPayloadHeader
    execution_branch: ExecutionBranch


# Containers embedding the header bind the field type at class creation;
# re-declare them against the capella header (fork.md modified containers).


class LightClientBootstrap(Container):
    header: LightClientHeader
    current_sync_committee: SyncCommittee
    current_sync_committee_branch: CurrentSyncCommitteeBranch


class LightClientUpdate(Container):
    attested_header: LightClientHeader
    next_sync_committee: SyncCommittee
    next_sync_committee_branch: NextSyncCommitteeBranch
    finalized_header: LightClientHeader
    finality_branch: FinalityBranch
    sync_aggregate: SyncAggregate
    signature_slot: Slot


class LightClientFinalityUpdate(Container):
    attested_header: LightClientHeader
    finalized_header: LightClientHeader
    finality_branch: FinalityBranch
    sync_aggregate: SyncAggregate
    signature_slot: Slot


class LightClientOptimisticUpdate(Container):
    attested_header: LightClientHeader
    sync_aggregate: SyncAggregate
    signature_slot: Slot


@dataclass
class LightClientStore(object):
    finalized_header: LightClientHeader
    current_sync_committee: SyncCommittee
    next_sync_committee: SyncCommittee
    best_valid_update: Optional[LightClientUpdate]
    optimistic_header: LightClientHeader
    previous_max_active_participants: uint64
    current_max_active_participants: uint64


def get_lc_execution_root(header: LightClientHeader) -> Root:
    epoch = compute_epoch_at_slot(header.beacon.slot)

    if epoch >= config.CAPELLA_FORK_EPOCH:
        return hash_tree_root(header.execution)

    return Root()


def is_valid_light_client_header(header: LightClientHeader) -> bool:
    epoch = compute_epoch_at_slot(header.beacon.slot)

    if epoch < config.CAPELLA_FORK_EPOCH:
        return (header.execution == ExecutionPayloadHeader()
                and header.execution_branch == ExecutionBranch())

    return is_valid_merkle_branch(
        leaf=get_lc_execution_root(header),
        branch=header.execution_branch,
        depth=floorlog2(EXECUTION_PAYLOAD_GINDEX),
        index=get_subtree_index(EXECUTION_PAYLOAD_GINDEX),
        root=header.beacon.body_root,
    )


def get_lc_execution_payload_header(payload) -> ExecutionPayloadHeader:
    return ExecutionPayloadHeader(
        parent_hash=payload.parent_hash,
        fee_recipient=payload.fee_recipient,
        state_root=payload.state_root,
        receipts_root=payload.receipts_root,
        logs_bloom=payload.logs_bloom,
        prev_randao=payload.prev_randao,
        block_number=payload.block_number,
        gas_limit=payload.gas_limit,
        gas_used=payload.gas_used,
        timestamp=payload.timestamp,
        extra_data=payload.extra_data,
        base_fee_per_gas=payload.base_fee_per_gas,
        block_hash=payload.block_hash,
        transactions_root=hash_tree_root(payload.transactions),
        withdrawals_root=hash_tree_root(payload.withdrawals),
    )


def block_to_light_client_header(block: SignedBeaconBlock) -> LightClientHeader:
    epoch = compute_epoch_at_slot(block.message.slot)

    if epoch >= config.CAPELLA_FORK_EPOCH:
        execution_header = get_lc_execution_payload_header(
            block.message.body.execution_payload)
        execution_branch = ExecutionBranch(
            compute_merkle_proof(block.message.body,
                                 EXECUTION_PAYLOAD_GINDEX))
    else:
        # Legacy data through upgrade_lc_header_to_capella carries no
        # execution info even though bellatrix blocks have payloads
        execution_header = ExecutionPayloadHeader()
        execution_branch = ExecutionBranch()

    return LightClientHeader(
        beacon=BeaconBlockHeader(
            slot=block.message.slot,
            proposer_index=block.message.proposer_index,
            parent_root=block.message.parent_root,
            state_root=block.message.state_root,
            body_root=hash_tree_root(block.message.body),
        ),
        execution=execution_header,
        execution_branch=execution_branch,
    )


# -- fork.md upgrade functions ----------------------------------------------


def upgrade_lc_header_to_capella(pre) -> LightClientHeader:
    return LightClientHeader(beacon=pre.beacon)


def upgrade_lc_bootstrap_to_capella(pre) -> LightClientBootstrap:
    return LightClientBootstrap(
        header=upgrade_lc_header_to_capella(pre.header),
        current_sync_committee=pre.current_sync_committee,
        current_sync_committee_branch=pre.current_sync_committee_branch,
    )


def upgrade_lc_update_to_capella(pre) -> LightClientUpdate:
    return LightClientUpdate(
        attested_header=upgrade_lc_header_to_capella(pre.attested_header),
        next_sync_committee=pre.next_sync_committee,
        next_sync_committee_branch=pre.next_sync_committee_branch,
        finalized_header=upgrade_lc_header_to_capella(pre.finalized_header),
        finality_branch=pre.finality_branch,
        sync_aggregate=pre.sync_aggregate,
        signature_slot=pre.signature_slot,
    )


def upgrade_lc_finality_update_to_capella(pre) -> LightClientFinalityUpdate:
    return LightClientFinalityUpdate(
        attested_header=upgrade_lc_header_to_capella(pre.attested_header),
        finalized_header=upgrade_lc_header_to_capella(pre.finalized_header),
        finality_branch=pre.finality_branch,
        sync_aggregate=pre.sync_aggregate,
        signature_slot=pre.signature_slot,
    )


def upgrade_lc_optimistic_update_to_capella(pre) -> LightClientOptimisticUpdate:
    return LightClientOptimisticUpdate(
        attested_header=upgrade_lc_header_to_capella(pre.attested_header),
        sync_aggregate=pre.sync_aggregate,
        signature_slot=pre.signature_slot,
    )


def upgrade_lc_store_to_capella(pre) -> LightClientStore:
    if pre.best_valid_update is None:
        best_valid_update = None
    else:
        best_valid_update = upgrade_lc_update_to_capella(
            pre.best_valid_update)
    return LightClientStore(
        finalized_header=upgrade_lc_header_to_capella(pre.finalized_header),
        current_sync_committee=pre.current_sync_committee,
        next_sync_committee=pre.next_sync_committee,
        best_valid_update=best_valid_update,
        optimistic_header=upgrade_lc_header_to_capella(
            pre.optimistic_header),
        previous_max_active_participants=(
            pre.previous_max_active_participants),
        current_max_active_participants=pre.current_max_active_participants,
    )
