# Capella -- Honest validator deltas (executable spec source).
# Parity contract: specs/capella/validator.md (:41-114): GetPayloadResponse
# gains block_value; prepare_execution_payload drops the merge-transition
# branch and passes expected withdrawals in the payload attributes.


@dataclass
class GetPayloadResponse(object):
    execution_payload: ExecutionPayload
    block_value: uint256 = uint256(0)


def prepare_execution_payload(state: BeaconState, safe_block_hash: Hash32,
                              finalized_block_hash: Hash32,
                              suggested_fee_recipient: ExecutionAddress,
                              execution_engine: ExecutionEngine):
    # [Modified in Capella] the merge is over: no transition branch
    parent_hash = state.latest_execution_payload_header.block_hash

    payload_attributes = PayloadAttributes(
        timestamp=compute_time_at_slot(state, state.slot),
        prev_randao=get_randao_mix(state, get_current_epoch(state)),
        suggested_fee_recipient=suggested_fee_recipient,
        withdrawals=get_expected_withdrawals(state),  # [New in Capella]
    )
    return execution_engine.notify_forkchoice_updated(
        head_block_hash=parent_hash,
        safe_block_hash=safe_block_hash,
        finalized_block_hash=finalized_block_hash,
        payload_attributes=payload_attributes,
    )
