# Capella -- Fork choice deltas (executable spec source).
# Parity contract: specs/capella/fork-choice.md (:51-120): PayloadAttributes
# gains withdrawals; on_block drops the merge-transition validation.


@dataclass
class PayloadAttributes(object):
    timestamp: uint64
    prev_randao: Bytes32
    suggested_fee_recipient: ExecutionAddress
    withdrawals: Sequence[Any] = ()  # [New in Capella]


def on_block(store: Store, signed_block: SignedBeaconBlock) -> None:
    """phase0 on_block without the merge-transition checks
    (capella/fork-choice.md :66-120)."""
    block = signed_block.message
    # Parent must be known
    assert block.parent_root in store.block_states
    pre_state = copy(store.block_states[block.parent_root])
    # Future blocks wait until their slot arrives
    assert get_current_slot(store) >= block.slot

    # Later than the finalized slot, descending from the finalized block
    finalized_slot = compute_start_slot_at_epoch(
        store.finalized_checkpoint.epoch)
    assert block.slot > finalized_slot
    finalized_checkpoint_block = get_checkpoint_block(
        store, block.parent_root, store.finalized_checkpoint.epoch)
    assert store.finalized_checkpoint.root == finalized_checkpoint_block

    # [Modified in Capella] no validate_merge_block: the transition is done

    # Validity + post-state
    state = pre_state
    block_root = hash_tree_root(block)
    state_transition(state, signed_block, True)

    store.blocks[block_root] = block
    store.block_states[block_root] = state

    # Timeliness + proposer boost
    time_into_slot = (store.time - store.genesis_time) % config.SECONDS_PER_SLOT
    is_before_attesting_interval = (
        time_into_slot < config.SECONDS_PER_SLOT // INTERVALS_PER_SLOT)
    is_timely = (get_current_slot(store) == block.slot
                 and is_before_attesting_interval)
    store.block_timeliness[hash_tree_root(block)] = is_timely

    is_first_block = store.proposer_boost_root == Root()
    if is_timely and is_first_block:
        store.proposer_boost_root = hash_tree_root(block)

    update_checkpoints(store, state.current_justified_checkpoint,
                       state.finalized_checkpoint)
    compute_pulled_up_tip(store, block_root)
