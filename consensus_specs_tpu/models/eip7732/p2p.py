# EIP-7732 (ePBS) -- p2p deltas: three new global gossip topics
# (`execution_payload`, `payload_attestation_message`,
# `execution_payload_header`) and the modified blob-sidecar inclusion
# proof rooted in the envelope's commitments list
# (specs/_features/eip7732/p2p-interface.md :83-260).


def is_valid_payload_envelope_gossip(
        state: BeaconState,
        signed_envelope: SignedExecutionPayloadEnvelope) -> bool:
    """`execution_payload` topic REJECT conditions against the committed
    bid (p2p-interface.md :173-199)."""
    envelope = signed_envelope.message
    header = state.latest_execution_payload_header
    if envelope.builder_index != header.builder_index:
        return False
    if not envelope.payload_withheld:
        if envelope.payload.block_hash != header.block_hash:
            return False
    return verify_execution_payload_envelope_signature(
        state, signed_envelope)


def is_valid_payload_attestation_message_gossip(
        state: BeaconState,
        message: PayloadAttestationMessage) -> bool:
    """`payload_attestation_message` topic REJECT conditions: status in
    range, index in the slot's PTC, valid signature
    (p2p-interface.md :201-225)."""
    data = message.data
    if data.payload_status >= PAYLOAD_INVALID_STATUS:
        return False
    ptc = get_ptc(state, data.slot)
    if message.validator_index not in ptc:
        return False
    domain = get_domain(state, DOMAIN_PTC_ATTESTER,
                        compute_epoch_at_slot(data.slot))
    signing_root = compute_signing_root(data, domain)
    pubkey = state.validators[message.validator_index].pubkey
    return bls.Verify(pubkey, signing_root, message.signature)


def is_valid_execution_payload_header_gossip(
        state: BeaconState,
        signed_header: SignedExecutionPayloadHeader,
        current_slot: Slot) -> bool:
    """`execution_payload_header` topic conditions: active non-slashed
    builder with funds, bid for the current or next slot, valid
    signature (p2p-interface.md :227-253)."""
    header = signed_header.message
    if header.builder_index >= len(state.validators):
        return False
    builder = state.validators[header.builder_index]
    if not is_active_validator(builder, get_current_epoch(state)):
        return False
    if builder.slashed:
        return False
    if header.value > state.balances[header.builder_index]:
        return False
    if header.slot not in (current_slot, current_slot + 1):
        return False
    return verify_execution_payload_header_signature(state, signed_header)
