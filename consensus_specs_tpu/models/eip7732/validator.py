# EIP-7732 (ePBS) -- Honest Validator + Builder duties (executable spec
# source).  Parity contract: specs/_features/eip7732/validator.md and
# builder.md (signature helpers :72-94, :172-190).


def get_ptc_assignment(state: BeaconState, epoch: Epoch,
                       validator_index: ValidatorIndex):
    """The slot in `epoch` where `validator_index` sits on the PTC, or
    None (validator.md `get_ptc_assignment`)."""
    next_epoch = Epoch(get_current_epoch(state) + 1)
    assert epoch <= next_epoch

    start_slot = compute_start_slot_at_epoch(epoch)
    for slot in range(start_slot, start_slot + SLOTS_PER_EPOCH):
        if validator_index in get_ptc(state, Slot(slot)):
            return Slot(slot)
    return None


def get_payload_attestation_message_signature(
        state: BeaconState, attestation: PayloadAttestationMessage,
        privkey: int) -> BLSSignature:
    """Sign only the PayloadAttestationData (validator.md)."""
    domain = get_domain(state, DOMAIN_PTC_ATTESTER,
                        compute_epoch_at_slot(attestation.data.slot))
    signing_root = compute_signing_root(attestation.data, domain)
    return bls.Sign(privkey, signing_root)


# --- Builder duties (builder.md) -------------------------------------------


def get_execution_payload_header_signature(
        state: BeaconState, header: ExecutionPayloadHeader,
        privkey: int) -> BLSSignature:
    """Builder signs its bid (builder.md :72-80)."""
    domain = get_domain(state, DOMAIN_BEACON_BUILDER,
                        compute_epoch_at_slot(header.slot))
    signing_root = compute_signing_root(header, domain)
    return bls.Sign(privkey, signing_root)


def get_execution_payload_envelope_signature(
        state: BeaconState, envelope: ExecutionPayloadEnvelope,
        privkey: int) -> BLSSignature:
    """Builder signs the revealed envelope (builder.md :172-180)."""
    domain = get_domain(state, DOMAIN_BEACON_BUILDER,
                        get_current_epoch(state))
    signing_root = compute_signing_root(envelope, domain)
    return bls.Sign(privkey, signing_root)
