# EIP-7732 (ePBS) -- The Beacon Chain (executable spec source, delta
# over electra).
#
# Enshrined proposer-builder separation: the beacon block commits to a
# signed builder bid (`SignedExecutionPayloadHeader`); the payload itself
# arrives later as a `SignedExecutionPayloadEnvelope` processed by an
# independent `process_execution_payload` transition, attested by the
# new Payload Timeliness Committee.  Parity contract:
# specs/_features/eip7732/beacon-chain.md (constants :94-125,
# containers :127-300, helpers :303-440, block :462-653,
# envelope :705-800).

# ---------------------------------------------------------------------------
# Constants (beacon-chain.md :94-125)
# ---------------------------------------------------------------------------

PAYLOAD_ABSENT = uint8(0)
PAYLOAD_PRESENT = uint8(1)
PAYLOAD_WITHHELD = uint8(2)
PAYLOAD_INVALID_STATUS = uint8(3)

DOMAIN_BEACON_BUILDER = DomainType("0x1B000000")
DOMAIN_PTC_ATTESTER = DomainType("0x0C000000")


# ---------------------------------------------------------------------------
# New containers (beacon-chain.md :127-196)
# ---------------------------------------------------------------------------


class PayloadAttestationData(Container):
    beacon_block_root: Root
    slot: Slot
    payload_status: uint8


class PayloadAttestation(Container):
    aggregation_bits: Bitvector[PTC_SIZE]
    data: PayloadAttestationData
    signature: BLSSignature


class PayloadAttestationMessage(Container):
    validator_index: ValidatorIndex
    data: PayloadAttestationData
    signature: BLSSignature


class IndexedPayloadAttestation(Container):
    attesting_indices: List[ValidatorIndex, PTC_SIZE]
    data: PayloadAttestationData
    signature: BLSSignature


class ExecutionPayloadHeader(Container):
    """[Modified in EIP7732] The builder's bid: block-hash commitment plus
    payment, gas limit and the KZG commitments root."""
    parent_block_hash: Hash32
    parent_block_root: Root
    block_hash: Hash32
    gas_limit: uint64
    builder_index: ValidatorIndex
    slot: Slot
    value: Gwei
    blob_kzg_commitments_root: Root


class SignedExecutionPayloadHeader(Container):
    message: ExecutionPayloadHeader
    signature: BLSSignature


class ExecutionPayloadEnvelope(Container):
    payload: ExecutionPayload
    execution_requests: ExecutionRequests
    builder_index: ValidatorIndex
    beacon_block_root: Root
    blob_kzg_commitments: List[KZGCommitment, MAX_BLOB_COMMITMENTS_PER_BLOCK]
    payload_withheld: boolean
    state_root: Root


class SignedExecutionPayloadEnvelope(Container):
    message: ExecutionPayloadEnvelope
    signature: BLSSignature


# ---------------------------------------------------------------------------
# Modified containers (beacon-chain.md :198-300)
# ---------------------------------------------------------------------------


class BeaconBlockBody(Container):
    randao_reveal: BLSSignature
    eth1_data: Eth1Data
    graffiti: Bytes32
    proposer_slashings: List[ProposerSlashing, MAX_PROPOSER_SLASHINGS]
    attester_slashings: List[AttesterSlashing, MAX_ATTESTER_SLASHINGS_ELECTRA]
    attestations: List[Attestation, MAX_ATTESTATIONS_ELECTRA]
    deposits: List[Deposit, MAX_DEPOSITS]
    voluntary_exits: List[SignedVoluntaryExit, MAX_VOLUNTARY_EXITS]
    sync_aggregate: SyncAggregate
    bls_to_execution_changes: List[SignedBLSToExecutionChange, MAX_BLS_TO_EXECUTION_CHANGES]
    # [New in EIP-7732] — execution_payload / blob_kzg_commitments /
    # execution_requests moved into the envelope
    signed_execution_payload_header: SignedExecutionPayloadHeader
    # [New in EIP-7732]
    payload_attestations: List[PayloadAttestation, MAX_PAYLOAD_ATTESTATIONS]


class BeaconBlock(Container):
    slot: Slot
    proposer_index: ValidatorIndex
    parent_root: Root
    state_root: Root
    body: BeaconBlockBody


class SignedBeaconBlock(Container):
    message: BeaconBlock
    signature: BLSSignature


class BeaconState(Container):
    genesis_time: uint64
    genesis_validators_root: Root
    slot: Slot
    fork: Fork
    latest_block_header: BeaconBlockHeader
    block_roots: Vector[Root, SLOTS_PER_HISTORICAL_ROOT]
    state_roots: Vector[Root, SLOTS_PER_HISTORICAL_ROOT]
    historical_roots: List[Root, HISTORICAL_ROOTS_LIMIT]
    eth1_data: Eth1Data
    eth1_data_votes: List[Eth1Data, EPOCHS_PER_ETH1_VOTING_PERIOD * SLOTS_PER_EPOCH]
    eth1_deposit_index: uint64
    validators: List[Validator, VALIDATOR_REGISTRY_LIMIT]
    balances: List[Gwei, VALIDATOR_REGISTRY_LIMIT]
    randao_mixes: Vector[Bytes32, EPOCHS_PER_HISTORICAL_VECTOR]
    slashings: Vector[Gwei, EPOCHS_PER_SLASHINGS_VECTOR]
    previous_epoch_participation: List[ParticipationFlags, VALIDATOR_REGISTRY_LIMIT]
    current_epoch_participation: List[ParticipationFlags, VALIDATOR_REGISTRY_LIMIT]
    justification_bits: Bitvector[JUSTIFICATION_BITS_LENGTH]
    previous_justified_checkpoint: Checkpoint
    current_justified_checkpoint: Checkpoint
    finalized_checkpoint: Checkpoint
    inactivity_scores: List[uint64, VALIDATOR_REGISTRY_LIMIT]
    current_sync_committee: SyncCommittee
    next_sync_committee: SyncCommittee
    # [Modified in EIP-7732] now the latest committed builder bid
    latest_execution_payload_header: ExecutionPayloadHeader
    next_withdrawal_index: WithdrawalIndex
    next_withdrawal_validator_index: ValidatorIndex
    historical_summaries: List[HistoricalSummary, HISTORICAL_ROOTS_LIMIT]
    deposit_requests_start_index: uint64
    deposit_balance_to_consume: Gwei
    exit_balance_to_consume: Gwei
    earliest_exit_epoch: Epoch
    consolidation_balance_to_consume: Gwei
    earliest_consolidation_epoch: Epoch
    pending_deposits: List[PendingDeposit, PENDING_DEPOSITS_LIMIT]
    pending_partial_withdrawals: List[PendingPartialWithdrawal, PENDING_PARTIAL_WITHDRAWALS_LIMIT]
    pending_consolidations: List[PendingConsolidation, PENDING_CONSOLIDATIONS_LIMIT]
    # [New in EIP-7732]
    latest_block_hash: Hash32
    # [New in EIP-7732]
    latest_full_slot: Slot
    # [New in EIP-7732]
    latest_withdrawals_root: Root


# ---------------------------------------------------------------------------
# Helpers (beacon-chain.md :303-440)
# ---------------------------------------------------------------------------


def bit_floor(n: uint64) -> uint64:
    """If ``n`` is not zero, the largest power of 2 not greater than n."""
    if n == 0:
        return 0
    return uint64(1) << (int(n).bit_length() - 1)


def remove_flag(flags: ParticipationFlags, flag_index: int) -> ParticipationFlags:
    flag = ParticipationFlags(2**flag_index)
    return flags & ~flag


def is_valid_indexed_payload_attestation(
        state: BeaconState,
        indexed_payload_attestation: IndexedPayloadAttestation) -> bool:
    """Non-empty, sorted-unique indices, valid aggregate signature."""
    if indexed_payload_attestation.data.payload_status >= PAYLOAD_INVALID_STATUS:
        return False

    indices = list(indexed_payload_attestation.attesting_indices)
    if len(indices) == 0 or indices != sorted(set(indices)):
        return False

    pubkeys = [state.validators[i].pubkey for i in indices]
    domain = get_domain(state, DOMAIN_PTC_ATTESTER, None)
    signing_root = compute_signing_root(
        indexed_payload_attestation.data, domain)
    return bls.FastAggregateVerify(
        pubkeys, signing_root, indexed_payload_attestation.signature)


def is_parent_block_full(state: BeaconState) -> bool:
    """True iff the last committed bid was fulfilled with a payload; must
    be called before `process_execution_payload_header`."""
    return state.latest_execution_payload_header.block_hash == state.latest_block_hash


def get_ptc(state: BeaconState, slot: Slot):
    """The Payload Timeliness Committee for ``slot``."""
    epoch = compute_epoch_at_slot(slot)
    committees_per_slot = bit_floor(
        min(get_committee_count_per_slot(state, epoch), PTC_SIZE))
    members_per_committee = PTC_SIZE // committees_per_slot

    validator_indices = []
    for idx in range(committees_per_slot):
        beacon_committee = get_beacon_committee(state, slot,
                                                CommitteeIndex(idx))
        validator_indices += list(beacon_committee)[:members_per_committee]
    return validator_indices


def get_attesting_indices(state: BeaconState, attestation: Attestation):
    """[Modified in EIP7732] PTC members' votes are ignored."""
    output = set()
    committee_indices = get_committee_indices(attestation.committee_bits)
    committee_offset = 0
    for index in committee_indices:
        committee = get_beacon_committee(state, attestation.data.slot, index)
        committee_attesters = set(
            vi for i, vi in enumerate(committee)
            if attestation.aggregation_bits[committee_offset + i])
        output = output.union(committee_attesters)
        committee_offset += len(committee)

    if compute_epoch_at_slot(attestation.data.slot) < config.EIP7732_FORK_EPOCH:
        return output
    ptc = get_ptc(state, attestation.data.slot)
    return set(i for i in output if i not in ptc)


def get_payload_attesting_indices(
        state: BeaconState, slot: Slot,
        payload_attestation: PayloadAttestation):
    ptc = get_ptc(state, slot)
    return set(index for i, index in enumerate(ptc)
               if payload_attestation.aggregation_bits[i])


def get_indexed_payload_attestation(
        state: BeaconState, slot: Slot,
        payload_attestation: PayloadAttestation) -> IndexedPayloadAttestation:
    attesting_indices = get_payload_attesting_indices(
        state, slot, payload_attestation)
    return IndexedPayloadAttestation(
        attesting_indices=sorted(attesting_indices),
        data=payload_attestation.data,
        signature=payload_attestation.signature,
    )


# ---------------------------------------------------------------------------
# Block processing (beacon-chain.md :462-653)
# ---------------------------------------------------------------------------


def process_block(state: BeaconState, block: BeaconBlock) -> None:
    process_block_header(state, block)
    process_withdrawals(state)  # [Modified in EIP-7732]
    # Removed `process_execution_payload` in EIP-7732
    process_execution_payload_header(state, block)  # [New in EIP-7732]
    process_randao(state, block.body)
    process_eth1_data(state, block.body)
    process_operations(state, block.body)  # [Modified in EIP-7732]
    process_sync_aggregate(state, block.body.sync_aggregate)


def process_withdrawals(state: BeaconState) -> None:
    """[Modified in EIP7732] Deterministic from the state alone; any
    payload building on this block must honor them in the EL."""
    # return early if the parent block was empty
    if not is_parent_block_full(state):
        return

    withdrawals, partial_withdrawals_count = get_expected_withdrawals(state)
    withdrawals_list = List[Withdrawal, MAX_WITHDRAWALS_PER_PAYLOAD](
        *withdrawals)
    state.latest_withdrawals_root = hash_tree_root(withdrawals_list)
    for withdrawal in withdrawals:
        decrease_balance(state, withdrawal.validator_index, withdrawal.amount)

    state.pending_partial_withdrawals = list(
        state.pending_partial_withdrawals)[partial_withdrawals_count:]

    if len(withdrawals) != 0:
        latest_withdrawal = withdrawals[-1]
        state.next_withdrawal_index = WithdrawalIndex(
            latest_withdrawal.index + 1)

    if len(withdrawals) == MAX_WITHDRAWALS_PER_PAYLOAD:
        next_validator_index = ValidatorIndex(
            (withdrawals[-1].validator_index + 1) % len(state.validators))
        state.next_withdrawal_validator_index = next_validator_index
    else:
        next_index = (state.next_withdrawal_validator_index
                      + MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)
        next_validator_index = ValidatorIndex(
            next_index % len(state.validators))
        state.next_withdrawal_validator_index = next_validator_index


def verify_execution_payload_header_signature(
        state: BeaconState,
        signed_header: SignedExecutionPayloadHeader) -> bool:
    builder = state.validators[signed_header.message.builder_index]
    signing_root = compute_signing_root(
        signed_header.message, get_domain(state, DOMAIN_BEACON_BUILDER))
    return bls.Verify(builder.pubkey, signing_root, signed_header.signature)


def process_execution_payload_header(state: BeaconState,
                                     block: BeaconBlock) -> None:
    # Verify the header signature
    signed_header = block.body.signed_execution_payload_header
    assert verify_execution_payload_header_signature(state, signed_header)

    # Check that the builder is active, non-slashed, and can cover the bid
    header = signed_header.message
    builder_index = header.builder_index
    builder = state.validators[builder_index]
    assert is_active_validator(builder, get_current_epoch(state))
    assert not builder.slashed
    amount = header.value
    assert state.balances[builder_index] >= amount

    # Verify that the bid is for the current slot and right parent block
    assert header.slot == block.slot
    assert header.parent_block_hash == state.latest_block_hash
    assert header.parent_block_root == block.parent_root

    # Transfer the funds from the builder to the proposer
    decrease_balance(state, builder_index, amount)
    increase_balance(state, block.proposer_index, amount)

    # Cache the signed execution payload header
    state.latest_execution_payload_header = header


def process_operations(state: BeaconState, body: BeaconBlockBody) -> None:
    # [Modified in EIP7732] requests moved into the payload envelope
    assert len(body.deposits) == min(
        MAX_DEPOSITS,
        state.eth1_data.deposit_count - state.eth1_deposit_index)

    def for_ops(operations, fn):
        for operation in operations:
            fn(state, operation)

    for_ops(body.proposer_slashings, process_proposer_slashing)
    for_ops(body.attester_slashings, process_attester_slashing)
    for_ops(body.attestations, process_attestation)
    for_ops(body.deposits, process_deposit)
    for_ops(body.voluntary_exits, process_voluntary_exit)
    for_ops(body.bls_to_execution_changes, process_bls_to_execution_change)
    # Removed `process_*_request` in EIP-7732 (moved to the envelope)
    # [New in EIP-7732]
    for_ops(body.payload_attestations, process_payload_attestation)


def process_payload_attestation(
        state: BeaconState,
        payload_attestation: PayloadAttestation) -> None:
    # For the parent beacon block, from the previous slot
    data = payload_attestation.data
    assert data.beacon_block_root == state.latest_block_header.parent_root
    assert data.slot + 1 == state.slot

    # Verify signature
    indexed_payload_attestation = get_indexed_payload_attestation(
        state, data.slot, payload_attestation)
    assert is_valid_indexed_payload_attestation(
        state, indexed_payload_attestation)

    if state.slot % SLOTS_PER_EPOCH == 0:
        epoch_participation = state.previous_epoch_participation
    else:
        epoch_participation = state.current_epoch_participation

    payload_was_present = data.slot == state.latest_full_slot
    voted_present = data.payload_status == PAYLOAD_PRESENT
    proposer_reward_denominator = (
        (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
        * WEIGHT_DENOMINATOR // PROPOSER_WEIGHT)
    proposer_index = get_beacon_proposer_index(state)
    if voted_present != payload_was_present:
        # Unset flags in case they were set by an equivocating attestation
        proposer_penalty_numerator = 0
        for index in indexed_payload_attestation.attesting_indices:
            for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
                if has_flag(epoch_participation[index], flag_index):
                    epoch_participation[index] = remove_flag(
                        epoch_participation[index], flag_index)
                    proposer_penalty_numerator += (
                        get_base_reward(state, index) * weight)
        # Penalize the proposer
        proposer_penalty = Gwei(
            2 * proposer_penalty_numerator // proposer_reward_denominator)
        decrease_balance(state, proposer_index, proposer_penalty)
        return

    # Reward the proposer and set the participation flags
    proposer_reward_numerator = 0
    for index in indexed_payload_attestation.attesting_indices:
        for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
            if not has_flag(epoch_participation[index], flag_index):
                epoch_participation[index] = add_flag(
                    epoch_participation[index], flag_index)
                proposer_reward_numerator += (
                    get_base_reward(state, index) * weight)

    proposer_reward = Gwei(
        proposer_reward_numerator // proposer_reward_denominator)
    increase_balance(state, proposer_index, proposer_reward)


def is_merge_transition_complete(state: BeaconState) -> bool:
    """[Modified in EIP7732] compares against the empty bid with the
    empty-list KZG commitments root."""
    header = ExecutionPayloadHeader()
    kzgs = List[KZGCommitment, MAX_BLOB_COMMITMENTS_PER_BLOCK]()
    header.blob_kzg_commitments_root = hash_tree_root(kzgs)

    return state.latest_execution_payload_header != header


def validate_merge_block(block: BeaconBlock) -> None:
    """[Modified in EIP7732] reads the parent hash from the committed
    bid."""
    if config.TERMINAL_BLOCK_HASH != Hash32():
        assert (compute_epoch_at_slot(block.slot)
                >= config.TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH)
        assert (block.body.signed_execution_payload_header.message
                .parent_block_hash == config.TERMINAL_BLOCK_HASH)
        return

    pow_block = get_pow_block(
        block.body.signed_execution_payload_header.message.parent_block_hash)
    assert pow_block is not None
    pow_parent = get_pow_block(pow_block.parent_hash)
    assert pow_parent is not None
    assert is_valid_terminal_pow_block(pow_block, pow_parent)


# ---------------------------------------------------------------------------
# Execution payload processing (beacon-chain.md :705-800)
# ---------------------------------------------------------------------------


def verify_execution_payload_envelope_signature(
        state: BeaconState,
        signed_envelope: SignedExecutionPayloadEnvelope) -> bool:
    builder = state.validators[signed_envelope.message.builder_index]
    signing_root = compute_signing_root(
        signed_envelope.message,
        get_domain(state, DOMAIN_BEACON_BUILDER))
    return bls.Verify(builder.pubkey, signing_root,
                      signed_envelope.signature)


def process_execution_payload(
        state: BeaconState,
        signed_envelope: SignedExecutionPayloadEnvelope,
        execution_engine: ExecutionEngine,
        verify: bool = True) -> None:
    """[Modified in EIP7732] An independent state transition, applied
    when the builder's envelope arrives."""
    # Verify signature
    if verify:
        assert verify_execution_payload_envelope_signature(
            state, signed_envelope)
    envelope = signed_envelope.message
    payload = envelope.payload
    # Cache latest block header state root
    previous_state_root = hash_tree_root(state)
    if state.latest_block_header.state_root == Root():
        state.latest_block_header.state_root = previous_state_root

    # Verify consistency with the beacon block
    assert envelope.beacon_block_root == hash_tree_root(
        state.latest_block_header)

    # Verify consistency with the committed header
    committed_header = state.latest_execution_payload_header
    assert envelope.builder_index == committed_header.builder_index
    assert committed_header.blob_kzg_commitments_root == hash_tree_root(
        envelope.blob_kzg_commitments)

    if not envelope.payload_withheld:
        # Verify the withdrawals root
        assert (hash_tree_root(payload.withdrawals)
                == state.latest_withdrawals_root)

        # Verify the gas limit and block-hash commitment
        assert committed_header.gas_limit == payload.gas_limit
        assert committed_header.block_hash == payload.block_hash
        # Consistency with the previous execution payload
        assert payload.parent_hash == state.latest_block_hash
        assert payload.prev_randao == get_randao_mix(
            state, get_current_epoch(state))
        assert payload.timestamp == compute_time_at_slot(state, state.slot)
        assert (len(envelope.blob_kzg_commitments)
                <= config.MAX_BLOBS_PER_BLOCK)
        # Verify the execution payload is valid
        versioned_hashes = [
            kzg_commitment_to_versioned_hash(commitment)
            for commitment in envelope.blob_kzg_commitments]
        requests = envelope.execution_requests
        assert execution_engine.verify_and_notify_new_payload(
            NewPayloadRequest(
                execution_payload=payload,
                versioned_hashes=versioned_hashes,
                parent_beacon_block_root=state.latest_block_header.parent_root,
                execution_requests=requests,
            ))

        # Process Electra operations
        def for_ops(operations, fn):
            for operation in operations:
                fn(state, operation)

        for_ops(requests.deposits, process_deposit_request)
        for_ops(requests.withdrawals, process_withdrawal_request)
        for_ops(requests.consolidations, process_consolidation_request)

        # Cache the execution payload header and full slot
        state.latest_block_hash = payload.block_hash
        state.latest_full_slot = state.slot

    # Verify the state root
    if verify:
        assert envelope.state_root == hash_tree_root(state)
