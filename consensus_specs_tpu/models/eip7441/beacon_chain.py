# EIP-7441 (Whisk) -- The Beacon Chain (executable spec source, delta
# over capella).
#
# Single secret leader election: proposers are selected through
# re-randomizable tracker commitments shuffled by block proposers, and
# prove ownership with a discrete-log-equality opening proof instead of
# exposing their index ahead of time.  Parity contract:
# specs/_features/eip7441/beacon-chain.md (constants :35-62,
# crypto :63-133, epoch :134-237, block :238-443).
#
# Proof backends (`ops/whisk.py`): tracker opening proofs are real
# Chaum-Pedersen DLEQ proofs; shuffle proofs use a transparent
# (non-hiding) argument verifying the same rerandomized-permutation
# relation as curdleproofs — see the module docstring.

DOMAIN_CANDIDATE_SELECTION = DomainType("0x07000000")
DOMAIN_SHUFFLE = DomainType("0x07100000")
DOMAIN_PROPOSER_SELECTION = DomainType("0x07200000")

BLSFieldElement = uint256
BLSG1Point = Bytes48
WhiskShuffleProof = ByteList[MAX_SHUFFLE_PROOF_SIZE]
WhiskTrackerProof = ByteList[MAX_OPENING_PROOF_SIZE]

BLS_G1_GENERATOR = BLSG1Point(bytes.fromhex(
    "97f1d3a73197d7942695638c4fa9ac0fc3688c4f9774b905a14e3a3f171bac58"
    "6c55e83ff97a1aeffb3af00adb22c6bb"))
BLS_MODULUS = 52435875175126190479447740508185965837690552500527637822603658699938581184513


def BLSG1ScalarMultiply(scalar: BLSFieldElement,
                        point: BLSG1Point) -> BLSG1Point:
    return bls.G1_to_bytes48(
        bls.ciphersuite.multiply(bls.bytes48_to_G1(point), int(scalar)))


def bytes_to_bls_field(b: Bytes32) -> BLSFieldElement:
    """Non-uniform bytes -> scalar reduction."""
    return BLSFieldElement(int.from_bytes(bytes(b), "little")
                           % BLS_MODULUS)


def IsValidWhiskShuffleProof(pre_shuffle_trackers, post_shuffle_trackers,
                             shuffle_proof) -> bool:
    """Verify `post_shuffle_trackers` is a rerandomized permutation of
    `pre_shuffle_trackers` (`ops/whisk.py` backend)."""
    from consensus_specs_tpu.ops.whisk import is_valid_whisk_shuffle_proof

    return is_valid_whisk_shuffle_proof(
        [(bytes(t.r_G), bytes(t.k_r_G)) for t in pre_shuffle_trackers],
        [(bytes(t.r_G), bytes(t.k_r_G)) for t in post_shuffle_trackers],
        bytes(shuffle_proof))


def IsValidWhiskOpeningProof(tracker, k_commitment,
                             tracker_proof) -> bool:
    """Verify knowledge of `k` with `tracker.k_r_G == k * tracker.r_G`
    and `k_commitment == k * G` (`ops/whisk.py` DLEQ backend)."""
    from consensus_specs_tpu.ops.whisk import is_valid_whisk_tracker_proof

    return is_valid_whisk_tracker_proof(
        bytes(tracker.r_G), bytes(tracker.k_r_G), bytes(k_commitment),
        bytes(tracker_proof))


class WhiskTracker(Container):
    r_G: BLSG1Point
    k_r_G: BLSG1Point


class BeaconState(Container):
    genesis_time: uint64
    genesis_validators_root: Root
    slot: Slot
    fork: Fork
    latest_block_header: BeaconBlockHeader
    block_roots: Vector[Root, SLOTS_PER_HISTORICAL_ROOT]
    state_roots: Vector[Root, SLOTS_PER_HISTORICAL_ROOT]
    historical_roots: List[Root, HISTORICAL_ROOTS_LIMIT]
    eth1_data: Eth1Data
    eth1_data_votes: List[Eth1Data, EPOCHS_PER_ETH1_VOTING_PERIOD * SLOTS_PER_EPOCH]
    eth1_deposit_index: uint64
    validators: List[Validator, VALIDATOR_REGISTRY_LIMIT]
    balances: List[Gwei, VALIDATOR_REGISTRY_LIMIT]
    randao_mixes: Vector[Bytes32, EPOCHS_PER_HISTORICAL_VECTOR]
    slashings: Vector[Gwei, EPOCHS_PER_SLASHINGS_VECTOR]
    previous_epoch_participation: List[ParticipationFlags, VALIDATOR_REGISTRY_LIMIT]
    current_epoch_participation: List[ParticipationFlags, VALIDATOR_REGISTRY_LIMIT]
    justification_bits: Bitvector[JUSTIFICATION_BITS_LENGTH]
    previous_justified_checkpoint: Checkpoint
    current_justified_checkpoint: Checkpoint
    finalized_checkpoint: Checkpoint
    inactivity_scores: List[uint64, VALIDATOR_REGISTRY_LIMIT]
    current_sync_committee: SyncCommittee
    next_sync_committee: SyncCommittee
    latest_execution_payload_header: ExecutionPayloadHeader
    next_withdrawal_index: WithdrawalIndex
    next_withdrawal_validator_index: ValidatorIndex
    historical_summaries: List[HistoricalSummary, HISTORICAL_ROOTS_LIMIT]
    # [New in EIP7441]
    whisk_candidate_trackers: Vector[WhiskTracker, CANDIDATE_TRACKERS_COUNT]
    # [New in EIP7441]
    whisk_proposer_trackers: Vector[WhiskTracker, PROPOSER_TRACKERS_COUNT]
    # [New in EIP7441]
    whisk_trackers: List[WhiskTracker, VALIDATOR_REGISTRY_LIMIT]
    # [New in EIP7441]
    whisk_k_commitments: List[BLSG1Point, VALIDATOR_REGISTRY_LIMIT]


# ---------------------------------------------------------------------------
# Epoch processing (beacon-chain.md :184-237)
# ---------------------------------------------------------------------------


def select_whisk_proposer_trackers(state: BeaconState,
                                   epoch: Epoch) -> None:
    # Select proposer trackers from candidate trackers
    proposer_seed = get_seed(
        state,
        Epoch(max(int(epoch) - int(config.PROPOSER_SELECTION_GAP), 0)),
        DOMAIN_PROPOSER_SELECTION)
    for i in range(PROPOSER_TRACKERS_COUNT):
        index = compute_shuffled_index(
            uint64(i), uint64(len(state.whisk_candidate_trackers)),
            proposer_seed)
        state.whisk_proposer_trackers[i] = \
            state.whisk_candidate_trackers[index]


def select_whisk_candidate_trackers(state: BeaconState,
                                    epoch: Epoch) -> None:
    # Select candidate trackers from active validator trackers
    active_validator_indices = get_active_validator_indices(state, epoch)
    for i in range(CANDIDATE_TRACKERS_COUNT):
        seed = hash(get_seed(state, epoch, DOMAIN_CANDIDATE_SELECTION)
                    + uint_to_bytes(uint64(i)))
        # sample by effective balance
        candidate_index = compute_proposer_index(
            state, active_validator_indices, seed)
        state.whisk_candidate_trackers[i] = \
            state.whisk_trackers[candidate_index]


def process_whisk_updates(state: BeaconState) -> None:
    next_epoch = Epoch(get_current_epoch(state) + 1)
    # select trackers at the start of shuffling phases
    if next_epoch % config.EPOCHS_PER_SHUFFLING_PHASE == 0:
        select_whisk_proposer_trackers(state, next_epoch)
        select_whisk_candidate_trackers(state, next_epoch)


def process_epoch(state: BeaconState) -> None:
    process_justification_and_finalization(state)
    process_inactivity_updates(state)
    process_rewards_and_penalties(state)
    process_registry_updates(state)
    process_slashings(state)
    process_eth1_data_reset(state)
    process_effective_balance_updates(state)
    process_slashings_reset(state)
    process_randao_mixes_reset(state)
    process_historical_summaries_update(state)
    process_participation_flag_updates(state)
    process_sync_committee_updates(state)
    process_whisk_updates(state)  # [New in EIP7441]


# ---------------------------------------------------------------------------
# Block processing (beacon-chain.md :238-443)
# ---------------------------------------------------------------------------


def process_whisk_opening_proof(state: BeaconState,
                                block: BeaconBlock) -> None:
    tracker = state.whisk_proposer_trackers[
        state.slot % PROPOSER_TRACKERS_COUNT]
    k_commitment = state.whisk_k_commitments[block.proposer_index]
    assert IsValidWhiskOpeningProof(tracker, k_commitment,
                                    block.body.whisk_opening_proof)


def process_block_header(state: BeaconState, block: BeaconBlock) -> None:
    # Verify that the slots match
    assert block.slot == state.slot
    # Verify that the block is newer than latest block header
    assert block.slot > state.latest_block_header.slot
    # [Removed in EIP7441] the proposer-index equality check: ownership
    # is proven through the tracker opening proof instead
    # Verify that the parent matches
    assert block.parent_root == hash_tree_root(state.latest_block_header)
    # Cache current block as the new latest block
    state.latest_block_header = BeaconBlockHeader(
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=block.parent_root,
        state_root=Bytes32(),  # Overwritten in the next process_slot call
        body_root=hash_tree_root(block.body),
    )

    # Verify proposer is not slashed
    proposer = state.validators[block.proposer_index]
    assert not proposer.slashed
    process_whisk_opening_proof(state, block)  # [New in EIP7441]


class BeaconBlockBody(Container):
    randao_reveal: BLSSignature
    eth1_data: Eth1Data
    graffiti: Bytes32
    proposer_slashings: List[ProposerSlashing, MAX_PROPOSER_SLASHINGS]
    attester_slashings: List[AttesterSlashing, MAX_ATTESTER_SLASHINGS]
    attestations: List[Attestation, MAX_ATTESTATIONS]
    deposits: List[Deposit, MAX_DEPOSITS]
    voluntary_exits: List[SignedVoluntaryExit, MAX_VOLUNTARY_EXITS]
    sync_aggregate: SyncAggregate
    execution_payload: ExecutionPayload
    bls_to_execution_changes: List[SignedBLSToExecutionChange, MAX_BLS_TO_EXECUTION_CHANGES]
    # [New in EIP7441]
    whisk_opening_proof: WhiskTrackerProof
    # [New in EIP7441]
    whisk_post_shuffle_trackers: Vector[WhiskTracker, VALIDATORS_PER_SHUFFLE]
    # [New in EIP7441]
    whisk_shuffle_proof: WhiskShuffleProof
    # [New in EIP7441]
    whisk_registration_proof: WhiskTrackerProof
    # [New in EIP7441]
    whisk_tracker: WhiskTracker
    # [New in EIP7441]
    whisk_k_commitment: BLSG1Point


class BeaconBlock(Container):
    slot: Slot
    proposer_index: ValidatorIndex
    parent_root: Root
    state_root: Root
    body: BeaconBlockBody


class SignedBeaconBlock(Container):
    message: BeaconBlock
    signature: BLSSignature


def get_shuffle_indices(randao_reveal: BLSSignature):
    """Indices shuffled out of the candidate set for this block."""
    indices = []
    for i in range(0, VALIDATORS_PER_SHUFFLE):
        # XXX ensure we are not suffering from modulo bias
        pre_image = randao_reveal + uint_to_bytes(uint64(i))
        shuffle_index = (bytes_to_uint64(hash(pre_image)[0:8])
                         % CANDIDATE_TRACKERS_COUNT)
        indices.append(shuffle_index)
    return indices


def process_shuffled_trackers(state: BeaconState,
                              body: BeaconBlockBody) -> None:
    shuffle_epoch = (get_current_epoch(state)
                     % config.EPOCHS_PER_SHUFFLING_PHASE)
    if (shuffle_epoch + config.PROPOSER_SELECTION_GAP + 1
            >= config.EPOCHS_PER_SHUFFLING_PHASE):
        # Require trackers set to zero during cooldown
        assert (body.whisk_post_shuffle_trackers
                == Vector[WhiskTracker, VALIDATORS_PER_SHUFFLE]())
        assert body.whisk_shuffle_proof == WhiskShuffleProof()
    else:
        # Require shuffled trackers during shuffle
        shuffle_indices = get_shuffle_indices(body.randao_reveal)
        pre_shuffle_trackers = [state.whisk_candidate_trackers[i]
                                for i in shuffle_indices]
        assert IsValidWhiskShuffleProof(
            pre_shuffle_trackers,
            body.whisk_post_shuffle_trackers,
            body.whisk_shuffle_proof,
        )
        # Shuffle candidate trackers
        for i, shuffle_index in enumerate(shuffle_indices):
            state.whisk_candidate_trackers[shuffle_index] = \
                body.whisk_post_shuffle_trackers[i]


def is_k_commitment_unique(state: BeaconState,
                           k_commitment: BLSG1Point) -> bool:
    return all(whisk_k_commitment != k_commitment
               for whisk_k_commitment in state.whisk_k_commitments)


def process_whisk_registration(state: BeaconState,
                               body: BeaconBlockBody) -> None:
    proposer_index = get_beacon_proposer_index(state)
    if state.whisk_trackers[proposer_index].r_G == BLS_G1_GENERATOR:
        # first Whisk proposal
        assert body.whisk_tracker.r_G != BLS_G1_GENERATOR
        assert is_k_commitment_unique(state, body.whisk_k_commitment)
        assert IsValidWhiskOpeningProof(
            body.whisk_tracker,
            body.whisk_k_commitment,
            body.whisk_registration_proof,
        )
        state.whisk_trackers[proposer_index] = body.whisk_tracker
        state.whisk_k_commitments[proposer_index] = \
            body.whisk_k_commitment
    else:  # next Whisk proposals
        assert body.whisk_registration_proof == WhiskTrackerProof()
        assert body.whisk_tracker == WhiskTracker()
        assert body.whisk_k_commitment == BLSG1Point()


def process_block(state: BeaconState, block: BeaconBlock) -> None:
    process_block_header(state, block)
    process_withdrawals(state, block.body.execution_payload)
    process_execution_payload(state, block.body, EXECUTION_ENGINE)
    process_randao(state, block.body)
    process_eth1_data(state, block.body)
    process_operations(state, block.body)
    process_sync_aggregate(state, block.body.sync_aggregate)
    process_shuffled_trackers(state, block.body)  # [New in EIP7441]
    process_whisk_registration(state, block.body)  # [New in EIP7441]


# ---------------------------------------------------------------------------
# Deposits (beacon-chain.md :385-431)
# ---------------------------------------------------------------------------


def get_initial_whisk_k(validator_index: ValidatorIndex,
                        counter: int) -> BLSFieldElement:
    # hash `validator_index || counter`
    return BLSFieldElement(bytes_to_bls_field(
        hash(uint_to_bytes(validator_index)
             + uint_to_bytes(uint64(counter)))))


def get_unique_whisk_k(state: BeaconState,
                       validator_index: ValidatorIndex) -> BLSFieldElement:
    counter = 0
    while True:
        k = get_initial_whisk_k(validator_index, counter)
        if is_k_commitment_unique(
                state, BLSG1ScalarMultiply(k, BLS_G1_GENERATOR)):
            return k  # unique by trial and error
        counter += 1


def get_k_commitment(k: BLSFieldElement) -> BLSG1Point:
    return BLSG1ScalarMultiply(k, BLS_G1_GENERATOR)


def get_initial_tracker(k: BLSFieldElement) -> WhiskTracker:
    return WhiskTracker(
        r_G=BLS_G1_GENERATOR,
        k_r_G=BLSG1ScalarMultiply(k, BLS_G1_GENERATOR))


def add_validator_to_registry(state: BeaconState, pubkey: BLSPubkey,
                              withdrawal_credentials: Bytes32,
                              amount: uint64) -> None:
    index = get_index_for_new_validator(state)
    validator = get_validator_from_deposit(pubkey,
                                           withdrawal_credentials, amount)
    set_or_append_list(state.validators, index, validator)
    set_or_append_list(state.balances, index, amount)
    set_or_append_list(state.previous_epoch_participation, index,
                       ParticipationFlags(0b0000_0000))
    set_or_append_list(state.current_epoch_participation, index,
                       ParticipationFlags(0b0000_0000))
    set_or_append_list(state.inactivity_scores, index, uint64(0))
    # [New in EIP7441]
    k = get_unique_whisk_k(state,
                           ValidatorIndex(len(state.validators) - 1))
    state.whisk_trackers.append(get_initial_tracker(k))
    state.whisk_k_commitments.append(get_k_commitment(k))


def get_beacon_proposer_index(state: BeaconState) -> ValidatorIndex:
    """Return the beacon proposer index at the current slot.

    [Modified in EIP7441] the proposer self-identifies through the
    opening proof; `process_block_header` must already have run."""
    assert state.latest_block_header.slot == state.slot
    return state.latest_block_header.proposer_index
