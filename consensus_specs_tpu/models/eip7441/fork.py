# EIP-7441 (Whisk) -- Fork Logic (executable spec source).
# Parity contract: specs/_features/eip7441/fork.md.


def compute_fork_version(epoch: Epoch) -> Version:
    """Fork version at `epoch`."""
    if epoch >= config.EIP7441_FORK_EPOCH:
        return config.EIP7441_FORK_VERSION
    if epoch >= config.CAPELLA_FORK_EPOCH:
        return config.CAPELLA_FORK_VERSION
    if epoch >= config.BELLATRIX_FORK_EPOCH:
        return config.BELLATRIX_FORK_VERSION
    if epoch >= config.ALTAIR_FORK_EPOCH:
        return config.ALTAIR_FORK_VERSION
    return config.GENESIS_FORK_VERSION


def upgrade_to_eip7441(pre) -> BeaconState:
    """capella -> eip7441 state upgrade: every validator receives a
    deterministic initial tracker/commitment; candidate and proposer
    trackers seed from them (fork.md `upgrade_to_eip7441`; the md's
    `validators=[]` is an obvious editorial slip — the registry carries
    over)."""
    # Compute initial unsafe trackers for all validators
    ks = [get_initial_whisk_k(ValidatorIndex(validator_index), 0)
          for validator_index in range(len(pre.validators))]
    whisk_k_commitments = [get_k_commitment(k) for k in ks]
    whisk_trackers = [get_initial_tracker(k) for k in ks]

    epoch = compute_epoch_at_slot(pre.slot)

    post = BeaconState(
        genesis_time=pre.genesis_time,
        genesis_validators_root=pre.genesis_validators_root,
        slot=pre.slot,
        fork=Fork(
            previous_version=pre.fork.current_version,
            # [Modified in EIP7441]
            current_version=config.EIP7441_FORK_VERSION,
            epoch=epoch,
        ),
        latest_block_header=pre.latest_block_header,
        block_roots=pre.block_roots,
        state_roots=pre.state_roots,
        historical_roots=pre.historical_roots,
        eth1_data=pre.eth1_data,
        eth1_data_votes=pre.eth1_data_votes,
        eth1_deposit_index=pre.eth1_deposit_index,
        validators=pre.validators,
        balances=pre.balances,
        randao_mixes=pre.randao_mixes,
        slashings=pre.slashings,
        previous_epoch_participation=pre.previous_epoch_participation,
        current_epoch_participation=pre.current_epoch_participation,
        justification_bits=pre.justification_bits,
        previous_justified_checkpoint=pre.previous_justified_checkpoint,
        current_justified_checkpoint=pre.current_justified_checkpoint,
        finalized_checkpoint=pre.finalized_checkpoint,
        inactivity_scores=pre.inactivity_scores,
        current_sync_committee=pre.current_sync_committee,
        next_sync_committee=pre.next_sync_committee,
        latest_execution_payload_header=pre.latest_execution_payload_header,
        next_withdrawal_index=pre.next_withdrawal_index,
        next_withdrawal_validator_index=pre.next_withdrawal_validator_index,
        historical_summaries=pre.historical_summaries,
        # [New in EIP7441]
        whisk_proposer_trackers=[WhiskTracker()
                                 for _ in range(PROPOSER_TRACKERS_COUNT)],
        whisk_candidate_trackers=[
            WhiskTracker() for _ in range(CANDIDATE_TRACKERS_COUNT)],
        whisk_trackers=whisk_trackers,
        whisk_k_commitments=whisk_k_commitments,
    )

    # Candidate selection with an old epoch (avoids reusing the next
    # selection's seed), proposer selection for the upcoming day, then a
    # final candidate round to shuffle over during the upcoming phase
    select_whisk_candidate_trackers(
        post, Epoch(max(int(epoch)
                        - int(config.PROPOSER_SELECTION_GAP) - 1, 0)))
    select_whisk_proposer_trackers(post, epoch)
    select_whisk_candidate_trackers(post, epoch)

    return post
