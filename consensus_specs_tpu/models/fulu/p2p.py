# Fulu -- p2p pure functions: data-column sidecar validation.
# Parity contract: specs/fulu/p2p-interface.md (:75-150).


NUMBER_OF_COLUMNS_LIMIT = int(config.NUMBER_OF_COLUMNS)


class DataColumnsByRootIdentifier(Container):
    block_root: Root
    columns: List[ColumnIndex, NUMBER_OF_COLUMNS_LIMIT]


def verify_data_column_sidecar(sidecar: DataColumnSidecar) -> bool:
    """Structural validity of a column sidecar."""
    # The sidecar index must be within the valid range
    if sidecar.index >= config.NUMBER_OF_COLUMNS:
        return False

    # A sidecar for zero blobs is invalid
    if len(sidecar.kzg_commitments) == 0:
        return False

    # Column length must equal the number of commitments/proofs
    if (len(sidecar.column) != len(sidecar.kzg_commitments)
            or len(sidecar.column) != len(sidecar.kzg_proofs)):
        return False

    return True


def verify_data_column_sidecar_kzg_proofs(sidecar: DataColumnSidecar) -> bool:
    """Batch-verify the column's cells against their commitments."""
    # The column index is also the cell index within each row
    cell_indices = [CellIndex(sidecar.index)] * len(sidecar.column)

    return verify_cell_kzg_proof_batch(
        commitments_bytes=sidecar.kzg_commitments,
        cell_indices=cell_indices,
        cells=sidecar.column,
        proofs_bytes=sidecar.kzg_proofs,
    )


def verify_data_column_sidecar_inclusion_proof(
        sidecar: DataColumnSidecar) -> bool:
    """Merkle proof that the commitment list is in the block body."""
    gindex = get_subtree_index(get_generalized_index(
        BeaconBlockBody, "blob_kzg_commitments"))
    return is_valid_merkle_branch(
        leaf=hash_tree_root(sidecar.kzg_commitments),
        branch=sidecar.kzg_commitments_inclusion_proof,
        depth=KZG_COMMITMENTS_INCLUSION_PROOF_DEPTH,
        index=gindex,
        root=sidecar.signed_block_header.message.body_root,
    )


def compute_subnet_for_data_column_sidecar(
        column_index: ColumnIndex) -> SubnetID:
    return SubnetID(column_index
                    % config.DATA_COLUMN_SIDECAR_SUBNET_COUNT)


# -- EIP-7892 digest plumbing: fulu redefines compute_fork_digest to take
# (genesis_validators_root, epoch) (fulu/p2p-interface.md :296,:551), so the
# digest-consuming p2p helpers re-bind to the new signature.


def compute_enr_fork_id(current_epoch: Epoch,
                        genesis_validators_root: Root) -> ENRForkID:
    fork_digest = compute_fork_digest(genesis_validators_root, current_epoch)
    next_version = compute_fork_version(current_epoch)
    next_epoch = FAR_FUTURE_EPOCH
    for name in ("ALTAIR", "BELLATRIX", "CAPELLA", "DENEB", "ELECTRA",
                 "FULU"):
        epoch = getattr(config, name + "_FORK_EPOCH", None)
        version = getattr(config, name + "_FORK_VERSION", None)
        if epoch is None or version is None:
            continue
        if current_epoch < epoch < next_epoch:
            next_epoch = epoch
            next_version = version
    return ENRForkID(
        fork_digest=fork_digest,
        next_fork_version=Version(next_version),
        next_fork_epoch=next_epoch,
    )


def compute_response_context(epoch: Epoch,
                             genesis_validators_root: Root) -> ForkDigest:
    return compute_fork_digest(genesis_validators_root, epoch)
