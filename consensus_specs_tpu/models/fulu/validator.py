# Fulu -- Honest Validator (executable spec source, delta).
# Parity contract: specs/fulu/validator.md (:60-300).


@dataclass
class BlobsBundle(object):
    commitments: Any
    proofs: Any  # cell proofs, CELLS_PER_EXT_BLOB per blob
    blobs: Any


@dataclass
class GetPayloadResponse(object):
    execution_payload: ExecutionPayload
    block_value: uint256
    blobs_bundle: BlobsBundle
    execution_requests: Sequence[bytes]


def get_validators_custody_requirement(state: BeaconState,
                                       validator_indices) -> uint64:
    """Custody-group requirement for a node by attached stake."""
    total_node_balance = sum(
        state.validators[index].effective_balance
        for index in validator_indices)
    count = total_node_balance // config.BALANCE_PER_ADDITIONAL_CUSTODY_GROUP
    return min(max(count, config.VALIDATOR_CUSTODY_REQUIREMENT),
               config.NUMBER_OF_CUSTODY_GROUPS)


def get_data_column_sidecars(signed_block_header, kzg_commitments,
                             kzg_commitments_inclusion_proof,
                             cells_and_kzg_proofs):
    """Assemble the per-column sidecars from each blob's cells/proofs."""
    assert len(cells_and_kzg_proofs) == len(kzg_commitments)

    sidecars = []
    for column_index in range(config.NUMBER_OF_COLUMNS):
        column_cells, column_proofs = [], []
        for cells, proofs in cells_and_kzg_proofs:
            column_cells.append(cells[column_index])
            column_proofs.append(proofs[column_index])
        sidecars.append(DataColumnSidecar(
            index=column_index,
            column=column_cells,
            kzg_commitments=kzg_commitments,
            kzg_proofs=column_proofs,
            signed_block_header=signed_block_header,
            kzg_commitments_inclusion_proof=kzg_commitments_inclusion_proof,
        ))
    return sidecars


def get_data_column_sidecars_from_block(signed_block, cells_and_kzg_proofs):
    """Sidecars straight from a signed block."""
    blob_kzg_commitments = signed_block.message.body.blob_kzg_commitments
    signed_block_header = compute_signed_block_header(signed_block)
    kzg_commitments_inclusion_proof = compute_merkle_proof_backing(
        signed_block.message.body,
        get_generalized_index(BeaconBlockBody, "blob_kzg_commitments"))
    return get_data_column_sidecars(
        signed_block_header, blob_kzg_commitments,
        kzg_commitments_inclusion_proof, cells_and_kzg_proofs)


def get_data_column_sidecars_from_column_sidecar(sidecar,
                                                 cells_and_kzg_proofs):
    """All sidecars from one received sidecar + recovered cells/proofs
    (distributed blob publishing)."""
    assert len(cells_and_kzg_proofs) == len(sidecar.kzg_commitments)

    return get_data_column_sidecars(
        sidecar.signed_block_header,
        sidecar.kzg_commitments,
        sidecar.kzg_commitments_inclusion_proof,
        cells_and_kzg_proofs)
