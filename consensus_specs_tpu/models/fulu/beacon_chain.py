# Fulu -- The Beacon Chain (executable spec source, delta over electra).
#
# EIP-7892 (blob-parameters-only forks via BLOB_SCHEDULE), EIP-7917
# (pre-computed proposer lookahead), EIP-7594 DAS plumbing.
# Parity contract: specs/fulu/beacon-chain.md.


class BeaconState(Container):
    genesis_time: uint64
    genesis_validators_root: Root
    slot: Slot
    fork: Fork
    latest_block_header: BeaconBlockHeader
    block_roots: Vector[Root, SLOTS_PER_HISTORICAL_ROOT]
    state_roots: Vector[Root, SLOTS_PER_HISTORICAL_ROOT]
    historical_roots: List[Root, HISTORICAL_ROOTS_LIMIT]
    eth1_data: Eth1Data
    eth1_data_votes: List[Eth1Data, EPOCHS_PER_ETH1_VOTING_PERIOD * SLOTS_PER_EPOCH]
    eth1_deposit_index: uint64
    validators: List[Validator, VALIDATOR_REGISTRY_LIMIT]
    balances: List[Gwei, VALIDATOR_REGISTRY_LIMIT]
    randao_mixes: Vector[Bytes32, EPOCHS_PER_HISTORICAL_VECTOR]
    slashings: Vector[Gwei, EPOCHS_PER_SLASHINGS_VECTOR]
    previous_epoch_participation: List[ParticipationFlags, VALIDATOR_REGISTRY_LIMIT]
    current_epoch_participation: List[ParticipationFlags, VALIDATOR_REGISTRY_LIMIT]
    justification_bits: Bitvector[JUSTIFICATION_BITS_LENGTH]
    previous_justified_checkpoint: Checkpoint
    current_justified_checkpoint: Checkpoint
    finalized_checkpoint: Checkpoint
    inactivity_scores: List[uint64, VALIDATOR_REGISTRY_LIMIT]
    current_sync_committee: SyncCommittee
    next_sync_committee: SyncCommittee
    latest_execution_payload_header: ExecutionPayloadHeader
    next_withdrawal_index: WithdrawalIndex
    next_withdrawal_validator_index: ValidatorIndex
    historical_summaries: List[HistoricalSummary, HISTORICAL_ROOTS_LIMIT]
    deposit_requests_start_index: uint64
    deposit_balance_to_consume: Gwei
    exit_balance_to_consume: Gwei
    earliest_exit_epoch: Epoch
    consolidation_balance_to_consume: Gwei
    earliest_consolidation_epoch: Epoch
    pending_deposits: List[PendingDeposit, PENDING_DEPOSITS_LIMIT]
    pending_partial_withdrawals: List[PendingPartialWithdrawal, PENDING_PARTIAL_WITHDRAWALS_LIMIT]
    pending_consolidations: List[PendingConsolidation, PENDING_CONSOLIDATIONS_LIMIT]
    # [New in Fulu:EIP7917]
    proposer_lookahead: Vector[ValidatorIndex, (MIN_SEED_LOOKAHEAD + 1) * SLOTS_PER_EPOCH]


# ---------------------------------------------------------------------------
# Misc helpers (beacon-chain.md :174-278)
# ---------------------------------------------------------------------------


@dataclass
class BlobParameters:
    epoch: Epoch
    max_blobs_per_block: uint64


def get_blob_parameters(epoch: Epoch) -> BlobParameters:
    """Blob parameters at `epoch` from the BPO schedule, defaulting to
    the electra values (EIP-7892)."""
    for entry in sorted(config.BLOB_SCHEDULE,
                        key=lambda e: e["EPOCH"], reverse=True):
        if epoch >= entry["EPOCH"]:
            return BlobParameters(entry["EPOCH"],
                                  entry["MAX_BLOBS_PER_BLOCK"])
    return BlobParameters(config.ELECTRA_FORK_EPOCH,
                          config.MAX_BLOBS_PER_BLOCK_ELECTRA)


def compute_fork_digest(genesis_validators_root: Root,
                        epoch: Epoch) -> ForkDigest:
    """Fork digest XOR'd with the blob-parameters hash so BPO-only forks
    separate on the p2p layer (EIP-7892)."""
    fork_version = compute_fork_version(epoch)
    base_digest = compute_fork_data_root(fork_version,
                                         genesis_validators_root)
    blob_parameters = get_blob_parameters(epoch)

    mask = hash(uint_to_bytes(uint64(blob_parameters.epoch))
                + uint_to_bytes(uint64(blob_parameters.max_blobs_per_block)))
    return ForkDigest(bytes(a ^ b for a, b in
                            zip(base_digest, mask))[:4])


def compute_proposer_indices(state: BeaconState, epoch: Epoch,
                             seed: Bytes32, indices):
    """Proposer indices for every slot of `epoch`."""
    start_slot = compute_start_slot_at_epoch(epoch)
    seeds = [hash(seed + uint_to_bytes(Slot(start_slot + i)))
             for i in range(SLOTS_PER_EPOCH)]
    return [compute_proposer_index(state, indices, s) for s in seeds]


def get_beacon_proposer_index(state: BeaconState) -> ValidatorIndex:
    """Proposer at the current slot, from the pre-computed lookahead."""
    return state.proposer_lookahead[state.slot % SLOTS_PER_EPOCH]


def get_beacon_proposer_indices(state: BeaconState, epoch: Epoch):
    """Proposer indices for the given `epoch`."""
    indices = get_active_validator_indices(state, epoch)
    seed = get_seed(state, epoch, DOMAIN_BEACON_PROPOSER)
    return compute_proposer_indices(state, epoch, seed, indices)


# ---------------------------------------------------------------------------
# Block processing (beacon-chain.md :56-113)
# ---------------------------------------------------------------------------


def process_execution_payload(state: BeaconState, body: BeaconBlockBody,
                              execution_engine: ExecutionEngine) -> None:
    payload = body.execution_payload

    assert payload.parent_hash == state.latest_execution_payload_header.block_hash
    assert payload.prev_randao == get_randao_mix(state, get_current_epoch(state))
    assert payload.timestamp == compute_time_at_slot(state, state.slot)
    # [Modified in Fulu:EIP7892] limit from the blob schedule
    assert (len(body.blob_kzg_commitments)
            <= get_blob_parameters(get_current_epoch(state)).max_blobs_per_block)
    versioned_hashes = [kzg_commitment_to_versioned_hash(commitment)
                        for commitment in body.blob_kzg_commitments]
    assert execution_engine.verify_and_notify_new_payload(
        NewPayloadRequest(
            execution_payload=payload,
            versioned_hashes=versioned_hashes,
            parent_beacon_block_root=state.latest_block_header.parent_root,
            execution_requests=body.execution_requests,
        ))
    state.latest_execution_payload_header = ExecutionPayloadHeader(
        parent_hash=payload.parent_hash,
        fee_recipient=payload.fee_recipient,
        state_root=payload.state_root,
        receipts_root=payload.receipts_root,
        logs_bloom=payload.logs_bloom,
        prev_randao=payload.prev_randao,
        block_number=payload.block_number,
        gas_limit=payload.gas_limit,
        gas_used=payload.gas_used,
        timestamp=payload.timestamp,
        extra_data=payload.extra_data,
        base_fee_per_gas=payload.base_fee_per_gas,
        block_hash=payload.block_hash,
        transactions_root=hash_tree_root(payload.transactions),
        withdrawals_root=hash_tree_root(payload.withdrawals),
        blob_gas_used=payload.blob_gas_used,
        excess_blob_gas=payload.excess_blob_gas,
    )


# ---------------------------------------------------------------------------
# Epoch processing (beacon-chain.md :279-330)
# ---------------------------------------------------------------------------


def process_epoch(state: BeaconState) -> None:
    process_justification_and_finalization(state)
    process_inactivity_updates(state)
    process_rewards_and_penalties(state)
    process_registry_updates(state)
    process_slashings(state)
    process_eth1_data_reset(state)
    process_pending_deposits(state)
    process_pending_consolidations(state)
    process_effective_balance_updates(state)
    process_slashings_reset(state)
    process_randao_mixes_reset(state)
    process_historical_summaries_update(state)
    process_participation_flag_updates(state)
    process_sync_committee_updates(state)
    process_proposer_lookahead(state)  # [New in Fulu:EIP7917]


def process_proposer_lookahead(state: BeaconState) -> None:
    """Shift the lookahead one epoch and append the newly-computable
    epoch's proposers (EIP-7917)."""
    last_epoch_start = len(state.proposer_lookahead) - SLOTS_PER_EPOCH
    # Shift out proposers in the first epoch
    state.proposer_lookahead[:last_epoch_start] = list(
        state.proposer_lookahead[SLOTS_PER_EPOCH:])
    # Fill in the last epoch with new proposer indices
    last_epoch_proposers = get_beacon_proposer_indices(
        state, Epoch(get_current_epoch(state) + MIN_SEED_LOOKAHEAD + 1))
    state.proposer_lookahead[last_epoch_start:] = last_epoch_proposers
