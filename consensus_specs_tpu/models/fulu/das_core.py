# Fulu -- Data Availability Sampling Core (PeerDAS).
# Parity contract: specs/fulu/das-core.md (types :48-58, containers
# :73-94, custody :100-133, matrix :135-185).

UINT256_MAX = uint256(2**256 - 1)


class RowIndex(uint64):
    pass


class ColumnIndex(uint64):
    pass


class CustodyIndex(uint64):
    pass


class DataColumnSidecar(Container):
    index: ColumnIndex
    column: List[Cell, MAX_BLOB_COMMITMENTS_PER_BLOCK]
    kzg_commitments: List[KZGCommitment, MAX_BLOB_COMMITMENTS_PER_BLOCK]
    kzg_proofs: List[KZGProof, MAX_BLOB_COMMITMENTS_PER_BLOCK]
    signed_block_header: SignedBeaconBlockHeader
    kzg_commitments_inclusion_proof: Vector[Bytes32, KZG_COMMITMENTS_INCLUSION_PROOF_DEPTH]


class MatrixEntry(Container):
    cell: Cell
    kzg_proof: KZGProof
    column_index: ColumnIndex
    row_index: RowIndex


def get_custody_groups(node_id: NodeID,
                       custody_group_count: uint64) -> Sequence[CustodyIndex]:
    """Deterministic public custody-group selection by node id; extending
    `custody_group_count` extends (not reshuffles) the list."""
    assert custody_group_count <= config.NUMBER_OF_CUSTODY_GROUPS

    current_id = uint256(node_id)
    custody_groups = []
    while len(custody_groups) < custody_group_count:
        custody_group = CustodyIndex(
            bytes_to_uint64(hash(uint_to_bytes(current_id))[0:8])
            % config.NUMBER_OF_CUSTODY_GROUPS)
        if custody_group not in custody_groups:
            custody_groups.append(custody_group)
        if current_id == UINT256_MAX:
            # Overflow prevention
            current_id = uint256(0)
        else:
            current_id = uint256(current_id + 1)

    assert len(custody_groups) == len(set(custody_groups))
    return sorted(custody_groups)


def compute_columns_for_custody_group(
        custody_group: CustodyIndex) -> Sequence[ColumnIndex]:
    assert custody_group < config.NUMBER_OF_CUSTODY_GROUPS
    columns_per_group = (config.NUMBER_OF_COLUMNS
                         // config.NUMBER_OF_CUSTODY_GROUPS)
    return [
        ColumnIndex(config.NUMBER_OF_CUSTODY_GROUPS * i + custody_group)
        for i in range(columns_per_group)
    ]


def compute_matrix(blobs) -> Sequence[MatrixEntry]:
    """Full flattened matrix of cells/proofs (rows = blobs, columns =
    cells of the extension)."""
    matrix = []
    for blob_index, blob in enumerate(blobs):
        cells, proofs = compute_cells_and_kzg_proofs(blob)
        for cell_index, (cell, proof) in enumerate(zip(cells, proofs)):
            matrix.append(MatrixEntry(
                cell=cell,
                kzg_proof=proof,
                row_index=blob_index,
                column_index=cell_index,
            ))
    return matrix


def recover_matrix(partial_matrix, blob_count: uint64) -> Sequence[MatrixEntry]:
    """Recover the full matrix from >= 50% of each row's cells."""
    matrix = []
    for blob_index in range(blob_count):
        cell_indices = [e.column_index for e in partial_matrix
                        if e.row_index == blob_index]
        cells = [e.cell for e in partial_matrix
                 if e.row_index == blob_index]
        recovered_cells, recovered_proofs = recover_cells_and_kzg_proofs(
            cell_indices, cells)
        for cell_index, (cell, proof) in enumerate(
                zip(recovered_cells, recovered_proofs)):
            matrix.append(MatrixEntry(
                cell=cell,
                kzg_proof=proof,
                row_index=blob_index,
                column_index=cell_index,
            ))
    return matrix
