# Fulu -- Fork Choice (executable spec source, delta over deneb).
# DA check via column sidecars (EIP-7594).
# Parity contract: specs/fulu/fork-choice.md.


def retrieve_column_sidecars(beacon_block_root: Root):
    """Stub replacing `retrieve_blobs_and_proofs`; tests monkeypatch
    (`pysetup/spec_builders/fulu.py` sundry)."""
    return []


def is_data_available(beacon_block_root: Root) -> bool:
    """Sample custody columns for the block; True iff every retrieved
    sidecar is structurally valid with correct KZG proofs."""
    column_sidecars = retrieve_column_sidecars(beacon_block_root)
    return all(
        verify_data_column_sidecar(column_sidecar)
        and verify_data_column_sidecar_kzg_proofs(column_sidecar)
        for column_sidecar in column_sidecars
    )


def on_block(store: Store, signed_block: SignedBeaconBlock) -> None:
    """deneb on_block with the column-sampling DA gate
    (fork-choice.md :46-97)."""
    block = signed_block.message
    # Parent must be known
    assert block.parent_root in store.block_states
    state = copy(store.block_states[block.parent_root])
    # Future blocks wait until their slot arrives
    assert get_current_slot(store) >= block.slot

    # Must descend from (and be after) the finalized checkpoint
    finalized_slot = compute_start_slot_at_epoch(
        store.finalized_checkpoint.epoch)
    assert block.slot > finalized_slot
    finalized_checkpoint_block = get_checkpoint_block(
        store, block.parent_root, store.finalized_checkpoint.epoch)
    assert store.finalized_checkpoint.root == finalized_checkpoint_block

    # [Modified in Fulu:EIP7594]
    assert is_data_available(hash_tree_root(block))

    # Full state transition (asserts internally on invalid blocks)
    block_root = hash_tree_root(block)
    state_transition(state, signed_block, True)

    store.blocks[block_root] = block
    store.block_states[block_root] = state

    # Timeliness: arrived in its own slot, before the attesting interval
    time_into_slot = ((store.time - store.genesis_time)
                      % config.SECONDS_PER_SLOT)
    is_before_attesting_interval = (
        time_into_slot < config.SECONDS_PER_SLOT // INTERVALS_PER_SLOT)
    is_timely = (get_current_slot(store) == block.slot
                 and is_before_attesting_interval)
    store.block_timeliness[block_root] = is_timely

    # Boost the first timely block of the slot
    if is_timely and store.proposer_boost_root == Root():
        store.proposer_boost_root = block_root

    update_checkpoints(store, state.current_justified_checkpoint,
                       state.finalized_checkpoint)
    compute_pulled_up_tip(store, block_root)
