# Fulu -- Polynomial Commitments Sampling (DAS KZG extension).
#
# Coefficient-form KZG: cell cosets, multi-evaluation proofs, the
# universal batch-verification equation, and FFT-based erasure recovery.
# Parity contract: specs/fulu/polynomial-commitments-sampling.md
# (types :73-103, FFTs :137-243, coefficient polynomials :245-363,
#  multiproofs :365-509, cosets :511-551, cells :553-668,
#  reconstruction :670-817).

# ---------------------------------------------------------------------------
# Types + preset (sampling.md :73-103)
# ---------------------------------------------------------------------------

FIELD_ELEMENTS_PER_EXT_BLOB = 2 * FIELD_ELEMENTS_PER_BLOB
FIELD_ELEMENTS_PER_CELL = uint64(64)
BYTES_PER_CELL = FIELD_ELEMENTS_PER_CELL * BYTES_PER_FIELD_ELEMENT
CELLS_PER_EXT_BLOB = FIELD_ELEMENTS_PER_EXT_BLOB // FIELD_ELEMENTS_PER_CELL
RANDOM_CHALLENGE_KZG_CELL_BATCH_DOMAIN = b"RCKZGCBATCH__V1_"

Cell = ByteVector[BYTES_PER_FIELD_ELEMENT * FIELD_ELEMENTS_PER_CELL]


class CellIndex(uint64):
    pass


class CommitmentIndex(uint64):
    pass


class PolynomialCoeff(PyList):
    """A polynomial in coefficient form (bounded by the extended blob)."""

    def __init__(self, coeffs=()):
        assert len(coeffs) <= FIELD_ELEMENTS_PER_EXT_BLOB
        super().__init__(coeffs)


class Coset(PyList):
    """The evaluation domain of a cell."""

    def __init__(self, evals=None):
        if evals is None:
            evals = [BLSFieldElement(0)] * FIELD_ELEMENTS_PER_CELL
        assert len(evals) == FIELD_ELEMENTS_PER_CELL
        super().__init__(evals)


class CosetEvals(PyList):
    """A cell's evaluations over its coset."""

    def __init__(self, evals=None):
        if evals is None:
            evals = [BLSFieldElement(0)] * FIELD_ELEMENTS_PER_CELL
        assert len(evals) == FIELD_ELEMENTS_PER_CELL
        super().__init__(evals)


# ---------------------------------------------------------------------------
# BLS helpers (sampling.md :107-135)
# ---------------------------------------------------------------------------


def cell_to_coset_evals(cell: Cell) -> CosetEvals:
    """Convert an untrusted ``Cell`` into a trusted ``CosetEvals``."""
    evals = CosetEvals()
    for i in range(FIELD_ELEMENTS_PER_CELL):
        start = i * BYTES_PER_FIELD_ELEMENT
        end = (i + 1) * BYTES_PER_FIELD_ELEMENT
        evals[i] = bytes_to_bls_field(cell[start:end])
    return evals


def coset_evals_to_cell(coset_evals: CosetEvals) -> Cell:
    """Convert a trusted ``CosetEvals`` into an untrusted ``Cell``."""
    cell = []
    for i in range(FIELD_ELEMENTS_PER_CELL):
        cell += bls_field_to_bytes(coset_evals[i])
    return Cell(cell)


# ---------------------------------------------------------------------------
# FFTs (sampling.md :137-243)
# ---------------------------------------------------------------------------


def _fft_field(vals, roots_of_unity):
    if len(vals) == 1:
        return vals
    L = _fft_field(vals[::2], roots_of_unity[::2])
    R = _fft_field(vals[1::2], roots_of_unity[::2])
    o = [BLSFieldElement(0) for _ in vals]
    for i, (x, y) in enumerate(zip(L, R)):
        y_times_root = y * roots_of_unity[i]
        o[i] = x + y_times_root
        o[i + len(L)] = x - y_times_root
    return o


def fft_field(vals, roots_of_unity, inv: bool = False):
    if inv:
        # Inverse FFT
        invlen = BLSFieldElement(len(vals)).pow(
            BLSFieldElement(BLS_MODULUS - 2))
        return [x * invlen for x in _fft_field(
            vals, list(roots_of_unity[0:1]) + list(roots_of_unity[:0:-1]))]
    else:
        # Regular FFT
        return _fft_field(vals, roots_of_unity)


def coset_fft_field(vals, roots_of_unity, inv: bool = False):
    """FFT/IFFT over a coset of the roots of unity — used to divide by a
    polynomial that vanishes inside the domain."""
    vals = [v for v in vals]  # copy

    def shift_vals(vals, factor):
        # [vals[0]*factor^0, vals[1]*factor^1, ...]
        updated_vals = []
        shift = BLSFieldElement(1)
        for i in range(len(vals)):
            updated_vals.append(vals[i] * shift)
            shift = shift * factor
        return updated_vals

    # the coset generator
    shift_factor = BLSFieldElement(PRIMITIVE_ROOT_OF_UNITY)
    if inv:
        vals = fft_field(vals, roots_of_unity, inv)
        return shift_vals(vals, shift_factor.inverse())
    else:
        vals = shift_vals(vals, shift_factor)
        return fft_field(vals, roots_of_unity, inv)


def compute_verify_cell_kzg_proof_batch_challenge(
        commitments, commitment_indices, cell_indices, cosets_evals,
        proofs) -> BLSFieldElement:
    """Fiat-Shamir challenge over everything influencing verification."""
    hashinput = RANDOM_CHALLENGE_KZG_CELL_BATCH_DOMAIN
    hashinput += int.to_bytes(FIELD_ELEMENTS_PER_BLOB, 8, KZG_ENDIANNESS)
    hashinput += int.to_bytes(FIELD_ELEMENTS_PER_CELL, 8, KZG_ENDIANNESS)
    hashinput += int.to_bytes(len(commitments), 8, KZG_ENDIANNESS)
    hashinput += int.to_bytes(len(cell_indices), 8, KZG_ENDIANNESS)
    for commitment in commitments:
        hashinput += commitment
    for k, coset_evals in enumerate(cosets_evals):
        hashinput += int.to_bytes(commitment_indices[k], 8, KZG_ENDIANNESS)
        hashinput += int.to_bytes(cell_indices[k], 8, KZG_ENDIANNESS)
        for coset_eval in coset_evals:
            hashinput += bls_field_to_bytes(coset_eval)
        hashinput += proofs[k]
    return hash_to_bls_field(hashinput)


# ---------------------------------------------------------------------------
# Polynomials in coefficient form (sampling.md :245-363)
# ---------------------------------------------------------------------------


def polynomial_eval_to_coeff(polynomial: Polynomial) -> PolynomialCoeff:
    """Interpolate an evaluation-form polynomial to coefficient form."""
    roots_of_unity = compute_roots_of_unity(FIELD_ELEMENTS_PER_BLOB)
    return PolynomialCoeff(fft_field(
        bit_reversal_permutation(polynomial), roots_of_unity, inv=True))


def add_polynomialcoeff(a: PolynomialCoeff,
                        b: PolynomialCoeff) -> PolynomialCoeff:
    """Sum of two coefficient-form polynomials."""
    a, b = (a, b) if len(a) >= len(b) else (b, a)
    length_a, length_b = len(a), len(b)
    return PolynomialCoeff([
        a[i] + (b[i] if i < length_b else BLSFieldElement(0))
        for i in range(length_a)
    ])


def multiply_polynomialcoeff(a: PolynomialCoeff,
                             b: PolynomialCoeff) -> PolynomialCoeff:
    """Product of two coefficient-form polynomials."""
    assert len(a) + len(b) <= FIELD_ELEMENTS_PER_EXT_BLOB

    r = PolynomialCoeff([BLSFieldElement(0)])
    for power, coef in enumerate(a):
        summand = PolynomialCoeff(
            [BLSFieldElement(0)] * power + [coef * x for x in b])
        r = add_polynomialcoeff(r, summand)
    return r


def divide_polynomialcoeff(a: PolynomialCoeff,
                           b: PolynomialCoeff) -> PolynomialCoeff:
    """Long polynomial division."""
    a = PolynomialCoeff(a[:])  # copy
    o = PolynomialCoeff([])
    apos = len(a) - 1
    bpos = len(b) - 1
    diff = apos - bpos
    while diff >= 0:
        quot = a[apos] / b[bpos]
        o.insert(0, quot)
        for i in range(bpos, -1, -1):
            a[diff + i] = a[diff + i] - b[i] * quot
        apos -= 1
        diff -= 1
    return o


def interpolate_polynomialcoeff(xs, ys) -> PolynomialCoeff:
    """Lagrange interpolation in coefficient form; leading coefficients
    may be zero."""
    assert len(xs) == len(ys)

    r = PolynomialCoeff([BLSFieldElement(0)])
    for i in range(len(xs)):
        summand = PolynomialCoeff([ys[i]])
        for j in range(len(ys)):
            if j != i:
                weight_adjustment = (xs[i] - xs[j]).inverse()
                summand = multiply_polynomialcoeff(
                    summand,
                    PolynomialCoeff([-weight_adjustment * xs[j],
                                     weight_adjustment]))
        r = add_polynomialcoeff(r, summand)
    return r


def vanishing_polynomialcoeff(xs) -> PolynomialCoeff:
    """The vanishing polynomial on ``xs`` (coefficient form)."""
    p = PolynomialCoeff([BLSFieldElement(1)])
    for x in xs:
        p = multiply_polynomialcoeff(
            p, PolynomialCoeff([-x, BLSFieldElement(1)]))
    return p


def evaluate_polynomialcoeff(polynomial_coeff: PolynomialCoeff,
                             z: BLSFieldElement) -> BLSFieldElement:
    """Horner evaluation at ``z``."""
    y = BLSFieldElement(0)
    for coef in polynomial_coeff[::-1]:
        y = y * z + coef
    return y


# ---------------------------------------------------------------------------
# KZG multiproofs (sampling.md :365-509)
# ---------------------------------------------------------------------------


def compute_kzg_proof_multi_impl(polynomial_coeff: PolynomialCoeff,
                                 zs: Coset):
    """Multi-evaluation proof over `k` points: commit to
    Q(X) = f(X) / Z(X) (I(X) vanishes in the monomial quotient since
    deg I < deg Z)."""
    # Evaluations at all the points
    ys = CosetEvals([evaluate_polynomialcoeff(polynomial_coeff, z)
                     for z in zs])

    # Compute Z(X)
    denominator_poly = vanishing_polynomialcoeff(zs)

    # Quotient directly in monomial form
    quotient_polynomial = divide_polynomialcoeff(polynomial_coeff,
                                                 denominator_poly)

    return KZGProof(g1_lincomb(
        KZG_SETUP_G1_MONOMIAL[:len(quotient_polynomial)],
        quotient_polynomial)), ys


def verify_cell_kzg_proof_batch_impl(commitments, commitment_indices,
                                     cell_indices, cosets_evals,
                                     proofs) -> bool:
    """The universal verification equation
    pairing(LL, LR) == pairing(RL, [1]) with
    LL = sum_k r^k proofs[k]; LR = [s^n];
    RL = RLC - RLI + RLP (sampling.md :405-509)."""
    assert (len(commitment_indices) == len(cell_indices)
            == len(cosets_evals) == len(proofs))
    assert len(commitments) == len(set(commitments))
    for commitment_index in commitment_indices:
        assert commitment_index < len(commitments)

    # Preparation
    num_cells = len(cell_indices)
    n = FIELD_ELEMENTS_PER_CELL
    num_commitments = len(commitments)

    # Challenge r and its powers
    r = compute_verify_cell_kzg_proof_batch_challenge(
        commitments, commitment_indices, cell_indices, cosets_evals, proofs)
    r_powers = compute_powers(r, num_cells)

    # LL = sum_k r^k proofs[k]
    ll = bls.bytes48_to_G1(g1_lincomb(proofs, r_powers))

    # LR = [s^n]
    lr = bls.bytes96_to_G2(KZG_SETUP_G2_MONOMIAL[n])

    # RLC = sum_i weights[i] commitments[i], where weights[i] folds the
    # r^k of every cell attached to commitment i
    weights = [BLSFieldElement(0)] * num_commitments
    for k in range(num_cells):
        i = commitment_indices[k]
        weights[i] += r_powers[k]
    rlc = bls.bytes48_to_G1(g1_lincomb(commitments, weights))

    # RLI = [sum_k r^k interpolation_poly_k(s)]
    sum_interp_polys_coeff = PolynomialCoeff([BLSFieldElement(0)] * n)
    for k in range(num_cells):
        interp_poly_coeff = interpolate_polynomialcoeff(
            coset_for_cell(cell_indices[k]), cosets_evals[k])
        interp_poly_scaled_coeff = multiply_polynomialcoeff(
            PolynomialCoeff([r_powers[k]]), interp_poly_coeff)
        sum_interp_polys_coeff = add_polynomialcoeff(
            sum_interp_polys_coeff, interp_poly_scaled_coeff)
    rli = bls.bytes48_to_G1(g1_lincomb(
        KZG_SETUP_G1_MONOMIAL[:n], sum_interp_polys_coeff))

    # RLP = sum_k (r^k * h_k^n) proofs[k]
    weighted_r_powers = []
    for k in range(num_cells):
        h_k = coset_shift_for_cell(cell_indices[k])
        h_k_pow = h_k.pow(BLSFieldElement(n))
        wrp = r_powers[k] * h_k_pow
        weighted_r_powers.append(wrp)
    rlp = bls.bytes48_to_G1(g1_lincomb(proofs, weighted_r_powers))

    # RL = RLC - RLI + RLP
    rl = bls.add(rlc, bls.neg(rli))
    rl = bls.add(rl, rlp)

    # pairing (LL, LR) == pairing (RL, [1])
    return bls.pairing_check([
        [ll, lr],
        [rl, bls.neg(bls.bytes96_to_G2(KZG_SETUP_G2_MONOMIAL[0]))],
    ])


# ---------------------------------------------------------------------------
# Cell cosets (sampling.md :511-551)
# ---------------------------------------------------------------------------


def coset_shift_for_cell(cell_index: CellIndex) -> BLSFieldElement:
    """The shift h defining cell `cell_index`'s coset h*G, where G is the
    order-FIELD_ELEMENTS_PER_CELL subgroup."""
    assert cell_index < CELLS_PER_EXT_BLOB
    roots_of_unity_brp = bit_reversal_permutation(
        compute_roots_of_unity(FIELD_ELEMENTS_PER_EXT_BLOB))
    return roots_of_unity_brp[FIELD_ELEMENTS_PER_CELL * cell_index]


def coset_for_cell(cell_index: CellIndex) -> Coset:
    """The coset h*G for cell `cell_index`."""
    assert cell_index < CELLS_PER_EXT_BLOB
    roots_of_unity_brp = bit_reversal_permutation(
        compute_roots_of_unity(FIELD_ELEMENTS_PER_EXT_BLOB))
    return Coset(roots_of_unity_brp[
        FIELD_ELEMENTS_PER_CELL * cell_index:
        FIELD_ELEMENTS_PER_CELL * (cell_index + 1)])


# ---------------------------------------------------------------------------
# Cells (sampling.md :553-668)
# ---------------------------------------------------------------------------


def compute_cells(blob: Blob):
    """Extend a blob and return all cells of the extension.
    Public method.

    The normative definition (sampling.md:560-576) evaluates the
    coefficient form at every coset point individually — O(n^2).  Every
    cell coset is a contiguous slice of the bit-reversed extended
    domain (`coset_for_cell`), so one size-2n FFT followed by the
    bit-reversal permutation produces the identical evaluations; pinned
    against the naive evaluator in
    tests/fulu/unittests/test_polynomial_commitments.py."""
    assert len(blob) == BYTES_PER_BLOB

    polynomial = blob_to_polynomial(blob)
    polynomial_coeff = polynomial_eval_to_coeff(polynomial)

    padded = list(polynomial_coeff) + [BLSFieldElement(0)] * (
        int(FIELD_ELEMENTS_PER_EXT_BLOB) - len(polynomial_coeff))
    extended = fft_field(
        padded, compute_roots_of_unity(FIELD_ELEMENTS_PER_EXT_BLOB))
    extended_brp = bit_reversal_permutation(extended)

    n = int(FIELD_ELEMENTS_PER_CELL)
    return [
        coset_evals_to_cell(CosetEvals(extended_brp[i * n:(i + 1) * n]))
        for i in range(CELLS_PER_EXT_BLOB)
    ]


def compute_cells_and_kzg_proofs_polynomialcoeff(
        polynomial_coeff: PolynomialCoeff):
    """Cells + proofs for a coefficient-form polynomial."""
    cells, proofs = [], []
    for i in range(CELLS_PER_EXT_BLOB):
        coset = coset_for_cell(CellIndex(i))
        proof, ys = compute_kzg_proof_multi_impl(polynomial_coeff, coset)
        cells.append(coset_evals_to_cell(CosetEvals(ys)))
        proofs.append(proof)
    return cells, proofs


def compute_cells_and_kzg_proofs(blob: Blob):
    """All cell proofs for an extended blob (naive O(n^2); FK20 is the
    performant path).  Public method.

    Device routing (the DAS subsystem, `consensus_specs_tpu/das/`):
    under the jax backend with real BLS active, the residue-grouped
    quotient route computes the identical cells and proofs — the
    per-cell long division disappears and every MSM dispatches to the
    Pippenger kernel (bit-exact, pinned by tests/test_das.py)."""
    assert len(blob) == BYTES_PER_BLOB

    if bls.backend_name() == "jax" and bls.bls_active:
        from consensus_specs_tpu.das import compute as _das_compute

        cells, proofs = _das_compute.compute_cells_and_kzg_proofs(
            bytes(blob))
        return ([Cell(c) for c in cells],
                [KZGProof(p) for p in proofs])

    polynomial = blob_to_polynomial(blob)
    polynomial_coeff = polynomial_eval_to_coeff(polynomial)
    return compute_cells_and_kzg_proofs_polynomialcoeff(polynomial_coeff)


def verify_cell_kzg_proof_batch(commitments_bytes, cell_indices, cells,
                                proofs_bytes) -> bool:
    """Verify (commitment, cell_index, cell, proof) tuples via the
    universal verification equation.  Public method.

    Device routing (the DAS subsystem): under the jax backend with
    real BLS active, the whole batch verifies on the device path —
    one `fr_batch` coset-interpolation dispatch for the RLI scalars,
    Pippenger MSMs for every point combination, one shared-accumulator
    multi-pairing — accept/reject identical to the oracle below
    (malformed input raises on both routes)."""
    if bls.backend_name() == "jax" and bls.bls_active:
        from consensus_specs_tpu.das import verify as _das_verify

        return _das_verify.verify_cell_proof_batch(
            commitments_bytes, cell_indices, cells, proofs_bytes,
            device=True)

    assert (len(commitments_bytes) == len(cells) == len(proofs_bytes)
            == len(cell_indices))
    for commitment_bytes in commitments_bytes:
        assert len(commitment_bytes) == BYTES_PER_COMMITMENT
    for cell_index in cell_indices:
        assert cell_index < CELLS_PER_EXT_BLOB
    for cell in cells:
        assert len(cell) == BYTES_PER_CELL
    for proof_bytes in proofs_bytes:
        assert len(proof_bytes) == BYTES_PER_PROOF

    # Deduplicated commitment list...
    deduplicated_commitments = [
        bytes_to_kzg_commitment(commitment_bytes)
        for commitment_bytes in set(commitments_bytes)
    ]
    # ...and the index mapping into it
    commitment_indices = [
        CommitmentIndex(deduplicated_commitments.index(commitment_bytes))
        for commitment_bytes in commitments_bytes
    ]

    cosets_evals = [cell_to_coset_evals(cell) for cell in cells]
    proofs = [bytes_to_kzg_proof(proof_bytes)
              for proof_bytes in proofs_bytes]

    return verify_cell_kzg_proof_batch_impl(
        deduplicated_commitments, commitment_indices, cell_indices,
        cosets_evals, proofs)


# ---------------------------------------------------------------------------
# Reconstruction (sampling.md :670-817)
# ---------------------------------------------------------------------------


def construct_vanishing_polynomial(missing_cell_indices):
    """Vanishing polynomial over every missing field element, built from
    the short per-cell vanishing polynomial via the closed form over a
    coset (assumes not all cells are missing)."""
    # The small domain
    roots_of_unity_reduced = compute_roots_of_unity(CELLS_PER_EXT_BLOB)

    # Vanishing polynomial over the small domain
    short_zero_poly = vanishing_polynomialcoeff([
        roots_of_unity_reduced[reverse_bits(missing_cell_index,
                                            CELLS_PER_EXT_BLOB)]
        for missing_cell_index in missing_cell_indices
    ])

    # Extend to the full domain
    zero_poly_coeff = [BLSFieldElement(0)] * FIELD_ELEMENTS_PER_EXT_BLOB
    for i, coeff in enumerate(short_zero_poly):
        zero_poly_coeff[i * FIELD_ELEMENTS_PER_CELL] = coeff

    return zero_poly_coeff


def recover_polynomialcoeff(cell_indices, cosets_evals) -> PolynomialCoeff:
    """Recover the coefficient-form polynomial whose evaluations give the
    extended blob (Reed-Solomon recovery via FFTs)."""
    # The FFT domain
    roots_of_unity_extended = compute_roots_of_unity(
        FIELD_ELEMENTS_PER_EXT_BLOB)

    # Flatten the evaluations; missing cells evaluate to zero
    extended_evaluation_rbo = ([BLSFieldElement(0)]
                               * FIELD_ELEMENTS_PER_EXT_BLOB)
    for cell_index, cell in zip(cell_indices, cosets_evals):
        start = cell_index * FIELD_ELEMENTS_PER_CELL
        end = (cell_index + 1) * FIELD_ELEMENTS_PER_CELL
        extended_evaluation_rbo[start:end] = cell
    extended_evaluation = bit_reversal_permutation(extended_evaluation_rbo)

    # Z(x): vanishes on all missing evaluations
    missing_cell_indices = [
        CellIndex(cell_index) for cell_index in range(CELLS_PER_EXT_BLOB)
        if cell_index not in cell_indices
    ]
    zero_poly_coeff = construct_vanishing_polynomial(missing_cell_indices)

    # Z(x) in evaluation form over the FFT domain
    zero_poly_eval = fft_field(zero_poly_coeff, roots_of_unity_extended)

    # (E*Z)(x) in evaluation form — agrees with (P*Z)(x) on the domain
    extended_evaluation_times_zero = [
        a * b for a, b in zip(zero_poly_eval, extended_evaluation)]

    # IFFT gives the coefficients of (P*Z)(x)
    extended_evaluation_times_zero_coeffs = fft_field(
        extended_evaluation_times_zero, roots_of_unity_extended, inv=True)

    # Divide (P*Z)(x) / Z(x) in evaluation form over a coset (no zeros)
    extended_evaluations_over_coset = coset_fft_field(
        extended_evaluation_times_zero_coeffs, roots_of_unity_extended)
    zero_poly_over_coset = coset_fft_field(zero_poly_coeff,
                                           roots_of_unity_extended)
    reconstructed_poly_over_coset = [
        a / b for a, b in zip(extended_evaluations_over_coset,
                              zero_poly_over_coset)]

    # Back to coefficient form
    reconstructed_poly_coeff = coset_fft_field(
        reconstructed_poly_over_coset, roots_of_unity_extended, inv=True)

    return PolynomialCoeff(reconstructed_poly_coeff[:FIELD_ELEMENTS_PER_BLOB])


def recover_cells_and_kzg_proofs(cell_indices, cells):
    """Given >= 50% of a blob's cells, recover all cells and proofs.
    Public method.

    Device routing (the DAS subsystem): under the jax backend with real
    BLS active, `das/recover.py` runs the coset-structured decode as
    device field-FFT dispatches and re-proves through the FK20 producer
    — byte-identical cells and proofs, same AssertionError contract on
    malformed input (pinned by tests/test_das.py and the kzg_7594
    recover vectors)."""
    if bls.backend_name() == "jax" and bls.bls_active:
        from consensus_specs_tpu.das import recover as _das_recover

        out_cells, out_proofs = _das_recover.recover_cells_and_kzg_proofs(
            [int(k) for k in cell_indices], [bytes(c) for c in cells])
        return ([Cell(c) for c in out_cells],
                [KZGProof(p) for p in out_proofs])

    # Same number of cells and indices
    assert len(cell_indices) == len(cells)
    # Enough cells to reconstruct
    assert CELLS_PER_EXT_BLOB // 2 <= len(cell_indices) <= CELLS_PER_EXT_BLOB
    # No duplicates
    assert len(cell_indices) == len(set(cell_indices))
    # Indices in bounds
    for cell_index in cell_indices:
        assert cell_index < CELLS_PER_EXT_BLOB
    # Cells correctly sized
    for cell in cells:
        assert len(cell) == BYTES_PER_CELL

    # Convert cells to coset evaluations
    cosets_evals = [cell_to_coset_evals(cell) for cell in cells]

    # Recover the polynomial in coefficient form
    polynomial_coeff = recover_polynomialcoeff(cell_indices, cosets_evals)

    # Recompute all cells/proofs
    return compute_cells_and_kzg_proofs_polynomialcoeff(polynomial_coeff)
