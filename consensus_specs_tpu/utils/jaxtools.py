"""Small JAX process-setup helpers shared by the entry points."""

from __future__ import annotations

from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent.parent


def enable_compile_cache(cache_dir: Path | None = None) -> None:
    """Point JAX's persistent compilation cache at `.jax_cache/` so repeated
    bench / driver runs on one machine pay the XLA compile once.  Failure is
    never fatal — the cache is an optimization."""
    import jax

    try:
        d = cache_dir or (REPO_ROOT / ".jax_cache")
        d.mkdir(exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(d))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass


def backends_initialized() -> bool:
    """True once any PJRT backend exists.  Must never *trigger* backend
    initialization: on this image the default platform is a pooled TPU whose
    claim can take minutes, so probing via `jax.devices()` is itself the
    multi-minute stall this predicate exists to avoid."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:
        return False
