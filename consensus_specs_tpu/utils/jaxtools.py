"""Small JAX process-setup helpers shared by the entry points."""

from __future__ import annotations

from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent.parent


def host_cache_key() -> str:
    """Host+platform fingerprint for the compile-cache directory.  XLA:CPU
    AOT results are machine-feature sensitive, and this repo moves between
    machines (driver vs dev box): a shared flat cache demonstrably loaded
    cross-machine entries (round-4 multichip log was full of 'machine
    features ... doesn't match' warnings), and a poisoned entry can break a
    later TPU compile.  Keying the directory by machine/cpu-count/platform
    pin makes stale cross-host reuse structurally impossible."""
    import hashlib
    import os
    import platform

    plat = os.environ.get("JAX_PLATFORMS", "default") or "default"
    # machine()/cpu_count alone cannot distinguish two x86_64 hosts with
    # different ISA extensions — hash the kernel's CPU feature flags too
    feats = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    feats = hashlib.sha256(
                        line.encode()).hexdigest()[:12]
                    break
    except OSError:
        pass
    return f"{platform.machine()}-{os.cpu_count()}cpu-{feats}-{plat}"


def enable_compile_cache(cache_dir: Path | None = None) -> None:
    """Point JAX's persistent compilation cache at a host-keyed subdir of
    `.jax_cache/` so repeated bench / driver runs on one machine pay the
    XLA compile once.  Failure is never fatal — the cache is an
    optimization.  Set CST_NO_COMPILE_CACHE=1 to disable entirely (bench
    retry path uses this to rule out cache poisoning).

    Telemetry records the chosen directory and its entry count at setup;
    cache HITS are not observable through jax's config API, so they are
    inferred downstream from first-call latency (a hit makes the
    `kernel.compile_first_s` sample collapse toward `kernel.run_s` —
    see the README's telemetry notes)."""
    import os

    from .. import telemetry

    import jax

    if os.environ.get("CST_NO_COMPILE_CACHE"):
        telemetry.set_meta("compile_cache.dir", None)
        return
    try:
        d = cache_dir or (REPO_ROOT / ".jax_cache" / host_cache_key())
        d.mkdir(parents=True, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(d))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # XLA:CPU AOT kernel caches are machine-feature sensitive beyond
        # what /proc/cpuinfo exposes (e.g. +prefer-no-scatter target
        # tuning): excluding them keeps cached entries loadable across
        # toolchain tweaks and silences the cpu_aot_loader SIGILL-hazard
        # warnings the round-4 multichip log was full of
        jax.config.update("jax_persistent_cache_enable_xla_caches",
                          "none")
        if telemetry.enabled():
            telemetry.set_meta("compile_cache.dir", str(d))
            telemetry.set_meta("compile_cache.entries_at_start",
                               sum(1 for p in d.iterdir() if p.is_file()))
    except Exception:
        pass


def backends_initialized() -> bool:
    """True once any PJRT backend exists.  Must never *trigger* backend
    initialization: on this image the default platform is a pooled TPU whose
    claim can take minutes, so probing via `jax.devices()` is itself the
    multi-minute stall this predicate exists to avoid."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:
        return False


def shard_map_compat(f, mesh, in_specs, out_specs):
    """`jax.shard_map` across jax versions: the stable API (jax >= 0.6,
    `check_vma`) when present, `jax.experimental.shard_map` (`check_rep`)
    on older builds like this image's 0.4.x.  Replication checking is
    disabled either way — the sharded kernels replicate reductions by
    explicit all_gathers."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)
