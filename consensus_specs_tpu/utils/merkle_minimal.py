"""Standalone padded Merkle tree + proofs — the independent oracle.

Deliberately naive (per-node hashlib recursion) so it cross-checks the
batched kernels and the SSZ engine from a totally different code path.
Mirrors the role of the reference's `eth2spec/utils/merkle_minimal.py:39-91`.
"""

from .hash import hash_eth2

ZERO_BYTES32 = b"\x00" * 32

zerohashes = [ZERO_BYTES32]
for _layer in range(1, 100):
    zerohashes.append(hash_eth2(zerohashes[_layer - 1] + zerohashes[_layer - 1]))


def calc_merkle_tree_from_leaves(values: list[bytes], layer_count: int = 32):
    """All tree layers bottom-up, zero-padded to 2**layer_count leaves."""
    values = list(values)
    tree = [values[:]]
    for h in range(layer_count):
        if len(values) % 2 == 1:
            values.append(zerohashes[h])
        values = [hash_eth2(values[i] + values[i + 1])
                  for i in range(0, len(values), 2)]
        tree.append(values[:])
    return tree


def get_merkle_tree(values: list[bytes], pad_to: int | None = None):
    layer_count = (max(pad_to, 1) - 1).bit_length() if pad_to else \
        max(len(values) - 1, 0).bit_length()
    if len(values) == 0:
        return zerohashes[layer_count]
    return calc_merkle_tree_from_leaves(values, layer_count)


def get_merkle_root(values: list[bytes], pad_to: int = 1) -> bytes:
    if pad_to == 0:
        return zerohashes[0]
    layer_count = (pad_to - 1).bit_length()
    if len(values) == 0:
        return zerohashes[layer_count]
    return calc_merkle_tree_from_leaves(values, layer_count)[-1][0]


def get_merkle_proof(tree, item_index: int, tree_len: int | None = None):
    proof = []
    for i in range(tree_len if tree_len is not None else len(tree)):
        subindex = (item_index // 2**i) ^ 1
        proof.append(tree[i][subindex] if subindex < len(tree[i])
                     else zerohashes[i])
    return proof


def merkleize_chunks(chunks: list[bytes], limit: int | None = None) -> bytes:
    """The SSZ `merkleize(chunks, limit)` primitive, naive level-by-level form."""
    count = len(chunks)
    if limit is None:
        limit = count
    assert count <= limit
    if limit == 0:
        return ZERO_BYTES32
    max_depth = (limit - 1).bit_length()
    level = list(chunks) if chunks else [zerohashes[0]]
    for d in range(max_depth):
        if len(level) % 2 == 1:
            level.append(zerohashes[d])
        level = [hash_eth2(level[i] + level[i + 1])
                 for i in range(0, len(level), 2)]
    return level[0]
