"""Execution-layer hashing primitives: keccak-256, RLP, and the hexary
Merkle-Patricia trie root.

The reference computes real EL block hashes in its test helpers
(`tests/core/pyspec/eth2spec/test/helpers/execution_payload.py:56-128`)
via the `eth_hash`/`rlp`/`trie` packages.  None of those are available
here, so this module provides original pure-Python equivalents.  Inputs
are tiny (block headers, a handful of transactions), so clarity wins
over throughput; the consensus hot path never touches this code.
"""

from __future__ import annotations

from typing import Sequence, Union

# ---------------------------------------------------------------------------
# keccak-256 (the pre-NIST Keccak padding, as used by Ethereum — NOT sha3_256)
# ---------------------------------------------------------------------------

_MASK = (1 << 64) - 1

_ROUND_CONSTANTS = (
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
)

# Rotation offsets r[x][y] for lane (x, y).
_ROTATIONS = (
    (0, 36, 3, 41, 18),
    (1, 44, 10, 45, 2),
    (62, 6, 43, 15, 61),
    (28, 55, 25, 21, 56),
    (27, 20, 39, 8, 14),
)

_RATE = 136  # bytes; capacity 512 bits for a 256-bit digest


def _rotl(v: int, n: int) -> int:
    return ((v << n) | (v >> (64 - n))) & _MASK


def _keccak_f1600(lanes):
    """One permutation over the 5x5 lane state (lanes[x][y])."""
    for rc in _ROUND_CONSTANTS:
        # theta
        c = [lanes[x][0] ^ lanes[x][1] ^ lanes[x][2] ^ lanes[x][3]
             ^ lanes[x][4] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        lanes = [[lanes[x][y] ^ d[x] for y in range(5)] for x in range(5)]
        # rho + pi
        b = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                b[y][(2 * x + 3 * y) % 5] = _rotl(lanes[x][y],
                                                  _ROTATIONS[x][y])
        # chi
        lanes = [[b[x][y] ^ ((~b[(x + 1) % 5][y]) & b[(x + 2) % 5][y]
                             & _MASK) for y in range(5)] for x in range(5)]
        # iota
        lanes[0][0] ^= rc
    return lanes


def keccak256(data: bytes) -> bytes:
    lanes = [[0] * 5 for _ in range(5)]
    # multi-rate padding with the 0x01 domain byte (original Keccak)
    padded = data + b"\x01" + b"\x00" * (_RATE - 1 - len(data) % _RATE)
    padded = padded[:len(padded) - 1] + bytes([padded[-1] | 0x80])
    for off in range(0, len(padded), _RATE):
        block = padded[off:off + _RATE]
        for i in range(_RATE // 8):
            lane = int.from_bytes(block[8 * i:8 * i + 8], "little")
            lanes[i % 5][i // 5] ^= lane
        lanes = _keccak_f1600(lanes)
    out = b"".join(lanes[i % 5][i // 5].to_bytes(8, "little")
                   for i in range(4))
    return out


# ---------------------------------------------------------------------------
# RLP encoding (https://ethereum.org/en/developers/docs/data-structures-and-encoding/rlp/)
# ---------------------------------------------------------------------------

RLPItem = Union[bytes, int, Sequence["RLPItem"]]


def _rlp_length(length: int, short_offset: int) -> bytes:
    if length < 56:
        return bytes([short_offset + length])
    length_bytes = length.to_bytes((length.bit_length() + 7) // 8, "big")
    return bytes([short_offset + 55 + len(length_bytes)]) + length_bytes


def rlp_encode(item: RLPItem) -> bytes:
    if isinstance(item, int):
        # big-endian minimal encoding; zero is the empty byte string
        item = item.to_bytes((item.bit_length() + 7) // 8, "big")
    if isinstance(item, (bytes, bytearray, memoryview)):
        item = bytes(item)
        if len(item) == 1 and item[0] < 0x80:
            return item
        return _rlp_length(len(item), 0x80) + item
    payload = b"".join(rlp_encode(sub) for sub in item)
    return _rlp_length(len(payload), 0xC0) + payload


# ---------------------------------------------------------------------------
# Hexary Merkle-Patricia trie root
# ---------------------------------------------------------------------------

# Nodes are python structures: leaf/extension -> [hp_path, value_or_ref],
# branch -> [ref0..ref15, value].  A reference is the node itself when its
# RLP is short (<32 bytes), else its keccak-256 hash — the standard MPT
# inlining rule.

EMPTY_TRIE_ROOT = bytes.fromhex(
    "56e81f171bcc55a6ff8345e692c0f86e5b48e01b996cadc001622fb5e363b421")


def _hex_prefix(nibbles: Sequence[int], leaf: bool) -> bytes:
    flag = 2 if leaf else 0
    if len(nibbles) % 2:
        head = bytes([(flag + 1) << 4 | nibbles[0]])
        nibbles = nibbles[1:]
    else:
        head = bytes([flag << 4])
    return head + bytes(nibbles[i] << 4 | nibbles[i + 1]
                        for i in range(0, len(nibbles), 2))


def _nibbles(key: bytes):
    out = []
    for byte in key:
        out.append(byte >> 4)
        out.append(byte & 0x0F)
    return out


def _node_ref(node):
    encoded = rlp_encode(node)
    return node if len(encoded) < 32 else keccak256(encoded)


def _build_node(pairs):
    """pairs: non-empty list of (nibble_list, value), all keys distinct and
    prefix-free below this point except possibly one empty key."""
    if len(pairs) == 1 and pairs[0][1] is not None:
        nib, value = pairs[0]
        return [_hex_prefix(nib, leaf=True), value]

    # longest common nibble prefix
    first = pairs[0][0]
    prefix_len = 0
    while (prefix_len < len(first)
           and all(len(nib) > prefix_len and nib[prefix_len]
                   == first[prefix_len] for nib, _ in pairs)):
        prefix_len += 1
    if prefix_len:
        stripped = [(nib[prefix_len:], v) for nib, v in pairs]
        return [_hex_prefix(first[:prefix_len], leaf=False),
                _node_ref(_build_node(stripped))]

    branch = [b""] * 17
    for digit in range(16):
        group = [(nib[1:], v) for nib, v in pairs if nib and nib[0] == digit]
        if group:
            branch[digit] = _node_ref(_build_node(group))
    for nib, value in pairs:
        if not nib:
            branch[16] = value
    return branch


def trie_root(items: dict) -> bytes:
    """Root hash of patriciaTrie(key_bytes => value_bytes).  Empty values
    are skipped, matching HexaryTrie.set semantics for b''."""
    pairs = [(_nibbles(k), v) for k, v in items.items() if v]
    if not pairs:
        return EMPTY_TRIE_ROOT
    return keccak256(rlp_encode(_build_node(pairs)))


def indexed_data_trie_root(data) -> bytes:
    """Root of patriciaTrie(rlp(index) => data) — the EIP-2718 shape used
    for transactions_root / withdrawals_root in EL block headers."""
    return trie_root({rlp_encode(i): bytes(obj)
                      for i, obj in enumerate(data)})
