"""KZG trusted-setup generator: tau powers in G1/G2, group FFT into the
Lagrange basis, JSON dump (the reference's `eth2spec/utils/kzg.py:22-125`;
the shipped ceremony setup JSONs in `presets/*/trusted_setups/` are data
artifacts — this module regenerates *testing* setups from a known secret,
`make kzg_setups`)."""

from __future__ import annotations

import json
import os
from pathlib import Path

from ..ops import bls
from ..ops.bls.curve import R as BLS_MODULUS

PRIMITIVE_ROOT_OF_UNITY = 7


def generate_setup(generator, secret: int, length: int):
    """[generator * secret**i for i in range(length)]."""
    result = [generator]
    for _ in range(1, length):
        result.append(bls.multiply(result[-1], secret))
    return tuple(result)


def compute_root_of_unity(length: int) -> int:
    assert (BLS_MODULUS - 1) % length == 0
    return pow(PRIMITIVE_ROOT_OF_UNITY, (BLS_MODULUS - 1) // length,
               BLS_MODULUS)


def compute_roots_of_unity(order: int) -> tuple:
    order = int(order)
    root = compute_root_of_unity(order)
    roots = []
    current = 1
    for _ in range(order):
        roots.append(current)
        current = current * root % BLS_MODULUS
    return tuple(roots)


def fft(vals, modulus: int, domain):
    """Radix-2 FFT over group elements (scalars in the exponent)."""
    if len(vals) == 1:
        return vals
    left = fft(vals[::2], modulus, domain[::2])
    right = fft(vals[1::2], modulus, domain[::2])
    out = [None] * len(vals)
    for i, (x, y) in enumerate(zip(left, right)):
        y_times_root = bls.multiply(y, domain[i])
        out[i] = bls.add(x, y_times_root)
        out[i + len(left)] = bls.add(x, bls.neg(y_times_root))
    return out


def get_lagrange(setup) -> tuple:
    """Monomial G1 setup -> Lagrange basis over the roots-of-unity domain
    (an inverse FFT expressed as FFT + index reversal + 1/n scaling)."""
    root_of_unity = compute_root_of_unity(len(setup))
    assert pow(root_of_unity, len(setup), BLS_MODULUS) == 1
    domain = [pow(root_of_unity, i, BLS_MODULUS)
              for i in range(len(setup))]
    fft_output = fft(setup, BLS_MODULUS, domain)
    inv_length = pow(len(setup), BLS_MODULUS - 2, BLS_MODULUS)
    return tuple(
        bls.G1_to_bytes48(bls.multiply(fft_output[-i], inv_length))
        for i in range(len(fft_output)))


def dump_kzg_trusted_setup_files(secret: int, g1_length: int,
                                 g2_length: int, output_dir: str) -> None:
    setup_g1 = generate_setup(bls.G1(), secret, g1_length)
    setup_g2 = generate_setup(bls.G2(), secret, g2_length)
    setup_g1_lagrange = get_lagrange(setup_g1)
    roots_of_unity = compute_roots_of_unity(g1_length)

    g1_monomial = ["0x" + bls.G1_to_bytes48(p).hex() for p in setup_g1]
    g2_monomial = ["0x" + bls.G2_to_bytes96(p).hex() for p in setup_g2]
    g1_lagrange = ["0x" + b.hex() for b in setup_g1_lagrange]

    out = Path(output_dir)
    os.makedirs(out, exist_ok=True)
    # modern key names, loadable by the in-tree setup loader
    # (models/deneb/polynomial_commitments.py reads g1_monomial/g1_lagrange/
    # g2_monomial from trusted_setup_<n>.json)
    path = out / f"trusted_setup_{len(setup_g1)}.json"
    with open(path, "w") as f:
        json.dump({
            "g1_monomial": g1_monomial,
            "g1_lagrange": g1_lagrange,
            "g2_monomial": g2_monomial,
        }, f)
    print(f"Generated trusted setup file: {path}")
    # legacy-named companion kept for parity with the reference's
    # testing_trusted_setups.json output shape
    legacy = out / "testing_trusted_setups.json"
    with open(legacy, "w") as f:
        json.dump({
            "setup_G1": g1_monomial,
            "setup_G2": g2_monomial,
            "setup_G1_lagrange": g1_lagrange,
            "roots_of_unity": roots_of_unity,
        }, f)
    print(f"Generated trusted setup file: {legacy}")


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(
        description="generate a testing KZG trusted setup")
    p.add_argument("--secret", type=int, required=True)
    p.add_argument("--g1-length", type=int, required=True)
    p.add_argument("--g2-length", type=int, required=True)
    p.add_argument("--output-dir", required=True)
    args = p.parse_args(argv)
    dump_kzg_trusted_setup_files(args.secret, args.g1_length,
                                 args.g2_length, args.output_dir)


if __name__ == "__main__":
    main()
