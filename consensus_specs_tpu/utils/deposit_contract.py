"""The EL deposit contract, modeled in Python — the incremental sparse
Merkle tree, deposit validation, event log, and root/count views of
`solidity_deposit_contract/deposit_contract.sol:64-161` (no solidity
toolchain ships in this environment, so the observable behavior is
ported; tree parity with the consensus spec's `DepositData` list root is
pinned by tests/test_deposit_contract.py)."""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

DEPOSIT_CONTRACT_TREE_DEPTH = 32
MAX_DEPOSIT_COUNT = 2**DEPOSIT_CONTRACT_TREE_DEPTH - 1
GWEI = 10**9
ETHER = 10**18


def _sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def _to_little_endian_64(value: int) -> bytes:
    return int(value).to_bytes(8, "little")


ZERO_HASHES = [b"\x00" * 32]
for _ in range(DEPOSIT_CONTRACT_TREE_DEPTH - 1):
    ZERO_HASHES.append(_sha256(ZERO_HASHES[-1] + ZERO_HASHES[-1]))


class DepositContractError(Exception):
    """A `require(...)` failure — the deposit reverts."""


@dataclass
class DepositEvent:
    pubkey: bytes
    withdrawal_credentials: bytes
    amount: bytes           # little-endian uint64 gwei
    signature: bytes
    index: bytes            # little-endian uint64


def compute_deposit_data_root(pubkey: bytes,
                              withdrawal_credentials: bytes,
                              amount_gwei: int,
                              signature: bytes) -> bytes:
    """The contract's inlined `DepositData` hash-tree-root
    (deposit_contract.sol:128-138)."""
    amount = _to_little_endian_64(amount_gwei)
    pubkey_root = _sha256(pubkey + b"\x00" * 16)
    signature_root = _sha256(
        _sha256(signature[:64]) + _sha256(signature[64:] + b"\x00" * 32))
    return _sha256(
        _sha256(pubkey_root + withdrawal_credentials)
        + _sha256(amount + b"\x00" * 24 + signature_root))


@dataclass
class DepositContract:
    """State of the deposit contract: 32 branch nodes + a counter."""

    branch: list = field(default_factory=lambda:
                         [b"\x00" * 32] * DEPOSIT_CONTRACT_TREE_DEPTH)
    deposit_count: int = 0
    events: list = field(default_factory=list)

    def get_deposit_root(self) -> bytes:
        """Incremental-tree root mixed with the little-endian count
        (deposit_contract.sol:80-95)."""
        node = b"\x00" * 32
        size = self.deposit_count
        for height in range(DEPOSIT_CONTRACT_TREE_DEPTH):
            if size & 1:
                node = _sha256(self.branch[height] + node)
            else:
                node = _sha256(node + ZERO_HASHES[height])
            size //= 2
        return _sha256(node + _to_little_endian_64(self.deposit_count)
                       + b"\x00" * 24)

    def get_deposit_count(self) -> bytes:
        return _to_little_endian_64(self.deposit_count)

    def deposit(self, pubkey: bytes, withdrawal_credentials: bytes,
                signature: bytes, deposit_data_root: bytes,
                value_wei: int) -> None:
        """`deposit(...)` with msg.value = value_wei
        (deposit_contract.sol:101-158)."""
        if len(pubkey) != 48:
            raise DepositContractError("invalid pubkey length")
        if len(withdrawal_credentials) != 32:
            raise DepositContractError(
                "invalid withdrawal_credentials length")
        if len(signature) != 96:
            raise DepositContractError("invalid signature length")

        if value_wei < ETHER:
            raise DepositContractError("deposit value too low")
        if value_wei % GWEI != 0:
            raise DepositContractError(
                "deposit value not multiple of gwei")
        deposit_amount = value_wei // GWEI
        if deposit_amount > 2**64 - 1:
            raise DepositContractError("deposit value too high")

        self.events.append(DepositEvent(
            pubkey=bytes(pubkey),
            withdrawal_credentials=bytes(withdrawal_credentials),
            amount=_to_little_endian_64(deposit_amount),
            signature=bytes(signature),
            index=_to_little_endian_64(self.deposit_count),
        ))

        node = compute_deposit_data_root(
            pubkey, withdrawal_credentials, deposit_amount, signature)
        if node != bytes(deposit_data_root):
            raise DepositContractError(
                "reconstructed DepositData does not match supplied "
                "deposit_data_root")

        if self.deposit_count >= MAX_DEPOSIT_COUNT:
            raise DepositContractError("merkle tree full")

        # update a single branch node
        self.deposit_count += 1
        size = self.deposit_count
        for height in range(DEPOSIT_CONTRACT_TREE_DEPTH):
            if size & 1:
                self.branch[height] = node
                return
            node = _sha256(self.branch[height] + node)
            size //= 2
        raise AssertionError("unreachable")  # loop always returns
