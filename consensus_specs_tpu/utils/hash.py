"""SHA-256 hashing front-end.

Host-side scalar path wraps hashlib; the batched paths live in
``consensus_specs_tpu.ops.sha256_np`` (vectorized numpy) and
``ops.sha256_jax`` (JAX/TPU).  Mirrors the role of the reference's
``eth2spec/utils/hash_function.py:8`` (``hash(x) = sha256(x).digest()``).
"""

from hashlib import sha256 as _sha256


def hash_eth2(data: bytes) -> bytes:
    """32-byte SHA-256 digest (the only hash the consensus spec uses)."""
    return _sha256(data).digest()


# Spec modules bind this under the name `hash`.
hash = hash_eth2
