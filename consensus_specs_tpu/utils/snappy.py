"""Pure-Python snappy block-format codec.

The reference compresses every SSZ vector part with C `python-snappy`
(`gen_helpers/gen_base/dumper.py:65-70`); that binding is not in this
image, so the generator layer uses this self-contained implementation of
the raw snappy block format (the same format `snappy.compress` emits:
a varint uncompressed length followed by literal/copy elements).

The compressor is a greedy hash-table LZ like the canonical algorithm:
4-byte hashes into a 16k-entry table, copies emitted with the 2-byte
offset encoding, literals for the rest.  Output decompresses with any
conforming snappy decoder (the consumers of `.ssz_snappy` vectors);
byte-identity with the C encoder's choices is not required by the format.
"""

from __future__ import annotations

_TAG_LITERAL = 0
_TAG_COPY1 = 1
_TAG_COPY2 = 2

_TABLE_BITS = 14
_TABLE_SIZE = 1 << _TABLE_BITS


def _write_varint(n: int, out: bytearray) -> None:
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)


def _read_varint(data: bytes, pos: int) -> tuple[int, int]:
    shift = 0
    result = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 35:
            raise ValueError("varint too long")


def _emit_literal(data: bytes, start: int, end: int, out: bytearray) -> None:
    length = end - start
    if length <= 0:
        return
    n = length - 1
    if n < 60:
        out.append((n << 2) | _TAG_LITERAL)
    elif n < (1 << 8):
        out.append((60 << 2) | _TAG_LITERAL)
        out.append(n)
    elif n < (1 << 16):
        out.append((61 << 2) | _TAG_LITERAL)
        out += n.to_bytes(2, "little")
    elif n < (1 << 24):
        out.append((62 << 2) | _TAG_LITERAL)
        out += n.to_bytes(3, "little")
    else:
        out.append((63 << 2) | _TAG_LITERAL)
        out += n.to_bytes(4, "little")
    out += data[start:end]


def _emit_copy(offset: int, length: int, out: bytearray) -> None:
    # prefer copy1 (4..11 byte copies, offset < 2048), else chains of copy2
    while length >= 68:
        out.append((63 << 2) | _TAG_COPY2)
        out += offset.to_bytes(2, "little")
        length -= 64
    if length > 64:
        # emit a 60-byte copy2 so the remainder is >= 4
        out.append((59 << 2) | _TAG_COPY2)
        out += offset.to_bytes(2, "little")
        length -= 60
    if length >= 12 or offset >= 2048:
        out.append(((length - 1) << 2) | _TAG_COPY2)
        out += offset.to_bytes(2, "little")
    else:
        out.append(((offset >> 8) << 5) | ((length - 4) << 2) | _TAG_COPY1)
        out.append(offset & 0xFF)


def _hash4(v: int) -> int:
    return ((v * 0x1E35A7BD) >> (32 - _TABLE_BITS)) & (_TABLE_SIZE - 1)


def compress(data: bytes) -> bytes:
    data = bytes(data)
    n = len(data)
    out = bytearray()
    _write_varint(n, out)
    if n == 0:
        return bytes(out)
    if n < 4:
        _emit_literal(data, 0, n, out)
        return bytes(out)

    table = [-1] * _TABLE_SIZE
    pos = 0
    lit_start = 0
    limit = n - 3  # last position where a 4-byte read fits
    while pos < limit:
        cur = int.from_bytes(data[pos:pos + 4], "little")
        h = _hash4(cur)
        cand = table[h]
        table[h] = pos
        if (cand >= 0 and pos - cand < 65536
                and data[cand:cand + 4] == data[pos:pos + 4]):
            _emit_literal(data, lit_start, pos, out)
            # extend the match
            length = 4
            while (pos + length < n
                   and data[cand + length] == data[pos + length]):
                length += 1
            _emit_copy(pos - cand, length, out)
            pos += length
            lit_start = pos
        else:
            pos += 1
    _emit_literal(data, lit_start, n, out)
    return bytes(out)


def decompress(data: bytes) -> bytes:
    data = bytes(data)
    expected, pos = _read_varint(data, 0)
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == _TAG_LITERAL:
            length = tag >> 2
            if length >= 60:
                extra = length - 59
                length = int.from_bytes(data[pos:pos + extra], "little")
                pos += extra
            length += 1
            out += data[pos:pos + length]
            pos += length
            continue
        if kind == _TAG_COPY1:
            length = ((tag >> 2) & 0x7) + 4
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == _TAG_COPY2:
            length = (tag >> 2) + 1
            offset = int.from_bytes(data[pos:pos + 2], "little")
            pos += 2
        else:  # copy4
            length = (tag >> 2) + 1
            offset = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
        if offset == 0 or offset > len(out):
            raise ValueError("invalid copy offset")
        if offset >= length:  # non-overlapping: one C-level slice copy
            start = len(out) - offset
            out += out[start:start + length]
        else:  # overlapping run: byte-by-byte is the semantics
            for _ in range(length):
                out.append(out[-offset])
    if len(out) != expected:
        raise ValueError(
            f"decompressed length {len(out)} != declared {expected}")
    return bytes(out)
