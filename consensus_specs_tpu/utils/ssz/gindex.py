"""Generalized indices + Merkle multiproof helpers over the SSZ type system.

Implements the algebra of the spec's `ssz/merkle-proofs.md` (generalized
index = 2**depth + leaf_index, path navigation through container fields and
list/vector elements) directly over our view classes, plus proof
construction by materializing sibling roots along the gindex path —
replacing the reference's remerkleable-backing walker
(`eth2spec/test/helpers/merkle.py:4-21`,
`pysetup/spec_builders/altair.py:28-51` `compute_merkle_proof`).
"""

from __future__ import annotations

from ..hash import hash_eth2
from .types import (
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Container,
    List,
    Vector,
    View,
    is_basic_type,
)

GeneralizedIndex = int


def _chunk_depth(chunk_count: int) -> int:
    return max(chunk_count - 1, 0).bit_length()


def item_length(typ) -> int:
    """Byte length of one element when packed (basic: its size; else 32)."""
    if is_basic_type(typ):
        return typ.type_byte_length()
    return 32


def chunk_count(typ) -> int:
    """Number of bottom-layer chunks of a type's merkleization."""
    if is_basic_type(typ):
        return 1
    if issubclass(typ, (Bitlist, Bitvector)):
        cap = typ._limit if issubclass(typ, Bitlist) else typ._length
        return (cap + 255) // 256
    if issubclass(typ, ByteVector):
        return (typ._length + 31) // 32
    if issubclass(typ, ByteList):
        return (typ._limit + 31) // 32
    if issubclass(typ, (List, Vector)):
        et = typ._element_type
        cap = typ._limit if issubclass(typ, List) else typ._length
        return (cap * item_length(et) + 31) // 32
    if issubclass(typ, Container):
        return len(typ.fields())
    raise TypeError(f"no chunk count for {typ}")


def get_elem_type(typ, index):
    """Type of the child at a path step (field name or element index)."""
    if issubclass(typ, Container):
        if not isinstance(index, str):
            raise TypeError("container navigation takes a field name")
        return typ.fields()[index]
    if issubclass(typ, (List, Vector)):
        return typ._element_type
    if issubclass(typ, (ByteVector, ByteList)):
        from .types import byte
        return byte
    if issubclass(typ, (Bitlist, Bitvector)):
        from .types import boolean
        return boolean
    raise TypeError(f"cannot navigate into {typ}")


def get_generalized_index_step(typ, index) -> tuple[GeneralizedIndex, type]:
    """One navigation step: returns (gindex within typ's tree, child type)."""
    if issubclass(typ, Container):
        names = list(typ.fields())
        pos = names.index(index)
        depth = _chunk_depth(len(names))
        return (1 << depth) + pos, typ.fields()[index]
    if index == "__len__":
        if not issubclass(typ, (List, Bitlist, ByteList)):
            raise TypeError("__len__ only on lists")
        return 3, None
    if issubclass(typ, (List, ByteList, Bitlist)):
        et = get_elem_type(typ, index)
        start = int(index) * item_length(et) // 32
        depth = _chunk_depth(chunk_count(typ))
        # list root = mix_in_length: data tree at gindex 2, length at 3
        return (2 << depth) + start, et
    if issubclass(typ, (Vector, ByteVector, Bitvector)):
        et = get_elem_type(typ, index)
        start = int(index) * item_length(et) // 32
        depth = _chunk_depth(chunk_count(typ))
        return (1 << depth) + start, et
    raise TypeError(f"cannot compute gindex into {typ}")


def get_generalized_index(typ, *path) -> GeneralizedIndex:
    """Generalized index of `path` (field names / element indices) in typ."""
    root: GeneralizedIndex = 1
    for step in path:
        assert not is_basic_type(typ), "cannot navigate into basic type"
        g, typ = get_generalized_index_step(typ, step)
        root = _concat_gindices(root, g)
    return root


def _concat_gindices(a: GeneralizedIndex, b: GeneralizedIndex) -> GeneralizedIndex:
    # splice b under a: a * 2**depth(b) + (b - msb(b))
    depth_b = b.bit_length() - 1
    return (a << depth_b) | (b - (1 << depth_b))


concat_generalized_indices = _concat_gindices


def get_subtree_chunks(value: View) -> list[bytes]:
    """Bottom-layer chunk roots of a value's own merkle tree (pre mix-in)."""
    from .types import _chunk_pack_np

    typ = type(value)
    if is_basic_type(typ):
        return [value.hash_tree_root()]
    if isinstance(value, (ByteVector, ByteList)):
        raw = bytes(value)
        if len(raw) % 32:
            raw += b"\x00" * (32 - len(raw) % 32)
        return [raw[i:i + 32] for i in range(0, len(raw), 32)] or [b"\x00" * 32]
    if isinstance(value, (Bitvector, Bitlist)):
        raw = value._chunks()
        return [raw[i:i + 32] for i in range(0, len(raw), 32)] or [b"\x00" * 32]
    if isinstance(value, (List, Vector)):
        et = typ._element_type
        if is_basic_type(et):
            if value._np_dtype() is not None:
                raw = _chunk_pack_np(value._np_view())
            else:
                raw = b"".join(e.encode_bytes() for e in value._data)
                if len(raw) % 32:
                    raw += b"\x00" * (32 - len(raw) % 32)
            return [raw[i:i + 32] for i in range(0, len(raw), 32)] or [b"\x00" * 32]
        return [el.hash_tree_root() for el in value._data]
    if isinstance(value, Container):
        return [value._values[n].hash_tree_root() for n in typ.fields()]
    raise TypeError(f"no chunks for {typ}")


def _subtree_node_root(value: View, gindex: GeneralizedIndex) -> bytes:
    """Root of the node at `gindex` within value's own (full, incl. mix-in)
    tree, computed recursively with zero-hash padding."""
    if gindex == 1:
        return bytes(value.hash_tree_root())
    if isinstance(value, (List, ByteList, Bitlist)):
        # root = H(data_root, len); gindex 2 subtree = data, 3 = length
        if gindex == 2:
            return _data_tree_root(value, 1)
        if gindex == 3:
            return len(value).to_bytes(32, "little")
        top_bit = 1 << (gindex.bit_length() - 1)
        second = (gindex >> (gindex.bit_length() - 2)) & 1
        if second != 0:
            raise ValueError("gindex under length leaf")
        # descend into data tree: strip the top "10" prefix, keep leading 1
        return _data_tree_root(
            value, (gindex & ~(top_bit | (top_bit >> 1))) | (top_bit >> 1))
    return _data_tree_root(value, gindex)


def _data_tree_root(value: View, gindex: GeneralizedIndex) -> bytes:
    """Root of node `gindex` within the (limit-padded) data tree of value."""
    from ..merkle_minimal import zerohashes

    chunks = get_subtree_chunks(value)
    total_depth = _chunk_depth(chunk_count(type(value)))
    if gindex == 1:
        node_depth = 0
    else:
        node_depth = gindex.bit_length() - 1
    # position of subtree at this depth
    pos = gindex - (1 << node_depth)
    sub_depth = total_depth - node_depth
    assert sub_depth >= 0, "gindex deeper than chunk layer"
    lo = pos << sub_depth
    hi = min(len(chunks), (pos + 1) << sub_depth)
    if lo >= len(chunks):
        return zerohashes[sub_depth]
    level = chunks[lo:hi]
    for d in range(sub_depth):
        if len(level) % 2 == 1:
            level.append(zerohashes[d])
        level = [hash_eth2(level[i] + level[i + 1])
                 for i in range(0, len(level), 2)]
    return level[0]


def compute_merkle_proof(value: View, gindex: GeneralizedIndex) -> list[bytes]:
    """Sibling hashes bottom-up proving `gindex` against value's root.

    Navigates type structure: at each tree level along the path, the sibling
    root is computed from the child views' cached roots — no global tree
    materialization, so proofs over a full BeaconState are cheap.
    """
    bits = bin(gindex)[3:]  # path from root, MSB first (drop leading 1)
    proof: list[bytes] = []
    # walk down accumulating (value, local_gindex) context
    node_val: View = value
    local_g = 1

    for depth, b in enumerate(bits):
        child_g_local_0 = local_g * 2
        sibling_g = child_g_local_0 + (1 - int(b))
        taken_g = child_g_local_0 + int(b)
        # can we descend into a child *view* (crossing a type boundary)?
        descended = _try_descend(node_val, taken_g)
        proof.append(_subtree_node_root(node_val, sibling_g))
        if descended is not None:
            node_val, local_g = descended, 1
        else:
            local_g = taken_g
    return list(reversed(proof))


def _try_descend(value: View, local_gindex: GeneralizedIndex):
    """If local_gindex lands exactly on a child view's root, return it."""
    typ = type(value)
    if isinstance(value, Container):
        names = list(typ.fields())
        depth = _chunk_depth(len(names))
        if local_gindex.bit_length() - 1 == depth:
            pos = local_gindex - (1 << depth)
            if pos < len(names):
                child = value._values[names[pos]]
                if not is_basic_type(type(child)):
                    return child
        return None
    if isinstance(value, (List, Vector)):
        et = typ._element_type
        if is_basic_type(et):
            return None
        data_depth = _chunk_depth(chunk_count(typ))
        full_depth = data_depth + (1 if isinstance(value, List) else 0)
        if local_gindex.bit_length() - 1 == full_depth:
            if isinstance(value, List):
                # must be under the data subtree (prefix 10...)
                second_bit = (local_gindex >> (full_depth - 1)) & 1
                if second_bit != 0:
                    return None
                pos = local_gindex - (1 << full_depth)
            else:
                pos = local_gindex - (1 << full_depth)
            if pos < len(value._data):
                return value._data[pos]
        return None
    return None


def is_valid_merkle_branch(leaf: bytes, branch, depth: int, index: int,
                           root: bytes) -> bool:
    """Spec-level proof verification (phase0 `is_valid_merkle_branch`)."""
    value = bytes(leaf)
    for i in range(depth):
        if index // (2**i) % 2:
            value = hash_eth2(bytes(branch[i]) + value)
        else:
            value = hash_eth2(value + bytes(branch[i]))
    return value == bytes(root)
