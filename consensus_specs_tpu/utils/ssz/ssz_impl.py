"""SSZ front-door functions, mirroring the reference facade
(`eth2spec/utils/ssz/ssz_impl.py:8-37`): serialize / deserialize /
hash_tree_root / uint_to_bytes / copy.
"""

from .types import View, uint


def serialize(obj: View) -> bytes:
    return obj.encode_bytes()


def deserialize(typ: type, data: bytes) -> View:
    return typ.decode_bytes(data)


def hash_tree_root(obj) -> bytes:
    """Root as a 32-byte value (spec code wraps it in Root/Bytes32)."""
    from .types import Bytes32

    if isinstance(obj, bytes) and not isinstance(obj, View):
        raise TypeError("hash_tree_root takes an SSZ view, not raw bytes")
    return Bytes32(obj.hash_tree_root())


def uint_to_bytes(n: uint) -> bytes:
    """Little-endian encoding at the uint's own byte length
    (reference: `ssz_impl.py:28-30`)."""
    assert isinstance(n, uint)
    return n.encode_bytes()


def copy(obj: View) -> View:
    return obj.copy()
