"""SSZ type system with TPU-shaped merkleization.

A ground-up redesign of the SSZ engine (the role remerkleable plays for the
reference, see `eth2spec/utils/ssz/ssz_typing.py` re-exports): instead of a
pointer-based persistent binary tree, composite values hold their leaves in
contiguous buffers — packed basic lists/vectors are numpy arrays — so
`hash_tree_root` is a *batched* Merkle reduction over chunk arrays
(`ops.sha256_np` on host, `ops.sha256_jax` on TPU) rather than a per-node
Python recursion.

Semantics preserved from the reference engine that spec code and tests rely
on:

- views are mutable (`state.balances[i] += x`, `state.validators.append(v)`)
  and `obj.copy()` produces an independent value (`ssz_impl.py:36`)
- element access on composite lists returns the live child object; mutating
  it dirties every ancestor's cached root (parent-pointer invalidation
  replaces remerkleable's immutable re-binding)
- assigning a child that already lives inside another composite stores a
  copy, keeping single-ownership (value semantics at the assignment
  boundary, like remerkleable's backing rebind)
- equality = type + hash_tree_root

Wire format + merkleization follow `ssz/simple-serialize.md` of the spec
(chunk packing, limits, length/selector mix-ins, offset encoding).
"""

from __future__ import annotations

import io
from typing import Any, Iterable

import numpy as np

from ...ops import sha256_np
from ..hash import hash_eth2

BYTES_PER_CHUNK = 32
OFFSET_BYTE_LENGTH = 4
ZERO_CHUNK = b"\x00" * 32


def _mix_in_length(root: bytes, length: int) -> bytes:
    return hash_eth2(root + length.to_bytes(32, "little"))


def _merkleize_chunks(chunks: bytes, limit: int | None = None) -> bytes:
    return sha256_np.merkleize_chunks_bytes(chunks, limit)


def _merkleize_roots(roots: list[bytes], limit: int | None = None) -> bytes:
    return sha256_np.merkleize_chunks_bytes(b"".join(roots), limit)


# ---------------------------------------------------------------------------
# View protocol
# ---------------------------------------------------------------------------


class View:
    """Common SSZ interface.  Class-level metadata + instance serialization.

    Immutable leaf types (uints, booleans, byte arrays) subclass Python
    builtins; mutable composites subclass MutableView below.
    """

    @classmethod
    def is_fixed_size(cls) -> bool:
        raise NotImplementedError

    @classmethod
    def type_byte_length(cls) -> int:
        """Serialized length; only valid for fixed-size types."""
        raise NotImplementedError

    @classmethod
    def default(cls) -> "View":
        raise NotImplementedError

    @classmethod
    def decode_bytes(cls, data: bytes) -> "View":
        raise NotImplementedError

    def encode_bytes(self) -> bytes:
        raise NotImplementedError

    def hash_tree_root(self) -> bytes:
        raise NotImplementedError

    def copy(self):
        return self  # immutable default

    @classmethod
    def coerce_view(cls, value: Any) -> "View":
        if type(value) is cls:
            return value
        if isinstance(value, View):
            if isinstance(value, cls):
                return value  # subclass instance (custom-type alias), keep
            if not isinstance(value, (int, bytes)):
                # Same-named foreign type: each fork's spec is built in its
                # own namespace, so e.g. a phase0 BeaconBlockHeader is a
                # different class from altair's.  Cross-fork upgrade
                # functions hand such values over; round-trip through the
                # wire format.  Differently-named types stay a TypeError —
                # a byte-compatible reinterpretation would be corruption.
                if type(value).__name__ == cls.__name__:
                    try:
                        return cls.decode_bytes(value.encode_bytes())
                    except Exception:
                        pass
                raise TypeError(
                    f"cannot coerce {type(value).__name__} "
                    f"to {cls.__name__}")
        return cls(value)  # type: ignore[call-arg]


class MutableView(View):
    """Mutable composite with cached root + upward dirty propagation."""

    __slots__ = ("_parent", "_root")

    def __init__(self):
        object.__setattr__(self, "_parent", None)
        object.__setattr__(self, "_root", None)

    def _mark_dirty(self) -> None:
        # Walk the full ancestor chain: a clean ancestor can sit above a
        # dirty node only if we ever stopped early, so never stop early.
        node: MutableView | None = self
        while node is not None:
            object.__setattr__(node, "_root", None)
            node = node._parent

    def _adopt(self, child: Any) -> Any:
        """Claim ownership of a mutable child, copying if already owned.

        Copying also when the present owner is `self` preserves value
        semantics for self-assignments like
        `state.previous_justified_checkpoint = state.current_justified_checkpoint`.
        """
        if isinstance(child, MutableView):
            if child._parent is not None:
                child = child.copy()
            object.__setattr__(child, "_parent", self)
        return child

    def hash_tree_root(self) -> bytes:
        if self._root is None:
            object.__setattr__(self, "_root", self._compute_root())
        return self._root

    def _compute_root(self) -> bytes:
        raise NotImplementedError

    def __eq__(self, other):
        # Compare by class *name*, not identity: every fork's spec builds
        # its containers in a separate namespace, and cross-fork code
        # (upgrade fns, transition tests) must see e.g. a phase0
        # Checkpoint(1, r) as equal to an altair Checkpoint(1, r).
        return (
            isinstance(other, View)
            and type(other).__name__ == type(self).__name__
            and other.hash_tree_root() == self.hash_tree_root()
        )

    def __hash__(self):
        return hash((type(self).__name__, self.hash_tree_root()))


# ---------------------------------------------------------------------------
# Basic types
# ---------------------------------------------------------------------------


class uint(int, View):
    _byte_len = 0

    def __new__(cls, value: int = 0):
        if not isinstance(value, (int, np.integer)):
            raise TypeError(f"uints are constructed from ints, got {type(value).__name__}")
        v = int(value)
        if v < 0 or v >> (cls._byte_len * 8):
            raise ValueError(f"{cls.__name__} out of range: {value}")
        return super().__new__(cls, v)

    @classmethod
    def is_fixed_size(cls) -> bool:
        return True

    @classmethod
    def type_byte_length(cls) -> int:
        return cls._byte_len

    @classmethod
    def default(cls):
        return cls(0)

    @classmethod
    def decode_bytes(cls, data: bytes):
        if len(data) != cls._byte_len:
            raise ValueError(f"{cls.__name__}: expected {cls._byte_len} bytes")
        return cls(int.from_bytes(data, "little"))

    def encode_bytes(self) -> bytes:
        return int(self).to_bytes(self._byte_len, "little")

    def hash_tree_root(self) -> bytes:
        return self.encode_bytes().ljust(32, b"\x00")


class uint8(uint):
    _byte_len = 1


class uint16(uint):
    _byte_len = 2


class uint32(uint):
    _byte_len = 4


class uint64(uint):
    _byte_len = 8


class uint128(uint):
    _byte_len = 16


class uint256(uint):
    _byte_len = 32


byte = uint8  # SSZ alias


class boolean(int, View):
    def __new__(cls, value: int = 0):
        if value not in (0, 1, False, True):
            raise ValueError(f"boolean out of range: {value}")
        return super().__new__(cls, int(value))

    @classmethod
    def is_fixed_size(cls) -> bool:
        return True

    @classmethod
    def type_byte_length(cls) -> int:
        return 1

    @classmethod
    def default(cls):
        return cls(0)

    @classmethod
    def decode_bytes(cls, data: bytes):
        if len(data) != 1 or data[0] > 1:
            raise ValueError("invalid boolean encoding")
        return cls(data[0])

    def encode_bytes(self) -> bytes:
        return bytes([int(self)])

    def hash_tree_root(self) -> bytes:
        return self.encode_bytes().ljust(32, b"\x00")


bit = boolean

_BASIC_NP_DTYPES: dict[type, Any] = {}


def _register_np_dtypes():
    _BASIC_NP_DTYPES.update({
        uint8: np.dtype("<u1"),
        uint16: np.dtype("<u2"),
        uint32: np.dtype("<u4"),
        uint64: np.dtype("<u8"),
        boolean: np.dtype("<u1"),
    })


_register_np_dtypes()


def is_basic_type(t: type) -> bool:
    return isinstance(t, type) and issubclass(t, (uint, boolean))


# ---------------------------------------------------------------------------
# Byte arrays (immutable)
# ---------------------------------------------------------------------------


class _ParamMeta(type):
    """Metaclass giving parametrized types (List[T, N] etc.) a cache."""

    _cache: dict = {}

    def __getitem__(cls, params):
        if not isinstance(params, tuple):
            params = (params,)
        key = (cls, params)
        cached = _ParamMeta._cache.get(key)
        if cached is None:
            cached = cls._parametrize(params)
            _ParamMeta._cache[key] = cached
        return cached


class ByteVector(bytes, View, metaclass=_ParamMeta):
    _length: int = 0

    @classmethod
    def _parametrize(cls, params):
        (n,) = params
        return type(f"ByteVector[{n}]", (ByteVector,), {"_length": int(n)})

    def __new__(cls, value: bytes = b"", *args):
        if args:
            value = bytes([value, *args])  # ByteVector(1, 2, 3) form
        if isinstance(value, (int,)):
            raise TypeError("ByteVector takes bytes")
        if isinstance(value, str):
            value = bytes.fromhex(value.replace("0x", ""))
        b = bytes(value)
        if cls._length == 0:
            raise TypeError("cannot instantiate unparametrized ByteVector")
        if len(b) == 0:
            b = b"\x00" * cls._length
        if len(b) != cls._length:
            raise ValueError(f"{cls.__name__}: expected {cls._length} bytes, got {len(b)}")
        return super().__new__(cls, b)

    @classmethod
    def is_fixed_size(cls):
        return True

    @classmethod
    def type_byte_length(cls):
        return cls._length

    @classmethod
    def default(cls):
        return cls(b"\x00" * cls._length)

    @classmethod
    def decode_bytes(cls, data: bytes):
        if len(data) != cls._length:
            raise ValueError(f"{cls.__name__}: expected {cls._length} bytes, "
                             f"got {len(data)}")
        return cls(data)

    def encode_bytes(self) -> bytes:
        return bytes(self)

    def hash_tree_root(self) -> bytes:
        if self._length <= 32:  # single chunk: the root IS the padded value
            return bytes(self).ljust(32, b"\x00")
        padded = bytes(self)
        if len(padded) % 32:
            padded += b"\x00" * (32 - len(padded) % 32)
        return _merkleize_chunks(padded)

    def __repr__(self):
        return f"{type(self).__name__}(0x{bytes(self).hex()})"


class ByteList(bytes, View, metaclass=_ParamMeta):
    _limit: int = 0

    @classmethod
    def _parametrize(cls, params):
        (n,) = params
        return type(f"ByteList[{n}]", (ByteList,), {"_limit": int(n)})

    def __new__(cls, value: bytes = b""):
        if isinstance(value, str):
            value = bytes.fromhex(value.replace("0x", ""))
        b = bytes(value)
        if len(b) > cls._limit:
            raise ValueError(f"{cls.__name__}: length {len(b)} exceeds limit {cls._limit}")
        return super().__new__(cls, b)

    @classmethod
    def is_fixed_size(cls):
        return False

    @classmethod
    def default(cls):
        return cls(b"")

    @classmethod
    def decode_bytes(cls, data: bytes):
        return cls(data)

    def encode_bytes(self) -> bytes:
        return bytes(self)

    def hash_tree_root(self) -> bytes:
        padded = bytes(self)
        if len(padded) % 32:
            padded += b"\x00" * (32 - len(padded) % 32)
        limit_chunks = (self._limit + 31) // 32
        return _mix_in_length(_merkleize_chunks(padded, limit_chunks), len(self))

    def __repr__(self):
        return f"{type(self).__name__}(0x{bytes(self).hex()})"


Bytes1 = ByteVector[1]
Bytes4 = ByteVector[4]
Bytes8 = ByteVector[8]
Bytes20 = ByteVector[20]
Bytes31 = ByteVector[31]
Bytes32 = ByteVector[32]
Bytes48 = ByteVector[48]
Bytes96 = ByteVector[96]


# ---------------------------------------------------------------------------
# Bitfields
# ---------------------------------------------------------------------------


class _BitsBase(MutableView):
    __slots__ = ("_bits", "_nbits")

    def __init__(self, *args):
        super().__init__()
        if len(args) == 1 and not isinstance(args[0], (int, bool, np.bool_)) \
                and isinstance(args[0], (Iterable,)):
            bits = list(args[0])
        else:
            bits = list(args)
        self._bits = np.array([bool(b) for b in bits], dtype=np.uint8)
        self._nbits = len(self._bits)

    def _view(self) -> np.ndarray:
        return self._bits[: self._nbits]

    def __len__(self):
        return self._nbits

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [bool(x) for x in self._view()[i]]
        i = int(i)
        if i < 0 or i >= self._nbits:
            raise IndexError(f"bit index {i} out of range for length {self._nbits}")
        return bool(self._bits[i])

    def __setitem__(self, i, v):
        if isinstance(i, slice):
            idxs = range(*i.indices(self._nbits))
            vals = list(v)
            if len(vals) != len(idxs):
                raise ValueError(
                    f"cannot assign {len(vals)} bits to slice of "
                    f"length {len(idxs)}")
            for j, val in zip(idxs, vals):
                self._bits[j] = bool(val)
            self._mark_dirty()
            return
        i = int(i)
        if i < 0 or i >= self._nbits:
            raise IndexError(f"bit index {i} out of range for length {self._nbits}")
        self._bits[i] = bool(v)
        self._mark_dirty()

    def __iter__(self):
        return iter(bool(x) for x in self._view())

    def _packed_bytes(self) -> bytes:
        return np.packbits(self._view(), bitorder="little").tobytes()

    def _chunks(self) -> bytes:
        packed = self._packed_bytes()
        if len(packed) % 32:
            packed += b"\x00" * (32 - len(packed) % 32)
        return packed

    def __repr__(self):
        return f"{type(self).__name__}({[bool(b) for b in self._view()]})"


class Bitvector(_BitsBase, metaclass=_ParamMeta):
    _length: int = 0

    @classmethod
    def _parametrize(cls, params):
        (n,) = params
        assert n > 0
        return type(f"Bitvector[{n}]", (Bitvector,), {"_length": int(n), "__slots__": ()})

    def __init__(self, *args):
        super().__init__(*args)
        if self._nbits == 0:
            self._bits = np.zeros(self._length, dtype=np.uint8)
            self._nbits = self._length
        if self._nbits != self._length:
            raise ValueError(f"{type(self).__name__}: need {self._length} bits")

    @classmethod
    def is_fixed_size(cls):
        return True

    @classmethod
    def type_byte_length(cls):
        return (cls._length + 7) // 8

    @classmethod
    def default(cls):
        return cls()

    @classmethod
    def decode_bytes(cls, data: bytes):
        if len(data) != cls.type_byte_length():
            raise ValueError(f"{cls.__name__}: bad byte length")
        bits = np.unpackbits(np.frombuffer(data, dtype=np.uint8), bitorder="little")
        if bits[cls._length:].any():
            raise ValueError(f"{cls.__name__}: padding bits set")
        return cls(bits[: cls._length])

    def encode_bytes(self) -> bytes:
        return self._packed_bytes()

    def _compute_root(self) -> bytes:
        return _merkleize_chunks(self._chunks(), (self._length + 255) // 256)

    def copy(self):
        return type(self)(self._view().copy())


class Bitlist(_BitsBase, metaclass=_ParamMeta):
    _limit: int = 0

    @classmethod
    def _parametrize(cls, params):
        (n,) = params
        return type(f"Bitlist[{n}]", (Bitlist,), {"_limit": int(n), "__slots__": ()})

    def __init__(self, *args):
        super().__init__(*args)
        if self._nbits > self._limit:
            raise ValueError(f"{type(self).__name__}: exceeds limit {self._limit}")

    @classmethod
    def is_fixed_size(cls):
        return False

    @classmethod
    def default(cls):
        return cls()

    @classmethod
    def decode_bytes(cls, data: bytes):
        if len(data) == 0:
            raise ValueError("Bitlist: empty encoding (delimiter bit required)")
        bits = np.unpackbits(np.frombuffer(data, dtype=np.uint8), bitorder="little")
        # find delimiter: highest set bit
        nz = np.nonzero(bits)[0]
        if len(nz) == 0:
            raise ValueError("Bitlist: missing delimiter bit")
        delim = nz[-1]
        if delim < (len(data) - 1) * 8:
            raise ValueError("Bitlist: delimiter not in last byte")
        return cls(bits[:delim])

    def encode_bytes(self) -> bytes:
        with_delim = np.concatenate([self._view(),
                                     np.array([1], dtype=np.uint8)])
        return np.packbits(with_delim, bitorder="little").tobytes()

    def _compute_root(self) -> bytes:
        return _mix_in_length(
            _merkleize_chunks(self._chunks(), (self._limit + 255) // 256),
            self._nbits,
        )

    def copy(self):
        return type(self)(self._view().copy())

    def append(self, v):
        if self._nbits + 1 > self._limit:
            raise ValueError("Bitlist: append exceeds limit")
        if self._nbits == len(self._bits):  # grow buffer, amortized O(1)
            buf = np.zeros(max(8, 2 * len(self._bits)), dtype=np.uint8)
            buf[: self._nbits] = self._bits[: self._nbits]
            self._bits = buf
        self._bits[self._nbits] = bool(v)
        self._nbits += 1
        self._mark_dirty()


# ---------------------------------------------------------------------------
# Homogeneous collections: List / Vector
# ---------------------------------------------------------------------------


def _chunk_pack_np(arr: np.ndarray) -> bytes:
    """Pack a little-endian basic-value array into 32-byte-aligned bytes."""
    raw = arr.tobytes()
    if len(raw) % 32:
        raw += b"\x00" * (32 - len(raw) % 32)
    return raw


class _SequenceBase(MutableView):
    """Shared machinery for List/Vector.

    Storage: numpy array for basic element types (uint8..64/boolean),
    Python list of child views otherwise.  uint128/uint256 elements use the
    Python-list path (no numpy dtype) with packed-byte merkleization.

    Numpy storage uses an over-allocated buffer `_data` with logical length
    `_len` (amortized O(1) append); `_np_view()` is the live window.
    """

    __slots__ = ("_data", "_len")
    _element_type: type = None  # type: ignore[assignment]

    @classmethod
    def _np_dtype(cls):
        return _BASIC_NP_DTYPES.get(cls._element_type)

    @classmethod
    def _validate_np(cls, arr) -> np.ndarray:
        """Bulk-validate an array for the packed storage path."""
        et = cls._element_type
        arr = np.asarray(arr)
        if arr.ndim != 1:
            raise ValueError(f"{cls.__name__}: need a 1-D array")
        if not (np.issubdtype(arr.dtype, np.integer) or arr.dtype == np.bool_):
            raise TypeError(f"{cls.__name__}: need an integer array, got {arr.dtype}")
        if arr.size:
            mn, mx = int(arr.min()), int(arr.max())
            if mn < 0:
                raise ValueError(f"{cls.__name__}: negative element")
            if et is boolean:
                if mx > 1:
                    raise ValueError(f"{cls.__name__}: boolean element > 1")
            else:
                bits = et.type_byte_length() * 8
                if bits < 64 and mx >> bits:
                    raise ValueError(f"{cls.__name__}: element out of range")
        return np.ascontiguousarray(arr, dtype=_BASIC_NP_DTYPES[et])

    def _np_view(self) -> np.ndarray:
        return self._data[: self._len]

    def _set_np(self, arr: np.ndarray) -> None:
        object.__setattr__(self, "_data", arr)
        object.__setattr__(self, "_len", len(arr))

    def __init__(self, *args):
        super().__init__()
        dtype = self._np_dtype()
        if (len(args) == 1 and isinstance(args[0], np.ndarray)
                and dtype is not None):
            self._set_np(self._validate_np(args[0]))
            return
        if len(args) == 1 and not isinstance(args[0], (bytes, str, int, View)) \
                and isinstance(args[0], Iterable):
            elems = list(args[0])
        elif len(args) == 1 and isinstance(args[0], _SequenceBase):
            elems = list(args[0])
        else:
            elems = list(args)
        if dtype is not None:
            self._set_np(np.array([int(self._element_type(e)) for e in elems],
                                  dtype=dtype))
        else:
            self._data = [self._adopt(self._element_type.coerce_view(e)) for e in elems]
            self._len = len(self._data)

    # -- sequence protocol --

    def __eq__(self, other):
        # Spec code compares SSZ lists against plain Python sequences
        # (e.g. `payload.withdrawals == expected_withdrawals` where the
        # right side is a list) — compare element-wise then
        if isinstance(other, (list, tuple)):
            return (len(self) == len(other)
                    and all(a == b for a, b in zip(self, other)))
        return MutableView.__eq__(self, other)

    def __hash__(self):
        return MutableView.__hash__(self)

    def __len__(self):
        return self._len if self._np_dtype() is not None else len(self._data)

    def __iter__(self):
        et = self._element_type
        if self._np_dtype() is not None:
            return iter(et(int(x)) for x in self._np_view())
        return iter(self._data)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return list(self)[i]
        i = int(i)
        n = len(self)
        if i < 0 or i >= n:
            raise IndexError(f"index {i} out of range for length {n}")
        if self._np_dtype() is not None:
            return self._element_type(int(self._data[i]))
        return self._data[i]

    def __setitem__(self, i, value):
        if isinstance(i, slice):
            # length-preserving slice assignment (the spec shifts the
            # fulu proposer lookahead this way)
            indices = range(*i.indices(len(self)))
            values = list(value)
            if len(values) != len(indices):
                raise ValueError(
                    f"slice assignment length mismatch: "
                    f"{len(indices)} slots, {len(values)} values")
            for j, v in zip(indices, values):
                self[j] = v
            return
        i = int(i)
        n = len(self)
        if i < 0 or i >= n:
            raise IndexError(f"index {i} out of range for length {n}")
        if self._np_dtype() is not None:
            self._data[i] = int(self._element_type(value))
        else:
            self._data[i] = self._adopt(self._element_type.coerce_view(value))
        self._mark_dirty()

    def __contains__(self, item):
        return any(x == item for x in self)

    def index(self, item):
        for j, x in enumerate(self):
            if x == item:
                return j
        raise ValueError(f"{item!r} not in sequence")

    # -- ssz plumbing --

    def _element_roots(self) -> list[bytes]:
        return [el.hash_tree_root() for el in self._data]

    def _merkle_over_elements(self, limit: int | None) -> bytes:
        et = self._element_type
        if is_basic_type(et):
            if self._np_dtype() is not None:
                chunks = _chunk_pack_np(self._np_view())
            else:  # uint128/uint256 python-list storage
                raw = b"".join(e.encode_bytes() for e in self._data)
                if len(raw) % 32:
                    raw += b"\x00" * (32 - len(raw) % 32)
                chunks = raw
            chunk_limit = None
            if limit is not None:
                chunk_limit = (limit * et.type_byte_length() + 31) // 32
            return _merkleize_chunks(chunks, chunk_limit)
        return _merkleize_roots(self._element_roots(), limit)

    def _serialize_elements(self) -> bytes:
        et = self._element_type
        if self._np_dtype() is not None:
            return self._np_view().tobytes()
        if et.is_fixed_size():
            return b"".join(e.encode_bytes() for e in self._data)
        parts = [e.encode_bytes() for e in self._data]
        offset = OFFSET_BYTE_LENGTH * len(parts)
        out = io.BytesIO()
        for p in parts:
            out.write(offset.to_bytes(4, "little"))
            offset += len(p)
        for p in parts:
            out.write(p)
        return out.getvalue()

    @classmethod
    def _deserialize_elements(cls, data: bytes, count_hint: int | None) -> list:
        et = cls._element_type
        if et.is_fixed_size():
            size = et.type_byte_length()
            if len(data) % size:
                raise ValueError(f"{cls.__name__}: byte length not multiple of element size")
            return [et.decode_bytes(data[i:i + size]) for i in range(0, len(data), size)]
        if len(data) == 0:
            return []
        first_offset = int.from_bytes(data[:4], "little")
        if first_offset % OFFSET_BYTE_LENGTH or first_offset > len(data):
            raise ValueError(f"{cls.__name__}: bad first offset {first_offset}")
        count = first_offset // OFFSET_BYTE_LENGTH
        offsets = [int.from_bytes(data[4 * i:4 * i + 4], "little") for i in range(count)]
        offsets.append(len(data))
        elems = []
        for i in range(count):
            if offsets[i + 1] < offsets[i] or offsets[i + 1] > len(data):
                raise ValueError(f"{cls.__name__}: bad offsets")
            elems.append(et.decode_bytes(data[offsets[i]:offsets[i + 1]]))
        return elems

    def copy(self):
        new = type(self).__new__(type(self))
        MutableView.__init__(new)
        if self._np_dtype() is not None:
            new._set_np(self._np_view().copy())
        else:
            object.__setattr__(new, "_data",
                               [new._adopt(e.copy()) for e in self._data])
            object.__setattr__(new, "_len", len(self._data))
        object.__setattr__(new, "_root", self._root)
        return new

    def __repr__(self):
        return f"{type(self).__name__}({list(self)!r})"

    # numpy escape hatch for the TPU sweeps (read-only contract)
    def to_numpy(self) -> np.ndarray:
        if self._np_dtype() is None:
            raise TypeError("to_numpy only for packed basic sequences")
        return self._np_view()

    def set_numpy(self, arr: np.ndarray) -> None:
        if self._np_dtype() is None:
            raise TypeError("set_numpy only for packed basic sequences")
        arr = self._validate_np(arr)
        if isinstance(self, Vector) and len(arr) != type(self)._length:
            raise ValueError("wrong length")
        if isinstance(self, List) and len(arr) > type(self)._limit:
            raise ValueError(f"{type(self).__name__}: exceeds limit")
        self._set_np(arr)
        self._mark_dirty()


class List(_SequenceBase, metaclass=_ParamMeta):
    _limit: int = 0

    @classmethod
    def _parametrize(cls, params):
        et, limit = params
        assert isinstance(et, type) and issubclass(et, View), et
        return type(f"List[{getattr(et, '__name__', et)},{limit}]", (List,),
                    {"_element_type": et, "_limit": int(limit), "__slots__": ()})

    def __init__(self, *args):
        super().__init__(*args)
        if len(self) > self._limit:
            raise ValueError(f"{type(self).__name__}: exceeds limit {self._limit}")

    @classmethod
    def is_fixed_size(cls):
        return False

    @classmethod
    def default(cls):
        return cls()

    @classmethod
    def decode_bytes(cls, data: bytes):
        elems = cls._deserialize_elements(data, None)
        if len(elems) > cls._limit:
            raise ValueError(f"{cls.__name__}: exceeds limit")
        return cls(elems)

    def encode_bytes(self) -> bytes:
        return self._serialize_elements()

    def _compute_root(self) -> bytes:
        return _mix_in_length(self._merkle_over_elements(self._limit), len(self))

    def append(self, value):
        if len(self) + 1 > self._limit:
            raise ValueError(f"{type(self).__name__}: append exceeds limit")
        if self._np_dtype() is not None:
            v = int(self._element_type(value))
            if self._len == len(self._data):  # grow buffer, amortized O(1)
                cap = max(8, 2 * len(self._data))
                buf = np.zeros(cap, dtype=self._np_dtype())
                buf[: self._len] = self._data[: self._len]
                object.__setattr__(self, "_data", buf)
            self._data[self._len] = v
            object.__setattr__(self, "_len", self._len + 1)
        else:
            self._data.append(self._adopt(self._element_type.coerce_view(value)))
            object.__setattr__(self, "_len", len(self._data))
        self._mark_dirty()

    def pop(self):
        if len(self) == 0:
            raise IndexError("pop from empty List")
        if self._np_dtype() is not None:
            last = self._element_type(int(self._data[self._len - 1]))
            object.__setattr__(self, "_len", self._len - 1)
        else:
            last = self._data.pop()
            object.__setattr__(self, "_len", len(self._data))
        self._mark_dirty()
        return last


class Vector(_SequenceBase, metaclass=_ParamMeta):
    _length: int = 0

    @classmethod
    def _parametrize(cls, params):
        et, n = params
        assert isinstance(et, type) and issubclass(et, View), et
        assert int(n) > 0
        return type(f"Vector[{getattr(et, '__name__', et)},{n}]", (Vector,),
                    {"_element_type": et, "_length": int(n), "__slots__": ()})

    def __init__(self, *args):
        super().__init__(*args)
        if len(self) == 0:
            dtype = self._np_dtype()
            if dtype is not None:
                self._set_np(np.zeros(self._length, dtype=dtype))
            else:
                self._data = [self._adopt(self._element_type.default())
                              for _ in range(self._length)]
                self._len = self._length
        if len(self) != self._length:
            raise ValueError(f"{type(self).__name__}: need {self._length} elements, "
                             f"got {len(self)}")

    @classmethod
    def is_fixed_size(cls):
        return cls._element_type.is_fixed_size()

    @classmethod
    def type_byte_length(cls):
        return cls._element_type.type_byte_length() * cls._length

    @classmethod
    def default(cls):
        return cls()

    @classmethod
    def decode_bytes(cls, data: bytes):
        elems = cls._deserialize_elements(data, cls._length)
        if len(elems) != cls._length:
            raise ValueError(f"{cls.__name__}: expected {cls._length} elements, "
                             f"got {len(elems)}")
        return cls(elems)

    def encode_bytes(self) -> bytes:
        return self._serialize_elements()

    def _compute_root(self) -> bytes:
        return self._merkle_over_elements(self._length)


# ---------------------------------------------------------------------------
# Container
# ---------------------------------------------------------------------------


class Container(MutableView):
    """SSZ container; fields declared via class annotations.

    class Checkpoint(Container):
        epoch: uint64
        root: Bytes32
    """

    __slots__ = ("_values",)
    _field_types: dict[str, type] | None = None

    @classmethod
    def fields(cls) -> dict[str, type]:
        if cls.__dict__.get("_field_types") is None:
            out: dict[str, type] = {}
            for klass in reversed(cls.__mro__):
                anns = klass.__dict__.get("__annotations__", {})
                for name, t in anns.items():
                    if name.startswith("_"):
                        continue
                    out[name] = t
            cls._field_types = out
        return cls._field_types

    def __init__(self, **kwargs):
        super().__init__()
        values: dict[str, View] = {}
        ftypes = self.fields()
        for name, t in ftypes.items():
            if name in kwargs:
                values[name] = self._adopt(t.coerce_view(kwargs.pop(name)))
            else:
                values[name] = self._adopt(t.default())
        if kwargs:
            raise TypeError(f"{type(self).__name__}: unknown fields {list(kwargs)}")
        object.__setattr__(self, "_values", values)

    def __getattr__(self, name):
        # only called when normal lookup fails (i.e. not a slot/classattr)
        try:
            return object.__getattribute__(self, "_values")[name]
        except KeyError:
            raise AttributeError(f"{type(self).__name__} has no field {name!r}") from None

    def __setattr__(self, name, value):
        ftypes = self.fields()
        if name in ftypes:
            self._values[name] = self._adopt(ftypes[name].coerce_view(value))
            self._mark_dirty()
        else:
            raise AttributeError(f"{type(self).__name__} has no field {name!r}")

    @classmethod
    def is_fixed_size(cls):
        return all(t.is_fixed_size() for t in cls.fields().values())

    @classmethod
    def type_byte_length(cls):
        assert cls.is_fixed_size()
        return sum(t.type_byte_length() for t in cls.fields().values())

    @classmethod
    def default(cls):
        return cls()

    def encode_bytes(self) -> bytes:
        ftypes = self.fields()
        fixed_parts: list[bytes | None] = []
        var_parts: list[bytes] = []
        for name, t in ftypes.items():
            v = self._values[name]
            if t.is_fixed_size():
                fixed_parts.append(v.encode_bytes())
            else:
                fixed_parts.append(None)
                var_parts.append(v.encode_bytes())
        fixed_len = sum(
            len(p) if p is not None else OFFSET_BYTE_LENGTH for p in fixed_parts)
        out = io.BytesIO()
        offset = fixed_len
        vi = 0
        for p in fixed_parts:
            if p is None:
                out.write(offset.to_bytes(4, "little"))
                offset += len(var_parts[vi])
                vi += 1
            else:
                out.write(p)
        for p in var_parts:
            out.write(p)
        return out.getvalue()

    @classmethod
    def decode_bytes(cls, data: bytes):
        ftypes = cls.fields()
        # pass 1: fixed segment layout
        pos = 0
        offsets: list[int] = []
        fixed_raw: dict[str, bytes] = {}
        for name, t in ftypes.items():
            if t.is_fixed_size():
                size = t.type_byte_length()
                if pos + size > len(data):
                    raise ValueError(f"{cls.__name__}: truncated at field {name}")
                fixed_raw[name] = data[pos:pos + size]
                pos += size
            else:
                if pos + 4 > len(data):
                    raise ValueError(f"{cls.__name__}: truncated offset at {name}")
                offsets.append(int.from_bytes(data[pos:pos + 4], "little"))
                pos += 4
        if offsets:
            if offsets[0] != pos:
                raise ValueError(f"{cls.__name__}: first offset {offsets[0]} != fixed end {pos}")
            bounds = offsets + [len(data)]
            for a, b in zip(bounds, bounds[1:]):
                if b < a:
                    raise ValueError(f"{cls.__name__}: offsets not monotonic")
        elif pos != len(data):
            raise ValueError(f"{cls.__name__}: trailing bytes")
        # pass 2: decode
        values: dict[str, View] = {}
        vi = 0
        for name, t in ftypes.items():
            if t.is_fixed_size():
                values[name] = t.decode_bytes(fixed_raw[name])
            else:
                a, b = offsets[vi], (offsets + [len(data)])[vi + 1]
                values[name] = t.decode_bytes(data[a:b])
                vi += 1
        return cls(**values)

    def _compute_root(self) -> bytes:
        roots = [self._values[n].hash_tree_root() for n in self.fields()]
        return _merkleize_roots(roots, len(roots))

    def copy(self):
        new = type(self).__new__(type(self))
        MutableView.__init__(new)
        object.__setattr__(new, "_values",
                           {n: new._adopt(v.copy()) for n, v in self._values.items()})
        object.__setattr__(new, "_root", self._root)
        return new

    def __repr__(self):
        inner = ", ".join(f"{n}={v!r}" for n, v in self._values.items())
        return f"{type(self).__name__}({inner})"


# ---------------------------------------------------------------------------
# Union
# ---------------------------------------------------------------------------


class Union(MutableView, metaclass=_ParamMeta):
    """SSZ union; options given as Union[TypeA, TypeB, ...]; option 0 may be None."""

    __slots__ = ("_selector", "_value")
    _options: tuple = ()

    @classmethod
    def _parametrize(cls, params):
        opts = tuple(params)
        assert len(opts) >= 1
        if opts[0] is None:
            assert len(opts) >= 2, "None-only union is invalid"
        names = ",".join("None" if o is None else o.__name__ for o in opts)
        return type(f"Union[{names}]", (Union,), {"_options": opts, "__slots__": ()})

    def __init__(self, selector: int = 0, value: Any = None):
        super().__init__()
        selector = int(selector)
        if selector >= len(self._options):
            raise ValueError("Union selector out of range")
        opt = self._options[selector]
        if opt is None:
            if value is not None:
                raise ValueError("Union option None takes no value")
            v = None
        else:
            v = self._adopt(opt.coerce_view(value if value is not None else opt.default()))
        object.__setattr__(self, "_selector", selector)
        object.__setattr__(self, "_value", v)

    @property
    def selector(self) -> int:
        return self._selector

    @property
    def value(self):
        return self._value

    @classmethod
    def is_fixed_size(cls):
        return False

    @classmethod
    def default(cls):
        return cls(0, None if cls._options[0] is None else cls._options[0].default())

    def encode_bytes(self) -> bytes:
        sel = bytes([self._selector])
        if self._value is None:
            return sel
        return sel + self._value.encode_bytes()

    @classmethod
    def decode_bytes(cls, data: bytes):
        if len(data) == 0:
            raise ValueError("Union: empty encoding")
        sel = data[0]
        if sel >= len(cls._options):
            raise ValueError("Union: selector out of range")
        opt = cls._options[sel]
        if opt is None:
            if len(data) != 1:
                raise ValueError("Union: trailing bytes after None")
            return cls(0, None)
        return cls(sel, opt.decode_bytes(data[1:]))

    def _compute_root(self) -> bytes:
        inner = ZERO_CHUNK if self._value is None else self._value.hash_tree_root()
        return hash_eth2(inner + self._selector.to_bytes(32, "little"))

    def copy(self):
        return type(self)(self._selector,
                          None if self._value is None else self._value.copy())

    def __repr__(self):
        return f"{type(self).__name__}(selector={self._selector}, value={self._value!r})"
