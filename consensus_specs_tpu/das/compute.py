"""Cell/proof computation for the DAS workload — the producer side.

The naive spec path (`compute_cells_and_kzg_proofs`) pays, PER CELL, a
Horner evaluation over all 4096 coefficients, a 64-step long division,
and a 4032-point MSM — measured at >570 s for ONE blob on the
pure-Python oracle (the reason the fulu real-blob merkle-proof tests
sat behind `@slow` until this subsystem).  Two structural identities
remove almost all of it, bit-exactly:

1. Cells come from ONE size-8192 FFT of the coefficient form (the
   extension evaluated over the whole bit-reversed extended domain) —
   the same fast path the spec's `compute_cells` already uses.

2. Because every cell coset satisfies x^64 = h_k^64 =: a_k, the
   quotient of f by Z_k = X^64 - a_k needs NO long division — grouping
   coefficients by residue mod 64,

       Q_k[t] = sum_{u >= 1} f[t + 64u] * a_k^(u-1)

   so a single column's proof is one scalar pass plus ONE MSM, and the
   all-columns form factors through the k-independent partials
   D_u = sum_t f[t + 64u] * [s^t]  (63 MSMs total — half a full MSM of
   work per 2 columns instead of one per column) with
   W_k = sum_u a_k^(u-1) * D_u a 63-point MSM each.

MSMs route through the active BLS backend (`device=None`): the device
Pippenger under "jax" (`ops.bls_batch` via `bls.multi_exp`), the host
Pippenger otherwise — both bit-exact vs `g1_lincomb`, so the proofs
equal the oracle's byte-for-byte (pinned by tests/test_das.py).
"""

from __future__ import annotations

import functools

from .. import telemetry
from ..ops.bls import curve as _curve
from . import ciphersuite as cs

M = cs.FIELD_ELEMENTS_PER_BLOB
L = cs.FIELD_ELEMENTS_PER_CELL
P = cs.BLS_MODULUS


# --- field FFTs (host ints, the oracle's recursive shape) -------------------


def _fft(vals, roots):
    if len(vals) == 1:
        return vals
    left = _fft(vals[::2], roots[::2])
    right = _fft(vals[1::2], roots[::2])
    out = [0] * len(vals)
    half = len(left)
    for i, (x, y) in enumerate(zip(left, right)):
        yr = y * roots[i] % P
        out[i] = (x + yr) % P
        out[i + half] = (x - yr) % P
    return out


def _ifft(vals, roots):
    inv_len = pow(len(vals), P - 2, P)
    rev = [roots[0]] + list(roots[:0:-1])
    return [v * inv_len % P for v in _fft(vals, rev)]


def blob_to_poly_ints(blob: bytes) -> list[int]:
    """The blob's evaluation form as validated ints (`blob_to
    _polynomial`)."""
    blob = bytes(blob)
    assert len(blob) == M * cs.BYTES_PER_FIELD_ELEMENT
    out = []
    for i in range(M):
        v = int.from_bytes(blob[i * 32:(i + 1) * 32], cs.KZG_ENDIANNESS)
        assert v < P
        out.append(v)
    return out


def poly_coefficients(blob: bytes) -> list[int]:
    """Coefficient form of the blob polynomial
    (`polynomial_eval_to_coeff`: un-brp, inverse FFT)."""
    evals = blob_to_poly_ints(blob)
    brp = [evals[cs.reverse_bits(i, M)] for i in range(M)]
    return _ifft(brp, list(cs.roots_of_unity(M)))


def compute_cells(blob: bytes) -> list[bytes]:
    """All 128 cells of the extended blob via one size-8192 FFT —
    bit-exact vs the spec's `compute_cells`."""
    with telemetry.span("das.compute_cells"):
        telemetry.count("das.compute.cells_calls")
        coeffs = poly_coefficients(blob)
        ext = _fft(coeffs + [0] * M,
                   list(cs.roots_of_unity(2 * M)))
        ext_brp = [ext[cs.reverse_bits(i, 2 * M)] for i in range(2 * M)]
        return [cs._encode_evals(ext_brp[k * L:(k + 1) * L])
                for k in range(cs.CELLS_PER_EXT_BLOB)]


# --- proofs ------------------------------------------------------------------


def _a_k(cell_index: int) -> int:
    return pow(cs.coset_shift(cell_index), L, P)


def _msm(points, scalars, device: bool | None):
    """Backend-routed MSM returning an oracle Jacobian point.  `None`
    follows the active BLS backend (the spec's `g1_lincomb` routing
    seam); True forces the device Pippenger, False the host one."""
    if device is None:
        from ..ops import bls

        device = bls.backend_name() == "jax"
    live = [(p, int(s) % P) for p, s in zip(points, scalars)
            if int(s) % P != 0 and not _curve.g1.is_inf(p)]
    if not live:
        return _curve.g1.infinity()
    if device:
        from ..ops.bls_batch import g1_multi_exp_device

        return g1_multi_exp_device([p for p, _ in live],
                                   [s for _, s in live])
    return _curve.g1.msm([p for p, _ in live], [s for _, s in live])


def _quotient_scalars(coeffs, a_k: int) -> list[int]:
    """Q_k's 4032 coefficients via the residue-mod-64 grouping (no
    long division) — identical to `divide_polynomialcoeff(f, Z_k)`."""
    out = [0] * (M - L)
    for c in range(L):
        # walk residue class c from the top so each step is one
        # multiply: Q[t] = f[t + 64] + a_k * Q[t + 64]
        acc = 0
        for v in range((M - L) // L - 1, -1, -1):
            t = c + v * L
            acc = (coeffs[t + L] + a_k * acc) % P
            out[t] = acc
    return out


def cell_proof_for_column(blob: bytes, cell_index: int,
                          device: bool | None = None) -> bytes:
    """One column's KZG multiproof for `blob` — one scalar pass + one
    MSM (the sampled-column producer path the un-@slow fulu
    merkle-proof tests ride).  Byte-equal to the proof the oracle's
    `compute_cells_and_kzg_proofs` emits at this index."""
    with telemetry.span("das.cell_proof", cell=int(cell_index)):
        telemetry.count("das.compute.column_proof_calls")
        coeffs = poly_coefficients(blob)
        q = _quotient_scalars(coeffs, _a_k(int(cell_index)))
        pts = [cs.setup_g1_point(t) for t in range(M - L)]
        return _curve.g1_to_bytes(_msm(pts, q, device))


@functools.lru_cache(maxsize=8)
def _cached_cells_and_column_proofs(blob: bytes, columns: tuple,
                                    device: bool | None):
    cells = compute_cells(blob)
    proofs = {k: cell_proof_for_column(blob, k, device=device)
              for k in columns}
    return cells, proofs


def cells_and_column_proofs(blob: bytes, columns,
                            device: bool | None = None):
    """(all 128 cells, {column: proof}) with a small per-process memo —
    the two un-@slow merkle-proof tests share one real blob."""
    return _cached_cells_and_column_proofs(
        bytes(blob), tuple(int(c) for c in columns), device)


def compute_cells_and_kzg_proofs(blob: bytes,
                                 device: bool | None = None):
    """All cells AND all 128 proofs via the k-independent D_u partials
    (63 shared MSMs + one 63-point MSM per column — about 4x less
    point work than 128 independent quotient MSMs, and every MSM a
    device dispatch under the jax backend).  Bit-exact vs the spec
    oracle; the jax-backend spec namespace routes here."""
    with telemetry.span("das.compute_cells_and_proofs"):
        telemetry.count("das.compute.full_calls")
        cells = compute_cells(blob)
        coeffs = poly_coefficients(blob)
        d_points = []
        for u in range(1, M // L):
            pts = [cs.setup_g1_point(t) for t in range(M - u * L)]
            d_points.append(_msm(pts, coeffs[u * L:], device))
        proofs = []
        for k in range(cs.CELLS_PER_EXT_BLOB):
            a = _a_k(k)
            pows, cur = [], 1
            for _ in range(len(d_points)):
                pows.append(cur)
                cur = cur * a % P
            proofs.append(_curve.g1_to_bytes(
                _msm(d_points, pows, device)))
        return cells, proofs
