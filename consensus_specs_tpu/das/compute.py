"""Cell/proof computation for the DAS workload — the producer side.

The naive spec path (`compute_cells_and_kzg_proofs`) pays, PER CELL, a
Horner evaluation over all 4096 coefficients, a 64-step long division,
and a 4032-point MSM — measured at >570 s for ONE blob on the
pure-Python oracle (the reason the fulu real-blob merkle-proof tests
sat behind `@slow` until this subsystem).  Two structural identities
remove almost all of it, bit-exactly:

1. Cells come from ONE size-8192 FFT of the coefficient form (the
   extension evaluated over the whole bit-reversed extended domain) —
   the same fast path the spec's `compute_cells` already uses.

2. Because every cell coset satisfies x^64 = h_k^64 =: a_k, the
   quotient of f by Z_k = X^64 - a_k needs NO long division — grouping
   coefficients by residue mod 64,

       Q_k[t] = sum_{u >= 1} f[t + 64u] * a_k^(u-1)

   so a single column's proof is one scalar pass plus ONE MSM, and the
   all-columns form factors through the k-independent partials
   D_u = sum_t f[t + 64u] * [s^t]  (63 MSMs total — half a full MSM of
   work per 2 columns instead of one per column) with
   W_k = sum_u a_k^(u-1) * D_u a 63-point MSM each.

MSMs route through the active BLS backend (`device=None`): the device
Pippenger under "jax" (`ops.bls_batch` via `bls.multi_exp`), the host
Pippenger otherwise — both bit-exact vs `g1_lincomb`, so the proofs
equal the oracle's byte-for-byte (pinned by tests/test_das.py).
"""

from __future__ import annotations

import functools
import os

from .. import telemetry
from ..ops.bls import curve as _curve
from . import ciphersuite as cs

M = cs.FIELD_ELEMENTS_PER_BLOB
L = cs.FIELD_ELEMENTS_PER_CELL
P = cs.BLS_MODULUS
K = M // L                      # residue classes / points per FK20 vector
N_EXT = cs.CELLS_PER_EXT_BLOB   # FK20 circulant order (128)


# --- field FFTs (host ints, the oracle's recursive shape) -------------------


def _fft(vals, roots):
    if len(vals) == 1:
        return vals
    left = _fft(vals[::2], roots[::2])
    right = _fft(vals[1::2], roots[::2])
    out = [0] * len(vals)
    half = len(left)
    for i, (x, y) in enumerate(zip(left, right)):
        yr = y * roots[i] % P
        out[i] = (x + yr) % P
        out[i + half] = (x - yr) % P
    return out


def _ifft(vals, roots):
    inv_len = pow(len(vals), P - 2, P)
    rev = [roots[0]] + list(roots[:0:-1])
    return [v * inv_len % P for v in _fft(vals, rev)]


def blob_to_poly_ints(blob: bytes) -> list[int]:
    """The blob's evaluation form as validated ints (`blob_to
    _polynomial`)."""
    blob = bytes(blob)
    assert len(blob) == M * cs.BYTES_PER_FIELD_ELEMENT
    out = []
    for i in range(M):
        v = int.from_bytes(blob[i * 32:(i + 1) * 32], cs.KZG_ENDIANNESS)
        assert v < P
        out.append(v)
    return out


def _device_default() -> bool:
    from ..ops import bls

    return bls.backend_name() == "jax"


def poly_coefficients(blob: bytes,
                      device: bool | None = None) -> list[int]:
    """Coefficient form of the blob polynomial
    (`polynomial_eval_to_coeff`: un-brp, inverse FFT).  Under the jax
    backend the inverse FFT is one `fr_batch.fr_fft` dispatch —
    value-identical to the host recursion (exact mod-p arithmetic)."""
    evals = blob_to_poly_ints(blob)
    brp = [evals[cs.reverse_bits(i, M)] for i in range(M)]
    roots = list(cs.roots_of_unity(M))
    if device is None:
        device = _device_default()
    if device:
        from ..ops.fr_batch import fr_fft

        return fr_fft([brp], roots, inverse=True)[0]
    return _ifft(brp, roots)


def _extended_evals(coeffs, device: bool | None = None) -> list[int]:
    """The extension: the blob polynomial evaluated over the whole
    size-8192 domain (natural order)."""
    if device is None:
        device = _device_default()
    padded = list(coeffs) + [0] * M
    roots = list(cs.roots_of_unity(2 * M))
    if device:
        from ..ops.fr_batch import fr_fft

        return fr_fft([padded], roots)[0]
    return _fft(padded, roots)


def compute_cells(blob: bytes,
                  device: bool | None = None) -> list[bytes]:
    """All 128 cells of the extended blob via one size-8192 FFT —
    bit-exact vs the spec's `compute_cells`; one device dispatch per
    transform under the jax backend."""
    with telemetry.span("das.compute_cells"):
        telemetry.count("das.compute.cells_calls")
        coeffs = poly_coefficients(blob, device=device)
        ext = _extended_evals(coeffs, device=device)
        ext_brp = [ext[cs.reverse_bits(i, 2 * M)] for i in range(2 * M)]
        return [cs._encode_evals(ext_brp[k * L:(k + 1) * L])
                for k in range(cs.CELLS_PER_EXT_BLOB)]


# --- proofs ------------------------------------------------------------------


def _a_k(cell_index: int) -> int:
    return pow(cs.coset_shift(cell_index), L, P)


def _msm(points, scalars, device: bool | None):
    """Backend-routed MSM returning an oracle Jacobian point.  `None`
    follows the active BLS backend (the spec's `g1_lincomb` routing
    seam); True forces the device Pippenger, False the host one."""
    if device is None:
        from ..ops import bls

        device = bls.backend_name() == "jax"
    live = [(p, int(s) % P) for p, s in zip(points, scalars)
            if int(s) % P != 0 and not _curve.g1.is_inf(p)]
    if not live:
        return _curve.g1.infinity()
    if device:
        from ..ops.bls_batch import g1_multi_exp_device

        return g1_multi_exp_device([p for p, _ in live],
                                   [s for _, s in live])
    return _curve.g1.msm([p for p, _ in live], [s for _, s in live])


def _quotient_scalars(coeffs, a_k: int) -> list[int]:
    """Q_k's 4032 coefficients via the residue-mod-64 grouping (no
    long division) — identical to `divide_polynomialcoeff(f, Z_k)`."""
    out = [0] * (M - L)
    for c in range(L):
        # walk residue class c from the top so each step is one
        # multiply: Q[t] = f[t + 64] + a_k * Q[t + 64]
        acc = 0
        for v in range((M - L) // L - 1, -1, -1):
            t = c + v * L
            acc = (coeffs[t + L] + a_k * acc) % P
            out[t] = acc
    return out


def cell_proof_for_column(blob: bytes, cell_index: int,
                          device: bool | None = None) -> bytes:
    """One column's KZG multiproof for `blob` — one scalar pass + one
    MSM (the sampled-column producer path the un-@slow fulu
    merkle-proof tests ride).  Byte-equal to the proof the oracle's
    `compute_cells_and_kzg_proofs` emits at this index."""
    with telemetry.span("das.cell_proof", cell=int(cell_index)):
        telemetry.count("das.compute.column_proof_calls")
        coeffs = poly_coefficients(blob)
        q = _quotient_scalars(coeffs, _a_k(int(cell_index)))
        pts = [cs.setup_g1_point(t) for t in range(M - L)]
        return _curve.g1_to_bytes(_msm(pts, q, device))


@functools.lru_cache(maxsize=8)
def _cached_cells_and_column_proofs(blob: bytes, columns: tuple,
                                    device: bool | None):
    cells = compute_cells(blob)
    proofs = {k: cell_proof_for_column(blob, k, device=device)
              for k in columns}
    return cells, proofs


def cells_and_column_proofs(blob: bytes, columns,
                            device: bool | None = None):
    """(all 128 cells, {column: proof}) with a small per-process memo —
    the two un-@slow merkle-proof tests share one real blob."""
    return _cached_cells_and_column_proofs(
        bytes(blob), tuple(int(c) for c in columns), device)


# --- FK20: all proofs from O(log) FFTs + one MSM ----------------------------
#
# Every cell coset satisfies x^64 = a_k = w_128^rev7(k) (w_128 the
# order-128 root), so the 128 proofs are the order-128 G1 FFT of the
# D_u partials:
#
#     proofs (cell order) = brp( FFT_128([D_1 .. D_63, inf x 65]) )
#
# and the D_u themselves factor through per-residue circular
# convolutions against the trusted setup: with b^c_m = f[c + 64m] and
# x^c_v = [s^(c + 64v)],
#
#     D_u = [ IFFT_128( sum_c FFT_fr(B^c) * X_fft^c ) ]_(128-u) mod 128
#
# where B^c is the circulant embedding (B_0 = b_0, B_(128-m) = b_m) and
# X_fft^c the order-128 G1 FFT of [x^c_0 .. x^c_63, inf x 64] — the
# bit-reversed Toeplitz/circulant extended-setup tables, computed as
# ONE batched 64-lane G1 FFT at first use and pinned device-resident
# (`_fk20_setup_tables`).  Per blob: one batched field FFT, one
# grouped Pippenger MSM (`fk20_hext_device`), one G1 IFFT + gather +
# G1 FFT — ~30x less point work than the D_u route's 63 wide MSMs +
# 128 narrow ones, byte-equal proofs (pinned by tests/test_das.py and
# the kzg_7594 vectors).


@functools.lru_cache(maxsize=1)
def _fk20_setup_tables():
    """Device-pinned X_fft tables (one per residue class), built by one
    batched G1-FFT dispatch the first time a proof is produced and kept
    on device for the life of the process."""
    import numpy as np

    from ..ops.bls_batch import g1fft_jax as gf

    with telemetry.span("das.fk20_setup"):
        telemetry.count("das.fk20.setup_builds")
        xs, ys, zs = [], [], []
        for c in range(L):
            pts = [cs.setup_g1_point(c + L * v) for v in range(K)]
            x, y, z = gf.points_to_limbs(pts, pad_to=N_EXT)
            xs.append(x)
            ys.append(y)
            zs.append(z)
        return gf.g1_fft_device(np.stack(xs), np.stack(ys),
                                np.stack(zs))


def _fk20_proofs_device(coeffs) -> list[bytes]:
    """All 128 compressed proofs for a coefficient-form blob polynomial
    via the FK20 pipeline above."""
    import numpy as np

    from ..ops.bls_batch import g1fft_jax as gf
    from ..ops.fr_batch import fr_fft

    with telemetry.span("das.fk20_proofs"):
        telemetry.count("das.compute.fk20_calls")
        rows = []
        for c in range(L):
            row = [0] * N_EXT
            row[0] = int(coeffs[c])
            for m in range(1, K):
                row[N_EXT - m] = int(coeffs[c + L * m])
            rows.append(row)
        sfft = fr_fft(rows, list(cs.roots_of_unity(N_EXT)))
        hext = gf.fk20_hext_device(*_fk20_setup_tables(), sfft)
        cg = gf.g1_fft_device(*(c[None] for c in hext), inverse=True)
        # gather E_d = C_(127-d) for d < 63, infinity beyond (Z = 0
        # masks the lane; the stale x/y limbs are dead under the
        # branchless is_inf selects)
        import jax.numpy as jnp

        idx = np.array([(N_EXT - 1 - d) % N_EXT for d in range(N_EXT)])
        keep = np.arange(N_EXT) < (K - 1)
        ex, ey, ez = (jnp.asarray(c)[:, idx] for c in cg)
        ez = jnp.where(jnp.asarray(keep)[None, :, None], ez, 0)
        out = gf.g1_fft_device(ex, ey, ez)
        pts = gf.limbs_to_oracle_list(out)
        return [_curve.g1_to_bytes(pts[cs.reverse_bits(k, N_EXT)])
                for k in range(N_EXT)]


def _du_proofs(coeffs, device: bool | None) -> list[bytes]:
    """The D_u route (63 shared MSMs + one 63-point MSM per column) —
    kept as the FK20 benchmark baseline and the host-route producer."""
    d_points = []
    for u in range(1, K):
        pts = [cs.setup_g1_point(t) for t in range(M - u * L)]
        d_points.append(_msm(pts, coeffs[u * L:], device))
    proofs = []
    for k in range(N_EXT):
        a = _a_k(k)
        pows, cur = [], 1
        for _ in range(len(d_points)):
            pows.append(cur)
            cur = cur * a % P
        proofs.append(_curve.g1_to_bytes(_msm(d_points, pows, device)))
    return proofs


def _producer_route(device: bool) -> str:
    """FK20 on the device path unless CST_DAS_PRODUCER=du pins the D_u
    baseline (the bench worker measures both); the host path keeps the
    D_u shape (no device kernels to amortize)."""
    if not device:
        return "du"
    route = os.environ.get("CST_DAS_PRODUCER", "fk20")
    return "du" if route == "du" else "fk20"


def compute_cells_and_kzg_proofs(blob: bytes,
                                 device: bool | None = None,
                                 route: str | None = None):
    """All cells AND all 128 proofs — the FK20 pipeline under the jax
    backend (O(log) FFTs + one MSM), the D_u partial route otherwise
    (or when `route='du'` / CST_DAS_PRODUCER=du pins the baseline).
    Byte-exact vs the spec oracle on every route; the jax-backend spec
    namespace routes here."""
    if device is None:
        device = _device_default()
    if route is None:
        route = _producer_route(device)
    with telemetry.span("das.compute_cells_and_proofs", route=route):
        telemetry.count("das.compute.full_calls")
        cells = compute_cells(blob, device=device)
        coeffs = poly_coefficients(blob, device=device)
        if route == "fk20":
            proofs = _fk20_proofs_device(coeffs)
        else:
            proofs = _du_proofs(coeffs, device)
        return cells, proofs
