"""Erasure recovery for PeerDAS extended blobs — the device decode path.

The fulu oracle (`recover_cells_and_kzg_proofs`) reconstructs a blob
polynomial from any >= 50% of its 128 cells by the classic
Reed-Solomon-via-FFT dance, then re-proves every cell with the naive
O(n^2) producer.  Both halves are pure recursive Python — minutes per
blob — which is why the super-node scenario (ingest damaged columns,
reconstruct, re-prove, re-serve) had no measurable path until now.

This module computes the SAME bytes on two routes:

host route (`recover_cells_and_kzg_proofs_host`)
    the spec oracle verbatim (its own `recover_polynomialcoeff` +
    per-coset quotient producer) — the breaker's degraded route and the
    bench baseline.  Bit-exact by construction.

device route (`recover_cells_and_kzg_proofs_async`)
    coset-structured decode: the vanishing polynomial over the missing
    cosets is built HOST-side from the short order-128 product (at most
    64 monomial multiplies — the stride-64 embedding into the order-8192
    domain is free), and every heavy step is an `fr_batch.fr_fft`
    dispatch on the extended domain —

        Z(x)   = FFT(zero_poly)                      [forward]
        (E*Z)  = IFFT(Z(x) * E(x))                   [inverse]
        coset  = FFT(shift^i * ..) for E*Z and Z     [forward, batch=2]
        P(x)   = IFFT(coset quotient) / shift^i      [inverse]

    two extended-domain FFT round-trips, with the coset quotient done by
    one host Montgomery batch-inversion (the coset is disjoint from the
    domain, so Z never vanishes there).  The recovered coefficients then
    re-prove through the FK20 producer (`compute._fk20_proofs_device`)
    and re-evaluate through the same device FFT that serves
    `compute_cells`.  Byte-identical output to the oracle on every
    surviving-set shape (pinned by tests/test_das.py and the kzg_7594
    recover vectors).

Facades: `*_async` settles through `serve.futures.DeviceFuture` (the
zero-poly FFT dispatches eagerly; everything else runs at settle time),
`recover_cells_and_kzg_proofs` is the sync wrapper, and
`CST_DAS_RECOVER_ROUTE=host` pins the oracle (the serve executor's
degraded mode uses the host entry point directly).
"""

from __future__ import annotations

import os

from .. import telemetry
from ..serve.futures import DeviceFuture
from ..telemetry import costmodel
from . import ciphersuite as cs
from . import compute as dc

P = cs.BLS_MODULUS
M = cs.FIELD_ELEMENTS_PER_BLOB
M_EXT = cs.FIELD_ELEMENTS_PER_EXT_BLOB
L = cs.FIELD_ELEMENTS_PER_CELL
N_EXT = cs.CELLS_PER_EXT_BLOB
_SHIFT = cs.PRIMITIVE_ROOT_OF_UNITY


def _assert_recoverable(cell_indices, cells) -> None:
    """The spec oracle's argument contract, mirrored bit-for-bit so both
    routes reject exactly the same inputs (AssertionError, like the
    oracle)."""
    assert len(cell_indices) == len(cells)
    assert N_EXT // 2 <= len(cell_indices) <= N_EXT
    assert len(cell_indices) == len(set(cell_indices))
    for cell_index in cell_indices:
        assert cell_index < N_EXT
    for cell in cells:
        assert len(cell) == cs.BYTES_PER_CELL


def _cell_rows(cells) -> list[list[int]]:
    return [[int.from_bytes(
        bytes(cell)[i * cs.BYTES_PER_FIELD_ELEMENT:
                    (i + 1) * cs.BYTES_PER_FIELD_ELEMENT],
        cs.KZG_ENDIANNESS) for i in range(L)] for cell in cells]


def _short_vanishing(missing_cell_indices) -> list[int]:
    """Coefficients of prod (X - w_128^rev7(k)) over the missing cells —
    the order-128 vanishing polynomial the oracle stride-embeds into the
    extended domain (at most 64 monomial multiplies, host arithmetic)."""
    roots = cs.roots_of_unity(N_EXT)
    poly = [1]
    for k in missing_cell_indices:
        r = roots[cs.reverse_bits(int(k), N_EXT)]
        nxt = [0] * (len(poly) + 1)
        for i, c in enumerate(poly):
            nxt[i] = (nxt[i] - c * r) % P
            nxt[i + 1] = (nxt[i + 1] + c) % P
        poly = nxt
    return poly


def construct_vanishing_poly(missing_cell_indices) -> list[int]:
    """The extended-domain vanishing polynomial: the short order-128
    product stride-64 embedded into 8192 coefficients (the oracle's
    `construct_vanishing_polynomial`, ints instead of field wrappers)."""
    short = _short_vanishing(missing_cell_indices)
    out = [0] * M_EXT
    for i, c in enumerate(short):
        out[i * L] = c
    return out


def _batch_inverse(vals: list[int]) -> list[int]:
    """Montgomery's trick: n inversions for one modpow + 3n mulmods."""
    pref = [1] * (len(vals) + 1)
    for i, v in enumerate(vals):
        pref[i + 1] = pref[i] * v % P
    inv = pow(pref[-1], P - 2, P)
    out = [0] * len(vals)
    for i in range(len(vals) - 1, -1, -1):
        out[i] = pref[i] * inv % P
        inv = inv * vals[i] % P
    return out


def _shift_scale(vals, factor: int) -> list[int]:
    out, cur = [], 1
    for v in vals:
        out.append(v * cur % P)
        cur = cur * factor % P
    return out


def recover_coefficients_device(cell_indices, rows,
                                zero_fut=None) -> list[int]:
    """The decode half: surviving cells (field-element rows in stored
    coset order) -> the 4096 blob polynomial coefficients, every FFT a
    device dispatch.  `zero_fut` lets the async facade pre-dispatch the
    zero-poly evaluation."""
    from ..ops.fr_batch import fr_fft, fr_fft_async

    roots = list(cs.roots_of_unity(M_EXT))
    have = {int(k) for k in cell_indices}
    missing = [k for k in range(N_EXT) if k not in have]
    zero_poly = construct_vanishing_poly(missing)
    with telemetry.span("das.recover_decode", cells=len(rows),
                        missing=len(missing)):
        telemetry.count("das.recover.decode_calls")
        telemetry.count("das.recover.missing_cells", len(missing))
        if zero_fut is None:
            zero_fut = fr_fft_async([zero_poly], roots)
        ext_rbo = [0] * M_EXT
        for k, row in zip(cell_indices, rows):
            ext_rbo[int(k) * L:(int(k) + 1) * L] = [int(v) % P
                                                    for v in row]
        ext = [ext_rbo[cs.reverse_bits(i, M_EXT)] for i in range(M_EXT)]
        zero_eval = zero_fut.result()[0]
        prod = [a * b % P for a, b in zip(zero_eval, ext)]
        ez_coeffs = fr_fft([prod], roots, inverse=True)[0]
        coset = fr_fft([_shift_scale(ez_coeffs, _SHIFT),
                        _shift_scale(zero_poly, _SHIFT)], roots)
        quotient = [a * zi % P for a, zi
                    in zip(coset[0], _batch_inverse(coset[1]))]
        shifted = fr_fft([quotient], roots, inverse=True)[0]
        coeffs = _shift_scale(shifted, pow(_SHIFT, P - 2, P))[:M]
    costmodel.sample_watermark("das.recover_decode")
    return coeffs


# --- host route (the oracle, the breaker's degraded mode) --------------------


def recover_cells_and_kzg_proofs_host(cell_indices, cells):
    """The pure-Python spec oracle end to end (decode + naive per-coset
    re-prove).  Slow — this is the degraded route and the bench
    baseline, not the serving path."""
    from ..models.builder import build_spec

    fulu = build_spec("fulu", "mainnet")
    _assert_recoverable(cell_indices, cells)
    with telemetry.span("das.recover_host", cells=len(cells)):
        telemetry.count("das.recover.host_calls")
        cosets_evals = [fulu.cell_to_coset_evals(bytes(cell))
                        for cell in cells]
        coeffs = fulu.recover_polynomialcoeff(
            [int(k) for k in cell_indices], cosets_evals)
        out_cells, out_proofs = \
            fulu.compute_cells_and_kzg_proofs_polynomialcoeff(coeffs)
        return ([bytes(c) for c in out_cells],
                [bytes(p) for p in out_proofs])


# --- device route ------------------------------------------------------------


def _recover_route(device: bool | None) -> bool:
    """True -> device decode + FK20 re-prove.  `CST_DAS_RECOVER_ROUTE=
    host` pins the oracle (the bench baseline switch); otherwise follow
    the active BLS backend like every other das entry point."""
    if os.environ.get("CST_DAS_RECOVER_ROUTE", "") == "host":
        return False
    if device is not None:
        return bool(device)
    from ..ops import bls

    return bls.backend_name() == "jax"


def recover_cells_and_kzg_proofs_async(cell_indices, cells,
                                       device: bool | None = None
                                       ) -> DeviceFuture:
    """Deferred (cells, proofs) recovery.  Argument validation and the
    zero-poly FFT dispatch happen eagerly; decode, re-evaluation, and
    the FK20 re-prove run at settle time with every device fetch going
    through `DeviceFuture.result()` (the sanctioned settle seam).
    `device=False` (or CST_DAS_RECOVER_ROUTE=host) answers on the spec
    oracle immediately."""
    if not _recover_route(device):
        try:
            return DeviceFuture.settled(recover_cells_and_kzg_proofs_host(
                cell_indices, cells))
        except Exception as exc:
            return DeviceFuture.failed(exc)

    from ..ops.fr_batch import fr_fft_async

    _assert_recoverable(cell_indices, cells)
    rows = _cell_rows(cells)
    indices = [int(k) for k in cell_indices]
    have = set(indices)
    missing = [k for k in range(N_EXT) if k not in have]
    with telemetry.span("das.recover_device", cells=len(cells),
                        missing=len(missing)):
        telemetry.count("das.recover.device_calls")
        # stage 1 dispatches NOW: the zero-poly evaluation depends only
        # on WHICH cells are missing, so it overlaps the caller's next
        # host prep (and the row parse above)
        zero_fut = fr_fft_async([construct_vanishing_poly(missing)],
                                list(cs.roots_of_unity(M_EXT)))
    costmodel.sample_watermark("das.recover_device")

    def _finish(fut: DeviceFuture, timeout=None) -> None:
        try:
            coeffs = recover_coefficients_device(indices, rows,
                                                 zero_fut=zero_fut)
            ext = dc._extended_evals(coeffs, device=True)
            ext_brp = [ext[cs.reverse_bits(i, M_EXT)]
                       for i in range(M_EXT)]
            out_cells = [cs._encode_evals(ext_brp[k * L:(k + 1) * L])
                         for k in range(N_EXT)]
            out_proofs = dc._fk20_proofs_device(coeffs)
            fut.set_result((out_cells, out_proofs))
        except Exception as exc:
            if fut.done():
                raise
            fut.set_exception(exc)

    return DeviceFuture(waiter=_finish)


def recover_cells_and_kzg_proofs(cell_indices, cells,
                                 device: bool | None = None):
    """Synchronous facade over `recover_cells_and_kzg_proofs_async`; the
    fetches live in `serve.futures`."""
    return recover_cells_and_kzg_proofs_async(cell_indices, cells,
                                              device=device).result()
