"""Data-column sampling rounds — sidecar-shaped checks over the
batched verifier.

A PeerDAS node sampling column `c` receives, per block, one
DataColumnSidecar: the c-th cell of every blob, the blob commitments,
the per-cell proofs, and a Merkle proof that the commitment list is in
the block body.  Verifying it is two independent halves:

  host   the commitment-INCLUSION proof (a sha256 Merkle branch walk —
         `verify_inclusion`, the spec's `is_valid_merkle_branch`);
  device the batched CELL checks (`das.verify.verify_cell_proof_batch`
         — all of the column's cells in one RLC pairing equation).

`DasSample` is the spec-free payload shape the serve executor's
`submit_das_sample` request kind carries (plain bytes — a serving
queue must not hold spec objects), `sample_from_sidecar` adapts a
built-spec `DataColumnSidecar`, and `sample_from_matrix` cuts column
samples out of a flat sampling matrix (`ciphersuite.closed_form
_matrix` — the bench/loadgen source).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from hashlib import sha256

from .. import telemetry
from ..serve.futures import DeviceFuture, FutureTimeout
from . import ciphersuite as cs
from . import verify as _verify


@dataclass
class InclusionProof:
    """One SSZ single-branch proof: `leaf` hashes up `branch` at
    subtree position `index` to `root`."""

    leaf: bytes
    branch: list
    index: int
    root: bytes


@dataclass
class DasSample:
    """One sampled data column as plain bytes (the serve payload)."""

    column_index: int
    commitments: list           # 48B per blob (row commitments)
    cells: list                 # 2048B each, this column's cell per row
    proofs: list                # 48B each
    inclusion: InclusionProof | None = None


def verify_inclusion(proof: InclusionProof) -> bool:
    """The spec's `is_valid_merkle_branch` (host sha256; depth is the
    branch length)."""
    value = bytes(proof.leaf)
    for i, sibling in enumerate(proof.branch):
        if (int(proof.index) >> i) & 1:
            value = sha256(bytes(sibling) + value).digest()
        else:
            value = sha256(value + bytes(sibling)).digest()
    return value == bytes(proof.root)


def _host_precheck(sample: DasSample) -> bool | None:
    """The device-free front half shared by every route: False on a
    structural or inclusion reject (cheap rejects never touch the
    device), None when the cell checks still have to decide."""
    if not (len(sample.commitments) == len(sample.cells)
            == len(sample.proofs)) or not sample.cells:
        telemetry.count("das.sample.rejected_structural")
        return False
    if int(sample.column_index) >= cs.CELLS_PER_EXT_BLOB:
        telemetry.count("das.sample.rejected_structural")
        return False
    if sample.inclusion is not None \
            and not verify_inclusion(sample.inclusion):
        telemetry.count("das.sample.rejected_inclusion")
        return False
    return None


def verify_sample_async(sample: DasSample,
                        device: bool | None = None) -> DeviceFuture:
    """Full sampling check for one column: structural shape + the host
    inclusion walk first, then the batched cell checks as ONE device
    batch.  Settles to bool; malformed tuples raise (the serve
    executor poisons exactly that handle, like every other request
    kind)."""
    with telemetry.span("das.verify_sample",
                        column=int(sample.column_index),
                        rows=len(sample.cells)):
        telemetry.count("das.sample.calls")
        early = _host_precheck(sample)
        if early is not None:
            return DeviceFuture.settled(early)
        return _verify.verify_cell_proof_batch_async(
            sample.commitments,
            [int(sample.column_index)] * len(sample.cells),
            sample.cells, sample.proofs, device=device)


def verify_sample(sample: DasSample, device: bool | None = None) -> bool:
    """Synchronous facade over `verify_sample_async`."""
    return verify_sample_async(sample, device=device).result()


def verify_sample_group_async(samples,
                              device: bool | None = True) -> DeviceFuture:
    """ALL the given samples' cell statements as ONE RLC device batch
    (the serve executor's per-pump cross-sample fold): host prechecks
    run per sample (structural/inclusion rejects settle False without
    touching the device), the surviving samples' statements concatenate
    into a single `verify_cell_proof_batch_async` dispatch, and a
    failed batch verdict rechecks per SAMPLE so each request still gets
    its own answer.  Settles to a list of bools aligned with
    `samples`."""
    samples = list(samples)
    verdicts: list[bool | None] = [None] * len(samples)
    live: list[int] = []
    for i, sample in enumerate(samples):
        early = _host_precheck(sample)
        if early is not None:
            verdicts[i] = early
        else:
            live.append(i)
    if not live:
        return DeviceFuture.settled([bool(v) for v in verdicts])
    coms: list = []
    idxs: list = []
    cells: list = []
    proofs: list = []
    for i in live:
        s = samples[i]
        coms.extend(s.commitments)
        cells.extend(s.cells)
        proofs.extend(s.proofs)
        idxs.extend([int(s.column_index)] * len(s.cells))
    with telemetry.span("das.verify_sample_group", samples=len(samples),
                        live=len(live), cells=len(cells)):
        telemetry.count("das.sample.group_calls")
        telemetry.count("das.sample.group_samples", len(live))
        batch_fut = _verify.verify_cell_proof_batch_async(
            coms, idxs, cells, proofs, device=device)

    def _finish(fut: DeviceFuture, timeout=None) -> None:
        # the bounded-wait contract: spend the caller's budget as a
        # declining deadline across the internal settles, and let a
        # FutureTimeout PROPAGATE unsettled (retrying stays legal and
        # the serve executor re-queues the batch)
        deadline = None if timeout is None \
            else time.perf_counter() + float(timeout)

        def remaining():
            if deadline is None:
                return None
            return max(deadline - time.perf_counter(), 1e-3)

        try:
            if batch_fut.result(timeout=remaining()):
                for i in live:
                    verdicts[i] = True
            else:
                # one bad sample must not fail its pump-mates: recheck
                # per sample (each its own small batch)
                telemetry.count("das.sample.group_recheck")
                futs = [(i, verify_sample_async(samples[i],
                                                device=device))
                        for i in live]
                for i, f in futs:
                    verdicts[i] = bool(f.result(timeout=remaining()))
            fut.set_result([bool(v) for v in verdicts])
        except FutureTimeout:
            raise
        except Exception as exc:
            if fut.done():
                raise
            fut.set_exception(exc)

    return DeviceFuture(waiter=_finish)


def verify_sample_host(sample: DasSample) -> bool:
    """The pure-host route (the serve executor's degraded-mode oracle
    for the `das` kind) — same verdict as the device route, and
    deliberately independent of the async dispatch plumbing: a sick
    device layer must not be able to take the degraded mode down with
    it."""
    early = _host_precheck(sample)
    if early is not None:
        return early
    return _verify.verify_cell_proof_batch_host(
        sample.commitments,
        [int(sample.column_index)] * len(sample.cells),
        sample.cells, sample.proofs)


def sample_from_matrix(commitments, cell_indices, cells, proofs,
                       column_index: int) -> DasSample:
    """Cut one column's sample out of a flat sampling matrix (the
    `closed_form_matrix` / `verify_cell_kzg_proof_batch` argument
    shape)."""
    column_index = int(column_index)
    rows = [k for k, c in enumerate(cell_indices)
            if int(c) == column_index]
    return DasSample(
        column_index=column_index,
        commitments=[bytes(commitments[k]) for k in rows],
        cells=[bytes(cells[k]) for k in rows],
        proofs=[bytes(proofs[k]) for k in rows],
    )


def sample_from_sidecar(spec, sidecar) -> DasSample:
    """Adapt a built-spec `DataColumnSidecar` (commitment list root +
    inclusion branch against the sidecar's block-body root)."""
    gindex = spec.get_generalized_index(spec.BeaconBlockBody,
                                        "blob_kzg_commitments")
    inclusion = InclusionProof(
        leaf=bytes(spec.hash_tree_root(sidecar.kzg_commitments)),
        branch=[bytes(b) for b in
                sidecar.kzg_commitments_inclusion_proof],
        index=int(spec.get_subtree_index(gindex)),
        root=bytes(sidecar.signed_block_header.message.body_root),
    )
    return DasSample(
        column_index=int(sidecar.index),
        commitments=[bytes(c) for c in sidecar.kzg_commitments],
        cells=[bytes(c) for c in sidecar.column],
        proofs=[bytes(p) for p in sidecar.kzg_proofs],
        inclusion=inclusion,
    )
