"""Host-side DAS statement parsing — the fulu sampling spec's wire
boundary, spec-build-free.

Mirrors `models/fulu/polynomial_commitments_sampling.py`'s
`verify_cell_kzg_proof_batch` front half exactly (same asserts, same
dedup expression, same Fiat-Shamir serialization) so the device path in
`das.verify` starts from the identical parsed statement the oracle
verifies — accept/reject parity is pinned by tests/test_das.py.

Also holds the coset machinery the kernels need in host-int form:
`coset_shift(k)` / `coset_points(k)` (the brp domain slice IS
h_k * (order-64 subgroup in bit-reversed order) — no re-sort anywhere),
the rev-folded inverse-DFT matrix behind `fr_batch.coset_interpolate
_sum`, and the closed-form sampling matrices (degree-65 polynomials:
every cell, proof and commitment is a 1-3 scalar-mult closed form) that
give the bench/smoke rounds real pairing work without paying a 128-MSM
`compute_cells_and_kzg_proofs` per blob.
"""

from __future__ import annotations

import functools
import json
from dataclasses import dataclass
from hashlib import sha256
from pathlib import Path

from ..ops.bls import ciphersuite as _bls_cs
from ..ops.bls import curve as _curve

# the scalar field (the KZG BLS_MODULUS) — same constant fr_batch keys
# its kernels on
BLS_MODULUS = _curve.R
PRIMITIVE_ROOT_OF_UNITY = 7

# both checked-in presets pin the mainnet polynomial degree (the
# trusted setup has exactly this many monomial points)
FIELD_ELEMENTS_PER_BLOB = 4096
FIELD_ELEMENTS_PER_CELL = 64
FIELD_ELEMENTS_PER_EXT_BLOB = 2 * FIELD_ELEMENTS_PER_BLOB
CELLS_PER_EXT_BLOB = FIELD_ELEMENTS_PER_EXT_BLOB // FIELD_ELEMENTS_PER_CELL

BYTES_PER_FIELD_ELEMENT = 32
BYTES_PER_CELL = FIELD_ELEMENTS_PER_CELL * BYTES_PER_FIELD_ELEMENT
BYTES_PER_COMMITMENT = 48
BYTES_PER_PROOF = 48
KZG_ENDIANNESS = "big"
RANDOM_CHALLENGE_KZG_CELL_BATCH_DOMAIN = b"RCKZGCBATCH__V1_"
G1_POINT_AT_INFINITY = b"\xc0" + b"\x00" * 47

_SETUP_PATH = (Path(__file__).resolve().parents[1] / "presets" / "mainnet"
               / "trusted_setups" / "trusted_setup_4096.json")


# --- trusted setup (parsed lazily; the ceremony output is trusted) ----------


@functools.lru_cache(maxsize=1)
def _setup_json() -> dict:
    return json.loads(_SETUP_PATH.read_text())


@functools.lru_cache(maxsize=1)
def setup_g1_monomial_bytes() -> tuple[bytes, ...]:
    """The 4096 monomial G1 points [s^t] as compressed bytes."""
    return tuple(bytes.fromhex(p[2:]) for p in _setup_json()["g1_monomial"])


@functools.lru_cache(maxsize=8192)
def setup_g1_point(t: int):
    """[s^t] as an oracle Jacobian point (parsed on demand — the verify
    path needs only the first FIELD_ELEMENTS_PER_CELL of them)."""
    return _curve.g1_from_bytes(setup_g1_monomial_bytes()[t])


@functools.lru_cache(maxsize=4)
def setup_g2_point(n: int):
    """[s^n] in G2 (the verify equation pairs against [s^64] and [1])."""
    return _curve.g2_from_bytes(
        bytes.fromhex(_setup_json()["g2_monomial"][n][2:]))


# --- roots of unity / cosets -------------------------------------------------


def reverse_bits(n: int, order: int) -> int:
    width = order.bit_length() - 1
    return int(format(n, f"0{width}b")[::-1], 2) if width else 0


@functools.lru_cache(maxsize=4)
def _root_of_unity(order: int) -> int:
    assert (BLS_MODULUS - 1) % order == 0
    return pow(PRIMITIVE_ROOT_OF_UNITY, (BLS_MODULUS - 1) // order,
               BLS_MODULUS)


@functools.lru_cache(maxsize=4)
def roots_of_unity(order: int) -> tuple[int, ...]:
    w = _root_of_unity(order)
    out, cur = [], 1
    for _ in range(order):
        out.append(cur)
        cur = cur * w % BLS_MODULUS
    return tuple(out)


def coset_shift(cell_index: int) -> int:
    """h_k — the extended-domain brp element opening cell k's coset
    (`coset_shift_for_cell` in the spec oracle)."""
    assert 0 <= cell_index < CELLS_PER_EXT_BLOB
    return roots_of_unity(FIELD_ELEMENTS_PER_EXT_BLOB)[
        reverse_bits(cell_index, CELLS_PER_EXT_BLOB)]


@functools.lru_cache(maxsize=CELLS_PER_EXT_BLOB + 2)
def coset_points(cell_index: int) -> tuple[int, ...]:
    """Cell k's evaluation points IN STORED ORDER (the brp domain
    slice): point j = h_k * eta^rev6(j), eta the order-64 root.  This
    is exactly `coset_for_cell` — the identity the device kernels lean
    on so no host-side re-sort ever happens."""
    h = coset_shift(cell_index)
    eta = roots_of_unity(FIELD_ELEMENTS_PER_CELL)
    return tuple(h * eta[reverse_bits(j, FIELD_ELEMENTS_PER_CELL)]
                 % BLS_MODULUS for j in range(FIELD_ELEMENTS_PER_CELL))


@functools.lru_cache(maxsize=1)
def coset_idft_matrix() -> tuple[tuple[int, ...], ...]:
    """M[i][j] with coeffs(I)[j] = h^-j * sum_i evals[i] * M[i][j] for
    evals given in STORED (bit-reversed coset) order: the 64-point
    inverse DFT with the rev6 permutation folded in, shared by the host
    oracle route and `fr_batch.coset_interpolate_sum`."""
    n = FIELD_ELEMENTS_PER_CELL
    eta_inv = pow(_root_of_unity(n), BLS_MODULUS - 2, BLS_MODULUS)
    inv_n = pow(n, BLS_MODULUS - 2, BLS_MODULUS)
    pows = [pow(eta_inv, t, BLS_MODULUS) for t in range(n)]
    return tuple(
        tuple(inv_n * pows[(j * reverse_bits(i, n)) % n] % BLS_MODULUS
              for j in range(n))
        for i in range(n))


def interpolate_coset_coeffs(cell_index: int, evals) -> list[int]:
    """Coefficients of the degree-<64 interpolant of `evals` (stored
    order) over cell `cell_index`'s coset — the host reference for the
    device kernel, bit-equal to the oracle's
    `interpolate_polynomialcoeff(coset_for_cell(k), evals)`."""
    m = coset_idft_matrix()
    h_inv = pow(coset_shift(cell_index), BLS_MODULUS - 2, BLS_MODULUS)
    coeffs = []
    hp = 1
    n = FIELD_ELEMENTS_PER_CELL
    for j in range(n):
        acc = 0
        for i in range(n):
            acc += evals[i] * m[i][j]
        coeffs.append(acc % BLS_MODULUS * hp % BLS_MODULUS)
        hp = hp * h_inv % BLS_MODULUS
    return coeffs


# --- Fiat-Shamir -------------------------------------------------------------


def compute_challenge(dedup_commitments, commitment_indices, cell_indices,
                      evals_per_cell, proofs_bytes) -> int:
    """`compute_verify_cell_kzg_proof_batch_challenge`, byte-for-byte."""
    data = RANDOM_CHALLENGE_KZG_CELL_BATCH_DOMAIN
    data += int.to_bytes(FIELD_ELEMENTS_PER_BLOB, 8, KZG_ENDIANNESS)
    data += int.to_bytes(FIELD_ELEMENTS_PER_CELL, 8, KZG_ENDIANNESS)
    data += int.to_bytes(len(dedup_commitments), 8, KZG_ENDIANNESS)
    data += int.to_bytes(len(cell_indices), 8, KZG_ENDIANNESS)
    for commitment in dedup_commitments:
        data += commitment
    for k, evals in enumerate(evals_per_cell):
        data += int.to_bytes(int(commitment_indices[k]), 8, KZG_ENDIANNESS)
        data += int.to_bytes(int(cell_indices[k]), 8, KZG_ENDIANNESS)
        for e in evals:
            data += int.to_bytes(e, BYTES_PER_FIELD_ELEMENT, KZG_ENDIANNESS)
        data += proofs_bytes[k]
    return int.from_bytes(sha256(data).digest(), KZG_ENDIANNESS) \
        % BLS_MODULUS


# --- statement parsing -------------------------------------------------------


def _validate_kzg_g1(b: bytes):
    """The oracle's `validate_kzg_g1` + point parse: infinity is legal,
    anything else must KeyValidate (on curve, in subgroup, not
    infinity).  Raises AssertionError exactly where the oracle does."""
    if bytes(b) == G1_POINT_AT_INFINITY:
        return _curve.g1.infinity()
    assert _bls_cs.KeyValidate(bytes(b))
    return _curve.g1_from_bytes(bytes(b))


@dataclass
class CellBatch:
    """One parsed batch of cell statements, oracle-aligned: the
    deduplicated commitment list, the index mapping into it, unpacked
    coset evaluations, and the Fiat-Shamir challenge every verifier
    term weights by."""

    n_cells: int
    commitment_bytes: list      # deduplicated, oracle dedup order
    commitments: list           # parsed Jacobian points, same order
    commitment_indices: list
    cell_indices: list
    evals: list                 # per cell: 64 ints (stored coset order)
    proof_bytes: list
    proofs: list                # parsed Jacobian points
    r: int
    r_powers: list
    shifts: list                # h_k per cell

    def weights(self) -> list[int]:
        """Per-deduped-commitment folded RLC weights (the RLC term)."""
        w = [0] * len(self.commitments)
        for k in range(self.n_cells):
            w[self.commitment_indices[k]] = (
                w[self.commitment_indices[k]] + self.r_powers[k]) \
                % BLS_MODULUS
        return w

    def weighted_r_powers(self) -> list[int]:
        """r^k * h_k^n per cell (the RLP term's proof scalars)."""
        n = FIELD_ELEMENTS_PER_CELL
        return [rp * pow(h, n, BLS_MODULUS) % BLS_MODULUS
                for rp, h in zip(self.r_powers, self.shifts)]


def parse_cell_batch(commitments_bytes, cell_indices, cells,
                     proofs_bytes) -> CellBatch:
    """Validate one `verify_cell_kzg_proof_batch` argument tuple and
    unpack it for the verifiers.  Mirrors the oracle's front half
    assert-for-assert (malformed input raises AssertionError on both
    paths — pinned by tests/test_das.py)."""
    assert (len(commitments_bytes) == len(cells) == len(proofs_bytes)
            == len(cell_indices))
    for commitment_bytes in commitments_bytes:
        assert len(commitment_bytes) == BYTES_PER_COMMITMENT
    for cell_index in cell_indices:
        assert int(cell_index) < CELLS_PER_EXT_BLOB
    for cell in cells:
        assert len(cell) == BYTES_PER_CELL
    for proof_bytes in proofs_bytes:
        assert len(proof_bytes) == BYTES_PER_PROOF

    # dedup with the oracle's exact expression (same in-process set
    # order, so the Fiat-Shamir challenge matches bit-for-bit)
    dedup_bytes = [bytes(c) for c in set(
        bytes(cb) for cb in commitments_bytes)]
    dedup_points = [_validate_kzg_g1(cb) for cb in dedup_bytes]
    commitment_indices = [dedup_bytes.index(bytes(cb))
                          for cb in commitments_bytes]

    evals = []
    for cell in cells:
        cell = bytes(cell)
        row = []
        for i in range(FIELD_ELEMENTS_PER_CELL):
            e = int.from_bytes(
                cell[i * BYTES_PER_FIELD_ELEMENT:
                     (i + 1) * BYTES_PER_FIELD_ELEMENT], KZG_ENDIANNESS)
            assert e < BLS_MODULUS
            row.append(e)
        evals.append(row)
    proof_bytes = [bytes(p) for p in proofs_bytes]
    proofs = [_validate_kzg_g1(p) for p in proof_bytes]

    cell_indices = [int(i) for i in cell_indices]
    r = compute_challenge(dedup_bytes, commitment_indices, cell_indices,
                          evals, proof_bytes)
    r_powers, cur = [], 1
    for _ in range(len(cell_indices)):
        r_powers.append(cur)
        cur = cur * r % BLS_MODULUS
    return CellBatch(
        n_cells=len(cell_indices),
        commitment_bytes=dedup_bytes,
        commitments=dedup_points,
        commitment_indices=commitment_indices,
        cell_indices=cell_indices,
        evals=evals,
        proof_bytes=proof_bytes,
        proofs=proofs,
        r=r,
        r_powers=r_powers,
        shifts=[coset_shift(i) for i in cell_indices],
    )


# --- closed-form sampling matrices ------------------------------------------


def _encode_evals(evals) -> bytes:
    return b"".join(int.to_bytes(e, BYTES_PER_FIELD_ELEMENT,
                                 KZG_ENDIANNESS) for e in evals)


def closed_form_row(c2: int, c1: int, c0: int, columns):
    """(commitment, {column: (cell, proof)}) for the degree-65
    polynomial f = c2*X^65 + c1*X^64 + c0.

    On cell k's coset every point satisfies x^64 = h_k^64 =: a_k, so
    f|coset = c2*a_k*x + c1*a_k + c0, the quotient by Z_k = X^64 - a_k
    is exactly c2*X + c1 for EVERY cell, and hence
    proof_k = c2*[s] + c1*[1] and commitment = c2*[s^65] + c1*[s^64]
    + c0*[1] — real, non-infinity pairing statements from three scalar
    multiplications, no MSM.  The bench/smoke sampling matrices are
    built from these so matrix construction never dominates the
    measured verification."""
    g1 = _curve.g1
    c2, c1, c0 = (c2 % BLS_MODULUS, c1 % BLS_MODULUS, c0 % BLS_MODULUS)
    commitment = g1.add(
        g1.add(g1.mul(setup_g1_point(65), c2),
               g1.mul(setup_g1_point(64), c1)),
        g1.mul(setup_g1_point(0), c0))
    proof = g1.add(g1.mul(setup_g1_point(1), c2),
                   g1.mul(setup_g1_point(0), c1))
    commitment_b = _curve.g1_to_bytes(commitment)
    proof_b = _curve.g1_to_bytes(proof)
    out = {}
    for k in columns:
        a_k = pow(coset_shift(k), FIELD_ELEMENTS_PER_CELL, BLS_MODULUS)
        evals = [(c2 * a_k % BLS_MODULUS * x + c1 * a_k + c0)
                 % BLS_MODULUS for x in coset_points(k)]
        out[k] = (_encode_evals(evals), proof_b)
    return commitment_b, out


def closed_form_matrix(n_blobs: int, columns=None, seed: int = 20250):
    """A full sampling matrix — `n_blobs` rows x `columns` (default all
    128) — as flat, oracle-shaped argument lists
    (commitments, cell_indices, cells, proofs), one entry per sampled
    cell, row-major.  Distinct rows get distinct commitments."""
    if columns is None:
        columns = range(CELLS_PER_EXT_BLOB)
    columns = [int(c) for c in columns]
    commitments, cell_indices, cells, proofs = [], [], [], []
    for row in range(n_blobs):
        commitment_b, per_cell = closed_form_row(
            seed + 3 * row + 1, seed + 3 * row + 2, seed + 3 * row + 3,
            columns)
        for k in columns:
            cell_b, proof_b = per_cell[k]
            commitments.append(commitment_b)
            cell_indices.append(k)
            cells.append(cell_b)
            proofs.append(proof_b)
    return commitments, cell_indices, cells, proofs
