"""Batched PeerDAS cell-proof verification — the universal equation on
the device path.

The fulu oracle (`verify_cell_kzg_proof_batch_impl`) checks K cell
statements with ONE pairing equation

    e(LL, [s^n]) == e(RL, [1]),      n = FIELD_ELEMENTS_PER_CELL
    LL = sum_k r^k W_k
    RL = RLC - RLI + RLP
       = sum_i w_i C_i - [sum_k r^k I_k(s)] + sum_k r^k h_k^n W_k

(r the Fiat-Shamir challenge, W_k the proofs, C_i the deduplicated
commitments with folded weights w_i, I_k the degree-<64 interpolant of
cell k's evaluations over its coset h_k*G).  This module computes the
SAME group elements from the same parsed statement
(`ciphersuite.parse_cell_batch`) on two routes:

host route (`verify_cell_proof_batch_host`)
    pure-Python Pippenger MSMs + the oracle pairing — but with the
    coset-IDFT interpolation (`ciphersuite.interpolate_coset_coeffs`,
    O(K*64^2) instead of the oracle's O(K*64^3) Lagrange build), so it
    doubles as the serve executor's degraded-mode oracle and the
    affordable comparison baseline.  Bit-exact vs the spec oracle
    (tests/test_das.py).

device route (`verify_cell_proof_batch_async`)
    the RLI coefficient fold runs in ONE `fr_batch.coset_interpolate
    _sum` dispatch (evals stay in stored bit-reversed coset order — no
    host re-sort), every point combination is a Pippenger MSM
    (`g1_multi_exp_device`, `g1_multi_exp_sharded` when a mesh is
    asked for), RLC/RLI/RLP fold into one MSM + RLP reusing LL's
    compiled rung, and the final check is one shared-accumulator
    multi-pairing.  Cell-batch shapes ride the `fr_batch.das_rung`
    ladder (16 / 128 / 1024 — a single sampled cell, one full column
    row, the 128x8 sampling matrix); every device fetch settles
    through `serve.futures.DeviceFuture`, keeping the
    `host-sync-outside-settle` analyzer rule clean.

`evaluate_cells_at` rides the generalized coset barycentric kernel
(`fr_batch.barycentric_eval(..., shift_int=h_k)`) — the
device-resident coset evaluation of each cell's interpolant at an
arbitrary point, the cross-check pinning the two interpolation
representations against each other (and the sampling round's spot
check).
"""

from __future__ import annotations

from .. import telemetry
from ..ops.bls import ciphersuite as _bls_cs
from ..ops.bls import curve as _curve
from ..serve.futures import DeviceFuture
from ..telemetry import costmodel
from . import ciphersuite as cs

N_CELL = cs.FIELD_ELEMENTS_PER_CELL


def das_rung(n: int) -> int:
    """The cell-batch shape ladder (re-exported from `fr_batch`, where
    the kernel lives)."""
    from ..ops.fr_batch import das_rung as _rung

    return _rung(n)


def _neg_g2_gen():
    return _curve.g2.neg(cs.setup_g2_point(0))


def _rli_weight_rows(batch: cs.CellBatch) -> list[list[int]]:
    """weights[k][j] = r^k * h_k^-j — the per-(cell, coefficient)
    factors folding the IDFT outputs into RLI's scalar vector."""
    rows = []
    for rp, h in zip(batch.r_powers, batch.shifts):
        h_inv = pow(h, cs.BLS_MODULUS - 2, cs.BLS_MODULUS)
        row, cur = [], rp
        for _ in range(N_CELL):
            row.append(cur)
            cur = cur * h_inv % cs.BLS_MODULUS
        rows.append(row)
    return rows


def _rl_terms(batch: cs.CellBatch, rli_coeffs) -> tuple[list, list]:
    """(points, scalars) of the RLC - RLI part of RL as one MSM: the
    deduplicated commitments with their folded weights, plus the first
    64 monomial setup points with the NEGATED summed interpolation
    coefficients."""
    points = list(batch.commitments) + [cs.setup_g1_point(j)
                                        for j in range(N_CELL)]
    scalars = batch.weights() + [(-int(c)) % cs.BLS_MODULUS
                                 for c in rli_coeffs]
    return points, scalars


# --- host route --------------------------------------------------------------


def _host_rli_coeffs(batch: cs.CellBatch) -> list[int]:
    coeffs = [0] * N_CELL
    for k in range(batch.n_cells):
        rp = batch.r_powers[k]
        ck = cs.interpolate_coset_coeffs(batch.cell_indices[k],
                                         batch.evals[k])
        for j in range(N_CELL):
            coeffs[j] = (coeffs[j] + rp * ck[j]) % cs.BLS_MODULUS
    return coeffs


def verify_cell_proof_batch_host(commitments_bytes, cell_indices, cells,
                                 proofs_bytes) -> bool:
    """The pure-host verifier (also the serve executor's degraded-mode
    oracle for the `das` request kind).  Same accept/reject verdict as
    the device route and the fulu spec oracle."""
    batch = cs.parse_cell_batch(commitments_bytes, cell_indices, cells,
                                proofs_bytes)
    if batch.n_cells == 0:
        return True
    with telemetry.span("das.verify_host", cells=batch.n_cells):
        telemetry.count("das.verify.host_calls")
        rli = _host_rli_coeffs(batch)
        ll = _curve.g1.msm(batch.proofs, batch.r_powers)
        pts, sc = _rl_terms(batch, rli)
        rl = _curve.g1.add(
            _curve.g1.msm(pts, sc),
            _curve.g1.msm(batch.proofs, batch.weighted_r_powers()))
        return _bls_cs._pairing_check(
            [(ll, cs.setup_g2_point(N_CELL)), (rl, _neg_g2_gen())])


# --- device route ------------------------------------------------------------


def verify_cell_proof_batch_async(commitments_bytes, cell_indices, cells,
                                  proofs_bytes, device: bool | None = None,
                                  n_devices: int | None = None,
                                  device_ids=None) -> DeviceFuture:
    """Deferred batch verdict: parsing and the RLI coset-interpolation
    dispatch happen eagerly, the MSM + pairing stages run at settle
    time with every device fetch going through `DeviceFuture.result()`
    (the sanctioned settle seam).  `device=None` follows the active BLS
    backend; `device=False` answers on the host route immediately (the
    tier-1 fallback when the device path is unavailable).  `n_devices`/
    `device_ids` shard the big MSMs over the mesh
    (`g1_multi_exp_sharded`)."""
    if device is None:
        from ..ops import bls

        device = bls.backend_name() == "jax"
    if not device:
        try:
            return DeviceFuture.settled(verify_cell_proof_batch_host(
                commitments_bytes, cell_indices, cells, proofs_bytes))
        except Exception as exc:
            return DeviceFuture.failed(exc)

    return _verify_device_async(commitments_bytes, cell_indices, cells,
                                proofs_bytes, n_devices=n_devices,
                                device_ids=device_ids)


def _verify_device_async(commitments_bytes, cell_indices, cells,
                         proofs_bytes, n_devices=None,
                         device_ids=None) -> DeviceFuture:
    from ..ops import bls_batch
    from ..ops.fr_batch import coset_interpolate_sum_async

    batch = cs.parse_cell_batch(commitments_bytes, cell_indices, cells,
                                proofs_bytes)
    if batch.n_cells == 0:
        return DeviceFuture.settled(True)
    rung = das_rung(batch.n_cells)
    with telemetry.span("das.verify_device", cells=batch.n_cells,
                        padded=rung):
        telemetry.count("das.verify.device_calls")
        telemetry.count("das.cells.live", batch.n_cells)
        telemetry.count("das.cells.padded", rung)
        # stage 1 dispatches NOW: the coset-interpolation fold (the
        # only stage with field-element inputs) overlaps the caller's
        # next host prep
        rli_fut = coset_interpolate_sum_async(
            batch.evals, cs.coset_idft_matrix(), _rli_weight_rows(batch))
    costmodel.sample_watermark("das.verify_device")

    sharded = n_devices is not None or device_ids is not None

    def _msm_async(points, scalars, block=False):
        if sharded:
            return bls_batch.g1_multi_exp_sharded_async(
                points, scalars, n_devices=n_devices,
                device_ids=device_ids)
        return bls_batch.g1_multi_exp_device_async(points, scalars,
                                                   block=block)

    def _finish(fut: DeviceFuture, timeout=None) -> None:
        try:
            rli = rli_fut.result()
            # LL and RLP share the proof points AND the compiled MSM
            # rung; RLC - RLI is one small MSM; RL composes on host
            ll_fut = _msm_async(batch.proofs, batch.r_powers)
            rlp_fut = _msm_async(batch.proofs, batch.weighted_r_powers())
            pts, sc = _rl_terms(batch, rli)
            rl_small_fut = _msm_async(pts, sc)
            rl = _curve.g1.add(rl_small_fut.result(), rlp_fut.result())
            ok_fut = bls_batch.pairing_check_device_async(
                [(ll_fut.result(), cs.setup_g2_point(N_CELL)),
                 (rl, _neg_g2_gen())])
            fut.set_result(bool(ok_fut.result()))
        except Exception as exc:
            if fut.done():
                raise
            fut.set_exception(exc)

    return DeviceFuture(waiter=_finish)


def verify_cell_proof_batch(commitments_bytes, cell_indices, cells,
                            proofs_bytes, device: bool | None = None,
                            n_devices: int | None = None,
                            device_ids=None) -> bool:
    """Synchronous facade over `verify_cell_proof_batch_async`; the
    fetches live in `serve.futures`."""
    return verify_cell_proof_batch_async(
        commitments_bytes, cell_indices, cells, proofs_bytes,
        device=device, n_devices=n_devices,
        device_ids=device_ids).result()


def verify_and_isolate(commitments_bytes, cell_indices, cells,
                       proofs_bytes,
                       device: bool | None = None) -> tuple[bool, list]:
    """(batch_verdict, per_statement_verdicts): one RLC batch check,
    and — only when the batch fails — a per-statement recheck so each
    bad cell is isolated instead of poisoning the whole sample (the
    serving semantics; all-or-nothing is a block semantics)."""
    ok = verify_cell_proof_batch(commitments_bytes, cell_indices, cells,
                                 proofs_bytes, device=device)
    if ok:
        return True, [True] * len(cell_indices)
    telemetry.count("das.verify.recheck_batches")
    futs = [verify_cell_proof_batch_async(
        [commitments_bytes[k]], [cell_indices[k]], [cells[k]],
        [proofs_bytes[k]], device=device)
        for k in range(len(cell_indices))]
    return False, [f.result() for f in futs]


# --- coset evaluation (the generalized barycentric surface) ------------------


def evaluate_cells_at(cells, cell_indices, z_int,
                      device: bool | None = None) -> list[int]:
    """I_k(z) for each cell — the degree-<64 interpolant of the cell's
    evaluations over its coset, evaluated at an arbitrary field point.

    Device route: `fr_batch.barycentric_eval` over the coset domain IN
    STORED ORDER with `shift_int=h_k` (the coset-generalized kernel —
    all dispatches go out before the first settle, so a batch of cells
    pipelines).  Host route: Horner on `interpolate_coset_coeffs`.
    The two agreeing — and agreeing with the oracle's Lagrange
    interpolant — is the coset-handling cross-check tests and the das
    smoke assert."""
    if device is None:
        from ..ops import bls

        device = bls.backend_name() == "jax"
    rows = []
    for cell in cells:
        cell = bytes(cell)
        assert len(cell) == cs.BYTES_PER_CELL
        rows.append([int.from_bytes(
            cell[i * cs.BYTES_PER_FIELD_ELEMENT:
                 (i + 1) * cs.BYTES_PER_FIELD_ELEMENT],
            cs.KZG_ENDIANNESS) for i in range(N_CELL)])
    z = int(z_int) % cs.BLS_MODULUS
    if device:
        from ..ops.fr_batch import barycentric_eval_async

        with telemetry.span("das.evaluate_cells", cells=len(rows)):
            telemetry.count("das.evaluate_cells.device_calls")
            # in-domain z short-circuits to the stored evaluation (the
            # barycentric denominators vanish there), matching the
            # oracle's `evaluate_polynomial_in_evaluation_form` guard
            futs = [
                DeviceFuture.settled(
                    row[cs.coset_points(int(k)).index(z)])
                if z in cs.coset_points(int(k))
                else barycentric_eval_async(
                    row, cs.coset_points(int(k)), z,
                    shift_int=cs.coset_shift(int(k)))
                for row, k in zip(rows, cell_indices)]
        return [f.result() for f in futs]
    out = []
    for row, k in zip(rows, cell_indices):
        if z in cs.coset_points(int(k)):
            out.append(row[cs.coset_points(int(k)).index(z)])
            continue
        coeffs = cs.interpolate_coset_coeffs(int(k), row)
        acc = 0
        for c in reversed(coeffs):
            acc = (acc * z + c) % cs.BLS_MODULUS
        out.append(acc)
    return out
