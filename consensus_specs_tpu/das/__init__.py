"""DAS workload subsystem — device-batched PeerDAS cell-proof checks.

The fulu data-availability-sampling workload (`specs/fulu/
polynomial-commitments-sampling.md`) verifies many
(row_commitment, cell_index, cell, proof) tuples at once through ONE
random-linear-combination pairing equation.  This package lifts that
verification onto the device path the earlier PRs built — the
`ops/fr_batch` scalar-field kernels for the coset interpolation work,
the Pippenger G1 MSM (`ops/bls_batch.g1_multi_exp_device`, sharded
variant on a mesh) for every linear combination of points, and the
shared-accumulator multi-pairing for the single final check — while the
pure-Python spec oracle in `models/fulu/polynomial_commitments_sampling
.py` stays the bit-exactness reference.

Modules:

    ciphersuite   host-side parse/validate of cell statements against
                  the fulu spec semantics (coset-shift handling,
                  cell -> field-element unpack, the Fiat-Shamir
                  challenge), plus the closed-form sampling matrices
                  the bench/smoke rounds use.
    verify        `verify_cell_proof_batch[_async]` — the batched RLC
                  verification itself, host oracle route and device
                  route, `_bucket`-style rung ladder over batch size.
    compute       cell/proof computation: `compute_cells` (one FFT
                  extension, bit-exact vs the spec) and the
                  residue-grouped quotient route that makes per-column
                  proofs affordable (the un-`@slow` fulu merkle-proof
                  tests ride it).
    sampling      a full data-column sampling round: commitment
                  inclusion proof on the host + batched cell checks on
                  device, the `submit_das_sample` serve payload.

See README "DAS / PeerDAS" and tests/test_das.py.
"""

from .ciphersuite import (  # noqa: F401
    CELLS_PER_EXT_BLOB,
    FIELD_ELEMENTS_PER_CELL,
    CellBatch,
    closed_form_matrix,
    parse_cell_batch,
)
from .sampling import (  # noqa: F401
    DasSample,
    sample_from_matrix,
    verify_sample,
    verify_sample_async,
)
from .verify import (  # noqa: F401
    das_rung,
    verify_cell_proof_batch,
    verify_cell_proof_batch_async,
    verify_cell_proof_batch_host,
)
