"""Static analyzer for the JAX device path — the compile-time
counterpart to the telemetry layer.

PRs 1-2 showed that the device hot path's failure modes are *statically
visible* properties of the kernel source: the 81s attestation
compile+first is an unbucketed shape reaching jit, the below-parity KZG
config is a silent host round-trip at a dispatch seam, a wrong-dtype
constant is a mis-typed `jnp.asarray` at trace time.  This package
catches those classes before a TPU bench round does, with four rule
families over `ops/bls_batch`, `ops/bls`, `ops/sha256_jax`,
`ops/fr_batch`, `parallel/` and `executor.py`:

    recompile-unbucketed-dim, recompile-traced-branch   (recompile.py)
    host-sync-item/-coerce/-np/-device-get/
        -outside-settle, device-const-at-import         (hostsync.py)
    dtype-int-literal/-float/-implicit-cast             (dtype.py)
    instr-uncovered-entry, instr-uncovered-cost         (instrumentation.py)
    exc-swallow-device                                  (excswallow.py)

(`exc-swallow-device` also scans `serve/` and `resilience/` — modules
where a swallowed exception turns a failed request into a healthy-
looking one.)

Findings print as `file:line: rule-id: message`; intentional cases are
annotated in-source with `# cst: allow(<rule-id>): <reason>` — the
allow inventory is itself a deliverable (it enumerates every remaining
host-sync and compile-key seam for the next perf PR).

Run it:

    python -m consensus_specs_tpu.analysis                # whole tree
    python -m consensus_specs_tpu.analysis path.py ...    # explicit files
    python -m consensus_specs_tpu.analysis --json out.json

Pure AST + stdlib: no jax import, no spec build — cheap enough for
`make lint` and the CI lint job (which uploads the --json report as an
artifact).  Sibling: `consensus_specs_tpu.lint` checks the *spec*
namespaces; this package checks the *kernel* layer.
"""

from .core import (  # noqa: F401
    ALL_ROLES,
    Finding,
    Report,
    RULE_IDS,
    analyze_source,
    analyze_tree,
    main,
)
