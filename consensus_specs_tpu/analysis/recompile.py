"""Rule family 1 — recompile hazards.

The 81s attestation compile+first (ROADMAP) is the cost model here:
every distinct value of a jit compile key traces and compiles a fresh
XLA executable.  The tree's defense is the `_bucket` 4-shape ladder —
any batch dimension that reaches a kernel must be quantized through it.

recompile-unbucketed-dim
    A call into a jit factory (all of whose arguments are compile keys)
    or into a jit-decorated function's *static* parameters, where the
    argument is a raw dimension: a `len(...)`/`.shape` expression, a
    mesh-shape device-count read (`jax.device_count()`,
    `jax.local_device_count()` — `len(jax.devices())` rides the
    generic len() taint), or a local name data-flow-derived from one,
    that was never routed through a `BUCKET_FUNCS` call (`_bucket` for
    batch shapes, `mesh_rung` for mesh widths).

recompile-traced-branch
    Python `if`/`while`/`assert`/conditional-expression tests that
    reference a traced value inside a jitted body (or, in kernel-role
    modules, inside ANY function — those modules' functions are traced
    via cross-module calls).  Metadata access (`x.shape`, `len(x)`,
    `isinstance`) is static under trace and exempt, as are parameters
    whose annotation/default marks them compile-time (`n: int`,
    `axis_name: str | None`, `unroll=False`).
"""

from __future__ import annotations

import ast

from .core import (
    BUCKET_FUNCS,
    DEVICE_COUNT_FUNCS,
    Finding,
    ModuleModel,
    ROLE_KERNEL,
    _dotted,
    nonstatic_refs,
    param_names,
    scope_nodes,
    static_params,
)


def _check_unbucketed(model: ModuleModel, fn) -> list[Finding]:
    findings = []
    aliases = model.factory_aliases(fn)
    tainted = model.raw_dim_tainted(fn)

    def is_raw_dim(arg) -> bool:
        """Mirrors `raw_dim_tainted`'s laundering rule: an inline
        `_bucket(...)` wrapping (anywhere in the expression) makes the
        value a ladder shape, not a raw dimension."""
        found = False

        def walk(node):
            nonlocal found
            if found:
                return
            if (isinstance(node, ast.Call)
                    and _dotted(node.func) in BUCKET_FUNCS):
                return                  # laundered subtree
            if (isinstance(node, ast.Call)
                    and _dotted(node.func) == "len"):
                found = True
            elif (isinstance(node, ast.Call)
                    and (_dotted(node.func) or "").split(".")[-1]
                    in DEVICE_COUNT_FUNCS):
                found = True            # mesh-shape compile key
            elif isinstance(node, ast.Attribute) and node.attr == "shape":
                found = True
            elif (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in tainted):
                found = True
            else:
                for child in ast.iter_child_nodes(node):
                    walk(child)

        walk(arg)
        return found

    def flag(call, arg, callee: str, what: str):
        findings.append(Finding(
            model.path, call.lineno, "recompile-unbucketed-dim",
            f"{what} of '{callee}' is a raw len()/shape-derived "
            f"dimension not routed through the _bucket ladder — every "
            f"distinct value compiles a new executable"))

    for node in scope_nodes(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not isinstance(f, ast.Name):
            continue
        if f.id in aliases:
            # a jit factory: every argument keys the executable cache
            for i, arg in enumerate(node.args):
                if is_raw_dim(arg):
                    flag(node, arg, f.id, f"argument {i}")
            for kw in node.keywords:
                if kw.arg and is_raw_dim(kw.value):
                    flag(node, kw.value, f.id, f"argument '{kw.arg}'")
            continue
        # a jit-decorated local: only static params are compile keys
        defs = [d for d in model.func_index.get(f.id, [])
                if d in model.jit_decorated]
        if not defs:
            continue
        target = defs[0]
        statics = model.jit_decorated[target]
        params = param_names(target)
        for i, arg in enumerate(node.args):
            if i < len(params) and params[i] in statics \
                    and is_raw_dim(arg):
                flag(node, arg, f.id, f"static argument '{params[i]}'")
        for kw in node.keywords:
            if kw.arg in statics and is_raw_dim(kw.value):
                flag(node, kw.value, f.id, f"static argument '{kw.arg}'")
    return findings


def _check_traced_branch(model: ModuleModel, fn,
                         traced: set[str]) -> list[Finding]:
    findings = []
    tests = []
    for node in scope_nodes(fn):
        if isinstance(node, (ast.If, ast.While)):
            tests.append((node.test, node.lineno, "branch"))
        elif isinstance(node, ast.IfExp):
            tests.append((node.test, node.lineno, "conditional"))
        elif isinstance(node, ast.Assert):
            tests.append((node.test, node.lineno, "assert"))
    for test, lineno, kind in tests:
        refs = nonstatic_refs(test, traced)
        if refs:
            names = ", ".join(sorted({r.id for r in refs}))
            findings.append(Finding(
                model.path, lineno, "recompile-traced-branch",
                f"Python {kind} on traced value(s) {names} inside a "
                f"jitted body in {fn.name}() — concretizes at trace "
                f"time (shape/dtype access is exempt; hoist the "
                f"decision to the host or use lax.cond/jnp.where)"))
    return findings


def check(model: ModuleModel) -> list[Finding]:
    findings: list[Finding] = []
    for fn in model.all_funcs:
        findings += _check_unbucketed(model, fn)

    kernel_role = ROLE_KERNEL in model.roles
    for fn in model.all_funcs:
        if fn in model.jit_bodies:
            traced = model.traced_params[fn]
        elif kernel_role:
            traced = set(param_names(fn)) - static_params(fn) - {"self"}
        else:
            continue
        findings += _check_traced_branch(model, fn, traced)
    return findings
