"""Rule family 2 — host-sync points.

A device value forced back to the host (`.item()`, `int()`/`float()`/
`bool()`, `np.asarray`, `jax.device_get`) blocks until every queued
device computation producing it has finished: the dispatch pipeline
serializes and the accelerator idles behind Python.  Inside the device
modules these syncs must be deliberate and visible — the intentional
ones (API boundaries returning a host bool, the final root fetch) carry
`# cst: allow(...)` annotations with reasons, which doubles as the
inventory of serialization points for the next perf PR.

Detection is provenance-based so the pure-Python oracle code sharing
these packages stays quiet: coercions are flagged only on values the
dataflow marks device-resident (results of `_dispatch`, a jitted local,
a `factory(B)(...)` double call, `jax.block_until_ready`), or — inside
jit bodies — on traced parameters (where a concretizing coercion is a
trace-time error waiting to happen).  `.item()` and `jax.device_get`
are unconditional: there is no host-side reason to use either in a
device module.

Since the serving subsystem landed, the sanctioned settle seam is
`serve/futures.py` (`DeviceFuture.result()` holds the ONE blocking
fetch) — the device entry points return futures and the old allow-
annotated API-boundary syncs are retired.  `host-sync-outside-settle`
keeps that contract from regressing: inside a device module it flags
(a) an `<entry>_async(...).result()` chain anywhere except the matching
synchronous facade (`def <entry>(): return <entry>_async(...).result()`
is the sanctioned compatibility shape — dispatching and immediately
blocking anywhere else rebuilds the serialization point the futures
API removed), and (b) `block_until_ready` in any form (there is no
reason to barrier the pipeline from a device module; the serve
executor settles batches through futures instead) — EXCEPT when the
barrier itself is `telemetry.enabled()`-gated (inside a positive
`if telemetry.enabled():` branch, or after the early-out
`if not telemetry.enabled(): return` guard): the compile-vs-run
timing seam must barrier to measure, and its telemetry-off path
dispatches without one, so instrumented barriers are measurement, not
serving — but a merely nearby enabled() call does not exempt an
unconditional barrier.  The
oracle stays exempt the same way as the other host-sync rules:
pure-Python code never produces `_async` chains or readiness barriers.

The sixth rule here is the inverse direction — device residency
established too EARLY: `device-const-at-import` flags jnp arrays
materialized at module scope.  Beyond allocating device memory at
import, they leak tracers when the module's first import happens
inside an active jit trace (kernels lazily import their dependencies
from traced code — `h2c_jax` pulls in `sha256_jax` that way), after
which every host-side use of the constant raises
UnexpectedTracerError.  Found live on this tree: keep module-level
constants numpy (the `fq.py` convention) and let jnp close over them
at trace time.
"""

from __future__ import annotations

import ast

from .core import Finding, ModuleModel, _dotted, nonstatic_refs, scope_nodes

_NP_NAMES = ("np.asarray", "np.array", "numpy.asarray", "numpy.array")
_COERCIONS = ("int", "float", "bool")


def _enabled_test(test) -> bool:
    return isinstance(test, ast.Call) \
        and (_dotted(test.func) or "").endswith("telemetry.enabled")


def _barrier_is_gated(fn, barrier) -> bool:
    """True when a readiness barrier is genuinely telemetry-gated — it
    only runs on instrumented rounds, so it is measurement, not
    serving.  Two sanctioned shapes (both the `_dispatch` structure):

        if telemetry.enabled():          # (a) positive gate
            out = jax.block_until_ready(...)

        if not telemetry.enabled():      # (b) early-out guard
            return fn(*args)
        ...
        out = jax.block_until_ready(...)

    A merely NEARBY `telemetry.enabled()` call (an unrelated counter
    guard elsewhere in the function) must not exempt an unconditional
    barrier — that would let the per-dispatch serialization this rule
    exists to prevent ship undetected."""
    for node in ast.walk(fn):
        if isinstance(node, ast.If) and _enabled_test(node.test) \
                and any(n is barrier for stmt in node.body
                        for n in ast.walk(stmt)):
            return True
    for stmt in getattr(fn, "body", []):
        if isinstance(stmt, ast.If) \
                and isinstance(stmt.test, ast.UnaryOp) \
                and isinstance(stmt.test.op, ast.Not) \
                and _enabled_test(stmt.test.operand) \
                and stmt.body \
                and isinstance(stmt.body[-1], (ast.Return, ast.Raise)) \
                and stmt.lineno < barrier.lineno:
            return True
    return False


def _check_scope(model: ModuleModel, fn, aliases, tainted,
                 traced: set[str]) -> list[Finding]:
    findings = []

    def is_device_value(arg) -> bool:
        if isinstance(arg, ast.Name) and arg.id in tainted:
            return True
        if model.device_producing(arg, aliases):
            return True
        if traced and nonstatic_refs(arg, traced):
            return True
        return False

    for node in scope_nodes(fn):
        if not isinstance(node, ast.Call):
            continue
        fd = _dotted(node.func)
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "item" and not node.args):
            findings.append(Finding(
                model.path, node.lineno, "host-sync-item",
                ".item() forces a blocking device->host transfer"))
        elif (fd or "").endswith("jax.device_get") or fd == "device_get":
            findings.append(Finding(
                model.path, node.lineno, "host-sync-device-get",
                "jax.device_get serializes the dispatch pipeline"))
        elif fd in _COERCIONS and len(node.args) == 1 \
                and is_device_value(node.args[0]):
            findings.append(Finding(
                model.path, node.lineno, "host-sync-coerce",
                f"{fd}() on a device value blocks until the pipeline "
                f"drains — keep results on device or sync once at the "
                f"API boundary"))
        elif fd in _NP_NAMES and node.args \
                and is_device_value(node.args[0]):
            findings.append(Finding(
                model.path, node.lineno, "host-sync-np",
                f"{fd}() on a device value is an implicit device fetch"))
        elif ((fd or "").endswith("block_until_ready")
                or (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "block_until_ready")) \
                and not _barrier_is_gated(fn, node):
            findings.append(Finding(
                model.path, node.lineno, "host-sync-outside-settle",
                "block_until_ready barriers the dispatch pipeline from "
                "a device module — return a serve.futures handle and "
                "let the settle path block once, at result()"))
        elif _is_immediate_settle(node, fn):
            findings.append(Finding(
                model.path, node.lineno, "host-sync-outside-settle",
                "dispatching and immediately blocking "
                "(`..._async(...).result()`) outside the synchronous "
                "facade rebuilds the host-sync seam the futures API "
                "retired — return the DeviceFuture (or route the work "
                "through the serve executor) instead"))
    return findings


def _is_immediate_settle(node: ast.Call, fn) -> bool:
    """`<name>_async(...).result()` chained in one expression — the
    dispatch-then-block anti-pattern — EXCEPT inside the matching
    synchronous facade, the one sanctioned compatibility shape:
    `def batch_verify(...): return batch_verify_async(...).result()`."""
    if not (isinstance(node.func, ast.Attribute)
            and node.func.attr == "result" and not node.args):
        return False
    inner = node.func.value
    if not isinstance(inner, ast.Call):
        return False
    callee = (_dotted(inner.func) or "").rsplit(".", 1)[-1]
    if not callee.endswith("_async"):
        return False
    return callee != getattr(fn, "name", None) + "_async" \
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
        else True


# jnp calls that materialize an array (aliases like `U64 = jnp.uint64`
# are references, not calls, and stay legal)
_JNP_CTORS = frozenset({
    "asarray", "array", "zeros", "ones", "empty", "full", "arange",
    "stack", "concatenate", "broadcast_to", "frombuffer", "linspace",
})


def _check_module_level(model: ModuleModel) -> list[Finding]:
    findings = []
    stack = []
    for node in model.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        stack.append(node)
    seen_lines = set()
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue                    # deferred execution — fine
        if isinstance(node, ast.Call):
            fd = _dotted(node.func)
            if fd and "." in fd:
                head, attr = fd.rsplit(".", 1)
                if head in ("jnp", "jax.numpy") and attr in _JNP_CTORS \
                        and node.lineno not in seen_lines:
                    seen_lines.add(node.lineno)
                    findings.append(Finding(
                        model.path, node.lineno, "device-const-at-import",
                        f"jnp.{attr}() at module scope materializes a "
                        f"device array at import time — a first import "
                        f"inside a jit trace binds it to a leaked "
                        f"tracer; keep the constant numpy and jnp will "
                        f"close over it at trace time"))
        stack.extend(ast.iter_child_nodes(node))
    return findings


def check(model: ModuleModel) -> list[Finding]:
    findings: list[Finding] = _check_module_level(model)
    for fn in model.all_funcs:
        aliases = model.factory_aliases(fn)
        tainted = model.device_tainted(fn, aliases)
        traced = model.traced_params.get(fn, set()) \
            if fn in model.jit_bodies else set()
        findings += _check_scope(model, fn, aliases, tainted, traced)
    return findings
