"""Shared machinery for the device-path static analyzer.

Everything here is pure-AST and stdlib-only: no jax import, no spec
build, no numpy — the whole analysis pass must stay cheap enough to run
inside `make lint` and CI without moving the tier-1 wall-time budget
(ROADMAP).  The rule modules (`recompile`, `hostsync`, `dtype`,
`instrumentation`) consume the `ModuleModel` built here:

- jit surface discovery: `@jax.jit`-decorated functions (incl.
  `@partial(jax.jit, static_argnames=...)`), jit *factories*
  (functions returning `jax.jit(...)` or a jit-decorated local — the
  `_rlc_kernel(batch)` lru-cached pattern), and *traced bodies* (the
  function objects handed to `jax.jit`/`shard_map`, plus everything
  nested inside them);
- per-scope walks that do not leak into nested function scopes;
- two taint lattices: *raw-dim* (values derived from `len()`/`.shape`
  that have not been routed through the `_bucket` ladder — the
  recompile-hazard input) and *device* (values produced by a kernel
  dispatch — the host-sync input);
- inline suppressions: `# cst: allow(<rule-id>): <reason>` on the
  finding's line, or alone on the line above it.

Reporting contract: `file:line: rule-id: message`, exit 1 iff any
finding is unsuppressed.
"""

from __future__ import annotations

import ast
import io
import json
import re
import sys
import tokenize
from dataclasses import dataclass
from pathlib import Path

PKG_ROOT = Path(__file__).resolve().parent.parent

# rule-id -> what it catches (the README table mirrors this registry)
RULE_IDS = {
    "recompile-unbucketed-dim":
        "raw len()/shape value — or a mesh-shape device-count read "
        "(jax.device_count(), len(jax.devices())) — used as a jit "
        "compile key without the _bucket shape ladder / mesh_rung "
        "mesh-width ladder — every distinct value compiles a new "
        "XLA executable",
    "recompile-traced-branch":
        "Python if/while/assert on a traced value inside a jitted "
        "body — trace-time concretization error or silent retrace",
    "host-sync-item":
        ".item() on a device value — blocking device->host round-trip",
    "host-sync-coerce":
        "int()/float()/bool() on a device value — silently serializes "
        "the dispatch pipeline",
    "host-sync-np":
        "np.asarray()/np.array() on a device value — implicit device "
        "fetch",
    "host-sync-device-get":
        "jax.device_get() inside a device module",
    "host-sync-outside-settle":
        "blocking fetch outside the serve.futures settle seam — "
        "an `..._async(...).result()` chain beyond the synchronous "
        "facade, or block_until_ready in a device module",
    "device-const-at-import":
        "jnp array materialized at module import time — leaks tracers "
        "when the module is first imported inside a jit trace (keep "
        "module constants as numpy; jnp closes over them at trace "
        "time)",
    "dtype-int-literal":
        "untyped Python int literal >= 2**32 mixed into limb "
        "arithmetic — silent int32 overflow / weak-promotion hazard",
    "dtype-float":
        "float construction in integer limb-arithmetic modules",
    "dtype-implicit-cast":
        "jnp array construction without an explicit dtype — default "
        "dtype (float32 / platform int) corrupts limb lanes",
    "instr-uncovered-entry":
        "public kernel entry point without a telemetry span/counter — "
        "new kernels must not land unobservable",
    "instr-uncovered-cost":
        "public device-kernel entry point that never passes through "
        "the cost-capture seam (_dispatch or costmodel.capture) — the "
        "kernel stays invisible to the roofline/utilization layer",
    "exc-swallow-device":
        "bare/over-broad except in a device or serve module that "
        "neither re-raises nor poisons/records the exception — device "
        "failures must stay typed and visible, not read as success",
    "reqtrace-uncovered-submit":
        "ServeExecutor submit_* entry point that never mints a "
        "reqtrace.RequestContext — requests entering through it would "
        "be invisible to tail-latency attribution (see README Request "
        "tracing)",
    "instr-uncovered-dispatch-ledger":
        "dispatch/settle seam function (`_dispatch*` or "
        "`_settle_from_device` on the occupancy surface) that never "
        "reaches an occupancy-ledger call — device work flowing "
        "through it would be invisible to the busy/bubble attribution "
        "(see README Pipeline occupancy)",
    "metric-name-invalid":
        "telemetry.count/observe/gauge/span name outside the dotted-"
        "name convention, or two distinct names that collide into the "
        "same exposition family after Prometheus sanitization — the "
        "metrics endpoint would silently rewrite or merge their series "
        "(see README Monitoring)",
}

# --- file roles (which rule families run where) ------------------------------

ROLE_DEVICE = "device"   # host-sync + recompile (jit surface) rules
ROLE_KERNEL = "kernel"   # traced-branch applies to EVERY function
ROLE_LIMB = "limb"       # dtype discipline rules
ROLE_INSTR = "instr"     # instrumentation coverage rules
ROLE_EXC = "exc"         # exception-swallow discipline (serve +
                         # resilience modules; device files get it via
                         # ROLE_DEVICE)
ROLE_SERVE = "serve"     # request-tracing coverage of serve submit_*
                         # entry points (reqtrace-uncovered-submit)
ROLE_METRIC = "metric"   # metric-name discipline at every telemetry
                         # call site (metric-name-invalid) — runs over
                         # the whole package, since counters/spans are
                         # minted everywhere the device path runs
ROLE_LEDGER = "ledger"   # occupancy-ledger coverage of the dispatch /
                         # settle seams (instr-uncovered-dispatch-ledger)
ALL_ROLES = frozenset((ROLE_DEVICE, ROLE_KERNEL, ROLE_LIMB, ROLE_INSTR,
                       ROLE_EXC, ROLE_SERVE, ROLE_METRIC, ROLE_LEDGER))

# the device path named by the north star: every module that builds or
# dispatches XLA programs (oracle siblings under ops/bls are scanned too;
# they produce no findings because nothing in them touches jax)
DEVICE_GLOBS = ("ops/bls_batch/*.py", "ops/bls/*.py", "parallel/*.py")
DEVICE_FILES = ("ops/sha256_jax.py", "ops/fr_batch.py", "executor.py",
                "forkchoice/kernels.py", "forkchoice/store.py",
                "das/recover.py")
# exception-swallow discipline beyond the device files: the serving
# subsystem (where a swallowed error reads as a healthy request) and
# the resilience layer itself (which exists to keep failures typed).
# NOT merged into DEVICE_GLOBS — the host-sync/recompile families
# would misfire on serve/loadgen's sanctioned warmup settles.
EXC_GLOBS = ("serve/*.py", "resilience/*.py")
# limb-arithmetic modules under the dtype discipline
LIMB_FILES = (
    "ops/bls_batch/fq.py", "ops/bls_batch/tower.py",
    "ops/bls_batch/curve_jax.py", "ops/bls_batch/h2c_jax.py",
    "ops/bls_batch/pairing_jax.py",
)
# modules whose every function body is (or is traced into) device code:
# traced-branch checking extends beyond syntactic jit bodies here
KERNEL_FILES = LIMB_FILES + (
    "ops/sha256_jax.py", "ops/fr_batch.py", "parallel/epoch.py",
    "parallel/merkle.py",
)
# kernel entry-point surface: analyzed in chain order so the facade
# (ops/bls) can credit calls into the already-covered bls_batch
# entries; sha256_jax and fr_batch joined the surface with the
# cost-capture rule (instr-uncovered-cost) — their device entry points
# must stay visible to the roofline layer too; parallel/incremental.py
# joined with the incremental-merkleization kernels (merkle_incr@…);
# resilience/mesh.py + checkpoint.py joined with the recovery surfaces
# (their public entries must stay span-covered like every other path
# that can reach a device dispatch); parallel/partition.py joined with
# the partition-rule registry (the sharded epoch step's dispatch
# surface must stay observable like the kernels it wires up);
# das/verify.py joined with the DAS workload (its batched cell-proof
# entries chain fr_batch + bls_batch dispatches and must stay
# span/cost-covered like the kernels they compose);
# forkchoice/store.py + kernels.py joined with the fork-choice
# subsystem (the proto-array store's apply/head dispatches must stay
# span/cost-covered like every other device path);
# das/recover.py + ops/bls_batch/g1fft_jax.py joined with the FK20
# producer / erasure-recovery path (the G1-FFT and circulant-MSM
# entries plus the recover decode chain dispatch fr_batch + bls_batch
# kernels and must stay span/cost-covered);
# telemetry/occupancy.py + flightrec.py joined with the occupancy /
# flight-recorder subsystems (stdlib-only modules — they never dispatch,
# so the entry rules stay silent, but joining the surface keeps their
# sources under the same instrumentation sweep and the metric-name
# tree pass as every other observability layer)
INSTR_FILES = ("ops/bls_batch/__init__.py", "ops/bls/__init__.py",
               "ops/bls_batch/g1fft_jax.py",
               "ops/sha256_jax.py", "ops/fr_batch.py",
               "parallel/incremental.py", "parallel/partition.py",
               "resilience/mesh.py", "resilience/checkpoint.py",
               "das/verify.py", "das/recover.py",
               "forkchoice/store.py", "forkchoice/kernels.py",
               "telemetry/occupancy.py", "telemetry/flightrec.py")

# metric-name discipline runs over EVERY package module: instrument
# calls are minted from ops, serve, resilience, telemetry itself — a
# bad name or a sanitization collision can land anywhere
METRIC_GLOBS = ("*.py", "*/*.py", "*/*/*.py")

# request-tracing coverage surface: every `submit_*` entry point of a
# serve executor class must mint a reqtrace.RequestContext (directly or
# via a same-module helper it calls — the same call-graph propagation
# as instr-uncovered-entry), or requests entering through it would be
# invisible to tail-latency attribution
SERVE_FILES = ("serve/executor.py",)

# occupancy-ledger coverage surface: every dispatch/settle seam
# function (`_dispatch*`, `_settle_from_device`) in these modules must
# reach an occupancy-ledger call (begin_batch / note_kernel_* /
# note_settled) directly or via the local call graph — a future
# dispatch seam that skips the ledger would silently punch a hole in
# the busy/bubble attribution (instr-uncovered-dispatch-ledger)
OCCUPANCY_FILES = ("ops/bls_batch/__init__.py", "serve/executor.py",
                   "serve/futures.py")

# shape-laundering functions: a value that went through one of these is
# a bucketed compile key, not a raw dimension.  `mesh_rung` is the
# mesh-width form (parallel.partition): device-count reads are
# mesh-shape compile keys, quantized to the power-of-two ladder;
# `das_rung` is the DAS cell-batch form (ops.fr_batch); `fc_rung` is
# the fork-choice form (forkchoice.kernels: block-count,
# validator-count and attestation-batch ladders); `g1fft_rung` is the
# G1-FFT point-vector form (ops.bls_batch.g1fft_jax)
BUCKET_FUNCS = frozenset({"_bucket", "mesh_rung", "das_rung",
                          "fc_rung", "g1fft_rung"})

# device-pool probes whose results are mesh-shape compile keys: a jit
# factory keyed by a raw device count recompiles per topology without
# the mesh_rung ladder (len(jax.devices()) is caught by the generic
# len() taint)
DEVICE_COUNT_FUNCS = frozenset({"device_count", "local_device_count"})

# annotations that mark a parameter as a static (compile-time) value
_STATIC_TYPE_NAMES = frozenset({"int", "bool", "str", "bytes", "float"})
# attribute metadata reads that are static under trace
_SHAPE_ATTRS = frozenset({"shape", "dtype", "ndim", "size"})


@dataclass(frozen=True)
class Finding:
    file: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.rule}: {self.message}"


@dataclass
class Report:
    """Findings split by suppression state, plus the reasons given."""

    unsuppressed: list[Finding]
    suppressed: list[tuple[Finding, str | None]]
    files: int = 0

    def extend(self, other: "Report") -> None:
        self.unsuppressed.extend(other.unsuppressed)
        self.suppressed.extend(other.suppressed)
        self.files += other.files

    def to_json(self) -> dict:
        return {
            "schema": "cst-analysis-v1",
            "files": self.files,
            "finding_count": len(self.unsuppressed),
            "suppressed_count": len(self.suppressed),
            "suppressed_with_reason_count": sum(
                1 for _, reason in self.suppressed if reason),
            "findings": [vars(f) for f in self.unsuppressed],
            "suppressed": [dict(vars(f), reason=reason)
                           for f, reason in self.suppressed],
        }


# --- suppression comments ----------------------------------------------------

_ALLOW_RE = re.compile(
    r"cst:\s*allow\(\s*([a-z0-9-]+(?:\s*,\s*[a-z0-9-]+)*)\s*\)"
    r"(?:\s*:\s*(.*\S))?")


def parse_suppressions(src: str) -> dict[int, dict[str, str | None]]:
    """line -> {rule-id allowed on that line: reason}.

    A trailing comment covers its own line.  A comment alone on its
    line covers the next CODE line; its reason continues across the
    immediately following comment lines up to the next `cst: allow`
    comment, a blank line, or the code line — so stacked multi-line
    allow annotations each keep their full reason."""
    out: dict[int, dict[str, str | None]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(src).readline)
        comments = [(t.start[0], t.string)
                    for t in tokens if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, SyntaxError, IndentationError):
        comments = [(i + 1, line.strip())
                    for i, line in enumerate(src.splitlines())
                    if line.lstrip().startswith("#")]
    lines = src.splitlines()

    def add(line: int, rules: frozenset, reason: str | None):
        entry = out.setdefault(line, {})
        for rule in rules:
            entry[rule] = reason

    for row, text in comments:
        m = _ALLOW_RE.search(text)
        if not m:
            continue
        rules = frozenset(r.strip() for r in m.group(1).split(","))
        reason_parts = [m.group(2)] if m.group(2) else []
        add(row, rules, m.group(2))
        own_line = lines[row - 1] if row - 1 < len(lines) else ""
        if not own_line.strip().startswith("#"):
            continue                     # trailing comment: done
        # standalone: collect the reason's continuation lines, then
        # register on the next code line
        collecting = bool(reason_parts)
        nxt = row + 1
        while nxt <= len(lines):
            stripped = lines[nxt - 1].strip()
            if stripped.startswith("#"):
                if _ALLOW_RE.search(stripped):
                    collecting = False   # the next annotation starts
                elif collecting:
                    reason_parts.append(stripped.lstrip("#").strip())
                nxt += 1
            elif not stripped:
                collecting = False       # blank: unrelated code follows
                nxt += 1
            else:
                break
        reason = " ".join(reason_parts) if reason_parts else None
        add(nxt, rules, reason)
    return out


# --- AST helpers -------------------------------------------------------------


def _dotted(node) -> str | None:
    """'jax.jit'-style dotted name for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_ref(node) -> bool:
    """Does this expression denote jax.jit (possibly partial-applied)?"""
    d = _dotted(node)
    if d in ("jit", "jax.jit"):
        return True
    if isinstance(node, ast.Call):
        fd = _dotted(node.func)
        if fd in ("partial", "functools.partial") and node.args:
            return _is_jit_ref(node.args[0])
        # jax.jit(static_argnums=...) decorator-factory form
        if fd in ("jit", "jax.jit"):
            return True
    return False


def _jit_static_names(dec, fn: ast.FunctionDef) -> set[str]:
    """static_argnames/static_argnums of a jit decorator -> param names."""
    if not isinstance(dec, ast.Call):
        return set()
    params = [a.arg for a in (list(fn.args.posonlyargs)
                              + list(fn.args.args))]
    static: set[str] = set()
    for kw in dec.keywords:
        v = kw.value
        if kw.arg == "static_argnames":
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                static.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                static |= {e.value for e in v.elts
                           if isinstance(e, ast.Constant)
                           and isinstance(e.value, str)}
        elif kw.arg == "static_argnums":
            nums = []
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                nums = [v.value]
            elif isinstance(v, (ast.Tuple, ast.List)):
                nums = [e.value for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, int)]
            static |= {params[i] for i in nums if i < len(params)}
    return static


def _annotation_is_static(ann) -> bool:
    """int/bool/str-style annotations (incl. `str | None`, Optional[int])
    mark compile-time parameters."""
    if ann is None:
        return False
    if isinstance(ann, ast.Name):
        return ann.id in _STATIC_TYPE_NAMES
    if isinstance(ann, ast.Constant):
        if ann.value is None:
            return True
        return isinstance(ann.value, str) and ann.value in _STATIC_TYPE_NAMES
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        return (_annotation_is_static(ann.left)
                and _annotation_is_static(ann.right))
    if isinstance(ann, ast.Subscript) and _dotted(ann.value) in (
            "Optional", "typing.Optional"):
        return _annotation_is_static(ann.slice)
    return False


def static_params(fn) -> set[str]:
    """Parameters that are static (compile-time) by annotation or by a
    literal int/bool/str default — `n: int`, `axis_name: str | None`,
    `unroll=False`."""
    args = (list(fn.args.posonlyargs) + list(fn.args.args)
            + list(fn.args.kwonlyargs))
    static = {a.arg for a in args if _annotation_is_static(a.annotation)}
    pos = list(fn.args.posonlyargs) + list(fn.args.args)
    defaults = list(fn.args.defaults)
    for a, d in zip(pos[len(pos) - len(defaults):], defaults):
        if isinstance(d, ast.Constant) and isinstance(
                d.value, (bool, int, str, bytes)):
            static.add(a.arg)
    for a, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
        if isinstance(d, ast.Constant) and isinstance(
                d.value, (bool, int, str, bytes)):
            static.add(a.arg)
    return static


def param_names(fn) -> list[str]:
    out = [a.arg for a in (list(fn.args.posonlyargs) + list(fn.args.args)
                           + list(fn.args.kwonlyargs))]
    if fn.args.vararg:
        out.append(fn.args.vararg.arg)
    if fn.args.kwarg:
        out.append(fn.args.kwarg.arg)
    return out


def scope_nodes(fn):
    """Every node in `fn`'s own scope: yields nested function/class
    definition nodes themselves but does NOT descend into their bodies
    (they are separate scopes, analyzed on their own)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def nonstatic_refs(expr, nonstatic: set[str]) -> list[ast.Name]:
    """Load-references to `nonstatic` names in `expr` that are NOT
    behind static metadata access (`x.shape`, `len(x)`, `isinstance`) —
    the references that would concretize a traced value."""
    out: list[ast.Name] = []

    def walk(node):
        if isinstance(node, ast.Attribute) and node.attr in _SHAPE_ATTRS:
            return
        if isinstance(node, ast.Call):
            fd = _dotted(node.func)
            if fd in ("len", "isinstance"):
                return
        if (isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in nonstatic):
            out.append(node)
            return
        for child in ast.iter_child_nodes(node):
            walk(child)

    walk(expr)
    return out


# --- the module model --------------------------------------------------------


class ModuleModel:
    """One parsed device-path module with its jit surface resolved."""

    def __init__(self, src: str, path: str, roles: frozenset):
        self.src = src
        self.path = path
        self.roles = roles
        self.tree = ast.parse(src)
        self.suppressions = parse_suppressions(src)

        # every function definition anywhere in the module, by name
        self.func_index: dict[str, list[ast.FunctionDef]] = {}
        self.all_funcs: list[ast.FunctionDef] = []
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.all_funcs.append(node)
                self.func_index.setdefault(node.name, []).append(node)

        # jit-decorated functions -> their static param names
        self.jit_decorated: dict[ast.FunctionDef, set[str]] = {}
        for fn in self.all_funcs:
            for dec in fn.decorator_list:
                if _is_jit_ref(dec):
                    self.jit_decorated[fn] = _jit_static_names(dec, fn)
                    break

        # functions handed to jit/shard_map by reference: jax.jit(run),
        # shard_map_compat(local, ...)
        referenced: set[str] = set()
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fd = _dotted(node.func)
            is_wrap = (fd in ("jit", "jax.jit")
                       or (fd or "").split(".")[-1] in (
                           "shard_map", "shard_map_compat"))
            if is_wrap and isinstance(node.args[0], ast.Name):
                referenced.add(node.args[0].id)

        # traced bodies: decorated + referenced, plus everything nested
        # inside them; traced_params maps each body to the union of its
        # own and its enclosing traced bodies' non-static params
        self.traced_params: dict[ast.FunctionDef, set[str]] = {}
        roots = list(self.jit_decorated) + [
            fn for name in referenced for fn in self.func_index.get(name, [])]
        for root in roots:
            inherited: set[str] = set()
            self._mark_traced(root, inherited)
        self.jit_bodies = set(self.traced_params)

        # jit factories: module-level functions returning jax.jit(...)
        # or a jit-decorated local function
        self.jit_factories: set[str] = set()
        for node in self.tree.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            for sub in scope_nodes(node):
                if not isinstance(sub, ast.Return) or sub.value is None:
                    continue
                v = sub.value
                if isinstance(v, ast.Call) and _is_jit_ref(v.func):
                    self.jit_factories.add(node.name)
                elif isinstance(v, ast.Name) and any(
                        f in self.jit_decorated
                        for f in self.func_index.get(v.id, [])):
                    self.jit_factories.add(node.name)

    def _mark_traced(self, fn, inherited: set[str]) -> None:
        own = (inherited
               | (set(param_names(fn)) - static_params(fn)
                  - self.jit_decorated.get(fn, set())))
        prev = self.traced_params.get(fn)
        if prev is not None and own <= prev:
            return
        self.traced_params[fn] = own | (prev or set())
        for node in scope_nodes(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._mark_traced(node, self.traced_params[fn])

    def nested_funcs(self, fn):
        return [n for n in scope_nodes(fn)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]

    def is_device_function(self, fn) -> bool:
        """Does this function build jax computations (jnp/lax use, the
        `jnp = _jnp()` idiom, or membership in a traced body)?"""
        if fn in self.jit_bodies:
            return True
        for node in scope_nodes(fn):
            if isinstance(node, ast.Name) and node.id in ("jnp", "lax"):
                return True
            if isinstance(node, ast.Attribute) and (
                    _dotted(node) or "").startswith("jax."):
                return True
            if (isinstance(node, ast.Call)
                    and _dotted(node.func) == "_jnp"):
                return True
        return False

    # --- per-scope dataflow ------------------------------------------------

    def factory_aliases(self, fn) -> set[str]:
        """Local names that (conditionally) hold a jit factory:
        `kernel = _rlc_kernel_h2c if device_h2c else _rlc_kernel`."""
        aliases = set(self.jit_factories)

        def is_factory_expr(e) -> bool:
            if isinstance(e, ast.Name):
                return e.id in aliases
            if isinstance(e, ast.IfExp):
                return is_factory_expr(e.body) and is_factory_expr(e.orelse)
            return False

        for _ in range(2):
            for node in scope_nodes(fn):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and is_factory_expr(node.value)):
                    aliases.add(node.targets[0].id)
        return aliases

    def _scope_assignments(self, fn):
        """Assignment statements of `fn`'s scope in SOURCE order —
        `scope_nodes` is a LIFO walk, and taint gen/kill is
        order-sensitive (`n = xs.shape[0]; n = _bucket(n)` must end
        clean, not tainted)."""
        assigns = [n for n in scope_nodes(fn)
                   if isinstance(n, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign))]
        return sorted(assigns, key=lambda n: (n.lineno, n.col_offset))

    def raw_dim_tainted(self, fn) -> set[str]:
        """Names carrying a raw dimension: derived from len()/`.shape`
        without passing through a BUCKET_FUNCS call."""
        tainted: set[str] = set()

        def expr_tainted(e) -> bool:
            if (isinstance(e, ast.Call)
                    and _dotted(e.func) in BUCKET_FUNCS):
                return False            # the ladder launders the value
            for node in ast.walk(e):
                if (isinstance(node, ast.Call)
                        and _dotted(node.func) == "len"):
                    return True
                if (isinstance(node, ast.Call)
                        and (_dotted(node.func) or "").split(".")[-1]
                        in DEVICE_COUNT_FUNCS):
                    return True         # mesh-shape compile key
                if (isinstance(node, ast.Attribute)
                        and node.attr == "shape"):
                    return True
                if (isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Load)
                        and node.id in tainted):
                    return True
            return False

        def bind(target, hot: bool):
            for n in ast.walk(target):
                if isinstance(n, ast.Name) and isinstance(
                        n.ctx, (ast.Store,)):
                    if hot:
                        tainted.add(n.id)
                    else:
                        tainted.discard(n.id)

        # two source-ordered passes: the second propagates through
        # loop-carried bindings while rebinding-through-_bucket kills
        for _ in range(2):
            for node in self._scope_assignments(fn):
                if isinstance(node, ast.Assign):
                    hot = expr_tainted(node.value)
                    for t in node.targets:
                        bind(t, hot)
                elif isinstance(node, ast.AugAssign):
                    if expr_tainted(node.value):
                        bind(node.target, True)
                elif node.value:        # AnnAssign
                    bind(node.target, expr_tainted(node.value))
        return tainted

    def device_producing(self, call, aliases: set[str]) -> bool:
        """Calls whose result lives on device: `_dispatch(...)`, a
        jitted local, `factory(B)(args)`, jax.block_until_ready."""
        if not isinstance(call, ast.Call):
            return False
        f = call.func
        fd = _dotted(f)
        if fd == "_dispatch" or (fd or "").endswith("block_until_ready"):
            return True
        if isinstance(f, ast.Name):
            if any(d in self.jit_decorated
                   for d in self.func_index.get(f.id, [])):
                return True
        if isinstance(f, ast.Call):        # factory(B)(args)
            inner = f.func
            if isinstance(inner, ast.Name) and inner.id in aliases:
                return True
        return False

    def device_tainted(self, fn, aliases: set[str]) -> set[str]:
        """Names bound (directly, by unpack, or as a comprehension
        target over a tainted iterable) to device values."""
        tainted: set[str] = set()

        def bind_names(target):
            for n in ast.walk(target):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                    tainted.add(n.id)

        for _ in range(2):
            for node in scope_nodes(fn):
                if isinstance(node, ast.Assign) and self.device_producing(
                        node.value, aliases):
                    for t in node.targets:
                        bind_names(t)
                elif isinstance(node, ast.comprehension):
                    it = node.iter
                    if (isinstance(it, ast.Name) and it.id in tainted) \
                            or self.device_producing(it, aliases):
                        bind_names(node.target)
                elif isinstance(node, ast.For):
                    it = node.iter
                    if (isinstance(it, ast.Name) and it.id in tainted) \
                            or self.device_producing(it, aliases):
                        bind_names(node.target)
        return tainted


# --- runner ------------------------------------------------------------------


def _apply_suppressions(model: ModuleModel,
                        findings: list[Finding]) -> Report:
    unsup: list[Finding] = []
    sup: list[tuple[Finding, str | None]] = []
    for f in sorted(findings, key=lambda f: (f.file, f.line, f.rule)):
        entry = model.suppressions.get(f.line)
        if entry and f.rule in entry:
            sup.append((f, entry[f.rule]))
        else:
            unsup.append(f)
    return Report(unsup, sup, files=1)


def analyze_source(src: str, path: str = "<snippet>",
                   roles: frozenset = ALL_ROLES,
                   external_covered: frozenset = frozenset(),
                   external_device: frozenset = frozenset(),
                   external_cost: frozenset = frozenset()) -> Report:
    """Analyze one module's source under the given roles.  Returns the
    suppression-resolved report; `external_covered`/`external_device`/
    `external_cost` feed the instrumentation rules' cross-module
    resolution."""
    from . import (dtype, excswallow, hostsync, instrumentation,
                   metricnames, recompile)

    model = ModuleModel(src, path, roles)
    findings: list[Finding] = []
    if ROLE_DEVICE in roles:
        findings += recompile.check(model)
        findings += hostsync.check(model)
    if ROLE_DEVICE in roles or ROLE_EXC in roles:
        findings += excswallow.check(model)
    if ROLE_LIMB in roles:
        findings += dtype.check(model)
    if ROLE_INSTR in roles:
        findings += instrumentation.check(
            model, external_covered, external_device, external_cost)[0]
    if ROLE_SERVE in roles:
        findings += instrumentation.check_reqtrace(model)
    if ROLE_LEDGER in roles:
        findings += instrumentation.check_occupancy(model)
    if ROLE_METRIC in roles:
        findings += metricnames.check(model)
    return _apply_suppressions(model, findings)


def _tree_files(root: Path) -> list[tuple[Path, frozenset]]:
    files: dict[Path, set] = {}
    for pattern in DEVICE_GLOBS:
        for p in sorted(root.glob(pattern)):
            files.setdefault(p, set()).add(ROLE_DEVICE)
    for rel in DEVICE_FILES:
        p = root / rel
        if p.exists():
            files.setdefault(p, set()).add(ROLE_DEVICE)
    for rel in LIMB_FILES:
        p = root / rel
        if p.exists():
            files.setdefault(p, set()).add(ROLE_LIMB)
    for rel in KERNEL_FILES:
        p = root / rel
        if p.exists():
            files.setdefault(p, set()).add(ROLE_KERNEL)
    for pattern in EXC_GLOBS:
        for p in sorted(root.glob(pattern)):
            files.setdefault(p, set()).add(ROLE_EXC)
    for rel in SERVE_FILES:
        p = root / rel
        if p.exists():
            files.setdefault(p, set()).add(ROLE_SERVE)
    for rel in OCCUPANCY_FILES:
        p = root / rel
        if p.exists():
            files.setdefault(p, set()).add(ROLE_LEDGER)
    for pattern in METRIC_GLOBS:
        for p in sorted(root.glob(pattern)):
            files.setdefault(p, set()).add(ROLE_METRIC)
    return [(p, frozenset(r)) for p, r in sorted(files.items())]


def _instr_chain(root: Path | None = None):
    """The ONE implementation of the ordered instrumentation pass over
    INSTR_FILES (ops/bls_batch first, so the facade's calls into its
    covered entry points count as coverage).  Returns, per file:
    (resolved_path, model, findings, entry_covered, entry_device,
    entry_cost) where the entry sets are the chained inputs that file's
    pass started from — both the tree run and spot runs consume this."""
    from . import instrumentation

    root = Path(root) if root is not None else PKG_ROOT
    covered: frozenset = frozenset()
    device: frozenset = frozenset()
    cost: frozenset = frozenset()
    out = []
    for rel in INSTR_FILES:
        path = root / rel
        if not path.exists():
            continue
        model = ModuleModel(path.read_text(),
                            str(path.relative_to(root.parent)),
                            frozenset({ROLE_INSTR}))
        findings, cov, dev, cst = instrumentation.check(
            model, covered, device, cost)
        out.append((path.resolve(), model, findings, covered, device,
                    cost))
        covered, device, cost = (frozenset(cov), frozenset(dev),
                                 frozenset(cst))
    return out


def analyze_tree(root: Path | None = None) -> Report:
    """Run every applicable rule family over the device path."""
    root = Path(root) if root is not None else PKG_ROOT
    repo = root.parent
    report = Report([], [])
    for path, roles in _tree_files(root):
        rel = str(path.relative_to(repo))
        report.extend(analyze_source(path.read_text(), rel, roles))

    for _, model, findings, _, _, _ in _instr_chain(root):
        sub = _apply_suppressions(model, findings)
        sub.files = 0           # already counted in the device pass
        report.extend(sub)
    return report


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    json_out = None
    if "--json" in argv:
        i = argv.index("--json")
        try:
            json_out = argv[i + 1]
        except IndexError:
            print("--json needs a path", file=sys.stderr)
            return 2
        del argv[i:i + 2]

    if argv:
        # package files keep their tree-mode roles (so a spot run of a
        # real module agrees with the tree run); anything else — e.g. a
        # test fixture — gets every rule family
        tree_roles = {p.resolve(): roles
                      for p, roles in _tree_files(PKG_ROOT)}
        instr_inputs = {path: (cov, dev, cst)
                        for path, _, _, cov, dev, cst in _instr_chain()}
        report = Report([], [])
        for arg in argv:
            p = Path(arg)
            try:
                src = p.read_text()
            except OSError as exc:
                print(f"{p}: cannot read ({exc})", file=sys.stderr)
                return 2
            try:
                resolved = p.resolve()
                roles = tree_roles.get(resolved, ALL_ROLES)
                ext_cov, ext_dev, ext_cost = instr_inputs.get(
                    resolved, (frozenset(), frozenset(), frozenset()))
                if resolved in instr_inputs:
                    roles = roles | {ROLE_INSTR}
                report.extend(analyze_source(src, str(p), roles,
                                             ext_cov, ext_dev, ext_cost))
            except SyntaxError as exc:
                print(f"{p}: not parseable python ({exc})",
                      file=sys.stderr)
                return 2
    else:
        report = analyze_tree()

    if json_out:
        out = Path(json_out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(report.to_json(), indent=2) + "\n")

    for f in report.unsuppressed:
        print(f.render())
    n_sup = len(report.suppressed)
    n_reason = sum(1 for _, r in report.suppressed if r)
    if report.unsuppressed:
        print(f"device-path analysis: {len(report.unsuppressed)} "
              f"finding(s), {n_sup} suppressed", file=sys.stderr)
        return 1
    print(f"device-path analysis: clean — {report.files} file(s), "
          f"{n_sup} finding(s) suppressed ({n_reason} with a reason)")
    return 0
