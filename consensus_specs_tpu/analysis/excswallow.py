"""Rule family 6 — swallowed exceptions in device + serve modules.

A bare `except:` (or an over-broad `except Exception:` /
`except BaseException:`) that neither re-raises nor records the error
turns a device failure into silence: a poisoned batch reads as healthy,
a failed dispatch as a slow one, and the resilience layer's whole
premise — every failure is either recovered or VISIBLE as a typed
error — quietly breaks.  This rule flags exactly that shape in the
device path and the serving subsystem.

A broad handler is fine when it demonstrably handles:

- it (re-)raises somewhere in its own scope, or
- it binds the exception (`except Exception as exc:`) and actually USES
  the bound name — poisoning a future (`set_exception(exc)` /
  `DeviceFuture.failed(exc)`), storing it for the read side
  (`self._exc = exc`), wrapping it, or recording it.  A bound-but-
  unused name is a swallow with extra steps.

Narrow handlers (`except ValueError:` etc.) are out of scope — catching
a specific expected error and defaulting is a normal host-side pattern
(the wire-format parsers do it throughout).  Intentional broad
swallows carry the usual `# cst: allow(exc-swallow-device): reason`
annotation, which doubles as the inventory of deliberate
error-suppression points.
"""

from __future__ import annotations

import ast

from .core import Finding, ModuleModel

_BROAD_NAMES = frozenset({"Exception", "BaseException"})


def _names_in_type(node) -> set[str]:
    """Exception-class names a handler's type expression mentions
    (follows tuples; dotted names use their last component)."""
    if node is None:
        return set()
    out: set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.add(sub.id)
        elif isinstance(sub, ast.Attribute):
            out.add(sub.attr)
    return out


def _is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    return bool(_names_in_type(handler.type) & _BROAD_NAMES)


def _handles_it(handler: ast.ExceptHandler) -> bool:
    """Does the handler body re-raise, or use the bound exception?"""
    for node in ast.walk(ast.Module(body=handler.body,
                                    type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if handler.name and isinstance(node, ast.Name) \
                and isinstance(node.ctx, ast.Load) \
                and node.id == handler.name:
            return True
    return False


def check(model: ModuleModel) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node) or _handles_it(node):
            continue
        what = "bare except" if node.type is None else \
            "over-broad except " + "/".join(
                sorted(_names_in_type(node.type) & _BROAD_NAMES))
        findings.append(Finding(
            model.path, node.lineno, "exc-swallow-device",
            f"{what} swallows device/serve errors without re-raising, "
            f"poisoning a handle, or recording the exception — "
            f"failures must stay typed and visible (narrow the except, "
            f"use the bound exception, or annotate why the swallow is "
            f"deliberate)"))
    return findings
