"""Rule family 3 — dtype discipline in the limb-arithmetic modules.

The BLS12-381 limb representation (33 x 12-bit limbs in int32 lanes,
`ops/bls_batch/fq.py`) is only sound while every array stays int32 and
every scalar mixed into lax ops fits the headroom budget.  Three ways
that discipline silently breaks:

dtype-int-literal    a Python int literal >= 2**32 mixed into an
                     expression with non-constant operands: under jax's
                     default 32-bit mode it wraps or weak-promotes
                     depending on context — never loudly.
dtype-float          any float literal or float-dtype reference: one
                     float32 intermediate destroys exact limb
                     arithmetic (and TPUs round f32 differently from
                     hosts, so the corruption is platform-dependent).
dtype-implicit-cast  jnp.asarray/array/zeros/ones/empty/full/arange
                     without an explicit dtype: `jnp.zeros(shape)` is
                     float32, `jnp.asarray(host_const)` inherits
                     whatever numpy default the host picked — both are
                     trace-time constants, so the wrong dtype bakes
                     into the compiled kernel.

These rules run module-wide (host conversion helpers included): the
limb modules' host side feeds trace-time constants, so the same
discipline applies.
"""

from __future__ import annotations

import ast

from .core import Finding, ModuleModel, _dotted

_BIG = 1 << 32
# NOTE: 'double'/'half' are deliberately absent — `g2.double(T)` (point
# doubling) would collide; the jnp aliases below cover the real hazards
_FLOAT_DTYPES = frozenset({"float16", "float32", "float64", "bfloat16",
                           "float_"})
# jnp constructors whose default dtype is a trap; zeros/ones/empty/full
# accept dtype positionally after the shape (full: after the fill value)
_CTORS = {"asarray": 1, "array": 1, "arange": 3,
          "zeros": 1, "ones": 1, "empty": 1, "full": 2}


def _is_big_literal(node) -> bool:
    return (isinstance(node, ast.Constant)
            and isinstance(node.value, int)
            and not isinstance(node.value, bool)
            and abs(node.value) >= _BIG)


def _is_const(node) -> bool:
    return isinstance(node, ast.Constant)


def _check_int_literals(model: ModuleModel) -> list[Finding]:
    findings = []
    for node in ast.walk(model.tree):
        operands = []
        if isinstance(node, ast.BinOp):
            operands = [node.left, node.right]
        elif isinstance(node, ast.Compare):
            operands = [node.left] + list(node.comparators)
        if not operands:
            continue
        if any(_is_big_literal(o) for o in operands) \
                and any(not _is_const(o) for o in operands):
            findings.append(Finding(
                model.path, node.lineno, "dtype-int-literal",
                "int literal >= 2**32 mixed into limb arithmetic — "
                "route it through int_to_limbs/to_mont or a typed "
                "constant"))
    return findings


def _check_floats(model: ModuleModel) -> list[Finding]:
    # whole-module walk: a module-level float constant is a trace-time
    # constant feeding limb arithmetic just like one inside a function
    findings = []
    for node in ast.walk(model.tree):
        if (isinstance(node, ast.Constant)
                and isinstance(node.value, float)):
            findings.append(Finding(
                model.path, node.lineno, "dtype-float",
                f"float literal {node.value!r} in a limb module"))
        elif (isinstance(node, ast.Attribute)
                and node.attr in _FLOAT_DTYPES):
            findings.append(Finding(
                model.path, node.lineno, "dtype-float",
                f"float dtype '{node.attr}' referenced in a limb "
                f"module"))
    return findings


def _check_implicit_casts(model: ModuleModel) -> list[Finding]:
    findings = []
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call):
            continue
        fd = _dotted(node.func)
        if fd is None or "." not in fd:
            continue
        head, attr = fd.rsplit(".", 1)
        if head != "jnp" or attr not in _CTORS:
            continue
        if any(kw.arg == "dtype" for kw in node.keywords):
            continue
        if len(node.args) > _CTORS[attr]:     # positional dtype
            continue
        findings.append(Finding(
            model.path, node.lineno, "dtype-implicit-cast",
            f"jnp.{attr}() without an explicit dtype — the default "
            f"(float32 / inherited) bakes into the traced constant; "
            f"pass dtype=jnp.int32 (or the intended type)"))
    return findings


def check(model: ModuleModel) -> list[Finding]:
    return (_check_int_literals(model) + _check_floats(model)
            + _check_implicit_casts(model))
