"""Rule family 7 — metric-name discipline (metric-name-invalid).

The metrics exposition endpoint (`telemetry/metrics_export.py`) renders
every registry name into the Prometheus exposition format by
sanitizing it (`sanitize_name`: anything outside ``[a-zA-Z0-9_:]``
becomes ``_``).  Sanitization never *fails* — it silently rewrites —
so two hazards stay invisible until a scrape looks wrong:

- a name outside the repo's dotted-name convention
  (``seg.seg2.seg3``, segments of ``[a-zA-Z0-9_]``, leading segment
  not starting with a digit) leaks a surprising exposition stem
  (``cst_foo__bar_total`` from ``foo-.bar``);
- two *different* registry names can sanitize to the SAME exposition
  family (``serve.queue_depth`` vs ``serve.queue.depth`` both become
  ``cst_serve_queue_depth``) and their series silently merge.

This rule makes both a lint invariant at every telemetry call site:
the literal first argument of ``telemetry.count / observe / gauge /
span / add_event`` (or ``core.*`` inside the telemetry package) must
match the dotted-name convention, and no two distinct literal names in
a module may collide after sanitization within the same instrument
family (counters, histograms, gauges, spans).

Names built with f-strings (``f"kernel.{kernel}.calls"``) are checked
on their LITERAL fragments only — the runtime segments are the point
of the f-string — and are exempt from the collision check (their final
spelling is not known statically).  Exposition *label* names come from
keyword arguments (`add_event(name, dur, kind=...)`) and reqtrace
context fields, which are Python identifiers and therefore always
inside the Prometheus label charset; they need no rule.
"""

from __future__ import annotations

import ast
import re

from .core import Finding, ModuleModel

# instrument API name -> exposition family (collisions only matter
# within a family: counters get a `_total` stem, spans `_seconds_*`,
# histogram summaries their own suffixes, gauges the bare stem)
_API = {
    "count": "counter",
    "observe": "histogram",
    "gauge": "gauge",
    "span": "span",
    "add_event": "span",
}

# the repo's dotted-name convention: dot-separated segments of the
# metric charset, first character a letter or underscore
_NAME_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*(\.[a-zA-Z0-9_]+)*\Z")
# literal fragments of an f-string name: any run of in-charset
# characters (the runtime segments supply the rest)
_FRAG_RE = re.compile(r"[a-zA-Z0-9_.]*\Z")

_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _sanitize(name: str) -> str:
    """Mirror of `metrics_export.sanitize_name` — duplicated here so
    the analyzer stays importable without the telemetry package (and
    pure-stdlib, like every other rule)."""
    out = _SANITIZE_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _telemetry_aliases(model: ModuleModel) -> tuple[set[str], dict[str, str]]:
    """(module aliases whose attributes are the instrument API,
    bare-imported instrument names -> API name)."""
    aliases: set[str] = set()
    bare: dict[str, str] = {}
    for node in ast.walk(model.tree):
        if isinstance(node, ast.ImportFrom):
            mod = (node.module or "").split(".")[-1]
            for a in node.names:
                if a.name == "telemetry":
                    aliases.add(a.asname or a.name)
                elif mod == "telemetry" and a.name == "core":
                    aliases.add(a.asname or a.name)
                elif node.module is None and node.level and a.name == "core":
                    # `from . import core` — the telemetry package's own
                    # modules; other packages' `core` has no instrument
                    # API, so a false alias can only match a call like
                    # core.count(...) that does not exist there
                    aliases.add(a.asname or a.name)
                elif mod in ("telemetry", "core") and a.name in _API:
                    bare[a.asname or a.name] = a.name
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.split(".")[-1] == "telemetry":
                    aliases.add(a.asname or a.name.split(".")[0])
    return aliases, bare


def _instrument_calls(model: ModuleModel, aliases: set[str],
                      bare: dict[str, str]):
    """Yield (call_node, api_name) for every instrument call site."""
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr in _API
                and isinstance(f.value, ast.Name)
                and f.value.id in aliases):
            yield node, f.attr
        elif isinstance(f, ast.Name) and f.id in bare:
            yield node, bare[f.id]


def check(model: ModuleModel) -> list:
    findings: list[Finding] = []
    # (family, sanitized stem) -> (first literal spelling, lineno)
    seen: dict[tuple[str, str], tuple[str, int]] = {}
    aliases, bare = _telemetry_aliases(model)

    for call, api in _instrument_calls(model, aliases, bare):
        if not call.args:
            continue
        arg = call.args[0]
        family = _API[api]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            name = arg.value
            if not _NAME_RE.match(name):
                findings.append(Finding(
                    model.path, arg.lineno, "metric-name-invalid",
                    f"telemetry.{api}() name {name!r} is outside the "
                    f"dotted-name convention "
                    f"([a-zA-Z_][a-zA-Z0-9_]*(.seg)*) — sanitization "
                    f"would silently rewrite its exposition stem (see "
                    f"README Monitoring)"))
                continue
            key = (family, _sanitize(name))
            prev = seen.get(key)
            if prev is None:
                seen[key] = (name, arg.lineno)
            elif prev[0] != name:
                findings.append(Finding(
                    model.path, arg.lineno, "metric-name-invalid",
                    f"telemetry.{api}() name {name!r} collides with "
                    f"{prev[0]!r} (line {prev[1]}) after exposition "
                    f"sanitization — both render as the "
                    f"'cst_{_sanitize(name)}' {family} family and "
                    f"their series would silently merge"))
        elif isinstance(arg, ast.JoinedStr):
            for i, part in enumerate(arg.values):
                if not (isinstance(part, ast.Constant)
                        and isinstance(part.value, str)):
                    continue
                frag = part.value
                ok = bool(_FRAG_RE.match(frag))
                if ok and i == 0 and frag and (frag[0].isdigit()
                                               or frag[0] == "."):
                    ok = False
                if not ok:
                    findings.append(Finding(
                        model.path, arg.lineno, "metric-name-invalid",
                        f"telemetry.{api}() f-string name has literal "
                        f"fragment {frag!r} outside the dotted-name "
                        f"charset [a-zA-Z0-9_.] — sanitization would "
                        f"silently rewrite its exposition stem (see "
                        f"README Monitoring)"))
                    break
    return findings
