"""Rule family 4 — instrumentation coverage of kernel entry points,
plus the request-tracing coverage of serve submit entry points.

PR 2's telemetry layer answers the ROADMAP's perf questions only while
every kernel entry point reports into it; a new kernel that lands
without a span or counter is invisible to the compile/run split, the
padding-waste accounting and the routing counters.  This rule family
makes that a lint invariant on the kernel surfaces named by
`core.INSTR_FILES`:

instr-uncovered-entry
    every PUBLIC function (or public method of a public class) that
    reaches a device dispatch — `_dispatch(...)`, a jit factory, a
    jit-decorated local, or a covered bls_batch entry — must open a
    `telemetry.span(...)` / `telemetry.count(...)` either directly or
    via a same-surface function it calls.

reqtrace-uncovered-submit
    every public `submit_*` method of a public class in the serve
    executor surface (`core.SERVE_FILES`) must mint a request-tracing
    context — a `reqtrace.mint(...)` call, directly or via a
    same-module function/method it calls (the same local call-graph
    propagation as instr-uncovered-entry).  A submit entry point that
    skips minting produces requests invisible to the tail-latency
    attribution the serve-p99 production claim leans on.

instr-uncovered-cost
    the same reach set must also pass through the COST-capture seam —
    `_dispatch(...)` (which embeds it) or a `costmodel.*` call
    (`costmodel.capture`, `costmodel.sample_watermark`) — directly or
    transitively, so every kernel stays visible to the roofline /
    utilization layer (`telemetry/costmodel.py`).  Intentional gaps are
    allow-annotated with a reason, like every other rule.

instr-uncovered-dispatch-ledger
    every dispatch/settle seam function (`_dispatch*` or
    `_settle_from_device`) in the occupancy surface
    (`core.OCCUPANCY_FILES`) must reach an occupancy-LEDGER call —
    `occupancy.begin_batch`, `note_kernel_busy`,
    `note_kernel_dispatched` or `note_settled`, directly or via the
    local call graph.  A bare `occupancy.enabled()` gate does not
    count, mirroring the cost-capture rule: the seam must actually
    stamp the ledger, not just consult it.  A future dispatch seam
    that skips the ledger would punch a silent hole in the busy /
    bubble attribution (README Pipeline occupancy).

Coverage propagates along the local call graph (a facade function that
delegates to `bls_batch.batch_verify` is covered by the span — and the
capture seam — inside `batch_verify`), which is why the tree runner
analyzes `ops/bls_batch` first and feeds its covered entry names into
the facade's pass.
"""

from __future__ import annotations

import ast

from .core import Finding, ModuleModel, _dotted, scope_nodes

# modules whose covered entries count as external coverage when called
# as `bls_batch.X(...)` or via `from ..bls_batch import X`
_DEVICE_PKG = "bls_batch"


def _functions(model: ModuleModel):
    """(qualname, node, public) for module-level functions and methods
    of module-level classes."""
    out = []
    for node in model.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append((node.name, node, not node.name.startswith("_")))
        elif isinstance(node, ast.ClassDef):
            cls_public = not node.name.startswith("_")
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    public = cls_public and not sub.name.startswith("_")
                    out.append((f"{node.name}.{sub.name}", sub, public))
    return out


def _imported_device_names(model: ModuleModel) -> tuple[set[str], set[str]]:
    """(names imported from the device package, module aliases of it)."""
    names: set[str] = set()
    aliases: set[str] = set()
    for node in ast.walk(model.tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.split(".")[-1] == _DEVICE_PKG:
                names |= {a.asname or a.name for a in node.names}
            else:
                aliases |= {a.asname or a.name for a in node.names
                            if a.name == _DEVICE_PKG}
        elif isinstance(node, ast.Import):
            aliases |= {a.asname or a.name.split(".")[0]
                        for a in node.names
                        if a.name.split(".")[-1] == _DEVICE_PKG}
    return names, aliases


def check(model: ModuleModel, external_covered=frozenset(),
          external_device=frozenset(), external_cost=frozenset()):
    """Returns (findings, covered_public_names, device_public_names,
    cost_public_names) so the tree runner can chain the bls_batch ->
    bls facade pair (and onward)."""
    funcs = _functions(model)
    by_name: dict[str, list] = {}
    for qual, node, _ in funcs:
        by_name.setdefault(qual.split(".")[-1], []).append(node)
    imported_dev, dev_aliases = _imported_device_names(model)

    telemetry_direct: set = set()
    cost_direct: set = set()
    reaches_device: set = set()
    calls: dict = {n: set() for _, n, _ in funcs}

    for qual, fn, _ in funcs:
        aliases = model.factory_aliases(fn)
        for node in scope_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            fd = _dotted(node.func)
            # the cost-capture seam, however the module spells the
            # import — ONLY the seam calls count: a bare
            # costmodel.enabled() gate must not silence the rule
            if fd:
                parts = fd.split(".")
                if "costmodel" in parts[:-1] and parts[-1] in (
                        "capture", "record_cost", "sample_watermark"):
                    cost_direct.add(fn)
                    continue
            if fd and fd.startswith("telemetry."):
                telemetry_direct.add(fn)
                continue
            # device dispatch sites (_dispatch also embeds the
            # cost-capture seam)
            if fd == "_dispatch":
                reaches_device.add(fn)
                cost_direct.add(fn)
            elif isinstance(node.func, ast.Name):
                name = node.func.id
                if name in model.jit_factories or name in aliases:
                    reaches_device.add(fn)
                elif any(d in model.jit_decorated
                         for d in model.func_index.get(name, [])):
                    reaches_device.add(fn)
                elif name in imported_dev and name in external_device:
                    reaches_device.add(fn)
                elif name in imported_dev and not external_device:
                    # standalone run: imported device names count
                    reaches_device.add(fn)
                for callee in by_name.get(name, []):
                    calls[fn].add(callee)
            elif isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                base = node.func.value
                if (isinstance(base, ast.Name) and base.id in dev_aliases
                        and (attr in external_device
                             or not external_device)):
                    reaches_device.add(fn)
                if (isinstance(base, ast.Name) and base.id in dev_aliases
                        and attr in external_covered):
                    telemetry_direct.add(fn)
                if (isinstance(base, ast.Name) and base.id in dev_aliases
                        and attr in external_cost):
                    cost_direct.add(fn)
                # method / local resolution by bare attribute name
                for callee in by_name.get(attr, []):
                    calls[fn].add(callee)
            # calls to names imported from bls_batch that are covered
            if (isinstance(node.func, ast.Name)
                    and node.func.id in imported_dev
                    and node.func.id in external_covered):
                telemetry_direct.add(fn)
            if (isinstance(node.func, ast.Name)
                    and node.func.id in imported_dev
                    and node.func.id in external_cost):
                cost_direct.add(fn)

    # propagate coverage, cost coverage and device reach over the
    # local call graph
    covered = set(telemetry_direct)
    cost_covered = set(cost_direct)
    reach = set(reaches_device)
    changed = True
    while changed:
        changed = False
        for _, fn, _ in funcs:
            if fn not in covered and calls[fn] & covered:
                covered.add(fn)
                changed = True
            if fn not in cost_covered and calls[fn] & cost_covered:
                cost_covered.add(fn)
                changed = True
            if fn not in reach and calls[fn] & reach:
                reach.add(fn)
                changed = True

    findings = []
    for qual, fn, public in funcs:
        if public and fn in reach and fn not in covered:
            findings.append(Finding(
                model.path, fn.lineno, "instr-uncovered-entry",
                f"public kernel entry point {qual}() dispatches to the "
                f"device without opening a telemetry span/counter — "
                f"new kernels must stay observable (see README "
                f"Telemetry)"))
        if public and fn in reach and fn not in cost_covered:
            findings.append(Finding(
                model.path, fn.lineno, "instr-uncovered-cost",
                f"public device-kernel entry point {qual}() never "
                f"passes through the cost-capture seam (_dispatch or "
                f"costmodel.capture) — the kernel stays invisible to "
                f"the roofline/utilization layer (see README Cost "
                f"model)"))

    covered_public = {qual.split(".")[-1] for qual, fn, public in funcs
                      if public and fn in covered}
    device_public = {qual.split(".")[-1] for qual, fn, public in funcs
                     if public and fn in reach}
    cost_public = {qual.split(".")[-1] for qual, fn, public in funcs
                   if public and fn in cost_covered}
    return findings, covered_public, device_public, cost_public


# --- request-tracing coverage (reqtrace-uncovered-submit) --------------------
#
# The serving counterpart of instr-uncovered-entry: a kernel must open a
# span, a submit entry point must mint a RequestContext.  Minting is
# recognized however the module spells the import — `reqtrace.mint(...)`
# through a module alias, or a bare `mint(...)` imported from the
# reqtrace module — and propagates over the same local call graph, so
# the canonical `submit_x() -> self._submit() -> reqtrace.mint()` chain
# covers every facade.

_REQTRACE_MOD = "reqtrace"


def _reqtrace_mint_names(model: ModuleModel) -> tuple[set[str], set[str]]:
    """(bare names importing reqtrace.mint, module aliases of reqtrace)."""
    names: set[str] = set()
    aliases: set[str] = set()
    for node in ast.walk(model.tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.split(".")[-1] == _REQTRACE_MOD:
                names |= {a.asname or a.name for a in node.names
                          if a.name == "mint"}
            else:
                aliases |= {a.asname or a.name for a in node.names
                            if a.name == _REQTRACE_MOD}
        elif isinstance(node, ast.Import):
            aliases |= {a.asname or a.name.split(".")[0]
                        for a in node.names
                        if a.name.split(".")[-1] == _REQTRACE_MOD}
    return names, aliases


def check_reqtrace(model: ModuleModel) -> list:
    """Findings for public `submit_*` methods (of public classes) that
    never reach a `reqtrace.mint(...)` call through the local call
    graph."""
    funcs = _functions(model)
    by_name: dict[str, list] = {}
    for qual, node, _ in funcs:
        by_name.setdefault(qual.split(".")[-1], []).append(node)
    mint_names, mod_aliases = _reqtrace_mint_names(model)

    mints: set = set()
    calls: dict = {n: set() for _, n, _ in funcs}
    for _, fn, _ in funcs:
        for node in scope_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "mint" \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in mod_aliases:
                mints.add(fn)
                continue
            if isinstance(f, ast.Name) and f.id in mint_names:
                mints.add(fn)
                continue
            # local call-graph edges: bare calls and self.method() /
            # cls.method() resolve by name, same as the kernel rule
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            if name:
                for callee in by_name.get(name, []):
                    calls[fn].add(callee)

    covered = set(mints)
    changed = True
    while changed:
        changed = False
        for _, fn, _ in funcs:
            if fn not in covered and calls[fn] & covered:
                covered.add(fn)
                changed = True

    findings = []
    for qual, fn, public in funcs:
        if public and qual.split(".")[-1].startswith("submit_") \
                and fn not in covered:
            findings.append(Finding(
                model.path, fn.lineno, "reqtrace-uncovered-submit",
                f"serve entry point {qual}() never mints a "
                f"reqtrace.RequestContext — requests submitted through "
                f"it are invisible to tail-latency attribution (see "
                f"README Request tracing)"))
    return findings


# --- occupancy-ledger coverage (instr-uncovered-dispatch-ledger) -------------
#
# The pipeline counterpart of the rules above: a kernel must open a
# span, a submit entry must mint a context, and a dispatch/settle seam
# must stamp the occupancy ledger.  Only the LEDGER entry points count
# as coverage — `occupancy.enabled()` is a gate, not a stamp, exactly
# like `costmodel.enabled()` under instr-uncovered-cost.

_OCCUPANCY_MOD = "occupancy"
_LEDGER_FUNCS = frozenset({"begin_batch", "note_kernel_busy",
                           "note_kernel_dispatched", "note_settled"})


def _occupancy_ledger_names(model: ModuleModel) -> tuple[set[str], set[str]]:
    """(bare names importing occupancy ledger entries, module aliases
    of the occupancy module)."""
    names: set[str] = set()
    aliases: set[str] = set()
    for node in ast.walk(model.tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.split(".")[-1] == _OCCUPANCY_MOD:
                names |= {a.asname or a.name for a in node.names
                          if a.name in _LEDGER_FUNCS}
            else:
                aliases |= {a.asname or a.name for a in node.names
                            if a.name == _OCCUPANCY_MOD}
        elif isinstance(node, ast.Import):
            aliases |= {a.asname or a.name.split(".")[0]
                        for a in node.names
                        if a.name.split(".")[-1] == _OCCUPANCY_MOD}
    return names, aliases


def check_occupancy(model: ModuleModel) -> list:
    """Findings for dispatch/settle seam functions (`_dispatch*` or
    `_settle_from_device`, module-level or method) that never reach an
    occupancy-ledger call through the local call graph."""
    funcs = _functions(model)
    by_name: dict[str, list] = {}
    for qual, node, _ in funcs:
        by_name.setdefault(qual.split(".")[-1], []).append(node)
    ledger_names, mod_aliases = _occupancy_ledger_names(model)

    stamps: set = set()
    calls: dict = {n: set() for _, n, _ in funcs}
    for _, fn, _ in funcs:
        for node in scope_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _LEDGER_FUNCS \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in mod_aliases:
                stamps.add(fn)
                continue
            if isinstance(f, ast.Name) and f.id in ledger_names:
                stamps.add(fn)
                continue
            # local call-graph edges, same resolution as the rules above
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            if name:
                for callee in by_name.get(name, []):
                    calls[fn].add(callee)

    covered = set(stamps)
    changed = True
    while changed:
        changed = False
        for _, fn, _ in funcs:
            if fn not in covered and calls[fn] & covered:
                covered.add(fn)
                changed = True

    def _is_seam(qual: str) -> bool:
        leaf = qual.split(".")[-1]
        return leaf.startswith("_dispatch") or leaf == "_settle_from_device"

    findings = []
    for qual, fn, _ in funcs:
        if _is_seam(qual) and fn not in covered:
            findings.append(Finding(
                model.path, fn.lineno, "instr-uncovered-dispatch-ledger",
                f"dispatch seam {qual}() never stamps the occupancy "
                f"ledger (begin_batch / note_kernel_* / note_settled) — "
                f"device work flowing through it is invisible to the "
                f"busy/bubble attribution (see README Pipeline "
                f"occupancy)"))
    return findings
