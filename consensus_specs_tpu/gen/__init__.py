"""Reference-test vector generator (layer L5).

Re-runs the dual-mode spec tests in generator mode and writes the
cross-client vector tree in the reference's on-disk contract
(`tests/formats/README.md`):

    <preset>/<fork>/<runner>/<handler>/<suite>/<case>/
        meta.yaml  *.yaml  *.ssz_snappy

Entry point: ``python -m consensus_specs_tpu.gen --output <dir> …``.
"""
