"""Vector part writers: YAML (pyyaml) + `.ssz_snappy` via the in-tree
snappy codec.  Output value conventions follow the reference dumper
(`gen_helpers/gen_base/dumper.py`): 0x-hex strings single-quoted, None as
null, config bytes as bare hex ints, snappy-compressed raw SSZ."""

from __future__ import annotations

import yaml

from ..utils.snappy import compress
from .typing import TestCase


class _VectorDumper(yaml.SafeDumper):
    pass


def _repr_none(dumper, _):
    return dumper.represent_scalar("tag:yaml.org,2002:null", "null")


def _repr_str(dumper, data):
    if data.startswith("0x"):
        # quote hex strings so a zero-byte value can't parse as an int
        return dumper.represent_scalar("tag:yaml.org,2002:str", data,
                                       style="'")
    return dumper.represent_str(data)


_VectorDumper.add_representer(type(None), _repr_none)
_VectorDumper.add_representer(str, _repr_str)


class _CfgDumper(yaml.SafeDumper):
    """Config YAML subset: one key per line, bytes as bare 0x ints."""


def _cfg_repr_bytes(dumper, data):
    return dumper.represent_scalar("tag:yaml.org,2002:int", "0x" + data.hex())


def _cfg_repr_str(dumper, data):
    return dumper.represent_scalar("tag:yaml.org,2002:str", data, style="'")


_CfgDumper.add_representer(bytes, _cfg_repr_bytes)


class quoted_str(str):
    """Marker for strings that must be quoted in config YAML (the
    reference's `context.quoted_str`)."""


_CfgDumper.add_representer(quoted_str, _cfg_repr_str)


def _coerce_ints(data):
    """YAML-encodable plain data: spec uint subclasses print as plain ints,
    nested structures recursively."""
    if isinstance(data, bool):
        return data
    if isinstance(data, int):
        return int(data)
    if isinstance(data, (list, tuple)):
        return [_coerce_ints(x) for x in data]
    if isinstance(data, dict):
        return {_coerce_ints(k): _coerce_ints(v) for k, v in data.items()}
    if isinstance(data, bytes):
        return "0x" + data.hex()
    return data


class Dumper:
    def dump_meta(self, test_case: TestCase, meta: dict) -> None:
        if not meta:
            return
        self._write_yaml(test_case, "meta", meta, _VectorDumper,
                         default_flow_style=None)

    def dump_cfg(self, test_case: TestCase, name: str, data) -> None:
        self._write_yaml(test_case, name, data, _CfgDumper,
                         default_flow_style=False)

    def dump_data(self, test_case: TestCase, name: str, data) -> None:
        self._write_yaml(test_case, name, data, _VectorDumper,
                         default_flow_style=None)

    def dump_ssz(self, test_case: TestCase, name: str, data: bytes) -> None:
        path = test_case.dir / f"{name}.ssz_snappy"
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_bytes(compress(data))

    def _write_yaml(self, test_case: TestCase, name: str, data, dumper_cls,
                    default_flow_style) -> None:
        path = test_case.dir / f"{name}.yaml"
        path.parent.mkdir(parents=True, exist_ok=True)
        with path.open("w") as f:
            yaml.dump(_coerce_ints(data), f, Dumper=dumper_cls,
                      default_flow_style=default_flow_style, width=1024,
                      sort_keys=False)
