"""Fork-choice compliance vector factory (the reference's
`compliance_runners/fork_choice/test_gen.py`, tiny config): enumerated
block-tree instances x seeded vote variations, emitted in the standard
fork_choice step format under runner name `fork_choice_compliance`."""

from __future__ import annotations

import random

from ...testlib.context import spec_state_test, with_phases
from ..compliance import enumerate_block_trees, instantiate_block_tree_test
from ..compliance.enumerator import attestation_variations
from ..from_tests import generate_case_fn
from ..typing import TestCase

# tiny configuration (the reference's tiny/test_gen.yaml knobs)
TINY = {
    "n_blocks": 5,
    "max_branching": 2,
    "seed": 123,
    "nr_variations": 2,
    "nr_mutations": 1,
}


def iter_tiny_cases():
    rng = random.Random(TINY["seed"])
    trees = enumerate_block_trees(TINY["n_blocks"],
                                  max_branching=TINY["max_branching"])
    for tree_index, parents in enumerate(trees):
        variations = attestation_variations(
            rng, len(parents), TINY["nr_variations"])
        for var_index, votes in enumerate(variations):
            name = f"block_tree_{tree_index}_var_{var_index}"
            yield name, parents, votes, 0, 0
            for mutation in range(TINY["nr_mutations"]):
                # fold the case identity into the seed so the operator
                # draws differ across the suite
                seed = (TINY["seed"] + 1000 * tree_index
                        + 100 * var_index + mutation)
                yield (f"{name}_mut_{mutation}", parents, votes,
                       mutation + 1, seed)


def get_test_cases():
    cases = []
    for name, parents, votes, n_mutations, seed in iter_tiny_cases():
        tfn = with_phases(["phase0"])(spec_state_test(
            instantiate_block_tree_test(
                parents, votes, n_mutations=n_mutations,
                mutation_seed=seed)))
        cases.append(TestCase(
            fork_name="phase0",
            preset_name="minimal",
            runner_name="fork_choice_compliance",
            handler_name="block_tree",
            suite_name="compliance",
            case_name=name,
            case_fn=generate_case_fn(tfn, phase="phase0",
                                     preset="minimal", bls_active=False),
        ))
    return cases
