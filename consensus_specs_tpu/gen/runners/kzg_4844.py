"""KZG (EIP-4844) test-vector factory — the deneb blob-commitment
surface with valid / incorrect-proof / malformed-input matrices (the
reference's `tests/generators/runners/kzg_4844.py:1-651`; same handler
names, 'general' preset identity, `kzg-mainnet` suite).

Vectors are produced by this repo's own KZG library
(`models/deneb/polynomial_commitments.py`) over the embedded mainnet
trusted setup.
"""

from __future__ import annotations

from ...testlib.kzg_fixtures import (
    bls_add_one,
    cached_blob_to_kzg_commitment,
    cached_compute_blob_kzg_proof,
    cached_compute_kzg_proof,
    encode_hex,
    encode_hex_list,
    invalid_blobs,
    invalid_field_elements,
    invalid_g1_points,
    kzg_spec,
    valid_blobs,
    valid_field_elements,
)
from ..typing import TestCase

G1_POINT_AT_INFINITY = b"\xc0" + b"\x00" * 47


def _data_part(input_obj, output_obj):
    return [("data", "data", {"input": input_obj, "output": output_obj})]


def _try(fn, *args):
    try:
        return fn(*args)
    except Exception:
        return None


def case_blob_to_kzg_commitment():
    def runner(blob):
        def _run():
            out = _try(cached_blob_to_kzg_commitment, bytes(blob))
            return _data_part(
                {"blob": encode_hex(blob)},
                encode_hex(out) if out is not None else None)
        return _run

    for i, blob in enumerate(valid_blobs()):
        yield f"blob_to_kzg_commitment_case_valid_blob_{i}", runner(blob)
    for i, blob in enumerate(invalid_blobs()):
        yield f"blob_to_kzg_commitment_case_invalid_blob_{i}", runner(blob)


def case_compute_kzg_proof():
    def runner(blob, z):
        def _run():
            out = _try(cached_compute_kzg_proof, bytes(blob), z)
            return _data_part(
                {"blob": encode_hex(blob), "z": encode_hex(z)},
                ((encode_hex(out[0]), encode_hex(out[1]))
                 if out is not None else None))
        return _run

    for i, blob in enumerate(valid_blobs()):
        for j, z in enumerate(valid_field_elements()):
            yield f"compute_kzg_proof_case_valid_blob_{i}_{j}", \
                runner(blob, z)
    for i, blob in enumerate(invalid_blobs()):
        yield f"compute_kzg_proof_case_invalid_blob_{i}", \
            runner(blob, valid_field_elements()[0])
    for i, z in enumerate(invalid_field_elements()):
        yield f"compute_kzg_proof_case_invalid_z_{i}", \
            runner(valid_blobs()[4], z)


def case_verify_kzg_proof():
    spec = kzg_spec()

    def runner(get_inputs):
        def _run():
            commitment, z, y, proof = get_inputs()
            ok = _try(spec.verify_kzg_proof, commitment, z, y, proof)
            return _data_part(
                {"commitment": encode_hex(commitment), "z": encode_hex(z),
                 "y": encode_hex(y), "proof": encode_hex(proof)},
                ok)
        return _run

    def proof_inputs(blob, z, mutate=None, proof_override=None):
        def _get():
            proof, y = cached_compute_kzg_proof(bytes(blob), z)
            commitment = cached_blob_to_kzg_commitment(bytes(blob))
            if proof_override is not None:
                proof = proof_override
            elif mutate is not None:
                proof = mutate(proof)
            return commitment, z, y, proof
        return _get

    blobs, zs = valid_blobs(), valid_field_elements()
    for i, blob in enumerate(blobs):
        for j, z in enumerate(zs):
            yield (f"verify_kzg_proof_case_correct_proof_{i}_{j}",
                   runner(proof_inputs(blob, z)))
    for i, blob in enumerate(blobs):
        for j, z in enumerate(zs):
            yield (f"verify_kzg_proof_case_incorrect_proof_{i}_{j}",
                   runner(proof_inputs(blob, z, mutate=bls_add_one)))
    # proof == infinity: wrong for a random blob, right for constant polys
    for j, z in enumerate(zs):
        yield (f"verify_kzg_proof_case_incorrect_proof_point_at_infinity_{j}",
               runner(proof_inputs(blobs[2], z,
                                   proof_override=G1_POINT_AT_INFINITY)))
    for j, z in enumerate(zs):
        yield (("verify_kzg_proof_case_correct_proof_point_at_infinity_"
                f"for_zero_poly_{j}"),
               runner(proof_inputs(blobs[0], z,
                                   proof_override=G1_POINT_AT_INFINITY)))
    for j, z in enumerate(zs):
        yield (("verify_kzg_proof_case_correct_proof_point_at_infinity_"
                f"for_twos_poly_{j}"),
               runner(proof_inputs(blobs[1], z,
                                   proof_override=G1_POINT_AT_INFINITY)))

    def bad_input(commitment=None, z=None, y=None, proof=None):
        def _get():
            blob, valid_z = blobs[2], zs[1]
            real_proof, real_y = cached_compute_kzg_proof(bytes(blob),
                                                          valid_z)
            real_commitment = cached_blob_to_kzg_commitment(bytes(blob))
            return (commitment if commitment is not None
                    else real_commitment,
                    z if z is not None else valid_z,
                    y if y is not None else real_y,
                    proof if proof is not None else real_proof)
        return _get

    for i, point in enumerate(invalid_g1_points()):
        yield (f"verify_kzg_proof_case_invalid_commitment_{i}",
               runner(bad_input(commitment=point)))
    for i, z in enumerate(invalid_field_elements()):
        yield f"verify_kzg_proof_case_invalid_z_{i}", runner(bad_input(z=z))
    for i, y in enumerate(invalid_field_elements()):
        yield f"verify_kzg_proof_case_invalid_y_{i}", runner(bad_input(y=y))
    for i, point in enumerate(invalid_g1_points()):
        yield (f"verify_kzg_proof_case_invalid_proof_{i}",
               runner(bad_input(proof=point)))


def case_compute_blob_kzg_proof():
    def runner(get_inputs):
        def _run():
            blob, commitment = get_inputs()
            out = _try(cached_compute_blob_kzg_proof, bytes(blob),
                       bytes(commitment))
            return _data_part(
                {"blob": encode_hex(blob),
                 "commitment": encode_hex(commitment)},
                encode_hex(out) if out is not None else None)
        return _run

    for i, blob in enumerate(valid_blobs()):
        yield (f"compute_blob_kzg_proof_case_valid_blob_{i}",
               runner(lambda blob=blob: (
                   blob, cached_blob_to_kzg_commitment(bytes(blob)))))
    for i, blob in enumerate(invalid_blobs()):
        yield (f"compute_blob_kzg_proof_case_invalid_blob_{i}",
               runner(lambda blob=blob: (
                   blob, cached_blob_to_kzg_commitment(
                       bytes(valid_blobs()[1])))))
    for i, commitment in enumerate(invalid_g1_points()):
        yield (f"compute_blob_kzg_proof_case_invalid_commitment_{i}",
               runner(lambda commitment=commitment: (
                   valid_blobs()[1], commitment)))


def case_verify_blob_kzg_proof():
    spec = kzg_spec()

    def runner(get_inputs):
        def _run():
            blob, commitment, proof = get_inputs()
            ok = _try(spec.verify_blob_kzg_proof, blob, commitment, proof)
            return _data_part(
                {"blob": encode_hex(blob),
                 "commitment": encode_hex(commitment),
                 "proof": encode_hex(proof)},
                ok)
        return _run

    def valid_inputs(blob, mutate=None):
        def _get():
            commitment = cached_blob_to_kzg_commitment(bytes(blob))
            proof = cached_compute_blob_kzg_proof(bytes(blob),
                                                  bytes(commitment))
            if mutate is not None:
                proof = mutate(proof)
            return blob, commitment, proof
        return _get

    for i, blob in enumerate(valid_blobs()):
        yield (f"verify_blob_kzg_proof_case_correct_proof_{i}",
               runner(valid_inputs(blob)))
    for i, blob in enumerate(valid_blobs()):
        yield (f"verify_blob_kzg_proof_case_incorrect_proof_{i}",
               runner(valid_inputs(blob, mutate=bls_add_one)))
    yield ("verify_blob_kzg_proof_case_proof_point_at_infinity",
           runner(valid_inputs(valid_blobs()[2],
                               mutate=lambda _: G1_POINT_AT_INFINITY)))

    def bad_input(blob=None, commitment=None, proof=None):
        def _get():
            good = valid_blobs()[2]
            real_commitment = cached_blob_to_kzg_commitment(bytes(good))
            real_proof = cached_compute_blob_kzg_proof(
                bytes(good), bytes(real_commitment))
            return (blob if blob is not None else good,
                    commitment if commitment is not None
                    else real_commitment,
                    proof if proof is not None else real_proof)
        return _get

    for i, blob in enumerate(invalid_blobs()):
        yield (f"verify_blob_kzg_proof_case_invalid_blob_{i}",
               runner(bad_input(blob=blob)))
    for i, point in enumerate(invalid_g1_points()):
        yield (f"verify_blob_kzg_proof_case_invalid_commitment_{i}",
               runner(bad_input(commitment=point)))
    for i, point in enumerate(invalid_g1_points()):
        yield (f"verify_blob_kzg_proof_case_invalid_proof_{i}",
               runner(bad_input(proof=point)))


def case_verify_blob_kzg_proof_batch():
    spec = kzg_spec()

    def runner(get_inputs):
        def _run():
            blobs, commitments, proofs = get_inputs()
            ok = _try(spec.verify_blob_kzg_proof_batch, blobs, commitments,
                      proofs)
            return _data_part(
                {"blobs": encode_hex_list(blobs),
                 "commitments": encode_hex_list(commitments),
                 "proofs": encode_hex_list(proofs)},
                ok)
        return _run

    def batch(n, mutate=None):
        def _get():
            blobs = valid_blobs()[:n]
            commitments = [cached_blob_to_kzg_commitment(bytes(b))
                           for b in blobs]
            proofs = [cached_compute_blob_kzg_proof(bytes(b), bytes(c))
                      for b, c in zip(blobs, commitments)]
            if mutate is not None:
                blobs, commitments, proofs = mutate(blobs, commitments,
                                                    proofs)
            return blobs, commitments, proofs
        return _get

    for n in range(len(valid_blobs()) + 1):
        yield (f"verify_blob_kzg_proof_batch_case_correct_{n}",
               runner(batch(n)))

    def swap_proofs(blobs, commitments, proofs):
        return blobs, commitments, [proofs[1], proofs[0]] + proofs[2:]

    yield ("verify_blob_kzg_proof_batch_case_incorrect_proof_add_one",
           runner(batch(4, mutate=lambda b, c, p:
                        (b, c, [bls_add_one(p[0])] + p[1:]))))
    yield ("verify_blob_kzg_proof_batch_case_proofs_swapped",
           runner(batch(4, mutate=swap_proofs)))
    yield ("verify_blob_kzg_proof_batch_case_proof_point_at_infinity",
           runner(batch(3, mutate=lambda b, c, p:
                        (b, c, [G1_POINT_AT_INFINITY] + p[1:]))))
    # malformed members
    for i, blob in enumerate(invalid_blobs()):
        yield (f"verify_blob_kzg_proof_batch_case_invalid_blob_{i}",
               runner(batch(3, mutate=lambda b, c, p, blob=blob:
                            ([b[0], blob, b[2]], c, p))))
    for i, point in enumerate(invalid_g1_points()):
        yield (f"verify_blob_kzg_proof_batch_case_invalid_commitment_{i}",
               runner(batch(3, mutate=lambda b, c, p, pt=point:
                            (b, [c[0], pt, c[2]], p))))
    for i, point in enumerate(invalid_g1_points()):
        yield (f"verify_blob_kzg_proof_batch_case_invalid_proof_{i}",
               runner(batch(3, mutate=lambda b, c, p, pt=point:
                            (b, c, [p[0], pt, p[2]]))))
    # length mismatches
    yield ("verify_blob_kzg_proof_batch_case_blob_length_different",
           runner(batch(3, mutate=lambda b, c, p: (b[:-1], c, p))))
    yield ("verify_blob_kzg_proof_batch_case_commitment_length_different",
           runner(batch(3, mutate=lambda b, c, p: (b, c[:-1], p))))
    yield ("verify_blob_kzg_proof_batch_case_proof_length_different",
           runner(batch(3, mutate=lambda b, c, p: (b, c, p[:-1]))))


CASE_FNS = [
    ("blob_to_kzg_commitment", case_blob_to_kzg_commitment),
    ("compute_kzg_proof", case_compute_kzg_proof),
    ("verify_kzg_proof", case_verify_kzg_proof),
    ("compute_blob_kzg_proof", case_compute_blob_kzg_proof),
    ("verify_blob_kzg_proof", case_verify_blob_kzg_proof),
    ("verify_blob_kzg_proof_batch", case_verify_blob_kzg_proof_batch),
]


def get_test_cases():
    cases = []
    for handler_name, case_fn in CASE_FNS:
        for case_name, runner in case_fn():
            cases.append(TestCase(
                fork_name="deneb",
                preset_name="general",
                runner_name="kzg",
                handler_name=handler_name,
                suite_name="kzg-mainnet",
                case_name=case_name,
                case_fn=runner,
            ))
    return cases
