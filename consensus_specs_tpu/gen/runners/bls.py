"""BLS test vectors: the spec-level aggregate helpers with valid / edge /
invalid inputs (the reference's `tests/generators/runners/bls.py` — same
handler names and 'general' preset identity, vectors produced by the
in-tree BLS implementation)."""

from ...models.builder import build_spec
from ...ops import bls
from ..typing import TestCase

MESSAGES = [b"\x00" * 32, b"\x56" * 32, b"\xab" * 32]
SAMPLE_MESSAGE = b"\x12" * 32

PRIVKEYS = [
    int("263dbd792f5b1be47ed85f8938c0f29586af0d3ac7b977f21c278fe1462040e3",
        16),
    int("47b8192d77bf871b62e87859d653922725724a5c031afeabc60bcef5ff665138",
        16),
    int("328388aff0d4a5b7dc9205abd374e7e98f3cd9f3418edb4eafda5fb16473d216",
        16),
]

ZERO_PUBKEY = b"\x00" * 48
G1_POINT_AT_INFINITY = b"\xc0" + b"\x00" * 47
ZERO_SIGNATURE = b"\x00" * 96
G2_POINT_AT_INFINITY = b"\xc0" + b"\x00" * 95


def _hex(b: bytes) -> str:
    return "0x" + bytes(b).hex()


def _try(fn, *args):
    try:
        return fn(*args)
    except Exception:
        return None


def case_eth_aggregate_pubkeys():
    spec = build_spec("altair", "minimal")

    def runner(get_inputs):
        def _run():
            pubkeys = get_inputs()
            out = _try(spec.eth_aggregate_pubkeys, pubkeys)
            return [("data", "data", {
                "input": [_hex(pk) for pk in pubkeys],
                "output": _hex(out) if out is not None else None,
            })]
        return _run

    for i, privkey in enumerate(PRIVKEYS):
        yield (f"eth_aggregate_pubkeys_valid_{i}",
               runner(lambda privkey=privkey: [bls.SkToPk(privkey)]))
    yield ("eth_aggregate_pubkeys_valid_pubkeys",
           runner(lambda: [bls.SkToPk(sk) for sk in PRIVKEYS]))
    yield "eth_aggregate_pubkeys_empty_list", runner(lambda: [])
    yield ("eth_aggregate_pubkeys_zero_pubkey",
           runner(lambda: [ZERO_PUBKEY]))
    yield ("eth_aggregate_pubkeys_infinity_pubkey",
           runner(lambda: [G1_POINT_AT_INFINITY]))
    yield ("eth_aggregate_pubkeys_x40_pubkey",
           runner(lambda: [b"\x40" + b"\x00" * 47]))


def case_eth_fast_aggregate_verify():
    spec = build_spec("altair", "minimal")

    def runner(get_inputs):
        def _run():
            pubkeys, message, signature = get_inputs()
            ok = bool(_try(spec.eth_fast_aggregate_verify,
                           pubkeys, message, signature))
            return [("data", "data", {
                "input": {
                    "pubkeys": [_hex(pk) for pk in pubkeys],
                    "message": _hex(message),
                    "signature": _hex(signature),
                },
                "output": ok,
            })]
        return _run

    for i, message in enumerate(MESSAGES):
        sks = PRIVKEYS[:i + 1]
        pubkeys = [bls.SkToPk(sk) for sk in sks]
        sig = bls.Aggregate([bls.Sign(sk, message) for sk in sks])
        yield (f"eth_fast_aggregate_verify_valid_{i}",
               runner(lambda p=pubkeys, m=message, s=sig: (p, m, s)))
        # tampered signature
        bad = sig[:-4] + b"\xff\xff\xff\xff"
        yield (f"eth_fast_aggregate_verify_tampered_signature_{i}",
               runner(lambda p=pubkeys, m=message, s=bad: (p, m, s)))
        # extra pubkey not in the aggregate
        extra = pubkeys + [bls.SkToPk(PRIVKEYS[-1])]
        yield (f"eth_fast_aggregate_verify_extra_pubkey_{i}",
               runner(lambda p=extra, m=message, s=sig: (p, m, s)))
    # the eth_ variant accepts the empty aggregate
    yield ("eth_fast_aggregate_verify_na_pubkeys_and_infinity_signature",
           runner(lambda: ([], MESSAGES[-1], G2_POINT_AT_INFINITY)))
    yield ("eth_fast_aggregate_verify_na_pubkeys_and_zero_signature",
           runner(lambda: ([], MESSAGES[-1], ZERO_SIGNATURE)))
    yield ("eth_fast_aggregate_verify_infinity_pubkey",
           runner(lambda: (
               [bls.SkToPk(sk) for sk in PRIVKEYS] + [G1_POINT_AT_INFINITY],
               SAMPLE_MESSAGE,
               bls.Aggregate([bls.Sign(sk, SAMPLE_MESSAGE)
                              for sk in PRIVKEYS]))))


def case_sign():
    def runner(privkey, message):
        def _run():
            sig = _try(bls.Sign, privkey, message)
            return [("data", "data", {
                "input": {"privkey": "0x" + privkey.to_bytes(32, "big").hex(),
                          "message": _hex(message)},
                "output": _hex(sig) if sig is not None else None,
            })]
        return _run

    for i, privkey in enumerate(PRIVKEYS):
        for j, message in enumerate(MESSAGES):
            yield f"sign_case_{i}_{j}", runner(privkey, message)
    yield "sign_case_zero_privkey", runner(0, SAMPLE_MESSAGE)


def case_verify():
    def runner(get_inputs):
        def _run():
            pubkey, message, signature = get_inputs()
            ok = bool(_try(bls.Verify, pubkey, message, signature))
            return [("data", "data", {
                "input": {"pubkey": _hex(pubkey), "message": _hex(message),
                          "signature": _hex(signature)},
                "output": ok,
            })]
        return _run

    for i, privkey in enumerate(PRIVKEYS):
        for j, message in enumerate(MESSAGES):
            pk = bls.SkToPk(privkey)
            sig = bls.Sign(privkey, message)
            yield (f"verify_valid_case_{i}_{j}",
                   runner(lambda p=pk, m=message, s=sig: (p, m, s)))
            wrong = bls.Sign(PRIVKEYS[(i + 1) % len(PRIVKEYS)], message)
            yield (f"verify_wrong_pubkey_case_{i}_{j}",
                   runner(lambda p=pk, m=message, s=wrong: (p, m, s)))
            bad = sig[:-4] + b"\xff\xff\xff\xff"
            yield (f"verify_tampered_signature_case_{i}_{j}",
                   runner(lambda p=pk, m=message, s=bad: (p, m, s)))
    yield ("verify_infinity_pubkey_and_infinity_signature",
           runner(lambda: (G1_POINT_AT_INFINITY, SAMPLE_MESSAGE,
                           G2_POINT_AT_INFINITY)))


def case_aggregate():
    def runner(get_sigs):
        def _run():
            sigs = get_sigs()
            out = _try(bls.Aggregate, sigs)
            return [("data", "data", {
                "input": [_hex(s) for s in sigs],
                "output": _hex(out) if out is not None else None,
            })]
        return _run

    for i, message in enumerate(MESSAGES):
        sigs = [bls.Sign(sk, message) for sk in PRIVKEYS]
        yield f"aggregate_{i}", runner(lambda s=sigs: s)
    yield "aggregate_na_signatures", runner(lambda: [])
    yield ("aggregate_infinity_signature",
           runner(lambda: [G2_POINT_AT_INFINITY]))


def case_fast_aggregate_verify():
    def runner(get_inputs):
        def _run():
            pubkeys, message, signature = get_inputs()
            ok = bool(_try(bls.FastAggregateVerify,
                           pubkeys, message, signature))
            return [("data", "data", {
                "input": {
                    "pubkeys": [_hex(pk) for pk in pubkeys],
                    "message": _hex(message),
                    "signature": _hex(signature),
                },
                "output": ok,
            })]
        return _run

    for i, message in enumerate(MESSAGES):
        sks = PRIVKEYS[:i + 1]
        pubkeys = [bls.SkToPk(sk) for sk in sks]
        sig = bls.Aggregate([bls.Sign(sk, message) for sk in sks])
        yield (f"fast_aggregate_verify_valid_{i}",
               runner(lambda p=pubkeys, m=message, s=sig: (p, m, s)))
    # unlike the eth_ variant, the empty aggregate is INVALID here
    yield ("fast_aggregate_verify_na_pubkeys_and_infinity_signature",
           runner(lambda: ([], MESSAGES[-1], G2_POINT_AT_INFINITY)))


def get_test_cases():
    cases = []
    handlers = {
        "sign": case_sign,
        "verify": case_verify,
        "aggregate": case_aggregate,
        "fast_aggregate_verify": case_fast_aggregate_verify,
        "eth_aggregate_pubkeys": case_eth_aggregate_pubkeys,
        "eth_fast_aggregate_verify": case_eth_fast_aggregate_verify,
    }
    for method, fn in handlers.items():
        for case_name, case_fn in fn():
            cases.append(TestCase(
                fork_name="altair",
                preset_name="general",
                runner_name="bls",
                handler_name=method,
                suite_name="bls",
                case_name=case_name,
                case_fn=case_fn,
            ))
    return cases
