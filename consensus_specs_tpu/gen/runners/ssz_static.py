"""ssz_static vectors: random objects of every container of every
fork × preset, 5 modes + chaos (the reference's
`tests/generators/runners/ssz_static.py`)."""

import hashlib
from random import Random

from ...debug import random_value
from ...debug.encode import encode
from ...models.builder import build_spec
from ...utils.ssz.ssz_impl import hash_tree_root, serialize
from ...utils.ssz.types import Container
from ..from_tests import TESTGEN_FORKS
from ..typing import TestCase

MAX_BYTES_LENGTH = 1000
MAX_LIST_LENGTH = 10


def create_test_case(seed, typ, mode, chaos):
    rng = Random(seed)
    value = random_value.get_random_ssz_object(
        rng, typ, MAX_BYTES_LENGTH, MAX_LIST_LENGTH, mode, chaos)
    yield "value", "data", encode(value)
    yield "serialized", "ssz", serialize(value)
    yield "roots", "data", {"root": "0x" + hash_tree_root(value).hex()}


def get_spec_ssz_types(spec):
    return sorted(
        (name, v) for name, v in spec._namespace.items()
        if isinstance(v, type) and issubclass(v, Container)
        and v is not Container and v.fields())


def deterministic_seed(**kwargs) -> int:
    """hash() is not deterministic between runs; sha256 the kwargs."""
    m = hashlib.sha256()
    for k, v in sorted(kwargs.items()):
        m.update(f"{k}={v}".encode())
    return int.from_bytes(m.digest()[:8], "little")


def ssz_static_cases(fork, preset, name, ssz_type, mode, chaos, count):
    random_mode_name = mode.to_name()
    for i in range(count):
        seed = deterministic_seed(
            fork_name=fork, preset_name=preset, name=name,
            ssz_type_name=ssz_type.__name__,
            random_mode_name=random_mode_name, chaos=chaos, count=count, i=i)
        yield TestCase(
            fork_name=fork,
            preset_name=preset,
            runner_name="ssz_static",
            handler_name=name,
            suite_name=f"ssz_{random_mode_name}{'_chaos' if chaos else ''}",
            case_name=f"case_{i}",
            case_fn=(lambda seed=seed, t=ssz_type, m=mode, c=chaos:
                     list(create_test_case(seed, t, m, c))),
        )


def get_test_cases():
    settings = []
    for mode in random_value.RandomizationMode:
        settings.append(("minimal", mode, False, 30))
    settings.append(
        ("minimal", random_value.RandomizationMode.mode_random, True, 30))
    settings.append(
        ("mainnet", random_value.RandomizationMode.mode_random, False, 5))

    cases = []
    for fork in TESTGEN_FORKS:
        for preset, mode, chaos, cases_if_random in settings:
            count = cases_if_random if chaos or mode.is_changing() else 1
            spec = build_spec(fork, preset)
            for name, ssz_type in get_spec_ssz_types(spec):
                cases.extend(ssz_static_cases(
                    fork, preset, name, ssz_type, mode, chaos, count))
    return cases
