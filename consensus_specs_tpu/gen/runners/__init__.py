"""Runner registry: every module here exposes `get_test_cases() ->
list[TestCase]` (the reference's `tests/generators/runners/`)."""

from __future__ import annotations

from importlib import import_module

RUNNER_MODULES = [
    "bls",
    "compliance",
    "epoch_processing",
    "finality",
    "fork_choice",
    "forks",
    "genesis",
    "kzg_4844",
    "kzg_7594",
    "light_client",
    "merkle_proof",
    "networking",
    "operations",
    "random",
    "rewards",
    "sanity",
    "shuffling",
    "ssz_generic",
    "ssz_static",
    "sync",
    "transition",
]


def all_test_cases():
    cases = []
    for name in RUNNER_MODULES:
        mod = import_module(f"{__name__}.{name}")
        cases.extend(mod.get_test_cases())
    return cases
