from ..from_tests import get_test_cases_for


def get_test_cases():
    return get_test_cases_for("finality")
