from ..from_tests import get_test_cases_for


def handler_name_fn(mod):
    handler_name = mod.split(".")[-1]
    if handler_name == "test_process_sync_aggregate_random":
        return "sync_aggregate"
    return handler_name.replace("test_process_", "")


def get_test_cases():
    return get_test_cases_for("operations", pkg="block_processing",
                              handler_name_fn=handler_name_fn)
