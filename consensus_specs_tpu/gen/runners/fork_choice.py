"""fork_choice runner: reflects tests/*/fork_choice/ — including the
`device_store` handler, whose head checks are the DEVICE proto-array
store's decisions (`consensus_specs_tpu/forkchoice/`), each asserted
bit-identical to the spec oracle's `get_head` before emission."""

from ..from_tests import get_test_cases_for


def get_test_cases():
    return get_test_cases_for("fork_choice")
