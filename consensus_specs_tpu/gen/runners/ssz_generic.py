"""ssz_generic vectors: spec-independent SSZ wire-format cases — valid
encodings with value/root, and malformed encodings clients must reject
(the reference's `tests/generators/runners/ssz_generic*`; same handler and
suite naming, cases authored for this engine)."""

from random import Random

from ...debug.encode import encode
from ...debug.random_value import RandomizationMode, get_random_ssz_object
from ...utils.ssz.ssz_impl import hash_tree_root, serialize
from ...utils.ssz.types import (
    Bitlist,
    Bitvector,
    Container,
    List,
    Vector,
    boolean,
    uint8,
    uint16,
    uint32,
    uint64,
    uint128,
    uint256,
)
from ..typing import TestCase

UINTS = (uint8, uint16, uint32, uint64, uint128, uint256)


def valid_test_case(value_fn):
    def case_fn():
        value = value_fn()
        return [
            ("value", "data", encode(value)),
            ("serialized", "ssz", serialize(value)),
            ("root", "meta", "0x" + hash_tree_root(value).hex()),
        ]
    return case_fn


def invalid_test_case(bytez_fn):
    def case_fn():
        return [("serialized", "ssz", bytez_fn())]
    return case_fn


def _random(rng, typ, mode):
    return get_random_ssz_object(rng, typ, max_bytes_length=1024,
                                 max_list_length=1024, mode=mode, chaos=False)


# -- boolean ---------------------------------------------------------------

def boolean_valid():
    yield "true", valid_test_case(lambda: boolean(True))
    yield "false", valid_test_case(lambda: boolean(False))


def boolean_invalid():
    yield "byte_2", invalid_test_case(lambda: b"\x02")
    yield "byte_rev_nibble", invalid_test_case(lambda: b"\x10")
    yield "byte_0x80", invalid_test_case(lambda: b"\x80")
    yield "byte_full", invalid_test_case(lambda: b"\xff")


# -- uints -----------------------------------------------------------------

def uints_valid():
    rng = Random(1234)
    for t in UINTS:
        n = t.type_byte_length()
        yield f"uint_{n * 8}_zero", valid_test_case(lambda t=t: t(0))
        yield (f"uint_{n * 8}_max",
               valid_test_case(lambda t=t, n=n: t(256 ** n - 1)))
        for i in range(3):
            yield (f"uint_{n * 8}_random_{i}", valid_test_case(
                lambda t=t, v=rng.randint(0, 256 ** n - 1): t(v)))


def uints_invalid():
    for t in UINTS:
        n = t.type_byte_length()
        yield (f"uint_{n * 8}_one_too_high_byte_count",
               invalid_test_case(lambda n=n: b"\x00" * (n + 1)))
        yield (f"uint_{n * 8}_one_byte_shorter",
               invalid_test_case(lambda n=n: b"\xff" * (n - 1)))


# -- bitvector -------------------------------------------------------------

def bitvector_valid():
    rng = Random(1234)
    for size in (1, 2, 3, 4, 5, 8, 16, 31, 512, 513):
        for mode in (RandomizationMode.mode_random,
                     RandomizationMode.mode_zero,
                     RandomizationMode.mode_max):
            yield (f"bitvec_{size}_{mode.to_name()}", valid_test_case(
                lambda rng=rng, size=size, mode=mode:
                _random(rng, Bitvector[size], mode)))


def bitvector_invalid():
    yield "bitvec_0", invalid_test_case(lambda: b"")
    for size, ser in (
            (8, b""), (8, b"\x00\x00"),
            (9, b"\xff"),  # one byte short
            (5, b"\xff"),  # pad bits set beyond length 5
            (3, b"\x08"),  # bit 3 set in a 3-bit vector
    ):
        yield (f"bitvec_{size}_bad_{ser.hex() or 'empty'}",
               invalid_test_case(lambda ser=ser: ser))


# -- bitlist ---------------------------------------------------------------

def bitlist_valid():
    rng = Random(1234)
    for limit in (1, 2, 3, 4, 5, 8, 16, 31, 512, 513):
        for mode in (RandomizationMode.mode_random,
                     RandomizationMode.mode_zero,
                     RandomizationMode.mode_max_count):
            yield (f"bitlist_{limit}_{mode.to_name()}", valid_test_case(
                lambda rng=rng, limit=limit, mode=mode:
                _random(rng, Bitlist[limit], mode)))


def bitlist_invalid():
    yield "bitlist_no_delimiter_empty", invalid_test_case(lambda: b"")
    yield ("bitlist_no_delimiter_zero_byte",
           invalid_test_case(lambda: b"\x00"))
    yield ("bitlist_no_delimiter_zeroes",
           invalid_test_case(lambda: b"\x00\x00"))
    # 9 bits in a limit-8 list (delimiter at bit 9)
    yield ("bitlist_8_but_9_bits",
           invalid_test_case(lambda: b"\xff\x03"))
    # delimiter-only trailing zero byte
    yield ("bitlist_trailing_zero_byte",
           invalid_test_case(lambda: b"\x01\x00"))


# -- basic_vector ----------------------------------------------------------

def basic_vector_valid():
    rng = Random(1234)
    for t in (boolean, uint8, uint16, uint32, uint64, uint128, uint256):
        for length in (1, 2, 3, 4, 5, 8, 16, 31, 512, 513):
            for mode in (RandomizationMode.mode_random,
                         RandomizationMode.mode_zero,
                         RandomizationMode.mode_max):
                name = (f"vec_{t.__name__}_{length}_{mode.to_name()}")
                yield (name, valid_test_case(
                    lambda rng=rng, t=t, length=length, mode=mode:
                    _random(rng, Vector[t, length], mode)))


def basic_vector_invalid():
    yield "vec_bool_0", invalid_test_case(lambda: b"")
    yield ("vec_uint16_3_one_byte_short",
           invalid_test_case(lambda: b"\x11\x22\x33\x44\x55"))
    yield ("vec_uint16_3_one_byte_long",
           invalid_test_case(lambda: b"\x11" * 7))
    yield ("vec_uint64_2_one_byte_short",
           invalid_test_case(lambda: b"\xee" * 15))


# -- containers ------------------------------------------------------------

class SingleFieldTestStruct(Container):
    A: uint8


class SmallTestStruct(Container):
    A: uint16
    B: uint16


class FixedTestStruct(Container):
    A: uint8
    B: uint64
    C: uint32


class VarTestStruct(Container):
    A: uint16
    B: List[uint16, 1024]
    C: uint8


class ComplexTestStruct(Container):
    A: uint16
    B: List[uint16, 128]
    C: uint8
    D: List[uint8, 256]
    E: VarTestStruct
    F: Vector[FixedTestStruct, 4]
    G: Vector[VarTestStruct, 2]


class BitsStruct(Container):
    A: Bitlist[5]
    B: Bitvector[2]
    C: Bitvector[1]
    D: Bitlist[6]
    E: Bitvector[8]


CONTAINER_TYPES = [SingleFieldTestStruct, SmallTestStruct, FixedTestStruct,
                   VarTestStruct, ComplexTestStruct, BitsStruct]


def container_valid():
    rng = Random(1234)
    for typ in CONTAINER_TYPES:
        for mode in (RandomizationMode.mode_random,
                     RandomizationMode.mode_zero,
                     RandomizationMode.mode_max,
                     RandomizationMode.mode_nil_count,
                     RandomizationMode.mode_max_count):
            yield (f"{typ.__name__}_{mode.to_name()}", valid_test_case(
                lambda rng=rng, typ=typ, mode=mode:
                _random(rng, typ, mode)))


def container_invalid():
    yield ("SingleFieldTestStruct_empty", invalid_test_case(lambda: b""))
    yield ("SingleFieldTestStruct_extra_byte",
           invalid_test_case(lambda: b"\xab\xcd"))
    yield ("SmallTestStruct_one_byte_short",
           invalid_test_case(lambda: b"\x00" * 3))
    # VarTestStruct: offset points before the fixed part ends
    yield ("VarTestStruct_offset_early",
           invalid_test_case(
               lambda: b"\xaa\xaa" + (2).to_bytes(4, "little") + b"\xff"))
    # VarTestStruct: offset beyond the buffer
    yield ("VarTestStruct_offset_beyond",
           invalid_test_case(
               lambda: b"\xaa\xaa" + (100).to_bytes(4, "little") + b"\xff"))
    # VarTestStruct: odd length tail for a uint16 list
    yield ("VarTestStruct_odd_list_tail",
           invalid_test_case(
               lambda: b"\xaa\xaa" + (7).to_bytes(4, "little")
               + b"\xff" + b"\x01\x02\x03"))


def get_test_cases():
    groups = [
        ("basic_vector", "valid", basic_vector_valid),
        ("basic_vector", "invalid", basic_vector_invalid),
        ("bitlist", "valid", bitlist_valid),
        ("bitlist", "invalid", bitlist_invalid),
        ("bitvector", "valid", bitvector_valid),
        ("bitvector", "invalid", bitvector_invalid),
        ("boolean", "valid", boolean_valid),
        ("boolean", "invalid", boolean_invalid),
        ("uints", "valid", uints_valid),
        ("uints", "invalid", uints_invalid),
        ("containers", "valid", container_valid),
        ("containers", "invalid", container_invalid),
    ]
    cases = []
    for handler_name, suite_name, gen in groups:
        for case_name, case_fn in gen():
            cases.append(TestCase(
                fork_name="phase0",
                preset_name="general",
                runner_name="ssz_generic",
                handler_name=handler_name,
                suite_name=suite_name,
                case_name=case_name,
                case_fn=case_fn,
            ))
    return cases
