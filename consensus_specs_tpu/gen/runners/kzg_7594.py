"""KZG cell/DAS (EIP-7594) test-vector factory — the fulu sampling
surface: compute_cells, cell proofs, batched cell verification and
recovery (the reference's `tests/generators/runners/kzg_7594.py:1-612`;
same handler names, 'general' preset, `kzg-mainnet` suite).

Heavy per-blob proof computation (128 multi-proofs, one MSM each) is
cached per process; generation cost matches the reference's equally
naive normative algorithms.
"""

from __future__ import annotations

from ...testlib.kzg_fixtures import (
    bls_add_one,
    cached_blob_to_kzg_commitment,
    cached_compute_cells_and_kzg_proofs,
    encode_hex,
    encode_hex_list,
    invalid_blobs,
    invalid_cells,
    invalid_g1_points,
    kzg_7594_spec,
    valid_blobs,
    valid_cells,
)
from ..typing import TestCase


def _data_part(input_obj, output_obj):
    return [("data", "data", {"input": input_obj, "output": output_obj})]


def _try(fn, *args):
    try:
        return fn(*args)
    except Exception:
        return None


def case_compute_cells():
    spec = kzg_7594_spec()

    def runner(blob):
        def _run():
            cells = _try(spec.compute_cells, blob)
            return _data_part(
                {"blob": encode_hex(blob)},
                encode_hex_list(cells) if cells is not None else None)
        return _run

    for i, blob in enumerate(valid_blobs()):
        yield f"compute_cells_case_valid_{i}", runner(blob)
    for i, blob in enumerate(invalid_blobs()):
        yield f"compute_cells_case_invalid_blob_{i}", runner(blob)


def case_compute_cells_and_kzg_proofs():
    def runner(blob):
        def _run():
            out = _try(cached_compute_cells_and_kzg_proofs, bytes(blob))
            return _data_part(
                {"blob": encode_hex(blob)},
                ((encode_hex_list(out[0]), encode_hex_list(out[1]))
                 if out is not None else None))
        return _run

    for i, blob in enumerate(valid_blobs()):
        yield f"compute_cells_and_kzg_proofs_case_valid_{i}", runner(blob)
    for i, blob in enumerate(invalid_blobs()):
        yield (f"compute_cells_and_kzg_proofs_case_invalid_blob_{i}",
               runner(blob))


def _proven_blob(index: int):
    """(blob_bytes, commitment, cells, proofs) for a valid blob; cached
    per process by the fixture layer."""
    blob = bytes(valid_blobs()[index])
    commitment = cached_blob_to_kzg_commitment(blob)
    cells, proofs = cached_compute_cells_and_kzg_proofs(blob)
    return blob, commitment, cells, proofs


def case_verify_cell_kzg_proof_batch():
    spec = kzg_7594_spec()

    def runner(get_inputs):
        def _run():
            commitments, cell_indices, cells, proofs = get_inputs()
            ok = _try(spec.verify_cell_kzg_proof_batch, commitments,
                      cell_indices, cells, proofs)
            return _data_part(
                {"commitments": encode_hex_list(commitments),
                 "cell_indices": [int(i) for i in cell_indices],
                 "cells": encode_hex_list(cells),
                 "proofs": encode_hex_list(proofs)},
                ok)
        return _run

    def subset(blob_index, indices, mutate=None):
        def _get():
            _, commitment, cells, proofs = _proven_blob(blob_index)
            inputs = ([commitment] * len(indices), list(indices),
                      [cells[i] for i in indices],
                      [proofs[i] for i in indices])
            if mutate is not None:
                inputs = mutate(*inputs)
            return inputs
        return _get

    # valid cases: different sizes and index patterns
    yield ("verify_cell_kzg_proof_batch_case_valid_empty",
           runner(subset(0, [])))
    yield ("verify_cell_kzg_proof_batch_case_valid_single",
           runner(subset(0, [3])))
    yield ("verify_cell_kzg_proof_batch_case_valid_first_half",
           runner(subset(1, list(range(64)))))
    yield ("verify_cell_kzg_proof_batch_case_valid_every_other",
           runner(subset(2, list(range(0, 128, 2)))))
    yield ("verify_cell_kzg_proof_batch_case_valid_duplicate_indices",
           runner(subset(0, [7, 7, 21])))

    def two_blobs():
        _, c0, cells0, proofs0 = _proven_blob(0)
        _, c1, cells1, proofs1 = _proven_blob(1)
        return ([c0, c1], [5, 9], [cells0[5], cells1[9]],
                [proofs0[5], proofs1[9]])

    yield ("verify_cell_kzg_proof_batch_case_valid_multiple_blobs",
           runner(two_blobs))

    # zero-blob closed form: infinity commitment, all-zero cells,
    # infinity proofs — valid (reference's *_case_valid_zero_* family)
    def zero_blob():
        spec_ = kzg_7594_spec()
        inf = b"\xc0" + b"\x00" * 47
        zero_cell = b"\x00" * int(spec_.BYTES_PER_CELL)
        return ([inf, inf], [0, 81], [zero_cell, zero_cell],
                [inf, inf])

    yield ("verify_cell_kzg_proof_batch_case_valid_zero_blob",
           runner(zero_blob))
    # the same statement repeated verbatim stays valid (duplicate
    # (commitment, index, cell, proof) rows are legal)
    yield ("verify_cell_kzg_proof_batch_case_valid_same_cell_repeated",
           runner(subset(0, [11, 11])))

    # incorrect (well-formed but wrong) inputs
    yield ("verify_cell_kzg_proof_batch_case_incorrect_proof_add_one",
           runner(subset(0, [4, 5], mutate=lambda c, i, cl, p:
                         (c, i, cl, [bls_add_one(p[0]), p[1]]))))
    yield ("verify_cell_kzg_proof_batch_case_incorrect_commitment",
           runner(subset(0, [4, 5], mutate=lambda c, i, cl, p:
                         ([bls_add_one(c[0]), c[1]], i, cl, p))))
    yield ("verify_cell_kzg_proof_batch_case_incorrect_cell",
           runner(subset(1, [2], mutate=lambda c, i, cl, p:
                         (c, i, [valid_cells()[0]], p))))
    yield ("verify_cell_kzg_proof_batch_case_cells_swapped",
           runner(subset(2, [1, 2], mutate=lambda c, i, cl, p:
                         (c, i, [cl[1], cl[0]], p))))
    yield ("verify_cell_kzg_proof_batch_case_incorrect_cell_index",
           runner(subset(1, [6], mutate=lambda c, i, cl, p:
                         (c, [7], cl, p))))
    yield ("verify_cell_kzg_proof_batch_case_incorrect_proof_point_at"
           "_infinity",
           runner(subset(0, [3], mutate=lambda c, i, cl, p:
                         (c, i, cl, [b"\xc0" + b"\x00" * 47]))))
    yield ("verify_cell_kzg_proof_batch_case_incorrect_commitment"
           "_point_at_infinity",
           runner(subset(0, [3], mutate=lambda c, i, cl, p:
                         ([b"\xc0" + b"\x00" * 47], i, cl, p))))
    yield ("verify_cell_kzg_proof_batch_case_proofs_swapped",
           runner(subset(2, [8, 9], mutate=lambda c, i, cl, p:
                         (c, i, cl, [p[1], p[0]]))))

    # malformed members
    for k, point in enumerate(invalid_g1_points()):
        yield (f"verify_cell_kzg_proof_batch_case_invalid_commitment_{k}",
               runner(subset(0, [0], mutate=lambda c, i, cl, p, pt=point:
                             ([pt], i, cl, p))))
    for k, cell in enumerate(invalid_cells()):
        yield (f"verify_cell_kzg_proof_batch_case_invalid_cell_{k}",
               runner(subset(0, [0], mutate=lambda c, i, cl, p, x=cell:
                             (c, i, [x], p))))
    for k, point in enumerate(invalid_g1_points()):
        yield (f"verify_cell_kzg_proof_batch_case_invalid_proof_{k}",
               runner(subset(0, [0], mutate=lambda c, i, cl, p, pt=point:
                             (c, i, cl, [pt]))))
    yield ("verify_cell_kzg_proof_batch_case_invalid_cell_index",
           runner(subset(0, [0], mutate=lambda c, i, cl, p:
                         (c, [int(kzg_7594_spec().CELLS_PER_EXT_BLOB)],
                          cl, p))))
    # length mismatches
    yield ("verify_cell_kzg_proof_batch_case_commitment_length_different",
           runner(subset(0, [1, 2], mutate=lambda c, i, cl, p:
                         (c[:-1], i, cl, p))))
    yield ("verify_cell_kzg_proof_batch_case_cell_length_different",
           runner(subset(0, [1, 2], mutate=lambda c, i, cl, p:
                         (c, i, cl[:-1], p))))
    yield ("verify_cell_kzg_proof_batch_case_proof_length_different",
           runner(subset(0, [1, 2], mutate=lambda c, i, cl, p:
                         (c, i, cl, p[:-1]))))
    yield ("verify_cell_kzg_proof_batch_case_index_length_different",
           runner(subset(0, [1, 2], mutate=lambda c, i, cl, p:
                         (c, i[:-1], cl, p))))


def case_recover_cells_and_kzg_proofs():
    spec = kzg_7594_spec()
    n_cells = int(spec.CELLS_PER_EXT_BLOB)

    def runner(get_inputs):
        def _run():
            cell_indices, cells = get_inputs()
            out = _try(spec.recover_cells_and_kzg_proofs, cell_indices,
                       cells)
            return _data_part(
                {"cell_indices": [int(i) for i in cell_indices],
                 "cells": encode_hex_list(cells)},
                ((encode_hex_list(out[0]), encode_hex_list(out[1]))
                 if out is not None else None))
        return _run

    def available(blob_index, indices, mutate=None):
        def _get():
            _, _, cells, _ = _proven_blob(blob_index)
            inputs = (list(indices), [cells[i] for i in indices])
            if mutate is not None:
                inputs = mutate(*inputs)
            return inputs
        return _get

    yield ("recover_cells_and_kzg_proofs_case_valid_no_missing",
           runner(available(0, list(range(n_cells)))))
    yield ("recover_cells_and_kzg_proofs_case_valid_half_missing_every"
           "_other_cell",
           runner(available(1, list(range(0, n_cells, 2)))))
    yield ("recover_cells_and_kzg_proofs_case_valid_half_missing_first"
           "_half",
           runner(available(2, list(range(n_cells // 2)))))
    yield ("recover_cells_and_kzg_proofs_case_valid_half_missing_last"
           "_half",
           runner(available(0, list(range(n_cells // 2, n_cells)))))

    # errors: not enough cells, malformed members, bad indices
    yield ("recover_cells_and_kzg_proofs_case_invalid_more_than_half"
           "_missing",
           runner(available(0, list(range(n_cells // 2 - 1)))))
    yield ("recover_cells_and_kzg_proofs_case_invalid_more_cells_than"
           "_exist",
           runner(available(0, list(range(n_cells)),
                            mutate=lambda i, c: (i + [0], c + [c[0]]))))
    for k, cell in enumerate(invalid_cells()):
        yield (f"recover_cells_and_kzg_proofs_case_invalid_cell_{k}",
               runner(available(0, list(range(0, n_cells, 2)),
                                mutate=lambda i, c, x=cell:
                                (i, [x] + c[1:]))))
    yield ("recover_cells_and_kzg_proofs_case_invalid_duplicate_cell"
           "_index",
           runner(available(0, list(range(0, n_cells, 2)),
                            mutate=lambda i, c: ([i[0], i[0]] + i[2:], c))))
    yield ("recover_cells_and_kzg_proofs_case_invalid_cell_index_out"
           "_of_range",
           runner(available(0, list(range(0, n_cells, 2)),
                            mutate=lambda i, c: ([n_cells] + i[1:], c))))
    yield ("recover_cells_and_kzg_proofs_case_invalid_length_mismatch",
           runner(available(0, list(range(0, n_cells, 2)),
                            mutate=lambda i, c: (i, c[:-1]))))
    # a recoverable set strictly between half and all (the reference's
    # more-than-half family): every other cell plus one extra
    yield ("recover_cells_and_kzg_proofs_case_valid_more_than_half",
           runner(available(1, list(range(0, n_cells, 2)) + [1])))

    # --- device-route vectors (coset erasure decode + FK20 re-prove).
    # Rendered through the jax route with the host oracle run on the
    # same inputs and byte-parity asserted BEFORE the vector is
    # written, so a published vector can never encode a device-only
    # answer.  A degree-65 closed-form blob keeps the pure-Python
    # oracle tractable (its MSM skips the ~4030 zero scalars).
    def device_runner(get_inputs):
        def _run():
            from ...das import recover as das_recover
            cell_indices, cells = get_inputs()
            dev = _try(das_recover.recover_cells_and_kzg_proofs,
                       cell_indices, cells, True)
            host = _try(das_recover.recover_cells_and_kzg_proofs_host,
                        cell_indices, cells)
            assert (dev is None) == (host is None), \
                (dev is None, host is None)
            if dev is not None:
                assert [bytes(c) for c in dev[0]] == \
                    [bytes(c) for c in host[0]], "device/oracle cells"
                assert [bytes(p) for p in dev[1]] == \
                    [bytes(p) for p in host[1]], "device/oracle proofs"
            return _data_part(
                {"cell_indices": [int(i) for i in cell_indices],
                 "cells": encode_hex_list(cells)},
                ((encode_hex_list(dev[0]), encode_hex_list(dev[1]))
                 if dev is not None else None))
        return _run

    def closed_form_available(indices, mutate=None):
        def _get():
            from ...das import ciphersuite as dcs
            _, by_col = dcs.closed_form_row(
                90007, 80007, 70007, list(range(n_cells)))
            inputs = (list(indices), [by_col[i][0] for i in indices])
            if mutate is not None:
                inputs = mutate(*inputs)
            return inputs
        return _get

    yield ("recover_cells_and_kzg_proofs_case_valid_device_half"
           "_missing",
           device_runner(closed_form_available(
               list(range(0, n_cells, 2)))))
    yield ("recover_cells_and_kzg_proofs_case_invalid_device_one_more"
           "_than_half_missing",
           device_runner(closed_form_available(
               list(range(n_cells // 2 - 1)))))
    yield ("recover_cells_and_kzg_proofs_case_invalid_device"
           "_duplicate_cell_index",
           device_runner(closed_form_available(
               list(range(0, n_cells, 2)),
               mutate=lambda i, c: ([i[0], i[0]] + i[2:], c))))


CASE_FNS = [
    ("compute_cells", case_compute_cells),
    ("compute_cells_and_kzg_proofs", case_compute_cells_and_kzg_proofs),
    ("verify_cell_kzg_proof_batch", case_verify_cell_kzg_proof_batch),
    ("recover_cells_and_kzg_proofs", case_recover_cells_and_kzg_proofs),
]


def get_test_cases():
    cases = []
    for handler_name, case_fn in CASE_FNS:
        for case_name, runner in case_fn():
            cases.append(TestCase(
                fork_name="fulu",
                preset_name="general",
                runner_name="kzg",
                handler_name=handler_name,
                suite_name="kzg-mainnet",
                case_name=case_name,
                case_fn=runner,
            ))
    return cases
