"""Shuffling vectors: seed × count → full shuffled mapping (the
reference's `tests/generators/runners/shuffling.py`)."""

import random

from ...models.builder import build_spec
from ..from_tests import ALL_PRESETS
from ..typing import TestCase


def shuffling_case_fn(spec, seed, count):
    yield ("mapping", "data", {
        "seed": "0x" + seed.hex(),
        "count": count,
        "mapping": [int(spec.compute_shuffled_index(i, count, seed))
                    for i in range(count)],
    })


def get_test_cases():
    cases = []
    for preset in ALL_PRESETS:
        spec = build_spec("phase0", preset)
        rng = random.Random(1234)
        seeds = [bytes(rng.randint(0, 255) for _ in range(32))
                 for _ in range(30)]
        for seed in seeds:
            for count in (0, 1, 2, 3, 5, 10, 33, 100, 1000, 9999):
                cases.append(TestCase(
                    fork_name="phase0",
                    preset_name=preset,
                    runner_name="shuffling",
                    handler_name="core",
                    suite_name="shuffle",
                    case_name=f"shuffle_0x{seed.hex()}_{count}",
                    case_fn=(lambda spec=spec, seed=seed, count=count:
                             list(shuffling_case_fn(spec, seed, count))),
                ))
    return cases
