from ..from_tests import get_test_cases_for


def handler_name_fn(mod):
    return "fork"


def get_test_cases():
    return get_test_cases_for("forks", pkg="fork",
                              handler_name_fn=handler_name_fn)
