from ..from_tests import get_test_cases_for


def handler_name_fn(mod):
    handler_name = mod.split(".")[-1]
    if handler_name == "test_apply_pending_deposit":
        return "pending_deposits"
    handler_name = handler_name.replace("test_process_", "")
    return handler_name.replace("test_apply_", "")


def get_test_cases():
    return get_test_cases_for("epoch_processing",
                              handler_name_fn=handler_name_fn)
