from ..from_tests import get_test_cases_for


def handler_name_fn(mod):
    handler_name = mod.split(".")[-1]
    if handler_name == "test_sync_protocol":
        return "sync"
    return handler_name.replace("test_", "")


def get_test_cases():
    return get_test_cases_for("light_client", handler_name_fn=handler_name_fn)
