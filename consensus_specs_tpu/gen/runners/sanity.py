from ..from_tests import get_test_cases_for


def handler_name_fn(mod):
    handler_name = mod.split(".")[-1]
    if handler_name == "test_deposit_transition":
        return "blocks"
    if handler_name == "test_lookahead":
        return "blocks"
    if handler_name == "test_lookahead_slots":
        return "slots"
    return handler_name.replace("test_", "")


def get_test_cases():
    return get_test_cases_for("sanity", handler_name_fn=handler_name_fn)
