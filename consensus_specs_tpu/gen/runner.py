"""Generator driver: execute TestCases, write vector parts, CLI.

Sequential or process-parallel (`--threads`), with per-case output-dir
cleanup and an incremental summary — the role of the reference's
`gen_helpers/gen_base/gen_runner.py` (pathos pool + rich table there;
multiprocessing + plain prints here).
"""

from __future__ import annotations

import argparse
import shutil
import sys
import time
from collections.abc import Iterable
from typing import Any

from .dumper import Dumper
from .typing import SkippedTest, TestCase


def execute_test(test_case: TestCase, dumper: Dumper) -> bool:
    """Run one case; returns False if the case skipped itself.  Output files
    are written only after the case function ran to completion, so a crash
    never leaves a partial vector dir."""
    meta: dict[str, Any] = {}
    outputs: list[tuple[str, str, Any]] = []

    parts = test_case.case_fn()
    if parts is None:
        return False
    for name, kind, data in parts:
        if kind == "meta":
            meta[name] = data
        elif kind in ("cfg", "data", "ssz"):
            outputs.append((name, kind, data))
        else:
            raise ValueError(f"unknown part kind {kind!r}")

    if test_case.dir.exists():
        shutil.rmtree(test_case.dir)
    for name, kind, data in outputs:
        getattr(dumper, f"dump_{kind}")(test_case, name, data)
    if meta:
        dumper.dump_meta(test_case, meta)
    return True


# Process-parallel support: TestCase.case_fn is a closure (built by the
# reflection bridge), so TestCase objects cannot be pickled into a Pool.
# Instead the selected case list is published here in the parent process
# and fork()ed workers receive *indices*, rebuilding nothing — the closure
# travels via copy-on-write memory inheritance.
_POOL_CASES: list[TestCase] = []


def _run_by_index(idx: int) -> tuple[str, str, str]:
    return _run_one(_POOL_CASES[idx])


def _run_one(test_case: TestCase) -> tuple[str, str, str]:
    """Worker: returns (identifier, status, detail)."""
    dumper = Dumper()
    try:
        wrote = execute_test(test_case, dumper)
        return (test_case.get_identifier(),
                "generated" if wrote else "skipped", "")
    except SkippedTest as e:
        return test_case.get_identifier(), "skipped", str(e)
    except Exception as e:  # record and continue; one bad case != no vectors
        import traceback

        return (test_case.get_identifier(), "failed",
                f"{type(e).__name__}: {e}\n{traceback.format_exc(limit=5)}")


def filter_cases(cases: Iterable[TestCase], args) -> list[TestCase]:
    out = []
    for tc in cases:
        if args.runners and tc.runner_name not in args.runners:
            continue
        if args.presets and tc.preset_name not in args.presets:
            continue
        if args.forks and tc.fork_name not in args.forks:
            continue
        if args.cases and not any(c in tc.case_name for c in args.cases):
            continue
        out.append(tc)
    return out


def parse_arguments(argv=None):
    p = argparse.ArgumentParser(
        prog="consensus_specs_tpu.gen",
        description="generate cross-client reference test vectors")
    p.add_argument("-o", "--output", required=True,
                   help="output directory for the vector tree")
    p.add_argument("--runners", nargs="*", default=[],
                   help="limit to these runners (default: all)")
    p.add_argument("--presets", nargs="*", default=[],
                   help="limit to these presets")
    p.add_argument("--forks", nargs="*", default=[],
                   help="limit to these forks")
    p.add_argument("--cases", nargs="*", default=[],
                   help="substring filters on case names")
    p.add_argument("--threads", type=int, default=1,
                   help="process-parallel execution")
    p.add_argument("--disable-bls", action="store_true",
                   help="skip real BLS signing/verification (vectors will "
                        "carry empty signatures; for pipeline debugging)")
    p.add_argument("--modcheck", action="store_true",
                   help="only check that runner modules import")
    p.add_argument("-v", "--verbose", action="store_true")
    return p.parse_args(argv)


def run_generator(test_cases: Iterable[TestCase], args) -> int:
    start = time.time()
    cases = filter_cases(test_cases, args)
    for tc in cases:
        tc.set_output_dir(args.output)
    print(f"{len(cases)} test cases selected", flush=True)

    # honor disable_bls regardless of entry point (gen/__main__ also sets it,
    # but programmatic callers pass an args namespace directly)
    from ..ops import bls

    prev_bls = bls.bls_active
    bls.bls_active = not getattr(args, "disable_bls", False)
    try:
        results = _execute_all(cases, args)
    finally:
        bls.bls_active = prev_bls

    n = {"generated": 0, "skipped": 0, "failed": 0}
    for _, status, _ in results:
        n[status] += 1
    dt = time.time() - start
    print(f"done in {dt:.1f}s: {n['generated']} generated, "
          f"{n['skipped']} skipped, {n['failed']} failed", flush=True)
    if n["failed"]:
        for ident, status, detail in results:
            if status == "failed":
                print(f"FAILED {ident}\n{detail}", file=sys.stderr)
    return 1 if n["failed"] else 0


def _execute_all(cases: list[TestCase], args) -> list[tuple[str, str, str]]:
    results: list[tuple[str, str, str]] = []
    if args.threads > 1:
        import multiprocessing as mp

        global _POOL_CASES
        _POOL_CASES = cases
        try:
            with mp.get_context("fork").Pool(args.threads) as pool:
                for res in pool.imap_unordered(
                        _run_by_index, range(len(cases))):
                    results.append(res)
                    _report(res, args)
        finally:
            _POOL_CASES = []
    else:
        for tc in cases:
            res = _run_one(tc)
            results.append(res)
            _report(res, args)
    return results


def _report(res: tuple[str, str, str], args) -> None:
    ident, status, _ = res
    if args.verbose or status == "failed":
        print(f"[{status}] {ident}", flush=True)
