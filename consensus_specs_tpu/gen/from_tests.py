"""Reflection bridge: walk the repo's `tests/<fork>/<category>/test_*.py`
modules, collect `test_*` functions, and wrap each as a TestCase running in
generator mode (the reference's `gen_helpers/gen_from_tests/gen.py`).

The repo's test tree is organized by fork first (`tests/phase0/sanity/…`)
where the reference nests fork under the eth2spec test package; the
reflection maps category directory → runner name identically.
"""

from __future__ import annotations

import pkgutil
import sys
from collections.abc import Iterable
from importlib import import_module
from inspect import getmembers, isfunction
from pathlib import Path

from ..models.builder import ALL_FORKS, PKG_ROOT
from ..ops import bls as bls_mod
from .typing import TestCase

REPO_ROOT = PKG_ROOT.parent

ALL_PRESETS = ("mainnet", "minimal")
TESTGEN_FORKS = tuple(ALL_FORKS)


def _ensure_importable() -> None:
    if str(REPO_ROOT) not in sys.path:
        sys.path.insert(0, str(REPO_ROOT))


def generate_case_fn(tfn, phase: str, preset: str, bls_active: bool):
    def case_fn():
        # default BLS-on for vectors (clients need real signatures); tests
        # marked @always_bls/@never_bls flip the switch themselves during
        # iteration, and the CLI's --disable-bls turns the default off
        prev = bls_mod.bls_active
        bls_mod.bls_active = bls_active and bls_mod.bls_active
        try:
            return tfn(generator_mode=True, phase=phase, preset=preset)
        finally:
            bls_mod.bls_active = prev

    return case_fn


def generate_from_tests(
    runner_name: str,
    handler_name: str,
    src,
    fork_name: str,
    preset_name: str,
    bls_active: bool = True,
    phase: str | None = None,
) -> Iterable[TestCase]:
    fn_names = [name for (name, _) in getmembers(src, isfunction)
                if name.startswith("test_")]
    if phase is None:
        phase = fork_name
    for name in fn_names:
        tfn = getattr(src, name)
        yield TestCase(
            fork_name=fork_name,
            preset_name=preset_name,
            runner_name=runner_name,
            handler_name=handler_name,
            suite_name=getattr(tfn, "suite_name", "pyspec_tests"),
            case_name=name[5:] if name.startswith("test_") else name,
            case_fn=generate_case_fn(tfn, phase=phase, preset=preset_name,
                                     bls_active=bls_active),
        )


def get_test_modules(category: str) -> list[str]:
    """Module paths of `tests/*/<category>/test_*.py` across every fork dir
    (the test tree is flat below the category level).  Like the reference,
    every module is offered to every target fork — a phase0 sanity test
    emits vectors for all forks via its `@with_all_phases`, and a module
    whose fork gate rejects the target simply skips."""
    _ensure_importable()
    out = []
    for fork in ALL_FORKS:
        pkg_dir = Path(REPO_ROOT) / "tests" / fork / category
        if not pkg_dir.is_dir():
            continue
        for info in pkgutil.iter_modules([str(pkg_dir)]):
            if info.name.startswith("test_"):
                out.append(f"tests.{fork}.{category}.{info.name}")
    return sorted(out)


def default_handler_name_fn(mod: str) -> str:
    return mod.split(".")[-1].replace("test_", "")


def get_test_cases_for(
    runner_name: str,
    pkg: str | None = None,
    handler_name_fn=default_handler_name_fn,
    bls_active: bool = True,
    presets: Iterable[str] = ALL_PRESETS,
    forks: Iterable[str] = TESTGEN_FORKS,
) -> list[TestCase]:
    cases: list[TestCase] = []
    modules = get_test_modules(pkg or runner_name)
    for preset in presets:
        for fork in forks:
            for mod in modules:
                src = import_module(mod)
                cases.extend(generate_from_tests(
                    runner_name=runner_name,
                    handler_name=handler_name_fn(mod),
                    src=src,
                    fork_name=fork,
                    preset_name=preset,
                    bls_active=bls_active,
                ))
    return cases
