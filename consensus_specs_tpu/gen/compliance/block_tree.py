"""Instantiate an abstract (parents, votes) instance into a concrete
fork-choice vector (the role of `instantiators/block_tree.py`): build the
block tree slot by slot, apply the vote loads as attestations, emit the
standard step sequence with head/store checks after every event.

The expected head in every check comes from this repo's own
`get_head` — the vector asserts spec conformance, not a particular
implementation's answer.
"""

from __future__ import annotations

from ...testlib.helpers.attestations import get_valid_attestation
from ...testlib.helpers.block import build_empty_block, sign_block
from ...testlib.helpers.fork_choice import (
    add_attestation,
    add_block,
    get_anchor_root,
    on_tick_and_append_step,
    output_head_check,
)
from ...testlib.helpers.state import state_transition_and_sign_block


def instantiate_block_tree_test(parents, votes, n_mutations: int = 0,
                                mutation_seed: int = 0):
    """A dual-mode test function for one abstract instance.

    parents: canonical parent vector (parents[0] == 0 is the anchor).
    votes: [(block_index, committee_fraction_percent)] attestation loads.
    n_mutations > 0 emits the MUTATED variant: the valid step sequence
    with `n_mutations` random shift/drop/duplicate operators applied
    (`compliance/mutations.py`), per-step validity re-derived and the
    final head check recomputed by replaying through a fresh store.
    """

    def case(spec, state):
        test_steps = []
        objects = {}  # part name -> SSZ object (for mutated replay)

        def tee(part_gen):
            for name, obj in part_gen:
                objects[name] = obj
                yield name, obj

        yield "anchor_state", state
        anchor_block = spec.BeaconBlock(
            state_root=spec.hash_tree_root(state))
        yield "anchor_block", anchor_block
        store = spec.get_forkchoice_store(state, anchor_block)

        anchor_root = get_anchor_root(spec, state)
        post_states = {0: state.copy()}
        signed_blocks = {0: None}
        roots = {0: anchor_root}

        # blocks 1..n-1: block i sits at slot anchor+i on top of parent
        for i in range(1, len(parents)):
            parent_state = post_states[parents[i]]
            block = build_empty_block(spec, parent_state,
                                      slot=state.slot + i)
            st = parent_state.copy()
            signed = state_transition_and_sign_block(spec, st, block)
            post_states[i] = st
            signed_blocks[i] = signed
            roots[i] = spec.hash_tree_root(block)

            time = (store.genesis_time
                    + block.slot * spec.config.SECONDS_PER_SLOT)
            on_tick_and_append_step(spec, store, time, test_steps)
            yield from tee(add_block(spec, store, signed, test_steps))

        # vote loads: committee-fraction attestations to chosen targets
        for block_index, fraction in votes:
            if block_index == 0:
                continue  # votes for the anchor do not move weights
            target_state = post_states[block_index]
            att_slot = target_state.slot - 1

            def participants(committee, fraction=fraction):
                k = max(1, len(committee) * fraction // 100)
                return set(list(committee)[:k])

            attestation = get_valid_attestation(
                spec, target_state, slot=att_slot,
                filter_participant_set=participants, signed=True)
            # attestations are valid from the next slot
            next_time = (store.genesis_time
                         + (attestation.data.slot + 1)
                         * spec.config.SECONDS_PER_SLOT)
            if next_time > store.time:
                on_tick_and_append_step(spec, store, next_time,
                                        test_steps)
            yield from tee(add_attestation(spec, store, attestation,
                                           test_steps))

        output_head_check(spec, store, test_steps)

        if n_mutations:
            test_steps = _mutated_replay(
                spec, state, anchor_block, test_steps, objects,
                n_mutations, mutation_seed)
        yield "steps", test_steps

    return case


def _mutated_replay(spec, anchor_state, anchor_block, base_steps,
                    objects, n_mutations, mutation_seed):
    """Mutate the valid sequence, replay it, annotate per-step validity,
    and append the recomputed final head check."""
    import random as random_mod

    from ...testlib.helpers.fork_choice import encode_hex
    from .mutations import mutate_steps

    rng = random_mod.Random(mutation_seed)
    steps = mutate_steps(base_steps, rng, n_mutations)

    store = spec.get_forkchoice_store(anchor_state, anchor_block)
    out_steps = []
    for step in steps:
        step = dict(step)
        try:
            if "tick" in step:
                spec.on_tick(store, step["tick"])
            elif "block" in step:
                signed = objects[step["block"]]
                spec.on_block(store, signed)
                for attestation in signed.message.body.attestations:
                    spec.on_attestation(store, attestation,
                                        is_from_block=True)
            elif "attestation" in step:
                spec.on_attestation(store, objects[step["attestation"]])
        except (AssertionError, KeyError):
            step["valid"] = False
        out_steps.append(step)

    head = spec.get_head(store)
    out_steps.append({"checks": {
        "head": {"slot": int(store.blocks[head].slot),
                 "root": encode_hex(head)}}})
    return out_steps
