"""Step-sequence mutation operators (the role of
`compliance_runners/fork_choice/instantiators/mutation_operators.py`):
derive adversarial orderings from a valid fork-choice step sequence —
time-shifted, dropped, and duplicated message deliveries — while keeping
the sequence REPLAYABLE (ticks stay monotone, the store never sees a
time earlier than it already reached).

A mutated vector carries no step-by-step checks (intermediate store
state differs run to run); the final head check is recomputed by
replaying the mutated sequence through the spec's own store, so the
vector still asserts spec conformance."""

from __future__ import annotations


def _message_indices(steps):
    """Indices of movable events (block/attestation deliveries — ticks
    and checks are scheduling scaffolding)."""
    return [i for i, step in enumerate(steps)
            if "block" in step or "attestation" in step]


def mut_shift(steps, rng):
    """Move one message delivery to a later position (delayed
    delivery)."""
    indices = _message_indices(steps)
    if len(indices) < 2:
        return list(steps)
    src = rng.choice(indices[:-1])
    dst = rng.choice([i for i in indices if i > src])
    out = list(steps)
    moved = out.pop(src)
    out.insert(dst, moved)
    return out


def mut_drop(steps, rng):
    """Drop one message delivery (lost message)."""
    indices = _message_indices(steps)
    if not indices:
        return list(steps)
    victim = rng.choice(indices)
    return [step for i, step in enumerate(steps) if i != victim]


def mut_dup(steps, rng):
    """Deliver one message twice (gossip duplicate) at a later point."""
    indices = _message_indices(steps)
    if not indices:
        return list(steps)
    src = rng.choice(indices)
    out = list(steps)
    insert_at = rng.randrange(src + 1, len(out) + 1)
    out.insert(insert_at, dict(out[src]))
    return out


MUTATIONS = (mut_shift, mut_drop, mut_dup)


def strip_checks(steps):
    """Remove per-step checks; keep ticks and deliveries."""
    out = []
    for step in steps:
        step = {k: v for k, v in step.items() if k != "checks"}
        if step:
            out.append(step)
    return out


def mutate_steps(steps, rng, count: int):
    """Apply `count` random mutation operators to a check-stripped copy
    of `steps`."""
    out = strip_checks(steps)
    for _ in range(count):
        op = rng.choice(MUTATIONS)
        out = op(out, rng)
    return out
