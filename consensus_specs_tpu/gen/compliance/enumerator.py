"""Abstract instance enumeration (the role of `model/Block_tree.mzn`).

A block-tree instance is a parent vector: `parents[i]` is the parent of
block `i`, `parents[0] == 0` (the anchor).  The constraint model:

- connectivity/topology: `parents[i] < i` (parents precede children)
- canonical form: the parent vector is non-decreasing, which picks one
  labeled representative per unordered tree shape (children of earlier
  nodes are numbered first) — the dedup the MiniZinc symmetry-breaking
  constraints perform
- shape bounds: max branching factor and leaf count keep tiny configs
  tiny, mirroring the .mzn parameters
"""

from __future__ import annotations


def enumerate_block_trees(n_blocks: int, max_branching: int = 3,
                          min_leaves: int = 1, max_leaves: int | None = None):
    """All canonical parent vectors for trees of `n_blocks` nodes
    (anchor included)."""
    if max_leaves is None:
        max_leaves = n_blocks

    out: list[list[int]] = []
    parents = [0] * n_blocks

    def children(upto: int, node: int) -> int:
        return sum(1 for i in range(1, upto) if parents[i] == node)

    def rec(i: int):
        if i == n_blocks:
            leaves = sum(1 for node in range(n_blocks)
                         if children(n_blocks, node) == 0)
            if min_leaves <= leaves <= max_leaves:
                out.append(parents[:])
            return
        lo = parents[i - 1] if i > 1 else 0
        for p in range(lo, i):
            if children(i, p) >= max_branching:
                continue
            parents[i] = p
            rec(i + 1)

    rec(1)

    # the ordering constraint leaves a few isomorphic duplicates (e.g.
    # [0,0,0,1] vs [0,0,0,2]); dedup by the AHU canonical form
    def canonical(parents):
        kids: dict[int, list[int]] = {i: [] for i in range(len(parents))}
        for i in range(1, len(parents)):
            kids[parents[i]].append(i)

        def shape(node):
            return tuple(sorted(shape(c) for c in kids[node]))

        return shape(0)

    seen: set = set()
    unique = []
    for parents in out:
        key = canonical(parents)
        if key not in seen:
            seen.add(key)
            unique.append(parents)
    return unique


def attestation_variations(rng, n_blocks: int, n_variations: int,
                           max_attesting: int = 6):
    """Seeded per-instance vote patterns (the `nr_variations` axis of the
    reference's test_gen.yaml): each variation is a list of
    (block_index, committee_fraction_percent) vote loads."""
    variations = []
    for _ in range(n_variations):
        n_votes = rng.randint(1, max_attesting)
        variations.append([
            (rng.randrange(n_blocks), rng.choice([25, 50, 100]))
            for _ in range(n_votes)
        ])
    return variations
