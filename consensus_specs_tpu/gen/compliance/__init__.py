"""Fork-choice compliance test generation — the Python edition of the
reference's `tests/generators/compliance_runners/fork_choice/` (MiniZinc
`Block_tree.mzn` model + `instantiators/block_tree.py`).

The reference enumerates abstract (block_parents, sm_links) instances
with a constraint solver, then instantiates each into a concrete chain
driven through the standard fork-choice step format.  At tiny scale a
direct Python enumerator covers the same instance space, so the solver
dependency disappears; the instantiation and the on-disk step format
are unchanged (`tests/formats/fork_choice/README.md`).
"""

from .enumerator import enumerate_block_trees
from .block_tree import instantiate_block_tree_test

__all__ = ["enumerate_block_trees", "instantiate_block_tree_test"]
