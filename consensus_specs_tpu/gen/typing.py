"""TestCase identity + part protocol (the reference's
`gen_helpers/gen_base/gen_typing.py`)."""

from __future__ import annotations

from collections.abc import Callable, Iterable
from dataclasses import dataclass
from pathlib import Path
from typing import Any

# (name, out_kind, data); out_kind in {"meta", "cfg", "data", "ssz"}
TestCasePart = tuple[str, str, Any]


class SkippedTest(Exception):
    """Raised by a case_fn to bail without writing files (preset/fork
    mismatch discovered at execution time)."""


@dataclass
class TestCase:
    fork_name: str
    preset_name: str
    runner_name: str
    handler_name: str
    suite_name: str
    case_name: str
    case_fn: Callable[[], Iterable[TestCasePart] | None]
    dir: Path | None = None

    def get_identifier(self) -> str:
        return "::".join([
            self.preset_name, self.fork_name, self.runner_name,
            self.handler_name, self.suite_name, self.case_name,
        ])

    def set_output_dir(self, output_dir: str) -> None:
        self.dir = (
            Path(output_dir)
            / self.preset_name
            / self.fork_name
            / self.runner_name
            / self.handler_name
            / self.suite_name
            / self.case_name
        )
