"""CLI: ``python -m consensus_specs_tpu.gen --output <dir> [--runners …]``.

Writes the reference-vector tree
`<preset>/<fork>/<runner>/<handler>/<suite>/<case>/…` per
`/root/reference/tests/formats/README.md`.
"""

from __future__ import annotations

import sys
from importlib import import_module

from .runner import parse_arguments, run_generator
from .runners import RUNNER_MODULES


def main(argv=None) -> int:
    args = parse_arguments(argv)
    selected = args.runners or RUNNER_MODULES
    unknown = [r for r in selected if r not in RUNNER_MODULES]
    if unknown:
        print(f"unknown runners: {unknown}; available: {RUNNER_MODULES}",
              file=sys.stderr)
        return 2

    if args.disable_bls:
        from ..ops import bls

        bls.bls_active = False

    cases = []
    for name in selected:
        mod = import_module(f"consensus_specs_tpu.gen.runners.{name}")
        if args.modcheck:
            print(f"runner {name}: module ok")
            continue
        got = mod.get_test_cases()
        print(f"runner {name}: {len(got)} cases", flush=True)
        cases.extend(got)
    if args.modcheck:
        return 0
    # module selection is already applied; some modules emit cases under
    # a different runner_name (compliance -> fork_choice_compliance,
    # kzg_4844/kzg_7594 -> kzg), so run_generator must not re-filter
    args.runners = []
    return run_generator(cases, args)


if __name__ == "__main__":
    raise SystemExit(main())
