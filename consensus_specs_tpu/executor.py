"""Batched block executor — the TPU-first state-transition entry point.

`state_transition(spec, state, block)` in the executable spec verifies
every aggregate signature inline (pure host pairings).  This executor
restructures that for the device: the spec runs with its
FastAggregateVerify pairings *deferred* (inputs still validated eagerly),
and all recorded statements — attestations, sync aggregates, indexed
attestations from slashings — settle afterwards in ONE random-linear-
combination batch on the accelerator (`ops.bls_batch.batch_verify`: B+1
pairings folded through a shared Fq12 Miller accumulator, one final
exponentiation, and the 32-byte signing-root hash-to-curve also on
device — the whole batch is device-resident end to end).  Semantics match the inline path for
every valid block; an invalid aggregate signature surfaces as the batch
check failing (AssertionError), the same acceptance boundary as the spec.

Individual signatures (proposer, randao, exits, deposits) stay eager:
deposits with bad signatures are *valid* blocks per the spec, so their
checks must resolve before affecting control flow.

Breaker-driven degradation (the resilience layer, PR 8's serve-only
recovery extended here): `settle_deferred` guards the batch-settle
phase with a per-phase circuit breaker (`arm_breakers` /
`state_transition_batched(breakers=...)`).  A device-batch failure
records into the breaker and the SAME statements settle on the
pure-Python spec oracle (per-statement host pairing checks —
bit-identical verdicts, just slow); while the breaker is OPEN the
settle skips the device entirely, and the half-open probe re-closes it
once the device answers again.  Degraded settles are counted
(`flagship.degraded_steps` / `degraded_steps()`), surfaced as the
chaos round's `"flagship"` block so benchwatch can see a degraded
round.  Unarmed (the default) the settle path is one None check —
block-import semantics are unchanged.

Reference seam being replaced: `eth2spec/utils/bls.py:141-296`'s native
milagro calls inside `state_transition` (specs/phase0/beacon-chain.md
:1358-1381).
"""

from __future__ import annotations

from . import telemetry
from .telemetry import costmodel
from .ops import bls

# --- breaker-guarded settle (the flagship's recovery ladder) -----------------

# armed registry (None == plain fail-fast settle) + degraded accounting;
# the last device failure is kept for introspection/the chaos block
_breakers = None
_degraded_steps = 0
last_degraded_error: BaseException | None = None

SETTLE_BREAKER_KEY = "flagship::batch_settle"


def arm_breakers(registry=None):
    """Arm (or replace) the module-level breaker registry every
    `state_transition_batched` call consumes; `registry=None` builds a
    default `resilience.BreakerRegistry()`.  Returns the armed
    registry.  `disarm_breakers()` restores fail-fast semantics."""
    global _breakers
    if registry is None:
        from .resilience.policies import BreakerRegistry

        registry = BreakerRegistry()
    _breakers = registry
    return registry


def disarm_breakers() -> None:
    global _breakers
    _breakers = None


def armed_breakers():
    return _breakers


def degraded_steps() -> int:
    """Settles answered by the spec oracle instead of the device since
    the last reset — the `flagship::degraded_steps` surface."""
    return _degraded_steps


def reset_degraded_steps() -> None:
    global _degraded_steps
    _degraded_steps = 0


def _oracle_settle_tasks(tasks) -> bool:
    """The pure-Python spec oracle for a deferred batch: per-statement
    host pairing checks, bit-identical to the device RLC verdict.
    Routed through the serve executor's MEMOIZED oracle — one pairing
    check per DISTINCT statement, not per settle: consecutive blocks
    re-settling overlapping attestations during a breaker-open stretch
    must not re-pay the seconds-per-statement pure-Python cost."""
    from .serve.executor import _oracle_verify

    return all(_oracle_verify(t) for t in tasks)


def _count_degraded(n_statements: int) -> None:
    global _degraded_steps
    _degraded_steps += 1
    telemetry.count("flagship.degraded_steps")
    telemetry.count("flagship.degraded_statements", n_statements)


def settle_deferred(batch, device: bool | None = None,
                    breakers=None) -> bool:
    """Settle a `DeferredBatch` through the per-phase breaker ladder.

    CLOSED: settle on the device as always (successes re-close /
    reset).  A device failure records into the breaker and the same
    statements re-settle on the spec oracle — degraded, counted, still
    correct.  OPEN: skip the device, answer on the oracle.  HALF_OPEN:
    `allow()` admits this settle as the probe; its outcome re-closes or
    re-trips.  `breakers=None` uses the module-armed registry; with
    neither, this is exactly `batch.verify(device=...)`."""
    global last_degraded_error
    registry = breakers if breakers is not None else _breakers
    br = None
    if registry is not None and batch.tasks and not batch.failed:
        br = registry.get(SETTLE_BREAKER_KEY)
    if br is not None and not br.allow():
        _count_degraded(len(batch.tasks))
        with telemetry.span("executor.degraded_settle",
                            statements=len(batch.tasks), reason="open"):
            return batch.verify(device=False)
    try:
        ok = batch.verify(device=device)
    except Exception as exc:
        # ANY settle exception (a False verdict is a return, never a
        # raise) walks the ladder — special-casing AssertionError here
        # would leave a HALF_OPEN probe's `_probe_inflight` set forever
        # and wedge the flagship onto the oracle permanently
        if br is None:
            raise
        br.record_failure()
        last_degraded_error = exc
        telemetry.count("flagship.settle_failures")
        _count_degraded(len(batch.tasks))
        # batch.verify already settled its handles with the exception;
        # the block verdict still resolves on the oracle so the import
        # completes correctly in degraded mode
        with telemetry.span("executor.degraded_settle",
                            statements=len(batch.tasks),
                            reason="device_failure"):
            return _oracle_settle_tasks(batch.tasks)
    if br is not None:
        br.record_success()
    return ok


def state_transition_batched(spec, state, signed_block,
                             validate_result: bool = True,
                             device: bool | None = None,
                             breakers=None):
    """Run `spec.state_transition` with aggregate pairings batched on the
    device.  Raises AssertionError exactly where the spec would (plus at
    the end if the signature batch fails); on failure the state is
    partially advanced — run on a copy, as `on_block` does.

    Each phase (slot advance, block body, batch settle, state-root
    check) runs under a telemetry span, so a `CST_TRACE_FILE` capture of
    a block import decomposes into per-phase wall time; on
    CST_COSTMODEL rounds each phase boundary also samples the
    per-device live-buffer watermark (`costmodel.sample_watermark`), so
    the same capture shows where device-memory pressure peaks inside a
    block import."""
    block = signed_block.message
    with telemetry.span("executor.state_transition_batched",
                        slot=int(block.slot)):
        costmodel.sample_watermark("executor.start")
        with telemetry.span("executor.process_slots"):
            spec.process_slots(state, block.slot)
        costmodel.sample_watermark("executor.process_slots")
        if validate_result:
            with telemetry.span("executor.verify_block_signature"):
                assert spec.verify_block_signature(state, signed_block)
        with bls.deferred_batch_verification() as batch:
            with telemetry.span("executor.process_block"):
                spec.process_block(state, block)
        costmodel.sample_watermark("executor.process_block")
        # settle is once-only (DeferredBatch caches the verdict and
        # resolves every recorded handle); the gauge mirrors the serve
        # executor's queue-depth track in block-import traces
        telemetry.gauge("executor.deferred_statements", len(batch.tasks))
        with telemetry.span("executor.batch_settle",
                            statements=len(batch.tasks)):
            ok = settle_deferred(batch, device=device, breakers=breakers)
        costmodel.sample_watermark("executor.batch_settle")
        assert ok, "batched aggregate-signature verification failed"
        if validate_result:
            with telemetry.span("executor.state_root_check"):
                assert block.state_root == spec.hash_tree_root(state)
            costmodel.sample_watermark("executor.state_root_check")
    return state
