"""Batched block executor — the TPU-first state-transition entry point.

`state_transition(spec, state, block)` in the executable spec verifies
every aggregate signature inline (pure host pairings).  This executor
restructures that for the device: the spec runs with its
FastAggregateVerify pairings *deferred* (inputs still validated eagerly),
and all recorded statements — attestations, sync aggregates, indexed
attestations from slashings — settle afterwards in ONE random-linear-
combination batch on the accelerator (`ops.bls_batch.batch_verify`: B+1
pairings folded through a shared Fq12 Miller accumulator, one final
exponentiation, and the 32-byte signing-root hash-to-curve also on
device — the whole batch is device-resident end to end).  Semantics match the inline path for
every valid block; an invalid aggregate signature surfaces as the batch
check failing (AssertionError), the same acceptance boundary as the spec.

Individual signatures (proposer, randao, exits, deposits) stay eager:
deposits with bad signatures are *valid* blocks per the spec, so their
checks must resolve before affecting control flow.

Reference seam being replaced: `eth2spec/utils/bls.py:141-296`'s native
milagro calls inside `state_transition` (specs/phase0/beacon-chain.md
:1358-1381).
"""

from __future__ import annotations

from . import telemetry
from .telemetry import costmodel
from .ops import bls


def state_transition_batched(spec, state, signed_block,
                             validate_result: bool = True,
                             device: bool | None = None):
    """Run `spec.state_transition` with aggregate pairings batched on the
    device.  Raises AssertionError exactly where the spec would (plus at
    the end if the signature batch fails); on failure the state is
    partially advanced — run on a copy, as `on_block` does.

    Each phase (slot advance, block body, batch settle, state-root
    check) runs under a telemetry span, so a `CST_TRACE_FILE` capture of
    a block import decomposes into per-phase wall time; on
    CST_COSTMODEL rounds each phase boundary also samples the
    per-device live-buffer watermark (`costmodel.sample_watermark`), so
    the same capture shows where device-memory pressure peaks inside a
    block import."""
    block = signed_block.message
    with telemetry.span("executor.state_transition_batched",
                        slot=int(block.slot)):
        costmodel.sample_watermark("executor.start")
        with telemetry.span("executor.process_slots"):
            spec.process_slots(state, block.slot)
        costmodel.sample_watermark("executor.process_slots")
        if validate_result:
            with telemetry.span("executor.verify_block_signature"):
                assert spec.verify_block_signature(state, signed_block)
        with bls.deferred_batch_verification() as batch:
            with telemetry.span("executor.process_block"):
                spec.process_block(state, block)
        costmodel.sample_watermark("executor.process_block")
        # settle is once-only (DeferredBatch caches the verdict and
        # resolves every recorded handle); the gauge mirrors the serve
        # executor's queue-depth track in block-import traces
        telemetry.gauge("executor.deferred_statements", len(batch.tasks))
        with telemetry.span("executor.batch_settle",
                            statements=len(batch.tasks)):
            ok = batch.verify(device=device)
        costmodel.sample_watermark("executor.batch_settle")
        assert ok, "batched aggregate-signature verification failed"
        if validate_result:
            with telemetry.span("executor.state_root_check"):
                assert block.state_root == spec.hash_tree_root(state)
            costmodel.sample_watermark("executor.state_root_check")
    return state
