"""Static checks over the built specs — the role of the reference's
mypy/pylint pass (`/root/reference/Makefile:183-189`), adapted to the
flat exec'd-namespace architecture where import-based type checkers
cannot resolve names.

Two checks per fork x preset:

1. **Undefined names**: every `Name` load inside every spec function
   must resolve in the built namespace, builtins, or a local binding.
   This statically catches the NameError class of spec bug (a call to a
   helper that no fork in the chain defines).
2. **config-attribute discipline**: every `config.X` attribute access
   must exist in the loaded Configuration for that preset.

Plus one repo-wide check:

3. **env-knob discipline**: every `os.environ` read of a `CST_*`
   variable anywhere in the tree must have a row in README.md's
   "Environment knobs" table (and every table row must still have a
   read) — the knob surface cannot silently drift from its docs.

Run via `python -m consensus_specs_tpu.lint` (wired into `make lint`).
"""

from __future__ import annotations

import ast
import builtins
import re
import sys

from .models.builder import (
    BUILDABLE_FORKS,
    PKG_ROOT,
    SPEC_SOURCES,
    build_spec,
    fork_chain,
)


class _LocalBindings(ast.NodeVisitor):
    """Names bound inside one function scope (params, assignments,
    targets, comprehensions, nested defs, imports, exception aliases)."""

    def __init__(self):
        self.bound: set[str] = set()

    def _bind_target(self, node):
        for child in ast.walk(node):
            if isinstance(child, ast.Name) and isinstance(
                    child.ctx, (ast.Store, ast.Del)):
                self.bound.add(child.id)

    def visit_arguments(self, node):
        for arg in (list(node.posonlyargs) + list(node.args)
                    + list(node.kwonlyargs)):
            self.bound.add(arg.arg)
        if node.vararg:
            self.bound.add(node.vararg.arg)
        if node.kwarg:
            self.bound.add(node.kwarg.arg)
        self.generic_visit(node)

    def visit_Assign(self, node):
        for target in node.targets:
            self._bind_target(target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        self._bind_target(node.target)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._bind_target(node.target)
        self.generic_visit(node)

    def visit_For(self, node):
        self._bind_target(node.target)
        self.generic_visit(node)

    def visit_withitem(self, node):
        if node.optional_vars is not None:
            self._bind_target(node.optional_vars)
        self.generic_visit(node)

    def visit_comprehension(self, node):
        self._bind_target(node.target)
        self.generic_visit(node)

    def visit_NamedExpr(self, node):
        self._bind_target(node.target)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node):
        if node.name:
            self.bound.add(node.name)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        self.bound.add(node.name)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self.visit_arguments(node.args)
        self.generic_visit(node)

    def visit_ClassDef(self, node):
        self.bound.add(node.name)
        self.generic_visit(node)

    def visit_Import(self, node):
        for alias in node.names:
            self.bound.add((alias.asname or alias.name).split(".")[0])

    visit_ImportFrom = visit_Import

    def visit_Global(self, node):
        self.bound.update(node.names)

    visit_Nonlocal = visit_Global


def _function_findings(fn_node, known: set[str], config_keys: set[str],
                       path: str):
    locals_visitor = _LocalBindings()
    locals_visitor.visit(fn_node)
    bound = locals_visitor.bound | known

    findings = []
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id not in bound:
                findings.append(
                    f"{path}:{node.lineno}: undefined name "
                    f"'{node.id}' in {fn_node.name}()")
        elif (isinstance(node, ast.Attribute)
              and isinstance(node.value, ast.Name)
              and node.value.id == "config"
              and isinstance(node.ctx, ast.Load)):
            if node.attr not in config_keys:
                findings.append(
                    f"{path}:{node.lineno}: unknown config attribute "
                    f"'config.{node.attr}' in {fn_node.name}()")
    return findings


def lint_spec(fork: str, preset: str) -> list[str]:
    spec = build_spec(fork, preset)
    known = set(spec._namespace) | set(vars(builtins))
    config_keys = set(spec.config.to_dict())

    findings = []
    for chain_fork in fork_chain(fork):
        for source in SPEC_SOURCES[chain_fork]:
            path = PKG_ROOT / "models" / chain_fork / source
            tree = ast.parse(path.read_text())
            rel = str(path.relative_to(PKG_ROOT.parent))
            # top-level functions and methods only: nested defs are
            # checked inside their parent's scope walk
            tops = list(tree.body)
            for node in tree.body:
                if isinstance(node, ast.ClassDef):
                    tops.extend(node.body)
            for node in tops:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    findings.extend(_function_findings(
                        node, known, config_keys, rel))
    return findings


# any os.environ get/subscript/setdefault or os.getenv whose string key
# carries the CST_ prefix, matched against whole-file text so reads
# wrapped across lines still register.  Internal knobs (leading
# underscore, e.g. _CST_DRYRUN_SUBPROCESS) are exempt by the prefix
# anchor.
_ENV_READ_RE = re.compile(
    r"""(?:environ(?:\.get|\.setdefault)?\s*[\(\[]|getenv\s*\()"""
    r"""\s*['"](CST_[A-Z0-9_]+)""")
_SKIP_DIRS = {"__pycache__", ".git", ".jax_cache", ".pytest_cache",
              "out", ".claude", ".venv", "venv", ".eggs",
              "site-packages", "node_modules"}


def lint_env_knobs() -> list[str]:
    """Every `CST_*` env read in the tree needs a row in README.md's
    knob table, and every row needs a surviving read."""
    repo = PKG_ROOT.parent
    readme = repo / "README.md"
    documented = set(re.findall(r"\|\s*`(CST_[A-Z0-9_]+)`",
                                readme.read_text()))

    used: dict[str, str] = {}
    for path in sorted(repo.rglob("*.py")):
        if any(part in _SKIP_DIRS for part in path.parts):
            continue
        rel = str(path.relative_to(repo))
        try:
            text = path.read_text()
        except (OSError, UnicodeDecodeError):
            continue    # unreadable stray file — not ours to lint
        for m in _ENV_READ_RE.finditer(text):
            line = text.count("\n", 0, m.start()) + 1
            used.setdefault(m.group(1), f"{rel}:{line}")

    findings = []
    for name in sorted(set(used) - documented):
        findings.append(
            f"{used[name]}: env knob '{name}' read but not documented "
            f"in README.md's Environment knobs table")
    for name in sorted(documented - set(used)):
        findings.append(
            f"README.md: env knob '{name}' documented but never read "
            f"in the tree (stale table row?)")
    return findings


def main(argv=None) -> int:
    presets = ("minimal", "mainnet")
    total = 0
    seen: set[str] = set()
    for fork in BUILDABLE_FORKS:
        for preset in presets:
            for finding in lint_spec(fork, preset):
                if finding not in seen:
                    seen.add(finding)
                    print(finding)
                    total += 1
    for finding in lint_env_knobs():
        print(finding)
        total += 1
    if total:
        print(f"spec lint: {total} finding(s)", file=sys.stderr)
        return 1
    print(f"spec lint: {len(BUILDABLE_FORKS) * len(presets)} "
          "spec builds clean (undefined-name + config-attribute checks); "
          "env-knob table in sync")
    return 0


if __name__ == "__main__":
    sys.exit(main())
