"""Static checks over the built specs — the role of the reference's
mypy/pylint pass (`/root/reference/Makefile:183-189`), adapted to the
flat exec'd-namespace architecture where import-based type checkers
cannot resolve names.

Three checks per fork x preset:

1. **Undefined names**: every `Name` load inside every spec function
   must resolve in the built namespace, builtins, or a local binding.
   This statically catches the NameError class of spec bug (a call to a
   helper that no fork in the chain defines).  Lambdas get their own
   scope: their parameters neither leak into the enclosing function's
   bound set nor go unchecked inside the lambda body.
2. **config-attribute discipline**: every `config.X` attribute access
   must exist in the loaded Configuration for that preset.
3. **call arity**: every call from a LIVE spec function (one whose
   definition survived fork overriding into the built namespace) to a
   spec-defined helper must bind against the helper's signature in
   that namespace — the fork-override drift the undefined-name check
   cannot see (the name exists; its parameters changed).

Plus one repo-wide check:

3. **env-knob discipline**: every `os.environ` read of a `CST_*`
   variable anywhere in the tree must have a row in README.md's
   "Environment knobs" table (and every table row must still have a
   read) — the knob surface cannot silently drift from its docs.

Run via `python -m consensus_specs_tpu.lint` (wired into `make lint`).
"""

from __future__ import annotations

import ast
import builtins
import inspect
import re
import sys
import types
from pathlib import Path

from .models.builder import (
    BUILDABLE_FORKS,
    PKG_ROOT,
    SPEC_SOURCES,
    build_spec,
    fork_chain,
)


class _LocalBindings(ast.NodeVisitor):
    """Names bound inside one function scope (params, assignments,
    targets, comprehensions, nested defs, imports, exception aliases)."""

    def __init__(self):
        self.bound: set[str] = set()

    def _bind_target(self, node):
        for child in ast.walk(node):
            if isinstance(child, ast.Name) and isinstance(
                    child.ctx, (ast.Store, ast.Del)):
                self.bound.add(child.id)

    def visit_arguments(self, node):
        for arg in (list(node.posonlyargs) + list(node.args)
                    + list(node.kwonlyargs)):
            self.bound.add(arg.arg)
        if node.vararg:
            self.bound.add(node.vararg.arg)
        if node.kwarg:
            self.bound.add(node.kwarg.arg)
        self.generic_visit(node)

    def visit_Assign(self, node):
        for target in node.targets:
            self._bind_target(target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        self._bind_target(node.target)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._bind_target(node.target)
        self.generic_visit(node)

    def visit_For(self, node):
        self._bind_target(node.target)
        self.generic_visit(node)

    def visit_withitem(self, node):
        if node.optional_vars is not None:
            self._bind_target(node.optional_vars)
        self.generic_visit(node)

    def visit_comprehension(self, node):
        self._bind_target(node.target)
        self.generic_visit(node)

    def visit_NamedExpr(self, node):
        self._bind_target(node.target)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node):
        if node.name:
            self.bound.add(node.name)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        self.bound.add(node.name)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        # Lambdas are their OWN scope: binding their parameters here
        # would leak them into the enclosing function's bound set and
        # mask genuine undefined-name findings after the lambda (the
        # body is checked separately by `_scope_findings`).  Only the
        # default expressions evaluate in the enclosing scope.
        for default in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]:
            self.visit(default)

    def visit_ClassDef(self, node):
        self.bound.add(node.name)
        self.generic_visit(node)

    def visit_Import(self, node):
        for alias in node.names:
            self.bound.add((alias.asname or alias.name).split(".")[0])

    visit_ImportFrom = visit_Import

    def visit_Global(self, node):
        self.bound.update(node.names)

    visit_Nonlocal = visit_Global


def _split_lambdas(root):
    """Walk `root` like ast.walk but stop at every Lambda subtree,
    returning (nodes_in_this_scope, lambdas_found).  Callers recurse on
    each lambda's body with its parameters bound (a body that is itself
    a lambda lands in `lambdas` again, so chains nest correctly)."""
    nodes, lambdas = [], []
    stack = [root]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Lambda):
            lambdas.append(node)
            continue
        nodes.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return nodes, lambdas


def _scope_findings(root, bound: set[str], config_keys: set[str],
                    path: str, owner: str):
    """Name/config findings for one scope; lambda subtrees recurse with
    their parameters (and walrus bindings) added to the bound set."""
    nodes, lambdas = _split_lambdas(root)
    findings = []
    for node in nodes:
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id not in bound:
                findings.append(
                    f"{path}:{node.lineno}: undefined name "
                    f"'{node.id}' in {owner}()")
        elif (isinstance(node, ast.Attribute)
              and isinstance(node.value, ast.Name)
              and node.value.id == "config"
              and isinstance(node.ctx, ast.Load)):
            if node.attr not in config_keys:
                findings.append(
                    f"{path}:{node.lineno}: unknown config attribute "
                    f"'config.{node.attr}' in {owner}()")
    for lam in lambdas:
        lam_locals = _LocalBindings()
        lam_locals.visit_arguments(lam.args)
        lam_locals.visit(lam.body)           # walrus bindings in the body
        findings.extend(_scope_findings(
            lam.body, bound | lam_locals.bound, config_keys, path, owner))
        # default expressions evaluate in the ENCLOSING scope
        for default in list(lam.args.defaults) + [
                d for d in lam.args.kw_defaults if d is not None]:
            findings.extend(_scope_findings(
                default, bound, config_keys, path, owner))
    return findings


def _function_findings(fn_node, known: set[str], config_keys: set[str],
                       path: str):
    locals_visitor = _LocalBindings()
    locals_visitor.visit(fn_node)
    bound = locals_visitor.bound | known
    return _scope_findings(fn_node, bound, config_keys, path,
                           fn_node.name)


def _call_arity_findings(fn_node, spec_funcs: dict, sig_cache: dict,
                         path: str):
    """Calls to spec-defined helpers must bind against the callee's
    signature in the BUILT namespace (catches fork-override parameter
    drift).  Skips *args/**kwargs call sites and locally shadowed
    names; placeholder binding checks arity/keywords only."""
    locals_visitor = _LocalBindings()
    locals_visitor.visit(fn_node)
    shadowed = locals_visitor.bound

    findings = []
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Name):
            continue
        name = node.func.id
        if name in shadowed or name not in spec_funcs:
            continue
        if any(isinstance(a, ast.Starred) for a in node.args) \
                or any(kw.arg is None for kw in node.keywords):
            continue
        if name not in sig_cache:
            try:
                sig_cache[name] = inspect.signature(spec_funcs[name])
            except (TypeError, ValueError):
                sig_cache[name] = None
        sig = sig_cache[name]
        if sig is None:
            continue
        try:
            sig.bind(*([None] * len(node.args)),
                     **{kw.arg: None for kw in node.keywords})
        except TypeError as exc:
            findings.append(
                f"{path}:{node.lineno}: call to {name}() in "
                f"{fn_node.name}() does not match the spec signature "
                f"{sig}: {exc}")
    return findings


def _is_live_def(node: ast.FunctionDef, path, spec) -> bool:
    """Did this source definition survive fork overriding into the
    built namespace?  Superseded bodies never run, so arity-checking
    them against the final namespace would be noise.

    The namespace entry may be the builder's LRU cache wrapper
    (`_install_caches` rewraps get_beacon_committee & co.) — unwrap
    through `__wrapped__` before comparing code locations, else those
    helpers' own bodies would silently escape the arity check.  A
    decorated def's co_firstlineno is its first decorator line, so any
    of those lines counts as a match."""
    obj = spec._namespace.get(node.name)
    if obj is None:
        return False
    try:
        obj = inspect.unwrap(obj)
    except ValueError:          # wrapper cycle — never ours
        return False
    code = getattr(obj, "__code__", None)
    def_lines = {node.lineno} | {d.lineno for d in node.decorator_list}
    return (code is not None and code.co_filename == str(path)
            and code.co_firstlineno in def_lines)


def lint_spec(fork: str, preset: str) -> list[str]:
    spec = build_spec(fork, preset)
    known = set(spec._namespace) | set(vars(builtins))
    config_keys = set(spec.config.to_dict())
    spec_funcs = {name: obj for name, obj in spec._namespace.items()
                  if isinstance(obj, types.FunctionType)}
    sig_cache: dict = {}

    findings = []
    for chain_fork in fork_chain(fork):
        for source in SPEC_SOURCES[chain_fork]:
            path = PKG_ROOT / "models" / chain_fork / source
            tree = ast.parse(path.read_text())
            rel = str(path.relative_to(PKG_ROOT.parent))
            # top-level functions and methods only: nested defs are
            # checked inside their parent's scope walk.  Call arity is
            # checked for LIVE top-level defs only (methods are called
            # through instances, not the flat namespace).
            for node in tree.body:
                if isinstance(node, ast.ClassDef):
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            findings.extend(_function_findings(
                                sub, known, config_keys, rel))
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    findings.extend(_function_findings(
                        node, known, config_keys, rel))
                    if _is_live_def(node, path, spec):
                        findings.extend(_call_arity_findings(
                            node, spec_funcs, sig_cache, rel))
    return findings


# any os.environ get/subscript/setdefault or os.getenv whose string key
# carries the CST_ prefix, matched against whole-file text so reads
# wrapped across lines still register.  Internal knobs (leading
# underscore, e.g. _CST_DRYRUN_SUBPROCESS) are exempt by the prefix
# anchor.
_ENV_READ_RE = re.compile(
    r"""(?:environ(?:\.get|\.setdefault)?\s*[\(\[]|getenv\s*\()"""
    r"""\s*['"](CST_[A-Z0-9_]+)""")
_SKIP_DIRS = {"__pycache__", ".git", ".jax_cache", ".pytest_cache",
              "out", ".claude", ".venv", "venv", ".eggs",
              "site-packages", "node_modules"}


def lint_env_knobs(repo=None) -> list[str]:
    """Every `CST_*` env read in the tree needs a row in README.md's
    knob table, and every row needs a surviving read.  Benchwatch knobs
    (`CST_BENCHWATCH_*`) additionally need a mention in the README's
    "Benchwatch" section, serving knobs (`CST_SERVE_*`) in the
    "Serving" section, incremental-merkleization knobs
    (`CST_MERKLE_*`) in the "Incremental merkleization" section,
    monitoring knobs (`CST_METRICS_*`, `CST_SLO_*`,
    `CST_PROFILE_ON_BREACH`) in the "Monitoring" section,
    occupancy knobs (`CST_OCCUPANCY`) in the "Pipeline occupancy"
    section, flight-recorder knobs (`CST_FLIGHTREC*`) in the
    "Flight recorder" section,
    fault-plan knobs (`CST_FAULTS*`) in the "Resilience" section,
    checkpoint knobs (`CST_CHECKPOINT_*`) in the "Mesh resilience &
    checkpointing" section, mesh-sharding knobs (`CST_SHARD_*`) in
    the "Mesh sharding" section, DAS knobs (`CST_DAS_*`) in the
    "DAS / PeerDAS" section, and fork-choice knobs (`CST_FC_*`) in
    the "Fork choice" section — a subsystem's configuration surface
    must be documented where the subsystem is explained, not only in
    the flat table.  `repo` overrides the tree root (tests)."""
    repo = Path(repo) if repo is not None else PKG_ROOT.parent
    readme = repo / "README.md"
    readme_text = readme.read_text()
    documented = set(re.findall(r"\|\s*`(CST_[A-Z0-9_]+)`", readme_text))

    def section(title: str) -> str:
        m = re.search(rf"^## {title}$(.*?)(?=^## |\Z)", readme_text,
                      re.M | re.S)
        return m.group(1) if m else ""

    sectioned_prefixes = (("CST_BENCHWATCH_", "Benchwatch",
                           section("Benchwatch")),
                          ("CST_SERVE_", "Serving", section("Serving")),
                          ("CST_MERKLE_", "Incremental merkleization",
                           section("Incremental merkleization")),
                          ("CST_METRICS_", "Monitoring",
                           section("Monitoring")),
                          ("CST_SLO_", "Monitoring",
                           section("Monitoring")),
                          ("CST_PROFILE_ON_BREACH", "Monitoring",
                           section("Monitoring")),
                          ("CST_OCCUPANCY", "Pipeline occupancy",
                           section("Pipeline occupancy")),
                          ("CST_FLIGHTREC", "Flight recorder",
                           section("Flight recorder")),
                          ("CST_FAULTS", "Resilience",
                           section("Resilience")),
                          ("CST_CHECKPOINT_",
                           "Mesh resilience & checkpointing",
                           section(re.escape(
                               "Mesh resilience & checkpointing"))),
                          ("CST_SHARD_", "Mesh sharding",
                           section("Mesh sharding")),
                          ("CST_DAS_", "DAS / PeerDAS",
                           section(re.escape("DAS / PeerDAS"))),
                          ("CST_FC_", "Fork choice",
                           section("Fork choice")))

    used: dict[str, str] = {}
    for path in sorted(repo.rglob("*.py")):
        if any(part in _SKIP_DIRS for part in path.parts):
            continue
        rel = str(path.relative_to(repo))
        try:
            text = path.read_text()
        except (OSError, UnicodeDecodeError):
            continue    # unreadable stray file — not ours to lint
        for m in _ENV_READ_RE.finditer(text):
            line = text.count("\n", 0, m.start()) + 1
            used.setdefault(m.group(1), f"{rel}:{line}")

    findings = []
    for name in sorted(set(used) - documented):
        findings.append(
            f"{used[name]}: env knob '{name}' read but not documented "
            f"in README.md's Environment knobs table")
    for name in sorted(documented - set(used)):
        findings.append(
            f"README.md: env knob '{name}' documented but never read "
            f"in the tree (stale table row?)")
    for name in sorted(set(used)):
        # a mention may carry an example value: `CST_BENCHWATCH_STRICT=1`
        for prefix, title, text in sectioned_prefixes:
            if name.startswith(prefix) and not re.search(
                    rf"`{name}(?:=[^`]*)?`", text):
                findings.append(
                    f"{used[name]}: {title.lower()} knob '{name}' must "
                    f"also be documented in README.md's \"## {title}\" "
                    f"section")
    return findings


def main(argv=None) -> int:
    presets = ("minimal", "mainnet")
    total = 0
    # ONE dedup set for every finding source: overlapping fork chains
    # re-surface the same spec findings, and repeated runs of the env
    # pass must not double-print either (they used to bypass `seen`)
    seen: set[str] = set()

    def emit(finding: str) -> None:
        nonlocal total
        if finding not in seen:
            seen.add(finding)
            print(finding)
            total += 1

    for fork in BUILDABLE_FORKS:
        for preset in presets:
            for finding in lint_spec(fork, preset):
                emit(finding)
    for finding in lint_env_knobs():
        emit(finding)
    if total:
        print(f"spec lint: {total} finding(s)", file=sys.stderr)
        return 1
    print(f"spec lint: {len(BUILDABLE_FORKS) * len(presets)} "
          "spec builds clean (undefined-name + config-attribute + "
          "call-arity checks); env-knob table in sync")
    return 0


if __name__ == "__main__":
    sys.exit(main())
