"""Deposit construction with real Merkle branches
(mirrors `test/helpers/deposits.py`)."""

from __future__ import annotations

from ...ops import bls
from ...utils.merkle_minimal import (
    calc_merkle_tree_from_leaves,
    get_merkle_proof,
)
from ..utils import expect_assertion_error
from .keys import privkeys, pubkey


def build_deposit_data(spec, pk, privkey_int, amount,
                       withdrawal_credentials, signed=False):
    deposit_data = spec.DepositData(
        pubkey=pk,
        withdrawal_credentials=withdrawal_credentials,
        amount=amount,
    )
    if signed:
        sign_deposit_data(spec, deposit_data, privkey_int)
    return deposit_data


def sign_deposit_data(spec, deposit_data, privkey_int):
    deposit_message = spec.DepositMessage(
        pubkey=deposit_data.pubkey,
        withdrawal_credentials=deposit_data.withdrawal_credentials,
        amount=deposit_data.amount,
    )
    domain = spec.compute_domain(spec.DOMAIN_DEPOSIT)
    signing_root = spec.compute_signing_root(deposit_message, domain)
    deposit_data.signature = bls.Sign(privkey_int, signing_root)


def build_deposit(spec, deposit_data_list, pk, privkey_int, amount,
                  withdrawal_credentials, signed):
    deposit_data = build_deposit_data(
        spec, pk, privkey_int, amount, withdrawal_credentials, signed)
    index = len(deposit_data_list)
    deposit_data_list.append(deposit_data)
    return deposit_from_context(spec, deposit_data_list, index)


def deposit_from_context(spec, deposit_data_list, index):
    deposit_data = deposit_data_list[index]
    root = spec.hash_tree_root(
        spec.List[spec.DepositData, 2**spec.DEPOSIT_CONTRACT_TREE_DEPTH](
            deposit_data_list))
    tree = calc_merkle_tree_from_leaves(
        [spec.hash_tree_root(d) for d in deposit_data_list],
        spec.DEPOSIT_CONTRACT_TREE_DEPTH)
    proof = (get_merkle_proof(tree, item_index=index,
                              tree_len=spec.DEPOSIT_CONTRACT_TREE_DEPTH)
             + [len(deposit_data_list).to_bytes(32, "little")])
    leaf = spec.hash_tree_root(deposit_data)
    assert spec.is_valid_merkle_branch(
        leaf, proof, spec.DEPOSIT_CONTRACT_TREE_DEPTH + 1, index, root)
    deposit = spec.Deposit(proof=proof, data=deposit_data)
    return deposit, root, deposit_data_list


def prepare_full_genesis_deposits(spec, amount, deposit_count,
                                  min_pubkey_index=0, signed=False,
                                  deposit_data_list=None):
    """`deposit_count` uniform deposits for consecutive pubkeys
    (mirrors `helpers/deposits.py prepare_full_genesis_deposits`)."""
    if deposit_data_list is None:
        deposit_data_list = []
    genesis_deposits = []
    root = None
    for pubkey_index in range(min_pubkey_index,
                              min_pubkey_index + deposit_count):
        pk = pubkey(pubkey_index)
        withdrawal_credentials = (
            bytes(spec.BLS_WITHDRAWAL_PREFIX) + spec.hash(pk)[1:])
        deposit, root, deposit_data_list = build_deposit(
            spec, deposit_data_list, pk, privkeys[pubkey_index], amount,
            withdrawal_credentials, signed)
        genesis_deposits.append(deposit)
    return genesis_deposits, root, deposit_data_list


def prepare_random_genesis_deposits(spec, deposit_count, max_pubkey_index,
                                    min_pubkey_index=0, max_amount=None,
                                    min_amount=None, deposit_data_list=None,
                                    rng=None):
    """Random-amount deposits over a random pubkey range (mirrors
    `helpers/deposits.py prepare_random_genesis_deposits`)."""
    import random as _random

    rng = rng or _random.Random(3131)
    if max_amount is None:
        max_amount = int(spec.MAX_EFFECTIVE_BALANCE)
    if min_amount is None:
        min_amount = int(spec.MIN_DEPOSIT_AMOUNT)
    if deposit_data_list is None:
        deposit_data_list = []
    deposits = []
    root = None
    for _ in range(deposit_count):
        pubkey_index = rng.randint(min_pubkey_index, max_pubkey_index)
        amount = rng.randint(min_amount, max_amount)
        random_byte = bytes([rng.randint(0, 255)])
        withdrawal_credentials = (
            bytes(spec.BLS_WITHDRAWAL_PREFIX) + spec.hash(random_byte)[1:])
        deposit, root, deposit_data_list = build_deposit(
            spec, deposit_data_list, pubkey(pubkey_index),
            privkeys[pubkey_index], amount, withdrawal_credentials,
            signed=True)
        deposits.append(deposit)
    return deposits, root, deposit_data_list


def prepare_state_and_deposit(spec, state, validator_index, amount,
                              withdrawal_credentials=None, signed=False):
    """Prepare state for a deposit for validator_index (new or top-up),
    returning the deposit object."""
    deposit_data_list = []
    pk = pubkey(validator_index)
    privkey_int = privkeys[validator_index]
    if withdrawal_credentials is None:
        withdrawal_credentials = (
            bytes(spec.BLS_WITHDRAWAL_PREFIX) + spec.hash(pk)[1:])
    deposit, root, deposit_data_list = build_deposit(
        spec, deposit_data_list, pk, privkey_int, amount,
        withdrawal_credentials, signed)
    state.eth1_deposit_index = 0
    state.eth1_data.deposit_root = root
    state.eth1_data.deposit_count = len(deposit_data_list)
    return deposit


def run_deposit_processing(spec, state, deposit, validator_index,
                           valid=True, effective=True):
    """Yield-protocol runner (mirrors `helpers/deposits.py`
    `run_deposit_processing`)."""
    pre_validator_count = len(state.validators)
    pre_balance = 0
    is_top_up = validator_index < pre_validator_count
    if is_top_up:
        pre_balance = state.balances[validator_index]
        pre_effective_balance = \
            state.validators[validator_index].effective_balance

    yield "pre", state
    yield "deposit", deposit

    if not valid:
        expect_assertion_error(lambda: spec.process_deposit(state, deposit))
        yield "post", None
        return

    from .forks import is_post_electra

    pre_pending = (len(state.pending_deposits) if is_post_electra(spec)
                   else 0)

    spec.process_deposit(state, deposit)

    yield "post", state

    if is_post_electra(spec):
        # electra queues the balance as a pending deposit; it is applied
        # at the epoch transition, not here
        if not effective or not bls.KeyValidate(deposit.data.pubkey):
            assert len(state.validators) == pre_validator_count
            assert len(state.pending_deposits) == pre_pending
        else:
            if is_top_up:
                assert len(state.validators) == pre_validator_count
            else:
                # new validator joins with zero balance
                assert len(state.validators) == pre_validator_count + 1
                assert state.balances[validator_index] == 0
                assert (state.validators[validator_index].effective_balance
                        == 0)
            assert len(state.pending_deposits) == pre_pending + 1
            pd = state.pending_deposits[pre_pending]
            assert pd.amount == deposit.data.amount
            assert pd.pubkey == deposit.data.pubkey
        if is_top_up:
            assert state.balances[validator_index] == pre_balance
    elif not effective or not bls.KeyValidate(deposit.data.pubkey):
        assert len(state.validators) == pre_validator_count
        assert len(state.balances) == pre_validator_count
        if is_top_up:
            assert state.balances[validator_index] == pre_balance
    else:
        if is_top_up:
            # Top-ups do not change effective balance
            assert (state.validators[validator_index].effective_balance
                    == pre_effective_balance)
            assert len(state.validators) == pre_validator_count
            assert len(state.balances) == pre_validator_count
        else:
            # new validator
            assert len(state.validators) == pre_validator_count + 1
            assert len(state.balances) == pre_validator_count + 1
        assert (state.balances[validator_index]
                == pre_balance + deposit.data.amount)
    assert state.eth1_deposit_index == state.eth1_data.deposit_count


def mock_deposit(spec, state, index):
    """Flip an active validator back to just-deposited (not yet eligible),
    used by the randomized-state machinery (`helpers/deposits.py:18`)."""
    from .forks import is_post_altair

    assert spec.is_active_validator(state.validators[index],
                                    spec.get_current_epoch(state))
    state.validators[index].activation_eligibility_epoch = \
        spec.FAR_FUTURE_EPOCH
    state.validators[index].activation_epoch = spec.FAR_FUTURE_EPOCH
    state.validators[index].effective_balance = spec.MAX_EFFECTIVE_BALANCE
    if is_post_altair(spec):
        state.inactivity_scores[index] = 0
    assert not spec.is_active_validator(state.validators[index],
                                        spec.get_current_epoch(state))
