"""Block construction + signing (mirrors `test/helpers/block.py`)."""

from __future__ import annotations

from .keys import privkeys


def get_parent_root(spec, state):
    """Root of the current head header, patching the pre-sealed state root
    the way the next `process_slot` would."""
    header = state.latest_block_header.copy()
    if header.state_root == spec.Root():
        header.state_root = spec.hash_tree_root(state)
    return spec.hash_tree_root(header)


def get_state_at_slot(spec, state, slot):
    if state.slot < slot:
        state = state.copy()
        spec.process_slots(state, slot)
    return state


def build_empty_block(spec, state, slot=None, proposer_index=None):
    if slot is None:
        slot = state.slot
    assert slot >= state.slot
    state_at = get_state_at_slot(spec, state, slot)
    if proposer_index is None:
        proposer_index = spec.get_beacon_proposer_index(state_at)

    block = spec.BeaconBlock(
        slot=slot,
        proposer_index=proposer_index,
        parent_root=get_parent_root(spec, state_at),
        body=spec.BeaconBlockBody(
            randao_reveal=get_randao_reveal(spec, state_at, proposer_index),
            eth1_data=spec.Eth1Data(
                deposit_root=state_at.eth1_data.deposit_root,
                deposit_count=state_at.eth1_deposit_index,
                block_hash=state_at.eth1_data.block_hash,
            ),
        ),
    )
    from .forks import is_post_altair, is_post_bellatrix

    if is_post_altair(spec):
        # An empty sync aggregate (no participants) carries the point at
        # infinity, which eth_fast_aggregate_verify accepts
        block.body.sync_aggregate.sync_committee_signature = (
            spec.G2_POINT_AT_INFINITY)
    from .forks import is_post_eip7732

    if is_post_eip7732(spec):
        from .execution_payload import (
            build_empty_signed_execution_payload_header,
        )

        block.body.signed_execution_payload_header = (
            build_empty_signed_execution_payload_header(spec, state_at))
        return block

    if is_post_bellatrix(spec):
        from .execution_payload import build_empty_execution_payload

        block.body.execution_payload = build_empty_execution_payload(
            spec, state_at)
    return block


def build_empty_block_for_next_slot(spec, state, proposer_index=None):
    return build_empty_block(spec, state, state.slot + 1, proposer_index)


def get_randao_reveal(spec, state, proposer_index):
    from ...ops import bls

    epoch = spec.compute_epoch_at_slot(state.slot)
    domain = spec.get_domain(state, spec.DOMAIN_RANDAO, epoch)
    signing_root = spec.compute_signing_root(spec.uint64(epoch), domain)
    return bls.Sign(privkeys[proposer_index], signing_root)


def sign_block(spec, state, block, proposer_index=None):
    from ...ops import bls

    if proposer_index is None:
        proposer_index = block.proposer_index
    domain = spec.get_domain(state, spec.DOMAIN_BEACON_PROPOSER,
                             spec.compute_epoch_at_slot(block.slot))
    signing_root = spec.compute_signing_root(block, domain)
    return spec.SignedBeaconBlock(
        message=block,
        signature=bls.Sign(privkeys[proposer_index], signing_root),
    )


def transition_unsigned_block(spec, state, block):
    assert state.slot < block.slot or state.slot == block.slot
    if state.slot < block.slot:
        spec.process_slots(state, block.slot)
    spec.process_block(state, block)
    return block


def apply_empty_block(spec, state, slot=None):
    """Advance via an empty block (signed), returning the signed block."""
    from .state import state_transition_and_sign_block

    block = build_empty_block(spec, state, slot)
    return state_transition_and_sign_block(spec, state, block)


def sign_indexed_attestation(spec, state, indexed_attestation):
    from ...ops import bls

    participants = indexed_attestation.attesting_indices
    data = indexed_attestation.data
    domain = spec.get_domain(state, spec.DOMAIN_BEACON_ATTESTER,
                             data.target.epoch)
    signing_root = spec.compute_signing_root(data, domain)
    sigs = [bls.Sign(privkeys[p], signing_root) for p in participants]
    indexed_attestation.signature = bls.Aggregate(sigs) if sigs else \
        spec.BLSSignature()
