"""Rewards-deltas harness: run every per-component delta function and
check each validator's reward/penalty against its participation and
eligibility (the reference's `test/helpers/rewards.py:27-545`).  The same
scenario runners feed pytest assertions and the rewards vector suite.

No `from __future__ import annotations` here: the Deltas container's field
annotations must stay live types for the SSZ engine's fields()."""

from random import Random

from ...utils.ssz.types import Container, List, uint64
from .attestations import cached_prepare_state_with_attestations
from .forks import is_post_altair, is_post_bellatrix
from .random import (
    exit_random_validators,
    randomize_state,
    set_some_new_deposits,
    slash_random_validators,
)
from .state import next_epoch

VALIDATOR_REGISTRY_LIMIT = 2**40


class Deltas(Container):
    rewards: List[uint64, VALIDATOR_REGISTRY_LIMIT]
    penalties: List[uint64, VALIDATOR_REGISTRY_LIMIT]


def get_inactivity_penalty_quotient(spec):
    if is_post_bellatrix(spec):
        return spec.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX
    if is_post_altair(spec):
        return spec.INACTIVITY_PENALTY_QUOTIENT_ALTAIR
    return spec.INACTIVITY_PENALTY_QUOTIENT


def has_enough_for_reward(spec, state, index):
    """Zero-balance edge: positive effective balance can still round the
    base reward down to zero."""
    return (
        state.validators[index].effective_balance * spec.BASE_REWARD_FACTOR
        > spec.integer_squareroot(spec.get_total_active_balance(state))
        // spec.BASE_REWARDS_PER_EPOCH
    )


def has_enough_for_leak_penalty(spec, state, index):
    if is_post_altair(spec):
        return (state.validators[index].effective_balance
                * state.inactivity_scores[index]
                > spec.config.INACTIVITY_SCORE_BIAS
                * get_inactivity_penalty_quotient(spec))
    return (state.validators[index].effective_balance
            * spec.get_finality_delay(state)
            > spec.INACTIVITY_PENALTY_QUOTIENT)


def deltas_name_to_flag_index(spec, deltas_name):
    if "source" in deltas_name:
        return spec.TIMELY_SOURCE_FLAG_INDEX
    if "head" in deltas_name:
        return spec.TIMELY_HEAD_FLAG_INDEX
    if "target" in deltas_name:
        return spec.TIMELY_TARGET_FLAG_INDEX
    raise ValueError(f"unknown deltas name {deltas_name}")


def run_deltas(spec, state):
    """Yield pre + one Deltas part per reward component, asserting every
    validator's deltas along the way."""
    yield "pre", state

    if is_post_altair(spec):
        def get_source_deltas(state):
            return spec.get_flag_index_deltas(
                state, spec.TIMELY_SOURCE_FLAG_INDEX)

        def get_target_deltas(state):
            return spec.get_flag_index_deltas(
                state, spec.TIMELY_TARGET_FLAG_INDEX)

        def get_head_deltas(state):
            return spec.get_flag_index_deltas(
                state, spec.TIMELY_HEAD_FLAG_INDEX)
    else:
        get_source_deltas = spec.get_source_deltas
        get_target_deltas = spec.get_target_deltas
        get_head_deltas = spec.get_head_deltas

    yield from run_attestation_component_deltas(
        spec, state, get_source_deltas,
        spec.get_matching_source_attestations, "source_deltas")
    yield from run_attestation_component_deltas(
        spec, state, get_target_deltas,
        spec.get_matching_target_attestations, "target_deltas")
    yield from run_attestation_component_deltas(
        spec, state, get_head_deltas,
        spec.get_matching_head_attestations, "head_deltas")
    if not is_post_altair(spec):
        yield from run_get_inclusion_delay_deltas(spec, state)
    yield from run_get_inactivity_penalty_deltas(spec, state)


def run_attestation_component_deltas(spec, state, component_delta_fn,
                                     matching_att_fn, deltas_name):
    rewards, penalties = component_delta_fn(state)
    yield deltas_name, Deltas(rewards=rewards, penalties=penalties)

    if is_post_altair(spec):
        matching_indices = spec.get_unslashed_participating_indices(
            state, deltas_name_to_flag_index(spec, deltas_name),
            spec.get_previous_epoch(state))
    else:
        matching_attestations = matching_att_fn(
            state, spec.get_previous_epoch(state))
        matching_indices = spec.get_unslashed_attesting_indices(
            state, matching_attestations)

    eligible_indices = spec.get_eligible_validator_indices(state)
    for index in range(len(state.validators)):
        if index not in eligible_indices:
            assert rewards[index] == 0
            assert penalties[index] == 0
            continue

        validator = state.validators[index]
        enough_for_reward = has_enough_for_reward(spec, state, index)
        if index in matching_indices and not validator.slashed:
            if is_post_altair(spec):
                if (not spec.is_in_inactivity_leak(state)
                        and enough_for_reward):
                    assert rewards[index] > 0
                else:
                    assert rewards[index] == 0
            elif enough_for_reward:
                assert rewards[index] > 0
            else:
                assert rewards[index] == 0
            assert penalties[index] == 0
        else:
            assert rewards[index] == 0
            if is_post_altair(spec) and "head" in deltas_name:
                assert penalties[index] == 0  # no head penalty post-altair
            elif enough_for_reward:
                assert penalties[index] > 0
            else:
                assert penalties[index] == 0


def run_get_inclusion_delay_deltas(spec, state):
    if is_post_altair(spec):
        yield ("inclusion_delay_deltas",
               Deltas(rewards=[0] * len(state.validators),
                      penalties=[0] * len(state.validators)))
        return

    rewards, penalties = spec.get_inclusion_delay_deltas(state)
    yield ("inclusion_delay_deltas",
           Deltas(rewards=rewards, penalties=penalties))

    eligible_attestations = spec.get_matching_source_attestations(
        state, spec.get_previous_epoch(state))
    attesting_indices = spec.get_unslashed_attesting_indices(
        state, eligible_attestations)

    rewarded_indices = set()
    rewarded_proposer_indices = set()
    for index in range(len(state.validators)):
        if (index in attesting_indices
                and has_enough_for_reward(spec, state, index)):
            assert rewards[index] > 0
            rewarded_indices.add(index)
            # earliest inclusion's proposer earns the proposer cut
            earliest = min(
                (a for a in eligible_attestations
                 if index in spec.get_attesting_indices(state, a)),
                key=lambda a: a.inclusion_delay)
            rewarded_proposer_indices.add(earliest.proposer_index)

    for index in (a.proposer_index for a in eligible_attestations):
        if index in rewarded_proposer_indices:
            assert rewards[index] > 0
            rewarded_indices.add(index)

    for index in range(len(state.validators)):
        assert penalties[index] == 0
        if index not in rewarded_indices:
            assert rewards[index] == 0


def run_get_inactivity_penalty_deltas(spec, state):
    rewards, penalties = spec.get_inactivity_penalty_deltas(state)
    yield ("inactivity_penalty_deltas",
           Deltas(rewards=rewards, penalties=penalties))

    if is_post_altair(spec):
        matching_attesting_indices = \
            spec.get_unslashed_participating_indices(
                state, spec.TIMELY_TARGET_FLAG_INDEX,
                spec.get_previous_epoch(state))
    else:
        matching_attestations = spec.get_matching_target_attestations(
            state, spec.get_previous_epoch(state))
        matching_attesting_indices = spec.get_unslashed_attesting_indices(
            state, matching_attestations)

    eligible_indices = spec.get_eligible_validator_indices(state)
    for index in range(len(state.validators)):
        assert rewards[index] == 0
        if index not in eligible_indices:
            assert penalties[index] == 0
            continue

        if spec.is_in_inactivity_leak(state):
            if not is_post_altair(spec):
                base_reward = spec.get_base_reward(state, index)
                base_penalty = (spec.BASE_REWARDS_PER_EPOCH * base_reward
                                - spec.get_proposer_reward(state, index))
            if not has_enough_for_reward(spec, state, index):
                assert penalties[index] == 0
            elif (index in matching_attesting_indices
                  or not has_enough_for_leak_penalty(spec, state, index)):
                if is_post_altair(spec):
                    assert penalties[index] == 0
                else:
                    assert penalties[index] == base_penalty
            elif is_post_altair(spec):
                assert penalties[index] > 0
            else:
                assert penalties[index] > base_penalty
        elif not is_post_altair(spec):
            assert penalties[index] == 0
        # post-altair the penalty tracks the inactivity score, leak or not
        elif index in matching_attesting_indices:
            assert penalties[index] == 0
        else:
            penalty_numerator = (state.validators[index].effective_balance
                                 * state.inactivity_scores[index])
            penalty_denominator = (spec.config.INACTIVITY_SCORE_BIAS
                                   * get_inactivity_penalty_quotient(spec))
            assert penalties[index] == \
                penalty_numerator // penalty_denominator


def transition_state_to_leak(spec, state, epochs=None):
    if epochs is None:
        epochs = spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY + 2
    assert epochs > spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY
    for _ in range(epochs):
        next_epoch(spec, state)
    assert spec.is_in_inactivity_leak(state)


_leak_cache: dict = {}


def leaking(epochs=None):
    """Decorator: hand the test a leaked version of its state (cached per
    pre-state root)."""
    def deco(fn):
        def entry(*args, spec, state, **kw):
            key = (state.hash_tree_root(),
                   spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY,
                   spec.SLOTS_PER_EPOCH, epochs)
            if key not in _leak_cache:
                leaked = state.copy()
                transition_state_to_leak(spec, leaked, epochs=epochs)
                _leak_cache[key] = leaked
            return fn(*args, spec=spec, state=_leak_cache[key].copy(), **kw)
        return entry
    return deco


# -- scenario runners --------------------------------------------------------


def run_test_empty(spec, state):
    yield from run_deltas(spec, state)


def run_test_full_all_correct(spec, state):
    cached_prepare_state_with_attestations(spec, state)
    yield from run_deltas(spec, state)


def run_test_full_but_partial_participation(spec, state, rng=None):
    rng = rng or Random(5522)
    cached_prepare_state_with_attestations(spec, state)
    if not is_post_altair(spec):
        for a in state.previous_epoch_attestations:
            a.aggregation_bits = type(a.aggregation_bits)(
                [rng.choice([True, False]) for _ in a.aggregation_bits])
    else:
        for index in range(len(state.validators)):
            if rng.choice([True, False]):
                state.previous_epoch_participation[index] = \
                    spec.ParticipationFlags(0)
    yield from run_deltas(spec, state)


def run_test_partial(spec, state, fraction_filled):
    cached_prepare_state_with_attestations(spec, state)
    if not is_post_altair(spec):
        num_attestations = int(len(state.previous_epoch_attestations)
                               * fraction_filled)
        state.previous_epoch_attestations = \
            state.previous_epoch_attestations[:num_attestations]
    else:
        # keep `fraction_filled` participating (mirror the phase0 branch)
        n_keep = int(len(state.validators) * fraction_filled)
        for index in range(n_keep, len(state.validators)):
            state.previous_epoch_participation[index] = \
                spec.ParticipationFlags(0)
    yield from run_deltas(spec, state)


def run_test_half_full(spec, state):
    yield from run_test_partial(spec, state, 0.5)


def run_test_one_attestation_one_correct(spec, state):
    cached_prepare_state_with_attestations(spec, state)
    if not is_post_altair(spec):
        state.previous_epoch_attestations = \
            state.previous_epoch_attestations[:1]
    else:
        # a single fully-correct participant
        for index in range(1, len(state.validators)):
            state.previous_epoch_participation[index] = \
                spec.ParticipationFlags(0)
    yield from run_deltas(spec, state)


def run_test_with_not_yet_activated_validators(spec, state, rng=None):
    rng = rng or Random(5555)
    set_some_new_deposits(spec, state, rng)
    cached_prepare_state_with_attestations(spec, state)
    yield from run_deltas(spec, state)


def run_test_with_exited_validators(spec, state, rng=None):
    rng = rng or Random(1337)
    exit_random_validators(spec, state, rng)
    cached_prepare_state_with_attestations(spec, state)
    yield from run_deltas(spec, state)


def run_test_with_slashed_validators(spec, state, rng=None):
    rng = rng or Random(3322)
    exit_random_validators(spec, state, rng)
    slash_random_validators(spec, state, rng)
    cached_prepare_state_with_attestations(spec, state)
    yield from run_deltas(spec, state)


def run_test_some_very_low_effective_balances_that_attested(spec, state):
    cached_prepare_state_with_attestations(spec, state)
    assert len(state.validators) >= 5
    for i, index in enumerate(range(5)):
        state.validators[index].effective_balance = i
    yield from run_deltas(spec, state)


def run_test_some_very_low_effective_balances_that_did_not_attest(
        spec, state):
    cached_prepare_state_with_attestations(spec, state)
    if not is_post_altair(spec):
        attestation = state.previous_epoch_attestations[0]
        state.previous_epoch_attestations = \
            state.previous_epoch_attestations[1:]
        indices = spec.get_unslashed_attesting_indices(state, [attestation])
        for i, index in enumerate(indices):
            state.validators[index].effective_balance = i
    else:
        state.validators[0].effective_balance = 1
        state.previous_epoch_participation[0] = spec.ParticipationFlags(0)
    yield from run_deltas(spec, state)


def run_test_full_fraction_incorrect(spec, state, correct_target,
                                     correct_head, fraction_incorrect):
    cached_prepare_state_with_attestations(spec, state)
    if not is_post_altair(spec):
        num_incorrect = int(fraction_incorrect
                            * len(state.previous_epoch_attestations))
        for pending in state.previous_epoch_attestations[:num_incorrect]:
            if not correct_target:
                pending.data.target.root = b"\x55" * 32
            if not correct_head:
                pending.data.beacon_block_root = b"\x66" * 32
    else:
        # clear the corresponding flags for the chosen fraction
        num_incorrect = int(fraction_incorrect * len(state.validators))
        for index in range(num_incorrect):
            flags = state.previous_epoch_participation[index]
            if not correct_target:
                flags &= ~spec.ParticipationFlags(
                    1 << spec.TIMELY_TARGET_FLAG_INDEX)
            if not correct_head:
                flags &= ~spec.ParticipationFlags(
                    1 << spec.TIMELY_HEAD_FLAG_INDEX)
            state.previous_epoch_participation[index] = flags
    yield from run_deltas(spec, state)


def run_test_full_delay_one_slot(spec, state):
    cached_prepare_state_with_attestations(spec, state)
    for a in state.previous_epoch_attestations:
        a.inclusion_delay += 1
    yield from run_deltas(spec, state)


def run_test_full_delay_max_slots(spec, state):
    cached_prepare_state_with_attestations(spec, state)
    for a in state.previous_epoch_attestations:
        a.inclusion_delay += spec.SLOTS_PER_EPOCH
    yield from run_deltas(spec, state)


def run_test_full_mixed_delay(spec, state, rng=None):
    rng = rng or Random(1234)
    cached_prepare_state_with_attestations(spec, state)
    for a in state.previous_epoch_attestations:
        a.inclusion_delay = rng.randint(1, spec.SLOTS_PER_EPOCH)
    yield from run_deltas(spec, state)


def run_test_all_balances_too_low_for_reward(spec, state):
    cached_prepare_state_with_attestations(spec, state)
    for index in range(len(state.validators)):
        state.validators[index].effective_balance = 10
    yield from run_deltas(spec, state)


def run_test_full_random(spec, state, rng=None):
    rng = rng or Random(8020)
    randomize_state(spec, state, rng)
    yield from run_deltas(spec, state)
