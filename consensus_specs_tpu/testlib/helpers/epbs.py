"""EIP-7732 (ePBS) test construction: payload envelopes and payload
attestations (no reference corpus exists; shapes follow
specs/_features/eip7732/beacon-chain.md and builder.md)."""

from __future__ import annotations

from ...ops import bls
from .keys import privkeys


def build_payload_envelope(spec, state, payload_withheld=False):
    """An envelope consistent with the committed bid in `state` (call
    after importing the block carrying the bid).  For a zero-value
    self-bid the payload is the empty-hash payload the bid committed
    to."""
    committed = state.latest_execution_payload_header

    payload = spec.ExecutionPayload(
        parent_hash=state.latest_block_hash,
        prev_randao=spec.get_randao_mix(state,
                                        spec.get_current_epoch(state)),
        gas_limit=committed.gas_limit,
        timestamp=spec.compute_time_at_slot(state, state.slot),
        block_hash=committed.block_hash,
    )
    # honor the withdrawals committed by process_withdrawals
    header = state.latest_block_header.copy()
    if header.state_root == spec.Root():
        header.state_root = spec.hash_tree_root(state)

    envelope = spec.ExecutionPayloadEnvelope(
        payload=payload,
        execution_requests=spec.ExecutionRequests(),
        builder_index=committed.builder_index,
        beacon_block_root=spec.hash_tree_root(header),
        blob_kzg_commitments=[],
        payload_withheld=payload_withheld,
        state_root=spec.Root(),
    )
    return envelope


def sign_payload_envelope(spec, state, envelope):
    privkey = privkeys[envelope.builder_index]
    signature = spec.get_execution_payload_envelope_signature(
        state, envelope, privkey)
    return spec.SignedExecutionPayloadEnvelope(
        message=envelope, signature=signature)


def run_envelope_processing(spec, state, signed_envelope, valid=True):
    """Apply `process_execution_payload`, filling the envelope's
    state_root with the correct post-root first (the builder's job)."""
    from ..utils import expect_assertion_error

    if not valid:
        expect_assertion_error(
            lambda: spec.process_execution_payload(
                state, signed_envelope, spec.EXECUTION_ENGINE))
        return

    # compute the post state root on a throwaway copy, then re-sign
    trial = state.copy()
    spec.process_execution_payload(trial, signed_envelope,
                                   spec.EXECUTION_ENGINE, verify=False)
    signed_envelope.message.state_root = spec.hash_tree_root(trial)
    signed_envelope = sign_payload_envelope(
        spec, state, signed_envelope.message)
    spec.process_execution_payload(state, signed_envelope,
                                   spec.EXECUTION_ENGINE)
    return signed_envelope


def make_payload_attestation(spec, state, payload_status,
                             beacon_block_root=None, slot=None,
                             participation=None):
    """A PTC attestation for the previous slot's payload status, signed
    by every participating committee member."""
    if slot is None:
        slot = spec.Slot(state.slot - 1)
    if beacon_block_root is None:
        beacon_block_root = state.latest_block_header.parent_root
    data = spec.PayloadAttestationData(
        beacon_block_root=beacon_block_root,
        slot=slot,
        payload_status=payload_status,
    )
    ptc = spec.get_ptc(state, slot)
    if participation is None:
        participation = [True] * len(ptc)
    attestation = spec.PayloadAttestation(data=data)
    sigs = []
    domain = spec.get_domain(state, spec.DOMAIN_PTC_ATTESTER, None)
    signing_root = spec.compute_signing_root(data, domain)
    for i, member in enumerate(ptc):
        if participation[i]:
            attestation.aggregation_bits[i] = True
            sigs.append(bls.Sign(privkeys[member], signing_root))
    attestation.signature = bls.Aggregate(sigs) if sigs else \
        spec.BLSSignature()
    return attestation
