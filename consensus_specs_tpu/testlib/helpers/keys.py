"""Deterministic validator keypairs (mirrors `test/helpers/keys.py:3-6`).

privkey(i) = i + 1; pubkeys computed lazily (pure-Python scalar mult) and
memoized — with BLS disabled, deterministic stub pubkeys keep tests fast
while staying unique per validator.
"""

from __future__ import annotations

from ...ops import bls as bls_mod
from ...ops.bls import ciphersuite as _cs

_PUBKEY_CACHE: dict[int, bytes] = {}

# the reference materializes 32*256 keypairs (`helpers/keys.py:3-6`);
# negative indices must wrap over that pool like a real list's would
KEY_COUNT = 32 * 256


def privkey(index: int) -> int:
    if index < 0:
        index += KEY_COUNT
    assert 0 <= index < KEY_COUNT, f"key index {index} out of pool"
    return index + 1


class _Privkeys:
    def __getitem__(self, i):
        if isinstance(i, slice):
            return [privkey(j) for j in range(*i.indices(KEY_COUNT))]
        return privkey(int(i))

    def __len__(self):
        return KEY_COUNT


class _Pubkeys:
    def __getitem__(self, i):
        if isinstance(i, slice):
            return [pubkey(j) for j in range(*i.indices(KEY_COUNT))]
        return pubkey(int(i))

    def __len__(self):
        return KEY_COUNT


def pubkey(index: int) -> bytes:
    """Real BLS pubkey for validator `index` (memoized)."""
    pk = _PUBKEY_CACHE.get(index)
    if pk is None:
        pk = _cs.SkToPk(privkey(index))
        _PUBKEY_CACHE[index] = pk
    return pk


privkeys = _Privkeys()
pubkeys = _Pubkeys()


def pubkey_to_privkey(pk: bytes) -> int:
    for i, cached in _PUBKEY_CACHE.items():
        if cached == bytes(pk):
            return privkey(i)
    raise KeyError("unknown pubkey")
