"""Proposer-slashing construction + runner
(mirrors `test/helpers/proposer_slashings.py`)."""

from __future__ import annotations

from ...ops import bls
from ..utils import expect_assertion_error
from .keys import privkeys
from .state import get_balance


def check_proposer_slashing_effect(spec, pre_state, state, slashed_index):
    slashed_validator = state.validators[slashed_index]
    assert slashed_validator.slashed
    assert slashed_validator.exit_epoch < spec.FAR_FUTURE_EPOCH
    assert slashed_validator.withdrawable_epoch < spec.FAR_FUTURE_EPOCH

    proposer_index = spec.get_beacon_proposer_index(state)
    if proposer_index != slashed_index:
        # slashed validator lost whistleblower reward portions
        assert (get_balance(state, slashed_index)
                < get_balance(pre_state, slashed_index))
        assert (get_balance(state, proposer_index)
                > get_balance(pre_state, proposer_index))


def get_valid_proposer_slashing(spec, state, random_root=b"\x99" * 32,
                                slashed_index=None, slot=None,
                                signed_1=False, signed_2=False):
    if slashed_index is None:
        current_epoch = spec.get_current_epoch(state)
        slashed_index = spec.get_active_validator_indices(
            state, current_epoch)[-1]
    if slot is None:
        slot = state.slot

    header_1 = spec.BeaconBlockHeader(
        slot=slot,
        proposer_index=slashed_index,
        parent_root=b"\x33" * 32,
        state_root=b"\x44" * 32,
        body_root=b"\x55" * 32,
    )
    header_2 = header_1.copy()
    header_2.parent_root = random_root

    signed_header_1 = spec.SignedBeaconBlockHeader(message=header_1)
    signed_header_2 = spec.SignedBeaconBlockHeader(message=header_2)
    if signed_1:
        signed_header_1 = sign_block_header(
            spec, state, header_1, privkeys[slashed_index])
    if signed_2:
        signed_header_2 = sign_block_header(
            spec, state, header_2, privkeys[slashed_index])

    return spec.ProposerSlashing(
        signed_header_1=signed_header_1,
        signed_header_2=signed_header_2,
    )


def sign_block_header(spec, state, header, privkey_int):
    domain = spec.get_domain(state, spec.DOMAIN_BEACON_PROPOSER,
                             spec.compute_epoch_at_slot(header.slot))
    signing_root = spec.compute_signing_root(header, domain)
    signature = bls.Sign(privkey_int, signing_root)
    return spec.SignedBeaconBlockHeader(message=header, signature=signature)


def run_proposer_slashing_processing(spec, state, proposer_slashing,
                                     valid=True):
    pre_state = state.copy()

    yield "pre", state
    yield "proposer_slashing", proposer_slashing

    if not valid:
        expect_assertion_error(
            lambda: spec.process_proposer_slashing(state, proposer_slashing))
        yield "post", None
        return

    spec.process_proposer_slashing(state, proposer_slashing)
    yield "post", state

    slashed_index = proposer_slashing.signed_header_1.message.proposer_index
    check_proposer_slashing_effect(spec, pre_state, state, slashed_index)
