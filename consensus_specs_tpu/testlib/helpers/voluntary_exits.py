"""Voluntary-exit construction + runner
(mirrors `test/helpers/voluntary_exits.py`)."""

from __future__ import annotations

from ...ops import bls
from ..utils import expect_assertion_error
from .keys import privkeys


def prepare_signed_exits(spec, state, indices, fork_version=None):
    def create_signed_exit(index):
        voluntary_exit = spec.VoluntaryExit(
            epoch=spec.get_current_epoch(state),
            validator_index=index,
        )
        return sign_voluntary_exit(spec, state, voluntary_exit,
                                   privkeys[index], fork_version)
    return [create_signed_exit(index) for index in indices]


def sign_voluntary_exit(spec, state, voluntary_exit, privkey_int,
                        fork_version=None):
    from .forks import is_post_deneb

    if fork_version is not None:
        domain = spec.compute_domain(spec.DOMAIN_VOLUNTARY_EXIT,
                                     fork_version,
                                     state.genesis_validators_root)
    elif is_post_deneb(spec):
        # EIP-7044 locks exit signatures to the capella domain
        domain = spec.compute_domain(spec.DOMAIN_VOLUNTARY_EXIT,
                                     spec.config.CAPELLA_FORK_VERSION,
                                     state.genesis_validators_root)
    else:
        domain = spec.get_domain(state, spec.DOMAIN_VOLUNTARY_EXIT,
                                 voluntary_exit.epoch)
    signing_root = spec.compute_signing_root(voluntary_exit, domain)
    return spec.SignedVoluntaryExit(
        message=voluntary_exit,
        signature=bls.Sign(privkey_int, signing_root),
    )


def get_unslashed_exited_validators(spec, state):
    """Indices that exited (epoch <= current) without being slashed."""
    current_epoch = spec.get_current_epoch(state)
    return [
        index for index, validator in enumerate(state.validators)
        if not validator.slashed and validator.exit_epoch <= current_epoch
    ]


def exit_validators(spec, state, indices):
    """Force-exit `indices` immediately (no signed exits involved)."""
    current_epoch = spec.get_current_epoch(state)
    for index in indices:
        validator = state.validators[index]
        validator.exit_epoch = current_epoch
        validator.withdrawable_epoch = spec.Epoch(
            current_epoch + spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY)


def run_voluntary_exit_processing(spec, state, signed_voluntary_exit,
                                  valid=True):
    validator_index = signed_voluntary_exit.message.validator_index

    yield "pre", state
    yield "voluntary_exit", signed_voluntary_exit

    if not valid:
        expect_assertion_error(
            lambda: spec.process_voluntary_exit(state, signed_voluntary_exit))
        yield "post", None
        return

    pre_exit_epoch = state.validators[validator_index].exit_epoch

    spec.process_voluntary_exit(state, signed_voluntary_exit)

    yield "post", state

    assert pre_exit_epoch == spec.FAR_FUTURE_EPOCH
    assert state.validators[validator_index].exit_epoch < spec.FAR_FUTURE_EPOCH
