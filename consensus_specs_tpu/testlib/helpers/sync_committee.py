"""Sync-committee test helpers (mirrors `test/helpers/sync_committee.py`)."""

from __future__ import annotations

from ...ops import bls
from ..utils import expect_assertion_error
from .block import build_empty_block_for_next_slot
from .keys import privkeys, pubkey_to_privkey


def compute_sync_committee_signature(spec, state, slot, privkey_int,
                                     block_root=None, domain_type=None):
    """One member's signature over the block root at `slot`."""
    domain = spec.get_domain(state, domain_type or spec.DOMAIN_SYNC_COMMITTEE,
                             spec.compute_epoch_at_slot(slot))
    if block_root is None:
        if slot == state.slot:
            # head root of the in-progress slot: the next block's parent
            block_root = build_empty_block_for_next_slot(
                spec, state).parent_root
        else:
            block_root = spec.get_block_root_at_slot(state, slot)
    signing_root = spec.compute_signing_root(block_root, domain)
    return bls.Sign(privkey_int, signing_root)


def compute_aggregate_sync_committee_signature(spec, state, slot,
                                               participants,
                                               block_root=None):
    """Aggregate signature of `participants` (validator indices) over the
    block root at `slot`."""
    if len(participants) == 0:
        return spec.G2_POINT_AT_INFINITY

    signatures = []
    for validator_index in participants:
        privkey_int = privkeys[validator_index]
        signatures.append(compute_sync_committee_signature(
            spec, state, slot, privkey_int, block_root=block_root))
    return bls.Aggregate(signatures)


def compute_committee_indices(state, committee=None):
    """Validator registry indices of the sync committee members."""
    if committee is None:
        committee = state.current_sync_committee
    all_pubkeys = [v.pubkey for v in state.validators]
    return [all_pubkeys.index(pubkey) for pubkey in committee.pubkeys]


def get_sync_aggregate(spec, state, num_participants=None, signature_slot=None):
    """A valid SyncAggregate for the *current* state slot (signing the
    previous slot's block root), with the first `num_participants`
    members participating."""
    if signature_slot is None:
        signature_slot = state.slot
    previous_slot = max(int(signature_slot), 1) - 1
    committee_indices = compute_committee_indices(state)
    if num_participants is None:
        num_participants = len(committee_indices)
    assert 0 <= num_participants <= len(committee_indices)

    participants = committee_indices[:num_participants]
    bits = [i < num_participants for i in range(len(committee_indices))]
    signature = compute_aggregate_sync_committee_signature(
        spec, state, spec.Slot(previous_slot), participants,
        block_root=spec.get_block_root_at_slot(state, previous_slot))
    return spec.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=signature,
    )


def run_sync_committee_processing(spec, state, block, expect_exception=False):
    """Process the block's sync aggregate; yields the operation-test
    vector parts."""
    pre_state = state.copy()
    yield "pre", state
    yield "sync_aggregate", block.body.sync_aggregate
    if expect_exception:
        expect_assertion_error(
            lambda: spec.process_sync_aggregate(
                state, block.body.sync_aggregate))
        yield "post", None
    else:
        spec.process_sync_aggregate(state, block.body.sync_aggregate)
        yield "post", state
        validate_sync_committee_rewards(
            spec, pre_state, state,
            committee_indices=compute_committee_indices(pre_state),
            committee_bits=block.body.sync_aggregate.sync_committee_bits,
            proposer_index=spec.get_beacon_proposer_index(state))


def compute_sync_committee_participant_reward_and_penalty(
        spec, state, participant_index, committee_indices, committee_bits):
    """(reward, penalty) a member accrues in one process_sync_aggregate
    (mirrors `helpers/sync_committee.py` reward math)."""
    total_active_increments = (spec.get_total_active_balance(state)
                               // spec.EFFECTIVE_BALANCE_INCREMENT)
    total_base_rewards = (spec.get_base_reward_per_increment(state)
                          * total_active_increments)
    max_participant_rewards = (total_base_rewards * spec.SYNC_REWARD_WEIGHT
                               // spec.WEIGHT_DENOMINATOR
                               // spec.SLOTS_PER_EPOCH)
    participant_reward = max_participant_rewards // spec.SYNC_COMMITTEE_SIZE

    included = sum(1 for i, bit in zip(committee_indices, committee_bits)
                   if bit and i == participant_index)
    excluded = sum(1 for i, bit in zip(committee_indices, committee_bits)
                   if not bit and i == participant_index)
    return (spec.Gwei(included * participant_reward),
            spec.Gwei(excluded * participant_reward))


def compute_sync_committee_proposer_reward(spec, state, committee_indices,
                                           committee_bits):
    total_active_increments = (spec.get_total_active_balance(state)
                               // spec.EFFECTIVE_BALANCE_INCREMENT)
    total_base_rewards = (spec.get_base_reward_per_increment(state)
                          * total_active_increments)
    max_participant_rewards = (total_base_rewards * spec.SYNC_REWARD_WEIGHT
                               // spec.WEIGHT_DENOMINATOR
                               // spec.SLOTS_PER_EPOCH)
    participant_reward = max_participant_rewards // spec.SYNC_COMMITTEE_SIZE
    proposer_reward = (participant_reward * spec.PROPOSER_WEIGHT
                       // (spec.WEIGHT_DENOMINATOR - spec.PROPOSER_WEIGHT))
    return spec.Gwei(sum(bool(b) for b in committee_bits) * proposer_reward)


def validate_sync_committee_rewards(spec, pre_state, post_state,
                                    committee_indices, committee_bits,
                                    proposer_index):
    for index in range(len(post_state.validators)):
        reward = spec.Gwei(0)
        penalty = spec.Gwei(0)
        if index in committee_indices:
            r, p = compute_sync_committee_participant_reward_and_penalty(
                spec, pre_state, index, committee_indices, committee_bits)
            reward += r
            penalty += p
        if proposer_index == index:
            reward += compute_sync_committee_proposer_reward(
                spec, pre_state, committee_indices, committee_bits)
        assert (post_state.balances[index]
                == pre_state.balances[index] + reward - penalty)


def run_successful_sync_committee_test(spec, state, committee_indices,
                                       committee_bits):
    block = build_empty_block_for_next_slot(spec, state)
    # advance first: the committee signs the block root at `slot - 1`,
    # which is only in `state.block_roots` once the state is at `slot`
    spec.process_slots(state, block.slot)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=committee_bits,
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1,
            [i for i, bit in zip(committee_indices, committee_bits) if bit]),
    )
    yield from run_sync_committee_processing(spec, state, block)
