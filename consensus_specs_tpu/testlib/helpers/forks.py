"""Fork predicates (mirrors `test/helpers/forks.py`)."""

from __future__ import annotations

from ...models.builder import ALL_FORKS, PREVIOUS_FORK_OF


def is_post_fork(a: str, b: str) -> bool:
    """True if fork `a` is `b` or later."""
    f: str | None = a
    while f is not None:
        if f == b:
            return True
        f = PREVIOUS_FORK_OF.get(f)
    return False


def is_post_altair(spec) -> bool:
    return is_post_fork(spec.fork, "altair")


def is_post_bellatrix(spec) -> bool:
    return is_post_fork(spec.fork, "bellatrix")


def is_post_capella(spec) -> bool:
    return is_post_fork(spec.fork, "capella")


def is_post_deneb(spec) -> bool:
    return is_post_fork(spec.fork, "deneb")


def is_post_electra(spec) -> bool:
    return is_post_fork(spec.fork, "electra")


def is_post_fulu(spec) -> bool:
    return is_post_fork(spec.fork, "fulu")


def is_post_eip7732(spec) -> bool:
    return is_post_fork(spec.fork, "eip7732")


def get_spec_for_fork_version(spec, fork_version):
    """Name of the fork whose version equals `fork_version` in config."""
    for fork in ALL_FORKS:
        if fork == "phase0":
            key = "GENESIS_FORK_VERSION"
        else:
            key = f"{fork.upper()}_FORK_VERSION"
        if getattr(spec.config, key, None) == fork_version:
            return fork
    raise ValueError(f"unknown fork version {fork_version!r}")


def all_pre_post_forks():
    """(pre, post) pairs of consecutive implemented forks."""
    from ...models.builder import ALL_FORKS, PREVIOUS_FORK_OF

    return [(PREVIOUS_FORK_OF[f], f) for f in ALL_FORKS
            if PREVIOUS_FORK_OF[f] is not None]


ALL_PRE_POST_FORKS = all_pre_post_forks()


def is_post_eip6800(spec) -> bool:
    return is_post_fork(spec.fork, "eip6800")
