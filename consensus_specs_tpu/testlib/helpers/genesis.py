"""Genesis-state construction for tests (mirrors `test/helpers/genesis.py`).

Builds the state directly (not via deposit processing) for speed; the
deposit path is exercised by the genesis initialization tests instead.
"""

from __future__ import annotations

from .forks import is_post_altair
from .keys import pubkey


def _fork_version_of(spec):
    """(previous_version, current_version) for a genesis state of this
    spec's fork (the reference sets versions per fork in
    `helpers/genesis.py create_genesis_state`)."""
    cfg = spec.config
    if spec.fork == "phase0":
        return cfg.GENESIS_FORK_VERSION, cfg.GENESIS_FORK_VERSION
    chain = []
    from ...models.builder import fork_chain

    names = fork_chain(spec.fork)
    for name in names:
        if name == "phase0":
            chain.append(cfg.GENESIS_FORK_VERSION)
        else:
            chain.append(getattr(cfg, f"{name.upper()}_FORK_VERSION"))
    return chain[-2], chain[-1]


def build_mock_validator(spec, i: int, balance: int,
                         activation_threshold: int):
    pk = pubkey(i)
    withdrawal_credentials = (
        bytes(spec.BLS_WITHDRAWAL_PREFIX) + spec.hash(pk)[1:])
    effective = min(balance - balance % spec.EFFECTIVE_BALANCE_INCREMENT,
                    spec.MAX_EFFECTIVE_BALANCE)
    return spec.Validator(
        pubkey=pk,
        withdrawal_credentials=withdrawal_credentials,
        activation_eligibility_epoch=spec.FAR_FUTURE_EPOCH,
        activation_epoch=spec.FAR_FUTURE_EPOCH,
        exit_epoch=spec.FAR_FUTURE_EPOCH,
        withdrawable_epoch=spec.FAR_FUTURE_EPOCH,
        effective_balance=effective,
    )


def create_genesis_state(spec, validator_balances, activation_threshold):
    deposit_root = b"\x42" * 32
    eth1_block_hash = b"\xda" * 32
    previous_version, current_version = _fork_version_of(spec)
    state = spec.BeaconState(
        genesis_time=0,
        eth1_deposit_index=len(validator_balances),
        eth1_data=spec.Eth1Data(
            deposit_root=deposit_root,
            deposit_count=len(validator_balances),
            block_hash=eth1_block_hash,
        ),
        fork=spec.Fork(
            previous_version=previous_version,
            current_version=current_version,
            epoch=spec.GENESIS_EPOCH,
        ),
        latest_block_header=spec.BeaconBlockHeader(
            body_root=spec.hash_tree_root(spec.BeaconBlockBody())),
        randao_mixes=[eth1_block_hash] * spec.EPOCHS_PER_HISTORICAL_VECTOR,
    )

    # Populate the registry
    for i, balance in enumerate(validator_balances):
        v = build_mock_validator(spec, i, balance, activation_threshold)
        if v.effective_balance >= activation_threshold:
            v.activation_eligibility_epoch = spec.GENESIS_EPOCH
            v.activation_epoch = spec.GENESIS_EPOCH
        state.validators.append(v)
        state.balances.append(balance)
        if is_post_altair(spec):
            state.previous_epoch_participation.append(
                spec.ParticipationFlags(0))
            state.current_epoch_participation.append(
                spec.ParticipationFlags(0))
            state.inactivity_scores.append(spec.uint64(0))

    state.genesis_validators_root = spec.hash_tree_root(state.validators)

    if is_post_altair(spec):
        # Fill in sync committees (duplicate committee at genesis)
        state.current_sync_committee = spec.get_next_sync_committee(state)
        state.next_sync_committee = spec.get_next_sync_committee(state)

    return state
