"""Genesis-state construction for tests (mirrors `test/helpers/genesis.py`).

Builds the state directly (not via deposit processing) for speed; the
deposit path is exercised by the genesis initialization tests instead.
"""

from __future__ import annotations

from .keys import pubkey


def build_mock_validator(spec, i: int, balance: int,
                         activation_threshold: int):
    pk = pubkey(i)
    withdrawal_credentials = (
        bytes(spec.BLS_WITHDRAWAL_PREFIX) + spec.hash(pk)[1:])
    effective = min(balance - balance % spec.EFFECTIVE_BALANCE_INCREMENT,
                    spec.MAX_EFFECTIVE_BALANCE)
    return spec.Validator(
        pubkey=pk,
        withdrawal_credentials=withdrawal_credentials,
        activation_eligibility_epoch=spec.FAR_FUTURE_EPOCH,
        activation_epoch=spec.FAR_FUTURE_EPOCH,
        exit_epoch=spec.FAR_FUTURE_EPOCH,
        withdrawable_epoch=spec.FAR_FUTURE_EPOCH,
        effective_balance=effective,
    )


def create_genesis_state(spec, validator_balances, activation_threshold):
    deposit_root = b"\x42" * 32
    eth1_block_hash = b"\xda" * 32
    state = spec.BeaconState(
        genesis_time=0,
        eth1_deposit_index=len(validator_balances),
        eth1_data=spec.Eth1Data(
            deposit_root=deposit_root,
            deposit_count=len(validator_balances),
            block_hash=eth1_block_hash,
        ),
        fork=spec.Fork(
            previous_version=spec.config.GENESIS_FORK_VERSION,
            current_version=spec.config.GENESIS_FORK_VERSION,
            epoch=spec.GENESIS_EPOCH,
        ),
        latest_block_header=spec.BeaconBlockHeader(
            body_root=spec.hash_tree_root(spec.BeaconBlockBody())),
        randao_mixes=[eth1_block_hash] * spec.EPOCHS_PER_HISTORICAL_VECTOR,
    )

    # Populate the registry
    for i, balance in enumerate(validator_balances):
        v = build_mock_validator(spec, i, balance, activation_threshold)
        if v.effective_balance >= activation_threshold:
            v.activation_eligibility_epoch = spec.GENESIS_EPOCH
            v.activation_epoch = spec.GENESIS_EPOCH
        state.validators.append(v)
        state.balances.append(balance)

    state.genesis_validators_root = spec.hash_tree_root(state.validators)

    return state
