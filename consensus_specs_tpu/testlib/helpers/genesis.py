"""Genesis-state construction for tests (mirrors `test/helpers/genesis.py`).

Builds the state directly (not via deposit processing) for speed; the
deposit path is exercised by the genesis initialization tests instead.
"""

from __future__ import annotations

from .forks import is_post_altair, is_post_bellatrix
from .keys import pubkey


def get_sample_genesis_execution_payload_header(spec, eth1_block_hash=None):
    """Mock post-merge EL header for genesis states with a real RLP
    block hash (`helpers/genesis.py
    get_sample_genesis_execution_payload_header:75-121`)."""
    from ...utils.eth1 import EMPTY_TRIE_ROOT
    from .execution_payload import (
        compute_el_header_block_hash,
        compute_requests_hash,
    )
    from .forks import (
        is_post_capella,
        is_post_deneb,
        is_post_eip7732,
        is_post_electra,
    )

    if eth1_block_hash is None:
        eth1_block_hash = b"\x55" * 32
    if is_post_eip7732(spec):
        # the post-ePBS header is a builder bid
        kzgs = spec.List[spec.KZGCommitment,
                         spec.MAX_BLOB_COMMITMENTS_PER_BLOCK]()
        return spec.ExecutionPayloadHeader(
            parent_block_hash=b"\x30" * 32,
            parent_block_root=b"\x00" * 32,
            block_hash=eth1_block_hash,
            gas_limit=30000000,
            slot=spec.Slot(0),
            blob_kzg_commitments_root=spec.hash_tree_root(kzgs),
        )
    payload_header = spec.ExecutionPayloadHeader(
        parent_hash=b"\x30" * 32,
        fee_recipient=b"\x42" * 20,
        state_root=b"\x20" * 32,
        receipts_root=b"\x20" * 32,
        logs_bloom=b"\x35" * int(spec.BYTES_PER_LOGS_BLOOM),
        prev_randao=eth1_block_hash,
        block_number=0,
        gas_limit=30000000,
        base_fee_per_gas=1000000000,
        block_hash=eth1_block_hash,
        transactions_root=spec.Root(b"\x56" * 32),
    )
    withdrawals_trie_root = EMPTY_TRIE_ROOT if is_post_capella(spec) else None
    parent_beacon_block_root = b"\x00" * 32 if is_post_deneb(spec) else None
    requests_hash = (compute_requests_hash([])
                     if is_post_electra(spec) else None)
    payload_header.block_hash = compute_el_header_block_hash(
        spec, payload_header, EMPTY_TRIE_ROOT, withdrawals_trie_root,
        parent_beacon_block_root, requests_hash)
    return payload_header


def _fork_version_of(spec):
    """(previous_version, current_version) for a genesis state of this
    spec's fork (the reference sets versions per fork in
    `helpers/genesis.py create_genesis_state`)."""
    cfg = spec.config
    if spec.fork == "phase0":
        return cfg.GENESIS_FORK_VERSION, cfg.GENESIS_FORK_VERSION
    chain = []
    from ...models.builder import fork_chain

    names = fork_chain(spec.fork)
    for name in names:
        if name == "phase0":
            chain.append(cfg.GENESIS_FORK_VERSION)
        else:
            chain.append(getattr(cfg, f"{name.upper()}_FORK_VERSION"))
    return chain[-2], chain[-1]


def build_mock_validator(spec, i: int, balance: int,
                         activation_threshold: int):
    from .forks import is_post_electra

    pk = pubkey(i)
    if is_post_electra(spec):
        if balance > spec.MIN_ACTIVATION_BALANCE:
            # compounding credentials above the activation minimum
            withdrawal_credentials = (
                bytes(spec.COMPOUNDING_WITHDRAWAL_PREFIX) + b"\x00" * 11
                + spec.hash(pk)[12:])
        else:
            withdrawal_credentials = (
                bytes(spec.BLS_WITHDRAWAL_PREFIX) + spec.hash(pk)[1:])
        max_effective_balance = spec.MAX_EFFECTIVE_BALANCE_ELECTRA
    else:
        withdrawal_credentials = (
            bytes(spec.BLS_WITHDRAWAL_PREFIX) + spec.hash(pk)[1:])
        max_effective_balance = spec.MAX_EFFECTIVE_BALANCE
    effective = min(balance - balance % spec.EFFECTIVE_BALANCE_INCREMENT,
                    max_effective_balance)
    return spec.Validator(
        pubkey=pk,
        withdrawal_credentials=withdrawal_credentials,
        activation_eligibility_epoch=spec.FAR_FUTURE_EPOCH,
        activation_epoch=spec.FAR_FUTURE_EPOCH,
        exit_epoch=spec.FAR_FUTURE_EPOCH,
        withdrawable_epoch=spec.FAR_FUTURE_EPOCH,
        effective_balance=effective,
    )


def create_genesis_state(spec, validator_balances, activation_threshold):
    deposit_root = b"\x42" * 32
    eth1_block_hash = b"\xda" * 32
    previous_version, current_version = _fork_version_of(spec)
    state = spec.BeaconState(
        genesis_time=0,
        eth1_deposit_index=len(validator_balances),
        eth1_data=spec.Eth1Data(
            deposit_root=deposit_root,
            deposit_count=len(validator_balances),
            block_hash=eth1_block_hash,
        ),
        fork=spec.Fork(
            previous_version=previous_version,
            current_version=current_version,
            epoch=spec.GENESIS_EPOCH,
        ),
        latest_block_header=spec.BeaconBlockHeader(
            body_root=spec.hash_tree_root(spec.BeaconBlockBody())),
        randao_mixes=[eth1_block_hash] * spec.EPOCHS_PER_HISTORICAL_VECTOR,
    )

    # Populate the registry
    for i, balance in enumerate(validator_balances):
        v = build_mock_validator(spec, i, balance, activation_threshold)
        if v.effective_balance >= activation_threshold:
            v.activation_eligibility_epoch = spec.GENESIS_EPOCH
            v.activation_epoch = spec.GENESIS_EPOCH
        state.validators.append(v)
        state.balances.append(balance)
        if is_post_altair(spec):
            state.previous_epoch_participation.append(
                spec.ParticipationFlags(0))
            state.current_epoch_participation.append(
                spec.ParticipationFlags(0))
            state.inactivity_scores.append(spec.uint64(0))

    state.genesis_validators_root = spec.hash_tree_root(state.validators)

    if is_post_altair(spec):
        # Fill in sync committees (duplicate committee at genesis)
        state.current_sync_committee = spec.get_next_sync_committee(state)
        state.next_sync_committee = spec.get_next_sync_committee(state)

    if is_post_bellatrix(spec):
        # Genesis is post-merge: install a sample EL header so
        # `is_merge_transition_complete` holds from the start
        state.latest_execution_payload_header = (
            get_sample_genesis_execution_payload_header(
                spec, eth1_block_hash=eth1_block_hash))

    from .forks import is_post_electra, is_post_fulu

    if is_post_electra(spec):
        state.deposit_requests_start_index = (
            spec.UNSET_DEPOSIT_REQUESTS_START_INDEX)
        state.earliest_exit_epoch = spec.GENESIS_EPOCH
        state.earliest_consolidation_epoch = 0

    from .forks import is_post_eip7732

    if is_post_eip7732(spec):
        withdrawals = spec.List[spec.Withdrawal,
                                spec.MAX_WITHDRAWALS_PER_PAYLOAD]()
        state.latest_withdrawals_root = spec.hash_tree_root(withdrawals)
        # last block is full
        state.latest_block_hash = (
            state.latest_execution_payload_header.block_hash)

    if is_post_fulu(spec):
        # pre-computed proposer lookahead (EIP-7917)
        state.proposer_lookahead = spec.initialize_proposer_lookahead(state)

    return state
