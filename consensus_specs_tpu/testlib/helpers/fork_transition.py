"""Cross-fork transition driving: blocks up to a fork boundary, the
irregular upgrade step, and block production under the post spec
(the reference's `test/helpers/fork_transition.py:84-330`)."""

from __future__ import annotations

from ...models.builder import PREVIOUS_FORK_OF
from .block import build_empty_block, build_empty_block_for_next_slot, \
    sign_block
from .state import next_slot, state_transition_and_sign_block, transition_to


def _state_transition_and_sign_block_at_slot(spec, state,
                                             sync_aggregate=None,
                                             operation_dict=None):
    """Produce the first block of an irregular transition: process_slots
    already ran, so only process_block applies here."""
    block = build_empty_block(spec, state)
    if sync_aggregate is not None:
        block.body.sync_aggregate = sync_aggregate
    if operation_dict:
        for key, value in operation_dict.items():
            setattr(block.body, key, value)

    assert state.latest_block_header.slot < block.slot
    assert state.slot == block.slot
    spec.process_block(state, block)
    block.state_root = state.hash_tree_root()
    return sign_block(spec, state, block)


def _all_blocks(_):
    return True


def skip_slots(*slots):
    """Make no block at the given slots."""
    def f(state_at_prior_slot):
        return state_at_prior_slot.slot + 1 not in slots
    return f


def no_blocks(_):
    return False


def only_at(slot):
    """Make a block only at `slot`."""
    def f(state_at_prior_slot):
        return state_at_prior_slot.slot + 1 == slot
    return f


def state_transition_across_slots(spec, state, to_slot,
                                  block_filter=_all_blocks):
    assert state.slot < to_slot
    while state.slot < to_slot:
        if block_filter(state):
            block = build_empty_block_for_next_slot(spec, state)
            yield state_transition_and_sign_block(spec, state, block)
        else:
            next_slot(spec, state)


def get_upgrade_fn(spec, fork: str):
    fn = getattr(spec, f"upgrade_to_{fork}", None)
    if fn is None:
        raise ValueError(f"no upgrade function for fork {fork!r}")
    return fn


def do_fork(state, spec, post_spec, fork_epoch, with_block=True,
            sync_aggregate=None, operation_dict=None):
    """The irregular transition: advance one slot onto the fork boundary,
    apply the upgrade function, verify the fork record, and (optionally)
    produce the first post-fork block."""
    spec.process_slots(state, state.slot + 1)

    assert state.slot % spec.SLOTS_PER_EPOCH == 0
    assert spec.get_current_epoch(state) == fork_epoch

    state = get_upgrade_fn(post_spec, post_spec.fork)(state)

    assert state.fork.epoch == fork_epoch

    previous_fork = PREVIOUS_FORK_OF[post_spec.fork]
    if previous_fork == "phase0":
        previous_version = spec.config.GENESIS_FORK_VERSION
    else:
        previous_version = getattr(
            post_spec.config, f"{previous_fork.upper()}_FORK_VERSION")
    current_version = getattr(
        post_spec.config, f"{post_spec.fork.upper()}_FORK_VERSION")

    assert bytes(state.fork.previous_version) == bytes(previous_version)
    assert bytes(state.fork.current_version) == bytes(current_version)

    if with_block:
        return state, _state_transition_and_sign_block_at_slot(
            post_spec, state, sync_aggregate=sync_aggregate,
            operation_dict=operation_dict)
    return state, None


def transition_until_fork(spec, state, fork_epoch):
    """Advance to the last pre-fork slot."""
    transition_to(spec, state, fork_epoch * spec.SLOTS_PER_EPOCH - 1)


def transition_to_next_epoch_and_append_blocks(spec, state, post_tag, blocks,
                                               only_last_block=False):
    to_slot = spec.SLOTS_PER_EPOCH + state.slot
    block_filter = only_at(to_slot) if only_last_block else _all_blocks
    blocks.extend(
        post_tag(block)
        for block in state_transition_across_slots(
            spec, state, to_slot, block_filter=block_filter))
