"""Random multi-operation block building: every operation type mixed into
one block (the reference's `test/helpers/multi_operations.py:22-364`).
Feeds the `random` test category and the randomized-block scenarios."""

from __future__ import annotations

from random import Random

from .attestations import get_valid_attestation
from .attester_slashings import get_valid_attester_slashing_by_indices
from .block import build_empty_block_for_next_slot
from .deposits import build_deposit, deposit_from_context
from .forks import is_post_electra
from .keys import privkeys, pubkeys
from .proposer_slashings import get_valid_proposer_slashing
from .state import state_transition_and_sign_block
from .voluntary_exits import prepare_signed_exits


def get_max_attestations(spec):
    if is_post_electra(spec):
        return spec.MAX_ATTESTATIONS_ELECTRA
    return spec.MAX_ATTESTATIONS


def get_random_proposer_slashings(spec, state, rng):
    num_slashings = rng.randrange(1, spec.MAX_PROPOSER_SLASHINGS)
    active = list(spec.get_active_validator_indices(
        state, spec.get_current_epoch(state)))
    indices = [i for i in active if not state.validators[i].slashed]
    return [
        get_valid_proposer_slashing(
            spec, state,
            slashed_index=indices.pop(rng.randrange(len(indices))),
            signed_1=True, signed_2=True)
        for _ in range(num_slashings)
    ]


def get_random_attester_slashings(spec, state, rng, slashed_indices=()):
    num_slashings = rng.randrange(1, spec.MAX_ATTESTER_SLASHINGS)
    active = list(spec.get_active_validator_indices(
        state, spec.get_current_epoch(state)))
    indices = [i for i in active
               if not state.validators[i].slashed
               and i not in slashed_indices]
    sample_upper_bound = 4
    if len(indices) < num_slashings * sample_upper_bound - 1:
        return []
    slot_range = list(range(
        max(1, state.slot - spec.SLOTS_PER_HISTORICAL_ROOT + 1),
        state.slot))
    return [
        get_valid_attester_slashing_by_indices(
            spec, state,
            sorted(indices.pop(rng.randrange(len(indices)))
                   for _ in range(rng.randrange(1, sample_upper_bound))),
            slot=slot_range.pop(rng.randrange(len(slot_range))),
            signed_1=True, signed_2=True)
        for _ in range(num_slashings)
    ]


def get_random_attestations(spec, state, rng):
    num_attestations = rng.randrange(1, get_max_attestations(spec))
    return [
        get_valid_attestation(
            spec, state,
            slot=rng.randrange(
                max(1, state.slot - spec.SLOTS_PER_EPOCH + 1),
                state.slot),
            signed=True)
        for _ in range(num_attestations)
    ]


def get_random_deposits(spec, state, rng, num_deposits=None):
    if num_deposits is None:
        num_deposits = rng.randrange(1, spec.MAX_DEPOSITS)
    if num_deposits == 0:
        return [], b"\x00" * 32

    deposit_data_leaves = [spec.DepositData()
                           for _ in range(len(state.validators))]
    root = None
    for i in range(num_deposits):
        index = len(state.validators) + i
        withdrawal_pubkey = pubkeys[-1 - index]
        withdrawal_credentials = (bytes(spec.BLS_WITHDRAWAL_PREFIX)
                                  + spec.hash(withdrawal_pubkey)[1:])
        _, root, deposit_data_leaves = build_deposit(
            spec, deposit_data_leaves, pubkeys[index], privkeys[index],
            spec.MAX_EFFECTIVE_BALANCE,
            withdrawal_credentials=withdrawal_credentials, signed=True)

    deposits = []
    for i in range(num_deposits):
        index = len(state.validators) + i
        deposit, _, _ = deposit_from_context(spec, deposit_data_leaves,
                                             index)
        deposits.append(deposit)
    return deposits, root


def prepare_state_and_get_random_deposits(spec, state, rng,
                                          num_deposits=None):
    deposits, root = get_random_deposits(spec, state, rng,
                                         num_deposits=num_deposits)
    state.eth1_data.deposit_root = root
    state.eth1_data.deposit_count += len(deposits)
    return deposits


def _eligible_for_exit(spec, state, index):
    validator = state.validators[index]
    current_epoch = spec.get_current_epoch(state)
    return (not validator.slashed
            and current_epoch >= (validator.activation_epoch
                                  + spec.config.SHARD_COMMITTEE_PERIOD)
            and validator.exit_epoch == spec.FAR_FUTURE_EPOCH)


def get_random_voluntary_exits(spec, state, to_be_slashed_indices, rng):
    num_exits = rng.randrange(1, spec.MAX_VOLUNTARY_EXITS)
    active = set(spec.get_active_validator_indices(
        state, spec.get_current_epoch(state)))
    eligible = set(i for i in active if _eligible_for_exit(spec, state, i))
    eligible -= set(to_be_slashed_indices)
    exit_indices = [eligible.pop()
                    for _ in range(min(num_exits, len(eligible)))]
    return prepare_signed_exits(spec, state, exit_indices)


def build_random_block_from_state_for_next_slot(spec, state, rng=None,
                                                deposits=None):
    rng = rng or Random(2188)
    block = build_empty_block_for_next_slot(spec, state)
    proposer_slashings = get_random_proposer_slashings(spec, state, rng)
    block.body.proposer_slashings = proposer_slashings
    slashed_indices = [s.signed_header_1.message.proposer_index
                       for s in proposer_slashings]
    block.body.attester_slashings = get_random_attester_slashings(
        spec, state, rng, slashed_indices)
    block.body.attestations = get_random_attestations(spec, state, rng)
    if deposits:
        block.body.deposits = deposits

    slashed = set(s.signed_header_1.message.proposer_index
                  for s in block.body.proposer_slashings)
    for attester_slashing in block.body.attester_slashings:
        slashed |= set(attester_slashing.attestation_1.attesting_indices)
        slashed |= set(attester_slashing.attestation_2.attesting_indices)
    block.body.voluntary_exits = get_random_voluntary_exits(
        spec, state, slashed, rng)
    return block


def run_test_full_random_operations(spec, state, rng=None):
    rng = rng or Random(2080)
    # age the registry so validators are eligible to exit
    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH

    deposits = prepare_state_and_get_random_deposits(spec, state, rng)
    block = build_random_block_from_state_for_next_slot(spec, state, rng,
                                                        deposits=deposits)
    yield "pre", state
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
