"""Attester-slashing construction + runner
(mirrors `test/helpers/attester_slashings.py`)."""

from __future__ import annotations

from ..utils import expect_assertion_error
from .attestations import get_valid_attestation, sign_attestation
from .state import get_balance


def get_valid_attester_slashing(spec, state, slot=None,
                                signed_1=False, signed_2=False):
    """Double vote: same target epoch, different data."""
    attestation_1 = get_valid_attestation(spec, state, slot=slot,
                                          signed=signed_1)
    attestation_2 = attestation_1.copy()
    attestation_2.data.target.root = b"\x01" * 32
    if signed_2:
        sign_attestation(spec, state, attestation_2)

    return spec.AttesterSlashing(
        attestation_1=spec.get_indexed_attestation(state, attestation_1),
        attestation_2=spec.get_indexed_attestation(state, attestation_2),
    )


def get_valid_attester_slashing_by_indices(spec, state, indices_1,
                                           indices_2=None, slot=None,
                                           signed_1=False, signed_2=False):
    from .block import sign_indexed_attestation

    if indices_2 is None:
        indices_2 = indices_1
    slashing = get_valid_attester_slashing(spec, state, slot=slot)
    slashing.attestation_1.attesting_indices = sorted(indices_1)
    slashing.attestation_2.attesting_indices = sorted(indices_2)
    if signed_1:
        sign_indexed_attestation(spec, state, slashing.attestation_1)
    if signed_2:
        sign_indexed_attestation(spec, state, slashing.attestation_2)
    return slashing


def get_indexed_attestation_participants(spec, indexed_att):
    return list(indexed_att.attesting_indices)


def run_attester_slashing_processing(spec, state, attester_slashing,
                                     valid=True):
    pre_state = state.copy()

    yield "pre", state
    yield "attester_slashing", attester_slashing

    if not valid:
        expect_assertion_error(
            lambda: spec.process_attester_slashing(state, attester_slashing))
        yield "post", None
        return

    slashed_indices = set(
        attester_slashing.attestation_1.attesting_indices
    ).intersection(attester_slashing.attestation_2.attesting_indices)

    proposer_index = spec.get_beacon_proposer_index(state)
    pre_proposer_balance = get_balance(state, proposer_index)

    spec.process_attester_slashing(state, attester_slashing)

    for slashed_index in slashed_indices:
        if state.validators[slashed_index].slashed:
            pass  # at least the newly slashed are marked
    # at least one is newly slashed
    assert any(state.validators[i].slashed for i in slashed_indices)
    # proposer gained reward (unless proposer was among slashed)
    if proposer_index not in slashed_indices:
        assert get_balance(state, proposer_index) > pre_proposer_balance

    yield "post", state
