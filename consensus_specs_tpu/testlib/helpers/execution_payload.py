"""Execution-payload construction for tests
(mirrors `test/helpers/execution_payload.py`).

Block hashes: the reference computes the real RLP header hash via an MPT
(`compute_el_header_block_hash`).  The spec itself never recomputes the
hash (`is_valid_block_hash` is a Noop stub), so this build derives a
deterministic placeholder hash from the header contents; swap in an RLP
encoder when emitting cross-client vectors.
"""

from __future__ import annotations

import hashlib

from .forks import is_post_capella, is_post_deneb


def compute_el_header_hash_stub(spec, payload_header):
    """Deterministic stand-in for the EL block hash: sha256 over the SSZ
    of the header with a zeroed block_hash field.  Single definition —
    genesis and block construction must agree on the scheme."""
    from ...utils.ssz.ssz_impl import serialize

    stub = payload_header.copy()
    stub.block_hash = spec.Hash32()
    return spec.Hash32(hashlib.sha256(b"el-block-hash:"
                                      + serialize(stub)).digest())


def compute_el_block_hash(spec, payload, pre_state=None):
    header = get_execution_payload_header(spec, pre_state, payload)
    return compute_el_header_hash_stub(spec, header)


def get_execution_payload_header(spec, state, execution_payload):
    payload_header = spec.ExecutionPayloadHeader(
        parent_hash=execution_payload.parent_hash,
        fee_recipient=execution_payload.fee_recipient,
        state_root=execution_payload.state_root,
        receipts_root=execution_payload.receipts_root,
        logs_bloom=execution_payload.logs_bloom,
        prev_randao=execution_payload.prev_randao,
        block_number=execution_payload.block_number,
        gas_limit=execution_payload.gas_limit,
        gas_used=execution_payload.gas_used,
        timestamp=execution_payload.timestamp,
        extra_data=execution_payload.extra_data,
        base_fee_per_gas=execution_payload.base_fee_per_gas,
        block_hash=execution_payload.block_hash,
        transactions_root=spec.hash_tree_root(execution_payload.transactions),
    )
    if is_post_capella(spec):
        payload_header.withdrawals_root = spec.hash_tree_root(
            execution_payload.withdrawals)
    if is_post_deneb(spec):
        payload_header.blob_gas_used = execution_payload.blob_gas_used
        payload_header.excess_blob_gas = execution_payload.excess_blob_gas
    return payload_header


def build_empty_execution_payload(spec, state, randao_mix=None):
    """Valid empty-transactions payload for a pre-state of the same
    slot."""
    latest = state.latest_execution_payload_header
    timestamp = spec.compute_time_at_slot(state, state.slot)
    empty_txs = spec.List[spec.Transaction,
                          spec.MAX_TRANSACTIONS_PER_PAYLOAD]()

    if randao_mix is None:
        randao_mix = spec.get_randao_mix(state, spec.get_current_epoch(state))

    payload = spec.ExecutionPayload(
        parent_hash=latest.block_hash,
        fee_recipient=spec.ExecutionAddress(),
        receipts_root=spec.Bytes32(bytes.fromhex(
            "1dcc4de8dec75d7aab85b567b6ccd41ad312451b948a7413f0a142fd40d49347")),
        logs_bloom=spec.ByteVector[spec.BYTES_PER_LOGS_BLOOM](),
        prev_randao=randao_mix,
        gas_used=0,
        gas_limit=latest.gas_limit,
        timestamp=timestamp,
        extra_data=spec.ByteList[spec.MAX_EXTRA_DATA_BYTES](),
        transactions=empty_txs,
    )
    payload.state_root = latest.state_root  # no changes to the state
    payload.block_number = latest.block_number + 1
    payload.base_fee_per_gas = latest.base_fee_per_gas
    if is_post_capella(spec):
        from .forks import is_post_electra

        if is_post_electra(spec):
            # electra returns (withdrawals, processed_partials_count)
            payload.withdrawals, _ = spec.get_expected_withdrawals(state)
        else:
            payload.withdrawals = spec.get_expected_withdrawals(state)
    if is_post_deneb(spec):
        payload.blob_gas_used = 0
        payload.excess_blob_gas = 0

    payload.block_hash = compute_el_block_hash(spec, payload, state)

    return payload


def build_state_with_incomplete_transition(spec, state):
    """State whose EL transition has not happened (empty header)."""
    return build_state_with_execution_payload_header(
        spec, state, spec.ExecutionPayloadHeader())


def build_state_with_complete_transition(spec, state):
    """State already past the merge (pre-populated sample header)."""
    from .genesis import get_sample_genesis_execution_payload_header

    pre_state_payload = get_sample_genesis_execution_payload_header(spec)
    return build_state_with_execution_payload_header(
        spec, state, pre_state_payload)


def build_state_with_execution_payload_header(spec, state,
                                              execution_payload_header):
    pre_state = state.copy()
    pre_state.latest_execution_payload_header = execution_payload_header
    return pre_state


def get_random_tx(rng):
    return spec_random_bytes(rng, rng.randint(1, 1000))


def spec_random_bytes(rng, length):
    return bytes(rng.randint(0, 255) for _ in range(length))
