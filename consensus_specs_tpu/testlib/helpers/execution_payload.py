"""Execution-payload construction for tests
(mirrors `test/helpers/execution_payload.py`).

Block hashes are the REAL execution-layer hashes: the RLP-encoded EL
header keccak-hashed, with transactions/withdrawals roots computed over
`patriciaTrie(rlp(index) => data)` — the same scheme as the reference's
`compute_el_header_block_hash`
(`test/helpers/execution_payload.py:77-147`), built on this repo's own
pure-Python keccak/RLP/MPT (`utils/eth1.py`).
"""

from __future__ import annotations

import hashlib

from ...utils.eth1 import indexed_data_trie_root, keccak256, rlp_encode
from .forks import (
    is_post_capella,
    is_post_deneb,
    is_post_eip6800,
    is_post_eip7732,
    is_post_electra,
)

OMMERS_HASH = bytes.fromhex(
    "1dcc4de8dec75d7aab85b567b6ccd41ad312451b948a7413f0a142fd40d49347")
EMPTY_NONCE = b"\x00" * 8


def compute_trie_root_from_indexed_data(data):
    """Root of `patriciaTrie(rlp(Index) => Data)` (EIP-2718)."""
    return indexed_data_trie_root(data)


def compute_requests_hash(block_requests):
    """EIP-7685 commitment: sha256 over the sha256 of each non-empty
    request (type byte + payload)."""
    m = hashlib.sha256()
    for request in block_requests:
        if len(request) > 1:
            m.update(hashlib.sha256(bytes(request)).digest())
    return m.digest()


def compute_el_header_block_hash(spec, payload_header,
                                 transactions_trie_root,
                                 withdrawals_trie_root=None,
                                 parent_beacon_block_root=None,
                                 requests_hash=None):
    """keccak-256 of the RLP execution block header described by an
    `ExecutionPayloadHeader` (EIP-4895 / EIP-4844 / EIP-7685 layout)."""
    if is_post_eip7732(spec):
        # the bid header carries no EL fields to hash
        return spec.Hash32()
    fields = [
        bytes(payload_header.parent_hash),
        OMMERS_HASH,
        bytes(payload_header.fee_recipient),
        bytes(payload_header.state_root),
        transactions_trie_root,
        bytes(payload_header.receipts_root),
        bytes(payload_header.logs_bloom),
        0,  # difficulty is zero post-merge
        int(payload_header.block_number),
        int(payload_header.gas_limit),
        int(payload_header.gas_used),
        int(payload_header.timestamp),
        bytes(payload_header.extra_data),
        bytes(payload_header.prev_randao),
        EMPTY_NONCE,
        int(payload_header.base_fee_per_gas),
    ]
    if is_post_capella(spec):
        fields.append(withdrawals_trie_root)
    if is_post_deneb(spec):
        fields.append(int(payload_header.blob_gas_used))
        # eip6800 keeps the pre-rename `excess_data_gas` field name
        fields.append(int(payload_header.excess_blob_gas)
                      if hasattr(payload_header, "excess_blob_gas")
                      else int(payload_header.excess_data_gas))
        fields.append(bytes(parent_beacon_block_root))
    if is_post_electra(spec):
        fields.append(requests_hash)
    return spec.Hash32(keccak256(rlp_encode(fields)))


def get_withdrawal_rlp(withdrawal):
    """EIP-4895 withdrawal encoding."""
    return rlp_encode([
        int(withdrawal.index),
        int(withdrawal.validator_index),
        bytes(withdrawal.address),
        int(withdrawal.amount),
    ])


def get_deposit_request_rlp_bytes(deposit_request):
    return b"\x00" + rlp_encode([
        bytes(deposit_request.pubkey),
        bytes(deposit_request.withdrawal_credentials),
        int(deposit_request.amount),
        bytes(deposit_request.signature),
        int(deposit_request.index),
    ])


def get_withdrawal_request_rlp_bytes(withdrawal_request):
    return b"\x01" + rlp_encode([
        bytes(withdrawal_request.source_address),
        bytes(withdrawal_request.validator_pubkey),
    ])


def get_consolidation_request_rlp_bytes(consolidation_request):
    return b"\x02" + rlp_encode([
        bytes(consolidation_request.source_address),
        bytes(consolidation_request.source_pubkey),
        bytes(consolidation_request.target_pubkey),
    ])


def compute_el_block_hash_with_new_fields(spec, payload,
                                          parent_beacon_block_root,
                                          requests_hash):
    if payload == spec.ExecutionPayload():
        return spec.Hash32()

    transactions_trie_root = compute_trie_root_from_indexed_data(
        payload.transactions)
    withdrawals_trie_root = None
    if is_post_capella(spec):
        withdrawals_trie_root = compute_trie_root_from_indexed_data(
            [get_withdrawal_rlp(w) for w in payload.withdrawals])
    if not is_post_deneb(spec):
        parent_beacon_block_root = None

    payload_header = get_execution_payload_header(
        spec, spec.BeaconState(), payload)
    return compute_el_header_block_hash(
        spec, payload_header, transactions_trie_root, withdrawals_trie_root,
        parent_beacon_block_root, requests_hash)


def compute_el_block_hash(spec, payload, pre_state):
    parent_beacon_block_root = None
    requests_hash = None
    if is_post_deneb(spec):
        previous_block_header = pre_state.latest_block_header.copy()
        if previous_block_header.state_root == spec.Root():
            previous_block_header.state_root = pre_state.hash_tree_root()
        parent_beacon_block_root = previous_block_header.hash_tree_root()
    if is_post_electra(spec):
        requests_hash = compute_requests_hash([])
    return compute_el_block_hash_with_new_fields(
        spec, payload, parent_beacon_block_root, requests_hash)


def compute_el_block_hash_for_block(spec, block):
    requests_hash = None
    if is_post_electra(spec):
        requests_list = spec.get_execution_requests_list(
            block.body.execution_requests)
        requests_hash = compute_requests_hash(requests_list)
    return compute_el_block_hash_with_new_fields(
        spec, block.body.execution_payload, block.parent_root, requests_hash)


def get_execution_payload_header(spec, state, execution_payload):
    if is_post_eip7732(spec):
        # the bid commits to the payload's hash, not its EL fields
        return spec.ExecutionPayloadHeader(
            parent_block_hash=execution_payload.parent_hash,
            parent_block_root=spec.hash_tree_root(
                state.latest_block_header),
            block_hash=execution_payload.block_hash,
            gas_limit=execution_payload.gas_limit,
            slot=state.slot,
            blob_kzg_commitments_root=spec.hash_tree_root(
                spec.List[spec.KZGCommitment,
                          spec.MAX_BLOB_COMMITMENTS_PER_BLOCK]()),
        )
    payload_header = spec.ExecutionPayloadHeader(
        parent_hash=execution_payload.parent_hash,
        fee_recipient=execution_payload.fee_recipient,
        state_root=execution_payload.state_root,
        receipts_root=execution_payload.receipts_root,
        logs_bloom=execution_payload.logs_bloom,
        prev_randao=execution_payload.prev_randao,
        block_number=execution_payload.block_number,
        gas_limit=execution_payload.gas_limit,
        gas_used=execution_payload.gas_used,
        timestamp=execution_payload.timestamp,
        extra_data=execution_payload.extra_data,
        base_fee_per_gas=execution_payload.base_fee_per_gas,
        block_hash=execution_payload.block_hash,
        transactions_root=spec.hash_tree_root(execution_payload.transactions),
    )
    if is_post_capella(spec):
        payload_header.withdrawals_root = spec.hash_tree_root(
            execution_payload.withdrawals)
    if is_post_deneb(spec):
        payload_header.blob_gas_used = execution_payload.blob_gas_used
        if is_post_eip6800(spec):
            payload_header.excess_data_gas = \
                execution_payload.excess_blob_gas
            payload_header.execution_witness_root = spec.hash_tree_root(
                execution_payload.execution_witness)
        else:
            payload_header.excess_blob_gas = \
                execution_payload.excess_blob_gas
    return payload_header


def build_empty_post_eip7732_execution_payload_header(spec, state):
    """An empty self-built bid: the highest-index active non-slashed
    validator acts as builder, zero value/gas (reference
    `helpers/execution_payload.py:272-294`)."""
    if not is_post_eip7732(spec):
        return None
    from .block import get_parent_root

    epoch = spec.get_current_epoch(state)
    builder_index = None
    for index in spec.get_active_validator_indices(state, epoch):
        if not state.validators[index].slashed:
            builder_index = index
    assert builder_index is not None
    kzg_list = spec.List[spec.KZGCommitment,
                         spec.MAX_BLOB_COMMITMENTS_PER_BLOCK]()
    return spec.ExecutionPayloadHeader(
        parent_block_hash=state.latest_block_hash,
        parent_block_root=get_parent_root(spec, state),
        block_hash=spec.Hash32(),
        gas_limit=spec.uint64(0),
        builder_index=builder_index,
        slot=state.slot,
        value=spec.Gwei(0),
        blob_kzg_commitments_root=spec.hash_tree_root(kzg_list),
    )


def build_empty_signed_execution_payload_header(spec, state):
    if not is_post_eip7732(spec):
        return None
    from .keys import privkeys

    message = build_empty_post_eip7732_execution_payload_header(spec, state)
    privkey = privkeys[message.builder_index]
    signature = spec.get_execution_payload_header_signature(
        state, message, privkey)
    return spec.SignedExecutionPayloadHeader(
        message=message,
        signature=signature,
    )


def build_empty_execution_payload(spec, state, randao_mix=None):
    """Valid empty-transactions payload for a pre-state of the same
    slot."""
    latest = state.latest_execution_payload_header
    timestamp = spec.compute_time_at_slot(state, state.slot)
    empty_txs = spec.List[spec.Transaction,
                          spec.MAX_TRANSACTIONS_PER_PAYLOAD]()

    if randao_mix is None:
        randao_mix = spec.get_randao_mix(state, spec.get_current_epoch(state))

    payload = spec.ExecutionPayload(
        parent_hash=latest.block_hash,
        fee_recipient=spec.ExecutionAddress(),
        receipts_root=spec.Bytes32(OMMERS_HASH),
        logs_bloom=spec.ByteVector[spec.BYTES_PER_LOGS_BLOOM](),
        prev_randao=randao_mix,
        gas_used=0,
        gas_limit=latest.gas_limit,
        timestamp=timestamp,
        extra_data=spec.ByteList[spec.MAX_EXTRA_DATA_BYTES](),
        transactions=empty_txs,
    )
    payload.state_root = latest.state_root  # no changes to the state
    payload.block_number = latest.block_number + 1
    payload.base_fee_per_gas = latest.base_fee_per_gas
    if is_post_capella(spec):
        if is_post_electra(spec):
            # electra returns (withdrawals, processed_partials_count)
            payload.withdrawals, _ = spec.get_expected_withdrawals(state)
        else:
            payload.withdrawals = spec.get_expected_withdrawals(state)
    if is_post_deneb(spec):
        payload.blob_gas_used = 0
        payload.excess_blob_gas = 0

    payload.block_hash = compute_el_block_hash(spec, payload, state)

    return payload


def build_state_with_incomplete_transition(spec, state):
    """State whose EL transition has not happened (empty header)."""
    return build_state_with_execution_payload_header(
        spec, state, spec.ExecutionPayloadHeader())


def build_state_with_complete_transition(spec, state):
    """State already past the merge (pre-populated sample header)."""
    from .genesis import get_sample_genesis_execution_payload_header

    pre_state_payload = get_sample_genesis_execution_payload_header(spec)
    return build_state_with_execution_payload_header(
        spec, state, pre_state_payload)


def build_state_with_execution_payload_header(spec, state,
                                              execution_payload_header):
    pre_state = state.copy()
    pre_state.latest_execution_payload_header = execution_payload_header
    return pre_state


def get_random_tx(rng):
    return spec_random_bytes(rng, rng.randint(1, 1000))


def spec_random_bytes(rng, length):
    return bytes(rng.randint(0, 255) for _ in range(length))
