"""Randomized-state mutators: validator status churn + participation noise
(the reference's `test/helpers/random.py:9-212`).  These feed the rewards
suites and the randomized block scenarios."""

from __future__ import annotations

from random import Random

from .attestations import cached_prepare_state_with_attestations
from .deposits import mock_deposit
from .forks import is_post_altair
from .state import next_epoch


def set_some_activations(spec, state, rng, activation_epoch=None):
    if activation_epoch is None:
        activation_epoch = spec.get_current_epoch(state)
    num_validators = len(state.validators)
    selected = []
    for index in range(num_validators):
        v = state.validators[index]
        if v.slashed or v.exit_epoch != spec.FAR_FUTURE_EPOCH:
            continue
        # ~1/10 get a pending activation
        if rng.randrange(num_validators) < num_validators // 10:
            v.activation_eligibility_epoch = max(
                int(activation_epoch) - int(spec.MAX_SEED_LOOKAHEAD) - 1,
                int(spec.GENESIS_EPOCH))
            v.activation_epoch = activation_epoch
            selected.append(index)
    return selected


def set_some_new_deposits(spec, state, rng):
    deposited = []
    num_validators = len(state.validators)
    for index in range(num_validators):
        if not spec.is_active_validator(state.validators[index],
                                        spec.get_current_epoch(state)):
            continue
        # ~1/10 look recently deposited
        if rng.randrange(num_validators) < num_validators // 10:
            mock_deposit(spec, state, index)
            if rng.choice([True, False]):
                state.validators[index].activation_eligibility_epoch = \
                    spec.get_current_epoch(state)
            else:
                deposited.append(index)
    return deposited


def exit_random_validators(spec, state, rng, fraction=0.5, exit_epoch=None,
                           withdrawable_epoch=None, from_epoch=None):
    """Exit ~fraction of active validators; with no explicit epochs, exit
    times scatter over the recent past and half become withdrawable."""
    if from_epoch is None:
        from_epoch = spec.MAX_SEED_LOOKAHEAD + 1
    for _ in range(int(from_epoch) - int(spec.get_current_epoch(state))):
        next_epoch(spec, state)

    current_epoch = spec.get_current_epoch(state)
    exited = []
    for index in spec.get_active_validator_indices(state, current_epoch):
        if rng.random() >= fraction:
            continue
        exited.append(index)
        validator = state.validators[index]
        if exit_epoch is None:
            assert withdrawable_epoch is None
            validator.exit_epoch = rng.choice(
                [current_epoch, current_epoch - 1,
                 current_epoch - 2, current_epoch - 3])
            if rng.choice([True, False]):
                validator.withdrawable_epoch = current_epoch
            else:
                validator.withdrawable_epoch = current_epoch + 1
        else:
            validator.exit_epoch = exit_epoch
            if withdrawable_epoch is None:
                validator.withdrawable_epoch = (
                    validator.exit_epoch
                    + spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY)
            else:
                validator.withdrawable_epoch = withdrawable_epoch
    return exited


def slash_random_validators(spec, state, rng, fraction=0.5):
    slashed = []
    for index in range(len(state.validators)):
        # always slash at least index 0
        if index == 0 or rng.random() < fraction:
            spec.slash_validator(state, index)
            slashed.append(index)
    return slashed


def randomize_epoch_participation(spec, state, epoch, rng):
    assert epoch in (spec.get_current_epoch(state),
                     spec.get_previous_epoch(state))
    if not is_post_altair(spec):
        if epoch == spec.get_current_epoch(state):
            pending_attestations = state.current_epoch_attestations
        else:
            pending_attestations = state.previous_epoch_attestations
        for pending in pending_attestations:
            if rng.randint(0, 2) == 0:  # ~1/3 bad target
                pending.data.target.root = b"\x55" * 32
            if rng.randint(0, 2) == 0:  # ~1/3 bad head
                pending.data.beacon_block_root = b"\x66" * 32
            pending.aggregation_bits = type(pending.aggregation_bits)(
                [rng.choice([True, False])
                 for _ in pending.aggregation_bits])
            pending.inclusion_delay = rng.randint(1, spec.SLOTS_PER_EPOCH)
    else:
        if epoch == spec.get_current_epoch(state):
            participation = state.current_epoch_participation
        else:
            participation = state.previous_epoch_participation
        for index in range(len(state.validators)):
            flags = participation[index]

            def set_flag(i, value):
                nonlocal flags
                flag = spec.ParticipationFlags(2**i)
                if value:
                    flags |= flag
                else:
                    flags &= 0xFF ^ flag

            # timely head implies timely source+target
            is_timely_correct_head = rng.randint(0, 2) != 0
            set_flag(spec.TIMELY_HEAD_FLAG_INDEX, is_timely_correct_head)
            if is_timely_correct_head:
                set_flag(spec.TIMELY_TARGET_FLAG_INDEX, True)
                set_flag(spec.TIMELY_SOURCE_FLAG_INDEX, True)
            else:
                set_flag(spec.TIMELY_TARGET_FLAG_INDEX,
                         rng.choice([True, False]))
                set_flag(spec.TIMELY_SOURCE_FLAG_INDEX,
                         rng.choice([True, False]))
            participation[index] = flags


def randomize_previous_epoch_participation(spec, state, rng=None):
    rng = rng or Random(8020)
    cached_prepare_state_with_attestations(spec, state)
    randomize_epoch_participation(spec, state,
                                  spec.get_previous_epoch(state), rng)
    if not is_post_altair(spec):
        state.current_epoch_attestations = []
    else:
        state.current_epoch_participation = [
            spec.ParticipationFlags(0) for _ in range(len(state.validators))]


def randomize_attestation_participation(spec, state, rng=None):
    rng = rng or Random(8020)
    cached_prepare_state_with_attestations(spec, state)
    randomize_epoch_participation(spec, state,
                                  spec.get_previous_epoch(state), rng)
    randomize_epoch_participation(spec, state,
                                  spec.get_current_epoch(state), rng)


def randomize_state(spec, state, rng=None, exit_fraction=0.5,
                    slash_fraction=0.5):
    rng = rng or Random(8020)
    set_some_new_deposits(spec, state, rng)
    exit_random_validators(spec, state, rng, fraction=exit_fraction)
    slash_random_validators(spec, state, rng, fraction=slash_fraction)
    randomize_attestation_participation(spec, state, rng)


def patch_state_to_non_leaking(spec, state):
    """Rewrite justification so a (possibly randomized) state is not in an
    inactivity leak: justified = previous epoch, finalized = the epoch
    before it."""
    state.justification_bits[0] = True
    state.justification_bits[1] = True
    previous_epoch = spec.get_previous_epoch(state)
    previous_root = spec.get_block_root(state, previous_epoch)
    previous_previous_epoch = max(spec.GENESIS_EPOCH,
                                  spec.Epoch(previous_epoch - 1))
    previous_previous_root = spec.get_block_root(state,
                                                 previous_previous_epoch)
    state.previous_justified_checkpoint = spec.Checkpoint(
        epoch=previous_previous_epoch, root=previous_previous_root)
    state.current_justified_checkpoint = spec.Checkpoint(
        epoch=previous_epoch, root=previous_root)
    state.finalized_checkpoint = spec.Checkpoint(
        epoch=previous_previous_epoch, root=previous_previous_root)
