"""FFG justification/finalization scenario machinery.

Builds mocked epoch-attestation support so the four finality rules
(234/23/123/12 — `process_justification_and_finalization`,
specs/phase0/beacon-chain.md "Justification and finalization") can be
exercised in isolation.  Scenario parity with the reference's
`test/phase0/epoch_processing/test_process_justification_and_finalization.py`
harness (`add_mock_attestations` there).
"""

from __future__ import annotations

from .forks import is_post_altair


def put_mock_attestations(spec, state, epoch, source, target,
                          sufficient_support=True, messed_up_target=False):
    """Record attestation support for `target` at `epoch` with `source`,
    crossing the 2/3 threshold iff `sufficient_support`.

    phase0: appends PendingAttestations to the matching epoch list.
    altair+: sets participation flags on the attesting committee members
    (target flag withheld when `messed_up_target`).
    """
    # the caller must sit on the last slot of the epoch, as
    # run_epoch_processing_to leaves it
    assert (state.slot + 1) % spec.SLOTS_PER_EPOCH == 0

    previous_epoch = spec.get_previous_epoch(state)
    current_epoch = spec.get_current_epoch(state)
    assert epoch in (previous_epoch, current_epoch), \
        f"epoch {epoch} is neither previous nor current"

    if not is_post_altair(spec):
        attestations = (state.current_epoch_attestations
                        if epoch == current_epoch
                        else state.previous_epoch_attestations)
    else:
        participation = (state.current_epoch_participation
                         if epoch == current_epoch
                         else state.previous_epoch_participation)

    total = int(spec.get_total_active_balance(state))
    budget = total * 2 // 3  # stop adding support once the 2/3 line is met

    start_slot = spec.compute_start_slot_at_epoch(epoch)
    per_slot = spec.get_committee_count_per_slot(state, epoch)
    for slot in range(start_slot, start_slot + spec.SLOTS_PER_EPOCH):
        for index in range(per_slot):
            if budget < 0:
                return
            committee = spec.get_beacon_committee(state, slot, index)
            bits = [0] * len(committee)
            for pos in range(len(committee) * 2 // 3 + 1):
                if budget <= 0:
                    break
                budget -= int(state.validators[committee[pos]]
                              .effective_balance)
                bits[pos] = 1
            if not sufficient_support:
                # drop a fifth of the attesters: support stays below 2/3
                for pos in range(max(len(committee) // 5, 1)):
                    bits[pos] = 0

            if not is_post_altair(spec):
                pending = spec.PendingAttestation(
                    aggregation_bits=bits,
                    data=spec.AttestationData(
                        slot=slot,
                        beacon_block_root=b"\xff" * 32,
                        source=source,
                        target=target,
                        index=index,
                    ),
                    inclusion_delay=1,
                )
                if messed_up_target:
                    pending.data.target.root = b"\x99" * 32
                attestations.append(pending)
            else:
                flags = (spec.ParticipationFlags(
                    2**spec.TIMELY_HEAD_FLAG_INDEX
                    | 2**spec.TIMELY_SOURCE_FLAG_INDEX))
                if not messed_up_target:
                    flags |= spec.ParticipationFlags(
                        2**spec.TIMELY_TARGET_FLAG_INDEX)
                for pos, vindex in enumerate(committee):
                    if bits[pos]:
                        participation[vindex] |= flags


def mock_checkpoints(spec, epoch):
    """Distinct checkpoints 1..5 epochs back (None where out of range)."""
    marks = (b"\xaa", b"\xbb", b"\xcc", b"\xdd", b"\xee")
    return tuple(
        spec.Checkpoint(epoch=epoch - back, root=marks[back - 1] * 32)
        if epoch >= back else None
        for back in range(1, 6))


def put_checkpoint_roots(spec, state, checkpoints):
    for c in checkpoints:
        if c is not None:
            slot = spec.compute_start_slot_at_epoch(c.epoch)
            state.block_roots[slot % spec.SLOTS_PER_HISTORICAL_ROOT] = c.root
