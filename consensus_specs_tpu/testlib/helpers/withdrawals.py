"""Withdrawal test helpers (mirrors `test/helpers/withdrawals.py`)."""

from __future__ import annotations


def get_expected_withdrawals(spec, state):
    """Fork-agnostic accessor: electra returns (withdrawals, count)."""
    from .forks import is_post_electra

    if is_post_electra(spec):
        withdrawals, _ = spec.get_expected_withdrawals(state)
        return withdrawals
    return spec.get_expected_withdrawals(state)


def set_validator_fully_withdrawable(spec, state, index,
                                     withdrawable_epoch=None):
    if withdrawable_epoch is None:
        withdrawable_epoch = spec.get_current_epoch(state)

    validator = state.validators[index]
    validator.withdrawable_epoch = withdrawable_epoch
    # eth1 credentials are required for withdrawals
    if not spec.has_eth1_withdrawal_credential(validator):
        validator.withdrawal_credentials = (
            spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11
            + bytes(validator.withdrawal_credentials[12:]))

    assert spec.is_fully_withdrawable_validator(
        validator, state.balances[index], withdrawable_epoch)


def set_validator_partially_withdrawable(spec, state, index,
                                         excess_balance=1000000000):
    validator = state.validators[index]
    validator.effective_balance = spec.MAX_EFFECTIVE_BALANCE
    state.balances[index] = spec.MAX_EFFECTIVE_BALANCE + excess_balance
    if not spec.has_eth1_withdrawal_credential(validator):
        validator.withdrawal_credentials = (
            spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11
            + bytes(validator.withdrawal_credentials[12:]))

    assert spec.is_partially_withdrawable_validator(
        validator, state.balances[index])


def set_eth1_withdrawal_credential_with_balance(spec, state, index,
                                                balance=None,
                                                effective_balance=None,
                                                address=None):
    if address is None:
        address = b"\x11" * 20
    state.validators[index].withdrawal_credentials = (
        bytes(spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX) + b"\x00" * 11 + address)
    if balance is None and effective_balance is None:
        return
    if balance is None:
        balance = effective_balance
    elif effective_balance is None:
        effective_balance = min(
            balance - balance % spec.EFFECTIVE_BALANCE_INCREMENT,
            spec.MAX_EFFECTIVE_BALANCE)
    state.validators[index].effective_balance = effective_balance
    state.balances[index] = balance


def set_compounding_withdrawal_credential(spec, state, index, address=None):
    if address is None:
        address = b"\x11" * 20
    state.validators[index].withdrawal_credentials = (
        bytes(spec.COMPOUNDING_WITHDRAWAL_PREFIX) + b"\x00" * 11 + address)


def set_compounding_withdrawal_credential_with_balance(
        spec, state, index, effective_balance=None, balance=None,
        address=None):
    set_compounding_withdrawal_credential(spec, state, index, address)
    if balance is None and effective_balance is None:
        balance = spec.MAX_EFFECTIVE_BALANCE_ELECTRA
        effective_balance = spec.MAX_EFFECTIVE_BALANCE_ELECTRA
    elif balance is None:
        balance = effective_balance
    elif effective_balance is None:
        effective_balance = min(
            balance - balance % spec.EFFECTIVE_BALANCE_INCREMENT,
            spec.MAX_EFFECTIVE_BALANCE_ELECTRA)
    state.validators[index].effective_balance = effective_balance
    state.balances[index] = balance


def prepare_expected_withdrawals(spec, state, rng,
                                 num_full_withdrawals=0,
                                 num_partial_withdrawals=0):
    bound = min(len(state.validators),
                spec.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)
    assert num_full_withdrawals + num_partial_withdrawals <= bound
    eligible = list(range(bound))
    rng.shuffle(eligible)
    fully_withdrawable_indices = eligible[:num_full_withdrawals]
    partial_withdrawals_indices = eligible[
        num_full_withdrawals:num_full_withdrawals + num_partial_withdrawals]

    for index in fully_withdrawable_indices:
        set_validator_fully_withdrawable(spec, state, index)
    for index in partial_withdrawals_indices:
        set_validator_partially_withdrawable(spec, state, index)

    return fully_withdrawable_indices, partial_withdrawals_indices


def run_withdrawals_processing(spec, state, execution_payload, valid=True):
    """Yield pre/execution_payload/post; run process_withdrawals."""
    from ..utils import expect_assertion_error

    expected_withdrawals = (get_expected_withdrawals(spec, state)
                            if valid else None)
    pre_state = state.copy()

    yield "pre", state
    yield "execution_payload", execution_payload

    if not valid:
        expect_assertion_error(
            lambda: spec.process_withdrawals(state, execution_payload))
        yield "post", None
        return

    spec.process_withdrawals(state, execution_payload)

    yield "post", state

    for withdrawal in expected_withdrawals:
        assert (state.balances[withdrawal.validator_index]
                == pre_state.balances[withdrawal.validator_index]
                - withdrawal.amount)

    if len(expected_withdrawals) != 0:
        assert (state.next_withdrawal_index
                == expected_withdrawals[-1].index + 1)
