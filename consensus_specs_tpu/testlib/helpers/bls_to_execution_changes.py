"""BLSToExecutionChange helpers
(mirrors `test/helpers/bls_to_execution_changes.py`)."""

from __future__ import annotations

from ...ops import bls
from .keys import privkeys, pubkey_to_privkey, pubkeys


def get_signed_address_change(spec, state, validator_index=None,
                              withdrawal_pubkey=None,
                              to_execution_address=None,
                              fork_version=None, genesis_validators_root=None):
    if validator_index is None:
        validator_index = 0

    if withdrawal_pubkey is None:
        key_index = validator_index
        withdrawal_pubkey = pubkeys[key_index]
        withdrawal_privkey = privkeys[key_index]
    else:
        withdrawal_privkey = pubkey_to_privkey(withdrawal_pubkey)

    if to_execution_address is None:
        to_execution_address = b"\x42" * 20

    if genesis_validators_root is None:
        genesis_validators_root = state.genesis_validators_root

    address_change = spec.BLSToExecutionChange(
        validator_index=validator_index,
        from_bls_pubkey=withdrawal_pubkey,
        to_execution_address=to_execution_address,
    )

    domain = spec.compute_domain(
        spec.DOMAIN_BLS_TO_EXECUTION_CHANGE,
        fork_version=fork_version,
        genesis_validators_root=genesis_validators_root)
    signing_root = spec.compute_signing_root(address_change, domain)
    return spec.SignedBLSToExecutionChange(
        message=address_change,
        signature=bls.Sign(withdrawal_privkey, signing_root),
    )
