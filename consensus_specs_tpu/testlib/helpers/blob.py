"""Blob test helpers (mirrors `test/helpers/blob.py`)."""

from __future__ import annotations

import random


def get_sample_blob(spec, rng=None, is_valid_blob=True):
    """Random blob; each 32-byte chunk is a canonical field element when
    `is_valid_blob` (top byte zeroed keeps it < BLS_MODULUS)."""
    if rng is None:
        rng = random.Random(5566)

    values = [
        rng.randrange(0, spec.BLS_MODULUS) if is_valid_blob
        else spec.BLS_MODULUS + 1
        for _ in range(spec.FIELD_ELEMENTS_PER_BLOB)
    ]

    b = b"".join([
        v.to_bytes(32, spec.KZG_ENDIANNESS) for v in values
    ])
    return spec.Blob(b)


def get_sample_blob_tx(spec, blob_count=1, rng=None, is_valid_blob=True):
    """(opaque_tx, blobs, commitments, proofs) for `blob_count` sample
    blobs — reference shape (`helpers/blob.py get_sample_blob_tx`).  The
    opaque tx is a type-3 stub carrying the versioned hashes; the spec
    never parses it (the engine stub validates out-of-band)."""
    if rng is None:
        # share one stream across the loop, or every blob is identical
        rng = random.Random(5566)
    blobs = []
    blob_kzg_commitments = []
    blob_kzg_proofs = []
    for _ in range(blob_count):
        blob = get_sample_blob(spec, rng, is_valid_blob=is_valid_blob)
        if is_valid_blob:
            blob_commitment = spec.KZGCommitment(
                spec.blob_to_kzg_commitment(blob))
            blob_kzg_proof = spec.compute_blob_kzg_proof(blob,
                                                         blob_commitment)
        else:
            blob_commitment = spec.KZGCommitment()
            blob_kzg_proof = spec.KZGProof()
        blobs.append(blob)
        blob_kzg_commitments.append(blob_commitment)
        blob_kzg_proofs.append(blob_kzg_proof)
    versioned_hashes = [spec.kzg_commitment_to_versioned_hash(c)
                        for c in blob_kzg_commitments]
    opaque_tx = spec.Transaction(
        b"\x03" + b"".join(bytes(h) for h in versioned_hashes))
    return opaque_tx, blobs, blob_kzg_commitments, blob_kzg_proofs


def get_max_blobs_per_block(spec):
    from .forks import is_post_electra

    if is_post_electra(spec):
        return int(spec.config.MAX_BLOBS_PER_BLOCK_ELECTRA)
    return int(spec.config.MAX_BLOBS_PER_BLOCK)


def get_blob_sidecar_subnet_count(spec):
    from .forks import is_post_electra

    if is_post_electra(spec):
        return int(spec.config.BLOB_SIDECAR_SUBNET_COUNT_ELECTRA)
    return int(spec.config.BLOB_SIDECAR_SUBNET_COUNT)
