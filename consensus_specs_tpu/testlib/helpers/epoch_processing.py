"""Epoch-processing slicing: run the epoch pipeline up to / through one
sub-transition (mirrors `test/helpers/epoch_processing.py:7-104`)."""

from __future__ import annotations

from .forks import (
    is_post_altair,
    is_post_capella,
    is_post_electra,
    is_post_fulu,
)


def get_process_calls(spec):
    """Ordered sub-transition names of `process_epoch` for this fork
    (must mirror each fork's `process_epoch` body exactly — the slicing
    helpers below replay a prefix/suffix of this list)."""
    if not is_post_altair(spec):
        return [
            "process_justification_and_finalization",
            "process_rewards_and_penalties",
            "process_registry_updates",
            "process_slashings",
            "process_eth1_data_reset",
            "process_effective_balance_updates",
            "process_slashings_reset",
            "process_randao_mixes_reset",
            "process_historical_roots_update",
            "process_participation_record_updates",
        ]
    calls = [
        "process_justification_and_finalization",
        "process_inactivity_updates",
        "process_rewards_and_penalties",
        "process_registry_updates",
        "process_slashings",
        "process_eth1_data_reset",
    ]
    if is_post_electra(spec):
        calls += [
            "process_pending_deposits",
            "process_pending_consolidations",
        ]
    calls += ["process_effective_balance_updates",
              "process_slashings_reset",
              "process_randao_mixes_reset"]
    calls += (["process_historical_summaries_update"]
              if is_post_capella(spec)
              else ["process_historical_roots_update"])
    calls += ["process_participation_flag_updates",
              "process_sync_committee_updates"]
    if is_post_fulu(spec):
        calls += ["process_proposer_lookahead"]
    return calls


def run_process_slots_up_to_epoch_boundary(spec, state):
    """Advance slot processing to the last slot of the current epoch."""
    slot = state.slot + (spec.SLOTS_PER_EPOCH
                         - state.slot % spec.SLOTS_PER_EPOCH)
    if state.slot < slot - 1:
        spec.process_slots(state, slot - 1)


def run_epoch_processing_to(spec, state, process_name: str,
                            enable_slots_processing: bool = True):
    """Advance to the last slot of the epoch and run the pipeline UP TO
    (not including) `process_name`."""
    if enable_slots_processing:
        run_process_slots_up_to_epoch_boundary(spec, state)
    # start the epoch transition, stopping before `process_name`
    for name in get_process_calls(spec):
        if name == process_name:
            break
        getattr(spec, name)(state)


def run_epoch_processing_with(spec, state, process_name: str):
    """Yield-protocol: pre -> run `process_name` -> post."""
    run_epoch_processing_to(spec, state, process_name)
    yield "pre", state
    getattr(spec, process_name)(state)
    yield "post", state


def run_epoch_processing_from(spec, state, process_name: str):
    """Run the pipeline AFTER `process_name` (exclusive) to the end."""
    assert (state.slot + 1) % spec.SLOTS_PER_EPOCH == 0
    hit = False
    for name in get_process_calls(spec):
        if name == process_name:
            hit = True
            continue
        if hit:
            getattr(spec, name)(state)
