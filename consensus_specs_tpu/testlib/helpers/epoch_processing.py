"""Epoch-processing slicing: run the epoch pipeline up to / through one
sub-transition (mirrors `test/helpers/epoch_processing.py:7-104`)."""

from __future__ import annotations

from .forks import is_post_altair


def get_process_calls(spec):
    """Ordered sub-transition names of `process_epoch` for this fork."""
    if is_post_altair(spec):
        return [
            "process_justification_and_finalization",
            "process_inactivity_updates",
            "process_rewards_and_penalties",
            "process_registry_updates",
            "process_slashings",
            "process_eth1_data_reset",
            "process_effective_balance_updates",
            "process_slashings_reset",
            "process_randao_mixes_reset",
            "process_historical_roots_update",
            "process_participation_flag_updates",
            "process_sync_committee_updates",
        ]
    return [
        "process_justification_and_finalization",
        "process_rewards_and_penalties",
        "process_registry_updates",
        "process_slashings",
        "process_eth1_data_reset",
        "process_effective_balance_updates",
        "process_slashings_reset",
        "process_randao_mixes_reset",
        "process_historical_roots_update",
        "process_participation_record_updates",
    ]


def run_epoch_processing_to(spec, state, process_name: str):
    """Advance to the last slot of the epoch and run the pipeline UP TO
    (not including) `process_name`."""
    slot = state.slot + (spec.SLOTS_PER_EPOCH
                         - state.slot % spec.SLOTS_PER_EPOCH)
    # transition to the last slot of the epoch
    if state.slot < slot - 1:
        spec.process_slots(state, slot - 1)
    # start the epoch transition, stopping before `process_name`
    for name in get_process_calls(spec):
        if name == process_name:
            break
        getattr(spec, name)(state)


def run_epoch_processing_with(spec, state, process_name: str):
    """Yield-protocol: pre -> run `process_name` -> post."""
    run_epoch_processing_to(spec, state, process_name)
    yield "pre", state
    getattr(spec, process_name)(state)
    yield "post", state


def run_epoch_processing_from(spec, state, process_name: str):
    """Run the pipeline FROM `process_name` (inclusive) to the end."""
    hit = False
    for name in get_process_calls(spec):
        if name == process_name:
            hit = True
        if hit:
            getattr(spec, name)(state)
