"""Attestation construction + chain-driving with full participation
(mirrors `test/helpers/attestations.py:17-493`)."""

from __future__ import annotations

from ...utils.ssz.ssz_impl import hash_tree_root
from ..utils import expect_assertion_error
from .block import build_empty_block_for_next_slot, get_parent_root
from .keys import privkeys
from .state import next_slot, state_transition_and_sign_block, transition_to


def run_attestation_processing(spec, state, attestation, valid=True):
    """Yield-protocol runner (mirrors `helpers/attestations.py:30-80`)."""
    yield "pre", state
    yield "attestation", attestation

    if not valid:
        expect_assertion_error(
            lambda: spec.process_attestation(state, attestation))
        yield "post", None
        return

    from .forks import is_post_altair

    is_current = (attestation.data.target.epoch
                  == spec.get_current_epoch(state))
    if not is_post_altair(spec):
        # phase0 appends a PendingAttestation to the epoch's list
        if is_current:
            pre_count = len(state.current_epoch_attestations)
        else:
            pre_count = len(state.previous_epoch_attestations)

    spec.process_attestation(state, attestation)

    if not is_post_altair(spec):
        if is_current:
            assert len(state.current_epoch_attestations) == pre_count + 1
        else:
            assert len(state.previous_epoch_attestations) == pre_count + 1
    else:
        # altair+ sets participation flags for the attesting indices
        participation = (state.current_epoch_participation if is_current
                         else state.previous_epoch_participation)
        flag_indices = spec.get_attestation_participation_flag_indices(
            state, attestation.data,
            state.slot - attestation.data.slot)
        for index in spec.get_attesting_indices(state, attestation):
            for flag_index in flag_indices:
                assert spec.has_flag(participation[index], flag_index)

    yield "post", state


def build_attestation_data(spec, state, slot, index):
    assert state.slot >= slot

    if slot == state.slot:
        block_root = get_parent_root(spec, state)
    else:
        block_root = spec.get_block_root_at_slot(state, slot)

    current_epoch_start_slot = spec.compute_start_slot_at_epoch(
        spec.get_current_epoch(state))
    if slot < current_epoch_start_slot:
        epoch_boundary_root = spec.get_block_root(
            state, spec.get_previous_epoch(state))
    elif slot == current_epoch_start_slot:
        epoch_boundary_root = block_root
    else:
        epoch_boundary_root = spec.get_block_root(
            state, spec.get_current_epoch(state))

    if slot < current_epoch_start_slot:
        source_checkpoint = state.previous_justified_checkpoint
    else:
        source_checkpoint = state.current_justified_checkpoint

    from .forks import is_post_electra

    return spec.AttestationData(
        slot=slot,
        # [EIP-7549] the committee index moves to committee_bits
        index=0 if is_post_electra(spec) else index,
        beacon_block_root=block_root,
        source=spec.Checkpoint(epoch=source_checkpoint.epoch,
                               root=source_checkpoint.root),
        target=spec.Checkpoint(epoch=spec.compute_epoch_at_slot(slot),
                               root=epoch_boundary_root),
    )


def get_valid_attestation(spec, state, slot=None, index=None,
                          filter_participant_set=None, signed=False):
    # If filter_participant_set is None, all committee members participate
    if slot is None:
        slot = state.slot
    if index is None:
        index = 0

    attestation_data = build_attestation_data(spec, state, slot=slot,
                                              index=index)
    attestation = spec.Attestation(data=attestation_data)
    # fill the attestation with participants
    fill_aggregate_attestation(
        spec, state, attestation, committee_index=index, signed=signed,
        filter_participant_set=filter_participant_set)
    return attestation


def get_eip7549_aggregation_bits_offset(spec, state, slot, committee_bits,
                                        committee_index):
    """Bit offset of `committee_index`'s members within the combined
    aggregation bitlist (EIP-7549)."""
    offset = 0
    for index in spec.get_committee_indices(committee_bits):
        if index == committee_index:
            break
        offset += len(spec.get_beacon_committee(state, slot, index))
    return offset


def fill_aggregate_attestation(spec, state, attestation, committee_index=None,
                               signed=False, filter_participant_set=None):
    from .forks import is_post_electra

    if committee_index is None:
        committee_index = (0 if is_post_electra(spec)
                           else attestation.data.index)
    beacon_committee = spec.get_beacon_committee(
        state, attestation.data.slot, committee_index)
    participants = set(beacon_committee)
    if filter_participant_set is not None:
        participants = filter_participant_set(participants)

    if is_post_electra(spec):
        attestation.committee_bits[committee_index] = True
        # total bitlist length spans every committee set in committee_bits
        total = sum(
            len(spec.get_beacon_committee(state, attestation.data.slot, i))
            for i in spec.get_committee_indices(attestation.committee_bits))
        attestation.aggregation_bits = spec.Bitlist[
            spec.MAX_VALIDATORS_PER_COMMITTEE
            * spec.MAX_COMMITTEES_PER_SLOT]([False] * total)
        offset = get_eip7549_aggregation_bits_offset(
            spec, state, attestation.data.slot, attestation.committee_bits,
            committee_index)
        for i in range(len(beacon_committee)):
            attestation.aggregation_bits[offset + i] = (
                beacon_committee[i] in participants)
    else:
        attestation.aggregation_bits = spec.Bitlist[
            spec.MAX_VALIDATORS_PER_COMMITTEE](
                [False] * len(beacon_committee))
        for i in range(len(beacon_committee)):
            attestation.aggregation_bits[i] = (
                beacon_committee[i] in participants)

    if signed and len(participants) > 0:
        sign_attestation(spec, state, attestation)


def sign_attestation(spec, state, attestation):
    participants = spec.get_attesting_indices(state, attestation)
    attestation.signature = sign_aggregate_attestation(
        spec, state, attestation.data, participants)


def sign_aggregate_attestation(spec, state, attestation_data, participants):
    from ...ops import bls

    domain = spec.get_domain(state, spec.DOMAIN_BEACON_ATTESTER,
                             attestation_data.target.epoch)
    signing_root = spec.compute_signing_root(attestation_data, domain)
    signatures = [bls.Sign(privkeys[p], signing_root)
                  for p in sorted(participants)]
    return bls.Aggregate(signatures)


def get_valid_attestation_at_slot(state, spec, slot_to_attest,
                                  participation_fn=None):
    """One attestation per committee of the slot."""
    committees_per_slot = spec.get_committee_count_per_slot(
        state, spec.compute_epoch_at_slot(slot_to_attest))
    for index in range(committees_per_slot):
        def participants_filter(comm):
            if participation_fn is None:
                return comm
            return participation_fn(
                spec.compute_epoch_at_slot(slot_to_attest),
                slot_to_attest, comm)
        yield get_valid_attestation(
            spec, state, slot_to_attest,
            index=spec.CommitteeIndex(index),
            signed=True, filter_participant_set=participants_filter)


def add_attestations_to_state(spec, state, attestations, slot):
    transition_to(spec, state, slot)
    for attestation in attestations:
        spec.process_attestation(state, attestation)


def next_slots_with_attestations(spec, state, slot_count,
                                 fill_cur_epoch, fill_prev_epoch,
                                 participation_fn=None):
    post_state = state.copy()
    signed_blocks = []
    for _ in range(slot_count):
        signed_block = state_transition_with_full_block(
            spec, post_state, fill_cur_epoch, fill_prev_epoch,
            participation_fn)
        signed_blocks.append(signed_block)
    return state, signed_blocks, post_state


def next_epoch_with_attestations(spec, state, fill_cur_epoch,
                                 fill_prev_epoch, participation_fn=None):
    assert state.slot % spec.SLOTS_PER_EPOCH == 0
    return next_slots_with_attestations(
        spec, state, spec.SLOTS_PER_EPOCH, fill_cur_epoch, fill_prev_epoch,
        participation_fn)


def state_transition_with_full_block(spec, state, fill_cur_epoch,
                                     fill_prev_epoch, participation_fn=None):
    """Build and apply a block carrying attestations for the prior slots
    (`helpers/attestations.py` `state_transition_with_full_block`)."""
    block = build_empty_block_for_next_slot(spec, state)
    if (fill_cur_epoch
            and state.slot >= spec.MIN_ATTESTATION_INCLUSION_DELAY):
        slot_to_attest = state.slot - spec.MIN_ATTESTATION_INCLUSION_DELAY + 1
        if (slot_to_attest >= spec.compute_start_slot_at_epoch(
                spec.get_current_epoch(state))):
            attestations = get_valid_attestation_at_slot(
                state, spec, slot_to_attest, participation_fn)
            for attestation in attestations:
                block.body.attestations.append(attestation)
    if fill_prev_epoch and state.slot >= spec.SLOTS_PER_EPOCH:
        slot_to_attest = state.slot - spec.SLOTS_PER_EPOCH + 1
        attestations = get_valid_attestation_at_slot(
            state, spec, slot_to_attest, participation_fn)
        for attestation in attestations:
            block.body.attestations.append(attestation)

    signed_block = state_transition_and_sign_block(spec, state, block)
    return signed_block


def prepare_state_with_attestations(spec, state, participation_fn=None):
    """Advance until previous-epoch attestations cover a full epoch
    (`helpers/attestations.py` `prepare_state_with_attestations`)."""
    start_slot = state.slot
    start_epoch = spec.get_current_epoch(state)
    next_epoch_start_slot = spec.compute_start_slot_at_epoch(start_epoch + 1)
    attestations = []
    for _ in range(spec.SLOTS_PER_EPOCH + spec.MIN_ATTESTATION_INCLUSION_DELAY):
        # create an attestation for each index in each slot of this epoch
        if state.slot < next_epoch_start_slot:
            for committee_index in range(
                    spec.get_committee_count_per_slot(
                        state, spec.get_current_epoch(state))):
                # participation_fn protocol: (slot, comm_index, comm) ->
                # participating subset (reference signature)
                def participants_filter(comm, _slot=state.slot,
                                        _index=committee_index):
                    if participation_fn is None:
                        return comm
                    return participation_fn(_slot, _index, comm)
                attestation = get_valid_attestation(
                    spec, state, index=committee_index,
                    signed=True,
                    filter_participant_set=participants_filter)
                attestations.append(attestation)
        # fill each created slot in state after inclusion delay
        if state.slot >= start_slot + spec.MIN_ATTESTATION_INCLUSION_DELAY:
            inclusion_slot = (state.slot
                              - spec.MIN_ATTESTATION_INCLUSION_DELAY)
            include_attestations = [
                att for att in attestations
                if att.data.slot == inclusion_slot]
            add_attestations_to_state(spec, state, include_attestations,
                                      state.slot)
        next_slot(spec, state)

    assert state.slot == (start_slot + spec.SLOTS_PER_EPOCH
                          + spec.MIN_ATTESTATION_INCLUSION_DELAY)
    if hasattr(state, "previous_epoch_attestations"):  # pre-altair record
        assert (len(state.previous_epoch_attestations)
                == len(attestations))

    return attestations


_prepared_state_cache: dict = {}


def cached_prepare_state_with_attestations(spec, state):
    """Mutate `state` to the fully-attested shape, via a per-(fork, preset,
    pre-root) cache — the epoch of block building behind
    prepare_state_with_attestations dominates rewards-test runtime
    (`helpers/attestations.py` `cached_prepare_state_with_attestations`)."""
    key = (spec.fork, spec.preset_name, hash_tree_root(state))
    if key not in _prepared_state_cache:
        fresh = state.copy()
        prepare_state_with_attestations(spec, fresh)
        _prepared_state_cache[key] = fresh
    # mutate the caller's state in place to match the cached shape
    prepared = _prepared_state_cache[key]
    data = prepared.encode_bytes()
    restored = type(state).decode_bytes(data)
    for name in type(state).fields():
        setattr(state, name, getattr(restored, name))
