"""Fork-choice test driving: event-sourced store steps (tick / block /
attestation / attester_slashing) plus the step+check emission used by the
reference-vector format (`tests/formats/fork_choice/README.md`).
Mirrors `eth2spec/test/helpers/fork_choice.py:43-556`.
"""

from __future__ import annotations

from .attestations import (
    next_epoch_with_attestations,
    next_slots_with_attestations,
    state_transition_with_full_block,
)


def encode_hex(value: bytes) -> str:
    return "0x" + bytes(value).hex()


# ---------------------------------------------------------------------------
# store construction
# ---------------------------------------------------------------------------


def get_anchor_root(spec, state):
    anchor_block_header = state.latest_block_header.copy()
    if anchor_block_header.state_root == spec.Bytes32():
        anchor_block_header.state_root = spec.hash_tree_root(state)
    return spec.hash_tree_root(anchor_block_header)


def get_genesis_forkchoice_store_and_block(spec, genesis_state):
    assert genesis_state.slot == spec.GENESIS_SLOT
    genesis_block = spec.BeaconBlock(
        state_root=spec.hash_tree_root(genesis_state))
    store = spec.get_forkchoice_store(genesis_state, genesis_block)
    return store, genesis_block


def get_genesis_forkchoice_store(spec, genesis_state):
    store, _ = get_genesis_forkchoice_store_and_block(spec, genesis_state)
    return store


# ---------------------------------------------------------------------------
# vector file naming (`helpers/fork_choice.py:224-254`)
# ---------------------------------------------------------------------------


def get_block_file_name(block):
    from ...utils.ssz.ssz_impl import hash_tree_root

    return f"block_{encode_hex(hash_tree_root(block))}"


def get_attestation_file_name(attestation):
    from ...utils.ssz.ssz_impl import hash_tree_root

    return f"attestation_{encode_hex(hash_tree_root(attestation))}"


def get_attester_slashing_file_name(attester_slashing):
    from ...utils.ssz.ssz_impl import hash_tree_root

    return f"attester_slashing_{encode_hex(hash_tree_root(attester_slashing))}"


# ---------------------------------------------------------------------------
# step runners
# ---------------------------------------------------------------------------


def check_head_against_root(spec, store, root):
    head = spec.get_head(store)
    assert head == root


def on_tick_and_append_step(spec, store, time, test_steps):
    assert time >= store.time
    spec.on_tick(store, time)
    test_steps.append({"tick": int(time)})
    output_store_checks(spec, store, test_steps)


def run_on_block(spec, store, signed_block, valid=True):
    if not valid:
        try:
            spec.on_block(store, signed_block)
        except AssertionError:
            return
        else:
            assert False, "on_block unexpectedly accepted the block"

    spec.on_block(store, signed_block)
    root = spec.hash_tree_root(signed_block.message)
    assert store.blocks[root] == signed_block.message


def add_block(spec, store, signed_block, test_steps, valid=True,
              is_optimistic=False):
    """Run on_block (+ the block's attestations and attester slashings,
    as receiving a block implies receiving its contents); yield the
    block as a vector part and append the step + store checks.

    With `is_optimistic`, an invalid payload does NOT reject the import:
    the INVALID determination arrives later from the execution engine, so
    the block enters the store and the step records valid=False
    (`helpers/fork_choice.py:337-341` in the reference)."""
    yield get_block_file_name(signed_block), signed_block

    if not valid:
        if is_optimistic:
            run_on_block(spec, store, signed_block, valid=True)
            test_steps.append({
                "block": get_block_file_name(signed_block),
                "valid": False,
            })
        else:
            try:
                run_on_block(spec, store, signed_block, valid=True)
            except AssertionError:
                test_steps.append({
                    "block": get_block_file_name(signed_block),
                    "valid": False,
                })
                return
            else:
                assert False, "on_block unexpectedly accepted the block"
    else:
        run_on_block(spec, store, signed_block, valid=True)
        test_steps.append({"block": get_block_file_name(signed_block),
                           "valid": True})

    for attestation in signed_block.message.body.attestations:
        run_on_attestation(spec, store, attestation, is_from_block=True,
                           valid=True)
    for attester_slashing in signed_block.message.body.attester_slashings:
        run_on_attester_slashing(spec, store, attester_slashing, valid=True)

    block_root = spec.hash_tree_root(signed_block.message)
    assert store.blocks[block_root] == signed_block.message
    assert (spec.hash_tree_root(store.block_states[block_root])
            == signed_block.message.state_root)
    if not is_optimistic:
        output_store_checks(spec, store, test_steps)

    return store.block_states[block_root]


def tick_and_add_block(spec, store, signed_block, test_steps, valid=True):
    """Advance time slot-by-slot to the block's slot, then add it."""
    pre_state = store.block_states[signed_block.message.parent_root]
    block_time = (pre_state.genesis_time
                  + signed_block.message.slot * spec.config.SECONDS_PER_SLOT)
    while store.time < block_time:
        time = (pre_state.genesis_time
                + (spec.get_current_slot(store) + 1)
                * spec.config.SECONDS_PER_SLOT)
        on_tick_and_append_step(spec, store, time, test_steps)

    post_state = yield from add_block(spec, store, signed_block, test_steps,
                                      valid=valid)
    return post_state


def run_on_attestation(spec, store, attestation, is_from_block=False,
                       valid=True):
    if not valid:
        try:
            spec.on_attestation(store, attestation,
                                is_from_block=is_from_block)
        except AssertionError:
            return
        else:
            assert False, "on_attestation unexpectedly accepted"

    spec.on_attestation(store, attestation, is_from_block=is_from_block)


def add_attestation(spec, store, attestation, test_steps,
                    is_from_block=False, valid=True):
    run_on_attestation(spec, store, attestation,
                       is_from_block=is_from_block, valid=valid)
    yield get_attestation_file_name(attestation), attestation
    step = {"attestation": get_attestation_file_name(attestation)}
    if not valid:
        step["valid"] = False
    test_steps.append(step)


def add_attestations(spec, store, attestations, test_steps,
                     is_from_block=False):
    for attestation in attestations:
        yield from add_attestation(spec, store, attestation, test_steps,
                                   is_from_block=is_from_block)


def tick_and_run_on_attestation(spec, store, attestation, test_steps,
                                is_from_block=False):
    # Attestations only count from the slot after their own
    min_time_to_include = ((attestation.data.slot + 1)
                           * spec.config.SECONDS_PER_SLOT)
    if store.time < min_time_to_include:
        spec.on_tick(store, min_time_to_include)
        test_steps.append({"tick": int(min_time_to_include)})

    yield from add_attestation(spec, store, attestation, test_steps,
                               is_from_block)


def run_on_attester_slashing(spec, store, attester_slashing, valid=True):
    if not valid:
        try:
            spec.on_attester_slashing(store, attester_slashing)
        except AssertionError:
            return
        else:
            assert False, "on_attester_slashing unexpectedly accepted"

    spec.on_attester_slashing(store, attester_slashing)


def add_attester_slashing(spec, store, attester_slashing, test_steps,
                          valid=True):
    slashing_file_name = get_attester_slashing_file_name(attester_slashing)
    yield slashing_file_name, attester_slashing

    if not valid:
        try:
            run_on_attester_slashing(spec, store, attester_slashing)
        except AssertionError:
            test_steps.append({"attester_slashing": slashing_file_name,
                               "valid": False})
            return
        else:
            assert False, "on_attester_slashing unexpectedly accepted"

    run_on_attester_slashing(spec, store, attester_slashing)
    test_steps.append({"attester_slashing": slashing_file_name})


# ---------------------------------------------------------------------------
# checks output (`helpers/fork_choice.py:406-463`)
# ---------------------------------------------------------------------------


def get_formatted_head_output(spec, store):
    head = spec.get_head(store)
    return {"slot": int(store.blocks[head].slot), "root": encode_hex(head)}


def output_head_check(spec, store, test_steps):
    test_steps.append({"checks": {
        "head": get_formatted_head_output(spec, store),
    }})


def output_store_checks(spec, store, test_steps,
                        with_viable_for_head_weights=False):
    checks = {
        "time": int(store.time),
        "head": get_formatted_head_output(spec, store),
        "justified_checkpoint": {
            "epoch": int(store.justified_checkpoint.epoch),
            "root": encode_hex(store.justified_checkpoint.root),
        },
        "finalized_checkpoint": {
            "epoch": int(store.finalized_checkpoint.epoch),
            "root": encode_hex(store.finalized_checkpoint.root),
        },
        "proposer_boost_root": encode_hex(store.proposer_boost_root),
    }

    if with_viable_for_head_weights:
        filtered_block_roots = spec.get_filtered_block_tree(store).keys()
        leaves_viable_for_head = [
            root for root in filtered_block_roots
            if not any(c for c in filtered_block_roots
                       if store.blocks[c].parent_root == root)
        ]
        checks["viable_for_head_roots_and_weights"] = [
            {"root": encode_hex(root),
             "weight": int(spec.get_weight(store, root))}
            for root in leaves_viable_for_head
        ]

    test_steps.append({"checks": checks})


# ---------------------------------------------------------------------------
# chain driving (`helpers/fork_choice.py:466-548`)
# ---------------------------------------------------------------------------


def apply_next_epoch_with_attestations(spec, state, store, fill_cur_epoch,
                                       fill_prev_epoch, participation_fn=None,
                                       test_steps=None):
    if test_steps is None:
        test_steps = []

    _, new_signed_blocks, post_state = next_epoch_with_attestations(
        spec, state, fill_cur_epoch, fill_prev_epoch,
        participation_fn=participation_fn)
    for signed_block in new_signed_blocks:
        block = signed_block.message
        yield from tick_and_add_block(spec, store, signed_block, test_steps)
        block_root = spec.hash_tree_root(block)
        assert store.blocks[block_root] == block
        last_signed_block = signed_block

    assert (spec.hash_tree_root(store.block_states[block_root])
            == spec.hash_tree_root(post_state))
    return post_state, store, last_signed_block


def apply_next_slots_with_attestations(spec, state, store, slots,
                                       fill_cur_epoch, fill_prev_epoch,
                                       test_steps, participation_fn=None):
    _, new_signed_blocks, post_state = next_slots_with_attestations(
        spec, state, slots, fill_cur_epoch, fill_prev_epoch,
        participation_fn=participation_fn)
    for signed_block in new_signed_blocks:
        block = signed_block.message
        yield from tick_and_add_block(spec, store, signed_block, test_steps)
        block_root = spec.hash_tree_root(block)
        assert store.blocks[block_root] == block
        last_signed_block = signed_block

    assert (spec.hash_tree_root(store.block_states[block_root])
            == spec.hash_tree_root(post_state))
    return post_state, store, last_signed_block


def is_ready_to_justify(spec, state):
    """True if the state justifies a new checkpoint at the epoch
    boundary."""
    temp_state = state.copy()
    spec.process_justification_and_finalization(temp_state)
    return (temp_state.current_justified_checkpoint.epoch
            > state.current_justified_checkpoint.epoch)


def find_next_justifying_slot(spec, state, fill_cur_epoch, fill_prev_epoch,
                              participation_fn=None):
    temp_state = state.copy()

    signed_blocks = []
    justifying_slot = None
    while justifying_slot is None:
        signed_block = state_transition_with_full_block(
            spec, temp_state, fill_cur_epoch, fill_prev_epoch,
            participation_fn)
        signed_blocks.append(signed_block)
        if is_ready_to_justify(spec, temp_state):
            justifying_slot = temp_state.slot

    return signed_blocks, justifying_slot
