"""Optimistic-sync test harness: engine payload statuses, the combined
fork-choice + optimistic store, and the optimistic block-import driver
(the reference's `test/helpers/optimistic_sync.py:1-225`)."""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum

from .fork_choice import add_block, get_block_file_name


def encode_hex(b: bytes) -> str:
    return "0x" + bytes(b).hex()


class PayloadStatusV1StatusAlias(Enum):
    NOT_VALIDATED = "NOT_VALIDATED"
    INVALIDATED = "INVALIDATED"


class PayloadStatusV1Status(Enum):
    VALID = "VALID"
    INVALID = "INVALID"
    SYNCING = "SYNCING"
    ACCEPTED = "ACCEPTED"
    INVALID_BLOCK_HASH = "INVALID_BLOCK_HASH"

    @property
    def alias(self) -> PayloadStatusV1StatusAlias | None:
        if self.value in (self.SYNCING.value, self.ACCEPTED.value):
            return PayloadStatusV1StatusAlias.NOT_VALIDATED
        if self.value in (self.INVALID.value, self.INVALID_BLOCK_HASH.value):
            return PayloadStatusV1StatusAlias.INVALIDATED
        return None  # VALID has no alias


@dataclass
class PayloadStatusV1:
    status: PayloadStatusV1Status = PayloadStatusV1Status.VALID
    latest_valid_hash: bytes | None = None
    validation_error: str | None = None

    @property
    def formatted_output(self):
        return {
            "status": str(self.status.value),
            "latest_valid_hash": (encode_hex(self.latest_valid_hash)
                                  if self.latest_valid_hash is not None
                                  else None),
            "validation_error": (str(self.validation_error)
                                 if self.validation_error is not None
                                 else None),
        }


class MegaStore:
    """Fork-choice store + optimistic store + per-block engine statuses."""

    def __init__(self, spec, fc_store, opt_store):
        self.spec = spec
        self.fc_store = fc_store
        self.opt_store = opt_store
        self.block_payload_statuses: dict = {}


def get_optimistic_store(spec, anchor_state, anchor_block):
    assert anchor_block.state_root == anchor_state.hash_tree_root()
    opt_store = spec.OptimisticStore(
        optimistic_roots=set(),
        head_block_root=anchor_block.hash_tree_root(),
    )
    root = anchor_block.hash_tree_root()
    opt_store.blocks[root] = anchor_block.copy()
    opt_store.block_states[root] = anchor_state.copy()
    return opt_store


def get_valid_flag_value(status: PayloadStatusV1Status) -> bool:
    if status == PayloadStatusV1Status.VALID:
        return True
    return status.alias == PayloadStatusV1StatusAlias.NOT_VALIDATED


def add_optimistic_block(spec, mega_store, signed_block, test_steps,
                         payload_status=None,
                         status=PayloadStatusV1Status.SYNCING):
    """Import a block under optimistic-sync rules: record the engine's
    payload status, propagate INVALID up to latestValidHash, run on_block,
    then update the optimistic store + head."""
    block = signed_block.message
    block_root = block.hash_tree_root()
    el_block_hash = block.body.execution_payload.block_hash

    if payload_status is None:
        payload_status = PayloadStatusV1(status=status)
        if payload_status.status == PayloadStatusV1Status.VALID:
            payload_status.latest_valid_hash = el_block_hash

    mega_store.block_payload_statuses[block_root] = payload_status
    test_steps.append({
        "block_hash": encode_hex(el_block_hash),
        "payload_status": payload_status.formatted_output,
    })

    valid = get_valid_flag_value(payload_status.status)

    # INVALID with latestValidHash: walk ancestors up to the valid hash,
    # marking them INVALID too (sync/optimistic.md latestValidHash table)
    if payload_status.status == PayloadStatusV1Status.INVALID:
        assert payload_status.latest_valid_hash is not None
        current_block = block
        current_hash = el_block_hash
        while (current_hash != payload_status.latest_valid_hash
               and current_hash != spec.Bytes32()):
            current_root = current_block.hash_tree_root()
            assert current_root in mega_store.block_payload_statuses
            mega_store.block_payload_statuses[current_root].status = \
                PayloadStatusV1Status.INVALID
            if current_block.parent_root not in mega_store.fc_store.blocks:
                break
            current_block = mega_store.fc_store.blocks[
                current_block.parent_root]
            current_hash = current_block.body.execution_payload.block_hash

    yield from add_block(spec, mega_store.fc_store, signed_block,
                         test_steps=test_steps, valid=valid,
                         is_optimistic=True)

    # update the optimistic store
    if spec.is_optimistic_candidate_block(
            mega_store.opt_store,
            current_slot=spec.get_current_slot(mega_store.fc_store),
            block=block):
        mega_store.opt_store.optimistic_roots.add(block_root)
        mega_store.opt_store.blocks[block_root] = block.copy()
        if not is_invalidated(mega_store, block_root):
            mega_store.opt_store.block_states[block_root] = \
                mega_store.fc_store.block_states[block_root].copy()

    mega_store.opt_store.head_block_root = \
        get_opt_head_block_root(spec, mega_store)
    test_steps.append({
        "checks": {
            "head": get_formatted_optimistic_head_output(mega_store),
        }
    })


def get_opt_head_block_root(spec, mega_store):
    """LMD-GHOST head over the filtered tree, skipping INVALIDATED blocks
    (the optimistic variant of `get_head`)."""
    store = mega_store.fc_store
    blocks = spec.get_filtered_block_tree(store)
    head = store.justified_checkpoint.root
    while True:
        children = [
            root for root in blocks
            if (blocks[root].parent_root == head
                and not is_invalidated(mega_store, root))
        ]
        if len(children) == 0:
            return head
        head = max(children,
                   key=lambda root: (spec.get_weight(store, root), root))


def is_invalidated(mega_store, block_root) -> bool:
    status = mega_store.block_payload_statuses.get(block_root)
    if status is None:
        return False
    return status.status.alias == PayloadStatusV1StatusAlias.INVALIDATED


def get_formatted_optimistic_head_output(mega_store):
    head = mega_store.opt_store.head_block_root
    slot = mega_store.fc_store.blocks[head].slot
    return {"slot": int(slot), "root": encode_hex(head)}


__all__ = [
    "MegaStore", "PayloadStatusV1", "PayloadStatusV1Status",
    "PayloadStatusV1StatusAlias", "add_optimistic_block",
    "get_optimistic_store", "get_opt_head_block_root", "is_invalidated",
    "get_block_file_name",
]
