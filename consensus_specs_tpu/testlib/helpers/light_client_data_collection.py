"""Light-client data collection: a full node's LC data store simulated
over an explicit block DAG — bootstraps for finalized roots, the best
`LightClientUpdate` per sync-committee period, and the latest
finality/optimistic updates, all recomputed on head changes.

Condensed single-spec edition of the reference's
`test/helpers/light_client_data_collection.py:1-998` (the Forked*
cross-fork wrappers are dropped: tests here run within one fork; the
derivation itself rides the spec's own full-node.md functions —
`create_light_client_bootstrap/update/finality_update/optimistic_update`).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .block import build_empty_block
from .state import state_transition_and_sign_block
from .sync_committee import (
    compute_aggregate_sync_committee_signature,
    compute_committee_indices,
)


@dataclass(frozen=True)
class BlockID:
    slot: int
    root: bytes


def _block_to_block_id(spec, block):
    return BlockID(slot=int(block.message.slot),
                   root=bytes(spec.hash_tree_root(block.message)))


def get_lc_bootstrap_block_id(spec, bootstrap) -> BlockID:
    header = bootstrap.header.beacon
    return BlockID(slot=int(header.slot),
                   root=bytes(spec.hash_tree_root(header)))


def get_lc_update_attested_block_id(spec, update) -> BlockID:
    header = update.attested_header.beacon
    return BlockID(slot=int(header.slot),
                   root=bytes(spec.hash_tree_root(header)))


@dataclass
class LightClientDataCollectionTest:
    spec: object
    anchor_bid: BlockID
    blocks: dict = field(default_factory=dict)        # root -> signed block
    post_states: dict = field(default_factory=dict)   # root -> BeaconState
    finalized_bid: BlockID = None
    head_bid: BlockID = None
    best_updates: dict = field(default_factory=dict)  # period -> update
    latest_finality_update: object = None
    latest_optimistic_update: object = None


def setup_lc_data_collection_test(spec, state):
    """Register the (finalized) anchor block/state."""
    anchor_block = spec.SignedBeaconBlock(message=spec.BeaconBlock(
        state_root=spec.hash_tree_root(state)))
    anchor_bid = _block_to_block_id(spec, anchor_block)
    test = LightClientDataCollectionTest(spec=spec, anchor_bid=anchor_bid)
    test.blocks[anchor_bid.root] = anchor_block
    test.post_states[anchor_bid.root] = state.copy()
    test.finalized_bid = anchor_bid
    test.head_bid = anchor_bid
    return test


def add_new_block(test, spec, state, slot=None, num_sync_participants=0):
    """Build + import a block on `state` whose sync aggregate carries
    `num_sync_participants` votes for its parent.  Returns
    (post_state, BlockID)."""
    if slot is None:
        slot = state.slot + 1
    block = build_empty_block(spec, state, slot=slot)

    committee_indices = compute_committee_indices(state)
    participants = committee_indices[:num_sync_participants]
    bits = [i < num_sync_participants
            for i in range(len(committee_indices))]
    signing_state = state.copy()
    spec.process_slots(signing_state, block.slot)
    signature = compute_aggregate_sync_committee_signature(
        spec, signing_state, block.slot - 1, participants,
        block_root=block.parent_root)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=signature,
    )

    post_state = state.copy()
    signed_block = state_transition_and_sign_block(spec, post_state, block)
    bid = _block_to_block_id(spec, signed_block)
    test.blocks[bid.root] = signed_block
    test.post_states[bid.root] = post_state.copy()
    return post_state, bid


def _chain_to_anchor(test, bid):
    """Blocks from (excluding) the anchor to `bid`, oldest first."""
    chain = []
    while bid.root != test.anchor_bid.root:
        block = test.blocks.get(bid.root)
        if block is None:
            break
        chain.append(bid)
        parent_root = bytes(block.message.parent_root)
        parent = test.blocks[parent_root]
        bid = _block_to_block_id(test.spec, parent)
    return list(reversed(chain))


def _finalized_block_for(test, attested_state):
    root = bytes(attested_state.finalized_checkpoint.root)
    if root == b"\x00" * 32:
        return test.blocks[test.anchor_bid.root]  # genesis finality
    return test.blocks.get(root)


def select_new_head(test, spec, head_bid):
    """Recompute the head-dependent LC data (the reference's
    `_process_head_change_for_light_client`): walk the new head chain,
    derive an update from every block with sync participation, keep the
    per-period best and the latest finality/optimistic updates."""
    test.head_bid = head_bid
    test.best_updates = {}
    test.latest_finality_update = None
    test.latest_optimistic_update = None

    for bid in _chain_to_anchor(test, head_bid):
        block = test.blocks[bid.root]
        participation = sum(
            block.message.body.sync_aggregate.sync_committee_bits)
        if participation < spec.MIN_SYNC_COMMITTEE_PARTICIPANTS:
            continue
        parent_root = bytes(block.message.parent_root)
        attested_block = test.blocks[parent_root]
        attested_state = test.post_states[parent_root]
        update = spec.create_light_client_update(
            test.post_states[bid.root], block, attested_state,
            attested_block, _finalized_block_for(test, attested_state))

        period = int(spec.compute_sync_committee_period_at_slot(
            attested_block.message.slot))
        best = test.best_updates.get(period)
        if best is None or spec.is_better_update(update, best):
            test.best_updates[period] = update

        test.latest_optimistic_update = \
            spec.create_light_client_optimistic_update(update)
        if spec.is_finality_update(update):
            test.latest_finality_update = \
                spec.create_light_client_finality_update(update)


def finalize_block(test, spec, finalized_bid):
    """Advance finality (the reference's
    `_process_finalization_for_light_client`): prune pre-finalized
    branches from the block index."""
    test.finalized_bid = finalized_bid
    keep = {test.anchor_bid.root}
    keep.update(b.root for b in _chain_to_anchor(test, test.head_bid))
    keep.add(finalized_bid.root)
    for root in list(test.blocks):
        block = test.blocks[root]
        if (int(block.message.slot) < finalized_bid.slot
                and root not in keep):
            del test.blocks[root]
            del test.post_states[root]


# --- queries (the reference's :537-578) ------------------------------------


def get_light_client_bootstrap(test, block_root):
    """Bootstrap for a finalized block root, or None."""
    block = test.blocks.get(bytes(block_root))
    if block is None:
        return None
    if int(block.message.slot) > test.finalized_bid.slot:
        return None
    state = test.post_states[bytes(block_root)]
    return test.spec.create_light_client_bootstrap(state, block)


def get_light_client_update_for_period(test, period):
    return test.best_updates.get(int(period))


def get_light_client_finality_update(test):
    return test.latest_finality_update


def get_light_client_optimistic_update(test):
    return test.latest_optimistic_update
