"""Light-client test construction: `create_update` and friends — the
core of the reference's `test/helpers/light_client.py:60-121` used by the
update-ranking and data-collection suites."""

from __future__ import annotations

from math import floor

from .sync_committee import (
    compute_aggregate_sync_committee_signature,
    compute_committee_indices,
)


def latest_finalized_root_gindex(spec):
    return spec.finalized_root_gindex_at_slot(spec.Slot(2**62))


def latest_next_sync_committee_gindex(spec):
    return spec.next_sync_committee_gindex_at_slot(spec.Slot(2**62))


def latest_current_sync_committee_gindex(spec):
    return spec.current_sync_committee_gindex_at_slot(spec.Slot(2**62))


def get_sync_aggregate(spec, state, num_participants=None,
                       signature_slot=None):
    """(SyncAggregate, signature_slot) signing the latest block root —
    the reference's LC-flavored helper (signature_slot defaults to the
    slot after the attested state's)."""
    if signature_slot is None:
        signature_slot = state.slot + 1
    assert signature_slot > state.slot
    signature_state = state.copy()
    spec.process_slots(signature_state, spec.Slot(signature_slot))

    committee_indices = compute_committee_indices(state)
    if num_participants is None:
        num_participants = len(committee_indices)
    assert 0 <= num_participants <= len(committee_indices)
    participants = committee_indices[:num_participants]
    bits = [i < num_participants for i in range(len(committee_indices))]

    signed_slot = spec.Slot(int(signature_slot) - 1)
    signature = compute_aggregate_sync_committee_signature(
        spec, signature_state, signed_slot, participants,
        block_root=spec.get_block_root_at_slot(signature_state,
                                               signed_slot))
    aggregate = spec.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=signature,
    )
    return aggregate, spec.Slot(signature_slot)


def create_update(spec, attested_state, attested_block, finalized_block,
                  with_next, with_finality, participation_rate,
                  signature_slot=None):
    """A LightClientUpdate with selectable quality attributes
    (`helpers/light_client.py:88-120`)."""
    num_participants = floor(
        int(spec.SYNC_COMMITTEE_SIZE) * participation_rate)

    update = spec.LightClientUpdate()
    update.attested_header = spec.block_to_light_client_header(
        attested_block)

    if with_next:
        update.next_sync_committee = attested_state.next_sync_committee
        update.next_sync_committee_branch = spec.compute_merkle_proof(
            attested_state, latest_next_sync_committee_gindex(spec))

    if with_finality:
        update.finalized_header = spec.block_to_light_client_header(
            finalized_block)
        update.finality_branch = spec.compute_merkle_proof(
            attested_state, latest_finalized_root_gindex(spec))

    update.sync_aggregate, update.signature_slot = get_sync_aggregate(
        spec, attested_state, num_participants,
        signature_slot=signature_slot)
    return update
