"""State-machine driving helpers (mirrors `test/helpers/state.py:18-115`)."""

from __future__ import annotations

from ..utils import expect_assertion_error
from .block import apply_empty_block, build_empty_block_for_next_slot, \
    sign_block, transition_unsigned_block


def next_slot(spec, state):
    spec.process_slots(state, state.slot + 1)


def next_slots(spec, state, slots):
    if slots > 0:
        spec.process_slots(state, state.slot + slots)


def next_epoch(spec, state):
    slot = state.slot + spec.SLOTS_PER_EPOCH - (state.slot % spec.SLOTS_PER_EPOCH)
    spec.process_slots(state, slot)


def next_epoch_via_block(spec, state, insert_state_root=False):
    """Transition to the next-epoch start slot via a (signed) empty block."""
    slot = state.slot + spec.SLOTS_PER_EPOCH - (state.slot % spec.SLOTS_PER_EPOCH)
    block = build_empty_block(spec, state, slot)
    signed = state_transition_and_sign_block(spec, state, block)
    return signed


def build_empty_block(spec, state, slot=None):
    from .block import build_empty_block as _b
    return _b(spec, state, slot)


def transition_to(spec, state, slot):
    """Advance (forward only; no-op if already there)."""
    assert state.slot <= slot
    if state.slot < slot:
        spec.process_slots(state, slot)


def transition_to_slot_via_block(spec, state, slot):
    assert state.slot < slot
    block = build_empty_block(spec, state, slot)
    return state_transition_and_sign_block(spec, state, block)


def state_transition_and_sign_block(spec, state, block,
                                    expect_fail: bool = False):
    """Run the unsigned transition, seal the state root, sign — or expect
    rejection (`helpers/state.py` `state_transition_and_sign_block`)."""
    if expect_fail:
        pre = state.copy()
        expect_assertion_error(
            lambda: transition_unsigned_block(spec, pre, block))
        return None
    transition_unsigned_block(spec, state, block)
    block.state_root = spec.hash_tree_root(state)
    return sign_block(spec, state, block)


def _full_flags(spec):
    flags = spec.ParticipationFlags(0)
    for flag_index in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
        flags = spec.add_flag(flags, flag_index)
    return flags


def set_full_participation(spec, state, current=True, previous=True):
    """Mark every validator as fully participating (altair+ flags)."""
    from .forks import is_post_altair

    assert is_post_altair(spec)
    flags = _full_flags(spec)
    for index in range(len(state.validators)):
        if current:
            state.current_epoch_participation[index] = flags
        if previous:
            state.previous_epoch_participation[index] = flags


def set_empty_participation(spec, state, current=True, previous=True):
    from .forks import is_post_altair

    assert is_post_altair(spec)
    for index in range(len(state.validators)):
        if current:
            state.current_epoch_participation[index] = \
                spec.ParticipationFlags(0)
        if previous:
            state.previous_epoch_participation[index] = \
                spec.ParticipationFlags(0)


def next_epoch_with_full_participation(spec, state):
    """Transition to the next-epoch start slot with full participation."""
    set_full_participation(spec, state)
    next_epoch(spec, state)


def get_balance(state, index):
    return state.balances[index]


def get_state_root(spec, state, slot):
    assert slot < state.slot <= slot + spec.SLOTS_PER_HISTORICAL_ROOT
    return state.state_roots[slot % spec.SLOTS_PER_HISTORICAL_ROOT]


def has_active_balance_differential(spec, state) -> bool:
    epoch = spec.get_current_epoch(state)
    active = spec.get_total_active_balance(state)
    total = sum(state.balances)
    return active != total
