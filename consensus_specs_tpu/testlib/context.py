"""Test context / decorator DSL (layer L4).

Builds the (fork × preset) spec matrix per test, provides cached genesis
states, BLS switches, and config overrides.  Mirrors the surface of
`eth2spec/test/context.py:74-860` (`spec_state_test`, `with_all_phases`,
`with_presets`, `with_config_overrides`, `always_bls`/`never_bls`,
balance-scenario helpers).
"""

from __future__ import annotations

import functools

from ..models.builder import ALL_FORKS, build_spec, spec_with_config
from ..ops import bls as bls_mod
from .utils import expect_assertion_error, vector_test  # noqa: F401 (re-export)

# set by tests/conftest.py from CLI flags
DEFAULT_TEST_PRESET = "minimal"
DEFAULT_FORK_RESTRICTION: str | None = None

MINIMAL = "minimal"
MAINNET = "mainnet"

# fork groups (mirror `test/context.py` phase selectors)
PHASE0 = "phase0"
ALTAIR = "altair"
BELLATRIX = "bellatrix"
CAPELLA = "capella"
DENEB = "deneb"
ELECTRA = "electra"
FULU = "fulu"
EIP7732 = "eip7732"  # feature fork (not in ALL_FORKS / @with_all_phases)


def _implemented_forks() -> list[str]:
    from ..models.builder import BUILDABLE_FORKS, PKG_ROOT, SPEC_SOURCES

    out = []
    for fork in BUILDABLE_FORKS:
        files = SPEC_SOURCES.get(fork, [])
        if files and any((PKG_ROOT / "models" / fork / f).exists()
                         for f in files):
            out.append(fork)
    return out


# ---------------------------------------------------------------------------
# genesis state cache (the reference's `_custom_state_cache_dict`,
# `test/context.py:71-93`)
# ---------------------------------------------------------------------------

def _hashable(v):
    if isinstance(v, bytes):
        return bytes(v)
    if isinstance(v, list):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


_GENESIS_CACHE: dict = {}


def _cached_genesis(spec, balances_fn, threshold_fn):
    from .helpers.genesis import create_genesis_state

    # genesis content depends on config (fork versions), so fingerprint the
    # whole config — override-carrying specs must not share a cache entry
    # with the base config
    cfg_fp = tuple(sorted(
        (k, _hashable(v)) for k, v in spec.config.to_dict().items()))
    key = (spec.fork, spec.preset_name, cfg_fp,
           f"{balances_fn.__module__}.{balances_fn.__qualname__}",
           f"{threshold_fn.__module__}.{threshold_fn.__qualname__}")
    if key not in _GENESIS_CACHE:
        balances = balances_fn(spec)
        threshold = threshold_fn(spec)
        _GENESIS_CACHE[key] = create_genesis_state(
            spec, balances, activation_threshold=threshold)
    return _GENESIS_CACHE[key].copy()


# balance scenarios (`test/context.py:96-261`)


def default_balances(spec):
    num_validators = spec.SLOTS_PER_EPOCH * 8
    return [spec.MAX_EFFECTIVE_BALANCE] * num_validators


def scaled_churn_balances_min_churn_limit(spec):
    # firmly over the churn limit: +2 because get_validator_churn_limit
    # floors the active-count quotient
    num_validators = (spec.config.CHURN_LIMIT_QUOTIENT
                      * (spec.config.MIN_PER_EPOCH_CHURN_LIMIT + 2))
    return [spec.MAX_EFFECTIVE_BALANCE] * num_validators


def scaled_churn_balances_exceed_activation_exit_churn_limit(spec):
    """Enough stake that the balance churn exceeds the activation/exit
    cap, leaving real consolidation churn
    (`test/context.py scaled_churn_balances_...`)."""
    num_validators = (
        2 * spec.config.CHURN_LIMIT_QUOTIENT
        * spec.config.MAX_PER_EPOCH_ACTIVATION_EXIT_CHURN_LIMIT
        // spec.MIN_ACTIVATION_BALANCE)
    return [spec.MIN_ACTIVATION_BALANCE] * num_validators


def low_balances(spec):
    num_validators = spec.SLOTS_PER_EPOCH * 8
    low_balance = 18 * 10**9
    return [low_balance] * num_validators


def misc_balances(spec):
    num_validators = spec.SLOTS_PER_EPOCH * 8
    balances = [spec.MAX_EFFECTIVE_BALANCE * 2 * i // num_validators
                for i in range(num_validators)]
    rng_order = list(range(num_validators))
    import random
    random.Random(1234).shuffle(rng_order)
    return [balances[i] for i in rng_order]


def one_validator_one_gwei_balances(spec):
    return default_balances(spec)[:-1] + [1]


def default_activation_threshold(spec):
    return spec.MAX_EFFECTIVE_BALANCE


def zero_activation_threshold(spec):
    return 0


# ---------------------------------------------------------------------------
# decorators
# ---------------------------------------------------------------------------


def with_phases(phases, other_phases=None):
    """Run the test for each requested fork that is implemented."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, generator_mode=False, phase=None, preset=None,
                    **kwargs):
            implemented = _implemented_forks()
            run_phases = [p for p in phases if p in implemented]
            if DEFAULT_FORK_RESTRICTION is not None:
                run_phases = [p for p in run_phases
                              if p == DEFAULT_FORK_RESTRICTION]
            if phase is not None:
                # explicit phase (generator mode): skip rather than run a
                # fork the test does not declare
                run_phases = [p for p in run_phases if p == phase]
            results = None
            for p in run_phases:
                spec = build_spec(p, preset or DEFAULT_TEST_PRESET)
                results = fn(*args, spec=spec, generator_mode=generator_mode,
                             **kwargs)
            return results

        wrapper.phases = phases
        # keep pytest from introspecting the wrapped signature and treating
        # (spec, state) as fixtures
        if hasattr(wrapper, "__wrapped__"):
            del wrapper.__wrapped__
        return wrapper

    return deco


def with_all_phases(fn):
    return with_phases(ALL_FORKS)(fn)


def with_all_phases_from(earliest):
    idx = ALL_FORKS.index(earliest)
    return with_phases(ALL_FORKS[idx:])


def with_all_phases_except(excluded):
    return with_phases([f for f in ALL_FORKS if f not in excluded])


def with_test_suite_name(suite_name: str):
    """Override the generator output suite dir (default pyspec_tests)."""
    def deco(fn):
        fn.suite_name = suite_name
        return fn
    return deco


def with_presets(presets, reason=None):
    """Skip unless the active preset is in `presets`."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, spec=None, **kwargs):
            if spec is not None and spec.preset_name not in presets:
                return None  # skipped
            return fn(*args, spec=spec, **kwargs)
        return wrapper

    return deco


def spec_test(fn):
    """vector_test over the bls-switchable test (`test/context.py:308`)."""
    return vector_test(fn)


def single_phase(fn):
    """Consume the spec kwarg only (no state)."""

    @functools.wraps(fn)
    def wrapper(*args, spec, generator_mode=False, **kwargs):
        return fn(*args, spec=spec, **kwargs)

    return wrapper


def with_state(balances_fn=default_balances,
               threshold_fn=default_activation_threshold):
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, spec, **kwargs):
            state = _cached_genesis(spec, balances_fn, threshold_fn)
            return fn(*args, spec=spec, state=state, **kwargs)
        return wrapper

    return deco


def spec_state_test(fn):
    """@with_state + @spec_test + single-phase consumption — the workhorse
    (`test/context.py:318`)."""
    inner = with_state()(fn)

    @functools.wraps(fn)
    def wrapper(*args, spec, generator_mode=False, **kwargs):
        return vector_test(inner)(*args, spec=spec,
                                  generator_mode=generator_mode, **kwargs)

    return wrapper


def spec_configured_state_test(config_overrides, balances_fn=default_balances,
                               threshold_fn=default_activation_threshold):
    """spec_state_test with per-test config overrides
    (`with_config_overrides`, `test/context.py:693-734`)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, spec, generator_mode=False, **kwargs):
            overridden = spec_with_config(spec, config_overrides)
            inner = with_state()(fn)
            return vector_test(inner)(*args, spec=overridden,
                                      generator_mode=generator_mode, **kwargs)
        return wrapper

    return deco


def with_custom_state(balances_fn, threshold_fn):
    return lambda fn: with_state(balances_fn, threshold_fn)(fn)


def spec_state_test_with_matching_config(fn):
    """spec_state_test whose config declares every fork up to the tested
    one active from genesis (`config_fork_epoch_overrides` +
    `spec_state_test_with_matching_config`, `test/context.py:340-366`) —
    needed by code that reads `config.<FORK>_FORK_EPOCH`, e.g. the light
    client protocol."""
    from ..models.builder import fork_chain

    @functools.wraps(fn)
    def wrapper(*args, spec, generator_mode=False, **kwargs):
        overrides = {}
        for f in fork_chain(spec.fork):
            if f != "phase0":
                overrides[f.upper() + "_FORK_EPOCH"] = 0
        overridden = spec_with_config(spec, overrides) if overrides else spec
        inner = with_state()(fn)
        return vector_test(inner)(*args, spec=overridden,
                                  generator_mode=generator_mode, **kwargs)

    return wrapper


def _bls_switch(value):
    """BLS override that holds for the *iteration* of the wrapped test
    generator, not just its creation (`test/context.py` `bls_switch`)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            prev = bls_mod.bls_active
            bls_mod.bls_active = value
            try:
                res = fn(*args, **kwargs)
                if res is not None:
                    # vector meta: 1 = BLS required, 2 = BLS ignored
                    # (`tests/formats/README.md` meta.yaml bls_setting)
                    yield "bls_setting", "meta", 1 if value else 2
                    yield from res
            finally:
                bls_mod.bls_active = prev

        wrapper.bls = "always" if value else "never"
        return wrapper

    return deco


def always_bls(fn):
    """Force BLS on for this test regardless of the global switch."""
    return _bls_switch(True)(fn)


def never_bls(fn):
    return _bls_switch(False)(fn)


def dump_skipping_message(reason: str):
    import pytest

    pytest.skip(reason)


# ---------------------------------------------------------------------------
# fork-transition machinery (`test/context.py:773-860`)
# ---------------------------------------------------------------------------

import dataclasses  # noqa: E402


@dataclasses.dataclass
class ForkMeta:
    pre_fork_name: str
    post_fork_name: str
    fork_epoch: int | None = None


def with_fork_metas(fork_metas):
    """Build a transition test: runs once per ForkMeta whose pre fork is
    implemented, passing (state, fork_epoch, spec, post_spec, pre_tag,
    post_tag); yields post_fork/fork_epoch/fork_block meta parts."""

    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, generator_mode=False, phase=None, preset=None,
                    **kwargs):
            implemented = _implemented_forks()
            metas = [m for m in fork_metas
                     if m.pre_fork_name in implemented
                     and m.post_fork_name in implemented]
            if DEFAULT_FORK_RESTRICTION is not None:
                metas = [m for m in metas
                         if m.pre_fork_name == DEFAULT_FORK_RESTRICTION]
            if phase is not None:
                metas = [m for m in metas if m.pre_fork_name == phase]
            results = None
            for meta in metas:
                spec = build_spec(meta.pre_fork_name,
                                  preset or DEFAULT_TEST_PRESET)
                post_spec = build_spec(meta.post_fork_name,
                                       preset or DEFAULT_TEST_PRESET)
                inner = with_state()(_yield_fork_meta(meta, post_spec)(fn))
                out = vector_test(inner)(
                    *args, spec=spec, generator_mode=generator_mode,
                    **kwargs)
                if out is not None:  # accumulate parts across metas
                    results = (results or []) + out
            return results

        # keep pytest from reading the wrapped signature as fixtures
        if hasattr(wrapper, "__wrapped__"):
            del wrapper.__wrapped__
        return wrapper

    return decorator


def _yield_fork_meta(meta: ForkMeta, post_spec):
    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, spec, state, **kw):
            pre_fork_counter = 0

            def pre_tag(obj):
                nonlocal pre_fork_counter
                pre_fork_counter += 1
                return obj

            def post_tag(obj):
                return obj

            yield "post_fork", "meta", meta.post_fork_name

            has_fork_epoch = False
            if meta.fork_epoch is not None:
                kw["fork_epoch"] = meta.fork_epoch
                has_fork_epoch = True
                yield "fork_epoch", "meta", int(meta.fork_epoch)

            result = fn(*args, spec=spec, state=state, post_spec=post_spec,
                        pre_tag=pre_tag, post_tag=post_tag, **kw)
            if result is not None:
                for part in result:
                    if part[0] == "fork_epoch":
                        has_fork_epoch = True
                    yield part
            assert has_fork_epoch

            if pre_fork_counter > 0:
                yield "fork_block", "meta", pre_fork_counter - 1

        return wrapper

    return decorator
