"""Randomized block-test scenario DSL (the role of the reference's
`test/utils/randomized_block_tests.py:1-476`): deterministic scenario
matrices combining state randomization, leak setup, epoch/slot
transitions, and per-fork random block content, executed through the
dual-mode yield protocol.

A scenario is a list of steps:

    ("randomize",)              heavy state randomization
    ("leak",)                   put the state into an inactivity leak
    ("epochs", n)               n empty epoch transitions
    ("slots", n)                n empty slot transitions
    ("block", kind)             produce+apply a block; kind in
                                {"empty", "random"}
    ("no_op",)                  nothing (scenario spacing)

`standard_scenarios` builds the deterministic matrix used by each
fork's `random/test_random.py`.
"""

from __future__ import annotations

from random import Random

from .helpers.block import build_empty_block_for_next_slot
from .helpers.forks import is_post_altair, is_post_capella
from .helpers.multi_operations import (
    build_random_block_from_state_for_next_slot,
    prepare_state_and_get_random_deposits,
)
from .helpers.random import (
    patch_state_to_non_leaking,
    randomize_state,
)
from .helpers.rewards import transition_state_to_leak
from .helpers.state import (
    next_epoch,
    next_slot,
    state_transition_and_sign_block,
)
from .helpers.sync_committee import (
    compute_aggregate_sync_committee_signature,
    compute_committee_indices,
)


def _random_sync_aggregate(spec, state, block, rng):
    """Random sync participation for the block being built (altair+)."""
    signing_state = state.copy()
    spec.process_slots(signing_state, block.slot)
    committee_indices = compute_committee_indices(signing_state)
    participation = [rng.random() < 0.8 for _ in committee_indices]
    participants = [i for i, bit in zip(committee_indices, participation)
                    if bit]
    if not participants:
        return  # keep the (valid) empty infinity aggregate
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=participation,
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, signing_state, block.slot - 1, participants,
            block_root=block.parent_root),
    )


def _skip_slashed_proposers(spec, state):
    """Advance until the next slot's proposer is unslashed (randomized
    registries can slash the scheduled proposer; producing there would
    be an invalid block)."""
    for _ in range(2 * int(spec.SLOTS_PER_EPOCH)):
        lookahead = state.copy()
        spec.process_slots(lookahead, state.slot + 1)
        proposer = spec.get_beacon_proposer_index(lookahead)
        if not state.validators[proposer].slashed:
            return
        next_slot(spec, state)
    raise AssertionError("no unslashed proposer found in two epochs")


def _produce_block(spec, state, kind, rng, deposits=None):
    _skip_slashed_proposers(spec, state)
    if kind == "random":
        block = build_random_block_from_state_for_next_slot(
            spec, state, rng=rng, deposits=deposits)
    else:
        block = build_empty_block_for_next_slot(spec, state)
    if is_post_altair(spec):
        _random_sync_aggregate(spec, state, block, rng)
    return state_transition_and_sign_block(spec, state, block)


def run_scenario(spec, state, scenario, seed):
    """Execute a scenario; yields the dual-mode vector parts.

    The "pre" part is captured AFTER the setup steps (randomize/leak/
    advance) AND after the deposit/eth1 preparation for every upcoming
    random block — none of those mutations are expressible as block
    transitions, so a consumer replaying pre + blocks must start from
    the fully set-up state (same contract as
    `helpers/multi_operations.run_test_full_random_operations`)."""
    rng = Random(seed)

    setup_steps = [s for s in scenario if s[0] != "block"]
    block_steps = [s for s in scenario if s[0] == "block"]
    assert scenario == setup_steps + block_steps, \
        "setup steps must precede block production"

    for step in setup_steps:
        op = step[0]
        if op == "randomize":
            randomize_state(spec, state, rng, exit_fraction=0.1,
                            slash_fraction=0.1)
            patch_state_to_non_leaking(spec, state)
        elif op == "leak":
            transition_state_to_leak(spec, state)
        elif op == "epochs":
            for _ in range(step[1]):
                next_epoch(spec, state)
        elif op == "slots":
            for _ in range(step[1]):
                next_slot(spec, state)
        elif op == "no_op":
            pass
        else:
            raise ValueError(f"unknown scenario step {step!r}")

    # deposits mutate eth1_data on the state: prepare them all pre-"pre"
    deposit_queue = [
        prepare_state_and_get_random_deposits(spec, state, rng)
        if kind == "random" else None
        for _, kind in block_steps
    ]

    yield "pre", state
    signed_blocks = [
        _produce_block(spec, state, kind, rng, deposits=deposits)
        for (_, kind), deposits in zip(block_steps, deposit_queue)
    ]
    yield "blocks", signed_blocks
    yield "post", state
    assert state.slot < 2**32  # the state survived


def standard_scenarios():
    """The deterministic scenario matrix: {name: scenario} — normal and
    leaking states crossed with epoch/slot offsets and block kinds (the
    reference's generated module enumerates the same axes)."""
    out = {}
    for leak in (False, True):
        leak_tag = "leak_" if leak else ""
        # non-leak states still advance past genesis so random blocks
        # have an attestable history (leak setup advances 6+ epochs)
        setup = [("randomize",)] + ([("leak",)] if leak
                                    else [("epochs", 2)])
        for epochs, slots, tag in (
                (0, 0, "last_slot"),
                (0, 1, "slot_offset"),
                (1, 0, "next_epoch"),
                (2, 3, "deep_offset")):
            advance = ([("epochs", epochs)] if epochs else []) \
                + ([("slots", slots)] if slots else [])
            out[f"random_{leak_tag}{tag}_empty_blocks"] = (
                setup + advance + [("block", "empty"), ("block", "empty")])
            out[f"random_{leak_tag}{tag}_random_block"] = (
                setup + advance + [("block", "random")])
    return out


def register_random_tests(module_globals, fork: str, seed_base: int):
    """Materialize the scenario matrix as pytest test functions in a
    fork's `random/test_random.py` module (the reference generates such
    modules as files; dynamic registration keeps one source of truth)."""
    from .context import spec_state_test, with_phases

    for offset, (name, scenario) in enumerate(
            sorted(standard_scenarios().items())):
        def make(scenario=scenario, seed=seed_base + offset):
            @with_phases([fork])
            @spec_state_test
            def test_fn(spec, state):
                yield from run_scenario(spec, state, scenario, seed)
            return test_fn

        fn = make()
        fn.__name__ = f"test_{name}"
        module_globals[f"test_{name}"] = fn
