"""Shared KZG test inputs for the kzg_4844 / kzg_7594 vector factories
and unit tests (role of the reference's `test/utils/kzg_tests.py:1-185`:
deterministic valid/invalid blobs, field elements, points and cells).

Everything is derived from the deneb mainnet spec at first use so import
stays cheap; the heavy MSMs happen only when a factory actually runs.
"""

from __future__ import annotations

from functools import lru_cache

from ..models.builder import build_spec
from ..ops import bls
from ..ops.bls import ciphersuite


@lru_cache(maxsize=1)
def kzg_spec():
    """Deneb/mainnet spec — the fork the 4844 vectors target."""
    return build_spec("deneb", "mainnet")


@lru_cache(maxsize=1)
def kzg_7594_spec():
    """Fulu/mainnet spec for the cell/DAS vectors."""
    return build_spec("fulu", "mainnet")


def encode_hex(b) -> str:
    return "0x" + bytes(b).hex()


def encode_hex_list(xs):
    return [encode_hex(x) for x in xs]


def field_element_bytes(x: int) -> bytes:
    spec = kzg_spec()
    assert x < spec.BLS_MODULUS
    return int.to_bytes(x, 32, "big")


def field_element_bytes_unchecked(x: int) -> bytes:
    return int.to_bytes(x, 32, "big")


def bls_add_one(x: bytes) -> bytes:
    """Add the G1 generator to a compressed point — a definitely-wrong
    proof/commitment that is still a valid curve point."""
    return bls.G1_to_bytes48(
        ciphersuite.add(bls.bytes48_to_G1(x), ciphersuite.G1()))


@lru_cache(maxsize=1)
def valid_field_elements():
    spec = kzg_spec()
    modulus = int(spec.BLS_MODULUS)
    root_of_unity = int(spec.compute_roots_of_unity(
        spec.FIELD_ELEMENTS_PER_BLOB)[1])
    return [
        field_element_bytes(0),
        field_element_bytes(1),
        field_element_bytes(2),
        field_element_bytes(pow(5, 1235, modulus)),
        field_element_bytes(modulus - 1),
        field_element_bytes(root_of_unity),
    ]


@lru_cache(maxsize=1)
def invalid_field_elements():
    spec = kzg_spec()
    modulus = int(spec.BLS_MODULUS)
    valid0 = valid_field_elements()[0]
    return [
        field_element_bytes_unchecked(modulus),
        field_element_bytes_unchecked(modulus + 1),
        field_element_bytes_unchecked(2**256 - 1),
        field_element_bytes_unchecked(2**256 - 2**128),
        valid0 + b"\x00",
        valid0[:-1],
    ]


def _blob_from_ints(ints):
    spec = kzg_spec()
    return spec.Blob(b"".join(field_element_bytes(i) for i in ints))


@lru_cache(maxsize=1)
def valid_blobs():
    spec = kzg_spec()
    n = int(spec.FIELD_ELEMENTS_PER_BLOB)
    modulus = int(spec.BLS_MODULUS)
    return [
        spec.Blob(),                                      # all zeros
        _blob_from_ints([2] * n),                         # all twos
        _blob_from_ints([pow(2, i + 256, modulus) for i in range(n)]),
        _blob_from_ints([pow(3, i + 256, modulus) for i in range(n)]),
        _blob_from_ints([pow(5, i + 256, modulus) for i in range(n)]),
        _blob_from_ints([modulus - 1] * n),
        _blob_from_ints([1 if i == 3211 else 0 for i in range(n)]),
    ]


@lru_cache(maxsize=1)
def invalid_blobs():
    spec = kzg_spec()
    n = int(spec.FIELD_ELEMENTS_PER_BLOB)
    modulus = int(spec.BLS_MODULUS)
    random_valid = bytes(valid_blobs()[2])
    return [
        b"\xff" * (n * 32),
        b"".join(field_element_bytes_unchecked(modulus) if i == 2111
                 else field_element_bytes(0) for i in range(n)),
        random_valid + b"\x00",
        random_valid[:-1],
    ]


@lru_cache(maxsize=1)
def g1_generator_bytes():
    return bls.G1_to_bytes48(bls.ciphersuite.G1())


@lru_cache(maxsize=1)
def invalid_g1_points():
    gen = g1_generator_bytes()
    return [
        gen[:-1],         # too few bytes
        gen + b"\x00",    # too many bytes
        bytes.fromhex(    # on curve but not in the subgroup
            "8123456789abcdef0123456789abcdef0123456789abcdef"
            "0123456789abcdef0123456789abcdef0123456789abcdef"),
        bytes.fromhex(    # not on the curve at all
            "8123456789abcdef0123456789abcdef0123456789abcdef"
            "0123456789abcdef0123456789abcdef0123456789abcde0"),
    ]


# --- 7594 cells ------------------------------------------------------------

def _cell_from_fn(value_fn):
    spec7 = kzg_7594_spec()
    n = int(spec7.FIELD_ELEMENTS_PER_CELL)
    return b"".join(value_fn(i) for i in range(n))


@lru_cache(maxsize=1)
def valid_cells():
    spec = kzg_spec()
    modulus = int(spec.BLS_MODULUS)
    return [
        _cell_from_fn(lambda i: field_element_bytes(
            pow(2, i + 256, modulus))),
        _cell_from_fn(lambda i: field_element_bytes(
            pow(3, i + 256, modulus))),
        _cell_from_fn(lambda i: field_element_bytes(
            pow(5, i + 256, modulus))),
    ]


@lru_cache(maxsize=1)
def invalid_cells():
    spec = kzg_spec()
    modulus = int(spec.BLS_MODULUS)
    return [
        _cell_from_fn(lambda i: field_element_bytes_unchecked(2**256 - 1)),
        _cell_from_fn(lambda i: field_element_bytes_unchecked(
            modulus if i == 7 else 0)),
        valid_cells()[0][:-1],
        valid_cells()[1] + b"\x00",
    ]


# Cached heavy ops shared across cases (mirrors the reference's @cache
# wrappers, `runners/kzg_4844.py:32-39`).

@lru_cache(maxsize=32)
def cached_blob_to_kzg_commitment(blob_bytes: bytes):
    spec = kzg_spec()
    return spec.blob_to_kzg_commitment(spec.Blob(blob_bytes))


@lru_cache(maxsize=64)
def cached_compute_kzg_proof(blob_bytes: bytes, z: bytes):
    spec = kzg_spec()
    return spec.compute_kzg_proof(spec.Blob(blob_bytes), z)


@lru_cache(maxsize=32)
def cached_compute_blob_kzg_proof(blob_bytes: bytes, commitment: bytes):
    spec = kzg_spec()
    return spec.compute_blob_kzg_proof(spec.Blob(blob_bytes), commitment)


@lru_cache(maxsize=16)
def cached_compute_cells_and_kzg_proofs(blob_bytes: bytes):
    spec7 = kzg_7594_spec()
    return spec7.compute_cells_and_kzg_proofs(spec7.Blob(blob_bytes))
