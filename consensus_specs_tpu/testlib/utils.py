"""Dual-mode test protocol.

Every spec test is a generator yielding (name, value) or (name, kind,
value): under pytest the yields are drained and in-test asserts validate
the spec; in generator mode the same yields become reference-vector files.
Mirrors `eth2spec/test/utils/utils.py:7-102` (`vector_test`).
"""

from __future__ import annotations

import functools
from typing import Any


def _infer_kind(value: Any):
    from ..utils.ssz.types import View

    if isinstance(value, View):
        return "ssz"
    if isinstance(value, bytes):
        return "ssz"
    return "data"


def vector_test(fn):
    """Wrap a yielding test function.

    - pytest mode (default): drain the generator, discard yields.
    - generator mode (`generator_mode=True`): collect (name, kind, value)
      triples and return them for the vector dumper.
    """

    @functools.wraps(fn)
    def entry(*args, generator_mode: bool = False, **kwargs):
        out = fn(*args, **kwargs)
        if out is None:
            return None
        parts = []
        for item in out:
            if not generator_mode:
                continue
            if len(item) == 3:
                name, kind, value = item
            else:
                name, value = item
                kind = _infer_kind(value)
            parts.append((name, kind, value))
        return parts if generator_mode else None

    return entry


def with_meta_tags(tags: dict):
    """Attach meta.yaml tags to a test's vector output."""

    def deco(fn):
        @functools.wraps(fn)
        def entry(*args, **kwargs):
            result = fn(*args, **kwargs)
            if result is not None:
                yielded = False
                for item in result:
                    yield item
                    yielded = True
                if yielded or True:
                    yield "meta", "meta", tags
        return entry

    return deco


def expect_assertion_error(fn):
    """Run fn expecting the spec to reject (AssertionError/IndexError/
    ValueError from SSZ bounds) — the invalid-case convention
    (`test/context.py:370-381`)."""
    try:
        fn()
    except (AssertionError, IndexError, ValueError):
        return
    raise AssertionError("expected the spec to reject, but it accepted")
