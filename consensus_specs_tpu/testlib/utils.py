"""Dual-mode test protocol.

Every spec test is a generator yielding (name, value) or (name, kind,
value): under pytest the yields are drained and in-test asserts validate
the spec; in generator mode the same yields become reference-vector files.
Mirrors `eth2spec/test/utils/utils.py:7-102` (`vector_test`).
"""

from __future__ import annotations

import functools
from typing import Any


def vector_test(fn):
    """Wrap a yielding test function.

    - pytest mode (default): drain the generator, discard yields.
    - generator mode (`generator_mode=True`): transform the yields into
      (name, kind, value) triples for the vector dumper, applying the
      reference's part contract (`test/utils/utils.py:31-58`): SSZ views
      serialize to "ssz" bytes, lists of views expand to indexed parts plus
      a `<name>_count` meta, `None` values are dropped, everything else is
      "data".
    """

    @functools.wraps(fn)
    def entry(*args, generator_mode: bool = False, **kwargs):
        out = fn(*args, **kwargs)
        if out is None:
            return None
        if not generator_mode:
            for _ in out:
                continue
            return None

        from ..utils.ssz.ssz_impl import serialize
        from ..utils.ssz.types import View

        parts = []
        for item in out:
            if len(item) != 2:
                parts.append(item)  # already (name, kind, value)
                continue
            key, value = item
            if value is None:
                continue
            if isinstance(value, View):
                parts.append((key, "ssz", serialize(value)))
            elif isinstance(value, bytes):
                parts.append((key, "ssz", value))
            elif (isinstance(value, list)
                  and all(isinstance(el, (View, bytes)) for el in value)):
                for i, el in enumerate(value):
                    parts.append((
                        f"{key}_{i}", "ssz",
                        serialize(el) if isinstance(el, View) else el))
                parts.append((f"{key}_count", "meta", len(value)))
            else:
                parts.append((key, "data", value))
        return parts

    return entry


def with_meta_tags(tags: dict):
    """Attach meta.yaml tags to a test's vector output."""

    def deco(fn):
        @functools.wraps(fn)
        def entry(*args, **kwargs):
            result = fn(*args, **kwargs)
            if result is not None:
                yielded = False
                for item in result:
                    yield item
                    yielded = True
                if yielded or True:
                    yield "meta", "meta", tags
        return entry

    return deco


def expect_assertion_error(fn):
    """Run fn expecting the spec to reject (AssertionError/IndexError/
    ValueError from SSZ bounds) — the invalid-case convention
    (`test/context.py:370-381`)."""
    try:
        fn()
    except (AssertionError, IndexError, ValueError):
        return
    raise AssertionError("expected the spec to reject, but it accepted")
