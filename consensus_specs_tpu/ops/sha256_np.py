"""Vectorized SHA-256 over numpy uint32 lanes.

The Merkle hot path of SSZ (`hash_tree_root`) hashes *pairs of 32-byte
chunks*: each parent = SHA-256(left ‖ right) where the message is exactly 64
bytes, i.e. one message block plus one constant padding block.  This module
implements the compression function over arrays of N messages at once, so a
whole Merkle tree level is hashed in two batched compressions — the same
data layout the JAX/TPU kernel (`ops.sha256_jax`) uses, which keeps the two
implementations bit-for-bit comparable.

Replaces the per-object `hashlib` loop of the reference
(`eth2spec/utils/hash_function.py:8` + remerkleable's per-node hashing).
"""

import numpy as np

# fmt: off
_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2], dtype=np.uint32)
# fmt: on

_IV = np.array(
    [0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
     0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19], dtype=np.uint32)

# Padding block for a 64-byte message: 0x80 then zeros then bit-length 512.
_PAD64 = np.zeros(16, dtype=np.uint32)
_PAD64[0] = 0x80000000
_PAD64[15] = 512


def _rotr(x: np.ndarray, n: int) -> np.ndarray:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def compress(state: np.ndarray, block: np.ndarray) -> np.ndarray:
    """One SHA-256 compression over a batch.

    state: (N, 8) uint32;  block: (N, 16) or (16,) uint32 (broadcast).
    Returns the new (N, 8) state.
    """
    w = [None] * 64
    if block.ndim == 1:
        block = np.broadcast_to(block, (state.shape[0], 16))
    for t in range(16):
        w[t] = block[:, t]
    for t in range(16, 64):
        s0 = _rotr(w[t - 15], 7) ^ _rotr(w[t - 15], 18) ^ (w[t - 15] >> np.uint32(3))
        s1 = _rotr(w[t - 2], 17) ^ _rotr(w[t - 2], 19) ^ (w[t - 2] >> np.uint32(10))
        w[t] = w[t - 16] + s0 + w[t - 7] + s1

    a, b, c, d, e, f, g, h = (state[:, i] for i in range(8))
    for t in range(64):
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + _K[t] + w[t]
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        h, g, f, e, d, c, b, a = g, f, e, d + t1, c, b, a, t1 + t2

    out = np.empty_like(state)
    for i, v in enumerate((a, b, c, d, e, f, g, h)):
        out[:, i] = state[:, i] + v
    return out


def sha256_64B_words(blocks: np.ndarray) -> np.ndarray:
    """SHA-256 of N 64-byte messages given as (N, 16) big-endian uint32 words.

    Returns digests as (N, 8) uint32 words.  This is the Merkle-parent hash:
    block = left_chunk_words ‖ right_chunk_words.
    """
    n = blocks.shape[0]
    state = np.broadcast_to(_IV, (n, 8)).copy()
    state = compress(state, blocks)
    state = compress(state, _PAD64)
    return state


def chunks_to_words(chunks: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 chunk bytes -> (N, 8) big-endian uint32 words."""
    w = chunks.reshape(-1, 8, 4).astype(np.uint32)
    return w[..., 0] << 24 | w[..., 1] << 16 | w[..., 2] << 8 | w[..., 3]


def words_to_chunks(words: np.ndarray) -> np.ndarray:
    """(N, 8) big-endian uint32 words -> (N, 32) uint8 chunk bytes."""
    out = np.empty(words.shape[:-1] + (8, 4), dtype=np.uint8)
    out[..., 0] = (words >> np.uint32(24)).astype(np.uint8)
    out[..., 1] = (words >> np.uint32(16)).astype(np.uint8)
    out[..., 2] = (words >> np.uint32(8)).astype(np.uint8)
    out[..., 3] = words.astype(np.uint8)
    return out.reshape(words.shape[:-1] + (32,))


def hash_pairs_words(words: np.ndarray) -> np.ndarray:
    """One Merkle level: (2N, 8) word chunks -> (N, 8) parent word chunks."""
    pairs = words.reshape(-1, 16)
    return sha256_64B_words(pairs)


# --- zero-subtree hashes -----------------------------------------------------

_MAX_DEPTH = 65  # depths 0..64 inclusive (SSZ gindex space caps at 2**64 leaves)


def _compute_zero_hashes() -> np.ndarray:
    """zero_hashes[i] = root (as 8 words) of a depth-i all-zero subtree."""
    zh = np.zeros((_MAX_DEPTH, 8), dtype=np.uint32)
    for i in range(1, _MAX_DEPTH):
        zh[i] = sha256_64B_words(np.concatenate([zh[i - 1], zh[i - 1]])[None, :])[0]
    return zh


ZERO_HASH_WORDS = _compute_zero_hashes()
ZERO_HASH_BYTES = [words_to_chunks(ZERO_HASH_WORDS[i][None, :])[0].tobytes()
                   for i in range(_MAX_DEPTH)]


def merkleize_words(words: np.ndarray, limit_depth: int) -> np.ndarray:
    """Merkle root of chunk words (N, 8) padded (virtually) to 2**limit_depth
    leaves.  Returns the root as (8,) uint32 words.

    Level-by-level batched reduction: odd tails are padded with the zero-hash
    of the current level, virtual all-zero subtrees above the data are folded
    in with precomputed zero hashes — the same algorithm
    `ssz/simple-serialize.md` specifies as `merkleize(chunks, limit)`.
    """
    n = words.shape[0]
    assert n <= (1 << limit_depth)
    if n == 0:
        return np.array(ZERO_HASH_WORDS[limit_depth], copy=True)
    level = words.astype(np.uint32)
    d = 0
    while level.shape[0] > 1:
        if level.shape[0] % 2:
            level = np.concatenate([level, ZERO_HASH_WORDS[d][None, :]])
        level = hash_pairs_words(level)
        d += 1
    root = level[0]
    while d < limit_depth:
        block = np.concatenate([root, ZERO_HASH_WORDS[d]])[None, :]
        root = sha256_64B_words(block)[0]
        d += 1
    return root


# Below this chunk count the per-call overhead of the batched numpy kernel
# dwarfs the work; OpenSSL-backed hashlib (SHA-NI) wins decisively.  The
# batched path exists for registry-scale trees (and mirrors the TPU layout).
SMALL_TREE_CHUNKS = 1024


def _merkleize_small(chunks: bytes, depth: int) -> bytes:
    """hashlib level-by-level reduction, bit-identical to the batched path."""
    from hashlib import sha256

    level = [chunks[i:i + 32] for i in range(0, len(chunks), 32)] or [ZERO_HASH_BYTES[0]]
    for d in range(depth):
        if len(level) % 2:
            level.append(ZERO_HASH_BYTES[d])
        level = [sha256(level[i] + level[i + 1]).digest()
                 for i in range(0, len(level), 2)]
    return level[0]


def merkleize_chunks_bytes(chunks: bytes, limit: int | None = None) -> bytes:
    """Merkle root of serialized chunk bytes (len % 32 == 0), as 32 bytes."""
    assert len(chunks) % 32 == 0
    count = len(chunks) // 32
    cap = count if limit is None else limit
    depth = max(cap - 1, 0).bit_length()
    assert count <= (1 << depth), "chunk count exceeds limit"
    if count == 1 and depth == 0:
        return chunks
    if count <= SMALL_TREE_CHUNKS:
        return _merkleize_small(chunks, depth)
    arr = np.frombuffer(chunks, dtype=np.uint8).reshape(-1, 32)
    words = chunks_to_words(arr)
    root = merkleize_words(words, depth)
    return words_to_chunks(root[None, :])[0].tobytes()
