"""BLS12-381 field tower: Fq, Fq2, Fq6, Fq12.

Pure-Python reference arithmetic (the "py" oracle backend, the role py_ecc
plays for the reference — `eth2spec/utils/bls.py:20-23`).  Tower:

    Fq2  = Fq[u]  / (u^2 + 1)
    Fq6  = Fq2[v] / (v^3 - (u + 1))
    Fq12 = Fq6[w] / (w^2 - v)

All derived constants (frobenius coefficients) are *computed* at import from
q and the non-residue — no transcribed tables.
"""

from __future__ import annotations

# Base field modulus and curve order (the two canonical BLS12-381 constants)
Q = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
# BLS parameter x (negative): q, r, and the ate loop count derive from it
BLS_X = -0xD201000000010000

assert (BLS_X ** 4 - BLS_X ** 2 + 1) == R  # r = x^4 - x^2 + 1
assert ((BLS_X - 1) ** 2 * R) // 3 + BLS_X == Q  # q(x) identity (signed x)


def fq_inv(a: int) -> int:
    return pow(a, Q - 2, Q)


class Fq2:
    """a + b*u with u^2 = -1."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: int, c1: int = 0):
        self.c0 = c0 % Q
        self.c1 = c1 % Q

    def __add__(s, o):
        return Fq2(s.c0 + o.c0, s.c1 + o.c1)

    def __sub__(s, o):
        return Fq2(s.c0 - o.c0, s.c1 - o.c1)

    def __neg__(s):
        return Fq2(-s.c0, -s.c1)

    def __mul__(s, o):
        if isinstance(o, int):
            return Fq2(s.c0 * o, s.c1 * o)
        # Karatsuba: (a+bu)(c+du) = ac - bd + ((a+b)(c+d) - ac - bd)u
        t0 = s.c0 * o.c0
        t1 = s.c1 * o.c1
        t2 = (s.c0 + s.c1) * (o.c0 + o.c1)
        return Fq2(t0 - t1, t2 - t0 - t1)

    __rmul__ = __mul__

    def square(s):
        # (a+bu)^2 = (a+b)(a-b) + 2ab u
        return Fq2((s.c0 + s.c1) * (s.c0 - s.c1), 2 * s.c0 * s.c1)

    def inv(s):
        # 1/(a+bu) = (a-bu)/(a^2+b^2)
        d = fq_inv(s.c0 * s.c0 + s.c1 * s.c1)
        return Fq2(s.c0 * d, -s.c1 * d)

    def conjugate(s):
        return Fq2(s.c0, -s.c1)

    def pow(s, e: int):
        res, base = FQ2_ONE, s
        while e:
            if e & 1:
                res = res * base
            base = base.square()
            e >>= 1
        return res

    def is_zero(s):
        return s.c0 == 0 and s.c1 == 0

    def __eq__(s, o):
        return isinstance(o, Fq2) and s.c0 == o.c0 and s.c1 == o.c1

    def __hash__(s):
        return hash((s.c0, s.c1))

    def __repr__(s):
        return f"Fq2({hex(s.c0)}, {hex(s.c1)})"

    def sgn0(s) -> int:
        """RFC 9380 sign: lexicographic on (c0, c1), parity of c0 unless 0."""
        sign_0 = s.c0 % 2
        zero_0 = s.c0 == 0
        sign_1 = s.c1 % 2
        return sign_0 | (zero_0 & sign_1)

    def sqrt(s):
        """Square root in Fq2 (None if non-residue).  q^2 = 9 mod 16; use
        the generic Tonelli–Shanks via pow over the group order."""
        # candidate via a^((q^2+7)/16)-style chains is fiddly; use
        # a^((q^2+1)/... ) trick: for q = 3 mod 4, compute with norm:
        # sqrt(a) = b where b = a^((q-3)/4-ish) ... do it via Fq arithmetic:
        # write a = x + yu; |a| = x^2+y^2; if |a| is QR with root n,
        # then candidates: c = sqrt((x+n)/2) or sqrt((x-n)/2), b = c + (y/(2c))u
        x, y = s.c0, s.c1
        if y == 0:
            n = _fq_sqrt(x)
            if n is not None:
                return Fq2(n, 0)
            # sqrt of non-residue x: x = -z^2 -> sqrt = z*u
            n = _fq_sqrt((-x) % Q)
            assert n is not None
            return Fq2(0, n)
        norm = _fq_sqrt((x * x + y * y) % Q)
        if norm is None:
            return None
        for sign in (1, -1):
            t = (x + sign * norm) * fq_inv(2) % Q
            c = _fq_sqrt(t)
            if c is not None and c != 0:
                b = Fq2(c, y * fq_inv(2 * c))
                if b.square() == s:
                    return b
        return None


FQ2_ZERO = Fq2(0, 0)
FQ2_ONE = Fq2(1, 0)
FQ2_U = Fq2(0, 1)
XI = Fq2(1, 1)  # the Fq6 non-residue  v^3 = xi = 1 + u


def _fq_sqrt(a: int):
    """Square root mod q (q = 3 mod 4), None if non-residue."""
    a %= Q
    if a == 0:
        return 0
    r = pow(a, (Q + 1) // 4, Q)
    return r if r * r % Q == a else None


class Fq6:
    """a + b*v + c*v^2 with v^3 = xi."""

    __slots__ = ("c0", "c1", "c2")

    def __init__(self, c0: Fq2, c1: Fq2, c2: Fq2):
        self.c0, self.c1, self.c2 = c0, c1, c2

    def __add__(s, o):
        return Fq6(s.c0 + o.c0, s.c1 + o.c1, s.c2 + o.c2)

    def __sub__(s, o):
        return Fq6(s.c0 - o.c0, s.c1 - o.c1, s.c2 - o.c2)

    def __neg__(s):
        return Fq6(-s.c0, -s.c1, -s.c2)

    def __mul__(s, o):
        if isinstance(o, (int, Fq2)):
            return Fq6(s.c0 * o, s.c1 * o, s.c2 * o)
        a0, a1, a2 = s.c0, s.c1, s.c2
        b0, b1, b2 = o.c0, o.c1, o.c2
        t0, t1, t2 = a0 * b0, a1 * b1, a2 * b2
        return Fq6(
            t0 + ((a1 + a2) * (b1 + b2) - t1 - t2) * XI,
            (a0 + a1) * (b0 + b1) - t0 - t1 + t2 * XI,
            (a0 + a2) * (b0 + b2) - t0 - t2 + t1,
        )

    __rmul__ = __mul__

    def square(s):
        return s * s

    def mul_by_v(s):
        """v * (a + bv + cv^2) = c*xi + a v + b v^2."""
        return Fq6(s.c2 * XI, s.c0, s.c1)

    def inv(s):
        a, b, c = s.c0, s.c1, s.c2
        t0 = a.square() - b * c * XI
        t1 = c.square() * XI - a * b
        t2 = b.square() - a * c
        d = (a * t0 + (c * t1 + b * t2) * XI).inv()
        return Fq6(t0 * d, t1 * d, t2 * d)

    def is_zero(s):
        return s.c0.is_zero() and s.c1.is_zero() and s.c2.is_zero()

    def __eq__(s, o):
        return isinstance(o, Fq6) and s.c0 == o.c0 and s.c1 == o.c1 and s.c2 == o.c2

    def __hash__(s):
        return hash((s.c0, s.c1, s.c2))

    def __repr__(s):
        return f"Fq6({s.c0!r}, {s.c1!r}, {s.c2!r})"


FQ6_ZERO = Fq6(FQ2_ZERO, FQ2_ZERO, FQ2_ZERO)
FQ6_ONE = Fq6(FQ2_ONE, FQ2_ZERO, FQ2_ZERO)


class Fq12:
    """a + b*w with w^2 = v."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fq6, c1: Fq6):
        self.c0, self.c1 = c0, c1

    def __add__(s, o):
        return Fq12(s.c0 + o.c0, s.c1 + o.c1)

    def __sub__(s, o):
        return Fq12(s.c0 - o.c0, s.c1 - o.c1)

    def __neg__(s):
        return Fq12(-s.c0, -s.c1)

    def __mul__(s, o):
        if isinstance(o, (int, Fq2, Fq6)):
            return Fq12(s.c0 * o, s.c1 * o)
        t0 = s.c0 * o.c0
        t1 = s.c1 * o.c1
        t2 = (s.c0 + s.c1) * (o.c0 + o.c1)
        return Fq12(t0 + t1.mul_by_v(), t2 - t0 - t1)

    __rmul__ = __mul__

    def square(s):
        t0 = s.c0 * s.c1
        a = (s.c0 + s.c1) * (s.c0 + s.c1.mul_by_v())
        return Fq12(a - t0 - t0.mul_by_v(), t0 + t0)

    def inv(s):
        d = (s.c0 * s.c0 - (s.c1 * s.c1).mul_by_v()).inv()
        return Fq12(s.c0 * d, -(s.c1 * d))

    def conjugate(s):
        """The p^6 frobenius: w -> -w."""
        return Fq12(s.c0, -s.c1)

    def pow(s, e: int):
        if e < 0:
            return s.inv().pow(-e)
        res, base = FQ12_ONE, s
        while e:
            if e & 1:
                res = res * base
            base = base.square()
            e >>= 1
        return res

    def frobenius(s, power: int = 1):
        """x -> x^(q^power), via coefficient conjugation + basis constants."""
        power %= 12
        res = s
        for _ in range(power):
            res = _frobenius_once(res)
        return res

    def is_one(s):
        return s.c0 == FQ6_ONE and s.c1.is_zero()

    def __eq__(s, o):
        return isinstance(o, Fq12) and s.c0 == o.c0 and s.c1 == o.c1

    def __hash__(s):
        return hash((s.c0, s.c1))

    def __repr__(s):
        return f"Fq12({s.c0!r}, {s.c1!r})"


FQ12_ZERO = Fq12(FQ6_ZERO, FQ6_ZERO)
FQ12_ONE = Fq12(FQ6_ONE, FQ6_ZERO)
FQ12_W = Fq12(FQ6_ZERO, FQ6_ONE)  # the tower generator w

# --- frobenius coefficients (derived, not transcribed) ---------------------
# Basis of Fq12 over Fq2: w^i for i in 0..5 interleaved through the Fq6
# coefficients: element = (c0.c0 + c0.c1 v + c0.c2 v^2) + (c1.c0 + ...) w
# with v = w^2.  frobenius maps u -> -u on each Fq2 coefficient and
# multiplies the w^i basis element by gamma_i = xi^(i*(q-1)/6) since
# (w^i)^q = w^i * xi^(i(q-1)/6)  (w^6 = xi).

_GAMMA = [XI.pow(i * (Q - 1) // 6) for i in range(6)]


def _frobenius_once(f: Fq12) -> Fq12:
    # coefficients in w-power order: w^0..w^5
    coeffs = [f.c0.c0, f.c1.c0, f.c0.c1, f.c1.c1, f.c0.c2, f.c1.c2]
    mapped = [c.conjugate() * _GAMMA[i] for i, c in enumerate(coeffs)]
    c0 = Fq6(mapped[0], mapped[2], mapped[4])
    c1 = Fq6(mapped[1], mapped[3], mapped[5])
    return Fq12(c0, c1)
