"""BLS12-381 curve groups G1 (over Fq) and G2 (over Fq2, the sextic twist).

Jacobian-coordinate arithmetic, ZCash-format point serialization
(compressed/uncompressed with c/i/s flag bits), subgroup checks, and
multi-scalar multiplication.  Group cofactors are *derived at import* from
q, r and the CM equation (then verified against the generators) rather than
transcribed.

Plays the role of the reference's external point libraries
(`py_arkworks_bls12381` / `py_ecc` behind `eth2spec/utils/bls.py:224-397`).
"""

from __future__ import annotations

from math import isqrt

from .fields import BLS_X, FQ2_ONE, FQ2_ZERO, Q, R, Fq2, fq_inv

# Curve: y^2 = x^3 + 4       over Fq
# Twist: y^2 = x^3 + 4(u+1)  over Fq2
B1 = 4
B2 = Fq2(4, 4)

# Canonical generators (public constants of the ciphersuite)
G1_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1
G2_X = Fq2(
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
G2_Y = Fq2(
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)


# ---------------------------------------------------------------------------
# Generic Jacobian point math, parametrized by field ops
# ---------------------------------------------------------------------------


class _Group:
    """One curve group; fields differ (int mod Q for G1, Fq2 for G2)."""

    def __init__(self, name, b, zero, one, add, sub, mul, sqr, inv, neg,
                 is_zero, eq):
        self.name = name
        self.b = b
        self.F_zero, self.F_one = zero, one
        self.fadd, self.fsub, self.fmul, self.fsqr = add, sub, mul, sqr
        self.finv, self.fneg, self.fis_zero, self.feq = inv, neg, is_zero, eq

    # Points are (X, Y, Z) Jacobian; Z = 0 encodes infinity.

    def infinity(self):
        return (self.F_one, self.F_one, self.F_zero)

    def is_inf(self, p):
        return self.fis_zero(p[2])

    def from_affine(self, x, y):
        return (x, y, self.F_one)

    def to_affine(self, p):
        if self.is_inf(p):
            return None
        zi = self.finv(p[2])
        zi2 = self.fsqr(zi)
        return (self.fmul(p[0], zi2), self.fmul(p[1], self.fmul(zi2, zi)))

    def on_curve(self, p):
        if self.is_inf(p):
            return True
        x, y = self.to_affine(p)
        lhs = self.fsqr(y)
        rhs = self.fadd(self.fmul(self.fsqr(x), x), self.b)
        return self.feq(lhs, rhs)

    def neg(self, p):
        return (p[0], self.fneg(p[1]), p[2])

    def double(self, p):
        X, Y, Z = p
        if self.fis_zero(Z) or self.fis_zero(Y):
            return self.infinity()
        A = self.fsqr(X)
        B = self.fsqr(Y)
        C = self.fsqr(B)
        t = self.fsub(self.fsqr(self.fadd(X, B)), self.fadd(A, C))
        D = self.fadd(t, t)
        E = self.fadd(self.fadd(A, A), A)
        F = self.fsqr(E)
        X3 = self.fsub(F, self.fadd(D, D))
        eight_c = self.fadd(self.fadd(C, C), self.fadd(C, C))
        eight_c = self.fadd(eight_c, eight_c)
        Y3 = self.fsub(self.fmul(E, self.fsub(D, X3)), eight_c)
        Z3 = self.fmul(self.fadd(Y, Y), Z)
        return (X3, Y3, Z3)

    def add(self, p, q):
        if self.is_inf(p):
            return q
        if self.is_inf(q):
            return p
        X1, Y1, Z1 = p
        X2, Y2, Z2 = q
        Z1Z1 = self.fsqr(Z1)
        Z2Z2 = self.fsqr(Z2)
        U1 = self.fmul(X1, Z2Z2)
        U2 = self.fmul(X2, Z1Z1)
        S1 = self.fmul(Y1, self.fmul(Z2Z2, Z2))
        S2 = self.fmul(Y2, self.fmul(Z1Z1, Z1))
        if self.feq(U1, U2):
            if self.feq(S1, S2):
                return self.double(p)
            return self.infinity()
        H = self.fsub(U2, U1)
        I = self.fsqr(self.fadd(H, H))
        J = self.fmul(H, I)
        rr = self.fsub(S2, S1)
        rr = self.fadd(rr, rr)
        V = self.fmul(U1, I)
        X3 = self.fsub(self.fsub(self.fsqr(rr), J), self.fadd(V, V))
        t = self.fsub(V, X3)
        Y3 = self.fsub(self.fmul(rr, t), self.fadd(self.fmul(S1, J),
                                                   self.fmul(S1, J)))
        Z3 = self.fmul(self.fmul(self.fadd(Z1, Z2), self.fadd(Z1, Z2)), H)
        Z3 = self.fsub(Z3, self.fmul(Z1Z1, H))
        Z3 = self.fsub(Z3, self.fmul(Z2Z2, H))
        return (X3, Y3, Z3)

    def mul(self, p, k: int):
        k %= R  # scalars act through the r-torsion on subgroup points
        if k == 0 or self.is_inf(p):
            return self.infinity()
        acc = self.infinity()
        addend = p
        while k:
            if k & 1:
                acc = self.add(acc, addend)
            addend = self.double(addend)
            k >>= 1
        return acc

    def mul_full(self, p, k: int):
        """Scalar mult WITHOUT reduction mod r (for cofactor clearing)."""
        if k < 0:
            return self.mul_full(self.neg(p), -k)
        acc = self.infinity()
        addend = p
        while k:
            if k & 1:
                acc = self.add(acc, addend)
            addend = self.double(addend)
            k >>= 1
        return acc

    def msm(self, points, scalars):
        """Multi-scalar multiplication via Pippenger's bucket method.

        Window cost: ceil(256/c) rounds of (n bucket adds + 2^c
        accumulation adds + c doublings); c chosen from n.  ~8x over the
        naive sum at n=4096 (one KZG blob commitment)."""
        scalars = [int(s) % R for s in scalars]
        pairs = [(p, s) for p, s in zip(points, scalars)
                 if s != 0 and not self.is_inf(p)]
        if not pairs:
            return self.infinity()
        if len(pairs) == 1:
            return self.mul(pairs[0][0], pairs[0][1])

        n = len(pairs)
        # window size tuning: per-round cost is n bucket adds + 2^c
        # accumulation adds, so keep 2^c well under n
        if n < 64:
            c = 4
        elif n < 512:
            c = 7
        elif n < 4096:
            c = 10
        else:
            c = 12
        bits = R.bit_length()  # 255
        windows = range(0, bits, c)

        result = self.infinity()
        for w_start in reversed(list(windows)):
            if not self.is_inf(result):
                for _ in range(c):
                    result = self.double(result)
            buckets = [None] * (1 << c)
            for p, s in pairs:
                idx = (s >> w_start) & ((1 << c) - 1)
                if idx:
                    buckets[idx] = (p if buckets[idx] is None
                                    else self.add(buckets[idx], p))
            # sum_{i} i * bucket[i] via running suffix sums
            running = self.infinity()
            window_sum = self.infinity()
            for b in reversed(buckets[1:]):
                if b is not None:
                    running = self.add(running, b)
                window_sum = self.add(window_sum, running)
            result = self.add(result, window_sum)
        return result

    def eq_points(self, p, q):
        """Jacobian equality: X1 Z2^2 == X2 Z1^2 and Y1 Z2^3 == Y2 Z1^3."""
        if self.is_inf(p) or self.is_inf(q):
            return self.is_inf(p) and self.is_inf(q)
        Z1Z1, Z2Z2 = self.fsqr(p[2]), self.fsqr(q[2])
        if not self.feq(self.fmul(p[0], Z2Z2), self.fmul(q[0], Z1Z1)):
            return False
        return self.feq(self.fmul(p[1], self.fmul(Z2Z2, q[2])),
                        self.fmul(q[1], self.fmul(Z1Z1, p[2])))


g1 = _Group(
    "G1", B1, 0, 1,
    add=lambda a, b: (a + b) % Q,
    sub=lambda a, b: (a - b) % Q,
    mul=lambda a, b: a * b % Q,
    sqr=lambda a: a * a % Q,
    inv=fq_inv,
    neg=lambda a: -a % Q,
    is_zero=lambda a: a % Q == 0,
    eq=lambda a, b: (a - b) % Q == 0,
)

g2 = _Group(
    "G2", B2, FQ2_ZERO, FQ2_ONE,
    add=lambda a, b: a + b,
    sub=lambda a, b: a - b,
    mul=lambda a, b: a * b,
    sqr=lambda a: a.square(),
    inv=lambda a: a.inv(),
    neg=lambda a: -a,
    is_zero=lambda a: a.is_zero(),
    eq=lambda a, b: a == b,
)

G1_GEN = g1.from_affine(G1_X, G1_Y)
G2_GEN = g2.from_affine(G2_X, G2_Y)

assert g1.on_curve(G1_GEN), "G1 generator not on curve"
assert g2.on_curve(G2_GEN), "G2 generator not on twist"


# ---------------------------------------------------------------------------
# Cofactors, derived from the CM equation  t^2 - 4q = -3f^2
# ---------------------------------------------------------------------------

def _derive_cofactors():
    t = BLS_X + 1  # trace of frobenius of E/Fq
    n1 = Q + 1 - t
    assert n1 % R == 0
    h1 = n1 // R
    # order of E over Fq2: q^2 + 1 - t2 with t2 = t^2 - 2q
    t2 = t * t - 2 * Q
    # CM: t2^2 - 4q^2 = -3 f2^2
    f2_sq, rem = divmod(4 * Q * Q - t2 * t2, 3)
    assert rem == 0
    f2 = isqrt(f2_sq)
    assert f2 * f2 == f2_sq
    # the sextic twists of E/Fq2 have orders q^2 + 1 - s for
    # s in {t2, -t2, (t2±3f2)/2, (-t2±3f2)/2}; exactly one correct twist
    # order is divisible by r — select it, then verify on the generator.
    candidates = set()
    for s2 in (t2, -t2):
        for sign in (1, -1):
            num = s2 + sign * 3 * f2
            if num % 2 == 0:
                candidates.add(Q * Q + 1 - num // 2)
        candidates.add(Q * Q + 1 - s2)
    valid = [n for n in candidates if n % R == 0]
    assert valid, "no twist order divisible by r"
    h2 = None
    q_pt = _random_twist_point(12345)  # out-of-subgroup witness point
    for n in valid:
        h = n // R
        # verify: clearing by h lands the witness in the r-torsion
        cleared = g2.mul_full(q_pt, h)
        if g2.is_inf(g2.mul_full(cleared, R)) and not g2.is_inf(cleared):
            h2 = h
            break
    assert h2 is not None, "cofactor derivation failed"
    return h1, h2


def _random_twist_point(seed: int):
    """Deterministic point on the twist (NOT in the subgroup, generally)."""
    x0 = seed
    while True:
        x = Fq2(x0, 1)
        rhs = x.square() * x + B2
        y = rhs.sqrt()
        if y is not None:
            return g2.from_affine(x, y)
        x0 += 1


H1, H2 = _derive_cofactors()

assert g1.is_inf(g1.mul_full(G1_GEN, R)), "G1 generator order != r"
assert g2.is_inf(g2.mul_full(G2_GEN, R)), "G2 generator order != r"


def subgroup_check_g1(p) -> bool:
    return g1.on_curve(p) and g1.is_inf(g1.mul_full(p, R))


def subgroup_check_g2(p) -> bool:
    return g2.on_curve(p) and g2.is_inf(g2.mul_full(p, R))


def clear_cofactor_g1(p):
    return g1.mul_full(p, H1)


def clear_cofactor_g2(p):
    return g2.mul_full(p, H2)


# ---------------------------------------------------------------------------
# ZCash serialization
# ---------------------------------------------------------------------------
# Flags in the top bits of the first byte:
#   C (0x80): compressed;  I (0x40): infinity;  S (0x20): y is the
#   lexicographically larger of the two roots (only when compressed, not inf).

def _y_is_larger_g1(y: int) -> bool:
    return y > Q - y


def _y_is_larger_g2(y: Fq2) -> bool:
    # lexicographic: compare imaginary part first, then real
    if y.c1 != (Q - y.c1) % Q:
        return y.c1 > (Q - y.c1) % Q
    return y.c0 > (Q - y.c0) % Q


def g1_to_bytes(p, compressed: bool = True) -> bytes:
    aff = g1.to_affine(p)
    if aff is None:
        if compressed:
            return bytes([0xC0]) + b"\x00" * 47
        return bytes([0x40]) + b"\x00" * 95
    x, y = aff
    if compressed:
        out = bytearray(x.to_bytes(48, "big"))
        out[0] |= 0x80
        if _y_is_larger_g1(y):
            out[0] |= 0x20
        return bytes(out)
    return x.to_bytes(48, "big") + y.to_bytes(48, "big")


def g1_from_bytes(data: bytes):
    """Deserialize (and on-curve check); raises on malformed input."""
    if len(data) == 48:
        flags = data[0]
        if not flags & 0x80:
            raise ValueError("48-byte G1 must be compressed")
        if flags & 0x40:
            if any(data[1:]) or flags & 0x3F:
                raise ValueError("bad infinity encoding")
            return g1.infinity()
        x = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
        if x >= Q:
            raise ValueError("x >= q")
        rhs = (x * x % Q * x + B1) % Q
        y = _sqrt_fq(rhs)
        if y is None:
            raise ValueError("x not on curve")
        if bool(flags & 0x20) != _y_is_larger_g1(y):
            y = Q - y
        return g1.from_affine(x, y)
    if len(data) == 96:
        flags = data[0]
        if flags & 0x80:
            raise ValueError("96-byte G1 must be uncompressed")
        if flags & 0x40:
            if any(data[1:]) or flags & 0x3F:
                raise ValueError("bad infinity encoding")
            return g1.infinity()
        x = int.from_bytes(data[:48], "big")
        y = int.from_bytes(data[48:], "big")
        if x >= Q or y >= Q:
            raise ValueError("coordinate >= q")
        p = g1.from_affine(x, y)
        if not g1.on_curve(p):
            raise ValueError("not on curve")
        return p
    raise ValueError(f"bad G1 length {len(data)}")


def g2_to_bytes(p, compressed: bool = True) -> bytes:
    aff = g2.to_affine(p)
    if aff is None:
        if compressed:
            return bytes([0xC0]) + b"\x00" * 95
        return bytes([0x40]) + b"\x00" * 191
    x, y = aff
    if compressed:
        out = bytearray(x.c1.to_bytes(48, "big") + x.c0.to_bytes(48, "big"))
        out[0] |= 0x80
        if _y_is_larger_g2(y):
            out[0] |= 0x20
        return bytes(out)
    return (x.c1.to_bytes(48, "big") + x.c0.to_bytes(48, "big")
            + y.c1.to_bytes(48, "big") + y.c0.to_bytes(48, "big"))


def g2_from_bytes(data: bytes):
    if len(data) == 96:
        flags = data[0]
        if not flags & 0x80:
            raise ValueError("96-byte G2 must be compressed")
        if flags & 0x40:
            if any(data[1:]) or flags & 0x3F:
                raise ValueError("bad infinity encoding")
            return g2.infinity()
        x1 = int.from_bytes(bytes([flags & 0x1F]) + data[1:48], "big")
        x0 = int.from_bytes(data[48:], "big")
        if x0 >= Q or x1 >= Q:
            raise ValueError("coordinate >= q")
        x = Fq2(x0, x1)
        rhs = x.square() * x + B2
        y = rhs.sqrt()
        if y is None:
            raise ValueError("x not on twist")
        if bool(flags & 0x20) != _y_is_larger_g2(y):
            y = -y
        return g2.from_affine(x, y)
    if len(data) == 192:
        flags = data[0]
        if flags & 0x80:
            raise ValueError("192-byte G2 must be uncompressed")
        if flags & 0x40:
            if any(data[1:]) or flags & 0x3F:
                raise ValueError("bad infinity encoding")
            return g2.infinity()
        x = Fq2(int.from_bytes(data[48:96], "big"),
                int.from_bytes(data[:48], "big"))
        y = Fq2(int.from_bytes(data[144:], "big"),
                int.from_bytes(data[96:144], "big"))
        p = g2.from_affine(x, y)
        if not g2.on_curve(p):
            raise ValueError("not on twist")
        return p
    raise ValueError(f"bad G2 length {len(data)}")


def _sqrt_fq(a: int):
    a %= Q
    r_ = pow(a, (Q + 1) // 4, Q)
    return r_ if r_ * r_ % Q == a else None
