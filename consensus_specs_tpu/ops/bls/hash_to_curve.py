"""Hash-to-curve for BLS12-381 G1/G2, RFC 9380 structure.

- expand_message_xmd(SHA-256) and hash_to_field: exact RFC 9380 §5.
- map_to_curve: Shallue–van de Woestijne (RFC 9380 §6.6.1 straight line),
  whose constants (Z, c1..c4) are fully determined by the curve equation and
  derived at import — no transcribed isogeny tables.

NOTE: the IETF ciphersuite BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_ uses
simplified-SWU over a 3-isogenous curve; its isogeny constant tables are not
available in this environment, so signatures here are *internally consistent
and secure* but not byte-identical to SSWU-suite implementations.  The map
is isolated behind `map_to_curve_g1/g2` so SSWU can be swapped in without
touching callers.  (Reference seam: `eth2spec/utils/bls.py` Sign/Verify.)
"""

from __future__ import annotations

import hashlib

from .curve import B1, B2, clear_cofactor_g1, clear_cofactor_g2, g1, g2
from .fields import Q, Fq2, _fq_sqrt, fq_inv

# RFC 9380 requires a distinct DST per distinct suite: this build maps with
# SVDW, so it advertises an SVDW DST.  When the SSWU 3-isogeny constants are
# added, switch the map AND this DST to the standard
# b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_" together.
DST_G2 = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SVDW_RO_POP_"


# --- RFC 9380 §5.3 expand_message_xmd --------------------------------------


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    b_in_bytes = 32  # sha256 output
    r_in_bytes = 64  # sha256 block
    ell = (len_in_bytes + b_in_bytes - 1) // b_in_bytes
    if ell > 255 or len(dst) > 255 or len_in_bytes > 65535:
        raise ValueError("expand_message_xmd: length overflow")
    dst_prime = dst + len(dst).to_bytes(1, "big")
    msg_prime = (b"\x00" * r_in_bytes + msg
                 + len_in_bytes.to_bytes(2, "big") + b"\x00" + dst_prime)
    b0 = hashlib.sha256(msg_prime).digest()
    bi = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    out = [bi]
    for i in range(2, ell + 1):
        xored = bytes(a ^ b for a, b in zip(b0, bi))
        bi = hashlib.sha256(xored + i.to_bytes(1, "big") + dst_prime).digest()
        out.append(bi)
    return b"".join(out)[:len_in_bytes]


# --- RFC 9380 §5.2 hash_to_field -------------------------------------------

_L = 64  # ceil((381 + 128) / 8)


def hash_to_field_fq2(msg: bytes, count: int, dst: bytes) -> list[Fq2]:
    uniform = expand_message_xmd(msg, dst, count * 2 * _L)
    out = []
    for i in range(count):
        vals = []
        for j in range(2):
            off = _L * (j + i * 2)
            vals.append(int.from_bytes(uniform[off:off + _L], "big") % Q)
        out.append(Fq2(vals[0], vals[1]))
    return out


def hash_to_field_fq(msg: bytes, count: int, dst: bytes) -> list[int]:
    uniform = expand_message_xmd(msg, dst, count * _L)
    return [int.from_bytes(uniform[_L * i:_L * (i + 1)], "big") % Q
            for i in range(count)]


# --- Shallue–van de Woestijne map (RFC 9380 §6.6.1) -------------------------


class _FieldOps:
    """Shim so one SVDW implementation covers Fq and Fq2."""

    def __init__(self, is_fq2: bool):
        self.is_fq2 = is_fq2

    def from_int(self, a: int):
        return Fq2(a, 0) if self.is_fq2 else a % Q

    def add(self, a, b):
        return a + b if self.is_fq2 else (a + b) % Q

    def sub(self, a, b):
        return a - b if self.is_fq2 else (a - b) % Q

    def mul(self, a, b):
        return a * b if self.is_fq2 else a * b % Q

    def sqr(self, a):
        return a.square() if self.is_fq2 else a * a % Q

    def neg(self, a):
        return -a if self.is_fq2 else -a % Q

    def inv(self, a):
        return a.inv() if self.is_fq2 else fq_inv(a)

    def sqrt(self, a):
        return a.sqrt() if self.is_fq2 else _fq_sqrt(a)

    def sgn0(self, a):
        return a.sgn0() if self.is_fq2 else a % 2

    def is_zero(self, a):
        return a.is_zero() if self.is_fq2 else a % Q == 0

    def candidates(self):
        """Deterministic Z enumeration (RFC find_z_svdw spirit)."""
        if not self.is_fq2:
            for mag in range(1, 16):
                yield mag % Q
                yield -mag % Q
        else:
            for a in range(0, 6):
                for b in range(0, 6):
                    if a == 0 and b == 0:
                        continue
                    yield Fq2(a, b)
                    yield Fq2(-a % Q, -b % Q)


class SVDWMap:
    def __init__(self, B, is_fq2: bool):
        self.F = _FieldOps(is_fq2)
        self.B = B
        self._derive_constants()

    def g(self, x):
        F = self.F
        return F.add(F.mul(F.sqr(x), x), self.B)

    def _derive_constants(self):
        F = self.F
        for Z in F.candidates():
            gz = self.g(Z)
            if F.is_zero(gz):
                continue
            three_z2 = F.mul(F.from_int(3), F.sqr(Z))
            if F.is_zero(three_z2):
                continue
            h = F.mul(F.neg(three_z2), F.inv(F.mul(F.from_int(4), gz)))
            if F.is_zero(h) or F.sqrt(h) is None:
                continue
            c3 = F.sqrt(F.mul(F.neg(gz), three_z2))
            if c3 is None:
                continue
            # exceptional-case guard: g(Z) or g(-Z/2) must be square
            neg_z_half = F.mul(F.neg(Z), F.inv(F.from_int(2)))
            if F.sqrt(gz) is None and F.sqrt(self.g(neg_z_half)) is None:
                continue
            if F.sgn0(c3) != 0:
                c3 = F.neg(c3)
            self.Z = Z
            self.c1 = gz
            self.c2 = neg_z_half
            self.c3 = c3
            self.c4 = F.mul(F.neg(F.mul(F.from_int(4), gz)), F.inv(three_z2))
            return
        raise AssertionError("SVDW: no valid Z found")

    def map_to_curve(self, u):
        """RFC 9380 §6.6.1: returns an affine curve point (never infinity)."""
        F = self.F
        tv1 = F.mul(F.sqr(u), self.c1)
        tv2 = F.add(F.from_int(1), tv1)
        tv1 = F.sub(F.from_int(1), tv1)
        tv3 = F.mul(tv1, tv2)
        tv3 = F.inv(tv3) if not F.is_zero(tv3) else tv3  # inv0
        tv4 = F.mul(F.mul(u, tv1), F.mul(tv3, self.c3))
        x1 = F.sub(self.c2, tv4)
        x2 = F.add(self.c2, tv4)
        t = F.sqr(F.mul(F.sqr(tv2), tv3))
        x3 = F.add(F.mul(t, self.c4), self.Z)
        for x in (x1, x2, x3):
            gx = self.g(x)
            y = F.sqrt(gx)
            if y is not None:
                if F.sgn0(u) != F.sgn0(y):
                    y = F.neg(y)
                return (x, y)
        raise AssertionError("SVDW: no square candidate (impossible)")


_SVDW_G1 = SVDWMap(B1, is_fq2=False)
_SVDW_G2 = SVDWMap(B2, is_fq2=True)


def map_to_curve_g1(u: int):
    return _SVDW_G1.map_to_curve(u)


def map_to_curve_g2(u: Fq2):
    return _SVDW_G2.map_to_curve(u)


# --- hash_to_curve (random-oracle construction, RFC 9380 §3) ----------------


def hash_to_g2(msg: bytes, dst: bytes = DST_G2):
    u0, u1 = hash_to_field_fq2(msg, 2, dst)
    q0 = g2.from_affine(*map_to_curve_g2(u0))
    q1 = g2.from_affine(*map_to_curve_g2(u1))
    return clear_cofactor_g2(g2.add(q0, q1))


def hash_to_g1(msg: bytes, dst: bytes):
    u0, u1 = hash_to_field_fq(msg, 2, dst)
    q0 = g1.from_affine(*map_to_curve_g1(u0))
    q1 = g1.from_affine(*map_to_curve_g1(u1))
    return clear_cofactor_g1(g1.add(q0, q1))
