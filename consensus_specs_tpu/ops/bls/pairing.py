"""Optimal ate pairing on BLS12-381.

G2 points are untwisted into E(Fq12) and the Miller loop runs entirely in
Fq12 (correctness-first oracle; the batched/TPU path optimizes separately).
The untwist direction and all final-exponentiation digits are derived at
import, not transcribed.

Replaces the native pairing backends behind the reference's
`eth2spec/utils/bls.py:142-222` (milagro/arkworks `pairing_check`).
"""

from __future__ import annotations

from .curve import G2_GEN, g1, g2
from .fields import (
    BLS_X,
    FQ2_ONE,
    FQ6_ZERO,
    FQ12_ONE,
    Q,
    R,
    Fq2,
    Fq6,
    Fq12,
)

# --- embed Fq2 -> Fq12 and untwist ------------------------------------------


def _fq2_to_fq12(a: Fq2) -> Fq12:
    return Fq12(Fq6(a, Fq2(0), Fq2(0)), FQ6_ZERO)


_W = Fq12(FQ6_ZERO, Fq6(FQ2_ONE, Fq2(0), Fq2(0)))  # w
_W2 = _W * _W   # = v
_W3 = _W2 * _W


def _derive_untwist():
    """Find (cx, cy) with untwist(x,y) = (x*cx, y*cy) landing on
    y^2 = x^3 + 4 in Fq12.  Try both sextic-twist directions."""
    x, y = g2.to_affine(G2_GEN)
    X = _fq2_to_fq12(x)
    Y = _fq2_to_fq12(y)
    four = Fq12(Fq6(Fq2(4), Fq2(0), Fq2(0)), FQ6_ZERO)
    for cx, cy in ((_W2.inv(), _W3.inv()), (_W2, _W3)):
        Xp, Yp = X * cx, Y * cy
        if Yp * Yp == Xp * Xp * Xp + four:
            return cx, cy
    raise AssertionError("untwist derivation failed")


_UNTWIST_CX, _UNTWIST_CY = _derive_untwist()


def untwist(q_pt):
    """E'(Fq2) (Jacobian) -> E(Fq12) affine pair (or None for infinity)."""
    aff = g2.to_affine(q_pt)
    if aff is None:
        return None
    x, y = aff
    return (_fq2_to_fq12(x) * _UNTWIST_CX, _fq2_to_fq12(y) * _UNTWIST_CY)


# --- Miller loop in Fq12 ----------------------------------------------------


def _line(p1, p2, t):
    """Evaluate the line through p1,p2 (affine Fq12 points) at t."""
    x1, y1 = p1
    x2, y2 = p2
    tx, ty = t
    if x1 == x2 and y1 == y2:
        # tangent
        slope = (x1 * x1 * 3) * (y1 + y1).inv()
        return ty - y1 - slope * (tx - x1)
    if x1 == x2:
        # vertical
        return tx - x1
    slope = (y2 - y1) * (x2 - x1).inv()
    return ty - y1 - slope * (tx - x1)


def _add_affine(p1, p2):
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2 and y1 == y2:
        slope = (x1 * x1 * 3) * (y1 + y1).inv()
    elif x1 == x2:
        return None  # infinity (cannot occur mid-loop: loop count < r)
    else:
        slope = (y2 - y1) * (x2 - x1).inv()
    x3 = slope * slope - x1 - x2
    y3 = slope * (x1 - x3) - y1
    return (x3, y3)


def miller_loop(q_untwisted, p_affine, final: bool = True) -> Fq12:
    """f_{|x|,Q}(P), conjugated for the negative BLS parameter; optionally
    runs the final exponentiation."""
    if q_untwisted is None or p_affine is None:
        return FQ12_ONE
    T = q_untwisted
    f = FQ12_ONE
    loop = abs(BLS_X)
    px, py = p_affine
    P = (px, py)
    for bit in bin(loop)[3:]:
        f = f * f * _line(T, T, P)
        T = _add_affine(T, T)
        if bit == "1":
            f = f * _line(T, q_untwisted, P)
            T = _add_affine(T, q_untwisted)
    # BLS parameter is negative: conjugate (cheap inverse in cyclotomic group)
    f = f.conjugate()
    return final_exponentiate(f) if final else f


def _p_to_fq12_affine(p_pt):
    aff = g1.to_affine(p_pt)
    if aff is None:
        return None
    x, y = aff
    return (Fq12(Fq6(Fq2(x), Fq2(0), Fq2(0)), FQ6_ZERO),
            Fq12(Fq6(Fq2(y), Fq2(0), Fq2(0)), FQ6_ZERO))


def pairing(p_pt, q_pt, final: bool = True) -> Fq12:
    """e(P, Q) for P in G1 (Jacobian), Q in G2 (Jacobian on the twist)."""
    if g1.is_inf(p_pt) or g2.is_inf(q_pt):
        return FQ12_ONE
    return miller_loop(untwist(q_pt), _p_to_fq12_affine(p_pt), final=final)


# --- final exponentiation ---------------------------------------------------
# f^((q^12-1)/r) = easy part (q^6-1)(q^2+1), then hard part
# (q^4-q^2+1)/r decomposed in base q so each digit exponentiation is ~381
# bits and the frobenius does the q-powers.

_HARD = (Q**4 - Q**2 + 1) // R
_DIGITS = []
_tmp = _HARD
for _ in range(4):
    _DIGITS.append(_tmp % Q)
    _tmp //= Q
assert _tmp == 0


def final_exponentiate(f: Fq12) -> Fq12:
    # easy: f <- f^(q^6 - 1) = conj(f) * f^-1 ; then f <- f^(q^2) * f
    f = f.conjugate() * f.inv()
    f = f.frobenius(2) * f
    # hard: f^(d0 + d1 q + d2 q^2 + d3 q^3)
    result = FQ12_ONE
    for i, d in enumerate(_DIGITS):
        result = result * f.frobenius(i).pow(d)
    return result


def pairing_check(pairs) -> bool:
    """prod e(Pi, Qi) == 1, with a single shared final exponentiation."""
    f = FQ12_ONE
    for p_pt, q_pt in pairs:
        if g1.is_inf(p_pt) or g2.is_inf(q_pt):
            continue
        f = f * pairing(p_pt, q_pt, final=False)
    return final_exponentiate(f).is_one()
