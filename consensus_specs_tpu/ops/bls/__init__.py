"""BLS facade — pluggable-backend front-end.

Mirrors the reference's backend-switchable `eth2spec/utils/bls.py` seam:
a module-global backend, a `bls_active` kill-switch returning stub values
(used by the test framework's `--disable-bls` fast path), and the full
Sign/Verify/aggregate + point API re-exported at module level.

Backends:
- "py":  pure-Python oracle (fields/curve/pairing/hash_to_curve here)
- "jax": the TPU path — parsing/subgroup checks/hash-to-curve stay on host
         (oracle code), every pairing runs on device via the batched
         Miller-loop kernels in `ops.bls_batch` (limb-decomposed Fq in
         int32 lanes, one shared final exponentiation per check); the RLC
         batch entry point is `ops.bls_batch.batch_verify`.

Accept/reject semantics are bit-identical between backends: both run the
same host-side validation, and the device pairing check computes the same
product-of-pairings predicate.
"""

from __future__ import annotations

from ... import telemetry
from . import ciphersuite as _py
from . import curve as _curve
from . import fields as _fields
from . import hash_to_curve as _h2c

bls_active = True
_backend_name = "py"


def _device_pairing_check(pairs) -> bool:
    from .. import bls_batch
    return bls_batch.pairing_check_device(pairs)


def _verify_jax(pubkey, message, signature):
    try:
        pk = _py._pk_to_point(pubkey)
        sig = _py._sig_to_point(signature)
    except ValueError:
        return False
    h = _h2c.hash_to_g2(message, _h2c.DST_G2)
    return _device_pairing_check(
        [(pk, h), (_curve.g1.neg(_curve.G1_GEN), sig)])


def _aggregate_verify_jax(pubkeys, messages, signature):
    if len(pubkeys) == 0 or len(pubkeys) != len(messages):
        return False
    try:
        sig = _py._sig_to_point(signature)
        pks = [_py._pk_to_point(pk) for pk in pubkeys]
    except ValueError:
        return False
    pairs = [(pk, _h2c.hash_to_g2(msg, _h2c.DST_G2))
             for pk, msg in zip(pks, messages)]
    pairs.append((_curve.g1.neg(_curve.G1_GEN), sig))
    return _device_pairing_check(pairs)


def _fast_aggregate_verify_jax(pubkeys, message, signature):
    if len(pubkeys) == 0:
        return False
    try:
        sig = _py._sig_to_point(signature)
        agg = _curve.g1.infinity()
        for pk in pubkeys:
            agg = _curve.g1.add(agg, _py._pk_to_point(pk))
    except ValueError:
        return False
    h = _h2c.hash_to_g2(message, _h2c.DST_G2)
    return _device_pairing_check(
        [(agg, h), (_curve.g1.neg(_curve.G1_GEN), sig)])

STUB_SIGNATURE = b"\x11" * 96
STUB_PUBKEY = b"\x22" * 48
G1_POINT_AT_INFINITY = _py.G1_POINT_AT_INFINITY
G2_POINT_AT_INFINITY = _py.G2_POINT_AT_INFINITY
STUB_COORDINATES = None


def use_backend(name: str) -> None:
    global _backend_name
    assert name in ("py", "jax"), name
    _backend_name = name


def backend_name() -> str:
    return _backend_name


# --- scheme functions, honoring the kill-switch -----------------------------


def Sign(privkey, message):
    if not bls_active:
        return STUB_SIGNATURE
    return _py.Sign(int(privkey), bytes(message))


def Verify(pubkey, message, signature):
    if not bls_active:
        return True
    if _backend_name == "jax":
        return _verify_jax(bytes(pubkey), bytes(message), bytes(signature))
    return _py.Verify(bytes(pubkey), bytes(message), bytes(signature))


def Aggregate(signatures):
    if not bls_active:
        return STUB_SIGNATURE
    return _py.Aggregate([bytes(s) for s in signatures])


def AggregateVerify(pubkeys, messages, signature):
    if not bls_active:
        return True
    if _backend_name == "jax":
        return _aggregate_verify_jax([bytes(p) for p in pubkeys],
                                     [bytes(m) for m in messages],
                                     bytes(signature))
    return _py.AggregateVerify([bytes(p) for p in pubkeys],
                               [bytes(m) for m in messages],
                               bytes(signature))


def FastAggregateVerify(pubkeys, message, signature):
    if not bls_active:
        return True
    if _deferred is not None:
        return _deferred.record([bytes(p) for p in pubkeys],
                                bytes(message), bytes(signature))
    if _backend_name == "jax":
        return _fast_aggregate_verify_jax([bytes(p) for p in pubkeys],
                                          bytes(message), bytes(signature))
    return _py.FastAggregateVerify([bytes(p) for p in pubkeys],
                                   bytes(message), bytes(signature))


def AggregatePKs(pubkeys):
    if not bls_active:
        return STUB_PUBKEY
    return _py.AggregatePKs([bytes(p) for p in pubkeys])


def SkToPk(privkey):
    if not bls_active:
        return STUB_PUBKEY
    return _py.SkToPk(int(privkey))


def KeyValidate(pubkey):
    if not bls_active:
        return True
    return _py.KeyValidate(bytes(pubkey))


# --- deferred batch verification --------------------------------------------
# The block executor's collection point: inside the context, every
# FastAggregateVerify statement (attestations, sync aggregates, indexed
# attestations) is input-validated eagerly but its pairing is deferred;
# `DeferredBatch.verify()` then settles ALL of them in one device RLC
# batch (B+1 pairings, one final exponentiation) via `ops.bls_batch`.
# Plain Verify stays eager: its few per-block call sites include deposit
# signatures whose invalidity must not fail the block.


class DeferredBatch:
    """Recorded FastAggregateVerify statements awaiting one batch check.

    This is the futures contract's origin (generalized repo-wide by
    `consensus_specs_tpu.serve`): every `record()` also appends a
    `DeviceFuture` handle to `self.handles`, settled — batch verdict or
    propagated exception — when the batch settles.  Settlement is
    once-only: `verify()` caches its outcome (a second call re-returns
    or re-raises without re-dispatching), and recording after
    settlement is a caller bug (`RuntimeError`) — the block executor
    creates one batch per block, it never reuses a settled one."""

    def __init__(self):
        self.tasks = []      # (g1_pk_jacobian, message, g2_sig_jacobian)
        self.failed = False  # an input failed eager validation
        self.handles = []    # one DeviceFuture per record() call
        self._pending = []   # handles awaiting the batch verdict
        self._settled = False
        self._result: bool | None = None
        self._exc: BaseException | None = None

    def record(self, pubkeys, message, signature) -> bool:
        from ...serve.futures import DeviceFuture
        from .ciphersuite import parse_fast_aggregate_task

        if self._settled:
            raise RuntimeError(
                "deferred batch already settled — record() after "
                "verify() would never be checked")
        task = parse_fast_aggregate_task(pubkeys, message, signature)
        if task is None:
            self.failed = True
            telemetry.count("bls.deferred.rejected")
            self.handles.append(DeviceFuture.settled(False))
            return False
        self.tasks.append(task)
        telemetry.count("bls.deferred.recorded")
        handle = DeviceFuture(waiter=lambda fut: self.verify())
        self.handles.append(handle)
        self._pending.append(handle)
        return True

    def _settle_handles(self, ok: bool | None,
                        exc: BaseException | None = None) -> None:
        """Resolve every pending handle with the batch verdict — or
        propagate a device-batch failure into each of them."""
        pending, self._pending = self._pending, []
        for handle in pending:
            if exc is not None:
                handle.set_exception(exc)
            else:
                handle.set_result(bool(ok))

    def verify(self, device: bool | None = None) -> bool:
        """Settle every recorded statement.  device=None follows the
        active backend (jax -> device batch, py -> host oracle).
        Idempotent: the first call dispatches and caches, later calls
        replay the cached verdict (or re-raise the cached exception)."""
        if self._settled:
            if self._exc is not None:
                raise self._exc
            return self._result
        self._settled = True
        if self.failed:
            self._result = False
            self._settle_handles(False)
            return False
        if not self.tasks:
            self._result = True
            return True
        if device is None:
            device = _backend_name == "jax"
        telemetry.count("bls.deferred.settled", len(self.tasks))
        telemetry.count("bls.deferred.backend.device" if device
                        else "bls.deferred.backend.host")
        try:
            with telemetry.span("bls.deferred.verify",
                                statements=len(self.tasks),
                                backend="device" if device else "host"):
                if device:
                    from ..bls_batch import batch_verify

                    ok = batch_verify(self.tasks)
                else:
                    from .ciphersuite import (
                        _pairing_check,
                        fast_aggregate_pairs,
                    )

                    ok = all(_pairing_check(fast_aggregate_pairs(t))
                             for t in self.tasks)
        except BaseException as exc:
            # a failed device batch fails EVERY pending handle, then
            # surfaces to the settle caller too
            self._exc = exc
            self._settle_handles(None, exc)
            raise
        self._result = ok
        self._settle_handles(ok)
        return ok


_deferred: DeferredBatch | None = None


class deferred_batch_verification:
    """Context manager handing out the recording handle."""

    def __enter__(self) -> DeferredBatch:
        global _deferred
        assert _deferred is None, "deferred batch already active"
        _deferred = DeferredBatch()
        return _deferred

    def __exit__(self, *exc) -> None:
        global _deferred
        _deferred = None


# --- point API (always active; KZG needs real math even with sigs off) ------

add = _py.add
multiply = _py.multiply
neg = _py.neg
eq = _py.eq

# G1 batches below this size are cheaper on the host Pippenger than a
# device dispatch round-trip
_MSM_DEVICE_MIN = 16


def multi_exp(points, integers):
    """MSM; G1 batches route to the device kernel under the jax backend
    (the KZG `g1_lincomb`/`verify_kzg_proof_batch` hot path).  Routing
    decisions are counted (`msm.route.{device,host}` + size histograms)
    so the `_MSM_DEVICE_MIN` break-even is measurable, not guessed."""
    is_g1 = bool(points) and points[0][0] == 1
    if (_backend_name == "jax" and len(points) >= _MSM_DEVICE_MIN
            and is_g1):
        from ..bls_batch import g1_multi_exp_device

        telemetry.count("msm.route.device")
        telemetry.observe("msm.route.device.n", len(points))
        return (1, g1_multi_exp_device([p for _, p in points],
                                       [int(i) for i in integers]))
    # the host-route counter means "the threshold kept a jax-backend MSM
    # on the host" — python-backend runs are not routing decisions
    if is_g1 and _backend_name == "jax":
        telemetry.count("msm.route.host")
        telemetry.observe("msm.route.host.n", len(points))
    return _py.multi_exp(points, integers)
Z1 = _py.Z1
Z2 = _py.Z2
G1 = _py.G1
G2 = _py.G2
G1_to_bytes48 = _py.G1_to_bytes48
G2_to_bytes96 = _py.G2_to_bytes96
bytes48_to_G1 = _py.bytes48_to_G1
bytes96_to_G2 = _py.bytes96_to_G2


def pairing_check(values):
    if not bls_active:
        return True
    if _backend_name == "jax":
        pairs = []
        for (tag1, p), (tag2, q) in values:
            assert tag1 == 1 and tag2 == 2
            pairs.append((p, q))
        return _device_pairing_check(pairs)
    return _py.pairing_check(values)


class Scalar(int):
    """BLS12-381 scalar-field element (mod r), the arithmetic the KZG
    library runs on (the reference wraps arkworks' Scalar,
    `utils/bls.py`; deneb's BLSFieldElement subclasses it)."""

    _R = _fields.R

    def __new__(cls, value=0):
        return super().__new__(cls, int(value) % cls._R)

    def __add__(self, other):
        return type(self)((int(self) + int(other)) % self._R)

    __radd__ = __add__

    def __sub__(self, other):
        return type(self)((int(self) - int(other)) % self._R)

    def __rsub__(self, other):
        return type(self)((int(other) - int(self)) % self._R)

    def __mul__(self, other):
        return type(self)((int(self) * int(other)) % self._R)

    __rmul__ = __mul__

    def __neg__(self):
        return type(self)(-int(self) % self._R)

    def __truediv__(self, other):
        return self * type(self)(int(other)).inverse()

    def __rtruediv__(self, other):
        return type(self)(int(other)) / self

    def inverse(self):
        return type(self)(pow(int(self), -1, self._R))

    def pow(self, exp):
        return type(self)(pow(int(self), int(exp), self._R))
