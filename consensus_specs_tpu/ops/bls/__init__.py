"""BLS facade — pluggable backend front-end (filled in by M3).

Mirrors the reference's backend-switchable `eth2spec/utils/bls.py` seam.
"""

bls_active = True
_backend = "py"


def use_backend(name: str) -> None:
    global _backend
    assert name in ("py", "jax"), name
    _backend = name
