"""BLS signature scheme (proof-of-possession scheme shape, pubkeys in G1,
signatures in G2) + the point API the KZG library uses.

Mirrors the functional surface of the reference facade
(`eth2spec/utils/bls.py:141-397`): Sign/Verify/Aggregate/AggregateVerify/
FastAggregateVerify/AggregatePKs/SkToPk/KeyValidate/pairing_check/multi_exp
and the G1/G2 byte converters.
"""

from __future__ import annotations

from .curve import (
    G1_GEN,
    G2_GEN,
    g1,
    g1_from_bytes,
    g1_to_bytes,
    g2,
    g2_from_bytes,
    g2_to_bytes,
    subgroup_check_g1,
    subgroup_check_g2,
)
from .fields import R
from .hash_to_curve import DST_G2, hash_to_g2
from .pairing import pairing_check as _pairing_check

G1_POINT_AT_INFINITY = b"\xc0" + b"\x00" * 47
G2_POINT_AT_INFINITY = b"\xc0" + b"\x00" * 95


# --- key & point plumbing ---------------------------------------------------


def SkToPk(privkey: int) -> bytes:
    assert 0 < privkey < R
    return g1_to_bytes(g1.mul(G1_GEN, privkey))


def KeyValidate(pubkey: bytes) -> bool:
    try:
        p = g1_from_bytes(pubkey)
    except ValueError:
        return False
    if g1.is_inf(p):
        return False
    return subgroup_check_g1(p)


def _sig_to_point(signature: bytes):
    p = g2_from_bytes(signature)
    if not subgroup_check_g2(p):
        raise ValueError("signature not in G2 subgroup")
    return p


def _pk_to_point(pubkey: bytes):
    p = g1_from_bytes(pubkey)
    if g1.is_inf(p) or not subgroup_check_g1(p):
        raise ValueError("invalid pubkey")
    return p


def parse_fast_aggregate_task(pubkeys, message, signature):
    """Eager wire-format validation for one FastAggregateVerify
    statement, shared by `DeferredBatch.record` (the block path) and
    `ServeExecutor.submit_fast_aggregate_verify` (the serving path) so
    the two can never drift on accept/reject behavior.  Returns the
    (aggregate_pk_jacobian, message_bytes, sig_jacobian) task tuple the
    batched RLC kernel consumes, or None when the inputs are invalid
    (empty pubkey list, unparseable/out-of-subgroup points) — the
    False verdict is decided here, without touching a kernel."""
    if len(pubkeys) == 0:
        return None
    try:
        sig = _sig_to_point(bytes(signature))
        agg = g1.infinity()
        for pk in pubkeys:
            agg = g1.add(agg, _pk_to_point(bytes(pk)))
    except ValueError:
        return None
    return (agg, bytes(message), sig)


def fast_aggregate_pairs(task):
    """The pairing-product statement for one parsed FastAggregateVerify
    task: e(PK, H(m)) · e(-G1, S) == 1, as the [(g1, g2), ...] pair
    list every pairing-check backend consumes.  The ONE definition of
    the verification identity — the oracle path, the deferred-batch
    host fallback, the serve recheck, and the load generator all call
    this, so the formula cannot drift between them."""
    pk, msg, sig = task
    return [(pk, hash_to_g2(bytes(msg), DST_G2)), (g1.neg(G1_GEN), sig)]


# --- core scheme ------------------------------------------------------------


def Sign(privkey: int, message: bytes) -> bytes:
    assert 0 < privkey < R
    return g2_to_bytes(g2.mul(hash_to_g2(message, DST_G2), privkey))


def Verify(pubkey: bytes, message: bytes, signature: bytes) -> bool:
    try:
        pk = _pk_to_point(pubkey)
        sig = _sig_to_point(signature)
    except ValueError:
        return False
    h = hash_to_g2(message, DST_G2)
    # e(pk, H(m)) * e(-g1, sig) == 1
    return _pairing_check([(pk, h), (g1.neg(G1_GEN), sig)])


def Aggregate(signatures: list[bytes]) -> bytes:
    assert len(signatures) > 0
    acc = g2.infinity()
    for s in signatures:
        acc = g2.add(acc, _sig_to_point(s))
    return g2_to_bytes(acc)


def AggregatePKs(pubkeys: list[bytes]) -> bytes:
    assert len(pubkeys) > 0
    acc = g1.infinity()
    for pk in pubkeys:
        acc = g1.add(acc, _pk_to_point(pk))
    return g1_to_bytes(acc)


def AggregateVerify(pubkeys: list[bytes], messages: list[bytes],
                    signature: bytes) -> bool:
    if len(pubkeys) == 0 or len(pubkeys) != len(messages):
        return False
    try:
        sig = _sig_to_point(signature)
        pks = [_pk_to_point(pk) for pk in pubkeys]
    except ValueError:
        return False
    pairs = [(pk, hash_to_g2(msg, DST_G2)) for pk, msg in zip(pks, messages)]
    pairs.append((g1.neg(G1_GEN), sig))
    return _pairing_check(pairs)


def FastAggregateVerify(pubkeys: list[bytes], message: bytes,
                        signature: bytes) -> bool:
    if len(pubkeys) == 0:
        return False
    try:
        sig = _sig_to_point(signature)
        agg = g1.infinity()
        for pk in pubkeys:
            agg = g1.add(agg, _pk_to_point(pk))
    except ValueError:
        return False
    return _pairing_check(fast_aggregate_pairs((agg, message, sig)))


# --- point API for the KZG / polynomial-commitment library ------------------
# (reference surface: `eth2spec/utils/bls.py:224-397`)


def add(a, b):
    """Group add; operands are (group_tag, jacobian) pairs from this API."""
    tag_a, pa = a
    tag_b, pb = b
    assert tag_a == tag_b
    grp = g1 if tag_a == 1 else g2
    return (tag_a, grp.add(pa, pb))


def multiply(a, n: int):
    tag, p = a
    grp = g1 if tag == 1 else g2
    return (tag, grp.mul(p, int(n)))


def neg(a):
    tag, p = a
    grp = g1 if tag == 1 else g2
    return (tag, grp.neg(p))


def multi_exp(points, integers):
    assert len(points) == len(integers) and len(points) > 0
    tag = points[0][0]
    grp = g1 if tag == 1 else g2
    return (tag, grp.msm([p for _, p in points], [int(i) for i in integers]))


def eq(a, b):
    tag_a, pa = a
    tag_b, pb = b
    if tag_a != tag_b:
        return False
    grp = g1 if tag_a == 1 else g2
    return grp.eq_points(pa, pb)


def Z1():
    return (1, g1.infinity())


def Z2():
    return (2, g2.infinity())


def G1():
    return (1, G1_GEN)


def G2():
    return (2, G2_GEN)


def G1_to_bytes48(a) -> bytes:
    tag, p = a
    assert tag == 1
    return g1_to_bytes(p)


def G2_to_bytes96(a) -> bytes:
    tag, p = a
    assert tag == 2
    return g2_to_bytes(p)


def bytes48_to_G1(b: bytes):
    return (1, g1_from_bytes(bytes(b)))


def bytes96_to_G2(b: bytes):
    return (2, g2_from_bytes(bytes(b)))


def pairing_check(values) -> bool:
    """values: list of ((1, G1pt), (2, G2pt)) pairs."""
    pairs = []
    for (tag1, p), (tag2, q) in values:
        assert tag1 == 1 and tag2 == 2
        pairs.append((p, q))
    return _pairing_check(pairs)
